"""Test configuration: force a clean 8-virtual-device CPU JAX.

Multi-chip sharding is validated the way the reference validates MNMG
logic without a cluster (SURVEY.md §4: LocalCUDACluster of local
processes) — here a single process exposing 8 virtual CPU devices via
``xla_force_host_platform_device_count``.

This environment routes every interpreter to a single remote TPU chip via
a PJRT relay plugin registered in ``sitecustomize``; it forces
``jax_platforms="axon,cpu"`` via jax.config (which overrides the
JAX_PLATFORMS env var). Tests must never contend for the one real chip,
so we override the config back to pure CPU *before any backend
initializes* — jax.config.update beats the plugin's registration-time
setting as long as it runs before the first ``jax.devices()``.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", False)
assert jax.devices()[0].platform == "cpu", "tests must run on CPU devices"

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: beyond the tier-1 budget (e.g. the 16-shard point of "
        "the quantized-wire recall study) — deselected by -m 'not "
        "slow'")


@pytest.fixture
def rng_np():
    return np.random.default_rng(42)


@pytest.fixture
def res():
    from raft_tpu import Resources

    return Resources(seed=42)


def pytest_sessionfinish(session, exitstatus):
    """Drop a metrics-snapshot artifact after the run when CI asks
    (``RAFT_TPU_METRICS_SNAPSHOT=<path>``, set by ``ci/test.sh``): the
    full tracing registries — counters, gauges, histogram summaries
    with cumulative buckets, span-ring stats — accumulated over the
    test session. A CI browser then sees the same accounting a live
    ``/metrics`` scrape would show, next to the bench JSONs."""
    path = os.environ.get("RAFT_TPU_METRICS_SNAPSHOT")
    if not path:
        return
    import json

    from raft_tpu.core import tracing

    rec = tracing.span_recorder()
    snap = {
        "exit_status": int(exitstatus),
        "counters": tracing.counters(),
        # session totals surviving per-test reset_counters() isolation —
        # what ci/bench_compare.py floors check (the live view above
        # only carries whatever ran after the LAST reset)
        "counters_lifetime": tracing.lifetime_counters(),
        "gauges": tracing.gauges(),
        "histograms": tracing.histograms(),
        "spans": {"recorded": len(rec), "dropped": rec.dropped,
                  "capacity": rec.capacity},
    }
    with open(path, "w") as f:
        json.dump(snap, f, indent=2, sort_keys=True, default=str)
        f.write("\n")
