"""Native + fallback IO tests (reference ``bench/ann/src/common/
dataset.hpp`` BinFile behavior)."""

import numpy as np
import pytest

from raft_tpu.io import BinDataset, native_available, read_bin, write_bin


@pytest.fixture(params=[True, False], ids=["native", "numpy"])
def use_native(request):
    if request.param and not native_available():
        pytest.skip("native IO library not built")
    return request.param


class TestBinFile:
    def test_roundtrip_fbin(self, tmp_path, rng_np, use_native):
        data = rng_np.standard_normal((100, 16)).astype(np.float32)
        p = tmp_path / "x.fbin"
        write_bin(p, data, use_native=use_native)
        with BinDataset(p, use_native=use_native) as ds:
            assert ds.shape == (100, 16)
            np.testing.assert_array_equal(ds.read(), data)

    def test_roundtrip_u8bin_i8bin(self, tmp_path, rng_np, use_native):
        for suffix, dt in [("u8bin", np.uint8), ("i8bin", np.int8)]:
            data = rng_np.integers(0, 100, (37, 9)).astype(dt)
            p = tmp_path / f"x.{suffix}"
            write_bin(p, data, use_native=use_native)
            np.testing.assert_array_equal(
                read_bin(p, use_native=use_native), data
            )

    def test_windowed_read(self, tmp_path, rng_np, use_native):
        data = rng_np.standard_normal((64, 8)).astype(np.float32)
        p = tmp_path / "x.fbin"
        write_bin(p, data, use_native=use_native)
        with BinDataset(p, use_native=use_native) as ds:
            np.testing.assert_array_equal(ds.read(10, 20), data[10:30])
            np.testing.assert_array_equal(ds.read(63, 1), data[63:64])

    def test_out_of_bounds(self, tmp_path, rng_np, use_native):
        data = rng_np.standard_normal((10, 4)).astype(np.float32)
        p = tmp_path / "x.fbin"
        write_bin(p, data, use_native=use_native)
        with BinDataset(p, use_native=use_native) as ds:
            with pytest.raises(IndexError):
                ds.read(5, 20)

    def test_truncated_file_rejected(self, tmp_path, use_native):
        p = tmp_path / "bad.fbin"
        with open(p, "wb") as fh:
            np.asarray([1000, 128], np.int32).tofile(fh)
            np.zeros(10, np.float32).tofile(fh)  # far too few
        with pytest.raises(IOError):
            BinDataset(p, use_native=use_native)

    def test_unknown_suffix(self, tmp_path):
        with pytest.raises(ValueError):
            BinDataset(tmp_path / "x.weird")

    def test_cross_impl_compat(self, tmp_path, rng_np):
        # files written by the native writer read back via numpy & vice versa
        if not native_available():
            pytest.skip("native IO library not built")
        data = rng_np.standard_normal((50, 12)).astype(np.float32)
        p1 = tmp_path / "a.fbin"
        p2 = tmp_path / "b.fbin"
        write_bin(p1, data, use_native=True)
        write_bin(p2, data, use_native=False)
        np.testing.assert_array_equal(read_bin(p1, use_native=False), data)
        np.testing.assert_array_equal(read_bin(p2, use_native=True), data)

    def test_threaded_large_read(self, tmp_path, rng_np):
        if not native_available():
            pytest.skip("native IO library not built")
        # > 4 MB so the threaded path engages
        data = rng_np.standard_normal((40000, 32)).astype(np.float32)
        p = tmp_path / "big.fbin"
        write_bin(p, data)
        with BinDataset(p, use_native=True) as ds:
            np.testing.assert_array_equal(ds.read(n_threads=8), data)


class TestPipeline:
    """Native prefetch pipeline + streaming IVF build."""

    def test_iter_chunks_native(self, tmp_path, rng_np):
        from raft_tpu.io import BinDataset, native_available, write_bin

        x = rng_np.standard_normal((1000, 16)).astype(np.float32)
        path = tmp_path / "d.fbin"
        write_bin(path, x)
        ds = BinDataset(path)
        got = np.empty_like(x)
        starts = []
        for first, chunk in ds.iter_chunks(192):
            got[first : first + chunk.shape[0]] = chunk
            starts.append(first)
        np.testing.assert_array_equal(got, x)
        assert starts == list(range(0, 1000, 192))
        ds.close()

    def test_iter_chunks_nocopy_view(self, tmp_path, rng_np):
        from raft_tpu.io import BinDataset, native_available, write_bin

        if not native_available():
            import pytest

            pytest.skip("no native toolchain")
        x = rng_np.standard_normal((300, 8)).astype(np.float32)
        path = tmp_path / "d.fbin"
        write_bin(path, x)
        with BinDataset(path) as ds:
            for first, chunk in ds.iter_chunks(100, copy=False):
                # view contents valid during this iteration
                np.testing.assert_array_equal(
                    chunk, x[first : first + chunk.shape[0]])

    def test_build_streaming_matches_search(self, tmp_path, rng_np):
        from raft_tpu.io import BinDataset, write_bin
        from raft_tpu.neighbors import ivf_flat

        x = rng_np.standard_normal((3000, 24)).astype(np.float32)
        q = rng_np.standard_normal((16, 24)).astype(np.float32)
        path = tmp_path / "d.fbin"
        write_bin(path, x)
        with BinDataset(path) as ds:
            index = ivf_flat.build_streaming(
                None, ivf_flat.IvfFlatIndexParams(n_lists=16), ds,
                chunk_rows=640)
        assert index.size == 3000
        d, i = ivf_flat.search(None, ivf_flat.IvfFlatSearchParams(n_probes=16),
                               index, q, 10)
        # full probes => exact
        d2 = ((q[:, None, :] - x[None, :, :]) ** 2).sum(-1)
        gt = np.argsort(d2, axis=1, kind="stable")[:, :10]
        assert np.array_equal(np.asarray(i), gt)

    def test_pq_build_streaming(self, tmp_path, rng_np):
        from raft_tpu.io import BinDataset, write_bin
        from raft_tpu.neighbors import ivf_pq
        from raft_tpu.utils import eval_recall

        x = rng_np.standard_normal((4000, 32)).astype(np.float32)
        q = rng_np.standard_normal((16, 32)).astype(np.float32)
        path = tmp_path / "d.fbin"
        write_bin(path, x)
        with BinDataset(path) as ds:
            index = ivf_pq.build_streaming(
                None, ivf_pq.IvfPqIndexParams(n_lists=16, pq_dim=16), ds,
                chunk_rows=1024)
        assert index.size == 4000
        _, i = ivf_pq.search(None, ivf_pq.IvfPqSearchParams(n_probes=16),
                             index, q, 10)
        d2 = ((q[:, None, :] - x[None, :, :]) ** 2).sum(-1)
        gt = np.argsort(d2, axis=1, kind="stable")[:, :10]
        r, _, _ = eval_recall(gt, np.asarray(i))
        assert r >= 0.5, r  # full probes, 8x compression bound

        # streamed build ~ in-memory build recall (same trainer shapes)
        mem = ivf_pq.build(None, ivf_pq.IvfPqIndexParams(
            n_lists=16, pq_dim=16), x)
        _, i2 = ivf_pq.search(None, ivf_pq.IvfPqSearchParams(n_probes=16),
                              mem, q, 10)
        r2, _, _ = eval_recall(gt, np.asarray(i2))
        assert abs(r - r2) < 0.12, (r, r2)

    def test_bq_build_streaming(self, tmp_path, rng_np):
        """Streamed codes-only BQ build (the many-times-HBM regime)
        matches the in-memory build's search results (same trainer
        shapes, same encoding), with the over-fetch coming from the
        bound-derived budget instead of the retired hand constant 60."""
        from raft_tpu.io import BinDataset, write_bin
        from raft_tpu.neighbors import ivf_bq
        from raft_tpu.neighbors.refine import refine
        from raft_tpu.utils import eval_recall

        x = rng_np.standard_normal((4000, 32)).astype(np.float32)
        q = rng_np.standard_normal((16, 32)).astype(np.float32)
        path = tmp_path / "d.fbin"
        write_bin(path, x)
        params = ivf_bq.IvfBqIndexParams(n_lists=16, bits=2,
                                         store_vectors=False)
        with BinDataset(path) as ds:
            index = ivf_bq.build_streaming(None, params, ds,
                                           chunk_rows=1024)
        assert index.size == 4000 and index.bits == 2
        assert index.data is None     # codes + scalars only in HBM

        mem = ivf_bq.build(None, params, x)
        sp = ivf_bq.IvfBqSearchParams(n_probes=16)
        # the bound-derived budget (unclustered gaussians are the
        # estimator's hardest case — residual ≈ the whole vector)
        # lands <= the retired constant 60 at the same recall floor
        budget = ivf_bq.overfetch_budget(index, 10)
        assert 10 < budget <= 60, budget
        _, i1 = ivf_bq.search(None, sp, index, q, budget)
        _, i2 = ivf_bq.search(None, sp, mem, q, budget)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))

        # end-to-end recall with refine
        d2 = ((q[:, None, :] - x[None, :, :]) ** 2).sum(-1)
        gt = np.argsort(d2, axis=1, kind="stable")[:, :10]
        _, i = refine(None, x, q, i1, 10)
        r, _, _ = eval_recall(gt, np.asarray(i))
        assert r >= 0.8, r

    def test_bq_build_streaming_with_vectors(self, tmp_path, rng_np):
        """Streaming with store_vectors=True fills the rerank plane
        chunk-by-chunk — fused search then matches the in-memory
        index exactly."""
        from raft_tpu.io import BinDataset, write_bin
        from raft_tpu.neighbors import ivf_bq

        x = rng_np.standard_normal((2000, 32)).astype(np.float32)
        q = rng_np.standard_normal((8, 32)).astype(np.float32)
        path = tmp_path / "dv.fbin"
        write_bin(path, x)
        params = ivf_bq.IvfBqIndexParams(n_lists=8)
        with BinDataset(path) as ds:
            index = ivf_bq.build_streaming(None, params, ds,
                                           chunk_rows=512)
        assert index.data is not None
        mem = ivf_bq.build(None, params, x)
        sp = ivf_bq.IvfBqSearchParams(n_probes=8)
        d1, i1 = ivf_bq.search(None, sp, index, q, 5)
        d2, i2 = ivf_bq.search(None, sp, mem, q, 5)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
        np.testing.assert_array_equal(np.asarray(d1), np.asarray(d2))

    def test_build_streaming_cancellable(self, tmp_path, rng_np):
        """cancel() from another thread interrupts a mid-flight
        streaming build at its per-chunk cancellation point (VERDICT r3
        weak #6: interruptible must actually interrupt the long paths,
        ``core/interruptible.hpp:83`` role)."""
        import threading

        from raft_tpu.core import interruptible
        from raft_tpu.io import BinDataset, write_bin
        from raft_tpu.neighbors import ivf_flat

        x = rng_np.standard_normal((3000, 24)).astype(np.float32)
        path = tmp_path / "d.fbin"
        write_bin(path, x)

        tid = threading.get_ident()
        # arm cancellation for THIS thread before starting: the first
        # yield_() the build reaches must raise
        interruptible.cancel(tid)
        with BinDataset(path) as ds:
            import pytest

            with pytest.raises(interruptible.InterruptedException):
                ivf_flat.build_streaming(
                    None, ivf_flat.IvfFlatIndexParams(n_lists=16), ds,
                    chunk_rows=640)
        # the flag is consumed by the raise — a fresh build succeeds
        with BinDataset(path) as ds:
            index = ivf_flat.build_streaming(
                None, ivf_flat.IvfFlatIndexParams(n_lists=16), ds,
                chunk_rows=640)
        assert index.size == 3000
