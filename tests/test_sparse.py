"""Sparse subsystem tests — reference pattern (cpp/test/sparse/):
every primitive validated against scipy.sparse / numpy references."""

import numpy as np
import pytest
import scipy.sparse as sp
import scipy.spatial.distance as spd

from raft_tpu.distance.types import DistanceType
from raft_tpu.sparse import COO, CSR, convert, linalg, neighbors, ops, solver


@pytest.fixture
def rand_csr(rng_np):
    def make(m=32, n=24, density=0.2, seed=0):
        rs = np.random.RandomState(seed)
        mat = sp.random(m, n, density=density, format="csr",
                        random_state=rs, dtype=np.float32)
        return CSR.from_scipy(mat), mat
    return make


class TestTypesAndConvert:
    def test_roundtrip_dense(self, rand_csr):
        csr, ref = rand_csr()
        np.testing.assert_allclose(np.asarray(csr.to_dense()),
                                   ref.toarray(), rtol=1e-6)
        coo = convert.csr_to_coo(csr)
        np.testing.assert_allclose(np.asarray(coo.to_dense()),
                                   ref.toarray(), rtol=1e-6)
        back = convert.coo_to_csr(coo)
        np.testing.assert_allclose(np.asarray(back.to_dense()),
                                   ref.toarray(), rtol=1e-6)

    def test_coo_padding(self):
        # capacity > actual nnz: padding rows = -1 are ignored
        dense = np.array([[1, 0], [0, 2]], np.float32)
        coo = COO.from_dense(dense, nnz=6)
        assert coo.nnz == 6
        np.testing.assert_allclose(np.asarray(coo.to_dense()), dense)
        csr = convert.coo_to_csr(coo)
        np.testing.assert_allclose(np.asarray(csr.to_dense()), dense)

    def test_from_dense_csr(self):
        dense = np.array([[0, 3, 0], [4, 0, 5]], np.float32)
        csr = CSR.from_dense(dense)
        assert csr.nnz == 3
        np.testing.assert_array_equal(np.asarray(csr.indptr), [0, 1, 3])
        np.testing.assert_allclose(np.asarray(csr.to_dense()), dense)


class TestOps:
    def test_sort_and_dedup(self):
        rows = np.array([2, 0, 2, 0, -1], np.int32)
        cols = np.array([1, 1, 1, 1, 0], np.int32)
        vals = np.array([5.0, 1.0, 7.0, 2.0, 9.0], np.float32)
        coo = COO(rows, cols, vals, (3, 2))
        summed = ops.sum_duplicates(coo)
        dense = np.asarray(summed.to_dense())
        np.testing.assert_allclose(dense, [[0, 3], [0, 0], [0, 12]])
        maxed = ops.max_duplicates(coo)
        np.testing.assert_allclose(np.asarray(maxed.to_dense()),
                                   [[0, 2], [0, 0], [0, 7]])

    def test_remove_scalar_degree(self, rand_csr):
        csr, ref = rand_csr()
        coo = convert.csr_to_coo(csr)
        deg = np.asarray(ops.degree(coo))
        np.testing.assert_array_equal(deg, np.diff(ref.indptr))
        cleaned = ops.remove_zeros(coo)
        np.testing.assert_allclose(np.asarray(cleaned.to_dense()),
                                   ref.toarray())

    def test_row_slice(self, rand_csr):
        csr, ref = rand_csr()
        sliced = ops.row_slice(csr, 8, 20)
        np.testing.assert_allclose(np.asarray(sliced.to_dense()),
                                   ref[8:20].toarray(), rtol=1e-6)


class TestLinalg:
    def test_spmm(self, rand_csr, rng_np):
        csr, ref = rand_csr()
        b = rng_np.standard_normal((24, 7)).astype(np.float32)
        out = linalg.spmm(csr, b)
        np.testing.assert_allclose(np.asarray(out), ref @ b,
                                   rtol=1e-4, atol=1e-5)

    def test_row_norms_and_normalize(self, rand_csr):
        csr, ref = rand_csr()
        np.testing.assert_allclose(
            np.asarray(linalg.row_norm_csr(csr, "l1")),
            np.abs(ref).sum(axis=1).A1, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(
            np.asarray(linalg.row_norm_csr(csr, "l2")),
            np.square(ref.toarray()).sum(axis=1), rtol=1e-5, atol=1e-6)
        normed = linalg.csr_row_normalize(csr, "l1")
        sums = np.abs(np.asarray(normed.to_dense())).sum(axis=1)
        nonzero = np.diff(ref.indptr) > 0
        np.testing.assert_allclose(sums[nonzero], 1.0, rtol=1e-5)

    def test_transpose_add(self, rand_csr):
        a, ra = rand_csr(seed=1)
        b, rb = rand_csr(seed=2)
        t = linalg.transpose(a)
        np.testing.assert_allclose(np.asarray(t.to_dense()),
                                   ra.toarray().T, rtol=1e-6)
        s = linalg.add(a, b)
        np.testing.assert_allclose(np.asarray(s.to_dense()),
                                   (ra + rb).toarray(), rtol=1e-5, atol=1e-6)

    def test_symmetrize(self):
        dense = np.array([[0, 2, 0], [0, 0, 4], [1, 0, 0]], np.float32)
        coo = COO.from_dense(dense)
        sym = linalg.coo_symmetrize(coo)
        np.testing.assert_allclose(np.asarray(sym.to_dense()),
                                   dense + dense.T)

    def test_laplacian(self):
        g = np.array([[0, 1, 1], [1, 0, 0], [1, 0, 0]], np.float32)
        lap = linalg.laplacian(CSR.from_dense(g), normalized=False)
        want = np.diag(g.sum(1)) - g
        np.testing.assert_allclose(np.asarray(lap.to_dense()), want)


class TestDistanceAndNeighbors:
    def test_pairwise(self, rand_csr):
        from raft_tpu.sparse.distance import pairwise_distance
        a, ra = rand_csr(m=20, seed=3)
        b, rb = rand_csr(m=16, seed=4)
        d = pairwise_distance(None, a, b, DistanceType.L2Expanded, tile=8)
        want = spd.cdist(ra.toarray(), rb.toarray(), "sqeuclidean")
        np.testing.assert_allclose(np.asarray(d), want, rtol=1e-3, atol=1e-3)

    @pytest.mark.parametrize("metric,scipy_name", [
        (DistanceType.L2Expanded, "sqeuclidean"),
        (DistanceType.L2SqrtExpanded, "euclidean"),
        (DistanceType.InnerProduct, None),
        (DistanceType.CosineExpanded, "cosine"),
    ])
    def test_pairwise_column_tiled(self, rand_csr, metric, scipy_name):
        """The SPMV-role path: forcing col_tile far below n_cols must
        reproduce the full-width result for every expanded metric."""
        from raft_tpu.sparse.distance import pairwise_distance
        a, ra = rand_csr(m=20, seed=7)
        b, rb = rand_csr(m=16, seed=8)
        d = pairwise_distance(None, a, b, metric, tile=8, col_tile=5)
        if scipy_name is None:  # InnerProduct returns raw similarity
            want = ra.toarray() @ rb.toarray().T
        else:
            want = spd.cdist(ra.toarray(), rb.toarray(), scipy_name)
        np.testing.assert_allclose(np.asarray(d), want,
                                   rtol=1e-3, atol=1e-3)

    def test_pairwise_column_tiled_rejects_unexpanded(self, rand_csr):
        from raft_tpu.core.validation import RaftError
        from raft_tpu.sparse.distance import pairwise_distance
        a, _ = rand_csr(m=8, seed=9)
        with pytest.raises(RaftError, match="expanded metric"):
            pairwise_distance(None, a, a, DistanceType.L1, col_tile=4)

    def test_pairwise_wide_budget_guard(self, rand_csr, monkeypatch):
        """Past the tile budget: decomposable metrics auto-switch to
        column tiling; L1-family fails loudly with the bound."""
        from raft_tpu.core.validation import RaftError
        from raft_tpu.sparse import distance as sdist
        a, ra = rand_csr(m=12, seed=10)
        monkeypatch.setenv("RAFT_TPU_SPARSE_TILE_MB", "0")
        d = sdist.pairwise_distance(None, a, a, DistanceType.L2Expanded)
        want = spd.cdist(ra.toarray(), ra.toarray(), "sqeuclidean")
        np.testing.assert_allclose(np.asarray(d), want, rtol=1e-3,
                                   atol=1e-3)
        with pytest.raises(RaftError, match="budget"):
            sdist.pairwise_distance(None, a, a, DistanceType.L1)

    def test_sparse_knn(self, rand_csr):
        db, rdb = rand_csr(m=64, seed=5)
        q, rq = rand_csr(m=10, seed=6)
        d, i = neighbors.brute_force_knn(None, db, q, 5, tile=16)
        want = spd.cdist(rq.toarray(), rdb.toarray(), "sqeuclidean")
        gt = np.argsort(want, axis=1, kind="stable")[:, :5]
        gt_d = np.take_along_axis(want, gt, axis=1)
        np.testing.assert_allclose(np.sort(np.asarray(d), axis=1),
                                   np.sort(gt_d, axis=1),
                                   rtol=1e-3, atol=1e-3)

    def test_knn_graph(self, rng_np):
        x = rng_np.standard_normal((50, 8)).astype(np.float32)
        g = neighbors.knn_graph(None, x, 4)
        rows = np.asarray(g.rows)
        cols = np.asarray(g.cols)
        valid = rows >= 0
        assert not np.any(rows[valid] == cols[valid])  # no self edges
        # each row has exactly k=4 valid edges (self dropped from k+1)
        counts = np.bincount(rows[valid], minlength=50)
        assert np.all(counts >= 4)

    def test_cross_component_nn(self, rng_np):
        # two well-separated blobs; the crossing edge must connect them
        a = rng_np.standard_normal((20, 4)).astype(np.float32)
        b = rng_np.standard_normal((20, 4)).astype(np.float32) + 50
        x = np.vstack([a, b])
        labels = np.array([0] * 20 + [1] * 20, np.int32)
        edges = neighbors.cross_component_nn(None, x, labels)
        src = np.asarray(edges.rows)
        dst = np.asarray(edges.cols)
        valid = src >= 0
        assert valid.sum() == 2  # one outgoing edge per component
        for s, t in zip(src[valid], dst[valid]):
            assert labels[s] != labels[t]


class TestSolvers:
    def test_mst_path_graph(self):
        # chain 0-1-2-3 with one heavy shortcut: MST = the chain
        dense = np.zeros((4, 4), np.float32)
        for i, w in [(0, 1.0), (1, 2.0), (2, 3.0)]:
            dense[i, i + 1] = dense[i + 1, i] = w
        dense[0, 3] = dense[3, 0] = 10.0
        result = solver.mst(None, CSR.from_dense(dense))
        assert result.n_edges == 3
        np.testing.assert_allclose(result.total_weight, 6.0)
        assert len(set(np.asarray(result.color).tolist())) == 1

    def test_mst_vs_scipy(self, rng_np):
        # random dense symmetric graph; compare weight to scipy
        n = 24
        w = rng_np.random((n, n)).astype(np.float32)
        w = np.triu(w, 1)
        w = w + w.T
        result = solver.mst(None, CSR.from_dense(w))
        from scipy.sparse.csgraph import minimum_spanning_tree
        want = minimum_spanning_tree(w).sum()
        assert result.n_edges == n - 1
        np.testing.assert_allclose(result.total_weight, want, rtol=1e-5)

    def test_lanczos_smallest(self, rng_np):
        # symmetric PSD matrix: compare smallest eigenvalues to numpy
        n = 40
        a = rng_np.standard_normal((n, n)).astype(np.float32)
        m = a @ a.T / n + np.eye(n, dtype=np.float32)
        m[np.abs(m) < 0.05] = 0  # sparsify
        m = (m + m.T) / 2
        evals, evecs = solver.lanczos_smallest(None, CSR.from_dense(m), 3)
        want = np.sort(np.linalg.eigvalsh(m))[:3]
        np.testing.assert_allclose(np.asarray(evals), want,
                                   rtol=5e-2, atol=5e-2)
        # residual check ||Av - λv||
        for j in range(3):
            v = np.asarray(evecs)[:, j]
            lam = float(evals[j])
            assert np.linalg.norm(m @ v - lam * v) < 0.1


class TestReviewRegressions:
    def test_lanczos_breakdown_restart(self):
        """Krylov breakdown (identity matrix) must not fabricate zero
        eigenvalues: restart with fresh orthogonal vectors."""
        from raft_tpu.sparse.solver import lanczos_smallest
        from raft_tpu.sparse.types import CSR

        ev, V = lanczos_smallest(None, CSR.from_dense(np.eye(40, dtype=np.float32)), 3)
        np.testing.assert_allclose(np.asarray(ev), 1.0, atol=1e-3)
        norms = np.linalg.norm(np.asarray(V), axis=0)
        np.testing.assert_allclose(norms, 1.0, atol=1e-3)

    def test_knn_graph_duplicate_rows_degree_cap(self):
        """Duplicate points displace the self-match out of top-(k+1);
        rows must still be capped at k out-edges."""
        from raft_tpu.sparse.neighbors import knn_graph

        g = knn_graph(None, np.zeros((10, 4), np.float32), 3)
        r = np.asarray(g.rows)
        counts = np.bincount(r[r >= 0], minlength=10)
        np.testing.assert_array_equal(counts, 3)

    def test_sparse_pairwise_distance_tiles_both_operands(self):
        from raft_tpu.sparse.distance import pairwise_distance
        from raft_tpu.sparse.types import CSR
        from raft_tpu.distance.pairwise import _pairwise_distance_impl
        from raft_tpu.distance.types import DistanceType

        rng = np.random.default_rng(0)
        x = CSR.from_dense(rng.standard_normal((30, 8)).astype(np.float32))
        y = CSR.from_dense(rng.standard_normal((25, 8)).astype(np.float32))
        d = pairwise_distance(None, x, y, tile=7)
        dref = _pairwise_distance_impl(
            x.to_dense(), y.to_dense(), DistanceType.L2Expanded, 2.0, "highest"
        )
        np.testing.assert_allclose(np.asarray(d), np.asarray(dref), atol=1e-3)


class TestSpgemm:
    def test_matches_dense_product(self, rng_np):
        from raft_tpu.sparse.convert import csr_to_dense, dense_to_csr
        from raft_tpu.sparse.linalg import spgemm

        a = rng_np.standard_normal((12, 8)) * (rng_np.random((12, 8)) < 0.3)
        b = rng_np.standard_normal((8, 10)) * (rng_np.random((8, 10)) < 0.3)
        a, b = a.astype(np.float32), b.astype(np.float32)
        out = spgemm(dense_to_csr(a), dense_to_csr(b))
        np.testing.assert_allclose(np.asarray(csr_to_dense(out)), a @ b,
                                   rtol=1e-5, atol=1e-5)
