"""IVF-PQ tests — reference pattern (cpp/test/neighbors/ann_ivf_pq.cuh):
recall floor scaled to compression ratio, exhaustive-probe sanity,
refinement rescue, both codebook kinds, serialization."""

import numpy as np
import pytest
import scipy.spatial.distance as spd

from raft_tpu.distance.types import DistanceType
from raft_tpu.neighbors import ivf_pq
from raft_tpu.neighbors.refine import refine
from raft_tpu.neighbors.ivf_pq import (
    CodebookKind,
    IvfPqIndexParams,
    IvfPqSearchParams,
)
from raft_tpu.utils import eval_recall


@pytest.fixture(scope="module")
def dataset():
    rng = np.random.default_rng(3)
    # clustered data (IVF-PQ's target regime, and makes recall stable)
    centers = rng.standard_normal((20, 32)) * 5
    labels = rng.integers(0, 20, 5000)
    x = (centers[labels] + rng.standard_normal((5000, 32))).astype(np.float32)
    q = (centers[rng.integers(0, 20, 40)]
         + rng.standard_normal((40, 32))).astype(np.float32)
    return x, q


def _gt(x, q, k):
    d = spd.cdist(q, x, "sqeuclidean")
    idx = np.argsort(d, axis=1, kind="stable")[:, :k]
    return np.take_along_axis(d, idx, axis=1), idx


class TestIvfPq:
    def test_recall_exhaustive(self, dataset):
        """All lists probed: recall limited only by PQ compression."""
        x, q = dataset
        params = IvfPqIndexParams(n_lists=20, pq_dim=8, pq_bits=8,
                                  kmeans_n_iters=10)
        index = ivf_pq.build(None, params, x)
        assert index.size == len(x)
        assert index.codes.shape[2] == 8
        _, idx = ivf_pq.search(None, IvfPqSearchParams(n_probes=20), index, q, 10)
        _, gt_i = _gt(x, q, 10)
        r, _, _ = eval_recall(gt_i, np.asarray(idx))
        assert r >= 0.55, f"recall {r}"  # 16x compression floor

    def test_refinement_rescues_recall(self, dataset):
        """PQ top-40 + exact refine to 10 ≈ exact search (the reference's
        two-pass pattern)."""
        x, q = dataset
        params = IvfPqIndexParams(n_lists=20, pq_dim=8, pq_bits=8)
        index = ivf_pq.build(None, params, x)
        _, cand = ivf_pq.search(None, IvfPqSearchParams(n_probes=20), index, q, 40)
        dist, idx = refine(None, x, q, np.asarray(cand), 10)
        _, gt_i = _gt(x, q, 10)
        r, _, _ = eval_recall(gt_i, np.asarray(idx))
        assert r >= 0.85, f"refined recall {r}"
        # refined distances must be exact
        gt_d = spd.cdist(q, x, "sqeuclidean")
        got = np.asarray(dist)
        want = np.take_along_axis(gt_d, np.asarray(idx), axis=1)
        np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-2)

    def test_per_cluster_codebooks(self, dataset):
        x, q = dataset
        params = IvfPqIndexParams(n_lists=20, pq_dim=8,
                                  codebook_kind=CodebookKind.PER_CLUSTER)
        index = ivf_pq.build(None, params, x)
        assert index.codebooks.shape[0] == 20
        _, idx = ivf_pq.search(None, IvfPqSearchParams(n_probes=20), index, q, 10)
        _, gt_i = _gt(x, q, 10)
        r, _, _ = eval_recall(gt_i, np.asarray(idx))
        assert r >= 0.5, f"recall {r}"

    def test_pq_bits_4(self, dataset):
        x, q = dataset
        params = IvfPqIndexParams(n_lists=20, pq_dim=16, pq_bits=4)
        index = ivf_pq.build(None, params, x)
        assert index.pq_book_size == 16
        # 4-bit codes are nibble-packed: storage halves, logical pq_dim holds
        assert index.packed and index.codes.shape[2] == 8
        assert index.pq_dim == 16
        from raft_tpu.neighbors.ivf_helpers import pq_unpack_list_data

        codes0, _ = pq_unpack_list_data(index, 0)
        assert codes0.shape[1] == 16
        assert int(np.asarray(codes0).max()) < 16
        _, idx = ivf_pq.search(None, IvfPqSearchParams(n_probes=20), index, q, 10)
        _, gt_i = _gt(x, q, 10)
        r, _, _ = eval_recall(gt_i, np.asarray(idx))
        assert r >= 0.4, f"recall {r}"

    def test_rotation_applied_when_dims_misalign(self):
        rng = np.random.default_rng(0)
        x = rng.standard_normal((500, 30)).astype(np.float32)  # 30 % 8 != 0
        params = IvfPqIndexParams(n_lists=4, pq_dim=8)
        index = ivf_pq.build(None, params, x)
        assert index.dim_ext == 32 and index.pq_len == 4
        _, idx = ivf_pq.search(None, IvfPqSearchParams(n_probes=4), index,
                               x[:5], 1)
        # self-queries should mostly find themselves even through PQ
        assert (np.asarray(idx)[:, 0] == np.arange(5)).mean() >= 0.6

    def test_extend_after_empty_build(self, dataset):
        x, q = dataset
        params = IvfPqIndexParams(n_lists=10, pq_dim=8, add_data_on_build=False)
        index = ivf_pq.build(None, params, x)
        assert index.size == 0
        index = ivf_pq.extend(None, index, x)
        assert index.size == len(x)

    def test_inner_product(self):
        """Gaussian data (healthy IP spread): top-10 must be contained in
        the PQ top-60 candidates. (Normalized clustered data is excluded:
        its top-10 score span is tighter than the 16x quantization error —
        fundamental to PQ, not an implementation property.)"""
        rng = np.random.default_rng(0)
        x = rng.standard_normal((5000, 32)).astype(np.float32)
        q = rng.standard_normal((30, 32)).astype(np.float32)
        params = IvfPqIndexParams(n_lists=10, pq_dim=8,
                                  metric=DistanceType.InnerProduct)
        index = ivf_pq.build(None, params, x)
        sims, cand = ivf_pq.search(None, IvfPqSearchParams(n_probes=10),
                                   index, q, 60)
        # scores must be descending (similarity direction)
        assert (np.diff(np.asarray(sims), axis=1) <= 1e-5).all()
        gt_i = np.argsort(-(q @ x.T), axis=1)[:, :10]
        cand = np.asarray(cand)
        containment = np.mean([
            len(set(cand[i]) & set(gt_i[i])) / 10 for i in range(len(q))
        ])
        assert containment >= 0.85, f"IP containment {containment}"

    def test_serialization_roundtrip(self, dataset, tmp_path):
        x, q = dataset
        params = IvfPqIndexParams(n_lists=10, pq_dim=8)
        index = ivf_pq.build(None, params, x)
        path = tmp_path / "pq.bin"
        ivf_pq.save(index, path)
        loaded = ivf_pq.load(None, path)
        d1, i1 = ivf_pq.search(None, IvfPqSearchParams(n_probes=5), index, q, 5)
        d2, i2 = ivf_pq.search(None, IvfPqSearchParams(n_probes=5), loaded, q, 5)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
        np.testing.assert_allclose(np.asarray(d1), np.asarray(d2), rtol=1e-5)


class TestRefine:
    def test_refine_exact_subset(self):
        rng = np.random.default_rng(1)
        x = rng.standard_normal((200, 8)).astype(np.float32)
        q = rng.standard_normal((10, 8)).astype(np.float32)
        cand = np.tile(np.arange(50, dtype=np.int32), (10, 1))
        dist, idx = refine(None, x, q, cand, 5)
        gt = spd.cdist(q, x[:50], "sqeuclidean")
        want_i = np.argsort(gt, 1)[:, :5]
        np.testing.assert_allclose(
            np.asarray(dist), np.take_along_axis(gt, want_i, 1),
            rtol=1e-3, atol=1e-3)

    def test_refine_with_missing(self):
        rng = np.random.default_rng(1)
        x = rng.standard_normal((50, 4)).astype(np.float32)
        q = x[:2]
        cand = np.array([[0, 1, -1, -1], [2, -1, -1, 3]], np.int32)
        dist, idx = refine(None, x, q, cand, 2)
        idx = np.asarray(idx)
        assert idx[0, 0] == 0  # self
        assert -1 not in idx[:, 0]


class TestScoreModes:
    def test_onehot_matches_gather(self, rng_np):
        """Both scoring paths rank identically (onehot scores in bf16, so
        compare rankings not raw floats)."""
        from raft_tpu.neighbors import ivf_pq
        from raft_tpu.utils import eval_recall

        x = rng_np.standard_normal((2000, 32)).astype(np.float32)
        q = rng_np.standard_normal((16, 32)).astype(np.float32)
        index = ivf_pq.build(
            None, ivf_pq.IvfPqIndexParams(n_lists=16, pq_dim=16), x)
        _, i1 = ivf_pq.search(
            None, ivf_pq.IvfPqSearchParams(n_probes=16), index, q, 10)
        _, i2 = ivf_pq.search(
            None, ivf_pq.IvfPqSearchParams(n_probes=16,
                                           score_mode="onehot"),
            index, q, 10)
        r, _, _ = eval_recall(np.asarray(i1), np.asarray(i2))
        assert r >= 0.95, r

    def test_lut_dtypes_rank_alike(self, dataset):
        """The fp32/bf16/fp8 LUT ladder (reference
        ivf_pq_compute_similarity-inl.cuh:125-177): lower-precision LUTs
        trade a little recall for VMEM; rankings must stay close and the
        fp8 path must not collapse (per-query scaling keeps entries in
        e4m3's +-448 range)."""
        import jax.numpy as jnp
        from raft_tpu.utils import eval_recall

        x, q = dataset
        params = IvfPqIndexParams(n_lists=20, pq_dim=16, pq_bits=8,
                                  kmeans_n_iters=10)
        index = ivf_pq.build(None, params, x)
        ids = {}
        for dt in (jnp.float32, jnp.bfloat16, jnp.float8_e4m3fn):
            _, i = ivf_pq.search(
                None, IvfPqSearchParams(n_probes=20, lut_dtype=dt),
                index, q, 10)
            ids[dt] = np.asarray(i)
        r_bf16, _, _ = eval_recall(ids[jnp.float32], ids[jnp.bfloat16])
        r_fp8, _, _ = eval_recall(ids[jnp.float32], ids[jnp.float8_e4m3fn])
        assert r_bf16 >= 0.95, r_bf16
        assert r_fp8 >= 0.85, r_fp8
        # and against ground truth the fp8 path still finds neighbors
        _, gt = _gt(x, q, 10)
        r_gt, _, _ = eval_recall(gt, ids[jnp.float8_e4m3fn])
        assert r_gt >= 0.7, r_gt

    def test_bad_lut_dtype_rejected(self, dataset):
        import jax.numpy as jnp
        from raft_tpu.core.validation import RaftError

        x, q = dataset
        params = IvfPqIndexParams(n_lists=8, pq_dim=8)
        index = ivf_pq.build(None, params, x[:500])
        with pytest.raises(RaftError, match="lut_dtype"):
            ivf_pq.search(None, IvfPqSearchParams(lut_dtype=jnp.int8),
                          index, q, 5)

    def test_auto_resolution(self, monkeypatch):
        from raft_tpu.core.validation import RaftError
        from raft_tpu.neighbors import ivf_pq as mod

        monkeypatch.setattr(mod.jax, "default_backend", lambda: "tpu")
        assert mod.resolve_score_mode("auto") == "onehot"
        # small codebooks route to the masked-sum select path on TPU
        assert mod.resolve_score_mode("auto", 16) == "select"
        assert mod.resolve_score_mode("auto", 32) == "select"
        assert mod.resolve_score_mode("auto", 64) == "onehot"
        monkeypatch.setattr(mod.jax, "default_backend", lambda: "cpu")
        assert mod.resolve_score_mode("auto") == "gather"
        assert mod.resolve_score_mode("auto", 16) == "gather"
        assert mod.resolve_score_mode("gather") == "gather"
        assert mod.resolve_score_mode("onehot") == "onehot"
        assert mod.resolve_score_mode("select", 16) == "select"
        with pytest.raises(RaftError):
            mod.resolve_score_mode("bogus")

    def test_select_matches_gather_exactly(self, rng_np):
        """The masked-sum select path is pure f32 adds of the same LUT
        entries the gather path reads — results must be bit-identical,
        for every code value in the book."""
        import jax
        import jax.numpy as jnp
        from raft_tpu.neighbors.ivf_pq import _score_gather, _score_select

        for J, s, m in ((16, 8, 37), (32, 4, 21)):
            kl, kr = jax.random.split(jax.random.key(J))
            lut = jax.random.normal(kl, (5, s, J), jnp.float32)
            rows = jax.random.randint(kr, (5, m, s), 0, J,
                                      jnp.int32).astype(jnp.uint8)
            # force coverage of every codeword incl. the J-1 edge
            rows = rows.at[0, 0, :].set(J - 1).at[0, 1, :].set(0)
            a = np.asarray(_score_gather(lut, rows))
            b = np.asarray(_score_select(lut, rows))
            np.testing.assert_array_equal(a, b)

    def test_select_mode_end_to_end(self, rng_np):
        """pq_bits=4 search via score_mode='select' returns the same
        neighbors as the gather reference path."""
        from raft_tpu.neighbors import ivf_pq

        x = rng_np.standard_normal((3000, 32)).astype(np.float32)
        q = rng_np.standard_normal((16, 32)).astype(np.float32)
        index = ivf_pq.build(
            None, ivf_pq.IvfPqIndexParams(n_lists=16, pq_dim=16,
                                          pq_bits=4), x)
        _, i1 = ivf_pq.search(
            None, ivf_pq.IvfPqSearchParams(n_probes=16,
                                           score_mode="gather"),
            index, q, 10)
        _, i2 = ivf_pq.search(
            None, ivf_pq.IvfPqSearchParams(n_probes=16,
                                           score_mode="select"),
            index, q, 10)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))


class TestIntDatasets:
    """Reference supports float/int8/uint8 datasets (ivf_pq_types.hpp);
    self-query must return itself first."""

    @pytest.mark.parametrize("dtype,lo,hi", [(np.int8, -100, 100),
                                             (np.uint8, 0, 200)])
    def test_int_dataset_self_hit(self, rng_np, dtype, lo, hi):
        from raft_tpu.neighbors import ivf_pq

        x = rng_np.integers(lo, hi, (2000, 32)).astype(dtype)
        q = x[:8].astype(np.float32)
        idx = ivf_pq.build(
            None, ivf_pq.IvfPqIndexParams(n_lists=16, pq_dim=16), x)
        _, i = ivf_pq.search(
            None, ivf_pq.IvfPqSearchParams(n_probes=16), idx, q, 5)
        assert (np.asarray(i)[:, 0] == np.arange(8)).all()


class TestNibblePacking:
    def test_roundtrip_and_extend(self, rng_np):
        """Packed 4-bit index: save/load round-trips, extend preserves
        packing, search results equal across the packed/unpacked forms."""
        import io as _io

        import dataclasses as _dc

        from raft_tpu.neighbors.ivf_pq import _unpack_nibbles

        x = rng_np.standard_normal((2000, 32)).astype(np.float32)
        q = rng_np.standard_normal((16, 32)).astype(np.float32)
        params = IvfPqIndexParams(n_lists=16, pq_dim=16, pq_bits=4)
        index = ivf_pq.build(None, params, x)
        assert index.packed

        # search equivalence vs manually unpacked index
        loose = _dc.replace(index, codes=_unpack_nibbles(index.codes),
                            packed=False)
        sp = IvfPqSearchParams(n_probes=16)
        d1, i1 = ivf_pq.search(None, sp, index, q, 10)
        d2, i2 = ivf_pq.search(None, sp, loose, q, 10)
        assert np.array_equal(np.asarray(i1), np.asarray(i2))
        # XLA fuses the two layouts differently; float association only
        np.testing.assert_allclose(np.asarray(d1), np.asarray(d2),
                                   rtol=1e-5)

        # serialization round-trip keeps packing
        buf = _io.BytesIO()
        ivf_pq.save(index, buf)
        buf.seek(0)
        index2 = ivf_pq.load(None, buf)
        assert index2.packed
        _, i3 = ivf_pq.search(None, sp, index2, q, 10)
        assert np.array_equal(np.asarray(i1), np.asarray(i3))

        # extend keeps packing and adds rows
        index3 = ivf_pq.extend(None, index, x[:100],
                               np.arange(2000, 2100, dtype=np.int32))
        assert index3.packed and index3.size == 2100


class TestApproxCoarse:
    def test_approx_coarse_matches_exact_closely(self, dataset):
        """coarse_algo='approx' (TPU approximate top-k unit; exact
        fallback semantics on CPU) returns near-identical results."""
        from raft_tpu.neighbors import ivf_pq
        from raft_tpu.utils import eval_recall

        x, q = dataset
        index = ivf_pq.build(
            None, ivf_pq.IvfPqIndexParams(n_lists=16, pq_dim=16), x)
        _, i1 = ivf_pq.search(
            None, ivf_pq.IvfPqSearchParams(n_probes=8), index, q, 10)
        _, i2 = ivf_pq.search(
            None, ivf_pq.IvfPqSearchParams(n_probes=8,
                                           coarse_algo="approx"),
            index, q, 10)
        r, _, _ = eval_recall(np.asarray(i1), np.asarray(i2))
        assert r >= 0.9, r
        with pytest.raises(Exception):
            ivf_pq.search(None, ivf_pq.IvfPqSearchParams(
                coarse_algo="bogus"), index, q, 5)
