"""linalg tests — numpy/scipy cross-checks, mirroring the reference's
``cpp/test/linalg/`` naive-reference pattern (SURVEY.md §4)."""

import jax.numpy as jnp
import numpy as np

from raft_tpu import linalg


class TestBlas:
    def test_gemm(self, rng_np, res):
        a = rng_np.standard_normal((17, 9)).astype(np.float32)
        b = rng_np.standard_normal((9, 23)).astype(np.float32)
        out = linalg.gemm(res, a, b)
        np.testing.assert_allclose(np.asarray(out), a @ b, rtol=1e-5, atol=1e-5)

    def test_gemm_trans_alpha_beta(self, rng_np, res):
        a = rng_np.standard_normal((9, 17)).astype(np.float32)
        b = rng_np.standard_normal((23, 9)).astype(np.float32)
        c = rng_np.standard_normal((17, 23)).astype(np.float32)
        out = linalg.gemm(res, a, b, alpha=2.0, beta=0.5, c=c, trans_a=True, trans_b=True)
        np.testing.assert_allclose(
            np.asarray(out), 2.0 * (a.T @ b.T) + 0.5 * c, rtol=1e-4, atol=1e-4
        )

    def test_gemv_axpy_dot(self, rng_np, res):
        a = rng_np.standard_normal((11, 7)).astype(np.float32)
        x = rng_np.standard_normal(7).astype(np.float32)
        y = rng_np.standard_normal(11).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(linalg.gemv(res, a, x)), a @ x, rtol=1e-5, atol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(linalg.axpy(res, 2.0, y, y)), 3.0 * y, rtol=1e-6
        )
        np.testing.assert_allclose(
            np.asarray(linalg.dot(res, x, x)), x @ x, rtol=1e-5
        )


class TestElementwise:
    def test_ops(self, rng_np, res):
        x = rng_np.standard_normal((5, 6)).astype(np.float32)
        y = rng_np.standard_normal((5, 6)).astype(np.float32)
        np.testing.assert_allclose(np.asarray(linalg.add(res, x, y)), x + y)
        np.testing.assert_allclose(np.asarray(linalg.subtract(res, x, y)), x - y)
        np.testing.assert_allclose(np.asarray(linalg.multiply(res, x, y)), x * y)
        np.testing.assert_allclose(
            np.asarray(linalg.divide(res, x, np.abs(y) + 1)), x / (np.abs(y) + 1)
        )
        np.testing.assert_allclose(
            np.asarray(linalg.sqrt(res, np.abs(x))), np.sqrt(np.abs(x)), rtol=1e-6
        )
        np.testing.assert_allclose(
            np.asarray(linalg.unary_op(res, x, lambda v: v * 3)), x * 3
        )

    def test_map_offset(self, res):
        out = linalg.map_offset(res, (3, 4), lambda i: i * 2, dtype=jnp.int32)
        np.testing.assert_array_equal(
            np.asarray(out), (np.arange(12) * 2).reshape(3, 4)
        )


class TestMatrixVector:
    def test_along_rows(self, rng_np, res):
        m = rng_np.standard_normal((6, 4)).astype(np.float32)
        v = rng_np.standard_normal(4).astype(np.float32)
        out = linalg.matrix_vector_op(res, m, v, jnp.add, along_rows=True)
        np.testing.assert_allclose(np.asarray(out), m + v[None, :])

    def test_along_cols(self, rng_np, res):
        m = rng_np.standard_normal((6, 4)).astype(np.float32)
        v = rng_np.standard_normal(6).astype(np.float32)
        out = linalg.matrix_vector_op(res, m, v, jnp.multiply, along_rows=False)
        np.testing.assert_allclose(np.asarray(out), m * v[:, None])


class TestReduce:
    def test_reduce_rows_cols(self, rng_np, res):
        # XLA's f32 reduce order differs from numpy's pairwise
        # summation by O(n * eps * sum|x|) ABSOLUTE error (~1 ulp of
        # the largest addend). A row of +-O(1) values can cancel to a
        # sum near 0, where that 6e-8 shows up as 6e-5 *relative* —
        # so rtol alone is the wrong contract for a sum. atol is
        # pinned to n * eps * max_row(sum|x|) with margin: 5 addends
        # * 1.2e-7 * ~4 ≈ 2.4e-6 → 1e-5.
        m = rng_np.standard_normal((8, 5)).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(linalg.coalesced_reduction(res, m)), m.sum(axis=1),
            rtol=1e-5, atol=1e-5,
        )
        np.testing.assert_allclose(
            np.asarray(linalg.strided_reduction(res, m)), m.sum(axis=0),
            rtol=1e-5, atol=1e-5,
        )

    def test_norms(self, rng_np, res):
        m = rng_np.standard_normal((8, 5)).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(linalg.norm(res, m, linalg.L1Norm)),
            np.abs(m).sum(axis=1),
            rtol=1e-5,
        )
        # reference L2 norm is squared unless sqrt=True
        np.testing.assert_allclose(
            np.asarray(linalg.norm(res, m, linalg.L2Norm)),
            (m**2).sum(axis=1),
            rtol=1e-5,
        )
        np.testing.assert_allclose(
            np.asarray(linalg.norm(res, m, linalg.L2Norm, sqrt=True)),
            np.linalg.norm(m, axis=1),
            rtol=1e-5,
        )
        np.testing.assert_allclose(
            np.asarray(linalg.norm(res, m, linalg.LinfNorm, along_rows=False)),
            np.abs(m).max(axis=0),
            rtol=1e-5,
        )

    def test_normalize(self, rng_np, res):
        m = rng_np.standard_normal((8, 5)).astype(np.float32)
        out = np.asarray(linalg.normalize(res, m))
        np.testing.assert_allclose(np.linalg.norm(out, axis=1), 1.0, rtol=1e-5)

    def test_mse(self, rng_np, res):
        a = rng_np.standard_normal((8, 5)).astype(np.float32)
        b = rng_np.standard_normal((8, 5)).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(linalg.mean_squared_error(res, a, b)),
            ((a - b) ** 2).mean(),
            rtol=1e-5,
        )

    def test_reduce_rows_by_key(self, rng_np, res):
        m = rng_np.standard_normal((20, 4)).astype(np.float32)
        keys = rng_np.integers(0, 3, 20)
        out = np.asarray(linalg.reduce_rows_by_key(res, m, jnp.asarray(keys), 3))
        want = np.zeros((3, 4), np.float32)
        for i, k in enumerate(keys):
            want[k] += m[i]
        np.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-5)

    def test_reduce_cols_by_key(self, rng_np, res):
        m = rng_np.standard_normal((4, 20)).astype(np.float32)
        keys = rng_np.integers(0, 5, 20)
        out = np.asarray(linalg.reduce_cols_by_key(res, m, jnp.asarray(keys), 5))
        want = np.zeros((4, 5), np.float32)
        for j, k in enumerate(keys):
            want[:, k] += m[:, j]
        np.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-5)


class TestSolvers:
    def test_eig(self, rng_np, res):
        a = rng_np.standard_normal((12, 12)).astype(np.float32)
        a = a @ a.T + 12 * np.eye(12, dtype=np.float32)
        v, w = linalg.eig_dc(res, a)
        v, w = np.asarray(v), np.asarray(w)
        np.testing.assert_allclose(a @ v, v * w[None, :], rtol=1e-2, atol=1e-2)
        assert np.all(np.diff(w) >= -1e-4)  # ascending

    def test_svd(self, rng_np, res):
        a = rng_np.standard_normal((15, 8)).astype(np.float32)
        u, s, v = (np.asarray(z) for z in linalg.svd(res, a))
        np.testing.assert_allclose(u @ np.diag(s) @ v.T, a, rtol=1e-3, atol=1e-3)

    def test_qr(self, rng_np, res):
        a = rng_np.standard_normal((10, 6)).astype(np.float32)
        q, r = (np.asarray(z) for z in linalg.qr(res, a))
        np.testing.assert_allclose(q @ r, a, rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(q.T @ q, np.eye(6), atol=1e-4)

    def test_rsvd_low_rank_recovery(self, rng_np, res):
        # exact low-rank matrix: rsvd must recover it to float tolerance
        u0 = rng_np.standard_normal((40, 5)).astype(np.float32)
        v0 = rng_np.standard_normal((5, 30)).astype(np.float32)
        a = u0 @ v0
        u, s, v = linalg.rsvd(res, a, 5, n_iters=3)
        approx = np.asarray(u) @ np.diag(np.asarray(s)) @ np.asarray(v).T
        np.testing.assert_allclose(approx, a, rtol=1e-2, atol=1e-2)
        s_true = np.linalg.svd(a, compute_uv=False)[:5]
        np.testing.assert_allclose(np.asarray(s), s_true, rtol=1e-3)

    def test_lstsq(self, rng_np, res):
        a = rng_np.standard_normal((30, 6)).astype(np.float32)
        x_true = rng_np.standard_normal(6).astype(np.float32)
        b = a @ x_true
        x = np.asarray(linalg.lstsq(res, a, b))
        np.testing.assert_allclose(x, x_true, rtol=1e-3, atol=1e-3)

    def test_cholesky_rank_one_update(self, rng_np, res):
        n = 7
        a = rng_np.standard_normal((n, n)).astype(np.float32)
        a = a @ a.T + n * np.eye(n, dtype=np.float32)
        x = rng_np.standard_normal(n).astype(np.float32)
        l0 = np.linalg.cholesky(a)
        l1 = np.asarray(linalg.cholesky_rank_one_update(res, l0, x))
        np.testing.assert_allclose(
            l1 @ l1.T, a + np.outer(x, x), rtol=1e-3, atol=1e-3
        )


class TestReduceInitSemantics:
    def test_init_seeds_accumulator(self, res):
        """init is the accumulator seed (reference linalg::reduce), not an
        additive bias: max-reduce of negatives with init=0 returns 0."""
        out = linalg.reduce(res, jnp.array([[-5.0, -2.0]]), reduce_op=jnp.max, init=0.0)
        assert float(out[0]) == 0.0
