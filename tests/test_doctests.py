"""Docstring examples are executable documentation — the reference runs
every pylibraft docstring example as a test
(``python/pylibraft/pylibraft/test/test_doctests.py:1``). Redesigned:
instead of the reference's fixture-generator over hand-listed modules,
this walks the whole ``raft_tpu`` package tree, collects doctests from
every importable public module, and runs them with NORMALIZE_WHITESPACE
(+ELLIPSIS) under the CPU conftest."""

from __future__ import annotations

import doctest
import importlib
import pkgutil

import pytest

import raft_tpu

_FLAGS = doctest.NORMALIZE_WHITESPACE | doctest.ELLIPSIS


def _modules():
    mods = []
    for info in pkgutil.walk_packages(raft_tpu.__path__, "raft_tpu."):
        if any(part.startswith("_") for part in info.name.split(".")[1:]):
            continue  # private modules document internals, not API
        try:
            mods.append(importlib.import_module(info.name))
        except Exception:  # noqa: BLE001 — optional-dep module
            continue
    return mods


_MODULES = _modules()


def _tests_of(mod):
    # exclude_empty only drops empty DOCSTRINGS; a docstring with no
    # ``>>>`` examples still yields a (vacuous) DocTest — filter those
    return [t for t in doctest.DocTestFinder(exclude_empty=True).find(
        mod, mod.__name__) if t.examples]


@pytest.mark.parametrize("mod", _MODULES, ids=lambda m: m.__name__)
def test_docstring_examples(mod):
    tests = _tests_of(mod)
    if not tests:
        pytest.skip("no docstring examples in this module")
    runner = doctest.DocTestRunner(optionflags=_FLAGS)
    failed = attempted = 0
    for t in tests:
        res = runner.run(t)
        failed += res.failed
        attempted += res.attempted
    assert failed == 0, (f"{failed}/{attempted} docstring example(s) "
                         f"failed in {mod.__name__}")


def test_examples_exist():
    """The runner must not be vacuous: the flagship APIs carry runnable
    examples (brute_force / ivf_flat / ivf_pq / cagra / kmeans /
    pairwise_distance / select_k / make_blobs)."""
    total = sum(len(_tests_of(m)) for m in _MODULES)
    assert total >= 8, f"only {total} docstring examples found"
