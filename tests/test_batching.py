"""tile_queries tests: uniform (padded) tile shapes, ragged-tail
correctness, and 2-D per-query filter-word slicing staying aligned with
its query tile."""

import jax.numpy as jnp
import numpy as np

from raft_tpu.neighbors import ivf_flat
from raft_tpu.neighbors._batching import pad_rows, tile_queries
from raft_tpu.neighbors.filters import BitmapFilter


class TestTileQueries:
    def test_uniform_tile_shapes(self, rng_np):
        """Every tile — including the ragged tail — must arrive at the
        run callback with the same (query_tile, d) shape, so only ONE
        program specialization ever compiles."""
        q = rng_np.standard_normal((10, 3)).astype(np.float32)
        seen = []

        def run(qt, fw):
            seen.append(qt.shape)
            return qt[:, :1], jnp.ones((qt.shape[0], 1), jnp.int32)

        d, i = tile_queries(run, jnp.asarray(q), None, 4)
        assert seen == [(4, 3), (4, 3), (4, 3)]
        assert d.shape == (10, 1) and i.shape == (10, 1)
        np.testing.assert_allclose(np.asarray(d), q[:, :1])

    def test_ragged_tail_correctness(self, rng_np):
        """Tiled results (with the tail padded into the bucket) must
        equal the single-shot run exactly."""
        q = rng_np.standard_normal((11, 4)).astype(np.float32)

        def run(qt, fw):
            d = jnp.cumsum(qt, axis=1)[:, -2:]
            return d, jnp.argsort(qt, axis=1)[:, :2].astype(jnp.int32)

        d0, i0 = run(jnp.asarray(q), None)
        d1, i1 = tile_queries(run, jnp.asarray(q), None, 4)
        np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))

    def test_2d_filter_words_stay_aligned(self, rng_np):
        """Per-query (2-D) filter words must be sliced AND padded with
        their query tile; a misalignment would feed tile t's queries
        with tile t±1's filter rows."""
        q = rng_np.standard_normal((9, 4)).astype(np.float32)
        fw = jnp.asarray(
            rng_np.integers(0, 2**31, (9, 2)).astype(np.uint32))

        def run(qt, fwt):
            assert fwt.shape[0] == qt.shape[0]  # aligned rows
            # a row-mixing function of (query, filter) so any row
            # misalignment changes the output
            d = qt[:, :1] + fwt.astype(jnp.float32).sum(1, keepdims=True)
            return d, fwt[:, :1].astype(jnp.int32)

        d0, i0 = run(jnp.asarray(q), fw)
        d1, i1 = tile_queries(run, jnp.asarray(q), fw, 4)
        np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))

    def test_1d_filter_words_pass_through(self, rng_np):
        q = rng_np.standard_normal((7, 2)).astype(np.float32)
        fw = jnp.asarray(np.array([123, 456], np.uint32))

        def run(qt, fwt):
            assert fwt is fw  # shared words: not sliced, not padded
            return qt[:, :1], jnp.zeros((qt.shape[0], 1), jnp.int32)

        d, _ = tile_queries(run, jnp.asarray(q), fw, 3)
        assert d.shape == (7, 1)

    def test_pad_rows(self):
        x = jnp.ones((3, 2), jnp.float32)
        p = pad_rows(x, 5)
        assert p.shape == (5, 2)
        np.testing.assert_array_equal(np.asarray(p[3:]), 0.0)
        assert pad_rows(x, 3) is x


class TestEndToEndTiling:
    def test_ivf_flat_tiled_matches_untiled_with_bitmap(self, rng_np):
        """Real-index regression: per-query BitmapFilter + small
        query_tile (forcing a padded ragged tail) must equal the
        untiled search bit-for-bit."""
        x = rng_np.standard_normal((400, 8)).astype(np.float32)
        q = rng_np.standard_normal((11, 8)).astype(np.float32)
        index = ivf_flat.build(
            None, ivf_flat.IvfFlatIndexParams(n_lists=8), x)
        p = ivf_flat.IvfFlatSearchParams(n_probes=8)
        mask = rng_np.random((11, 400)) < 0.7
        bm = BitmapFilter.from_mask(mask)
        d0, i0 = ivf_flat.search(None, p, index, q, 5, sample_filter=bm)
        d1, i1 = ivf_flat.search(None, p, index, q, 5, sample_filter=bm,
                                 query_tile=4)
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
        np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))
