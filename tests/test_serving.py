"""Serving-frontend tests: dynamic batcher coalescing + re-split
correctness, dual-trigger timing, admission control, the load-shed
ladder, and the fault-injection suite (deadline expiry mid-queue,
overflow -> typed Overloaded, cancellation before/after batch
assembly, clean shutdown drain) — all deterministic via the manual
clock + executor shims (no sleeps-as-synchronization), plus the
real-executor acceptance criteria: bit-identity with direct
``SearchExecutor`` calls under coalescing, and zero-recompile steady
state asserted against ``xla.backend_compile_count``."""

import dataclasses
import threading

import numpy as np
import pytest

from raft_tpu import SearchExecutor
from raft_tpu.core import tracing
from raft_tpu.neighbors import brute_force, ivf_flat
from raft_tpu.serving import (
    BatcherConfig,
    Cancelled,
    DeadlineExceeded,
    DynamicBatcher,
    LoadShed,
    Overloaded,
    ShutDown,
)
from raft_tpu.serving import metrics
from raft_tpu.serving.harness import (
    FakeExecutor,
    ManualClock,
    ShimExecutor,
    burst_schedule,
    drive_open_loop,
)


class _Index:
    """Opaque index token for FakeExecutor tests."""


def q_block(ids, dim=4):
    """Query block whose first column encodes per-row ids (the
    FakeExecutor reflects them into results)."""
    b = np.zeros((len(ids), dim), np.float32)
    b[:, 0] = ids
    return b


def manual_batcher(executor=None, **cfg):
    clock = ManualClock()
    ex = executor or FakeExecutor()
    b = DynamicBatcher(ex, BatcherConfig(**cfg), clock=clock,
                       start=False)
    return b, ex, clock


class TestCoalescing:
    def test_batches_and_splits_per_request(self):
        b, ex, clock = manual_batcher(max_wait_s=0.01)
        idx = _Index()
        h1 = b.submit(idx, q_block([1, 2]), 3)
        h2 = b.submit(idx, q_block([7]), 3)
        h3 = b.submit(idx, q_block([4, 5, 6]), 3)
        assert b.pump() == 0          # neither trigger armed yet
        clock.advance(0.01)           # max-wait timer fires
        assert b.pump() == 1
        assert ex.calls == [(3, 6)]   # ONE coalesced executor call
        d1, i1 = h1.result(timeout=0)
        np.testing.assert_array_equal(i1[:, 0], [1 * 3, 2 * 3])
        _, i2 = h2.result(timeout=0)
        np.testing.assert_array_equal(i2[:, 0], [7 * 3])
        _, i3 = h3.result(timeout=0)
        np.testing.assert_array_equal(i3[:, 0], [4 * 3, 5 * 3, 6 * 3])
        assert d1.shape == (2, 3)
        b.close()

    def test_incompatible_requests_do_not_coalesce(self):
        b, ex, clock = manual_batcher(max_wait_s=0.01)
        idx, idx2 = _Index(), _Index()
        b.submit(idx, q_block([1]), 3)
        b.submit(idx, q_block([2]), 5)      # different k
        b.submit(idx2, q_block([3]), 3)     # different index identity
        clock.advance(0.01)
        assert b.pump() == 3
        assert sorted(ex.calls) == [(1, 1)] * 3
        b.close()

    def test_bucket_full_dispatches_without_wait(self):
        b, ex, clock = manual_batcher(max_wait_s=10.0, full_batch_rows=4)
        idx = _Index()
        b.submit(idx, q_block([1, 2]), 3)
        b.submit(idx, q_block([3, 4]), 3)
        # rows == full_batch_rows: dispatches with NO time advance
        assert b.pump() == 1
        assert ex.calls == [(2, 4)]
        b.close()

    def test_oversized_request_dispatches_alone(self):
        b, ex, clock = manual_batcher(max_wait_s=10.0, full_batch_rows=4)
        idx = _Index()
        h = b.submit(idx, q_block(list(range(10))), 2)
        assert b.pump() == 1           # 10 rows >= full -> immediate
        assert ex.calls == [(1, 10)]
        _, i = h.result(timeout=0)
        assert i.shape == (10, 2)
        b.close()

    def test_max_rows_splits_across_micro_batches(self):
        b, ex, clock = manual_batcher(max_wait_s=0.0, full_batch_rows=4)
        idx = _Index()
        for i in range(3):
            b.submit(idx, q_block([i, i + 10, i + 20]), 2)  # 3 rows each
        assert b.pump() >= 2
        assert sum(r for _, r in ex.calls) == 9
        assert all(r <= 4 for _, r in ex.calls)
        b.close()


class TestScheduling:
    def test_edf_within_priority(self):
        b, ex, clock = manual_batcher(max_wait_s=0.0, full_batch_rows=2)
        late, soon = _Index(), _Index()
        b.submit(late, q_block([1]), 3, timeout_s=100.0)
        b.submit(soon, q_block([2]), 3, timeout_s=1.0)
        b.pump()
        # the earlier-deadline group dispatched first
        assert ex.calls and ex.calls[0] == (1, 1)
        b.close()

    def test_priority_beats_deadline(self):
        b, ex, clock = manual_batcher(max_wait_s=0.0)
        lo, hi = _Index(), _Index()
        h_lo = b.submit(lo, q_block([1]), 3, timeout_s=1.0, priority=1)
        h_hi = b.submit(hi, q_block([2, 3]), 3, priority=0)  # no deadline
        b.pump()
        assert ex.calls[0] == (1, 2)   # priority-0 group first
        assert h_lo.done() and h_hi.done()
        b.close()


class TestFaultPaths:
    """The ISSUE's deterministic fault-injection suite."""

    def test_deadline_expiry_mid_queue_sheds_before_dispatch(self):
        metrics.reset()
        b, ex, clock = manual_batcher(max_wait_s=1.0)
        idx = _Index()
        h = b.submit(idx, q_block([1]), 3, timeout_s=0.5)
        clock.advance(0.75)            # past deadline, before max-wait
        assert b.pump() == 0
        with pytest.raises(DeadlineExceeded):
            h.result(timeout=0)
        assert ex.calls == []          # NO device work was spent
        assert tracing.get_counter("serving.batcher.shed_deadline") == 1
        b.close()

    def test_queue_overflow_raises_typed_overloaded(self):
        b, ex, clock = manual_batcher(max_wait_s=10.0, capacity=2)
        idx = _Index()
        b.submit(idx, q_block([1]), 3)
        b.submit(idx, q_block([2]), 3)
        with pytest.raises(Overloaded):
            b.submit(idx, q_block([3]), 3)
        b.close()

    def test_cancellation_before_assembly(self):
        metrics.reset()
        b, ex, clock = manual_batcher(max_wait_s=0.01)
        idx = _Index()
        h1 = b.submit(idx, q_block([1]), 3)
        h2 = b.submit(idx, q_block([2]), 3)
        assert h1.cancel() is True
        assert h1.cancelled()
        with pytest.raises(Cancelled):
            h1.result(timeout=0)
        clock.advance(0.01)
        b.pump()
        assert ex.calls == [(1, 1)]    # only the live request ran
        assert h2.result(timeout=0)[1][0, 0] == 2 * 3
        assert tracing.get_counter("serving.batcher.cancelled") == 1
        b.close()

    def test_cancellation_after_assembly_fails(self):
        b, ex, clock = manual_batcher(max_wait_s=0.0)
        idx = _Index()
        h = b.submit(idx, q_block([5]), 3)
        b.pump()                       # assembled + completed
        assert h.cancel() is False     # too late — result stands
        assert h.result(timeout=0)[1][0, 0] == 5 * 3
        b.close()

    def test_shutdown_drains_in_flight(self):
        b, ex, clock = manual_batcher(max_wait_s=100.0)
        idx = _Index()
        hs = [b.submit(idx, q_block([i]), 3) for i in range(4)]
        b.close(drain=True)            # dispatches despite max-wait
        for i, h in enumerate(hs):
            assert h.result(timeout=0)[1][0, 0] == i * 3
        assert ex.calls == [(4, 4)]

    def test_shutdown_without_drain_fails_typed(self):
        metrics.reset()
        b, ex, clock = manual_batcher(max_wait_s=100.0)
        idx = _Index()
        h = b.submit(idx, q_block([1]), 3)
        b.close(drain=False)
        with pytest.raises(ShutDown):
            h.result(timeout=0)
        assert ex.calls == []
        with pytest.raises(ShutDown):
            b.submit(idx, q_block([2]), 3)
        assert tracing.get_counter("serving.batcher.shutdown_shed") == 1

    def test_executor_failure_fails_the_batch_not_the_worker(self):
        inner = FakeExecutor()
        clock = ManualClock()
        shim = ShimExecutor(inner, fail_on={0: RuntimeError("boom")})
        b = DynamicBatcher(shim, BatcherConfig(max_wait_s=0.0),
                           clock=clock, start=False)
        idx = _Index()
        h1 = b.submit(idx, q_block([1]), 3)
        b.pump()
        assert isinstance(h1.exception(timeout=0), RuntimeError)
        h2 = b.submit(idx, q_block([2]), 3)   # worker survives
        b.pump()
        assert h2.result(timeout=0)[1][0, 0] == 2 * 3
        b.close()

    def test_slow_executor_piles_queue_deterministically(self):
        inner = FakeExecutor()
        clock = ManualClock()
        shim = ShimExecutor(inner, delay_s=0.5, clock=clock)
        b = DynamicBatcher(shim, BatcherConfig(max_wait_s=0.0),
                           clock=clock, start=False)
        idx = _Index()
        h1 = b.submit(idx, q_block([1]), 3, timeout_s=0.1)
        b.pump()                        # executes; clock += 0.5
        h2 = b.submit(idx, q_block([2]), 3, timeout_s=0.1)
        clock.advance(0.2)              # h2 expires while "device busy"
        b.pump()
        assert h1.result(timeout=0)[1][0, 0] == 3
        with pytest.raises(DeadlineExceeded):
            h2.result(timeout=0)
        b.close()


class TestLoadShedLadder:
    def test_rung1_shrinks_max_wait(self):
        b, ex, clock = manual_batcher(max_wait_s=100.0, capacity=10)
        idx = _Index()
        for i in range(5):             # occupancy 0.5 -> rung 1
            b.submit(idx, q_block([i]), 3)
        assert b.pump() == 1           # dispatched with NO time advance
        b.close()

    def test_rung2_applies_params_override(self):
        shed = LoadShed(degrade_params_at=0.5,
                        params_override=lambda p: "degraded")
        clock = ManualClock()
        ex = FakeExecutor()
        b = DynamicBatcher(
            ex, BatcherConfig(max_wait_s=0.0, capacity=4, shed=shed),
            clock=clock, start=False)
        idx = _Index()
        b.submit(idx, q_block([1]), 3)
        b.submit(idx, q_block([2]), 3)          # occupancy hits 0.5
        h = b.submit(idx, q_block([3]), 3)      # rung 2: override applies
        assert tracing.get_counter(
            "serving.batcher.shed_degraded_params") >= 1
        b.pump()
        assert h.done()
        b.close()

    def test_rung3_is_typed_overload(self):
        b, ex, clock = manual_batcher(max_wait_s=100.0, capacity=1)
        idx = _Index()
        b.submit(idx, q_block([1]), 3)
        with pytest.raises(Overloaded):
            b.submit(idx, q_block([2]), 3)
        b.close()


class TestOpenLoopLoad:
    def test_bursty_load_coalesces(self):
        metrics.reset()
        b, ex, clock = manual_batcher(max_wait_s=0.005,
                                      full_batch_rows=64)
        idx = _Index()

        def submit(ordinal, t):
            return b.submit(idx, q_block([ordinal]), 3, timeout_s=1.0)

        sched = burst_schedule(n_bursts=5, burst_size=8, period_s=0.01)
        handles = drive_open_loop(submit, sched, clock, pump=b.pump)
        clock.advance(0.01)
        b.pump()
        assert all(h.done() for h in handles)
        occ = metrics.occupancy()
        # bursts coalesce: well above one request per executor call
        assert occ["requests_per_batch"] >= 2.0
        assert tracing.get_counter("serving.batcher.requests") == 40
        b.close()


class TestThreadedMode:
    """Real worker thread + real clock: liveness and leak checks (all
    waits are event-based with bounded timeouts, not sleeps)."""

    def test_background_thread_serves_and_joins(self):
        ex = FakeExecutor()
        b = DynamicBatcher(ex, BatcherConfig(max_wait_s=0.001))
        idx = _Index()
        hs = [b.submit(idx, q_block([i, i + 50]), 4) for i in range(8)]
        for i, h in enumerate(hs):
            _, ii = h.result(timeout=10.0)
            np.testing.assert_array_equal(ii[:, 0], [i * 4, (i + 50) * 4])
        t = b._thread
        b.close()
        assert b._thread is None and not t.is_alive()

    def test_no_leaked_threads_or_futures(self):
        before = threading.active_count()
        for _ in range(3):
            b = DynamicBatcher(FakeExecutor(),
                               BatcherConfig(max_wait_s=0.001))
            h = b.submit(_Index(), q_block([1]), 2)
            h.result(timeout=10.0)
            b.close()
        assert threading.active_count() == before

    def test_concurrent_submitters(self):
        ex = FakeExecutor()
        b = DynamicBatcher(ex, BatcherConfig(max_wait_s=0.001))
        idx = _Index()
        results = {}

        def worker(base):
            h = b.submit(idx, q_block([base]), 2)
            results[base] = h.result(timeout=10.0)

        ts = [threading.Thread(target=worker, args=(i,))
              for i in range(16)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        b.close()
        for base, (_, ii) in results.items():
            assert ii[0, 0] == base * 2


@pytest.fixture(scope="module")
def real_setup():
    rng = np.random.default_rng(3)
    x = rng.standard_normal((400, 16)).astype(np.float32)
    q = rng.standard_normal((24, 16)).astype(np.float32)
    return {
        "x": x, "q": q,
        "bf": brute_force.build(None, x),
        "ivf": ivf_flat.build(
            None, ivf_flat.IvfFlatIndexParams(n_lists=8), x),
    }


class TestRealExecutor:
    """Acceptance criteria against the real serving path."""

    def test_bit_identical_to_direct_executor(self, real_setup):
        ex = SearchExecutor()
        clock = ManualClock()
        b = DynamicBatcher(ex, BatcherConfig(max_wait_s=0.01),
                           clock=clock, start=False)
        q = real_setup["q"]
        p = ivf_flat.IvfFlatSearchParams(n_probes=4)
        cases = [("bf", None, {}), ("ivf", p, {})]
        for name, params, kw in cases:
            index = real_setup[name]
            want_d, want_i = ex.search(index, q, 5, params=params, **kw)
            # three requests coalesce into one call, then re-split
            h1 = b.submit(index, q[:7], 5, params=params, **kw)
            h2 = b.submit(index, q[7:10], 5, params=params, **kw)
            h3 = b.submit(index, q[10:], 5, params=params, **kw)
            clock.advance(0.01)
            b.pump()
            got_d = np.concatenate([np.asarray(h.result(timeout=0)[0])
                                    for h in (h1, h2, h3)])
            got_i = np.concatenate([np.asarray(h.result(timeout=0)[1])
                                    for h in (h1, h2, h3)])
            np.testing.assert_array_equal(got_i, np.asarray(want_i))
            np.testing.assert_array_equal(got_d, np.asarray(want_d))
        b.close()

    def test_steady_state_zero_recompile(self, real_setup):
        tracing.install_xla_compile_listener()
        ex = SearchExecutor()
        clock = ManualClock()
        b = DynamicBatcher(ex, BatcherConfig(max_wait_s=0.01),
                           clock=clock, start=False)
        index, q = real_setup["bf"], real_setup["q"]

        def roundtrip(sizes):
            hs, at = [], 0
            for m in sizes:
                hs.append(b.submit(index, q[at:at + m], 5))
                at += m
            clock.advance(0.01)
            b.pump()
            return [h.result(timeout=0) for h in hs]

        roundtrip([7, 3, 6])           # prime: executable + pad programs
        roundtrip([5, 5, 6])
        backend0 = tracing.get_counter(tracing.XLA_COMPILE_COUNT)
        compiles0 = ex.stats.compile_count
        for sizes in ([7, 3, 6], [5, 5, 6], [16], [7, 3, 6]):
            roundtrip(sizes)
        assert ex.stats.compile_count == compiles0
        assert tracing.get_counter(tracing.XLA_COMPILE_COUNT) == backend0
        b.close()

    def test_degraded_params_stay_zero_recompile_after_warmup(
            self, real_setup):
        """Rung 2's override is part of the coalesce key; warming the
        degraded specialization keeps the whole ladder compile-free."""
        tracing.install_xla_compile_listener()
        index, q = real_setup["ivf"], real_setup["q"]
        p = ivf_flat.IvfFlatSearchParams(n_probes=8)
        p_shed = dataclasses.replace(p, n_probes=2)
        ex = SearchExecutor()
        shed = LoadShed(degrade_params_at=0.4,
                        params_override=lambda _:  p_shed)
        clock = ManualClock()
        b = DynamicBatcher(
            ex, BatcherConfig(max_wait_s=0.0, capacity=10, shed=shed),
            clock=clock, start=False)
        # prime both rungs' specializations through the batcher, at the
        # same coalesced shape steady state produces (5 x 8 rows)
        for params in (p, p_shed):
            hs = [b.submit(index, q[:8], 5, params=params)
                  for _ in range(5)]
            b.pump()
            for h in hs:
                h.result(timeout=0)
        backend0 = tracing.get_counter(tracing.XLA_COMPILE_COUNT)
        hs = [b.submit(index, q[:8], 5, params=p) for _ in range(5)]
        b.pump()
        for h in hs:
            h.result(timeout=0)
        assert tracing.get_counter(tracing.XLA_COMPILE_COUNT) == backend0
        b.close()


class TestFiltersAndCagra:
    """Post-review coverage: filters coalesce safely (or not at all)
    and CAGRA keeps per-block bit-identity while coalescing —
    graftbeam made its seeds a pure function of query content, so
    concatenated blocks cannot perturb each other."""

    def test_distinct_shared_filters_never_coalesce(self, real_setup):
        from raft_tpu.core.bitset import Bitset
        from raft_tpu.neighbors.filters import BitsetFilter

        x, q = real_setup["x"], real_setup["q"]
        index = real_setup["ivf"]
        p = ivf_flat.IvfFlatSearchParams(n_probes=8)
        m1 = np.ones(x.shape[0], bool)
        m1[::2] = False
        m2 = np.ones(x.shape[0], bool)
        m2[1::2] = False
        f1 = BitsetFilter(Bitset.from_mask(m1))
        f2 = BitsetFilter(Bitset.from_mask(m2))
        ex = SearchExecutor()
        want1 = np.asarray(ex.search(index, q[:8], 5, params=p,
                                     sample_filter=f1)[1])
        want2 = np.asarray(ex.search(index, q[8:16], 5, params=p,
                                     sample_filter=f2)[1])
        clock = ManualClock()
        b = DynamicBatcher(ex, BatcherConfig(max_wait_s=0.01),
                           clock=clock, start=False)
        h1 = b.submit(index, q[:8], 5, params=p, sample_filter=f1)
        h2 = b.submit(index, q[8:16], 5, params=p, sample_filter=f2)
        clock.advance(0.01)
        n_batches = b.pump()
        assert n_batches == 2   # equal specs, different words: 2 calls
        np.testing.assert_array_equal(
            np.asarray(h1.result(timeout=0)[1]), want1)
        np.testing.assert_array_equal(
            np.asarray(h2.result(timeout=0)[1]), want2)
        b.close()

    def test_per_row_bitmap_filters_coalesce_and_resplit(self,
                                                         real_setup):
        from raft_tpu.neighbors.filters import BitmapFilter

        x, q = real_setup["x"], real_setup["q"]
        index = real_setup["ivf"]
        p = ivf_flat.IvfFlatSearchParams(n_probes=8)
        rng = np.random.default_rng(5)
        mask = rng.random((16, x.shape[0])) > 0.3
        bm1 = BitmapFilter.from_mask(mask[:9])
        bm2 = BitmapFilter.from_mask(mask[9:])
        ex = SearchExecutor()
        want1 = np.asarray(ex.search(index, q[:9], 5, params=p,
                                     sample_filter=bm1)[1])
        want2 = np.asarray(ex.search(index, q[9:16], 5, params=p,
                                     sample_filter=bm2)[1])
        clock = ManualClock()
        b = DynamicBatcher(ex, BatcherConfig(max_wait_s=0.01),
                           clock=clock, start=False)
        h1 = b.submit(index, q[:9], 5, params=p, sample_filter=bm1)
        h2 = b.submit(index, q[9:16], 5, params=p, sample_filter=bm2)
        clock.advance(0.01)
        assert b.pump() == 1    # per-row words concat: ONE call
        np.testing.assert_array_equal(
            np.asarray(h1.result(timeout=0)[1]), want1)
        np.testing.assert_array_equal(
            np.asarray(h2.result(timeout=0)[1]), want2)
        b.close()

    def test_cagra_blocks_keep_solo_bit_identity(self, real_setup):
        from raft_tpu.neighbors import cagra

        x, q = real_setup["x"], real_setup["q"]
        index = cagra.build(None, cagra.CagraIndexParams(
            graph_degree=8, intermediate_graph_degree=16,
            build_algo=cagra.BuildAlgo.NN_DESCENT), x)
        ex = SearchExecutor()
        # direct solo searches are the oracle: coalesced CAGRA blocks
        # concatenate (content-pure seeds) yet stay bit-identical
        want = [np.asarray(ex.search(index, q[lo:hi], 5)[1])
                for lo, hi in ((0, 7), (7, 12), (12, 24))]
        clock = ManualClock()
        b = DynamicBatcher(ex, BatcherConfig(max_wait_s=0.01),
                           clock=clock, start=False)
        hs = [b.submit(index, q[lo:hi], 5)
              for lo, hi in ((0, 7), (7, 12), (12, 24))]
        clock.advance(0.01)
        b.pump()
        for h, w in zip(hs, want):
            np.testing.assert_array_equal(
                np.asarray(h.result(timeout=0)[1]), w)
        b.close()


class TestHistograms:
    def test_stage_histograms_populate(self):
        metrics.reset()
        b, ex, clock = manual_batcher(max_wait_s=0.0)
        idx = _Index()
        for i in range(4):
            b.submit(idx, q_block([i]), 3)
            b.pump()
        b.close()
        hist = tracing.histograms(metrics.PREFIX)
        for name in (metrics.QUEUE_WAIT, metrics.EXECUTE, metrics.E2E):
            assert hist[name]["count"] == 4, name

    def test_quantile_estimates(self):
        h = tracing.Histogram()
        for v in [0.001] * 90 + [0.1] * 10:
            h.observe(v)
        assert h.count == 100
        assert h.quantile(0.5) <= 0.002
        assert h.quantile(0.99) >= 0.05
        assert h.quantile(0.5) <= h.quantile(0.95) <= h.quantile(0.99)


class TestObservability:
    """graftscope (PR 6): the end-to-end trace acceptance criterion —
    request spans through every stage with one trace_id, shed /
    degrade / cancel reasons in the flight recorder, admission gauges,
    and a live Prometheus scrape of the exporter."""

    def test_end_to_end_span_tree_and_chrome_round_trip(self):
        import json

        metrics.reset()                 # clears spans too
        b, ex, clock = manual_batcher(max_wait_s=0.01)
        idx = _Index()
        h1 = b.submit(idx, q_block([1, 2]), 3, timeout_s=1.0)
        h2 = b.submit(idx, q_block([3]), 3, timeout_s=1.0)
        clock.advance(0.01)
        b.pump()
        assert h1.done() and h2.done()
        rec = tracing.span_recorder()
        # the request's whole journey under ONE trace id, in order
        (req_span,) = rec.spans(name="serving.request")[:1]
        tid = req_span.trace_ids[0]
        stages = {}
        for name in ("serving.admission", "serving.assembly",
                     "serving.execute", "serving.split"):
            got = rec.spans(trace_id=tid, name=name)
            assert got, f"missing {name} span for trace {tid}"
            stages[name] = got[0]
        assert (stages["serving.admission"].start
                <= stages["serving.assembly"].start
                <= stages["serving.execute"].start
                <= stages["serving.split"].start)
        # batch stages carry BOTH coalesced requests' ids
        assert len(stages["serving.execute"].trace_ids) == 2
        assert stages["serving.assembly"].attrs["rows"] == 3
        # Chrome trace-event JSON parses and round-trips exactly
        data = json.loads(json.dumps(rec.to_chrome_trace()))
        assert {e["ph"] for e in data["traceEvents"]} <= {"X", "i"}
        assert tracing.SpanRecorder.from_chrome_trace(data) == rec.spans()
        b.close()

    def test_shed_and_cancel_reasons_in_flight_recorder(self):
        metrics.reset()
        b, ex, clock = manual_batcher(max_wait_s=100.0)
        idx = _Index()
        h_exp = b.submit(idx, q_block([1]), 3, timeout_s=0.05)
        h_cxl = b.submit(idx, q_block([2]), 3, timeout_s=10.0)
        assert h_cxl.cancel()
        clock.advance(0.1)              # expire the first request
        b.pump()
        with pytest.raises(DeadlineExceeded):
            h_exp.result(timeout=0)
        rec = tracing.span_recorder()
        (shed,) = rec.spans(name="serving.shed")
        assert shed.attrs["reason"] == "deadline"
        assert shed.attrs["late_s"] > 0
        (cxl,) = rec.spans(name="serving.cancelled")
        assert cxl.trace_ids != shed.trace_ids
        b.close()

    def test_reject_and_degrade_reasons(self):
        metrics.reset()
        shed = LoadShed(degrade_params_at=0.5,
                        params_override=lambda p: "degraded")
        clock = ManualClock()
        b = DynamicBatcher(
            FakeExecutor(),
            BatcherConfig(max_wait_s=100.0, capacity=2, shed=shed),
            clock=clock, start=False)
        idx = _Index()
        b.submit(idx, q_block([1]), 3)  # occupancy 0.5 -> rung 2 next
        h2 = b.submit(idx, q_block([2]), 3)
        with pytest.raises(Overloaded):
            b.submit(idx, q_block([3]), 3)
        rec = tracing.span_recorder()
        (rej,) = rec.spans(name="serving.rejected")
        assert rej.attrs["reason"] == "queue_full"
        adm = rec.spans(name="serving.admission",
                        trace_id=None)
        degraded = [s for s in adm
                    if any(e[1] == "degraded_params" for e in s.events)]
        assert len(degraded) == 1       # only the rung-2 submission
        assert h2.done() is False
        b.close()

    def test_admission_gauges_and_arrival_rate(self):
        metrics.reset()
        b, ex, clock = manual_batcher(max_wait_s=100.0, capacity=8)
        idx = _Index()
        for i in range(4):              # arrivals spaced exactly 0.1 s
            b.submit(idx, q_block([i]), 3)
            clock.advance(0.1)
        assert tracing.get_gauge("serving.admission.queue_depth") == 4.0
        assert b._queue.arrival_rate() == pytest.approx(10.0)
        assert tracing.get_gauge(
            "serving.admission.arrival_rate_hz") == pytest.approx(10.0)
        assert tracing.get_gauge("serving.admission.shed_level") == 1.0
        b.pump()                        # rung 1: dispatches eagerly
        assert tracing.get_gauge("serving.admission.queue_depth") == 0.0
        b.close()

    def test_exporter_live_scrape(self):
        import json
        import re
        import urllib.request

        from raft_tpu.serving import MetricsExporter

        metrics.reset()
        b, ex, clock = manual_batcher(max_wait_s=0.0)
        idx = _Index()
        for i in range(5):
            b.submit(idx, q_block([i, i]), 3, timeout_s=1.0)
            b.pump()
        with MetricsExporter(executor=ex, batcher=b) as exp:
            text = urllib.request.urlopen(
                exp.url("/metrics"), timeout=10).read().decode()
            # every exposition line parses: name[{labels}] value
            line_re = re.compile(
                r'^[a-zA-Z_:][a-zA-Z0-9_:]*'
                r'(\{[a-zA-Z_]+="[^"]*"(,[a-zA-Z_]+="[^"]*")*\})? '
                r"[-+0-9.e]+$")
            for line in text.strip().splitlines():
                if not line.startswith("#"):
                    assert line_re.match(line), line
            # serving histograms are present with cumulative buckets
            assert "# TYPE serving_batcher_e2e_seconds histogram" in text
            bucket_counts = [
                int(m.group(1)) for m in re.finditer(
                    r'serving_batcher_e2e_seconds_bucket\{le="[^"]*"\} '
                    r"(\d+)", text)]
            assert bucket_counts == sorted(bucket_counts)
            assert bucket_counts[-1] == 5      # +Inf == count
            assert "serving_batcher_e2e_seconds_count 5" in text
            assert "serving_admission_queue_depth" in text
            # JSON snapshot and Chrome trace endpoints
            snap = json.loads(urllib.request.urlopen(
                exp.url("/snapshot.json"), timeout=10).read())
            assert snap["counters"]["serving.batcher.requests"] == 5
            assert snap["admission"]["shed_level"] == 0
            assert snap["spans"]["recorded"] > 0
            trace = json.loads(urllib.request.urlopen(
                exp.url("/trace.json"), timeout=10).read())
            assert any(e["name"] == "serving.execute"
                       for e in trace["traceEvents"])
            assert urllib.request.urlopen(
                exp.url("/healthz"), timeout=10).status == 200
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(exp.url("/nope"), timeout=10)
        b.close()

    def test_real_executor_costs_and_tracing_stay_zero_recompile(
            self, real_setup):
        """Acceptance: with tracing fully enabled (spans default-on),
        the instrumented path still never recompiles in steady state,
        cost introspection is populated, and the modeled-work counters
        advance so achieved GB/s is derivable from one scrape."""
        metrics.reset()
        tracing.install_xla_compile_listener()
        ex = SearchExecutor()
        clock = ManualClock()
        # scripted 1 ms execute latency charged to the manual clock, so
        # the achieved-GB/s denominator is deterministic and nonzero
        b = DynamicBatcher(ShimExecutor(ex, delay_s=0.001, clock=clock),
                           BatcherConfig(max_wait_s=0.01),
                           clock=clock, start=False)
        index, q = real_setup["bf"], real_setup["q"]

        def roundtrip():
            hs = [b.submit(index, q[:7], 5), b.submit(index, q[7:10], 5)]
            clock.advance(0.01)
            b.pump()
            return [h.result(timeout=0) for h in hs]

        roundtrip()                     # prime executable + pad programs
        costs = ex.executable_costs()
        assert costs, "cost table empty after compile"
        info = next(iter(costs.values()))
        assert info["family"] in ("bf_fused", "bf_scan")
        assert info["bytes_accessed"] > 0
        digest = next(iter(costs))
        assert tracing.get_gauge(
            f"serving.executable.{digest}.bytes_accessed") > 0
        bytes0 = tracing.get_counter("serving.execute.modeled_bytes")
        backend0 = tracing.get_counter(tracing.XLA_COMPILE_COUNT)
        roundtrip()
        roundtrip()
        assert tracing.get_counter(tracing.XLA_COMPILE_COUNT) == backend0
        assert tracing.get_counter(
            "serving.execute.modeled_bytes") > bytes0
        derived = metrics.derived()
        assert derived["achieved_gbps"] > 0
        assert 0 < derived["cache_hit_rate"] <= 1.0
        assert len(tracing.span_recorder().spans(
            name="serving.execute")) >= 3
        # metrics.reset() (the bench-rider warmup flow) wipes the
        # serving gauges while the cache keeps its executables — a
        # scrape re-publishes them, so /metrics never disagrees with
        # executable_costs() about which programs are resident
        from raft_tpu.serving import MetricsExporter

        metrics.reset()
        assert tracing.gauges(f"serving.executable.{digest}.") == {}
        text = MetricsExporter(executor=ex, batcher=b).prometheus_text()
        assert tracing.get_gauge(
            f"serving.executable.{digest}.bytes_accessed") > 0
        # PR 7: one labeled family per field; the sha1-embedded flat
        # name only comes back under the deprecation flag
        assert (f'serving_executable_bytes_accessed{{digest="{digest}"}}'
                in text)
        assert f"serving_executable_{digest}_bytes_accessed" not in text
        legacy = MetricsExporter(
            executor=ex, batcher=b,
            legacy_executable_metrics=True).prometheus_text()
        assert f"serving_executable_{digest}_bytes_accessed" in legacy
        assert (f'serving_executable_bytes_accessed{{digest="{digest}"}}'
                in legacy)
        b.close()


class TestSloBurnRate:
    """graftscope v2 SLO surface — attainment counters and the
    sliding-window burn rate, pinned exactly under the manual clock
    (targets are binary-exact fractions so the budget arithmetic has
    no float fuzz)."""

    def _batcher(self, **slo_kw):
        from raft_tpu.serving import SloConfig

        clock = ManualClock()
        b = DynamicBatcher(
            FakeExecutor(),
            BatcherConfig(max_wait_s=0.01,
                          slo=SloConfig(**slo_kw)),
            clock=clock, start=False)
        return b, clock

    def test_attained_and_late_completion(self):
        metrics.reset()
        b, clock = self._batcher(window_s=10.0, target=0.75)
        idx = _Index()
        h1 = b.submit(idx, q_block([1]), 3, timeout_s=1.0)
        clock.advance(0.01)
        b.pump()                        # completes well before deadline
        assert h1.result(timeout=0)
        assert tracing.get_counter(metrics.SLO_ATTAINED) == 1.0
        assert tracing.get_counter(metrics.SLO_MISSED) == 0.0
        assert tracing.get_gauge(metrics.SLO_BURN_RATE) == 0.0
        # a request that COMPLETES after its deadline is a miss even
        # though the caller gets a result: claimed into a batch before
        # expiry (so not shed), finished late under a slow executor
        shim = ShimExecutor(FakeExecutor(), delay_s=0.2, clock=clock)
        b2 = DynamicBatcher(
            shim, BatcherConfig(max_wait_s=0.0, slo=b.config.slo),
            clock=clock, start=False)
        h2 = b2.submit(idx, q_block([2]), 3, timeout_s=0.1)
        b2.pump()                       # dispatches now, takes 0.2 s
        assert h2.result(timeout=0)     # result delivered...
        assert tracing.get_counter(metrics.SLO_MISSED) == 1.0  # ...late
        b.close()
        b2.close()

    def test_shed_is_a_miss_and_burn_rate_exact(self):
        metrics.reset()
        b, clock = self._batcher(window_s=10.0, target=0.75)
        idx = _Index()
        h_ok = b.submit(idx, q_block([1]), 3, timeout_s=1.0)
        clock.advance(0.01)
        b.pump()
        assert h_ok.done()
        h_exp = b.submit(idx, q_block([2]), 3, timeout_s=0.05)
        clock.advance(1.0)              # expires in queue
        b.pump()
        with pytest.raises(DeadlineExceeded):
            h_exp.result(timeout=0)
        assert tracing.get_counter(metrics.SLO_ATTAINED) == 1.0
        assert tracing.get_counter(metrics.SLO_MISSED) == 1.0
        # window: 1 miss of 2 outcomes; budget = 1 - 0.75 = 0.25 exact
        assert tracing.get_gauge(metrics.SLO_BURN_RATE) == 2.0
        b.close()

    def test_window_slide_decays_burn_rate(self):
        metrics.reset()
        b, clock = self._batcher(window_s=5.0, target=0.75)
        idx = _Index()
        h = b.submit(idx, q_block([1]), 3, timeout_s=0.05)
        clock.advance(1.0)
        b.pump()                        # miss at t=1.0
        assert tracing.get_gauge(metrics.SLO_BURN_RATE) == 4.0
        clock.advance(4.0)              # t=5.0: event at horizon edge
        b.publish_slo_gauges()
        assert tracing.get_gauge(metrics.SLO_BURN_RATE) == 4.0
        clock.advance(1.01)             # t=6.01: miss aged out
        b.publish_slo_gauges()
        assert tracing.get_gauge(metrics.SLO_BURN_RATE) == 0.0
        assert tracing.get_gauge("serving.slo.window_total") == 0.0
        # monotone counters are untouched by the slide
        assert tracing.get_counter(metrics.SLO_MISSED) == 1.0
        assert h.done()
        b.close()

    def test_no_deadline_means_no_slo_sample(self):
        metrics.reset()
        b, clock = self._batcher(window_s=10.0, target=0.75)
        idx = _Index()
        h = b.submit(idx, q_block([1]), 3)      # no deadline
        clock.advance(0.05)
        b.pump()
        assert h.result(timeout=0)
        assert tracing.get_counter(metrics.SLO_ATTAINED) == 0.0
        assert tracing.get_counter(metrics.SLO_MISSED) == 0.0
        b.close()

    def test_admission_reject_is_a_miss(self):
        """Total overload must drive the burn rate UP: a
        deadline-carrying request rejected at submit is an SLO miss,
        so a saturated queue can't starve the window into a
        healthy-looking 0.0 during the outage."""
        from raft_tpu.serving import Overloaded, SloConfig

        metrics.reset()
        clock = ManualClock()
        b = DynamicBatcher(
            FakeExecutor(),
            BatcherConfig(max_wait_s=0.01, capacity=1,
                          slo=SloConfig(window_s=10.0, target=0.75)),
            clock=clock, start=False)
        idx = _Index()
        b.submit(idx, q_block([1]), 3, timeout_s=1.0)
        with pytest.raises(Overloaded):
            b.submit(idx, q_block([2]), 3, timeout_s=1.0)
        assert tracing.get_counter(metrics.SLO_MISSED) == 1.0
        assert tracing.get_gauge(metrics.SLO_BURN_RATE) == 4.0
        # a rejected request WITHOUT a deadline is not an SLO sample
        with pytest.raises(Overloaded):
            b.submit(idx, q_block([3]), 3)
        assert tracing.get_counter(metrics.SLO_MISSED) == 1.0
        b.close()

    def test_failed_batch_is_a_miss(self):
        """A wedged executor fails the handles AND burns budget: each
        deadline-carrying member of the failed batch is a miss."""
        from raft_tpu.serving import SloConfig

        metrics.reset()
        clock = ManualClock()
        shim = ShimExecutor(FakeExecutor(), clock=clock,
                            fail_on={0: RuntimeError("wedged")})
        b = DynamicBatcher(
            shim,
            BatcherConfig(max_wait_s=0.0,
                          slo=SloConfig(window_s=10.0, target=0.75)),
            clock=clock, start=False)
        idx = _Index()
        h1 = b.submit(idx, q_block([1]), 3, timeout_s=1.0)
        h2 = b.submit(idx, q_block([2]), 3)     # no deadline: no sample
        b.pump()
        with pytest.raises(RuntimeError):
            h1.result(timeout=0)
        with pytest.raises(RuntimeError):
            h2.result(timeout=0)
        assert tracing.get_counter(metrics.SLO_MISSED) == 1.0
        assert tracing.get_counter(metrics.SLO_ATTAINED) == 0.0
        b.close()


class TestAdaptiveWait:
    """The arrival-rate → max-wait control law (serving follow-on (b)):
    clock-domain EWMA in, deterministic wait out; off by default; the
    shed ladder's rung 1 still wins."""

    def test_off_by_default(self):
        b, ex, clock = manual_batcher(max_wait_s=0.123)
        assert b.config.adaptive_wait is None
        assert b._effective_max_wait() == 0.123
        b.close()

    def test_control_law_endpoints_and_interpolation(self):
        from raft_tpu.serving import AdaptiveWait

        aw = AdaptiveWait(low_rate_hz=10.0, high_rate_hz=110.0,
                          min_wait_s=0.001)
        assert aw.wait_for(0.0, 0.101) == 0.101      # idle -> full cap
        assert aw.wait_for(10.0, 0.101) == 0.101
        assert aw.wait_for(110.0, 0.101) == 0.001    # hot -> min
        assert aw.wait_for(10_000.0, 0.101) == 0.001
        # exact midpoint of the linear ramp
        assert aw.wait_for(60.0, 0.101) == pytest.approx(0.051)

    def test_live_rate_drives_effective_wait(self):
        from raft_tpu.serving import AdaptiveWait

        metrics.reset()
        aw = AdaptiveWait(low_rate_hz=10.0, high_rate_hz=110.0,
                          min_wait_s=0.001)
        clock = ManualClock()
        b = DynamicBatcher(
            FakeExecutor(),
            BatcherConfig(max_wait_s=0.101, capacity=64,
                          adaptive_wait=aw),
            clock=clock, start=False)
        idx = _Index()
        # uniform 60 Hz arrivals: the EWMA converges to exactly 60.0
        for i in range(6):
            b.submit(idx, q_block([i]), 3)
            clock.advance(1 / 60.0)
        rate = b._queue.arrival_rate()
        assert rate == pytest.approx(60.0)
        want = aw.wait_for(rate, 0.101)
        assert b._effective_max_wait() == pytest.approx(want)
        assert tracing.get_gauge(
            "serving.batcher.effective_max_wait_s") == pytest.approx(
                want)
        b.pump()
        b.close()

    def test_rung1_overrides_adaptive(self):
        from raft_tpu.serving import AdaptiveWait

        clock = ManualClock()
        b = DynamicBatcher(
            FakeExecutor(),
            BatcherConfig(max_wait_s=0.101, capacity=4,
                          adaptive_wait=AdaptiveWait()),
            clock=clock, start=False)
        idx = _Index()
        b.submit(idx, q_block([1]), 3)
        b.submit(idx, q_block([2]), 3)  # occupancy 0.5 -> rung 1
        assert b._effective_max_wait() == 0.0
        b.pump()
        b.close()


class TestMeshSpansViaShim:
    """Scripted per-shard latencies drive the straggler detector
    end-to-end through the batcher: skew gauges exact, shard spans
    carry the member requests' trace ids."""

    def test_scripted_shard_skew_gauges_exact(self):
        metrics.reset()
        clock = ManualClock()
        shim = ShimExecutor(FakeExecutor(), clock=clock,
                            shard_times=[0.003, 0.011, 0.005, 0.004])
        b = DynamicBatcher(shim, BatcherConfig(max_wait_s=0.0),
                           clock=clock, start=False)
        idx = _Index()
        h1 = b.submit(idx, q_block([1]), 3, timeout_s=5.0)
        h2 = b.submit(idx, q_block([2]), 3, timeout_s=5.0)
        b.pump()
        assert h1.done() and h2.done()
        assert tracing.get_gauge(
            tracing.MESH_SHARD_SKEW) == pytest.approx(0.008)
        assert tracing.get_gauge(tracing.MESH_SLOWEST_SHARD) == 1.0
        shards = tracing.span_recorder().spans(name="serving.mesh.shard")
        assert len(shards) == 4
        # the mesh spans carry BOTH coalesced requests' trace ids —
        # the straggler attributes back to the requests it delayed
        for s in shards:
            assert len(s.trace_ids) == 2
        b.close()

    def test_per_call_scripts_by_ordinal(self):
        metrics.reset()
        clock = ManualClock()
        shim = ShimExecutor(
            FakeExecutor(), clock=clock,
            shard_times={1: [0.002, 0.009]})
        b = DynamicBatcher(shim, BatcherConfig(max_wait_s=0.0),
                           clock=clock, start=False)
        idx = _Index()
        b.submit(idx, q_block([1]), 3)
        b.pump()                        # call 0: no script, no spans
        assert not tracing.span_recorder().spans(
            name="serving.mesh.shard")
        b.submit(idx, q_block([2]), 3)
        b.pump()                        # call 1: scripted
        assert tracing.get_gauge(
            tracing.MESH_SHARD_SKEW) == pytest.approx(0.007)
        b.close()


class TestExporterV2Endpoints:
    """/trace.json?trace_id= filter and the gated /profile capture."""

    def test_trace_id_filter_and_unknown_id(self):
        import json
        import urllib.request

        from raft_tpu.serving import MetricsExporter

        metrics.reset()
        b, ex, clock = manual_batcher(max_wait_s=0.0)
        idx = _Index()
        h1 = b.submit(idx, q_block([1]), 3, timeout_s=1.0)
        b.pump()
        h2 = b.submit(idx, q_block([2]), 3, timeout_s=1.0)
        b.pump()
        assert h1.done() and h2.done()
        rec = tracing.span_recorder()
        tid = rec.spans(name="serving.request")[0].trace_ids[0]
        with MetricsExporter(batcher=b) as exp:
            t = json.loads(urllib.request.urlopen(
                exp.url(f"/trace.json?trace_id={tid}"),
                timeout=10).read())
            assert t["traceEvents"], "filtered trace must not be empty"
            for e in t["traceEvents"]:
                ids = e.get("args", {}).get("trace_ids")
                if ids is not None:
                    assert tid in ids
            # unknown id: 200 with an empty, VALID trace
            t2 = json.loads(urllib.request.urlopen(
                exp.url("/trace.json?trace_id=999999999"),
                timeout=10).read())
            assert t2["traceEvents"] == []
            # malformed id: 400 — including present-but-EMPTY
            # (parse_qs must keep blank values: '?trace_id=' silently
            # vanishing would dump the whole ring instead)
            for bad in ("trace_id=bogus", "trace_id="):
                with pytest.raises(urllib.error.HTTPError) as ei:
                    urllib.request.urlopen(
                        exp.url(f"/trace.json?{bad}"), timeout=10)
                assert ei.value.code == 400
        b.close()

    def test_profile_endpoint_gated_and_captures(self, tmp_path,
                                                 monkeypatch):
        import contextlib
        import json
        import os
        import urllib.request

        from raft_tpu.serving import MetricsExporter

        b, ex, clock = manual_batcher(max_wait_s=0.0)
        # ungated: 403, and nothing written anywhere
        with MetricsExporter(batcher=b) as exp:
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(exp.url("/profile?seconds=0"),
                                       timeout=10)
            assert ei.value.code == 403
        prof = tmp_path / "prof"
        prof.mkdir()

        # layout-faithful fake capture: stop_trace serializes
        # session-accumulated profiler state (~a minute late in a full
        # suite) — the REAL capture path is proven by the core capture
        # smoke and graftflight's live-correlation test; this test
        # owns the HTTP contract (gating, arming, status codes)
        @contextlib.contextmanager
        def fake_capture(log_dir):
            run = os.path.join(log_dir, "plugins", "profile", "r1")
            os.makedirs(run, exist_ok=True)
            with open(os.path.join(run, "host.trace.json"), "w") as f:
                json.dump({"traceEvents": []}, f)
            yield

        monkeypatch.setattr(tracing, "capture", fake_capture)
        with MetricsExporter(batcher=b,
                             profile_dir=str(prof)) as exp:
            out = json.loads(urllib.request.urlopen(
                exp.url("/profile?seconds=0"), timeout=60).read())
            assert out["log_dir"] == str(prof)
            assert os.listdir(prof), "capture wrote nothing"
            # PR 11 exporter hardening: the response names the capture
            assert out["trace_file"].startswith(str(prof))
            # bad seconds: 400 (malformed and out-of-range alike)
            for q in ("seconds=bogus", "seconds=-1", "seconds=999",
                      "seconds="):    # blank must 400, not default
                with pytest.raises(urllib.error.HTTPError) as ei:
                    urllib.request.urlopen(exp.url(f"/profile?{q}"),
                                           timeout=10)
                assert ei.value.code == 400
        b.close()


class TestPrometheusLabels:
    """One metric family per executable field with a digest label; the
    collective payload gauges label by family/wire; legacy flat names
    only behind the deprecation flag."""

    def test_render_groups_digest_labels(self):
        from raft_tpu.serving.exporter import render_prometheus

        gauges = {
            "serving.executable.aaa111.flops": 10.0,
            "serving.executable.bbb222.flops": 20.0,
            "serving.executable.aaa111.peak_hbm_bytes": 512.0,
            "serving.collective.dist_ivf_flat.f32.int8.merge_bytes":
                1280.0,
            "serving.executor.cached_executables": 2.0,
        }
        text = render_prometheus({}, gauges, {})
        assert '# TYPE serving_executable_flops gauge' in text
        assert 'serving_executable_flops{digest="aaa111"} 10' in text
        assert 'serving_executable_flops{digest="bbb222"} 20' in text
        assert ('serving_executable_peak_hbm_bytes{digest="aaa111"} 512'
                in text)
        assert ('serving_collective_merge_bytes{family="dist_ivf_flat"'
                ',wire="f32",probe_wire="int8"} 1280' in text)
        # the TYPE header appears once per family, not per executable
        assert text.count("# TYPE serving_executable_flops gauge") == 1
        # plain gauges are untouched; no flat digest names by default
        assert "serving_executor_cached_executables 2" in text
        assert "serving_executable_aaa111_flops" not in text

    def test_legacy_flag_emits_both(self):
        from raft_tpu.serving.exporter import render_prometheus

        gauges = {"serving.executable.aaa111.flops": 10.0}
        text = render_prometheus({}, gauges, {},
                                 legacy_executable_metrics=True)
        assert 'serving_executable_flops{digest="aaa111"} 10' in text
        assert "serving_executable_aaa111_flops 10" in text


class TestMultiBurnAlert:
    """PR 8 satellite: the paired 5 m + 1 h multiwindow burn-rate
    policy — ``serving.slo.alert`` fires only when BOTH windows burn,
    pinned exactly under the manual clock."""

    def _multiburn(self, short_s=10.0, long_s=100.0, target=0.5):
        from raft_tpu.serving import MultiBurnConfig, SloConfig

        cfg = MultiBurnConfig(
            short=metrics.SloConfig(window_s=short_s, target=target),
            long=metrics.SloConfig(window_s=long_s, target=target),
            short_label="short", long_label="long")
        return metrics.MultiBurnAlert(cfg)

    def test_alert_requires_both_windows(self):
        metrics.reset()
        mb = self._multiburn()
        # burn only the short window: misses at t=0..2, then a long
        # stretch of attained keeps the LONG window healthy
        for t in (0.0, 1.0, 2.0):
            mb.record(t, attained=False)
        for t in range(3, 30):
            mb.record(float(t), attained=True)
        now = 29.0
        short_rate, long_rate = mb.burn_rates(now)
        # short window (last 10 s) holds only attained events
        assert short_rate == 0.0
        assert long_rate > 0.0
        assert not mb.alert(now)
        assert tracing.get_gauge(metrics.SLO_ALERT) == 0.0

    def test_alert_fires_when_both_burn_then_clears(self):
        metrics.reset()
        mb = self._multiburn(target=0.5)    # budget = 0.5
        # 100% misses: both windows burn at 1/0.5 = 2.0 >= 1.0
        for t in (0.0, 1.0, 2.0, 3.0):
            mb.record(float(t), attained=False)
        assert mb.burn_rates(3.0) == (pytest.approx(2.0),
                                      pytest.approx(2.0))
        assert mb.alert(3.0)
        assert tracing.get_gauge(metrics.SLO_ALERT) == 1.0
        assert tracing.get_gauge(
            "serving.slo.burn_rate.short") == pytest.approx(2.0)
        assert tracing.get_gauge(
            "serving.slo.burn_rate.long") == pytest.approx(2.0)
        # the misses age out of the SHORT window -> alert clears at
        # scrape-time publish even though the long window still burns
        mb.publish(50.0)
        assert tracing.get_gauge(
            "serving.slo.burn_rate.short") == 0.0
        assert tracing.get_gauge(
            "serving.slo.burn_rate.long") == pytest.approx(2.0)
        assert tracing.get_gauge(metrics.SLO_ALERT) == 0.0

    def test_counters_bump_exactly_once_per_outcome(self):
        metrics.reset()
        mb = self._multiburn()
        mb.record(0.0, attained=True)
        mb.record(1.0, attained=False)
        assert tracing.get_counter(metrics.SLO_ATTAINED) == 1.0
        assert tracing.get_counter(metrics.SLO_MISSED) == 1.0

    def test_batcher_swaps_in_multiburn(self):
        """``BatcherConfig(multiburn=...)`` routes every completion
        outcome through the paired windows — shed-at-expiry lands in
        both, and the alert gauge goes live."""
        from raft_tpu.serving import MultiBurnConfig

        metrics.reset()
        clock = ManualClock()
        cfg = MultiBurnConfig(
            short=metrics.SloConfig(window_s=10.0, target=0.5),
            long=metrics.SloConfig(window_s=100.0, target=0.5),
            short_label="short", long_label="long")
        b = DynamicBatcher(
            FakeExecutor(),
            BatcherConfig(max_wait_s=0.01, multiburn=cfg),
            clock=clock, start=False)
        idx = _Index()
        h = b.submit(idx, q_block([1]), 3, timeout_s=0.05)
        clock.advance(0.2)              # expires in queue -> shed
        b.pump()
        with pytest.raises(DeadlineExceeded):
            h.result(timeout=0)
        assert tracing.get_counter(metrics.SLO_MISSED) == 1.0
        assert tracing.get_gauge(
            "serving.slo.burn_rate.short") == pytest.approx(2.0)
        assert tracing.get_gauge(metrics.SLO_ALERT) == 1.0
        h2 = b.submit(idx, q_block([2]), 3, timeout_s=5.0)
        clock.advance(0.01)
        b.pump()
        assert h2.result(timeout=0)
        assert tracing.get_counter(metrics.SLO_ATTAINED) == 1.0
        b.close()


class TestExpositionHelpTypePairing:
    """PR 8 satellite: EVERY family on /metrics — flat, labeled, and
    histogram — carries # HELP and # TYPE lines, checked line by line
    against the exposition grammar."""

    def test_every_family_has_help_and_type(self, real_setup):
        import re
        import urllib.request

        from raft_tpu.serving import MetricsExporter

        metrics.reset()
        ex = SearchExecutor(probe_accounting=True)
        clock = ManualClock()
        b = DynamicBatcher(ex, BatcherConfig(max_wait_s=0.0),
                           clock=clock, start=False)
        p = ivf_flat.IvfFlatSearchParams(n_probes=4)
        b.submit(real_setup["ivf"], real_setup["q"], 5, params=p)
        b.pump()
        gauge = __import__("raft_tpu.serving.gauge",
                           fromlist=["IndexGauge"]).IndexGauge(
            executor=ex, indexes={"main": real_setup["ivf"]})
        # graftledger (PR 13): the memory.* families must carry
        # HELP/TYPE and parse like every other labeled family
        from raft_tpu.core.memwatch import MemoryLedger

        ledger = MemoryLedger(executor=ex)
        ledger.watch("main", real_setup["ivf"])
        with MetricsExporter(executor=ex, batcher=b,
                             index_gauge=gauge, memory=ledger) as exp:
            text = urllib.request.urlopen(
                exp.url("/metrics"), timeout=10).read().decode()
        b.close()
        helped, typed, histograms = set(), set(), set()
        sample_re = re.compile(
            r'^([a-zA-Z_:][a-zA-Z0-9_:]*)'
            r'(\{[a-zA-Z_]+="[^"]*"(,[a-zA-Z_]+="[^"]*")*\})? '
            r"[-+0-9.e]+$")
        samples = []
        for line in text.strip().splitlines():
            if line.startswith("# HELP "):
                helped.add(line.split()[2])
                assert len(line.split(None, 3)) == 4, line  # has text
            elif line.startswith("# TYPE "):
                name, mtype = line.split()[2:4]
                typed.add(name)
                assert mtype in ("counter", "gauge", "histogram"), line
                if mtype == "histogram":
                    histograms.add(name)
            else:
                m = sample_re.match(line)
                assert m, line
                samples.append(m.group(1))
        families = set()
        for fam in samples:
            # histogram _bucket/_count/_sum series fold onto their
            # declared family; _count is ALSO a legitimate standalone
            # family suffix (index_probe_freq_count), so only fold
            # onto names # TYPE declared as histograms
            base = re.sub(r"_(bucket|count|sum)$", "", fam)
            families.add(base if base in histograms else fam)
        missing_help = families - helped
        missing_type = families - typed
        assert not missing_help, f"families without HELP: {missing_help}"
        assert not missing_type, f"families without TYPE: {missing_type}"
        # the graftgauge labeled families are present and annotated
        assert "index_health_rows" in families
        # the graftledger labeled + flat families are present and
        # annotated (per-device families appear only on backends with
        # live memory_stats — not CPU)
        assert "memory_index_resident_bytes" in families
        assert "memory_hbm_headroom_bytes" in families
        assert "memory_live_supported" in families
        assert any(f.startswith("index_probe_freq") for f in families)


class TestRaggedBatcher:
    """Ragged continuous batching (BatcherConfig(ragged=True)): one
    packed tile admits continuously, requests split at tile boundaries,
    and everything not raggable falls back to the bucketed path."""

    def ragged_batcher(self, executor=None, tile=4, **cfg):
        clock = ManualClock()
        ex = executor or FakeExecutor(ragged_tile=tile)
        cfg.setdefault("max_wait_s", 0.01)
        b = DynamicBatcher(ex, BatcherConfig(ragged=True, **cfg),
                           clock=clock, start=False)
        return b, ex, clock

    def test_continuous_packing_dual_trigger(self):
        b, ex, clock = self.ragged_batcher(tile=4)
        idx = _Index()
        h1 = b.submit(idx, q_block([1, 2, 3]), 3)
        h2 = b.submit(idx, q_block([7, 8]), 2)
        h3 = b.submit(idx, q_block([4, 5, 6, 9]), 3)
        # two FULL tiles dispatch with no time advance (tile-full
        # trigger): [h1 rows + h2 row 0], [h2 row 1 + h3 rows 0-2]
        assert b.pump() == 2
        assert ex.ragged_calls == [(2, 4), (2, 4)]
        assert not h3.done()            # one row still queued
        clock.advance(0.01)             # timer flushes the remainder
        assert b.pump() == 1
        _, i1 = h1.result(timeout=0)
        np.testing.assert_array_equal(i1[:, 0], [3, 6, 9])
        _, i2 = h2.result(timeout=0)
        np.testing.assert_array_equal(i2[:, 0], [14, 16])
        d3, i3 = h3.result(timeout=0)
        assert i3.shape == (4, 3)
        np.testing.assert_array_equal(i3[:, 0], [12, 15, 18, 27])
        b.close()

    def test_tile_overflow_split_reassembles(self):
        """A request bigger than the tile streams across tiles and
        reassembles bit-exactly (per-row values prove the order)."""
        b, ex, clock = self.ragged_batcher(tile=4)
        idx = _Index()
        ids = list(range(10))
        h = b.submit(idx, q_block(ids), 2)
        assert b.pump() == 2            # two full tiles immediately
        assert not h.done()
        clock.advance(0.01)
        assert b.pump() == 1            # final 2-row remainder
        d, i = h.result(timeout=0)
        assert i.shape == (10, 2)
        np.testing.assert_array_equal(i[:, 0], [v * 2 for v in ids])
        b.close()

    def test_mixed_k_packs_into_one_call(self):
        """Different per-request k share one packed dispatch (the
        fake's params class ignores k, like the executor's pow2
        class)."""
        b, ex, clock = self.ragged_batcher(tile=4)
        idx = _Index()
        h1 = b.submit(idx, q_block([1, 2]), 3)
        h2 = b.submit(idx, q_block([5, 6]), 7)
        assert b.pump() == 1
        assert ex.ragged_calls == [(2, 4)]
        assert h1.result(timeout=0)[1].shape == (2, 3)
        assert h2.result(timeout=0)[1].shape == (2, 7)
        b.close()

    def test_empty_after_shed_batch(self):
        """Every queued request expires before the trigger: the worker
        sheds them (typed DeadlineExceeded) and dispatches NOTHING."""
        b, ex, clock = self.ragged_batcher(tile=8)
        idx = _Index()
        h1 = b.submit(idx, q_block([1]), 2, timeout_s=0.005)
        h2 = b.submit(idx, q_block([2, 3]), 2, timeout_s=0.005)
        clock.advance(0.02)             # past deadline AND max-wait
        assert b.pump() == 0
        assert not ex.ragged_calls and not ex.calls
        for h in (h1, h2):
            with pytest.raises(DeadlineExceeded):
                h.result(timeout=0)
        b.close()

    def test_edf_order_preserved(self):
        """The earlier-deadline group still dispatches first, and a
        split remainder keeps its order key."""
        b, ex, clock = self.ragged_batcher(tile=2, max_wait_s=0.0)
        late, soon = _Index(), _Index()
        b.submit(late, q_block([1]), 3, timeout_s=100.0)
        b.submit(soon, q_block([2]), 3, timeout_s=1.0)
        b.pump()
        assert ex.ragged_calls and ex.ragged_calls[0] == (1, 1)
        b.close()

    def test_cancel_before_first_slice(self):
        b, ex, clock = self.ragged_batcher(tile=4)
        idx = _Index()
        h = b.submit(idx, q_block([1, 2]), 2)
        assert h.cancel()
        clock.advance(0.01)
        assert b.pump() == 0
        assert not ex.ragged_calls
        b.close()

    def test_shutdown_drains_split_requests(self):
        b, ex, clock = self.ragged_batcher(tile=4)
        idx = _Index()
        h = b.submit(idx, q_block(list(range(6))), 2)
        assert b.pump() == 1            # first tile only (4 of 6 rows)
        b.close(drain=True)             # close flushes the remainder
        d, i = h.result(timeout=0)
        assert i.shape == (6, 2)
        np.testing.assert_array_equal(i[:, 0], [0, 2, 4, 6, 8, 10])

    def test_failed_tile_fails_split_request_once(self):
        inner = FakeExecutor(ragged_tile=4)
        clock = ManualClock()
        shim = ShimExecutor(inner, fail_on={0: RuntimeError("boom")},
                            clock=clock)
        b = DynamicBatcher(shim, BatcherConfig(ragged=True,
                                               max_wait_s=0.0),
                           clock=clock, start=False)
        idx = _Index()
        h = b.submit(idx, q_block(list(range(6))), 2)
        b.pump()
        assert isinstance(h.exception(timeout=0), RuntimeError)
        b.close()

    def test_bucketed_only_index_falls_back(self):
        b, ex, clock = self.ragged_batcher(tile=4)
        idx = _Index()
        idx.bucketed_only = True
        h = b.submit(idx, q_block([5]), 2)
        clock.advance(0.01)
        assert b.pump() == 1
        assert ex.calls == [(1, 1)] and not ex.ragged_calls
        np.testing.assert_array_equal(h.result(timeout=0)[1][:, 0], [10])
        b.close()


class TestRaggedRealExecutor:
    """Acceptance criteria of the ragged path against the real
    executor: per-request bit-identity with direct bucketed calls,
    zero recompiles after the ONE warmup, CAGRA packing through the
    same family (graftbeam)."""

    def test_bit_identity_and_zero_recompile(self, real_setup):
        ex = SearchExecutor(ragged_tile=16)
        clock = ManualClock()
        b = DynamicBatcher(ex, BatcherConfig(max_wait_s=0.01,
                                             ragged=True),
                           clock=clock, start=False)
        q = real_setup["q"]
        index = real_setup["ivf"]
        p1 = ivf_flat.IvfFlatSearchParams(n_probes=4, scan_engine="xla")
        p2 = ivf_flat.IvfFlatSearchParams(n_probes=7, scan_engine="xla")
        ex.warmup_ragged(index, k=5, params=p1)
        assert ex.ragged_executables() == 1
        # mixed n_probes AND k in one params class, over several
        # load shapes; then measure compiles over a repeat pass
        def drive():
            hs = [b.submit(index, q[:7], 5, params=p1),
                  b.submit(index, q[7:10], 3, params=p2),
                  b.submit(index, q[10:], 8, params=p1)]
            clock.advance(0.01)
            b.pump()
            return hs
        drive()
        tracing.install_xla_compile_listener()
        before = tracing.get_counter(tracing.XLA_COMPILE_COUNT)
        hs = drive()
        assert tracing.get_counter(tracing.XLA_COMPILE_COUNT) == before
        assert ex.ragged_executables() == 1
        for h, (blk, k, p) in zip(hs, [(q[:7], 5, p1), (q[7:10], 3, p2),
                                       (q[10:], 8, p1)]):
            d, i = h.result(timeout=0)
            dd, ii = ex.search(index, blk, k, params=p)
            np.testing.assert_array_equal(np.asarray(i), np.asarray(ii))
            np.testing.assert_array_equal(np.asarray(d), np.asarray(dd))
        b.close()

    def test_pad_waste_collapses_vs_bucketed(self, real_setup):
        """The acceptance headline in miniature: a packed full tile
        carries near-zero pad while the bucketed path pads every
        request to its bucket."""
        q = real_setup["q"]
        index = real_setup["ivf"]
        p = ivf_flat.IvfFlatSearchParams(n_probes=4, scan_engine="xla")
        blocks = [q[:3], q[3:6], q[6:11], q[11:16]]     # 16 rows

        metrics.reset()
        ex = SearchExecutor(ragged_tile=16)
        ex.search_ragged(index, blocks, 5, params_list=p)
        assert metrics.derived()["pad_waste_fraction"] == 0.0

        metrics.reset()
        for blk in blocks:              # bucketed: 3->8, 3->8, 5->8, 5->8
            ex.search(index, blk, 5, params=p)
        assert metrics.derived()["pad_waste_fraction"] == 0.5

    def test_cagra_packs_through_ragged_family(self, real_setup):
        """CAGRA requests under a ragged batcher pack into ONE ragged
        executable (graftbeam retired the per-block exemption:
        content-pure seeds, per-row iteration budgets) and each
        request stays bit-identical to its direct bucketed search."""
        from raft_tpu.neighbors import cagra

        x = real_setup["x"]
        gindex = cagra.build(None, cagra.CagraIndexParams(
            graph_degree=8, intermediate_graph_degree=16,
            build_algo=cagra.BuildAlgo.NN_DESCENT), x)
        ex = SearchExecutor(ragged_tile=16)
        clock = ManualClock()
        b = DynamicBatcher(ex, BatcherConfig(max_wait_s=0.01,
                                             ragged=True),
                           clock=clock, start=False)
        p = cagra.CagraSearchParams(itopk_size=16)
        assert ex.ragged_key(gindex, 4, params=p) is not None
        q = real_setup["q"]
        h1 = b.submit(gindex, q[:5], 4, params=p)
        h2 = b.submit(gindex, q[5:9], 4, params=p)
        clock.advance(0.01)
        b.pump()
        assert ex.ragged_executables(family="cagra") >= 1
        for h, blk in ((h1, q[:5]), (h2, q[5:9])):
            d, i = h.result(timeout=0)
            dd, ii = ex.search(gindex, blk, 4, params=p)
            np.testing.assert_array_equal(np.asarray(i), np.asarray(ii))
            np.testing.assert_array_equal(np.asarray(d), np.asarray(dd))
        b.close()

    def test_2d_filter_slices_ride_the_split(self, real_setup):
        """Per-row bitmap filters slice with their rows across a tile
        split and still mask exactly."""
        x = real_setup["x"]
        index = real_setup["ivf"]
        q = real_setup["q"]
        from raft_tpu.neighbors.filters import BitmapFilter

        rng = np.random.default_rng(9)
        ex = SearchExecutor(ragged_tile=8)
        clock = ManualClock()
        b = DynamicBatcher(ex, BatcherConfig(max_wait_s=0.01,
                                             ragged=True),
                           clock=clock, start=False)
        p = ivf_flat.IvfFlatSearchParams(n_probes=8, scan_engine="xla")
        mask = rng.random((12, len(x))) < 0.5
        bm = BitmapFilter.from_mask(mask)
        h = b.submit(index, q[:12], 5, params=p, sample_filter=bm)
        clock.advance(0.01)
        b.pump()                        # 12 rows through an 8-row tile
        d, i = h.result(timeout=0)
        dd, ii = ex.search(index, q[:12], 5, params=p, sample_filter=bm)
        np.testing.assert_array_equal(np.asarray(i), np.asarray(ii))
        np.testing.assert_array_equal(np.asarray(d), np.asarray(dd))
        b.close()


class TestGroupFairness:
    """Cross-index fairness: the per-group dispatch budget keeps one
    group from monopolizing the worker, pinned by manual clock."""

    def test_budget_forces_other_ready_group(self):
        clock = ManualClock()
        ex = FakeExecutor(ragged_tile=2)
        b = DynamicBatcher(ex, BatcherConfig(ragged=True,
                                             max_wait_s=0.0,
                                             group_budget=2),
                           clock=clock, start=False)
        A, B = _Index(), _Index()
        for i in range(8):
            b.submit(A, q_block([i]), 2)
        hb = b.submit(B, q_block([99]), 2)
        clock.advance(0.01)
        order = []
        while True:
            got = b._poll()
            if not got:
                break
            key, items, ragged = got
            order.append("B" if items[0][0].queries[0, 0] == 99
                         else "A")
            b._dispatch_ragged(key, items)
        # A is always most urgent (earlier seq), but after 2
        # consecutive A dispatches the budget serves B
        assert order == ["A", "A", "B", "A", "A"]
        assert hb.done()
        b.close()

    def test_starvation_gauge_pinned(self):
        metrics.reset()
        clock = ManualClock()
        ex = FakeExecutor(ragged_tile=2)
        b = DynamicBatcher(ex, BatcherConfig(ragged=True,
                                             max_wait_s=0.0,
                                             group_budget=0),
                           clock=clock, start=False)
        A, B = _Index(), _Index()
        b.submit(A, q_block([1, 2]), 2)
        b.submit(B, q_block([3, 4]), 2)
        clock.advance(0.25)
        got = b._poll()                 # serves A; B has waited 0.25 s
        assert got and got[1][0][0].queries[0, 0] == 1
        assert tracing.get_gauge(
            "serving.batcher.group_starvation_s") == 0.25
        b._dispatch_ragged(got[0], got[1])
        got = b._poll()                 # serves B; nobody else waits
        assert tracing.get_gauge(
            "serving.batcher.group_starvation_s") == 0.0
        b._dispatch_ragged(got[0], got[1])
        b.close()

    def test_budget_zero_disables(self):
        clock = ManualClock()
        ex = FakeExecutor(ragged_tile=2)
        b = DynamicBatcher(ex, BatcherConfig(ragged=True,
                                             max_wait_s=0.0,
                                             group_budget=0),
                           clock=clock, start=False)
        A, B = _Index(), _Index()
        for i in range(6):
            b.submit(A, q_block([i]), 2)
        hb = b.submit(B, q_block([99]), 2)
        clock.advance(0.01)
        order = []
        while True:
            got = b._poll()
            if not got:
                break
            order.append("B" if got[1][0][0].queries[0, 0] == 99
                         else "A")
            b._dispatch_ragged(got[0], got[1])
        assert order == ["A", "A", "A", "B"]   # pure EDF, no override
        b.close()

    def test_full_group_not_stuck_behind_urgent_timer(self):
        """A tile-full group dispatches even while a more-urgent group
        is still waiting out its timer (the old head-of-line scan
        would sleep on the urgent group's timer)."""
        clock = ManualClock()
        ex = FakeExecutor(ragged_tile=4)
        b = DynamicBatcher(ex, BatcherConfig(ragged=True,
                                             max_wait_s=10.0),
                           clock=clock, start=False)
        urgent, full = _Index(), _Index()
        b.submit(urgent, q_block([1]), 2, timeout_s=50.0)  # EDF winner
        b.submit(full, q_block([2, 3, 4, 5]), 2)           # tile-full
        assert b.pump() == 1            # the FULL group went, now
        assert ex.ragged_calls == [(1, 4)]
        b.close()

    def test_empty_pop_does_not_burn_fairness_budget(self):
        """The streak advances only on REAL dispatches (_record_pick):
        cancel-race empty pops must not count against the picked
        group, or a group starved by cancellations gets passed over
        the moment it has real work."""
        clock = ManualClock()
        ex = FakeExecutor(ragged_tile=2)
        b = DynamicBatcher(ex, BatcherConfig(ragged=True,
                                             max_wait_s=0.0,
                                             group_budget=2),
                           clock=clock, start=False)
        A = _Index()

        class _Head:
            def __init__(self, key):
                self.key = key
                self.arrival = 0.0

        a_head, b_head = _Head("A"), _Head("B")
        # phantom picks (no _record_pick): budget must stay unburned
        for _ in range(5):
            assert b._pick_fair([a_head, b_head]).key == "A"
        assert b._consecutive == 0
        # real dispatches burn it; the 3rd pick yields to B
        b._record_pick(a_head, [a_head, b_head], 0.0)
        b._record_pick(a_head, [a_head, b_head], 0.0)
        assert b._pick_fair([a_head, b_head]).key == "B"
        b.close()

    def test_failed_split_remainder_not_counted_cancelled(self):
        """A split request whose dispatched slice failed leaves its
        remainder in the queue with a done handle; pruning it must
        not inflate serving.batcher.cancelled (the failure was
        already counted in failed_batches)."""
        metrics.reset()
        inner = FakeExecutor(ragged_tile=4)
        clock = ManualClock()
        shim = ShimExecutor(inner, fail_on={0: RuntimeError("boom")},
                            clock=clock)
        b = DynamicBatcher(shim, BatcherConfig(ragged=True,
                                               max_wait_s=0.0),
                           clock=clock, start=False)
        idx = _Index()
        h = b.submit(idx, q_block(list(range(6))), 2)  # splits at 4
        b.pump()                       # tile 1 fails the handle
        assert isinstance(h.exception(timeout=0), RuntimeError)
        assert tracing.get_counter(
            "serving.batcher.failed_batches") == 1
        b.pump()                       # remainder pruned, not dispatched
        assert tracing.get_counter("serving.batcher.cancelled") == 0
        assert len(b._queue) == 0
        assert inner.ragged_calls == []    # shim failed before inner
        b.close()
