"""Regenerate docs/api.md — the full API reference — from the live
package: per module, every public function's signature + summary line
and every public class with its fields/methods (the role of the
reference's generated doc site, ``docs/source/``).

Run:  JAX_PLATFORMS=cpu python docs/gen_api.py
"""

import dataclasses
import importlib
import inspect
import pathlib
import re

MODULES = [
    "raft_tpu.core.resources", "raft_tpu.core.executor",
    "raft_tpu.core.bitset", "raft_tpu.core.logger",
    "raft_tpu.core.tracing", "raft_tpu.core.interruptible",
    "raft_tpu.core.serialize", "raft_tpu.core.operators",
    "raft_tpu.core.validation",
    "raft_tpu.analysis", "raft_tpu.analysis.core",
    "raft_tpu.analysis.astutil", "raft_tpu.analysis.report",
    "raft_tpu.distance", "raft_tpu.distance.types",
    "raft_tpu.distance.fused_l2_nn", "raft_tpu.distance.masked_nn",
    "raft_tpu.distance.kernels",
    "raft_tpu.linalg", "raft_tpu.matrix", "raft_tpu.matrix.select_k",
    "raft_tpu.ops",
    "raft_tpu.random", "raft_tpu.stats", "raft_tpu.label",
    "raft_tpu.sparse.types", "raft_tpu.sparse.convert",
    "raft_tpu.sparse.linalg",
    "raft_tpu.sparse.distance", "raft_tpu.sparse.neighbors",
    "raft_tpu.sparse.ops", "raft_tpu.sparse.solver",
    "raft_tpu.cluster.kmeans", "raft_tpu.cluster.kmeans_balanced",
    "raft_tpu.cluster.single_linkage", "raft_tpu.spectral", "raft_tpu.solver",
    "raft_tpu.neighbors.ann_types",
    "raft_tpu.neighbors.brute_force", "raft_tpu.neighbors.ivf_flat",
    "raft_tpu.neighbors.ivf_pq", "raft_tpu.neighbors.ivf_bq",
    "raft_tpu.neighbors.cagra", "raft_tpu.neighbors.hnsw",
    "raft_tpu.neighbors.nn_descent", "raft_tpu.neighbors.cluster_join",
    "raft_tpu.neighbors.refine",
    "raft_tpu.neighbors.ball_cover", "raft_tpu.neighbors.epsilon_neighborhood",
    "raft_tpu.neighbors.quantized", "raft_tpu.neighbors.filters",
    "raft_tpu.neighbors.ivf_helpers", "raft_tpu.neighbors.tiered",
    "raft_tpu.ops.tier_scan",
    "raft_tpu.spatial.knn",
    "raft_tpu.serving", "raft_tpu.serving.request",
    "raft_tpu.serving.batcher", "raft_tpu.serving.admission",
    "raft_tpu.serving.metrics", "raft_tpu.serving.exporter",
    "raft_tpu.serving.harness", "raft_tpu.serving.gauge",
    "raft_tpu.serving.flight", "raft_tpu.serving.continuous",
    "raft_tpu.serving.federation", "raft_tpu.serving.placement",
    "raft_tpu.serving.prefetch",
    "raft_tpu.fleet", "raft_tpu.fleet.table",
    "raft_tpu.fleet.planner", "raft_tpu.fleet.router",
    "raft_tpu.fleet.harness",
    "raft_tpu.core.profiling",
    "raft_tpu.core.xplane", "raft_tpu.core.memwatch",
    "raft_tpu.comms", "raft_tpu.comms.bootstrap",
    "raft_tpu.distributed.ivf", "raft_tpu.distributed.knn",
    "raft_tpu.distributed.kmeans", "raft_tpu.distributed.sharded_ann",
    "raft_tpu.distributed.checkpoint", "raft_tpu.distributed.bq",
    "raft_tpu.io",
    "raft_tpu.bench", "raft_tpu.bench.datasets", "raft_tpu.bench.runner",
    "raft_tpu.bench.prims", "raft_tpu.bench.hnsw_cpu",
    "raft_tpu.bench.ivf_flat_cpu",
    "raft_tpu.utils",
]


def first_para(obj) -> str:
    doc = inspect.getdoc(obj) or ""
    para = doc.split("\n\n", 1)[0].strip()
    return " ".join(para.split())


def sig_of(obj) -> str:
    try:
        sig = str(inspect.signature(obj))
    except (ValueError, TypeError):
        return "(...)"
    # callable defaults repr with a process-specific address
    # ("<function sum at 0x7f...>"); strip it so regeneration is
    # byte-stable across runs/machines
    return re.sub(r" at 0x[0-9a-f]+", "", sig)


def public_symbols(m, name):
    pub = []
    names = getattr(m, "__all__", None) or sorted(vars(m))
    for s in names:
        if s.startswith("_"):
            continue
        obj = getattr(m, s, None)
        if obj is None or inspect.ismodule(obj):
            continue
        if inspect.isfunction(obj) or inspect.isclass(obj):
            defmod = getattr(obj, "__module__", "")
            # list a symbol where it is DEFINED (or explicitly
            # re-exported via __all__) — cross-module imports like
            # serialize helpers or private packing utilities are
            # not part of that module's public surface
            explicit = s in (getattr(m, "__all__", None) or ())
            if defmod == name or (explicit
                                  and defmod.startswith("raft_tpu")):
                pub.append((s, obj))
    return pub


def render_class(s, obj, lines):
    lines.append(f"### class `{s}`")
    lines.append("")
    doc = first_para(obj)
    if doc:
        lines.append(doc)
        lines.append("")
    if dataclasses.is_dataclass(obj):
        rows = []
        for f in dataclasses.fields(obj):
            default = ""
            if f.default is not dataclasses.MISSING:
                default = f" = {f.default!r}"
            elif f.default_factory is not dataclasses.MISSING:  # type: ignore
                default = " = <factory>"
            rows.append(f"- `{f.name}{default}`")
        if rows:
            lines.append("Fields:")
            lines.extend(rows)
            lines.append("")
    # public methods/properties defined on the class itself (enums skip
    # this: their members are values, not callables). Descriptor check
    # must come BEFORE callable(): classmethod/property objects are not
    # callable in CPython
    for mn, mv in sorted(vars(obj).items()):
        if mn.startswith("_"):
            continue
        if isinstance(mv, property):
            lines.append(f"- **`.{mn}`** (property) — "
                         f"{first_para(mv.fget) if mv.fget else ''}")
            continue
        if isinstance(mv, (staticmethod, classmethod)):
            mv = mv.__func__
        if not inspect.isfunction(mv):
            continue
        lines.append(f"- **`.{mn}{sig_of(mv)}`** — {first_para(mv)}")
    if lines[-1] != "":
        lines.append("")


def main():
    lines = [
        "# raft_tpu API reference", "",
        "Generated from the live package (`python docs/gen_api.py`); "
        "every public function with its signature and summary, every "
        "public class with its fields and methods. Module docstrings "
        "cite the reference-RAFT files they re-design "
        "(see PARITY.md for the mapping).", "",
        "Modules:", "",
    ]
    toc = []
    body = []
    for name in MODULES:
        m = importlib.import_module(name)
        pub = public_symbols(m, name)
        if not pub:
            continue
        anchor = name.replace(".", "")
        toc.append(f"- [`{name}`](#{anchor})")
        body.append(f"## `{name}`")
        body.append("")
        mdoc = first_para(m)
        if mdoc:
            body.append(mdoc)
            body.append("")
        for s, obj in pub:
            if inspect.isclass(obj):
                render_class(s, obj, body)
            else:
                body.append(f"### `{s}{sig_of(obj)}`")
                body.append("")
                doc = first_para(obj)
                if doc:
                    body.append(doc)
                    body.append("")
    out = pathlib.Path(__file__).parent / "api.md"
    out.write_text("\n".join(lines + toc + [""] + body) + "\n")
    n_funcs = sum(1 for line in body if line.startswith("### `"))
    n_classes = sum(1 for line in body if line.startswith("### class"))
    print(f"wrote {out} ({len(toc)} modules, {n_funcs} functions, "
          f"{n_classes} classes)")


if __name__ == "__main__":
    main()
