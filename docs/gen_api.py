"""Regenerate docs/api.md from the live package.

Run:  JAX_PLATFORMS=cpu python docs/gen_api.py
"""

import importlib
import inspect
import pathlib

MODULES = [
    "raft_tpu.core.resources", "raft_tpu.core.bitset", "raft_tpu.core.logger",
    "raft_tpu.core.tracing", "raft_tpu.core.interruptible",
    "raft_tpu.core.serialize", "raft_tpu.core.operators",
    "raft_tpu.core.validation",
    "raft_tpu.distance", "raft_tpu.linalg", "raft_tpu.matrix", "raft_tpu.ops",
    "raft_tpu.random", "raft_tpu.stats", "raft_tpu.label",
    "raft_tpu.sparse.convert", "raft_tpu.sparse.linalg",
    "raft_tpu.sparse.distance", "raft_tpu.sparse.neighbors",
    "raft_tpu.sparse.ops", "raft_tpu.sparse.solver",
    "raft_tpu.cluster.kmeans", "raft_tpu.cluster.kmeans_balanced",
    "raft_tpu.cluster.single_linkage", "raft_tpu.spectral", "raft_tpu.solver",
    "raft_tpu.neighbors.brute_force", "raft_tpu.neighbors.ivf_flat",
    "raft_tpu.neighbors.ivf_pq", "raft_tpu.neighbors.ivf_bq",
    "raft_tpu.neighbors.cagra",
    "raft_tpu.neighbors.nn_descent", "raft_tpu.neighbors.cluster_join",
    "raft_tpu.neighbors.refine",
    "raft_tpu.neighbors.ball_cover", "raft_tpu.neighbors.epsilon_neighborhood",
    "raft_tpu.neighbors.quantized", "raft_tpu.neighbors.filters",
    "raft_tpu.neighbors.ivf_helpers",
    "raft_tpu.comms", "raft_tpu.comms.bootstrap",
    "raft_tpu.distributed.ivf", "raft_tpu.distributed.knn",
    "raft_tpu.distributed.kmeans", "raft_tpu.distributed.sharded_ann",
    "raft_tpu.distributed.checkpoint", "raft_tpu.distributed.bq",
    "raft_tpu.io", "raft_tpu.bench", "raft_tpu.utils",
]


def main():
    lines = ["# API index", "",
             "Public callables and classes per module (generated from the "
             "package; regenerate with `python docs/gen_api.py`).", ""]
    for name in MODULES:
        m = importlib.import_module(name)
        pub = []
        names = getattr(m, "__all__", None) or sorted(vars(m))
        for s in names:
            if s.startswith("_"):
                continue
            obj = getattr(m, s, None)
            if obj is None or inspect.ismodule(obj):
                continue
            if inspect.isfunction(obj) or inspect.isclass(obj):
                defmod = getattr(obj, "__module__", "")
                # list a symbol where it is DEFINED (or explicitly
                # re-exported via __all__) — cross-module imports like
                # serialize helpers or private packing utilities are
                # not part of that module's public surface
                explicit = s in (getattr(m, "__all__", None) or ())
                if defmod == name or (explicit
                                      and defmod.startswith("raft_tpu")):
                    pub.append(s + ("()" if inspect.isfunction(obj) else ""))
        if pub:
            lines.append(f"- **`{name}`** — "
                         + ", ".join(f"`{s}`" for s in pub))
    out = pathlib.Path(__file__).parent / "api.md"
    out.write_text("\n".join(lines) + "\n")
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
