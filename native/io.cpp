// Native IO runtime for raft_tpu — the TPU-build analog of the
// reference's C++ dataset machinery (bench/ann/src/common/dataset.hpp:
// BinFile<T> mmap loader with header parse + subset windows, and the
// conversion tooling under raft-ann-bench/get_dataset/).
//
// Exposed as a plain C ABI consumed from Python via ctypes
// (raft_tpu/io/native.py). Formats:
//   .fbin / .u8bin / .i8bin : int32 n_rows, int32 dim, then row-major
//   payload of float32 / uint8 / int8 (the big-ann-benchmarks layout).
//
// Capabilities beyond np.memmap (why this is native):
//   - threaded strided reads: subsetting a row range fans out across
//     N threads of pread(2), saturating NVMe/page-cache far better than
//     a single-thread numpy copy for 100M+ row datasets;
//   - bounds-checked header validation with errno-style reporting;
//   - streaming fbin writer used by the bench converter.

#include <atomic>
#include <cerrno>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct BinFile {
  int fd = -1;
  void* map = nullptr;
  size_t file_bytes = 0;
  int64_t n_rows = 0;
  int64_t dim = 0;
  int64_t elem_size = 0;  // bytes per element
  std::string error;
};

thread_local std::string g_last_error;

void set_error(BinFile* f, const std::string& msg) {
  if (f) f->error = msg;
  g_last_error = msg;
}

}  // namespace

extern "C" {

// Open a *.bin file (fbin/u8bin/i8bin): parses the (n, dim) header and
// mmaps the payload read-only. elem_size selects the dtype width.
// Returns an opaque handle or nullptr (see rt_io_last_error).
void* rt_io_open(const char* path, int64_t elem_size) {
  auto* f = new BinFile();
  f->elem_size = elem_size;
  f->fd = ::open(path, O_RDONLY);
  if (f->fd < 0) {
    set_error(nullptr, std::string("open failed: ") + std::strerror(errno));
    delete f;
    return nullptr;
  }
  struct stat st;
  if (fstat(f->fd, &st) != 0) {
    set_error(nullptr, std::string("fstat failed: ") + std::strerror(errno));
    ::close(f->fd);
    delete f;
    return nullptr;
  }
  f->file_bytes = static_cast<size_t>(st.st_size);
  if (f->file_bytes < 8) {
    set_error(nullptr, "file too small for (n, dim) header");
    ::close(f->fd);
    delete f;
    return nullptr;
  }
  int32_t header[2];
  if (pread(f->fd, header, 8, 0) != 8) {
    set_error(nullptr, "header read failed");
    ::close(f->fd);
    delete f;
    return nullptr;
  }
  f->n_rows = header[0];
  f->dim = header[1];
  if (f->n_rows < 0 || f->dim <= 0) {
    set_error(nullptr, "invalid header: negative n or non-positive dim");
    ::close(f->fd);
    delete f;
    return nullptr;
  }
  size_t expected =
      8 + static_cast<size_t>(f->n_rows) * f->dim * f->elem_size;
  if (expected > f->file_bytes) {
    set_error(nullptr, "file truncated: header promises " +
                           std::to_string(expected) + " bytes, have " +
                           std::to_string(f->file_bytes));
    ::close(f->fd);
    delete f;
    return nullptr;
  }
  f->map = mmap(nullptr, f->file_bytes, PROT_READ, MAP_SHARED, f->fd, 0);
  if (f->map == MAP_FAILED) {
    f->map = nullptr;  // reads fall back to pread
  } else {
    madvise(f->map, f->file_bytes, MADV_SEQUENTIAL);
  }
  return f;
}

int64_t rt_io_rows(void* handle) { return static_cast<BinFile*>(handle)->n_rows; }
int64_t rt_io_dim(void* handle) { return static_cast<BinFile*>(handle)->dim; }

const char* rt_io_last_error() { return g_last_error.c_str(); }

// Copy rows [row_start, row_start + n) into out. Fans the copy out over
// n_threads (0 = hardware concurrency, capped at 16). Returns 0 on
// success, -1 on bounds error.
int rt_io_read_rows(void* handle, int64_t row_start, int64_t n, void* out,
                    int n_threads) {
  auto* f = static_cast<BinFile*>(handle);
  if (row_start < 0 || n < 0 || row_start + n > f->n_rows) {
    set_error(f, "read_rows out of bounds");
    return -1;
  }
  const int64_t row_bytes = f->dim * f->elem_size;
  const size_t offset = 8 + static_cast<size_t>(row_start) * row_bytes;
  const size_t total = static_cast<size_t>(n) * row_bytes;

  if (n_threads <= 0) {
    unsigned hc = std::thread::hardware_concurrency();
    n_threads = hc == 0 ? 4 : static_cast<int>(hc);
  }
  if (n_threads > 16) n_threads = 16;
  if (total < (1u << 22)) n_threads = 1;  // small read: threads not worth it

  std::atomic<int> failed{0};
  auto worker = [&](int t) {
    size_t chunk = total / n_threads;
    size_t begin = t * chunk;
    size_t end = (t == n_threads - 1) ? total : begin + chunk;
    if (f->map != nullptr) {
      std::memcpy(static_cast<char*>(out) + begin,
                  static_cast<const char*>(f->map) + offset + begin,
                  end - begin);
    } else {
      size_t pos = begin;
      while (pos < end) {
        ssize_t got = pread(f->fd, static_cast<char*>(out) + pos,
                            end - pos, offset + pos);
        if (got <= 0) {
          failed.store(1);
          return;
        }
        pos += static_cast<size_t>(got);
      }
    }
  };
  if (n_threads == 1) {
    worker(0);
  } else {
    std::vector<std::thread> threads;
    threads.reserve(n_threads);
    for (int t = 0; t < n_threads; ++t) threads.emplace_back(worker, t);
    for (auto& th : threads) th.join();
  }
  if (failed.load()) {
    set_error(f, "pread failed mid-copy");
    return -1;
  }
  return 0;
}

void rt_io_close(void* handle) {
  auto* f = static_cast<BinFile*>(handle);
  if (f->map != nullptr) munmap(f->map, f->file_bytes);
  if (f->fd >= 0) ::close(f->fd);
  delete f;
}

// Streaming writer: create a bin file with a (n, dim) header; rows are
// appended with rt_io_append_rows and the header count is fixed up at
// close (n passed here may be 0 when unknown).
void* rt_io_create(const char* path, int64_t n_rows, int64_t dim,
                   int64_t elem_size) {
  auto* f = new BinFile();
  f->elem_size = elem_size;
  f->dim = dim;
  f->n_rows = 0;
  f->fd = ::open(path, O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (f->fd < 0) {
    set_error(nullptr, std::string("create failed: ") + std::strerror(errno));
    delete f;
    return nullptr;
  }
  int32_t header[2] = {static_cast<int32_t>(n_rows),
                       static_cast<int32_t>(dim)};
  if (write(f->fd, header, 8) != 8) {
    set_error(nullptr, "header write failed");
    ::close(f->fd);
    delete f;
    return nullptr;
  }
  return f;
}

int rt_io_append_rows(void* handle, const void* data, int64_t n) {
  auto* f = static_cast<BinFile*>(handle);
  size_t bytes = static_cast<size_t>(n) * f->dim * f->elem_size;
  size_t pos = 0;
  while (pos < bytes) {
    ssize_t put = write(f->fd, static_cast<const char*>(data) + pos,
                        bytes - pos);
    if (put <= 0) {
      set_error(f, std::string("write failed: ") + std::strerror(errno));
      return -1;
    }
    pos += static_cast<size_t>(put);
  }
  f->n_rows += n;
  return 0;
}

// ---------------------------------------------------------------------------
// Prefetching chunk pipeline: a background thread reads chunk i+1 while
// the caller consumes chunk i (double-buffered). This is the streaming
// ingestion path for 100M+-row datasets — the role the reference's
// subset-window BinFile plays for its batched index builds, plus
// read-ahead the reference leaves to the page cache.
// ---------------------------------------------------------------------------

namespace {

struct Pipeline {
  BinFile* file = nullptr;
  int64_t chunk_rows = 0;
  int n_threads = 0;
  int64_t next_row = 0;          // next row the reader will fetch
  std::vector<char> buf[2];
  int64_t buf_rows[2] = {0, 0};  // rows in each buffer (0 = empty)
  int64_t buf_first[2] = {-1, -1};
  bool buf_ready[2] = {false, false};
  int consume_slot = 0;          // next slot handed to the caller
  int last_returned = -1;        // slot whose lifetime ends on next call
  bool done = false;             // reader reached EOF
  bool failed = false;
  bool stop = false;
  std::mutex mu;
  std::condition_variable cv;
  std::thread reader;
};

void pipeline_reader(Pipeline* p) {
  int fill_slot = 0;
  for (;;) {
    std::unique_lock<std::mutex> lk(p->mu);
    p->cv.wait(lk, [&] { return p->stop || !p->buf_ready[fill_slot]; });
    if (p->stop) return;
    int64_t row = p->next_row;
    if (row >= p->file->n_rows) {
      p->done = true;
      p->cv.notify_all();
      return;
    }
    int64_t n = p->file->n_rows - row;
    if (n > p->chunk_rows) n = p->chunk_rows;
    p->next_row = row + n;
    lk.unlock();

    int rc = rt_io_read_rows(p->file, row, n, p->buf[fill_slot].data(),
                             p->n_threads);

    lk.lock();
    if (rc != 0) {
      p->failed = true;
      p->cv.notify_all();
      return;
    }
    p->buf_rows[fill_slot] = n;
    p->buf_first[fill_slot] = row;
    p->buf_ready[fill_slot] = true;
    p->cv.notify_all();
    fill_slot ^= 1;
  }
}

}  // namespace

// Start a prefetching reader over an open rt_io handle. The pipeline
// owns read positions [0, n_rows) in chunk_rows steps.
void* rt_io_pipeline_start(void* handle, int64_t chunk_rows, int n_threads) {
  auto* f = static_cast<BinFile*>(handle);
  if (chunk_rows <= 0) {
    set_error(f, "pipeline chunk_rows must be positive");
    return nullptr;
  }
  auto* p = new Pipeline();
  p->file = f;
  p->chunk_rows = chunk_rows;
  p->n_threads = n_threads;
  size_t bytes = static_cast<size_t>(chunk_rows) * f->dim * f->elem_size;
  p->buf[0].resize(bytes);
  p->buf[1].resize(bytes);
  p->reader = std::thread(pipeline_reader, p);
  return p;
}

// Block until the next chunk is ready. On success returns 0 and fills
// (*data, *first_row, *n_rows); the buffer stays valid until the NEXT
// rt_io_pipeline_next call. Returns 1 at end-of-file, -1 on read error.
int rt_io_pipeline_next(void* pipe, void** data, int64_t* first_row,
                        int64_t* n_rows) {
  auto* p = static_cast<Pipeline*>(pipe);
  std::unique_lock<std::mutex> lk(p->mu);
  // the buffer handed out by the previous call dies now — release it
  // so the reader can refill it
  if (p->last_returned >= 0) {
    p->buf_ready[p->last_returned] = false;
    p->last_returned = -1;
    p->cv.notify_all();
  }
  int slot = p->consume_slot;
  p->cv.wait(lk, [&] {
    return p->buf_ready[slot] || p->done || p->failed;
  });
  if (p->failed) return -1;
  if (!p->buf_ready[slot]) return 1;  // done and nothing buffered
  *data = p->buf[slot].data();
  *first_row = p->buf_first[slot];
  *n_rows = p->buf_rows[slot];
  p->last_returned = slot;
  p->consume_slot = slot ^ 1;
  return 0;
}

void rt_io_pipeline_close(void* pipe) {
  auto* p = static_cast<Pipeline*>(pipe);
  {
    std::lock_guard<std::mutex> lk(p->mu);
    p->stop = true;
  }
  p->cv.notify_all();
  if (p->reader.joinable()) p->reader.join();
  delete p;
}

int rt_io_close_writer(void* handle) {
  auto* f = static_cast<BinFile*>(handle);
  int32_t n = static_cast<int32_t>(f->n_rows);
  int rc = 0;
  if (pwrite(f->fd, &n, 4, 0) != 4) {
    set_error(f, "header fixup failed");
    rc = -1;
  }
  ::close(f->fd);
  delete f;
  return rc;
}

}  // extern "C"
