// Native CPU HNSW — the competitor baseline for the bench harness.
//
// Role: the reference benchmarks RAFT against hnswlib on CPU
// (cpp/bench/ann/src/hnswlib/hnswlib_wrapper.h); this environment has
// no hnswlib, so the comparison baseline is this from-scratch C++17
// implementation of the HNSW algorithm (Malkov & Yashunin,
// arXiv:1603.09320): multi-layer proximity graph, greedy descent on
// upper layers, best-first ef-search on layer 0, heuristic neighbor
// selection with pruned-fill. Single-threaded by design — the bench
// host has one core, and a 1-thread baseline matches the reference's
// per-thread QPS accounting.
//
// C ABI only (loaded via ctypes from raft_tpu/bench/hnsw_cpu.py).

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <queue>
#include <random>
#include <string>
#include <vector>

namespace {

thread_local std::string g_error;

constexpr uint32_t kMagic = 0x72684e57;  // "rhNW"
constexpr int kMetricL2 = 0;
constexpr int kMetricIP = 1;

struct Hnsw {
  int64_t dim = 0;
  int64_t M = 16;         // links per node, upper layers
  int64_t M0 = 32;        // links per node, layer 0
  int64_t ef_construction = 200;
  int metric = kMetricL2;
  double mult = 0.0;      // level multiplier 1/ln(M)
  std::mt19937_64 rng;

  int64_t n = 0;
  std::vector<float> vecs;              // n * dim
  std::vector<int32_t> levels;          // per node
  // links[l][node] is a fixed-capacity row: [count, id0, id1, ...]
  // upper layers store rows only for nodes whose level >= l.
  // Layer rows are flat per level for cache friendliness.
  std::vector<std::vector<uint32_t>> links;  // per level, flat rows
  std::vector<int64_t> row_of;          // node -> row index per upper level? (see note)
  // Simpler: upper-level links are stored per node in a ragged table.
  std::vector<std::vector<std::vector<uint32_t>>> upper;  // [node][level-1] -> ids
  std::vector<std::vector<uint32_t>> level0;              // [node] -> ids
  int32_t max_level = -1;
  int64_t entry = -1;

  // visited-epoch tags (reused across searches)
  std::vector<uint32_t> visited;
  uint32_t epoch = 0;

  float dist(const float* a, const float* b) const {
    double acc = 0.0;
    if (metric == kMetricL2) {
      for (int64_t i = 0; i < dim; ++i) {
        const double d = double(a[i]) - double(b[i]);
        acc += d * d;
      }
      return float(acc);
    }
    for (int64_t i = 0; i < dim; ++i) acc += double(a[i]) * double(b[i]);
    return float(-acc);  // min-form inner product
  }

  const float* vec(int64_t id) const { return vecs.data() + id * dim; }

  uint32_t* touch_epoch() {
    if (++epoch == 0) {  // wrap: clear tags once every 2^32 searches
      std::fill(visited.begin(), visited.end(), 0u);
      epoch = 1;
    }
    visited.resize(size_t(n), 0u);
    return visited.data();
  }

  const std::vector<uint32_t>& neighbors(int64_t id, int level) const {
    if (level == 0) return level0[size_t(id)];
    return upper[size_t(id)][size_t(level - 1)];
  }
  std::vector<uint32_t>& neighbors_mut(int64_t id, int level) {
    if (level == 0) return level0[size_t(id)];
    return upper[size_t(id)][size_t(level - 1)];
  }

  // Greedy single-step descent used on layers above the target.
  int64_t greedy(const float* q, int64_t ep, int level) const {
    int64_t cur = ep;
    float curd = dist(q, vec(cur));
    bool improved = true;
    while (improved) {
      improved = false;
      for (uint32_t nb : neighbors(cur, level)) {
        const float d = dist(q, vec(nb));
        if (d < curd) {
          curd = d;
          cur = nb;
          improved = true;
        }
      }
    }
    return cur;
  }

  using HeapItem = std::pair<float, uint32_t>;

  // Best-first search on one layer; returns up to ef closest as a
  // max-heap (worst on top).
  std::priority_queue<HeapItem> search_layer(const float* q, int64_t ep,
                                             int level, size_t ef) {
    uint32_t* seen = touch_epoch();
    const uint32_t tag = epoch;
    std::priority_queue<HeapItem> best;                       // max-heap
    std::priority_queue<HeapItem, std::vector<HeapItem>,
                        std::greater<HeapItem>> cand;         // min-heap
    const float epd = dist(q, vec(ep));
    best.emplace(epd, uint32_t(ep));
    cand.emplace(epd, uint32_t(ep));
    seen[ep] = tag;
    while (!cand.empty()) {
      const auto [cd, c] = cand.top();
      if (cd > best.top().first && best.size() >= ef) break;
      cand.pop();
      for (uint32_t nb : neighbors(c, level)) {
        if (seen[nb] == tag) continue;
        seen[nb] = tag;
        const float d = dist(q, vec(nb));
        if (best.size() < ef || d < best.top().first) {
          cand.emplace(d, nb);
          best.emplace(d, nb);
          if (best.size() > ef) best.pop();
        }
      }
    }
    return best;
  }

  // Heuristic neighbor selection (algorithm 4 of the paper, with the
  // pruned-fill extension): keep a candidate only if it is closer to
  // the base point than to every already-kept neighbor — spreads the
  // links over the cluster structure; backfill from pruned if short.
  void select_neighbors(std::vector<HeapItem>& cand, size_t M,
                        std::vector<uint32_t>& out) const {
    std::sort(cand.begin(), cand.end());
    out.clear();
    std::vector<HeapItem> pruned;
    for (const auto& [d, id] : cand) {
      if (out.size() >= M) break;
      bool keep = true;
      for (uint32_t s : out) {
        if (dist(vec(id), vec(s)) < d) {
          keep = false;
          break;
        }
      }
      if (keep)
        out.push_back(id);
      else
        pruned.emplace_back(d, id);
    }
    for (const auto& [d, id] : pruned) {
      if (out.size() >= M) break;
      out.push_back(id);
    }
  }

  void shrink(int64_t id, int level) {
    auto& nbs = neighbors_mut(id, level);
    const size_t cap = size_t(level == 0 ? M0 : M);
    if (nbs.size() <= cap) return;
    std::vector<HeapItem> cand;
    cand.reserve(nbs.size());
    const float* base = vec(id);
    for (uint32_t nb : nbs) cand.emplace_back(dist(base, vec(nb)), nb);
    std::vector<uint32_t> kept;
    select_neighbors(cand, cap, kept);
    nbs = std::move(kept);
  }

  void add_one(const float* v) {
    const int64_t id = n++;
    vecs.insert(vecs.end(), v, v + dim);
    std::uniform_real_distribution<double> uni(0.0, 1.0);
    double u = uni(rng);
    if (u < 1e-12) u = 1e-12;
    const int32_t level = int32_t(-std::log(u) * mult);
    levels.push_back(level);
    level0.emplace_back();
    level0.back().reserve(size_t(M0));
    upper.emplace_back(size_t(std::max<int32_t>(level, 0)));
    if (entry < 0) {
      entry = id;
      max_level = level;
      return;
    }
    int64_t ep = entry;
    for (int l = max_level; l > level; --l) ep = greedy(v, ep, l);
    for (int l = std::min(level, max_level); l >= 0; --l) {
      auto found = search_layer(v, ep, l, size_t(ef_construction));
      std::vector<HeapItem> cand;
      cand.reserve(found.size());
      while (!found.empty()) {
        cand.push_back(found.top());
        found.pop();
      }
      std::vector<uint32_t> sel;
      select_neighbors(cand, size_t(M), sel);
      auto& mine = neighbors_mut(id, l);
      mine = sel;
      for (uint32_t nb : sel) {
        neighbors_mut(nb, l).push_back(uint32_t(id));
        shrink(nb, l);
      }
      if (!sel.empty()) ep = sel[0];  // closest kept neighbor
    }
    if (level > max_level) {
      max_level = level;
      entry = id;
    }
  }

  int search(const float* q, int64_t k, int64_t ef, float* out_d,
             int64_t* out_i) {
    if (n == 0) return -1;
    int64_t ep = entry;
    for (int l = max_level; l > 0; --l) ep = greedy(q, ep, l);
    auto best = search_layer(q, ep, 0, size_t(std::max(ef, k)));
    while (int64_t(best.size()) > k) best.pop();
    int64_t got = int64_t(best.size());
    for (int64_t i = got - 1; i >= 0; --i) {
      out_d[i] = best.top().first;
      out_i[i] = int64_t(best.top().second);
      best.pop();
    }
    for (int64_t i = got; i < k; ++i) {
      out_d[i] = INFINITY;
      out_i[i] = -1;
    }
    return 0;
  }
};

template <typename T>
bool wr(FILE* f, const T& v) {
  return std::fwrite(&v, sizeof(T), 1, f) == 1;
}
template <typename T>
bool wr_vec(FILE* f, const std::vector<T>& v) {
  const uint64_t sz = v.size();
  if (!wr(f, sz)) return false;
  return sz == 0 || std::fwrite(v.data(), sizeof(T), sz, f) == sz;
}
template <typename T>
bool rd(FILE* f, T& v) {
  return std::fread(&v, sizeof(T), 1, f) == 1;
}
template <typename T>
bool rd_vec(FILE* f, std::vector<T>& v) {
  uint64_t sz = 0;
  if (!rd(f, sz)) return false;
  v.resize(size_t(sz));
  return sz == 0 || std::fread(v.data(), sizeof(T), sz, f) == sz;
}

}  // namespace

extern "C" {

const char* hnsw_last_error() { return g_error.c_str(); }

void* hnsw_create(int64_t dim, int64_t M, int64_t ef_construction,
                  int metric, uint64_t seed) {
  if (dim <= 0 || M < 2 || ef_construction < 1 ||
      (metric != kMetricL2 && metric != kMetricIP)) {
    g_error = "hnsw_create: bad parameters";
    return nullptr;
  }
  auto* h = new Hnsw();
  h->dim = dim;
  h->M = M;
  h->M0 = 2 * M;
  h->ef_construction = ef_construction;
  h->metric = metric;
  h->mult = 1.0 / std::log(double(M));
  h->rng.seed(seed);
  return h;
}

int hnsw_add(void* ptr, const float* vecs, int64_t count) {
  if (!ptr || !vecs || count < 0) {
    g_error = "hnsw_add: bad arguments";
    return -1;
  }
  auto* h = static_cast<Hnsw*>(ptr);
  for (int64_t i = 0; i < count; ++i) h->add_one(vecs + i * h->dim);
  return 0;
}

int64_t hnsw_size(void* ptr) {
  return ptr ? static_cast<Hnsw*>(ptr)->n : -1;
}

// accessors so a loader can cross-check a cache file's recorded
// geometry/metric against what the caller expects — a mismatched file
// would otherwise stride queries by the WRONG dim at search time
int64_t hnsw_dim(void* ptr) {
  return ptr ? static_cast<Hnsw*>(ptr)->dim : -1;
}

int hnsw_metric(void* ptr) {
  return ptr ? static_cast<Hnsw*>(ptr)->metric : -1;
}

int hnsw_search(void* ptr, const float* queries, int64_t nq, int64_t k,
                int64_t ef, float* out_d, int64_t* out_i) {
  if (!ptr || !queries || nq < 0 || k < 1) {
    g_error = "hnsw_search: bad arguments";
    return -1;
  }
  auto* h = static_cast<Hnsw*>(ptr);
  for (int64_t i = 0; i < nq; ++i) {
    if (h->search(queries + i * h->dim, k, ef, out_d + i * k,
                  out_i + i * k) != 0) {
      g_error = "hnsw_search: empty index";
      return -1;
    }
  }
  return 0;
}

int hnsw_save(void* ptr, const char* path) {
  auto* h = static_cast<Hnsw*>(ptr);
  FILE* f = std::fopen(path, "wb");
  if (!f) {
    g_error = std::string("hnsw_save: cannot open ") + path;
    return -1;
  }
  bool ok = wr(f, kMagic) && wr(f, h->dim) && wr(f, h->M) &&
            wr(f, h->ef_construction) && wr(f, h->metric) && wr(f, h->n) &&
            wr(f, h->max_level) && wr(f, h->entry) && wr_vec(f, h->vecs) &&
            wr_vec(f, h->levels);
  for (int64_t i = 0; ok && i < h->n; ++i) {
    ok = wr_vec(f, h->level0[size_t(i)]);
    for (const auto& row : h->upper[size_t(i)])
      ok = ok && wr_vec(f, row);
  }
  std::fclose(f);
  if (!ok) g_error = "hnsw_save: short write";
  return ok ? 0 : -1;
}

void* hnsw_load(const char* path) try {
  FILE* f = std::fopen(path, "rb");
  if (!f) {
    g_error = std::string("hnsw_load: cannot open ") + path;
    return nullptr;
  }
  auto* h = new Hnsw();
  uint32_t magic = 0;
  bool ok = rd(f, magic) && magic == kMagic && rd(f, h->dim) &&
            rd(f, h->M) && rd(f, h->ef_construction) && rd(f, h->metric) &&
            rd(f, h->n) && rd(f, h->max_level) && rd(f, h->entry);
  // validate scalar fields BEFORE any size-driven allocation: a corrupt
  // cache file must come back as an error the Python runner can recover
  // from (rebuild), never a std::bad_alloc escaping into ctypes
  ok = ok && h->dim > 0 && h->dim <= (1 << 20) && h->M >= 2 &&
       h->M <= (1 << 20) && h->n >= 0 && h->entry >= -1 &&
       h->entry < h->n;
  ok = ok && rd_vec(f, h->vecs) && rd_vec(f, h->levels) &&
       h->vecs.size() == size_t(h->n) * size_t(h->dim) &&
       h->levels.size() == size_t(h->n);
  // max_level must be consistent with levels[]: greedy()/neighbors()
  // index upper[entry][max_level-1], so a corrupt max_level above the
  // entry's actual level list is an out-of-bounds read at SEARCH time —
  // reject it here like every other corruption
  // an empty index is always saved with max_level == -1; for n > 0 the
  // levels[] cross-check below pins max_level (>= 0) exactly
  ok = ok && (h->n > 0 || h->max_level == -1);
  if (ok && h->n > 0) {
    ok = h->entry >= 0 && h->levels[size_t(h->entry)] == h->max_level;
    for (int64_t i = 0; ok && i < h->n; ++i)
      ok = h->levels[size_t(i)] >= 0 && h->levels[size_t(i)] <= h->max_level;
  }
  if (ok) {
    h->M0 = 2 * h->M;
    h->mult = 1.0 / std::log(double(h->M));
    h->level0.resize(size_t(h->n));
    h->upper.resize(size_t(h->n));
    for (int64_t i = 0; ok && i < h->n; ++i) {
      ok = rd_vec(f, h->level0[size_t(i)]);
      for (uint32_t nb : h->level0[size_t(i)])
        ok = ok && int64_t(nb) < h->n;  // stale ids read out of bounds
      h->upper[size_t(i)].resize(
          size_t(std::max<int32_t>(h->levels[size_t(i)], 0)));
      for (auto& row : h->upper[size_t(i)]) {
        ok = ok && rd_vec(f, row);
        for (uint32_t nb : row) ok = ok && int64_t(nb) < h->n;
      }
    }
  }
  std::fclose(f);
  if (!ok) {
    g_error = "hnsw_load: corrupt or truncated file";
    delete h;
    return nullptr;
  }
  return h;
} catch (const std::exception& e) {
  g_error = std::string("hnsw_load: ") + e.what();
  return nullptr;
}

void hnsw_free(void* ptr) { delete static_cast<Hnsw*>(ptr); }

}  // extern "C"
