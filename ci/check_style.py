#!/usr/bin/env python
"""Style gate — thin wrapper over graftlint rule R0.

The AST style pass that used to live in this file (syntax, unused
imports, whitespace, no print-in-lib, no NotImplementedError stubs) is
now rule R0 of ``raft_tpu.analysis`` (graftlint), behind the shared
rule registry, so style and the serving-path invariant rules R1–R6 run
one traversal and one suppression mechanism.

Run: ``python ci/check_style.py`` (exit 1 on any finding).
The full analyzer is ``python -m raft_tpu.analysis`` — ci/test.sh runs
that as the real gate; this entry point stays for the quick
style-only loop.
"""
from __future__ import annotations

import pathlib
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))


def main() -> int:
    from raft_tpu.analysis import Project, run
    from raft_tpu.analysis.report import render_text

    report = run(Project.from_root(ROOT), rules=["R0"])
    out = render_text(report)
    print(out.replace("graftlint:", "check_style [graftlint R0]:"),
          end="")
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
