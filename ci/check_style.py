#!/usr/bin/env python
"""DEPRECATED shim — use ``python -m raft_tpu.analysis --rules=R0``.

The style pass lives in graftlint (``raft_tpu.analysis``) as rule R0;
this file survives only so old muscle memory and scripts keep working.
It prints a pointer and delegates to the real CLI with the same exit
code. It will be removed once nothing invokes it.
"""
from __future__ import annotations

import pathlib
import subprocess
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent


def main() -> int:
    sys.stderr.write(
        "ci/check_style.py is deprecated; run "
        "`python -m raft_tpu.analysis --rules=R0` instead "
        "(full analyzer: `python -m raft_tpu.analysis`).\n")
    return subprocess.call(
        [sys.executable, "-m", "raft_tpu.analysis", "--rules=R0",
         "--root", str(ROOT)], cwd=str(ROOT))


if __name__ == "__main__":
    sys.exit(main())
