#!/usr/bin/env python
"""Static style/sanity checks — role of the reference's
``ci/checks/check_style.sh`` (flake8/black/clang-format there). The
image ships no third-party linters, so this is a self-contained AST
pass enforcing the repo's own hygiene rules:

  * every source file byte-compiles (syntax)
  * no unused imports (except explicit ``# noqa`` / re-export manifests)
  * no tabs, no trailing whitespace, newline at EOF
  * no ``print(`` in library code (loggers only; bench/examples/scripts
    and the CLI are exempt — printing is their job)
  * no ``NotImplementedError`` stubs in ``raft_tpu/``

Run: ``python ci/check_style.py`` (exit 1 on any finding).
"""
from __future__ import annotations

import ast
import pathlib
import py_compile
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent
LIB = ROOT / "raft_tpu"
CHECK_DIRS = [LIB, ROOT / "tests", ROOT / "examples", ROOT / "scripts"]
PRINT_EXEMPT = ("bench", "examples", "scripts", "__main__")

errors: list[str] = []


def err(path: pathlib.Path, line: int, msg: str) -> None:
    errors.append(f"{path.relative_to(ROOT)}:{line}: {msg}")


class ImportTracker(ast.NodeVisitor):
    """Collect imported names and every name read anywhere."""

    def __init__(self) -> None:
        self.imported: dict[str, int] = {}
        self.used: set[str] = set()

    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            name = (a.asname or a.name).split(".")[0]
            self.imported[name] = node.lineno

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module == "__future__":
            return
        for a in node.names:
            if a.name == "*":
                continue
            self.imported[a.asname or a.name] = node.lineno

    def visit_Name(self, node: ast.Name) -> None:
        if isinstance(node.ctx, ast.Load):
            self.used.add(node.id)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        self.generic_visit(node)


def check_file(path: pathlib.Path) -> None:
    rel = str(path.relative_to(ROOT))
    try:
        py_compile.compile(str(path), doraise=True, cfile=None)
    except py_compile.PyCompileError as e:
        err(path, 0, f"does not compile: {e.msg}")
        return

    text = path.read_text()
    lines = text.splitlines()
    noqa = {i + 1 for i, ln in enumerate(lines) if "# noqa" in ln}
    for i, ln in enumerate(lines, 1):
        if "\t" in ln:
            err(path, i, "tab character")
        if ln != ln.rstrip():
            err(path, i, "trailing whitespace")
    if text and not text.endswith("\n"):
        err(path, len(lines), "no newline at end of file")

    tree = ast.parse(text)

    # unused imports — skip __init__.py (re-export manifests) and conftest
    if path.name not in ("__init__.py", "conftest.py"):
        tracker = ImportTracker()
        tracker.visit(tree)
        # names referenced in __all__ strings or docstring references count
        all_strings = {
            s.value
            for s in ast.walk(tree)
            if isinstance(s, ast.Constant) and isinstance(s.value, str)
        }
        for name, line in tracker.imported.items():
            if line in noqa or name.startswith("_"):
                continue
            if name not in tracker.used and name not in all_strings:
                err(path, line, f"unused import '{name}'")

    in_lib = path.is_relative_to(LIB)
    exempt = any(p in path.parts for p in PRINT_EXEMPT)
    if in_lib and not exempt:
        for node in ast.walk(tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "print"
                    and node.lineno not in noqa):
                err(path, node.lineno, "print() in library code — use the logger")
            # a function whose whole body is `raise NotImplementedError`
            # is a stub; a terminal raise after exhaustive dispatch is fine
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                body = [s for s in node.body
                        if not (isinstance(s, ast.Expr)
                                and isinstance(s.value, ast.Constant))]
                if len(body) == 1 and isinstance(body[0], ast.Raise):
                    exc = body[0].exc
                    name = (exc.func.id if isinstance(exc, ast.Call)
                            and isinstance(exc.func, ast.Name) else
                            exc.id if isinstance(exc, ast.Name) else None)
                    if name == "NotImplementedError":
                        err(path, node.lineno, "NotImplementedError stub")


def main() -> int:
    n = 0
    for d in CHECK_DIRS:
        if not d.exists():
            continue
        for path in sorted(d.rglob("*.py")):
            n += 1
            check_file(path)
    if errors:
        print(f"check_style: {len(errors)} finding(s) in {n} files")
        for e in errors:
            print("  " + e)
        return 1
    print(f"check_style: OK ({n} files)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
