#!/usr/bin/env bash
# CI orchestration — role of the reference's ci/ tree:
#   ci/checks/check_style.sh  -> ci/check_style.py (AST lint, no deps)
#   ci/test_python.sh / ctest -> pytest (tests cover the whole framework;
#                                native IO is built on demand via tests/test_io.py)
#   wheel smoke tests         -> editable install + bare import + CLI --help
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== style =="
python ci/check_style.py

echo "== packaging smoke =="
python -m pip install -e . --no-deps --no-build-isolation --quiet
(cd /tmp && JAX_PLATFORMS=cpu python -c "import raft_tpu; print('import OK', raft_tpu.__name__)")
JAX_PLATFORMS=cpu python -m raft_tpu.bench --help > /dev/null && echo "bench CLI OK"

echo "== tests =="
python -m pytest tests/ -q "$@"
