#!/usr/bin/env bash
# CI orchestration — role of the reference's ci/ tree:
#   ci/checks/check_style.sh  -> graftlint (python -m raft_tpu.analysis;
#                                AST+dataflow lint, no deps — style is
#                                rule R0, serving invariants R1-R6)
#   ci/test_python.sh / ctest -> pytest (tests cover the whole framework;
#                                native IO is built on demand via tests/test_io.py)
#   wheel smoke tests         -> editable install + bare import + CLI --help
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== graftlint =="
# exits non-zero on any unsuppressed finding; the JSON report lands
# next to the bench JSONs as a build artifact
JAX_PLATFORMS=cpu python -m raft_tpu.analysis --format=ci \
    --output ci/graftlint_report.json \
    --lockgraph ci/graftlint_lockgraph.json

echo "== packaging smoke =="
python -m pip install -e . --no-deps --no-build-isolation --quiet
(cd /tmp && JAX_PLATFORMS=cpu python -c "import raft_tpu; print('import OK', raft_tpu.__name__)")
JAX_PLATFORMS=cpu python -m raft_tpu.bench --help > /dev/null && echo "bench CLI OK"

echo "== tests =="
# the session drops ci/metrics_snapshot.json — the full tracing
# registries (counters / gauges / cumulative-bucket histograms / span
# ring stats) as a build artifact next to the graftlint report
RAFT_TPU_METRICS_SNAPSHOT="$PWD/ci/metrics_snapshot.json" \
    python -m pytest tests/ -q "$@"

echo "== bench regression gate =="
# graftscope v2: replay the pinned small-config bench and diff it (plus
# the metrics snapshot's modeled-throughput columns) against the
# committed baseline with tolerance bands — exits nonzero on a
# throughput/latency/recompile regression. Re-baseline deliberately:
#   python ci/bench_compare.py --run --update
python ci/bench_compare.py --run --snapshot ci/metrics_snapshot.json
