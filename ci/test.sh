#!/usr/bin/env bash
# Test orchestration — role of the reference's ci/test_python.sh /
# test_cpp.sh (pytest + ctest). One suite here: the Python tests cover
# the whole framework; the native IO library is built on demand by the
# io module and exercised through tests/test_io.py.
set -euo pipefail
cd "$(dirname "$0")/.."

python -m pytest tests/ -q "$@"
