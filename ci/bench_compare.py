#!/usr/bin/env python
"""CI perf-regression gate (graftscope v2) — diff a fresh
``BENCH_SERVING`` run against the committed baseline with tolerance
bands, and sanity-check the test session's ``ci/metrics_snapshot.json``
modeled-throughput columns.

Why: PRs 1–6 built the serving hot path and the instrumentation that
prices it, but nothing *gated* on the numbers — a PR could halve
steady-state QPS or silently stop pricing dispatches and CI would stay
green. This script closes that loop:

1. **Bench diff** — replay the baseline's pinned small-config bench
   (``BENCH_CHILD=1``, CPU, seconds-scale) and compare the recorded
   columns against ``ci/bench_baseline.json``. Bands are wide where CI
   machines are noisy (wall-clock QPS/p99) and tight where the quantity
   is structural (batch occupancy, backend compiles during load —
   a recompiling steady state is a bug regardless of wall clock).
2. **Snapshot floors** — the metrics snapshot the test session drops
   must still carry live modeled-throughput accounting
   (``serving.execute.modeled_{bytes,flops}`` > 0): if a refactor
   disconnects cost introspection from the dispatch path, every
   achieved-GB/s surface goes dark while looking "green"; this catches
   it structurally.

Exit codes: 0 pass, 1 regression (messages on stderr), 2 usage/missing
inputs. Re-baseline deliberately with ``--update`` (writes the fresh
record + current default tolerances back to the baseline file) — the
diff then shows reviewers exactly what moved.

**Multi-baseline** (PR 8): ``--baseline`` repeats, and with none given
every committed ``ci/bench_baseline*.json`` gates — so a TPU-recorded
baseline (``scripts/record_tpu_baseline.py`` →
``ci/bench_baseline_tpu.json``) rides next to the pinned CPU one. A
baseline carrying ``"requires_backend"`` is skipped with a note when
the current jax backend differs (the TPU baseline is inert on CPU CI
and live on the TPU runner); each baseline replays its OWN pinned env,
and identical envs share one bench run.

Usage (what ``ci/test.sh`` runs)::

    python ci/bench_compare.py --run --snapshot ci/metrics_snapshot.json
    python ci/bench_compare.py --run --update        # re-baseline
    python ci/bench_compare.py --fresh some_run.json  # offline diff
    python ci/bench_compare.py --run \
        --baseline ci/bench_baseline_tpu.json         # TPU gate only
"""

from __future__ import annotations

import argparse
import glob as _glob
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE_PATH = os.path.join(REPO, "ci", "bench_baseline.json")

# The pinned replay config: small enough for seconds-scale CI on CPU,
# big enough that the serving rider coalesces real micro-batches. It is
# recorded into the baseline and replayed from there on compare runs,
# so baseline and fresh always measure the same problem.
PINNED_ENV = {
    "BENCH_CHILD": "1",
    "JAX_PLATFORMS": "cpu",
    "BENCH_N": "20000",
    "BENCH_DIM": "64",
    "BENCH_BATCH": "10",
    "BENCH_K": "10",
    "BENCH_SECONDS": "3",
    "BENCH_DTYPE": "float32",
    "BENCH_SERVING": "1",
    "BENCH_SV_N": "20000",
    "BENCH_SV_LISTS": "32",
    "BENCH_SV_BURSTS": "6",
    # high occupancy with MIXED request sizes — the regime the
    # pad-waste acceptance column is defined over: whole-request
    # assembly stops mid-bucket when the next (large) request does
    # not fit, so the bucketed leg pays the pow2 rounding, while the
    # ragged leg splits at tile boundaries and keeps tiles full
    # (light load pads partial tiles on both paths, but light load
    # has idle compute to burn)
    "BENCH_SV_BURST": "8",
    "BENCH_SV_MAX_ROWS": "96",
    "BENCH_SV_RAGGED_TILE": "128",
    # graftragged (PR 15): the dual small tile and the PQ/BQ/mesh
    # family legs; the forced virtual CPU devices give the mesh leg
    # its 4-shard mesh (every rider in the child sees 4 devices —
    # single-device riders place on device 0 as before)
    "BENCH_SV_RAGGED_SMALL": "32",
    "BENCH_SV_FAMILIES": "1",
    "BENCH_SV_MESH_SHARDS": "4",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
    "BENCH_SV_PERIOD_MS": "10",
    "BENCH_SV_WAIT_MS": "2",
    # generous deadline: on a loaded CI host the CPU executes batches
    # near the second scale, and a deadline-shed would make the
    # completion column timing-flaky — attainment is still measured
    # (slo_* columns), it just isn't gated
    "BENCH_SV_TIMEOUT_MS": "10000",
    # graftfleet continuous-capture overhead A/B (PR 12): a fast
    # cadence so the seconds-scale run still pays >= 1 real profiler
    # window; the 1% duty budget then gates the rest as deployed
    "BENCH_SV_CONT": "1",
    "BENCH_SV_CONT_PERIOD_MS": "50",
    "BENCH_SV_CONT_CAPTURE_MS": "20",
    # RaBitQ IVF-BQ rider (this PR): small enough for seconds-scale
    # CPU CI, clustered enough that the recall floor band is stable
    "BENCH_BQ": "1",
    "BENCH_BQ_N": "20000",
    "BENCH_BQ_LISTS": "32",
    "BENCH_BQ_PROBES": "8",
    "BENCH_BQ_SECONDS": "2",
    # graftbeam (PR 16): the CAGRA A/B rider — pool vs coarse-plane
    # seeding vs coarse + BQ traversal on one small graph index; the
    # coarse pool is pinned 8x under the legacy pool (the frontier
    # claim the recall bands then hold at)
    "BENCH_CAGRA": "1",
    "BENCH_CAGRA_N": "8000",
    "BENCH_CAGRA_DEG": "16",
    "BENCH_CAGRA_BITS": "2",
    "BENCH_CAGRA_POOL": "4096",
    "BENCH_CAGRA_COARSE_POOL": "512",
    "BENCH_CAGRA_SECONDS": "2",
    # graftwire (this PR): the multichip rider on the 4 forced virtual
    # CPU devices — the quantized-vs-f32 k-means build A/B and the 2-D
    # query×list grid's compiles-during-load column; small enough for
    # seconds-scale CI, sharded enough that the wires actually cross
    # shard boundaries
    "BENCH_MULTICHIP": "1",
    "BENCH_MC_N": "4096",
    "BENCH_MC_LISTS": "32",
    "BENCH_MC_PROBES": "5",
    "BENCH_MC_SECONDS": "1",
    "BENCH_MC_KMEANS_ITERS": "3",
    "BENCH_MC_KMEANS_ROWS": "2048",
    # grafttier (PR 14): tiered storage rider — half the lists cold,
    # dual rooflines, two live placement epochs
    "BENCH_TIERED": "1",
    "BENCH_TIER_N": "20000",
    "BENCH_TIER_LISTS": "32",
    "BENCH_TIER_PROBES": "8",
    "BENCH_TIER_SECONDS": "2",
    # graftroute (PR 20): the fleet-router rider — device-free
    # N-replica harness, so every structural column (bit-identity,
    # recall, merge bytes, coverage split) is deterministic at the
    # pinned geometry
    "BENCH_FLEET": "1",
    "BENCH_FLEET_REPLICAS": "4",
    "BENCH_FLEET_LISTS": "64",
    "BENCH_FLEET_SECONDS": "1",
}

# Tolerance bands, keyed by dotted path into the bench record.
#   min_ratio:    fresh >= baseline * r   (higher is better)
#   max_ratio:    fresh <= baseline * r   (lower is better; a zero
#                 baseline only requires fresh to stay finite-small
#                 via max_increase when given)
#   max_increase: fresh <= baseline + n   (absolute slack)
# Wall-clock columns get wide bands (shared CI hosts are noisy);
# structural columns get tight ones.
DEFAULT_TOLERANCES = {
    "value": {"min_ratio": 0.30},                  # headline QPS
    "serving.qps": {"min_ratio": 0.30},
    "serving.baseline_one_per_call_qps": {"min_ratio": 0.30},
    "serving.p99_ms": {"max_ratio": 4.0, "max_increase": 50.0},
    "serving.requests_per_batch": {"min_ratio": 0.6},
    "serving.completed": {"min_ratio": 0.9},
    # steady state must not start recompiling: small absolute slack
    # covers the per-batch-size pad/concat micro-programs whose count
    # varies with thread-timing-dependent batch composition
    "serving.backend_compiles_during_load": {"max_increase": 25},
    "serving.modeled_exec_bytes": {"min_ratio": 0.5},
    "serving.modeled_exec_flops": {"min_ratio": 0.5},
    # ragged A/B leg (PR 9): same stream through the packed-batch
    # plan family. Structural columns are TIGHT — the whole point is
    # one executable, no recompiles, near-zero pad — while wall-clock
    # columns keep the wide CI-host bands.
    "serving.ragged.qps": {"min_ratio": 0.30},
    "serving.ragged.completed": {"min_ratio": 0.9},
    "serving.ragged.p99_ms": {"max_ratio": 4.0, "max_increase": 50.0},
    "serving.ragged.pad_waste_fraction": {"max_increase": 0.05},
    # the packed path has NO per-shape micro-programs (host-side
    # packing in, one batched fetch out), so its during-load compile
    # band is far tighter than the bucketed leg's
    "serving.ragged.backend_compiles_during_load": {"max_increase": 5},
    "serving.ragged.executables": {"max_increase": 0},
    "serving.pad_waste_fraction": {"max_increase": 0.15},
    # graftragged family legs (PR 15): PQ, BQ, and the 4-shard mesh
    # serve the SAME mixed-size stream through the unified ragged plan
    # family. Structural columns TIGHT per leg — at most the dual-tile
    # executable pair, a near-zero during-load compile band (the
    # packed path has no per-shape micro-programs; the small slack
    # covers one-time lazily-created planes), pad waste inside the
    # acceptance band — while wall-clock columns keep the wide
    # CI-host bands.
    "serving.ragged_families.pq.completed": {"min_ratio": 0.9},
    "serving.ragged_families.pq.qps": {"min_ratio": 0.30},
    "serving.ragged_families.pq.p99_ms": {"max_ratio": 4.0,
                                          "max_increase": 50.0},
    "serving.ragged_families.pq.pad_waste_fraction":
        {"max_increase": 0.05},
    "serving.ragged_families.pq.backend_compiles_during_load":
        {"max_increase": 5},
    "serving.ragged_families.pq.executables": {"max_increase": 0},
    "serving.ragged_families.bq.completed": {"min_ratio": 0.9},
    "serving.ragged_families.bq.qps": {"min_ratio": 0.30},
    "serving.ragged_families.bq.p99_ms": {"max_ratio": 4.0,
                                          "max_increase": 50.0},
    "serving.ragged_families.bq.pad_waste_fraction":
        {"max_increase": 0.05},
    "serving.ragged_families.bq.backend_compiles_during_load":
        {"max_increase": 5},
    "serving.ragged_families.bq.executables": {"max_increase": 0},
    "serving.ragged_families.mesh.completed": {"min_ratio": 0.9},
    "serving.ragged_families.mesh.qps": {"min_ratio": 0.30},
    "serving.ragged_families.mesh.p99_ms": {"max_ratio": 4.0,
                                            "max_increase": 50.0},
    "serving.ragged_families.mesh.pad_waste_fraction":
        {"max_increase": 0.05},
    "serving.ragged_families.mesh.backend_compiles_during_load":
        {"max_increase": 5},
    "serving.ragged_families.mesh.executables": {"max_increase": 0},
    "serving.ragged_families.mesh.shards": {"min_ratio": 1.0,
                                            "max_increase": 0},
    # RaBitQ IVF-BQ rider: the recall floor band (the fused exact
    # rerank must keep hitting the probe-set ceiling; the
    # deterministic pinned config makes these tight), the structural
    # codes-slot width, and the prune rule's deterministic signal —
    # survivor_row_fraction is a host-side replay of the engines' own
    # margin rule on the pinned seeds, so a margin/prune-math change
    # that starts re-ranking materially more rows moves it exactly
    # (block-level one_stream_fraction only separates at production
    # scale and is reported, not gated)
    "bq.fused_recall": {"min_ratio": 0.95},
    "bq.estimate_refine_recall": {"min_ratio": 0.90},
    "bq.bytes_per_vector_codes": {"max_increase": 0},
    "bq.survivor_row_fraction": {"max_increase": 0.05},
    "bq.fused_qps": {"min_ratio": 0.30},
    # graftbeam CAGRA rider (PR 16). Recall bands per arm (the pinned
    # seeds make recall deterministic on CPU; the ratio band absorbs
    # platform-precision wiggle); pool_shrink_factor is structural —
    # the coarse arm must keep serving from a pool >= 8x smaller;
    # compiles_during_measure pins the AOT steady state; raggable
    # pins the retired per-block dispatch exemption (the default
    # CAGRA plan must stay inside the ragged family). QPS keeps the
    # wide wall-clock band; modeled byte columns are reported, and
    # the BQ arm's byte reduction is banded loosely (the survivor
    # fraction moves it only through margin/prune-math changes).
    "cagra.pool.recall": {"min_ratio": 0.95},
    "cagra.coarse.recall": {"min_ratio": 0.95},
    "cagra.coarse_bq.recall": {"min_ratio": 0.95},
    "cagra.coarse.qps": {"min_ratio": 0.30},
    "cagra.coarse_bq.qps": {"min_ratio": 0.30},
    "cagra.pool_shrink_factor": {"min_ratio": 1.0, "max_increase": 0},
    "cagra.bq_byte_reduction": {"min_ratio": 0.9},
    "cagra.compiles_during_measure": {"max_increase": 0},
    "cagra.raggable": {"min_ratio": 1.0},
    "cagra.survivor_row_fraction": {"max_increase": 0.05},
    # graftfleet continuous-capture overhead A/B (PR 12): the same
    # bucketed stream with real profiler windows armed. The RATIO
    # band is the tight one — p99 with the duty cycle on may not
    # drift past baseline + 1.0x of the capture-free leg (absolute
    # p99 keeps the wide wall-clock band); capture_attempts proves
    # every gated run actually paid for profiler windows
    "serving.continuous.p99_ms": {"max_ratio": 4.0,
                                  "max_increase": 50.0},
    "serving.continuous.p99_ratio": {"max_increase": 1.0},
    # how many ticks fire inside the short load window is wall-clock
    # timing; the structural claim is "every gated run paid for AT
    # LEAST one real profiler window" (0.15 x the 6-attempt baseline
    # floors the integer count at 1)
    "serving.continuous.capture_attempts": {"min_ratio": 0.15},
    "serving.continuous.completed": {"min_ratio": 0.9},
    # grafttier tiered storage (PR 14). Structural columns TIGHT:
    # bit_identical is the correctness gate (tiered results must
    # equal the all-HBM index, pre and post placement epochs);
    # compiles_during_epochs pins the zero-recompile-across-
    # re-placement contract; cold_lists and the per-epoch swap bytes
    # are exact at the pinned config (pinned seeds → deterministic
    # coarse selection → deterministic plans). GB/s columns keep the
    # wide wall-clock bands.
    "tiered.bit_identical": {"min_ratio": 1.0},
    "tiered.compiles_during_epochs": {"max_increase": 0},
    "tiered.cold_lists": {"min_ratio": 1.0, "max_increase": 0},
    "tiered.swap_bytes_total": {"min_ratio": 1.0, "max_increase": 0},
    "tiered.qps": {"min_ratio": 0.30},
    "tiered.hot_gbps": {"min_ratio": 0.2},
    "tiered.cold_gbps": {"min_ratio": 0.2},
    # graftcast prefetch A/B (PR 18). Structural columns TIGHT:
    # reduces_cold_bytes is the acceptance criterion itself —
    # prefetch-on must STRICTLY beat the reactive leg's cold-stream
    # bytes on the identical seeded drift (both legs replay the same
    # traffic, so the promotions match and only staged hits separate
    # them); compiles_during_load pins "the prefetcher adds zero" (the
    # measured window runs after one warm drift cycle, like the epoch
    # warm above); hit_rate keeps a generous floor band (the forecast
    # is deterministic at the pinned seeds, the band absorbs plan-
    # policy tuning). p99 keeps the wide wall-clock band.
    "tiered.prefetch.reduces_cold_bytes": {"min_ratio": 1.0},
    "tiered.prefetch.on.compiles_during_load": {"max_increase": 0},
    "tiered.prefetch.hit_rate": {"min_ratio": 0.5},
    "tiered.prefetch.on.p99_ms": {"max_ratio": 4.0,
                                  "max_increase": 50.0},
    # graftwire multichip rider (this PR). Structural columns TIGHT:
    # the 2-D query×list grid must keep serving mixed batch sizes with
    # ZERO backend compiles after warmup+primer (the recompile hole
    # this PR closed — any regression reopens it); the modeled
    # per-EM-iteration wire bytes are exact at the pinned config, so
    # the int8 < bf16 < f32 ordering is encoded in the recorded
    # values with zero slack; the narrow-wire inertia ratios may not
    # drift past 2% of the f32 EM (the same tolerance the tier-1
    # convergence test pins). Wall-clock columns keep the wide bands.
    "multichip.grid2d.compiles_during_load": {"max_increase": 0},
    "multichip.grid2d.qps": {"min_ratio": 0.30},
    "multichip.kmeans_wire.cases.bf16.modeled_iter_wire_bytes":
        {"max_increase": 0},
    "multichip.kmeans_wire.cases.int8.modeled_iter_wire_bytes":
        {"max_increase": 0},
    "multichip.kmeans_wire.cases.bf16.inertia_vs_f32":
        {"max_increase": 0.02},
    "multichip.kmeans_wire.cases.int8.inertia_vs_f32":
        {"max_increase": 0.02},
    # graftroute fleet router (PR 20). Everything except wall clock
    # is deterministic in the device-free harness, so the structural
    # columns are EXACT: steered and f32-wire fan-out answers must
    # stay bit-identical to the solo oracle, the bf16-wire recall is
    # a fixed value >= the 0.99 floor at the pinned seed, the
    # modeled merge payloads follow route_payload_model with zero
    # slack (bf16 strictly under f32), and the planner's
    # replication/coverage split cannot drift at the pinned plane.
    # QPS columns are host-side routing overhead — wide bands.
    "fleet.steer.bit_identical": {"min_ratio": 1.0},
    "fleet.fanout_f32.bit_identical": {"min_ratio": 1.0},
    "fleet.fanout_bf16.recall": {"min_ratio": 0.99},
    "fleet.merge_bytes_f32": {"min_ratio": 1.0, "max_increase": 0},
    "fleet.merge_bytes_bf16": {"min_ratio": 1.0, "max_increase": 0},
    "fleet.wire_bytes_saved_frac": {"min_ratio": 1.0,
                                    "max_increase": 0},
    "fleet.replicated_lists": {"min_ratio": 1.0, "max_increase": 0},
    "fleet.coverage_rate": {"min_ratio": 1.0, "max_increase": 0},
    "fleet.fanout_fraction": {"min_ratio": 1.0, "max_increase": 0},
    "fleet.steer.qps": {"min_ratio": 0.30},
    "fleet.fanout_f32.qps": {"min_ratio": 0.30},
}

# counters the test session's metrics snapshot must carry ABOVE these
# values — the modeled-throughput accounting staying alive, and (PR 8)
# the graftgauge probe-frequency accounting: ``accounted`` mirrors the
# lifetime total fetched off the DEVICE counter planes, so a refactor
# that silently disconnects the scatter-add (or the scrape-side fetch)
# zeroes it and fails here structurally
SNAPSHOT_FLOORS = {
    "serving.execute.calls": 0.0,
    "serving.execute.modeled_bytes": 0.0,
    "serving.execute.modeled_flops": 0.0,
    "index.probe.dispatches": 0.0,
    "index.probe_freq.accounted": 0.0,
    # graftflight (PR 11): trace ingestion and incident capture must
    # stay alive — a refactor that silently disconnects the parser
    # pipeline or the flight-recorder triggers zeroes these
    "profiling.captures": 0.0,
    "incident.bundles": 0.0,
    # graftfleet (PR 12): the continuous-capture -> rolling-EWMA
    # pipeline and the multi-replica federation scrape loop must stay
    # alive the same way
    "profiling.rolling.folds": 0.0,
    "fleet.scrapes": 0.0,
    # graftledger (PR 13): the dispatch-time watermark sample must
    # stay wired into the executor — a refactor that disconnects
    # MemoryLedger.sample_dispatch() from the dispatch path zeroes
    # this and fails structurally
    "memory.samples": 0.0,
    # grafttier (PR 14): placement swaps must actually move blocks —
    # the tier-1 epoch suite promotes/demotes through apply_plan, so
    # a refactor that disconnects the swap executor (or its byte
    # accounting) zeroes the lifetime ledger and fails here
    "tier.swaps": 0.0,
    "tier.swap_bytes": 0.0,
    # graftroute (PR 20): the router must actually route and the
    # planner must actually plan in the tier-1 session — a refactor
    # that silently disconnects either (or their metric emission)
    # zeroes the lifetime ledger and fails structurally
    "fleet.route.requests": 0.0,
    "fleet.plan.builds": 0.0,
}


def get_path(record: dict, dotted: str):
    """Resolve ``"serving.qps"``-style paths; None when absent."""
    cur = record
    for part in dotted.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur


def compare(baseline: dict, fresh: dict, tolerances=None) -> list:
    """Regression messages from diffing two bench records (empty list
    = within bands). Columns missing from the BASELINE are skipped (an
    old baseline predating a new column must not fail the gate);
    columns missing from the FRESH record are regressions — the
    measurement itself disappeared."""
    msgs = []
    for path, tol in (tolerances or DEFAULT_TOLERANCES).items():
        base = get_path(baseline, path)
        if base is None:
            continue
        got = get_path(fresh, path)
        if got is None:
            msgs.append(f"{path}: present in baseline ({base}) but "
                        "missing from the fresh record")
            continue
        base, got = float(base), float(got)
        if "min_ratio" in tol and got < base * tol["min_ratio"]:
            msgs.append(
                f"{path}: {got:g} < {tol['min_ratio']:g}x baseline "
                f"({base:g}) — throughput regression")
        ceiling = None
        if "max_ratio" in tol and base > 0:
            ceiling = base * tol["max_ratio"]
        if "max_increase" in tol:
            inc = base + tol["max_increase"]
            ceiling = inc if ceiling is None else max(ceiling, inc)
        if ceiling is not None and got > ceiling:
            msgs.append(
                f"{path}: {got:g} > allowed {ceiling:g} "
                f"(baseline {base:g}) — latency/compile regression")
    return msgs


def check_snapshot(snapshot: dict, floors=None) -> list:
    """Floor checks on the test session's metrics snapshot: the
    modeled-throughput counters must exist and exceed their floors.
    Reads the session-lifetime ledger (``counters_lifetime`` — totals
    that survive per-test ``reset_counters()`` isolation) when the
    snapshot carries one; the live ``counters`` view only holds what
    ran after the LAST reset, which depends on test ordering."""
    msgs = []
    counters = snapshot.get("counters_lifetime") or \
        snapshot.get("counters", {})
    for name, floor in (floors or SNAPSHOT_FLOORS).items():
        v = counters.get(name)
        if v is None:
            msgs.append(f"metrics snapshot: counter {name!r} missing — "
                        "modeled-throughput accounting went dark")
        elif float(v) <= floor:
            msgs.append(f"metrics snapshot: {name} = {v} (must be > "
                        f"{floor}) — modeled-throughput accounting "
                        "went dark")
    return msgs


def run_bench(env_overrides: dict) -> dict:
    """Run the bench CHILD directly (no backend probes — the pinned
    config is CPU) and return its last JSON stdout line."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)   # CPU child must not touch
    env.pop("BENCH_TAG", None)              # the relay plugin / naming
    env.pop("BENCH_SUFFIX", None)
    env.update(env_overrides)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, env=env, timeout=1800)
    rec = None
    for line in proc.stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
    if rec is None:
        sys.stderr.write(proc.stderr[-4000:] + "\n")
        raise RuntimeError(
            f"bench child produced no JSON (exit {proc.returncode})")
    return rec


def backend_available(required: str) -> bool:
    """Whether the current jax backend matches a baseline's
    ``requires_backend`` declaration. Imported lazily — the common
    CPU-only gate never pays the jax import."""
    try:
        import jax

        return jax.default_backend() == required
    except Exception:                        # pragma: no cover
        return False


def default_baselines() -> list:
    """Every committed ``ci/bench_baseline*.json``, sorted — the
    multi-baseline default, so a TPU-recorded baseline gates
    automatically once committed. Falls back to the canonical path
    (for the --update bootstrap) when none exist yet."""
    found = sorted(_glob.glob(
        os.path.join(REPO, "ci", "bench_baseline*.json")))
    return found or [BASELINE_PATH]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--baseline", action="append",
                    help="baseline JSON to gate against (repeatable; "
                    "default: every ci/bench_baseline*.json)")
    ap.add_argument("--fresh", help="existing bench-record JSON to "
                    "diff instead of running the bench")
    ap.add_argument("--run", action="store_true",
                    help="run each baseline's pinned bench config to "
                    "get the fresh record")
    ap.add_argument("--snapshot", help="metrics_snapshot.json to "
                    "floor-check (skipped silently if the file is "
                    "absent — local runs without the pytest artifact)")
    ap.add_argument("--update", action="store_true",
                    help="write the fresh record back as the baseline "
                    "(deliberate re-baseline) instead of comparing")
    args = ap.parse_args(argv)

    paths = args.baseline or default_baselines()
    if args.update and len(paths) != 1:
        sys.stderr.write(
            "bench_compare: --update needs exactly ONE --baseline "
            f"target, got {len(paths)}\n")
        return 2
    if not (args.fresh or args.run or args.update):
        sys.stderr.write("bench_compare: need --run or --fresh\n")
        return 2

    fresh_fixed = None
    if args.fresh:
        with open(args.fresh) as f:
            fresh_fixed = json.load(f)

    msgs = []
    gated = 0
    failing_paths = []
    run_cache: dict = {}       # env (sorted tuple) -> bench record
    for path in paths:
        baseline = None
        if os.path.exists(path):
            with open(path) as f:
                baseline = json.load(f)
        if baseline is None and not args.update:
            sys.stderr.write(
                f"bench_compare: no baseline at {path} — run with "
                "--update to create one\n")
            return 2
        required = (baseline or {}).get("requires_backend")
        if required and not backend_available(required):
            print(f"bench_compare: SKIP {os.path.basename(path)} — "
                  f"requires backend {required!r}, not present")
            continue

        # gating replays the baseline's pinned env (baseline and fresh
        # always measure the same problem); a deliberate --update
        # re-baselines onto the CURRENT pinned config, so pinned-env
        # changes land together with the record they produced
        env = (dict(PINNED_ENV) if args.update
               else dict((baseline or {}).get("env") or PINNED_ENV))
        if fresh_fixed is not None:
            fresh = fresh_fixed
        else:
            key = tuple(sorted(env.items()))
            if key not in run_cache:
                print(f"bench_compare: running pinned bench config "
                      f"({env.get('BENCH_N')}x{env.get('BENCH_DIM')}, "
                      f"serving rider on)", flush=True)
                run_cache[key] = run_bench(env)
            fresh = run_cache[key]

        if args.update:
            out = {
                "env": env,
                "tolerances": DEFAULT_TOLERANCES,
                "snapshot_floors": SNAPSHOT_FLOORS,
                "record": fresh,
            }
            if required:
                out["requires_backend"] = required
            with open(path, "w") as f:
                json.dump(out, f, indent=2, sort_keys=True)
                f.write("\n")
            print(f"bench_compare: baseline updated at {path}")
            return 0

        gated += 1
        path_msgs = compare(
            baseline.get("record", {}), fresh,
            baseline.get("tolerances") or DEFAULT_TOLERANCES)
        if args.snapshot and os.path.exists(args.snapshot):
            with open(args.snapshot) as f:
                path_msgs += check_snapshot(
                    json.load(f),
                    baseline.get("snapshot_floors") or SNAPSHOT_FLOORS)
        if path_msgs:
            failing_paths.append(path)
        msgs += [f"[{os.path.basename(path)}] {m}" for m in path_msgs]

    if msgs:
        for m in msgs:
            sys.stderr.write(f"bench_compare: REGRESSION: {m}\n")
        # --update takes exactly one target, so the hint names each
        # failing baseline explicitly
        for p in failing_paths:
            rel = os.path.relpath(p, REPO) if p.startswith(REPO) else p
            sys.stderr.write(
                "bench_compare: if the change is intentional, "
                "re-baseline with: python ci/bench_compare.py --run "
                f"--update --baseline {rel}\n")
        return 1
    print(f"bench_compare: OK — fresh run within tolerance of "
          f"{gated} baseline(s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
