"""CAGRA ⇄ hnswlib interop example — the index-interop story of the
reference's ``serialize_to_hnswlib`` (post-v23.10 cagra_serialize):
build a CAGRA graph on TPU, export it to hnswlib's native file format
(loadable by stock ``hnswlib.Index.load_index`` on any CPU box), then
import it back and search with the TPU beam engine.

Run:  PYTHONPATH=.. python hnsw_interop_example.py
"""

import os
import tempfile

import numpy as np
import scipy.spatial.distance as spd

from raft_tpu.neighbors import cagra, hnsw
from raft_tpu.utils import eval_recall

N, DIM, N_QUERIES, K = 20_000, 128, 100, 10


def main():
    rng = np.random.default_rng(0)
    centers = rng.standard_normal((32, DIM)) * 4
    x = (centers[rng.integers(0, 32, N)]
         + rng.standard_normal((N, DIM))).astype(np.float32)
    q = (centers[rng.integers(0, 32, N_QUERIES)]
         + rng.standard_normal((N_QUERIES, DIM))).astype(np.float32)
    gt = np.argsort(spd.cdist(q, x, "sqeuclidean"), 1)[:, :K]

    params = cagra.CagraIndexParams(
        graph_degree=32, intermediate_graph_degree=64,
        build_algo=cagra.BuildAlgo.NN_DESCENT)
    index = cagra.build(None, params, x)

    path = os.path.join(tempfile.mkdtemp(), "cagra.hnsw")
    hnsw.save_hnswlib(None, index, path)
    print(f"exported {path} ({os.path.getsize(path) / 1e6:.1f} MB) — "
          "load with hnswlib.Index(space='l2', dim="
          f"{DIM}).load_index(path)")

    # the reverse bridge: any level-0-complete hnswlib file becomes a
    # TPU-searchable CagraIndex
    loaded = hnsw.load_hnswlib(None, path, DIM)
    sp = cagra.CagraSearchParams(itopk_size=64, search_width=4)
    _, ids = cagra.search(None, sp, loaded, q, K)
    r, _, _ = eval_recall(gt, np.asarray(ids))
    print(f"recall@{K} after round-trip: {r:.3f}")
    assert r >= 0.9

    try:
        import hnswlib

        h = hnswlib.Index(space="l2", dim=DIM)
        h.load_index(path)
        h.set_ef(64)
        ids_h, _ = h.knn_query(q, k=K)
        rh, _, _ = eval_recall(gt, ids_h)
        print(f"hnswlib-native search recall@{K}: {rh:.3f}")
    except ImportError:
        print("(hnswlib not installed here — file verified via the "
              "round-trip parser instead)")


if __name__ == "__main__":
    main()
