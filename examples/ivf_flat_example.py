"""IVF-Flat end-to-end example — analog of the reference template project's
``cpp/template/src/ivf_flat_example.cu``: generate data, build an index,
search, filter, and round-trip through serialization.

Run:  PYTHONPATH=.. python ivf_flat_example.py
"""

import numpy as np

from raft_tpu import Resources
from raft_tpu.core.bitset import Bitset
from raft_tpu.neighbors import ivf_flat

N, DIM, N_QUERIES, K = 50_000, 64, 100, 10


def main():
    res = Resources(seed=0)
    rng = np.random.default_rng(0)
    dataset = rng.standard_normal((N, DIM)).astype(np.float32)
    queries = rng.standard_normal((N_QUERIES, DIM)).astype(np.float32)

    # build — trains a balanced-kmeans coarse quantizer and packs lists
    params = ivf_flat.IvfFlatIndexParams(n_lists=256)
    index = ivf_flat.build(res, params, dataset)
    print(f"built IVF-Flat index: {index.size} vectors, "
          f"{index.n_lists} lists")

    # search
    sp = ivf_flat.IvfFlatSearchParams(n_probes=32)
    dist, idx = ivf_flat.search(res, sp, index, queries, K)
    print("first query neighbors:", np.asarray(idx[0]))

    # filtered search: exclude the first half of the dataset
    mask = np.ones(N, bool)
    mask[: N // 2] = False
    dist_f, idx_f = ivf_flat.search(res, sp, index, queries, K,
                                    sample_filter=Bitset.from_mask(mask))
    assert (np.asarray(idx_f)[np.asarray(idx_f) >= 0] >= N // 2).all()
    print("filtered search ok")

    # serialize / deserialize
    import tempfile

    path = tempfile.mktemp(suffix=".idx")
    ivf_flat.save(index, path)
    index2 = ivf_flat.load(res, path)
    d2, i2 = ivf_flat.search(res, sp, index2, queries, K)
    assert np.array_equal(np.asarray(idx), np.asarray(i2))
    print("serialization round-trip ok")


if __name__ == "__main__":
    main()
