"""CAGRA end-to-end example — analog of the reference template project's
``cpp/template/src/cagra_example.cu``: build the graph index two ways
(IVF-PQ batches vs NN-descent), search, and measure recall.

Run:  PYTHONPATH=.. python cagra_example.py
"""

import numpy as np
import scipy.spatial.distance as spd

from raft_tpu import Resources
from raft_tpu.neighbors import cagra
from raft_tpu.utils import eval_recall

N, DIM, N_QUERIES, K = 20_000, 64, 100, 10


def main():
    res = Resources(seed=0)
    rng = np.random.default_rng(0)
    centers = rng.standard_normal((64, DIM)).astype(np.float32) * 4
    dataset = (centers[rng.integers(0, 64, N)]
               + rng.standard_normal((N, DIM))).astype(np.float32)
    queries = (centers[rng.integers(0, 64, N_QUERIES)]
               + rng.standard_normal((N_QUERIES, DIM))).astype(np.float32)

    gt = np.argsort(spd.cdist(queries, dataset, "sqeuclidean"),
                    axis=1, kind="stable")[:, :K]

    for algo in (cagra.BuildAlgo.NN_DESCENT, cagra.BuildAlgo.IVF_PQ):
        params = cagra.CagraIndexParams(
            graph_degree=32, intermediate_graph_degree=64, build_algo=algo)
        index = cagra.build(res, params, dataset)
        # search_width widens both the per-iteration expansion and the
        # random seed pool — the lever that matters on clustered data
        sp = cagra.CagraSearchParams(itopk_size=64, search_width=4)
        dist, idx = cagra.search(res, sp, index, queries, K)
        recall, _, _ = eval_recall(gt, np.asarray(idx))
        print(f"cagra[{algo.value}] recall@{K} = {recall:.3f}")


if __name__ == "__main__":
    main()
