"""IVF-BQ walkthrough — the 1-bit sign-quantized index (TPU-first, no
reference analog; RaBitQ-style quantizer): probe scoring is a single
MXU GEMM against the ±1 code matrix, the deepest compression in the
library (D bits + 8 scalar bytes per vector), recovered to high recall
by exact re-ranking.

Run:  PYTHONPATH=.. python ivf_bq_example.py
"""

import numpy as np
import scipy.spatial.distance as spd

from raft_tpu import Resources
from raft_tpu.neighbors import ivf_bq, refine
from raft_tpu.utils import eval_recall

N, DIM, N_QUERIES, K = 50_000, 96, 100, 10


def main():
    res = Resources(seed=0)
    rng = np.random.default_rng(0)
    centers = rng.standard_normal((64, DIM)) * 4
    dataset = (centers[rng.integers(0, 64, N)]
               + rng.standard_normal((N, DIM))).astype(np.float32)
    queries = (centers[rng.integers(0, 64, N_QUERIES)]
               + rng.standard_normal((N_QUERIES, DIM))).astype(np.float32)
    gt = np.argsort(spd.cdist(queries, dataset, "sqeuclidean"),
                    axis=1, kind="stable")[:, :K]

    index = ivf_bq.build(res, ivf_bq.IvfBqIndexParams(n_lists=256, bits=2),
                         dataset)
    code_bytes = index.codes.shape[2] + 4 * (index.bits + 1)
    print(f"compression ratio ≈ {DIM * 4 / code_bytes:.1f}x "
          f"({code_bytes} B/vector)")

    sp = ivf_bq.IvfBqSearchParams(n_probes=64)

    # raw sign-code estimates: coarse by design
    _, idx_raw = ivf_bq.search(res, sp, index, queries, K)
    r_raw, _, _ = eval_recall(gt, np.asarray(idx_raw))

    # over-fetch 5x, exact re-rank — the intended usage
    _, cand = ivf_bq.search(res, sp, index, queries, 5 * K)
    _, idx_ref = refine(res, dataset, queries, cand, K)
    r_ref, _, _ = eval_recall(gt, np.asarray(idx_ref))

    print(f"recall@{K}: raw {index.bits}-bit {r_raw:.3f} -> refined {r_ref:.3f}")


if __name__ == "__main__":
    main()
