"""Question-retrieval vector search demo — analog of the reference's
``notebooks/VectorSearch_QuestionRetrieval.ipynb``: embed a question
corpus, build an ANN index, and serve nearest-question lookups.

The reference notebook downloads sentence embeddings; this environment
is air-gapped, so questions are embedded with hashed character-n-gram
features (a deterministic stand-in with the same API shape — swap
``embed`` for a real encoder in production).

Run:  PYTHONPATH=.. python question_retrieval_demo.py
"""

import hashlib

import numpy as np

from raft_tpu import Resources
from raft_tpu.distance.types import DistanceType
from raft_tpu.neighbors import ivf_flat

DIM = 256

CORPUS = [
    "how do I transpose a matrix in numpy",
    "what is the capital of france",
    "best way to reverse a list in python",
    "how to normalize rows of a matrix",
    "what time zone is tokyo in",
    "difference between list and tuple in python",
    "how do I compute eigenvalues of a symmetric matrix",
    "what is the population of paris",
    "fastest way to sort a large array",
    "how to slice the last column of a 2d array",
    "currency used in japan",
    "how to concatenate two numpy arrays",
    "what language is spoken in brazil",
    "compute the inverse of a matrix numpy",
    "append an element to a python list",
    "distance between paris and london",
]

QUERIES = [
    "transpose numpy matrix",
    "capital city of france",
    "reverse python list",
]


def embed(texts, dim: int = DIM) -> np.ndarray:
    """Hashed character-trigram embedding, L2-normalized."""
    out = np.zeros((len(texts), dim), np.float32)
    for i, t in enumerate(texts):
        t = f"  {t.lower()}  "
        for j in range(len(t) - 2):
            g = t[j : j + 3].encode()
            h = int.from_bytes(hashlib.blake2b(g, digest_size=4).digest(),
                               "little")
            out[i, h % dim] += 1.0 if (h >> 31) & 1 else -1.0
    norms = np.linalg.norm(out, axis=1, keepdims=True)
    return out / np.maximum(norms, 1e-12)


def main():
    res = Resources(seed=0)
    corpus_vecs = embed(CORPUS)
    # cosine on unit vectors == inner product
    index = ivf_flat.build(
        res,
        ivf_flat.IvfFlatIndexParams(n_lists=4,
                                    metric=DistanceType.InnerProduct),
        corpus_vecs,
    )
    sims, ids = ivf_flat.search(
        res, ivf_flat.IvfFlatSearchParams(n_probes=4), index,
        embed(QUERIES), k=3)
    for q, row_ids, row_sims in zip(QUERIES, np.asarray(ids),
                                    np.asarray(sims)):
        print(f"Q: {q}")
        for rid, s in zip(row_ids, row_sims):
            print(f"   {s:5.2f}  {CORPUS[rid]}")
    # the top hit for each query is the intended match
    assert CORPUS[np.asarray(ids)[0, 0]].startswith("how do I transpose")
    assert CORPUS[np.asarray(ids)[1, 0]].startswith("what is the capital")
    assert CORPUS[np.asarray(ids)[2, 0]].startswith("best way to reverse")
    print("retrieval demo OK")


if __name__ == "__main__":
    main()
