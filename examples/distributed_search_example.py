"""Multi-chip sharded build + search — the raft-dask MNMG analog
(``python/raft-dask/raft_dask/common/comms.py``), expressed TPU-natively:
a jax.sharding Mesh, shard_map collectives, per-shard indexes, and a
global all-gather top-k merge.

Runs on any device count; to simulate a pod on CPU:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      PYTHONPATH=.. python distributed_search_example.py
"""

import jax
import numpy as np

from raft_tpu.comms import Comms
from raft_tpu.comms.bootstrap import make_mesh
from raft_tpu.distributed import brute_force_knn, kmeans_fit

N_PER_DEV, DIM, N_QUERIES, K = 25_000, 64, 32, 10


def main():
    devices = jax.devices()
    comms = Comms(make_mesh(devices=devices), "data")
    n = N_PER_DEV * len(devices)
    print(f"mesh: {len(devices)} × {devices[0].platform}")

    rng = np.random.default_rng(0)
    dataset = rng.standard_normal((n, DIM)).astype(np.float32)
    queries = rng.standard_normal((N_QUERIES, DIM)).astype(np.float32)

    # distributed balanced-kmeans: per-shard E-step, psum'd center update
    centers, inertia = kmeans_fit(comms, dataset, n_clusters=64, n_iters=5)
    print(f"distributed kmeans inertia = {float(inertia):.1f}")

    # sharded exact search: per-shard top-k, all-gather merge
    dist, idx = brute_force_knn(comms, dataset, queries, K)

    # verify against a single-process reference
    d2 = ((queries[:, None, :] - dataset[None, :, :]) ** 2).sum(-1)
    gt = np.argsort(d2, axis=1, kind="stable")[:, :K]
    assert np.array_equal(np.asarray(idx), gt)
    print("distributed search matches exact ground truth")

    # SPMD list-sharded IVF: ONE logical index sharded over the mesh,
    # searched by a single jitted program (capacity scales with chips)
    from raft_tpu.distributed import ivf as dist_ivf
    from raft_tpu.neighbors.ivf_flat import (
        IvfFlatIndexParams,
        IvfFlatSearchParams,
    )
    from raft_tpu.utils import eval_recall

    index = dist_ivf.build(None, comms, IvfFlatIndexParams(n_lists=128),
                           dataset)
    _, ids = dist_ivf.search(None, IvfFlatSearchParams(n_probes=64),
                             index, queries, K)
    recall, _, _ = eval_recall(gt, np.asarray(ids))
    print(f"sharded IVF recall@{K} = {recall:.3f}")


if __name__ == "__main__":
    main()
