"""IVF-PQ + refinement walkthrough — analog of the reference's
``notebooks/VectorSearch_QuestionRetrieval.ipynb`` / ivf_pq tutorial:
compressed-index search, then exact re-ranking to recover recall.

Run:  PYTHONPATH=.. python ivf_pq_refine_example.py
"""

import numpy as np
import scipy.spatial.distance as spd

from raft_tpu import Resources
from raft_tpu.neighbors import ivf_pq, refine
from raft_tpu.utils import eval_recall

N, DIM, N_QUERIES, K = 50_000, 96, 100, 10


def main():
    res = Resources(seed=0)
    rng = np.random.default_rng(0)
    dataset = rng.standard_normal((N, DIM)).astype(np.float32)
    queries = rng.standard_normal((N_QUERIES, DIM)).astype(np.float32)
    gt = np.argsort(spd.cdist(queries, dataset, "sqeuclidean"),
                    axis=1, kind="stable")[:, :K]

    params = ivf_pq.IvfPqIndexParams(n_lists=256, pq_dim=48, pq_bits=8)
    index = ivf_pq.build(res, params, dataset)
    print(f"compression ratio ≈ "
          f"{DIM * 4 / (params.pq_dim * params.pq_bits / 8):.1f}x")

    sp = ivf_pq.IvfPqSearchParams(n_probes=64)

    # plain PQ search: approximate distances
    _, idx_pq = ivf_pq.search(res, sp, index, queries, K)
    r_pq, _, _ = eval_recall(gt, np.asarray(idx_pq))

    # over-fetch 4x candidates, then re-rank with exact distances
    _, cand = ivf_pq.search(res, sp, index, queries, 4 * K)
    _, idx_ref = refine(res, dataset, queries, cand, K)
    r_ref, _, _ = eval_recall(gt, np.asarray(idx_ref))

    print(f"recall@{K}: pq-only {r_pq:.3f}  →  refined {r_ref:.3f}")


if __name__ == "__main__":
    main()
