"""Sparse primitives — analog of ``raft/sparse/`` (SURVEY.md §2.3):
COO/CSR containers, conversions, structure ops, linalg (spmm/norm/
symmetrize/transpose/add/laplacian), pairwise distances, sparse kNN +
kNN-graph construction + cross-component NN, Borůvka MST and Lanczos.
"""

from raft_tpu.sparse import convert
from raft_tpu.sparse import distance
from raft_tpu.sparse import linalg
from raft_tpu.sparse import neighbors
from raft_tpu.sparse import ops
from raft_tpu.sparse import solver
from raft_tpu.sparse.types import COO, CSR

__all__ = [
    "COO",
    "CSR",
    "convert",
    "distance",
    "linalg",
    "neighbors",
    "ops",
    "solver",
]
