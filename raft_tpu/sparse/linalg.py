"""Sparse linear algebra — analog of ``raft/sparse/linalg/``
(``spmm.cuh``, ``norm.cuh``, ``add.cuh``, ``symmetrize.cuh``,
``transpose.cuh``).

The reference routes through cuSPARSE; the TPU-native forms are
gather + multiply + ``segment_sum`` (rides the VPU, fuses under jit) —
raggedness never reaches XLA because nnz capacities are static.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from raft_tpu.sparse.convert import coo_to_csr, csr_to_coo
from raft_tpu.sparse.ops import coo_sort, sum_duplicates
from raft_tpu.sparse.types import COO, CSR


def spmm(csr: CSR, dense, transpose_output: bool = False) -> jax.Array:
    """CSR × dense GEMM (``linalg::spmm``): out[m, k] = A @ B for
    B (n, k). Gather B rows per entry, scale, segment-sum by row."""
    dense = jnp.asarray(dense)
    r = csr.row_ids()
    valid = r >= 0
    gathered = jnp.take(dense, jnp.where(valid, csr.indices, 0), axis=0)
    contrib = gathered * jnp.where(valid, csr.data, 0)[:, None]
    out = jax.ops.segment_sum(contrib, jnp.clip(r, 0),
                              num_segments=csr.shape[0])
    return out.T if transpose_output else out


def spgemm(a: CSR, b: CSR) -> CSR:
    """Sparse × sparse → sparse (``sparse/linalg`` spgemm via cuSPARSE in
    the reference). TPU-native form: densify the right operand and ride
    the MXU, then re-sparsify — the product's structure is data-dependent
    (dynamic nnz), which XLA cannot express natively, and at the graph
    sizes this stack serves the dense intermediate is the fast path."""
    from raft_tpu.sparse.convert import csr_to_dense, dense_to_csr

    out = spmm(a, csr_to_dense(b))
    return dense_to_csr(out)


def spmv(csr: CSR, vec) -> jax.Array:
    """CSR × vector."""
    return spmm(csr, jnp.asarray(vec)[:, None])[:, 0]


def row_norm_csr(csr: CSR, norm_type: str = "l2") -> jax.Array:
    """``linalg::rowNormCsr``: per-row L1/L2/Linf norms."""
    r = csr.row_ids()
    valid = r >= 0
    v = jnp.where(valid, csr.data, 0)
    seg = jnp.clip(r, 0)
    m = csr.shape[0]
    if norm_type == "l1":
        return jax.ops.segment_sum(jnp.abs(v), seg, num_segments=m)
    if norm_type == "l2":
        return jax.ops.segment_sum(jnp.square(v), seg, num_segments=m)
    if norm_type == "linf":
        return jax.ops.segment_max(jnp.where(valid, jnp.abs(csr.data), 0),
                                   seg, num_segments=m)
    raise ValueError(f"unknown norm {norm_type!r}")


def csr_row_normalize(csr: CSR, norm_type: str = "l1") -> CSR:
    """``linalg::csr_row_normalize_l1`` / ``_max``."""
    norms = row_norm_csr(csr, norm_type)
    if norm_type == "l2":
        norms = jnp.sqrt(norms)
    r = csr.row_ids()
    denom = jnp.take(norms, jnp.clip(r, 0))
    data = jnp.where((r >= 0) & (denom > 0), csr.data / denom, 0)
    return CSR(csr.indptr, csr.indices, data, csr.shape)


def transpose(csr: CSR) -> CSR:
    """``linalg::transpose`` (cuSPARSE csr2csc in the reference): swap
    coordinates and re-sort."""
    coo = csr_to_coo(csr)
    valid = coo.rows >= 0
    t = COO(jnp.where(valid, coo.cols, -1),
            jnp.where(valid, coo.rows, 0), coo.vals,
            (csr.shape[1], csr.shape[0]))
    return coo_to_csr(coo_sort(t))


def add(a: CSR, b: CSR) -> CSR:
    """``linalg::csr_add_calc_inds``/``csr_add_finalize``: A + B with
    duplicate-coordinate summation; capacity = nnz_a + nnz_b."""
    assert a.shape == b.shape, "shape mismatch"
    ca, cb = csr_to_coo(a), csr_to_coo(b)
    merged = COO(
        jnp.concatenate([ca.rows, cb.rows]),
        jnp.concatenate([ca.cols, cb.cols]),
        jnp.concatenate([ca.vals, cb.vals]),
        a.shape,
    )
    return coo_to_csr(sum_duplicates(merged))


def coo_symmetrize(coo: COO, op=None) -> COO:
    """``linalg::coo_symmetrize``: out = op(A, A^T) with duplicate
    merging; default op sums (then the caller typically halves), matching
    the reference's edge-mean symmetrization of kNN graphs."""
    valid = coo.rows >= 0
    t_rows = jnp.where(valid, coo.cols, -1)
    t_cols = jnp.where(valid, coo.rows, 0)
    both = COO(
        jnp.concatenate([coo.rows, t_rows]),
        jnp.concatenate([coo.cols, t_cols]),
        jnp.concatenate([coo.vals, coo.vals]),
        coo.shape,
    )
    merged = sum_duplicates(both)
    if op is not None:
        merged = COO(merged.rows, merged.cols, op(merged.vals), merged.shape)
    return merged


def laplacian(csr: CSR, normalized: bool = True) -> CSR:
    """Graph Laplacian L = D - A (or normalized I - D^-1/2 A D^-1/2) —
    the operator ``linalg/spectral.cuh`` feeds to Lanczos."""
    m = csr.shape[0]
    deg = row_norm_csr(csr, "l1")
    r = csr.row_ids()
    valid = r >= 0
    if normalized:
        dinv = jnp.where(deg > 0, 1.0 / jnp.sqrt(deg), 0)
        off = -csr.data * jnp.take(dinv, jnp.clip(r, 0)) \
            * jnp.take(dinv, jnp.clip(csr.indices, 0, m - 1))
        diag_val = jnp.ones((m,), csr.data.dtype)
    else:
        off = -csr.data
        diag_val = deg
    off = jnp.where(valid, off, 0)
    coo = COO(jnp.where(valid, r, -1), csr.indices, off, csr.shape)
    diag = COO(jnp.arange(m, dtype=jnp.int32), jnp.arange(m, dtype=jnp.int32),
               diag_val, csr.shape)
    merged = sum_duplicates(COO(
        jnp.concatenate([coo.rows, diag.rows]),
        jnp.concatenate([coo.cols, diag.cols]),
        jnp.concatenate([coo.vals, diag.vals]),
        csr.shape,
    ))
    return coo_to_csr(merged)
