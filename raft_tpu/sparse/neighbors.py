"""Sparse neighbors — analog of ``raft/sparse/neighbors/``
(``brute_force.cuh`` tiled sparse kNN, ``knn_graph.cuh`` graph
construction, ``cross_component_nn.cuh`` MST-component connection).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core import tracing
from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.distance.pairwise import _pairwise_distance_impl
from raft_tpu.distance.types import DistanceType, is_min_close
from raft_tpu.matrix.select_k import merge_topk
from raft_tpu.sparse.ops import row_slice
from raft_tpu.sparse.types import COO, CSR


def brute_force_knn(
    res: Optional[Resources],
    database: CSR,
    queries: CSR,
    k: int,
    metric: DistanceType = DistanceType.L2Expanded,
    metric_arg: float = 2.0,
    tile: int = 2048,
) -> Tuple[jax.Array, jax.Array]:
    """Exact kNN between sparse row sets (``sparse::neighbors::
    brute_force_knn``): tiled densify + dense distance + running top-k
    merge (the reference's batcher, ``detail/knn.cuh``)."""
    ensure_resources(res)
    assert database.shape[1] == queries.shape[1], "column dims must match"
    n = database.shape[0]
    q = queries.shape[0]
    select_min = is_min_close(metric)
    pad_val = jnp.inf if select_min else -jnp.inf
    qd = queries.to_dense()

    with tracing.range("raft_tpu.sparse.brute_force_knn"):
        best_d = jnp.full((q, k), pad_val, jnp.float32)
        best_i = jnp.full((q, k), -1, jnp.int32)
        for start in range(0, n, tile):
            stop = min(start + tile, n)
            bd = row_slice(database, start, stop).to_dense()
            dist = _pairwise_distance_impl(qd, bd, metric, metric_arg,
                                           "highest")
            kk = min(k, stop - start)
            if select_min:
                td, ti = jax.lax.top_k(-dist, kk)
                td = -td
            else:
                td, ti = jax.lax.top_k(dist, kk)
            best_d, best_i = merge_topk(best_d, best_i, td,
                                        (ti + start).astype(jnp.int32),
                                        k, select_min)
        return best_d, best_i


def knn_graph(
    res: Optional[Resources],
    x,
    k: int,
    metric: DistanceType = DistanceType.L2Expanded,
) -> COO:
    """Symmetric k-NN graph over dense rows → COO adjacency
    (``sparse::neighbors::knn_graph``; consumed by single-linkage).
    Self-edges are excluded; edges carry distances."""
    from raft_tpu.neighbors import brute_force  # local: avoid import cycle

    res = ensure_resources(res)
    x = jnp.asarray(x)
    n = x.shape[0]
    with tracing.range("raft_tpu.sparse.knn_graph"):
        d, i = brute_force.knn(res, x, x, k + 1, metric)
        rows2d = jnp.arange(n, dtype=jnp.int32)[:, None]
        # keep the first k non-self hits per row: with duplicate points the
        # self-match may be displaced out of the top-(k+1), so dropping
        # self-edges alone would leave k+1 edges on some rows
        nonself = (i != rows2d) & (i >= 0)
        rank = jnp.cumsum(nonself, axis=1)
        keep = (nonself & (rank <= k)).reshape(-1)
        rows = jnp.repeat(jnp.arange(n, dtype=jnp.int32), k + 1)
        cols = i.reshape(-1)
        vals = d.reshape(-1).astype(jnp.float32)
        return COO(jnp.where(keep, rows, -1),
                   jnp.where(keep, cols, 0),
                   jnp.where(keep, vals, 0), (n, n))


def cross_component_nn(
    res: Optional[Resources],
    x,
    labels,
    metric: DistanceType = DistanceType.L2Expanded,
    tile: int = 1024,
) -> COO:
    """Nearest neighbor in a *different* component per component —
    ``sparse::neighbors::cross_component_nn`` (connects MST forests in
    single-linkage). Returns COO edges (one per component: min outgoing).
    """
    res = ensure_resources(res)
    x = jnp.asarray(x)
    labels = jnp.asarray(labels, jnp.int32)
    n = x.shape[0]
    n_comp = int(jnp.max(labels)) + 1

    with tracing.range("raft_tpu.sparse.cross_component_nn"):
        best_d = jnp.full((n,), jnp.inf, jnp.float32)
        best_j = jnp.zeros((n,), jnp.int32)
        for start in range(0, n, tile):
            stop = min(start + tile, n)
            dist = _pairwise_distance_impl(x, x[start:stop], metric, 2.0,
                                           "highest")          # (n, t)
            same = labels[:, None] == labels[None, start:stop]
            dist = jnp.where(same, jnp.inf, dist)
            td = jnp.min(dist, axis=1)
            tj = jnp.argmin(dist, axis=1).astype(jnp.int32) + start
            upd = td < best_d
            best_d = jnp.where(upd, td, best_d)
            best_j = jnp.where(upd, tj, best_j)
        # reduce per component: min outgoing edge
        comp_min = jax.ops.segment_min(best_d, labels, num_segments=n_comp)
        is_min = best_d == jnp.take(comp_min, labels)
        # first vertex achieving the min per component
        first = jax.ops.segment_min(
            jnp.where(is_min, jnp.arange(n), n), labels, num_segments=n_comp)
        src = jnp.clip(first, 0, n - 1).astype(jnp.int32)
        dst = jnp.take(best_j, src)
        w = jnp.take(best_d, src)
        valid = (first < n) & jnp.isfinite(w)
        return COO(jnp.where(valid, src, -1), jnp.where(valid, dst, 0),
                   jnp.where(valid, w, 0), (n, n))
