"""Sparse matrix containers — analog of the reference's COO/CSR types
(``core/sparse_types.hpp``, ``core/device_coo_matrix.hpp``,
``core/device_csr_matrix.hpp``, ``sparse/coo.hpp``, ``sparse/csr.hpp``).

TPU re-design: XLA requires static shapes, so both containers are
registered pytrees of fixed-size ``jax.Array``s whose *capacity* (nnz) is
a static Python int; padding entries carry ``row == -1`` (COO) or simply
zero value. Host code owns construction/compaction; device code uses
gather + ``segment_sum`` in place of the reference's cuSPARSE handles.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass(frozen=True)
class COO:
    """Coordinate-format sparse matrix (``raft::sparse::COO``,
    ``sparse/coo.hpp``). Invalid (padding) entries have ``rows == -1``."""

    rows: jax.Array   # (nnz,) int32, -1 = padding
    cols: jax.Array   # (nnz,) int32
    vals: jax.Array   # (nnz,)
    shape: Tuple[int, int]

    def tree_flatten(self):
        return (self.rows, self.cols, self.vals), (self.shape,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, shape=aux[0])

    @property
    def nnz(self) -> int:
        return self.rows.shape[0]

    def to_dense(self) -> jax.Array:
        m, n = self.shape
        out = jnp.zeros((m, n), self.vals.dtype)
        valid = self.rows >= 0
        r = jnp.where(valid, self.rows, 0)
        c = jnp.where(valid, self.cols, 0)
        v = jnp.where(valid, self.vals, 0)
        return out.at[r, c].add(v)

    @classmethod
    def from_dense(cls, dense, nnz: Optional[int] = None) -> "COO":
        dense = np.asarray(dense)
        r, c = np.nonzero(dense)
        v = dense[r, c]
        if nnz is None:
            nnz = len(r)
        pad = nnz - len(r)
        if pad < 0:
            raise ValueError(f"nnz capacity {nnz} < actual nonzeros {len(r)}")
        rows = np.concatenate([r, np.full(pad, -1)]).astype(np.int32)
        cols = np.concatenate([c, np.zeros(pad)]).astype(np.int32)
        vals = np.concatenate([v, np.zeros(pad, dense.dtype)])
        return cls(jnp.asarray(rows), jnp.asarray(cols), jnp.asarray(vals),
                   dense.shape)

    @classmethod
    def from_scipy(cls, mat) -> "COO":
        coo = mat.tocoo()
        return cls(jnp.asarray(coo.row, jnp.int32),
                   jnp.asarray(coo.col, jnp.int32),
                   jnp.asarray(coo.data), coo.shape)


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass(frozen=True)
class CSR:
    """Compressed-sparse-row matrix (``raft::sparse::csr``,
    ``sparse/csr.hpp``). Padding entries (beyond ``indptr[-1]``) hold
    zero values so device math can ignore them."""

    indptr: jax.Array   # (m + 1,) int32
    indices: jax.Array  # (nnz,) int32
    data: jax.Array     # (nnz,)
    shape: Tuple[int, int]

    def tree_flatten(self):
        return (self.indptr, self.indices, self.data), (self.shape,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, shape=aux[0])

    @property
    def nnz(self) -> int:
        return self.indices.shape[0]

    def row_ids(self) -> jax.Array:
        """Expanded (nnz,) row id per entry, -1 for padding — the COO view
        the segment-sum kernels consume."""
        m = self.shape[0]
        counts = jnp.diff(self.indptr)
        ids = jnp.repeat(jnp.arange(m, dtype=jnp.int32), counts,
                         total_repeat_length=self.nnz)
        # jnp.repeat pads the tail with the last row id when
        # sum(counts) < nnz; rewrite padding as -1
        valid = jnp.arange(self.nnz) < self.indptr[-1]
        return jnp.where(valid, ids, -1)

    def to_dense(self) -> jax.Array:
        m, n = self.shape
        r = self.row_ids()
        valid = r >= 0
        out = jnp.zeros((m, n), self.data.dtype)
        return out.at[jnp.where(valid, r, 0),
                      jnp.where(valid, self.indices, 0)].add(
            jnp.where(valid, self.data, 0))

    @classmethod
    def from_dense(cls, dense) -> "CSR":
        dense = np.asarray(dense)
        m, n = dense.shape
        r, c = np.nonzero(dense)
        v = dense[r, c]
        indptr = np.zeros(m + 1, np.int32)
        np.add.at(indptr, r + 1, 1)
        indptr = np.cumsum(indptr).astype(np.int32)
        return cls(jnp.asarray(indptr), jnp.asarray(c.astype(np.int32)),
                   jnp.asarray(v), (m, n))

    @classmethod
    def from_scipy(cls, mat) -> "CSR":
        csr = mat.tocsr()
        return cls(jnp.asarray(csr.indptr, jnp.int32),
                   jnp.asarray(csr.indices, jnp.int32),
                   jnp.asarray(csr.data), csr.shape)
