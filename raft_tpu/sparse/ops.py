"""Sparse structure operations — analog of ``raft/sparse/op/``
(``sort.cuh``, ``reduce.cuh`` max-duplicate merge, ``filter.cuh`` value
filtering, ``slice.cuh`` row slicing) plus ``linalg/degree.cuh``."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from raft_tpu.sparse.types import COO, CSR


def coo_sort(coo: COO) -> COO:
    """``op::coo_sort``: order entries by (row, col); padding last.

    Componentwise lexsort — no fused int64 key, which would overflow
    int32 under JAX's default x64-disabled mode."""
    m = coo.shape[0]
    row_key = jnp.where(coo.rows >= 0, coo.rows, m)
    order = jnp.lexsort((coo.cols, row_key))
    return COO(coo.rows[order], coo.cols[order], coo.vals[order], coo.shape)


def max_duplicates(coo: COO) -> COO:
    """``op::max_duplicates``: merge duplicate (row, col) entries keeping
    the max value (used when symmetrizing kNN graphs)."""
    return _merge_duplicates(coo, "max")


def sum_duplicates(coo: COO) -> COO:
    """Merge duplicate (row, col) entries by summation (the cuSPARSE
    ``coosort``+reduce idiom the reference leans on)."""
    return _merge_duplicates(coo, "sum")


def _merge_duplicates(coo: COO, how: str) -> COO:
    c = coo_sort(coo)
    same_prev = (c.rows[1:] == c.rows[:-1]) & (c.cols[1:] == c.cols[:-1])
    is_first = jnp.concatenate(
        [jnp.ones((1,), bool), ~same_prev]) & (c.rows >= 0)
    seg = jnp.cumsum(is_first) - 1                   # group id per entry
    seg = jnp.where(c.rows >= 0, seg, c.nnz)         # padding → drop bucket
    if how == "sum":
        merged = jax.ops.segment_sum(c.vals, seg, num_segments=c.nnz + 1)
    else:
        merged = jax.ops.segment_max(c.vals, seg, num_segments=c.nnz + 1)
    ngroups = jnp.sum(is_first)
    slot = jnp.where(is_first, seg, c.nnz)
    rows = jnp.full((c.nnz + 1,), -1, jnp.int32).at[slot].set(c.rows, mode="drop")
    cols = jnp.zeros((c.nnz + 1,), jnp.int32).at[slot].set(c.cols, mode="drop")
    valid = jnp.arange(c.nnz) < ngroups
    vals = jnp.where(valid, merged[: c.nnz], 0)
    return COO(jnp.where(valid, rows[: c.nnz], -1), cols[: c.nnz],
               vals, coo.shape)


def remove_scalar(coo: COO, scalar) -> COO:
    """``op::coo_remove_scalar``: entries equal to ``scalar`` become
    padding (capacity is static, so they are masked, not compacted)."""
    drop = (coo.vals == scalar) | (coo.rows < 0)
    return COO(jnp.where(drop, -1, coo.rows), coo.cols,
               jnp.where(drop, 0, coo.vals), coo.shape)


def remove_zeros(coo: COO) -> COO:
    """``op::coo_remove_zeros``."""
    return remove_scalar(coo, 0)


def row_slice(csr: CSR, start: int, stop: int) -> CSR:
    """``op::csr_row_slice_indptr`` + populate: rows [start, stop).

    Static-shape form: capacity stays the full nnz; entries outside the
    slice are zeroed padding past the new indptr."""
    m = stop - start
    indptr = csr.indptr[start : stop + 1] - csr.indptr[start]
    n_keep = csr.indptr[stop] - csr.indptr[start]
    idx = jnp.arange(csr.nnz) + csr.indptr[start]
    valid = jnp.arange(csr.nnz) < n_keep
    indices = jnp.where(valid, csr.indices[jnp.clip(idx, 0, csr.nnz - 1)], 0)
    data = jnp.where(valid, csr.data[jnp.clip(idx, 0, csr.nnz - 1)], 0)
    return CSR(indptr, indices, data, (m, csr.shape[1]))


def degree(coo: COO) -> jax.Array:
    """``linalg::coo_degree``: nonzeros per row."""
    valid = coo.rows >= 0
    return jax.ops.segment_sum(
        valid.astype(jnp.int32), jnp.clip(coo.rows, 0),
        num_segments=coo.shape[0])


def csr_row_op(csr: CSR, fn) -> CSR:
    """``op::csr_row_op``: map ``fn(row_id, value)`` over entries."""
    r = csr.row_ids()
    out = fn(r, csr.data)
    return CSR(csr.indptr, csr.indices, jnp.where(r >= 0, out, 0), csr.shape)
