"""Sparse format conversions — analog of ``raft/sparse/convert/``
(``convert/coo.cuh``, ``convert/csr.cuh``, ``convert/dense.cuh``).

All conversions are jittable except the dense→sparse directions, which
need a host-side nonzero count (static shapes)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from raft_tpu.sparse.types import COO, CSR


def coo_to_csr(coo: COO) -> CSR:
    """``convert::sorted_coo_to_csr``: sort by (row, col), build indptr.
    Padding rows (-1) sort to the back."""
    m, n = coo.shape
    row_key = jnp.where(coo.rows >= 0, coo.rows, m)
    order = jnp.lexsort((coo.cols, row_key))
    rows = coo.rows[order]
    cols = coo.cols[order]
    vals = coo.vals[order]
    counts = jax.ops.segment_sum(
        jnp.where(rows >= 0, 1, 0), jnp.clip(rows, 0), num_segments=m)
    indptr = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(counts).astype(jnp.int32)])
    return CSR(indptr, cols, vals, coo.shape)


def csr_to_coo(csr: CSR) -> COO:
    """``convert::csr_to_coo``: expand indptr to row ids."""
    return COO(csr.row_ids(), csr.indices, csr.data, csr.shape)


def dense_to_csr(dense) -> CSR:
    """``convert::dense_to_csr`` (host-side nnz count)."""
    return CSR.from_dense(dense)


def dense_to_coo(dense) -> COO:
    return COO.from_dense(dense)


def csr_to_dense(csr: CSR) -> jax.Array:
    """``convert::csr_to_dense``."""
    return csr.to_dense()


def coo_to_dense(coo: COO) -> jax.Array:
    return coo.to_dense()
