"""Sparse solvers — analog of ``raft/sparse/solver/``:
parallel Borůvka MST (``mst_solver.cuh``, ``detail/mst_solver_inl.cuh``)
and the Lanczos smallest-eigenvector solver (``lanczos.cuh:68``
``computeSmallestEigenvectors``).

TPU re-design of Borůvka: the reference's per-vertex atomic min-edge
kernels become ``segment_min`` reductions over a static edge list, and
supervertex contraction becomes pointer-jumping on a label array —
every round is a fixed-shape XLA program; ``ceil(log2 n)`` rounds
suffice because components at least halve.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core import tracing
from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.sparse.types import CSR


@dataclasses.dataclass
class MSTResult:
    """``Graph_COO`` result of the MST solver (src/dst/weights) plus the
    per-vertex component color (``mst_solver_t::solve`` outputs)."""

    src: jax.Array      # (n_edges_cap,) int32, -1 padding
    dst: jax.Array
    weights: jax.Array
    color: jax.Array    # (n,) final component label per vertex
    n_edges: int        # valid edge count

    @property
    def total_weight(self) -> float:
        return float(jnp.sum(jnp.where(self.src >= 0, self.weights, 0.0)))


@partial(jax.jit, static_argnames=("n", "rounds"))
def _boruvka(u, v, w, rank, n: int, rounds: int):
    e = u.shape[0]
    big = jnp.int32(jnp.iinfo(jnp.int32).max)

    def round_fn(_, state):
        comp, in_mst = state
        cu = jnp.take(comp, jnp.clip(u, 0))
        cv = jnp.take(comp, jnp.clip(v, 0))
        alive = (cu != cv) & (u >= 0)
        key = jnp.where(alive, rank, big)
        # min outgoing edge rank per component (both directions)
        m1 = jax.ops.segment_min(key, cu, num_segments=n)
        m2 = jax.ops.segment_min(key, cv, num_segments=n)
        minkey = jnp.minimum(m1, m2)
        chosen = alive & (
            (rank == jnp.take(minkey, cu)) | (rank == jnp.take(minkey, cv))
        )
        in_mst = in_mst | chosen

        # hooking: each component points at its min-edge partner
        partner = jnp.arange(n, dtype=jnp.int32)
        sel_u = chosen & (rank == jnp.take(minkey, cu))
        sel_v = chosen & (rank == jnp.take(minkey, cv))
        partner = partner.at[jnp.where(sel_u, cu, n)].set(
            jnp.where(sel_u, cv, 0), mode="drop")
        partner = partner.at[jnp.where(sel_v, cv, n)].set(
            jnp.where(sel_v, cu, 0), mode="drop")
        # break 2-cycles toward the smaller label
        two_cycle = jnp.take(partner, partner) == jnp.arange(n)
        par = jnp.where(two_cycle & (jnp.arange(n) < partner),
                        jnp.arange(n), partner)
        # pointer jumping to forest roots
        for _ in range(max(1, rounds)):
            par = jnp.take(par, par)
        comp = jnp.take(par, comp)
        return comp, in_mst

    comp0 = jnp.arange(n, dtype=jnp.int32)
    in_mst0 = jnp.zeros((e,), bool)
    comp, in_mst = jax.lax.fori_loop(0, rounds, round_fn, (comp0, in_mst0))
    return comp, in_mst


def mst(
    res: Optional[Resources],
    adjacency: CSR,
) -> MSTResult:
    """Minimum spanning forest of a (symmetric, weighted) CSR graph —
    ``solver::mst`` (``mst_solver.cuh``). Deterministic: ties broken by a
    global weight-rank ordering (the reference's alteration trick,
    ``detail/mst_solver_inl.cuh``)."""
    ensure_resources(res)
    n = adjacency.shape[0]
    r = adjacency.row_ids()
    u = jnp.where(r >= 0, r, -1)
    v = adjacency.indices
    w = adjacency.data.astype(jnp.float32)

    with tracing.range("raft_tpu.sparse.mst"):
        # canonical rank: (weight, lo, hi) lexicographic — no fused
        # int key (int32 would overflow for large n); the two directed
        # copies of an undirected edge share the lower rank
        lo = jnp.minimum(u, v)
        hi = jnp.maximum(u, v)
        order = jnp.lexsort((hi, lo, w))
        rank = jnp.zeros((u.shape[0],), jnp.int32).at[order].set(
            jnp.arange(u.shape[0], dtype=jnp.int32))
        srt = jnp.lexsort((hi, lo))
        rank_srt = rank[srt]
        same_prev = jnp.concatenate(
            [jnp.zeros((1,), bool),
             (lo[srt][1:] == lo[srt][:-1]) & (hi[srt][1:] == hi[srt][:-1])])
        pair_min = jnp.minimum(rank_srt,
                               jnp.where(same_prev,
                                         jnp.roll(rank_srt, 1), rank_srt))
        rank = rank.at[srt].set(pair_min)
        rank = jnp.where(u >= 0, rank, jnp.iinfo(jnp.int32).max)

        rounds = max(1, math.ceil(math.log2(max(n, 2))))
        comp, in_mst = _boruvka(u, v, w, rank, n, rounds)

        # emit each undirected MST edge once (first copy in (lo, hi) order)
        in_srt = in_mst[srt]
        dup = jnp.concatenate([jnp.zeros((1,), bool), same_prev[1:] & in_srt[:-1]])
        first_copy = jnp.zeros_like(in_mst).at[srt].set(~dup)
        emit = in_mst & first_copy
        src = jnp.where(emit, u, -1)
        dst = jnp.where(emit, v, 0)
        ww = jnp.where(emit, w, 0)
        return MSTResult(src=src, dst=dst, weights=ww, color=comp,
                         n_edges=int(jnp.sum(emit)))


def lanczos_smallest(
    res: Optional[Resources],
    a: CSR,
    k: int,
    max_iter: int = 0,
    seed: int = 0,
) -> Tuple[jax.Array, jax.Array]:
    """k smallest eigenpairs of a symmetric sparse matrix —
    ``sparse::solver::lanczos`` ``computeSmallestEigenvectors``
    (``lanczos.cuh:68``). Lanczos with full reorthogonalization; the
    tridiagonal eigenproblem is solved densely (role of the reference's
    LAPACK steqr call).

    Returns (eigenvalues (k,), eigenvectors (n, k))."""
    from raft_tpu.sparse.linalg import spmv

    ensure_resources(res)
    n = a.shape[0]
    m = min(n, max_iter or max(4 * k + 8, 32))

    with tracing.range("raft_tpu.sparse.lanczos"):
        key = jax.random.key(seed)
        v0 = jax.random.normal(key, (n,), jnp.float32)
        v0 = v0 / jnp.linalg.norm(v0)

        def body(j, state):
            vmat, alpha, beta = state
            vj = vmat[j]
            wv = spmv(a, vj)
            aj = jnp.dot(vj, wv)
            wv = wv - aj * vj - jnp.where(j > 0, beta[j - 1], 0.0) * vmat[j - 1]
            # full reorthogonalization against all previous vectors
            mask = (jnp.arange(m + 1) <= j)[:, None]
            proj = (vmat * mask) @ wv
            wv = wv - ((vmat * mask).T @ proj)
            bj = jnp.linalg.norm(wv)
            # breakdown (invariant subspace exhausted): restart with a
            # fresh random vector orthogonalized against the basis, and
            # record beta=0 so T decouples into blocks — the reference's
            # LAPACK-restart behavior; without this, un-run iterations
            # would inject spurious zero eigenvalues
            breakdown = bj <= 1e-6
            rv = jax.random.normal(jax.random.fold_in(key, j + 1), (n,),
                                   jnp.float32)
            for _ in range(2):
                rv = rv - ((vmat * mask).T @ ((vmat * mask) @ rv))
            rv = rv / jnp.maximum(jnp.linalg.norm(rv), 1e-30)
            vnext = jnp.where(breakdown, rv, wv / jnp.maximum(bj, 1e-30))
            vmat = vmat.at[j + 1].set(vnext)
            return (vmat, alpha.at[j].set(aj),
                    beta.at[j].set(jnp.where(breakdown, 0.0, bj)))

        vmat0 = jnp.zeros((m + 1, n), jnp.float32).at[0].set(v0)
        alpha0 = jnp.zeros((m,), jnp.float32)
        beta0 = jnp.zeros((m,), jnp.float32)
        vmat, alpha, beta = jax.lax.fori_loop(0, m, body,
                                              (vmat0, alpha0, beta0))

        t = jnp.diag(alpha) + jnp.diag(beta[: m - 1], 1) \
            + jnp.diag(beta[: m - 1], -1)
        evals, evecs = jnp.linalg.eigh(t)
        eigvecs = vmat[:m].T @ evecs[:, :k]
        # normalize (guard rank deficiency)
        norms = jnp.linalg.norm(eigvecs, axis=0)
        eigvecs = eigvecs / jnp.maximum(norms, 1e-30)
        return evals[:k], eigvecs
