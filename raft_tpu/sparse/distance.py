"""Sparse pairwise distances — analog of ``raft/sparse/distance/``
(``distance/distance.cuh:38-58`` supported-metric set).

The reference computes CSR×CSR distances with expanded (SPMV-based) and
unexpanded (nested-loop) CUDA paths. TPU re-design: densify row *tiles*
of both operands (static tile shapes) and reuse the dense 20-metric
engine — on TPU the MXU eats dense tiles far faster than any
gather-heavy sparse inner loop, and the tiling bounds memory at
``tile × n_cols``. This supports every metric the dense engine does,
a superset of the reference's sparse set.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from raft_tpu.core import tracing
from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.distance.pairwise import _pairwise_distance_impl
from raft_tpu.distance.types import DistanceType
from raft_tpu.sparse.ops import row_slice
from raft_tpu.sparse.types import CSR


def pairwise_distance(
    res: Optional[Resources],
    x: CSR,
    y: CSR,
    metric: DistanceType = DistanceType.L2Expanded,
    metric_arg: float = 2.0,
    tile: int = 2048,
) -> jax.Array:
    """Dense (m, n) distance matrix between CSR row sets —
    ``sparse::distance::pairwiseDistance``."""
    ensure_resources(res)
    assert x.shape[1] == y.shape[1], "column dims must match"
    m = x.shape[0]
    n = y.shape[0]
    with tracing.range("raft_tpu.sparse.pairwise_distance"):
        rows = []
        for xs in range(0, m, tile):
            xe = min(xs + tile, m)
            xd = row_slice(x, xs, xe).to_dense()
            cols = []
            for ys in range(0, n, tile):
                ye = min(ys + tile, n)
                yd = row_slice(y, ys, ye).to_dense()
                cols.append(
                    _pairwise_distance_impl(xd, yd, metric, metric_arg,
                                            "highest")
                )
            rows.append(cols[0] if len(cols) == 1
                        else jnp.concatenate(cols, axis=1))
        return rows[0] if len(rows) == 1 else jnp.concatenate(rows, axis=0)
