"""Sparse pairwise distances — analog of ``raft/sparse/distance/``
(``distance/distance.cuh:38-58`` supported-metric set).

The reference computes CSR×CSR distances with expanded (SPMV-based) and
unexpanded (nested-loop) CUDA paths. TPU re-design, two regimes:

- **full-width tiles** (default at moderate ``n_cols``): densify row
  *tiles* of both operands (static tile shapes) and reuse the dense
  20-metric engine — the MXU eats dense tiles far faster than any
  gather-heavy sparse inner loop. Memory is ``tile × n_cols``.

- **column-tiled expanded path** (the SPMV role, for text-scale widths):
  the expanded metrics (L2/IP/cosine) are functions of ``x·yᵀ``,
  ``‖x‖²``, ``‖y‖²`` only, so the Gram block accumulates over
  ``col_tile``-wide dense column slabs under ``lax.scan`` — memory is
  ``tile × col_tile`` regardless of ``n_cols``, matching the bound of
  the reference's SPMV path (``distance/detail/l2_distance.cuh``).
  Row norms are one ``segment_sum`` per tile (hoisted out of the slab
  loop; InnerProduct needs none).

Row tiles are sliced with TIGHT nnz capacity (bucketed to a power of
two so jit shapes stay bounded): every densify costs O(tile_nnz), not
O(total_nnz) as a full-capacity ``row_slice`` would.

Non-decomposable metrics on very wide inputs fail loudly with the
memory bound (``RAFT_TPU_SPARSE_TILE_MB`` raises it) instead of
silently allocating ``tile × n_cols``.
"""

from __future__ import annotations

import os
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core import tracing
from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.core.validation import expect
from raft_tpu.distance.pairwise import _pairwise_distance_impl
from raft_tpu.distance.types import DistanceType
from raft_tpu.sparse.types import CSR

# expanded metrics: computable from (x·yT, |x|^2, |y|^2) alone, hence
# column-tileable. L2Unexpanded equals L2Expanded in exact arithmetic.
_DECOMPOSABLE = (
    DistanceType.InnerProduct,
    DistanceType.L2Expanded,
    DistanceType.L2SqrtExpanded,
    DistanceType.L2Unexpanded,
    DistanceType.L2SqrtUnexpanded,
    DistanceType.CosineExpanded,
)


def _tile_budget_mb() -> int:
    return int(os.environ.get("RAFT_TPU_SPARSE_TILE_MB", "2048"))


def _tight_row_slice(csr: CSR, indptr_host: np.ndarray, s: int,
                     e: int) -> CSR:
    """Rows [s, e) with nnz capacity bucketed to the next power of two
    (bounded jit-shape count) — densifies in O(tile_nnz)."""
    o = int(indptr_host[s])
    n_keep = int(indptr_host[e]) - o
    cap = max(8, 1 << (max(n_keep, 1) - 1).bit_length())
    end = min(o + cap, csr.nnz)
    pad = cap - (end - o)
    idx = jnp.pad(csr.indices[o:end], (0, pad))
    dat = jnp.pad(csr.data[o:end], (0, pad))
    indptr = jnp.asarray(
        np.clip(indptr_host[s:e + 1] - o, 0, n_keep), jnp.int32)
    return CSR(indptr, idx, dat, (e - s, csr.shape[1]))


def _dense_cols(csr: CSR, row_ids, cs, col_tile: int):
    """Dense (rows, col_tile) slab of the columns [cs, cs+col_tile) of a
    row-sliced CSR — ``cs`` may be traced (scan carry)."""
    ind = csr.indices
    valid = (row_ids >= 0) & (ind >= cs) & (ind < cs + col_tile)
    out = jnp.zeros((csr.shape[0], col_tile), csr.data.dtype)
    return out.at[
        jnp.where(valid, row_ids, 0),
        jnp.where(valid, ind - cs, 0),
    ].add(jnp.where(valid, csr.data, 0))


@jax.jit
def _row_sq_norms(csr: CSR):
    """Per-row Σ data² — one segment_sum, independent of col tiling."""
    r = csr.row_ids()
    sq = jnp.where(r >= 0, jnp.square(csr.data.astype(jnp.float32)), 0.0)
    return jax.ops.segment_sum(sq, jnp.clip(r, 0),
                               num_segments=csr.shape[0])


@partial(jax.jit, static_argnames=("metric", "col_tile", "n_cols"))
def _expanded_block(xt: CSR, yt: CSR, xn, yn, metric: DistanceType,
                    col_tile: int, n_cols: int):
    """One (x-tile, y-tile) distance block, Gram-accumulated over dense
    column slabs — never materializes a full-width dense tile. Norms
    arrive precomputed (hoisted out of the slab loop)."""
    xr = xt.row_ids()
    yr = yt.row_ids()
    nb = -(-n_cols // col_tile)
    init = jnp.zeros((xt.shape[0], yt.shape[0]), jnp.float32)

    def step(ip, cs):
        xd = _dense_cols(xt, xr, cs, col_tile).astype(jnp.float32)
        yd = _dense_cols(yt, yr, cs, col_tile).astype(jnp.float32)
        return ip + jax.lax.dot_general(
            xd, yd, (((1,), (1,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32), None

    starts = jnp.arange(nb, dtype=jnp.int32) * col_tile
    ip, _ = jax.lax.scan(step, init, starts)

    if metric == DistanceType.InnerProduct:
        return ip
    if metric == DistanceType.CosineExpanded:
        denom = jnp.sqrt(jnp.maximum(xn[:, None] * yn[None, :], 1e-30))
        return 1.0 - ip / denom
    d2 = jnp.maximum(xn[:, None] + yn[None, :] - 2.0 * ip, 0.0)
    if metric in (DistanceType.L2SqrtExpanded,
                  DistanceType.L2SqrtUnexpanded):
        return jnp.sqrt(d2)
    return d2


def pairwise_distance(
    res: Optional[Resources],
    x: CSR,
    y: CSR,
    metric: DistanceType = DistanceType.L2Expanded,
    metric_arg: float = 2.0,
    tile: int = 2048,
    col_tile: Optional[int] = None,
) -> jax.Array:
    """Dense (m, n) distance matrix between CSR row sets —
    ``sparse::distance::pairwiseDistance``.

    ``col_tile`` bounds the dense slab width for the expanded metrics:
    ``None`` auto-enables column tiling (slab width 8192) once a
    full-width tile would exceed the ``RAFT_TPU_SPARSE_TILE_MB``
    budget; pass an int to force it. Non-decomposable metrics (L1,
    Hamming, …) need full rows and are bounded by the same budget —
    past it they fail with the bound rather than allocate."""
    ensure_resources(res)
    assert x.shape[1] == y.shape[1], "column dims must match"
    expect(tile > 0, f"tile must be positive, got {tile}")
    m = x.shape[0]
    n = y.shape[0]
    n_cols = x.shape[1]
    itemsize = jnp.dtype(x.data.dtype).itemsize
    # ceil, not floor: a sub-MB tile must still compare > a 0 MB budget
    full_tile_mb = -(-(min(tile, max(m, n)) * n_cols * itemsize) // (1 << 20))
    decomposable = metric in _DECOMPOSABLE
    if col_tile is None and decomposable and full_tile_mb > _tile_budget_mb():
        col_tile = 8192
    if col_tile is not None:
        expect(col_tile > 0, f"col_tile must be positive, got {col_tile}")
        expect(decomposable,
               f"column tiling needs an expanded metric (got {metric!r}); "
               "L1/Lp/Hamming-family metrics need full rows")
        col_tile = min(col_tile, n_cols)
    else:
        expect(full_tile_mb <= _tile_budget_mb(),
               f"a {tile}×{n_cols} dense tile is ~{full_tile_mb} MB, over "
               f"the {_tile_budget_mb()} MB RAFT_TPU_SPARSE_TILE_MB budget "
               "— use an expanded metric (column-tiled) or shrink `tile`")

    xip = np.asarray(jax.device_get(x.indptr))
    yip = np.asarray(jax.device_get(y.indptr))
    ip_metric = metric == DistanceType.InnerProduct
    with tracing.range("raft_tpu.sparse.pairwise_distance"):
        # y tiles (and their norms) are reused across every x tile
        ytiles = [_tight_row_slice(y, yip, ys, min(ys + tile, n))
                  for ys in range(0, n, tile)]
        yns = (None if col_tile is None or ip_metric
               else [_row_sq_norms(yt) for yt in ytiles])
        rows = []
        for xs in range(0, m, tile):
            xe = min(xs + tile, m)
            xt = _tight_row_slice(x, xip, xs, xe)
            if col_tile is not None:
                xn = None if ip_metric else _row_sq_norms(xt)
                cols = [_expanded_block(xt, yt, xn,
                                        None if yns is None else yns[j],
                                        metric, col_tile, n_cols)
                        for j, yt in enumerate(ytiles)]
            else:
                xd = xt.to_dense()
                cols = [_pairwise_distance_impl(
                    xd, yt.to_dense(), metric, metric_arg, "highest")
                    for yt in ytiles]
            rows.append(cols[0] if len(cols) == 1
                        else jnp.concatenate(cols, axis=1))
        return rows[0] if len(rows) == 1 else jnp.concatenate(rows, axis=0)
