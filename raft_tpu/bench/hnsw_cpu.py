"""CPU HNSW competitor baseline — the role of the reference's hnswlib
wrapper (``cpp/bench/ann/src/hnswlib/hnswlib_wrapper.h:1``): the
benchmark harness's non-RAFT comparison point on the recall-vs-QPS
pareto plot (``docs/source/raft_ann_benchmarks.md:229``).

This environment has no hnswlib, so the baseline is a from-scratch
C++17 HNSW (``native/hnsw.cpp``, Malkov & Yashunin arXiv:1603.09320)
loaded via ctypes — a real graph-search competitor measured on the
same host the way the reference measures hnswlib on CPU.
"""

from __future__ import annotations

import ctypes
import pathlib
import subprocess
import threading

import numpy as np

from raft_tpu.distance.types import DistanceType

_REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent.parent
_NATIVE_DIR = _REPO_ROOT / "native"
_SO_PATH = _NATIVE_DIR / "libraft_tpu_hnsw.so"

_lib = None
_lib_lock = threading.Lock()
_build_attempted = False

_METRIC_CODES = {
    DistanceType.L2Expanded: 0,
    DistanceType.L2SqrtExpanded: 0,   # same graph; sqrt applied on top
    DistanceType.L2Unexpanded: 0,
    DistanceType.InnerProduct: 1,
}


def _so_stale() -> bool:
    """Missing, or older than the sources that produce it — a stale
    library lacks newer symbols (hnsw_dim/…). Decided by mtime BEFORE
    dlopen: rebuilding after a dlopen would truncate a mapped file."""
    if not _SO_PATH.exists():
        return True
    so_m = _SO_PATH.stat().st_mtime
    return any(src.exists() and src.stat().st_mtime > so_m
               for src in (_NATIVE_DIR / "hnsw.cpp",
                           _NATIVE_DIR / "Makefile"))


def _load():
    global _lib, _build_attempted
    with _lib_lock:
        if _lib is not None:
            return _lib
        if _so_stale() and not _build_attempted:
            _build_attempted = True
            try:
                subprocess.run(["make", "-s"], cwd=_NATIVE_DIR, check=True,
                               capture_output=True, timeout=300)
            except (OSError, subprocess.SubprocessError):
                pass  # an existing (possibly stale) .so may still do
        if not _SO_PATH.exists():
            return None
        try:
            lib = ctypes.CDLL(str(_SO_PATH))
        except OSError:
            return None
        # a stale prebuilt .so (toolchain missing, make failed) must
        # degrade to available() == False, not AttributeError out of
        # every caller that relies on it to skip the baseline
        for sym in ("hnsw_create", "hnsw_add", "hnsw_size", "hnsw_dim",
                    "hnsw_metric", "hnsw_search", "hnsw_save",
                    "hnsw_load", "hnsw_free", "hnsw_last_error"):
            if not hasattr(lib, sym):
                return None
        lib.hnsw_create.restype = ctypes.c_void_p
        lib.hnsw_create.argtypes = [ctypes.c_int64, ctypes.c_int64,
                                    ctypes.c_int64, ctypes.c_int,
                                    ctypes.c_uint64]
        lib.hnsw_add.restype = ctypes.c_int
        lib.hnsw_add.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                 ctypes.c_int64]
        lib.hnsw_size.restype = ctypes.c_int64
        lib.hnsw_size.argtypes = [ctypes.c_void_p]
        lib.hnsw_dim.restype = ctypes.c_int64
        lib.hnsw_dim.argtypes = [ctypes.c_void_p]
        lib.hnsw_metric.restype = ctypes.c_int
        lib.hnsw_metric.argtypes = [ctypes.c_void_p]
        lib.hnsw_search.restype = ctypes.c_int
        lib.hnsw_search.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                    ctypes.c_int64, ctypes.c_int64,
                                    ctypes.c_int64, ctypes.c_void_p,
                                    ctypes.c_void_p]
        lib.hnsw_save.restype = ctypes.c_int
        lib.hnsw_save.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.hnsw_load.restype = ctypes.c_void_p
        lib.hnsw_load.argtypes = [ctypes.c_char_p]
        lib.hnsw_free.argtypes = [ctypes.c_void_p]
        lib.hnsw_last_error.restype = ctypes.c_char_p
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def _err(lib) -> str:
    return lib.hnsw_last_error().decode(errors="replace")


class HnswCpuIndex:
    """Owns the native handle; frees it on GC."""

    def __init__(self, handle, dim: int, metric: DistanceType):
        self._h = handle
        self._free = _load().hnsw_free  # bound now: _load() and module
        self.dim = dim                  # globals may be gone at GC time
        self.metric = metric

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            try:
                self._free(h)
            except TypeError:  # interpreter teardown already unloaded it
                pass
            self._h = None


def build(base, metric: DistanceType, *, M: int = 16,
          ef_construction: int = 200, seed: int = 0) -> HnswCpuIndex:
    """Insert every base row (single-threaded, like a 1-thread hnswlib
    build). ``base`` must be float32 (n, dim)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native HNSW library unavailable (g++/make "
                           "missing?); cannot run the CPU baseline")
    base = np.ascontiguousarray(base, np.float32)
    n, dim = base.shape
    code = _METRIC_CODES.get(metric)
    if code is None:
        raise ValueError(f"hnsw_cpu: unsupported metric {metric}")
    h = lib.hnsw_create(dim, M, ef_construction, code, seed)
    if not h:
        raise RuntimeError(f"hnsw_create failed: {_err(lib)}")
    if lib.hnsw_add(h, base.ctypes.data_as(ctypes.c_void_p), n) != 0:
        lib.hnsw_free(h)
        raise RuntimeError(f"hnsw_add failed: {_err(lib)}")
    return HnswCpuIndex(h, dim, metric)


def search(index: HnswCpuIndex, queries, k: int, *, ef: int = 64):
    """(q, k) distances + ids. L2 metrics return squared L2 (sqrt for
    L2SqrtExpanded); InnerProduct returns the (positive) similarity."""
    lib = _load()
    queries = np.ascontiguousarray(queries, np.float32)
    q = queries.shape[0]
    if queries.ndim != 2 or queries.shape[1] != index.dim:
        raise ValueError("queries must be (q, dim)")
    out_d = np.empty((q, k), np.float32)
    out_i = np.empty((q, k), np.int64)
    rc = lib.hnsw_search(index._h,
                         queries.ctypes.data_as(ctypes.c_void_p), q, k,
                         max(ef, k),
                         out_d.ctypes.data_as(ctypes.c_void_p),
                         out_i.ctypes.data_as(ctypes.c_void_p))
    if rc != 0:
        raise RuntimeError(f"hnsw_search failed: {_err(lib)}")
    if index.metric == DistanceType.L2SqrtExpanded:
        out_d = np.sqrt(np.maximum(out_d, 0.0))
    elif index.metric == DistanceType.InnerProduct:
        out_d = -out_d  # native stores min-form
    return out_d, out_i.astype(np.int32)


def save(index: HnswCpuIndex, path: str) -> None:
    lib = _load()
    if lib.hnsw_save(index._h, str(path).encode()) != 0:
        raise RuntimeError(f"hnsw_save failed: {_err(lib)}")


def load(path: str, dim: int, metric: DistanceType) -> HnswCpuIndex:
    lib = _load()
    if lib is None:
        raise RuntimeError("native HNSW library unavailable")
    h = lib.hnsw_load(str(path).encode())
    if not h:
        raise RuntimeError(f"hnsw_load failed: {_err(lib)}")
    # cross-check the file's recorded geometry/metric against the
    # caller's: search() validates queries against the caller-supplied
    # dim while the native side strides by the FILE's dim, so accepting
    # a mismatched cache (stale, hand-placed, name collision) would read
    # past the query buffer or score under the wrong metric
    stored_dim = lib.hnsw_dim(h)
    stored_metric = lib.hnsw_metric(h)
    want_metric = _METRIC_CODES.get(metric)
    if stored_dim != dim or stored_metric != want_metric:
        lib.hnsw_free(h)
        raise RuntimeError(
            f"hnsw_load: cache {path} holds dim={stored_dim} "
            f"metric_code={stored_metric}, caller expects dim={dim} "
            f"metric_code={want_metric} ({metric.name}) — stale or "
            f"mismatched cache file")
    return HnswCpuIndex(h, dim, metric)
