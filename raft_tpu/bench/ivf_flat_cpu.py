"""CPU IVF-Flat exact-scan competitor baseline — the role of the
reference's FAISS wrapper in the ANN benchmark
(``cpp/bench/ann/src/faiss/faiss_benchmark.cu:1``, a *second*
non-RAFT series on the recall-vs-QPS pareto beside hnswlib,
``docs/source/raft_ann_benchmarks.md:229``).

This environment has no FAISS, so the baseline is a from-scratch
numpy IVF-Flat: Lloyd-trained coarse centroids over a training
subsample, inverted lists as contiguous row blocks with their squared
norms precomputed at build, and a per-query exact scan of the
``n_probes`` closest lists (coarse scoring is one BLAS gemm per query
batch; the fine scan is one gemv per probed list span against the
precomputed norms — the same per-query scan-selected-lists structure
as FAISS's CPU ``IndexIVFFlat``). Pure numpy, no jax import: the
competitor must not ride the subject library's compute path.
"""

from __future__ import annotations

import numpy as np

from raft_tpu.distance.types import DistanceType

_L2_METRICS = (DistanceType.L2Expanded, DistanceType.L2SqrtExpanded,
               DistanceType.L2Unexpanded)
_MAGIC = b"RTIVFCPU"
_VERSION = 1


class IvfFlatCpuIndex:
    """Trained centroids + per-list contiguous row blocks."""

    def __init__(self, centroids, list_rows, list_ids, list_offsets,
                 metric: DistanceType):
        self.centroids = centroids      # (n_lists, dim) f32
        self.list_rows = list_rows      # (n, dim) f32, rows grouped by list
        self.list_ids = list_ids        # (n,) int32 original row ids
        self.list_offsets = list_offsets  # (n_lists + 1,) int64
        self.metric = metric
        # squared row norms, precomputed once: the L2 fine scan's
        # ||x||^2 term must not be recomputed per query
        self.list_row_sq = (list_rows * list_rows).sum(axis=1)

    @property
    def dim(self) -> int:
        return self.centroids.shape[1]


def _pairwise_sq_l2(a, b_t, b_sq):
    """(m, d) x (d, n) -> (m, n) squared L2 via the expanded form —
    one gemm, the scan's hot loop."""
    return np.maximum(
        (a * a).sum(axis=1, keepdims=True) - 2.0 * (a @ b_t) + b_sq, 0.0)


def build(base, metric: DistanceType, *, n_lists: int = 1024,
          train_iters: int = 10, trainset_fraction: float = 0.1,
          seed: int = 0) -> IvfFlatCpuIndex:
    """Lloyd k-means on a subsample, then assign every row to its
    nearest centroid and pack the inverted lists contiguously."""
    base = np.ascontiguousarray(base, np.float32)
    n, dim = base.shape
    if metric not in _L2_METRICS + (DistanceType.InnerProduct,):
        raise ValueError(f"ivf_flat_cpu: unsupported metric {metric}")
    n_lists = min(n_lists, n)
    rng = np.random.default_rng(seed)
    n_train = max(n_lists, min(n, int(n * trainset_fraction)))
    train = base[rng.choice(n, n_train, replace=False)] \
        if n_train < n else base
    cent = train[rng.choice(n_train, n_lists, replace=False)].copy()

    def assign(rows, chunk=65536):
        out = np.empty(rows.shape[0], np.int64)
        c_t = np.ascontiguousarray(cent.T)
        c_sq = (cent * cent).sum(axis=1)[None, :]
        for s in range(0, rows.shape[0], chunk):
            d = _pairwise_sq_l2(rows[s:s + chunk], c_t, c_sq)
            out[s:s + chunk] = d.argmin(axis=1)
        return out

    for _ in range(train_iters):
        lbl = assign(train)
        # batched centroid update; empty lists keep their old centroid
        sums = np.zeros((n_lists, dim), np.float64)
        np.add.at(sums, lbl, train)
        counts = np.bincount(lbl, minlength=n_lists)
        nz = counts > 0
        cent[nz] = (sums[nz] / counts[nz, None]).astype(np.float32)

    lbl = assign(base)
    order = np.argsort(lbl, kind="stable")
    list_ids = order.astype(np.int32)
    list_rows = base[order]
    counts = np.bincount(lbl, minlength=n_lists)
    offsets = np.zeros(n_lists + 1, np.int64)
    np.cumsum(counts, out=offsets[1:])
    return IvfFlatCpuIndex(cent, list_rows, list_ids, offsets, metric)


def search(index: IvfFlatCpuIndex, queries, k: int, *,
           n_probes: int = 32):
    """Exact scan of the ``n_probes`` closest lists per query.
    Returns (q, k) distances + int32 ids; L2 metrics return squared L2
    (sqrt applied for L2SqrtExpanded), InnerProduct the similarity."""
    queries = np.ascontiguousarray(queries, np.float32)
    if queries.ndim != 2 or queries.shape[1] != index.dim:
        raise ValueError("queries must be (q, dim)")
    q = queries.shape[0]
    n_lists = index.centroids.shape[0]
    n_probes = min(n_probes, n_lists)
    ip_metric = index.metric == DistanceType.InnerProduct

    c_t = np.ascontiguousarray(index.centroids.T)
    if ip_metric:
        cd = -(queries @ c_t)  # min-form coarse scores
    else:
        c_sq = (index.centroids * index.centroids).sum(axis=1)[None, :]
        cd = _pairwise_sq_l2(queries, c_t, c_sq)
    probes = np.argpartition(cd, n_probes - 1, axis=1)[:, :n_probes]

    out_d = np.full((q, k), np.inf, np.float32)
    out_i = np.full((q, k), -1, np.int32)
    offs = index.list_offsets
    q_sq = (queries * queries).sum(axis=1)
    for qi in range(q):
        spans = [(offs[p], offs[p + 1]) for p in probes[qi]]
        total = int(sum(e - s for s, e in spans))
        if total == 0:
            continue
        # per-span gemvs against precomputed norms: no per-query copy
        # of the row data, no per-query norm recompute
        qv = queries[qi]
        d = np.empty(total, np.float32)
        ids = np.empty(total, np.int32)
        pos = 0
        for s, e in spans:
            seg = index.list_rows[s:e]
            if ip_metric:
                d[pos:pos + (e - s)] = -(seg @ qv)
            else:
                d[pos:pos + (e - s)] = (index.list_row_sq[s:e]
                                        - 2.0 * (seg @ qv) + q_sq[qi])
            ids[pos:pos + (e - s)] = index.list_ids[s:e]
            pos += e - s
        if not ip_metric:
            np.maximum(d, 0.0, out=d)
        kk = min(k, total)
        top = np.argpartition(d, kk - 1)[:kk]
        top = top[np.argsort(d[top], kind="stable")]
        out_d[qi, :kk] = d[top]
        out_i[qi, :kk] = ids[top]
    if index.metric == DistanceType.L2SqrtExpanded:
        out_d = np.sqrt(np.maximum(out_d, 0.0))
    elif ip_metric:
        out_d = -out_d
    return out_d, out_i


def save(index: IvfFlatCpuIndex, path) -> None:
    with open(path, "wb") as fh:
        fh.write(_MAGIC)
        np.save(fh, np.int64([_VERSION, int(index.metric)]))
        np.save(fh, index.centroids)
        np.save(fh, index.list_rows)
        np.save(fh, index.list_ids)
        np.save(fh, index.list_offsets)


def load(path, dim: int, metric: DistanceType) -> IvfFlatCpuIndex:
    with open(path, "rb") as fh:
        if fh.read(len(_MAGIC)) != _MAGIC:
            raise ValueError(f"{path}: not an ivf_flat_cpu index")
        version, stored_metric = np.load(fh)
        if version != _VERSION:
            raise ValueError(f"{path}: unsupported version {version}")
        cent = np.load(fh)
        rows = np.load(fh)
        ids = np.load(fh)
        offs = np.load(fh)
    if (cent.ndim != 2 or rows.ndim != 2 or rows.shape[1] != cent.shape[1]
            or ids.shape[0] != rows.shape[0]
            or offs.shape[0] != cent.shape[0] + 1
            or offs[0] != 0 or offs[-1] != rows.shape[0]
            or np.any(np.diff(offs) < 0)):
        raise ValueError(f"{path}: corrupt ivf_flat_cpu index")
    # cross-check the file's recorded geometry/metric against the
    # caller's (same contract as hnsw_cpu.load)
    if cent.shape[1] != dim or stored_metric != int(metric):
        raise ValueError(
            f"{path}: cache holds dim={cent.shape[1]} "
            f"metric={stored_metric}, caller expects dim={dim} "
            f"metric={int(metric)} ({metric.name}) — stale or "
            f"mismatched cache file")
    return IvfFlatCpuIndex(cent, rows, ids, offs, DistanceType(metric))
