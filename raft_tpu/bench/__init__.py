"""ANN benchmark harness — analog of ``python/raft-ann-bench``
(SURVEY.md §2.8): dataset preparation, run orchestration from JSON
configs, CSV export, and recall-vs-QPS plotting.

CLI::

    python -m raft_tpu.bench get-dataset --kind random --n 100000 ...
    python -m raft_tpu.bench run --dataset data/random-100k --config conf.json
    python -m raft_tpu.bench data-export --results results/
    python -m raft_tpu.bench plot --results results/ --out plot.png
"""

from raft_tpu.bench.datasets import convert_hdf5, make_dataset
from raft_tpu.bench.runner import ALGO_REGISTRY, run_benchmark

__all__ = [
    "ALGO_REGISTRY",
    "convert_hdf5",
    "make_dataset",
    "run_benchmark",
]
