"""Benchmark orchestration — analog of ``raft-ann-bench/run``
(``run/__main__.py:48-120``): an algorithm registry (the ``algos.yaml``
role), JSON param-sweep configs, build+search timing, recall against
groundtruth, and JSON-lines results the exporter/plotter consume.

The reference shells out to gbench executables; here algorithms are
in-process wrappers over the framework APIs (``bench/ann/src/common/
ann_types.hpp:79`` ``ANN<T>`` interface analog).
"""

from __future__ import annotations

import dataclasses
import json
import pathlib
import re
import time
from typing import Any, Callable, Dict, List

import numpy as np

from raft_tpu.bench.datasets import METRICS
from raft_tpu.core.logger import warn as _log_warn
from raft_tpu.io import read_bin
from raft_tpu.utils.recall import eval_recall


@dataclasses.dataclass
class AlgoWrapper:
    """The ``ANN<T>`` interface (``ann_types.hpp:79-93``): build once,
    search per search-param set. ``save``/``load`` (optional) enable the
    reference harness's build/search separation with on-disk index files
    (``benchmark.hpp`` build phase saves, search phase loads) — a rerun
    on the same dataset+build-params reloads instead of rebuilding."""

    name: str
    build: Callable[..., Any]                 # (base, metric, **params) -> index
    search: Callable[..., Any]                # (index, queries, k, **params) -> (d, i)
    save: Callable[..., None] = None          # (index, path)
    load: Callable[..., Any] = None           # (path, base, metric, **params) -> index


def _brute_force_build(base, metric, **params):
    from raft_tpu.neighbors import brute_force

    return brute_force.build(None, base, metric)


def _brute_force_search(index, queries, k, **params):
    from raft_tpu.neighbors import brute_force

    return brute_force.search(None, index, queries, k)


def _ivf_flat_build(base, metric, *, n_lists=1024, **params):
    from raft_tpu.neighbors import ivf_flat

    p = ivf_flat.IvfFlatIndexParams(n_lists=n_lists, metric=metric, **params)
    return ivf_flat.build(None, p, base)


def _ivf_flat_search(index, queries, k, *, n_probes=32, **params):
    from raft_tpu.neighbors import ivf_flat

    p = ivf_flat.IvfFlatSearchParams(n_probes=n_probes, **params)
    return ivf_flat.search(None, p, index, queries, k)


def _ivf_pq_build(base, metric, *, n_lists=1024, pq_dim=0, pq_bits=8,
                  **params):
    from raft_tpu.neighbors import ivf_pq

    p = ivf_pq.IvfPqIndexParams(n_lists=n_lists, pq_dim=pq_dim,
                                pq_bits=pq_bits, metric=metric, **params)
    # keep the raw dataset alongside: the refine re-ranking pass needs it
    # (the reference's bench wrapper does the same for refine_ratio > 1)
    return {"index": ivf_pq.build(None, p, base), "base": base,
            "metric": metric}


def _search_with_refine(search_fn, bundle, queries, k, params,
                        refine_ratio):
    """Shared over-fetch + exact re-rank wrapper (the reference bench
    wrappers' refine_ratio semantics), used by the PQ and BQ entries."""
    from raft_tpu.neighbors import refine

    if refine_ratio > 1.0:
        k0 = max(k, int(k * refine_ratio))
        _, cand = search_fn(None, params, bundle["index"], queries, k0)
        return refine(None, bundle["base"], queries, cand, k,
                      bundle["metric"])
    return search_fn(None, params, bundle["index"], queries, k)


def _ivf_pq_search(bundle, queries, k, *, n_probes=32, refine_ratio=1.0,
                   **params):
    from raft_tpu.neighbors import ivf_pq

    p = ivf_pq.IvfPqSearchParams(n_probes=n_probes, **params)
    return _search_with_refine(ivf_pq.search, bundle, queries, k, p,
                               refine_ratio)


def _ivf_bq_build(base, metric, *, n_lists=1024, **params):
    from raft_tpu.neighbors import ivf_bq

    p = ivf_bq.IvfBqIndexParams(n_lists=n_lists, metric=metric, **params)
    return {"index": ivf_bq.build(None, p, base), "base": base,
            "metric": metric}


def _ivf_bq_search(bundle, queries, k, *, n_probes=32, refine_ratio=4.0,
                   **params):
    from raft_tpu.neighbors import ivf_bq

    p = ivf_bq.IvfBqSearchParams(n_probes=n_probes, **params)
    return _search_with_refine(ivf_bq.search, bundle, queries, k, p,
                               refine_ratio)


def _cagra_build(base, metric, *, graph_degree=64,
                 intermediate_graph_degree=128, **params):
    from raft_tpu.neighbors import cagra

    if "build_algo" in params:
        # native configs carry the enum value; reference confs spell it
        # graph_build_algo: "IVF_PQ"/"NN_DESCENT" (raft_benchmark.cu:153)
        params["build_algo"] = cagra.BuildAlgo(
            str(params["build_algo"]).lower())
    p = cagra.CagraIndexParams(
        graph_degree=graph_degree,
        intermediate_graph_degree=intermediate_graph_degree,
        metric=metric, **params)
    # keep the RAW base for refine — with storage_dtype the index holds
    # a quantized copy, and re-ranking against that recovers nothing
    return {"index": cagra.build(None, p, base), "base": base,
            "metric": metric}


def _cagra_search(bundle, queries, k, *, itopk_size=64, max_iterations=0,
                  refine_ratio=1.0, **params):
    from raft_tpu.neighbors import cagra

    p = cagra.CagraSearchParams(itopk_size=itopk_size,
                                max_iterations=max_iterations, **params)
    return _search_with_refine(cagra.search, bundle, queries, k, p,
                               refine_ratio)


def _quantized_build(base, metric, **params):
    from raft_tpu.neighbors import quantized

    if params:
        raise ValueError(f"raft_quantized build takes no params, got {params}")
    return quantized.build(None, base, metric)


def _quantized_search(index, queries, k, **params):
    from raft_tpu.neighbors import quantized

    if params:
        raise ValueError(f"raft_quantized search takes no params, got {params}")
    return quantized.search(None, index, queries, k)


def _ivf_flat_save(index, path):
    from raft_tpu.neighbors import ivf_flat

    ivf_flat.save(index, path)


def _ivf_flat_load(path, base, metric, **params):
    from raft_tpu.neighbors import ivf_flat

    return ivf_flat.load(None, path)


def _bundle_save(mod_name):
    def save_fn(bundle, path):
        import importlib

        importlib.import_module(mod_name).save(bundle["index"], path)
    return save_fn


def _bundle_load(mod_name):
    def load_fn(path, base, metric, **params):
        import importlib

        index = importlib.import_module(mod_name).load(None, path)
        return {"index": index, "base": base, "metric": metric}
    return load_fn


def _cagra_save(bundle, path):
    from raft_tpu.neighbors import cagra

    cagra.save(bundle["index"], path, include_dataset=True)


def _hnswlib_build(base, metric, *, M=16, ef_construction=200, **params):
    from raft_tpu.bench import hnsw_cpu

    if params:
        raise ValueError(f"hnswlib build takes M/ef_construction, "
                         f"got {params}")
    return hnsw_cpu.build(base, metric, M=M,
                          ef_construction=ef_construction)


def _hnswlib_search(index, queries, k, *, ef=64, **params):
    from raft_tpu.bench import hnsw_cpu

    if params:
        raise ValueError(f"hnswlib search takes ef, got {params}")
    return hnsw_cpu.search(index, np.asarray(queries), k, ef=ef)


def _hnswlib_save(index, path):
    from raft_tpu.bench import hnsw_cpu

    hnsw_cpu.save(index, path)


def _hnswlib_load(path, base, metric, **params):
    from raft_tpu.bench import hnsw_cpu

    return hnsw_cpu.load(path, base.shape[1], metric)


def _ivf_flat_cpu_build(base, metric, *, n_lists=1024, train_iters=10,
                        trainset_fraction=0.1, **params):
    from raft_tpu.bench import ivf_flat_cpu

    if params:
        raise ValueError(f"ivf_flat_cpu build takes n_lists/train_iters/"
                         f"trainset_fraction, got {params}")
    return ivf_flat_cpu.build(np.asarray(base), metric, n_lists=n_lists,
                              train_iters=train_iters,
                              trainset_fraction=trainset_fraction)


def _ivf_flat_cpu_search(index, queries, k, *, n_probes=32, **params):
    from raft_tpu.bench import ivf_flat_cpu

    if params:
        raise ValueError(f"ivf_flat_cpu search takes n_probes, "
                         f"got {params}")
    return ivf_flat_cpu.search(index, np.asarray(queries), k,
                               n_probes=n_probes)


def _ivf_flat_cpu_save(index, path):
    from raft_tpu.bench import ivf_flat_cpu

    ivf_flat_cpu.save(index, path)


def _ivf_flat_cpu_load(path, base, metric, **params):
    from raft_tpu.bench import ivf_flat_cpu

    return ivf_flat_cpu.load(path, base.shape[1], metric)


ALGO_REGISTRY: Dict[str, AlgoWrapper] = {
    "raft_brute_force": AlgoWrapper("raft_brute_force",
                                    _brute_force_build, _brute_force_search),
    "raft_ivf_flat": AlgoWrapper("raft_ivf_flat",
                                 _ivf_flat_build, _ivf_flat_search,
                                 _ivf_flat_save, _ivf_flat_load),
    "raft_ivf_pq": AlgoWrapper("raft_ivf_pq", _ivf_pq_build, _ivf_pq_search,
                               _bundle_save("raft_tpu.neighbors.ivf_pq"),
                               _bundle_load("raft_tpu.neighbors.ivf_pq")),
    "raft_ivf_bq": AlgoWrapper("raft_ivf_bq", _ivf_bq_build, _ivf_bq_search,
                               _bundle_save("raft_tpu.neighbors.ivf_bq"),
                               _bundle_load("raft_tpu.neighbors.ivf_bq")),
    "raft_cagra": AlgoWrapper("raft_cagra", _cagra_build, _cagra_search,
                              _cagra_save,
                              _bundle_load("raft_tpu.neighbors.cagra")),
    "raft_quantized": AlgoWrapper("raft_quantized",
                                  _quantized_build, _quantized_search),
    # the comparison baseline (the reference's hnswlib competitor role,
    # cpp/bench/ann/src/hnswlib/hnswlib_wrapper.h) — native C++ HNSW
    # on the host CPU, not a TPU algorithm
    "hnswlib": AlgoWrapper("hnswlib", _hnswlib_build, _hnswlib_search,
                           _hnswlib_save, _hnswlib_load),
    # second comparison series (the reference's FAISS competitor role,
    # cpp/bench/ann/src/faiss/faiss_benchmark.cu) — from-scratch numpy
    # IVF-Flat exact scan on the host CPU, not a TPU algorithm
    "ivf_flat_cpu": AlgoWrapper("ivf_flat_cpu", _ivf_flat_cpu_build,
                                _ivf_flat_cpu_search, _ivf_flat_cpu_save,
                                _ivf_flat_cpu_load),
}


def save_index_atomic(algo: AlgoWrapper, index: Any,
                      cache: pathlib.Path) -> None:
    """Write an index cache file atomically (tmp + rename) so a crash
    mid-save can never leave a half-written file at the cache path.
    Shared by the runner and the CPU prebuild script — the two must
    keep one write protocol."""
    cache.parent.mkdir(parents=True, exist_ok=True)
    tmp = cache.with_suffix(".tmp")
    algo.save(index, str(tmp))
    tmp.replace(cache)


def _index_cache_key(algo: str, dataset_name: str, n: int, dim: int,
                     metric_name: str,
                     build_params: Dict[str, Any]) -> str:
    """Deterministic readable filename for a (dataset, algo, build
    params) combination — the role of the reference's per-index
    ``index.file`` naming in its conf files. ``dataset_name`` is in the
    key so same-shaped datasets can't reuse each other's indexes."""
    parts = [algo, dataset_name, f"{n}x{dim}", metric_name]
    for key in sorted(build_params):
        parts.append(f"{key}={build_params[key]}")
    raw = "-".join(parts)
    return re.sub(r"[^A-Za-z0-9_.=-]", "_", raw)


def _block(x):
    """Wait for x AND fetch one element: ``block_until_ready`` is a
    no-op on relayed backends (axon), so completion must be anchored on
    a host fetch. The fetch is one element — negligible transfer."""
    import jax

    jax.block_until_ready(x)
    leaves = [l for l in jax.tree_util.tree_leaves(x)
              if hasattr(l, "ravel") and getattr(l, "size", 0)]
    if leaves:
        np.asarray(leaves[0].ravel()[:1])
    return x


# reference raft-ann-bench param spellings → this framework's
_BUILD_KEY_MAP = {
    "nlist": "n_lists",
    "niter": "kmeans_n_iters",
    "pq_dim": "pq_dim",
    "pq_bits": "pq_bits",
    "graph_degree": "graph_degree",
    "intermediate_graph_degree": "intermediate_graph_degree",
    "graph_build_algo": "build_algo",   # reference conf spelling
    "M": "M",                           # hnswlib spellings
    "efConstruction": "ef_construction",
}
_SEARCH_KEY_MAP = {
    "nprobe": "n_probes",
    "n_probes": "n_probes",
    "itopk": "itopk_size",
    "itopk_size": "itopk_size",
    "search_width": "search_width",
    "max_iterations": "max_iterations",
    "refine_ratio": "refine_ratio",
    "ef": "ef",                         # hnswlib spelling
}
_ALGO_ALIASES = {"raft_bfknn": "raft_brute_force"}


def normalize_config(config: Dict[str, Any]) -> Dict[str, Any]:
    """Accept the reference's ``conf/*.json`` schema (an ``index`` list
    with ``build_param``/``search_params``, ``run/conf/`` files) as well
    as the native ``algos`` schema; translate raft and hnswlib param
    spellings (nlist/nprobe/itopk/ratio/M/efConstruction/ef/…) and drop
    competitor entries with no wrapper here (faiss/ggnn benchmark OTHER
    libraries; hnswlib maps onto the native C++ baseline)."""
    if "algos" in config:
        return config
    if "index" not in config:
        raise ValueError("config needs an 'algos' or 'index' section")
    algos = []
    for entry in config["index"]:
        algo = _ALGO_ALIASES.get(entry["algo"], entry["algo"])
        if algo not in ALGO_REGISTRY:
            continue  # competitor wrapper (hnswlib/faiss/...)
        build = {}
        for key, val in entry.get("build_param", {}).items():
            if key == "ratio":  # subsample ratio → trainset fraction
                build["kmeans_trainset_fraction"] = 1.0 / max(val, 1)
            elif key in _BUILD_KEY_MAP:
                build[_BUILD_KEY_MAP[key]] = val
        search = []
        for sp in entry.get("search_params", [{}]):
            search.append({_SEARCH_KEY_MAP[k]: v for k, v in sp.items()
                           if k in _SEARCH_KEY_MAP})
        algos.append({"name": algo, "build": build, "search": search})
    if not algos:
        raise ValueError("config contained no raft algorithms")
    return {"algos": algos}


def run_benchmark(
    dataset_dir,
    config: Dict[str, Any],
    out_dir,
    *,
    k: int = 10,
    batch_size: int = 0,
    max_base_rows: int = 0,
    search_iters: int = 3,
    force_rebuild: bool = False,
    resume: bool = False,
    only_algos=None,
    require_cached_index: bool = False,
) -> List[Dict[str, Any]]:
    """Run every (algo, build-params, search-params) combination in
    ``config`` against the dataset tree; write JSON-lines results.

    ``resume=True`` appends to an existing ``results.jsonl`` and skips
    combinations already recorded there (same dataset/algo/build/
    search/k/batch/search_iters), so an interrupted sweep (this harness
    drives a TPU through a relay that can die mid-run) continues where
    it stopped instead of redoing finished measurements. ``only_algos``
    (iterable of names) restricts the sweep to those algo entries — the
    piece-at-a-time pattern: one process per family bounds what a crash
    can lose. ``require_cached_index=True`` raises instead of building
    when a saveable algo's index cache misses — the guard for runs
    where an index build on the measurement device is not acceptable
    (e.g. the multi-compile 1M builds that wedge the TPU relay).

    Config schema (the reference's ``conf/*.json`` shape)::

        {"algos": [{"name": "raft_ivf_flat",
                    "build": {"n_lists": 1024},
                    "search": [{"n_probes": 16}, {"n_probes": 64}]}]}
    """
    if search_iters < 1:
        raise ValueError(f"search_iters must be >= 1, got {search_iters}")
    if force_rebuild and require_cached_index:
        raise ValueError(
            "force_rebuild and require_cached_index are contradictory: "
            "one demands a fresh build, the other forbids building")
    config = normalize_config(config)
    dataset_dir = pathlib.Path(dataset_dir)
    out_dir = pathlib.Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)

    base = read_bin(dataset_dir / "base.fbin")
    queries = read_bin(dataset_dir / "query.fbin")
    if queries.shape[0] == 0:
        raise ValueError("query set is empty — qps would be undefined")
    gt = read_bin(dataset_dir / "groundtruth.neighbors.ibin")
    metric_name = (dataset_dir / "metric.txt").read_text().strip() \
        if (dataset_dir / "metric.txt").exists() else "euclidean"
    metric = METRICS[metric_name]
    if max_base_rows:
        base = base[:max_base_rows]
        gt = None  # groundtruth invalidated by truncation
    if batch_size <= 0:
        batch_size = queries.shape[0]

    def _combo_key(algo_name, build_params, search_params):
        return json.dumps(
            [dataset_dir.name, int(max_base_rows), algo_name,
             build_params, search_params, k, batch_size, search_iters],
            sort_keys=True)

    if only_algos is not None:
        only_algos = {a.strip() for a in only_algos}
        in_config = {a["name"] for a in config["algos"]}
        unknown = only_algos - in_config
        if unknown:
            raise ValueError(
                f"only_algos entries {sorted(unknown)} not in the "
                f"config (it has {sorted(in_config)})")

    done = set()
    results = []
    out_file = out_dir / "results.jsonl"
    import jax

    backend = jax.default_backend()

    def _same_sweep(row):
        """Row belongs to this sweep's identity (dataset, depth, k,
        batch, iters) — the shared predicate for both the resume
        done-guard and the legacy-row cleanup.  .get defaults: rows
        written before the search_iters / max_base_rows fields existed
        carry the values those defaults had (3 / 0) — without this,
        resuming over a legacy results.jsonl re-measures every
        combination and the export doubles up (ADVICE r3)."""
        return (row.get("dataset") == dataset_dir.name
                and row.get("max_base_rows", 0) == int(max_base_rows)
                and row.get("k") == k
                and row.get("batch_size") == batch_size
                and row.get("search_iters", 3) == search_iters)

    # combos whose pre-backend-field rows this run has superseded: once
    # the replacement row is FLUSHED, the legacy row is dropped in the
    # end-of-run rewrite below (never before — a crash between an
    # eager rewrite and the re-measurement would lose measured data)
    superseded = set()
    if resume and out_file.exists():
        legacy_seen = set()
        with open(out_file) as fh:
            for line in fh:
                try:
                    row = json.loads(line)
                except json.JSONDecodeError:
                    continue  # truncated tail from a killed run
                if not _same_sweep(row):
                    continue
                # a row measured on another backend (e.g. a CPU
                # rehearsal sharing the out_dir) must not satisfy this
                # sweep; a missing backend field does NOT imply this
                # backend (unlike search_iters there is no known
                # default), so legacy rows are re-measured once and the
                # stale line cleaned up after its replacement lands
                if "backend" not in row:
                    legacy_seen.add(_combo_key(row.get("algo"),
                                               row.get("build_params"),
                                               row.get("search_params")))
                elif row.get("backend") == backend:
                    done.add(_combo_key(row.get("algo"),
                                        row.get("build_params"),
                                        row.get("search_params")))
                    # returned/printed rows honor only_algos: a
                    # per-family step must not replay other families
                    if (only_algos is None
                            or row.get("algo") in only_algos):
                        results.append(row)
        # a legacy row whose combo already has a backend-bearing row is
        # provably superseded even though this run won't re-measure it
        # (e.g. the run that replaced it crashed before its own cleanup)
        superseded |= legacy_seen & done
        if done:
            _log_warn("resume: %d finished combination(s) found in %s",
                      len(done), out_file)
    with open(out_file, "a" if resume else "w") as fh:
        for algo_cfg in config["algos"]:
            if only_algos is not None and \
                    algo_cfg["name"] not in only_algos:
                continue
            algo = ALGO_REGISTRY[algo_cfg["name"]]
            build_params = algo_cfg.get("build", {})
            pending = [sp for sp in algo_cfg.get("search", [{}])
                       if _combo_key(algo.name, build_params, sp)
                       not in done]
            if not pending:
                continue  # every search combo finished in a prior run
            from raft_tpu.core import interruptible

            interruptible.yield_()  # cancellation point per algo entry
            if algo.name == "hnswlib":
                # the CPU baseline needs the native toolchain; a host
                # without it (bare wheel install) must lose the
                # comparison series, not the whole sweep
                from raft_tpu.bench import hnsw_cpu

                if not hnsw_cpu.available():
                    _log_warn("skipping hnswlib: native HNSW library "
                              "unavailable (no C++ toolchain?)")
                    continue
            cache = None
            if algo.save is not None and algo.load is not None:
                key = _index_cache_key(
                    algo.name, dataset_dir.name, base.shape[0],
                    base.shape[1], metric_name, build_params)
                cache = out_dir / "indexes" / f"{key}.bin"
            index = None
            build_cached = False
            t0 = time.perf_counter()
            if (cache is not None and cache.exists()
                    and not force_rebuild):
                try:
                    index = _block(algo.load(str(cache), base, metric,
                                             **build_params))
                    build_cached = True
                except Exception as e:  # noqa: BLE001 — truncated file
                    # from a crash mid-save: fall through to a fresh
                    # build, but say so (a silent fall-through would
                    # hide a never-hitting cache)
                    _log_warn("index cache load failed (%s: %s) — "
                              "rebuilding", cache.name, e)
                    index = None
            if index is None:
                if require_cached_index and cache is not None:
                    raise RuntimeError(
                        f"require_cached_index: no cached index for "
                        f"{algo.name} {build_params} (expected "
                        f"{cache}); prebuild it off-device first")
                index = _block(algo.build(base, metric, **build_params))
            build_s = time.perf_counter() - t0
            if cache is not None and not build_cached:
                # save AFTER timing: the write (which for cagra includes
                # the dataset copy) must not inflate build_seconds, and
                # a save failure must not discard the finished build
                try:
                    save_index_atomic(algo, index, cache)
                except Exception as e:  # noqa: BLE001
                    _log_warn("index cache save failed (%s: %s) — "
                              "continuing without cache", cache.name, e)

            for search_params in pending:
                interruptible.yield_()  # cancellation point per combo
                # warm (compile) every batch shape, including a ragged
                # final batch, so no compile lands in the timed loop
                _block(algo.search(index, queries[:batch_size], k,
                                   **search_params))
                tail = queries.shape[0] % batch_size
                if tail:
                    _block(algo.search(index, queries[-tail:], k,
                                       **search_params))
                # recall pass (untimed): fetch every batch's indices
                all_i = []
                for s in range(0, queries.shape[0], batch_size):
                    _, i = algo.search(index, queries[s : s + batch_size],
                                       k, **search_params)
                    all_i.append(np.asarray(i))
                # timed pass: dispatch everything, sync once at the end —
                # per-batch fetches would serialize the device pipeline
                # behind the host round-trip (65 ms each on the relay)
                t0 = time.perf_counter()
                n_done = 0
                out = None
                for _ in range(search_iters):
                    for s in range(0, queries.shape[0], batch_size):
                        qb = queries[s : s + batch_size]
                        out = algo.search(index, qb, k, **search_params)
                        n_done += qb.shape[0]
                _block(out)
                dt = time.perf_counter() - t0
                qps = n_done / dt
                got = np.concatenate(all_i)[: queries.shape[0]]
                rec = (eval_recall(gt[:, :k], got)[0]
                       if gt is not None else float("nan"))
                row = {
                    "dataset": dataset_dir.name,
                    "max_base_rows": int(max_base_rows),
                    "backend": backend,
                    "algo": algo.name,
                    "build_params": build_params,
                    "search_params": search_params,
                    "k": k,
                    "batch_size": batch_size,
                    "search_iters": search_iters,
                    "build_seconds": round(build_s, 4),
                    "build_cached": build_cached,
                    "qps": round(qps, 2),
                    "recall": None if np.isnan(rec) else round(float(rec), 4),
                }
                results.append(row)
                fh.write(json.dumps(row) + "\n")
                fh.flush()
                superseded.add(_combo_key(algo.name, build_params,
                                          search_params))
    if resume and superseded:
        _drop_superseded_legacy_rows(out_file, _same_sweep, _combo_key,
                                     superseded)
    return results


def _drop_superseded_legacy_rows(out_file, same_sweep, combo_key,
                                 superseded) -> None:
    """Rewrite ``results.jsonl`` without pre-backend-field rows whose
    combos were re-measured this run.  Runs only AFTER the replacement
    rows are flushed: a legacy row's backend is unknowable, so resume
    re-measures its combo, and keeping both would double up the
    export/plot — but dropping before the replacement lands would turn
    a mid-sweep crash into silent data loss."""
    kept, dropped = [], 0
    for line in out_file.read_text().splitlines(keepends=True):
        try:
            row = json.loads(line)
        except json.JSONDecodeError:
            continue  # truncated tail from a killed run
        if ("backend" not in row and same_sweep(row)
                and combo_key(row.get("algo"), row.get("build_params"),
                              row.get("search_params")) in superseded):
            dropped += 1
            continue
        kept.append(line)
    if dropped:
        tmp = out_file.with_suffix(".jsonl.tmp")
        tmp.write_text("".join(kept))
        tmp.replace(out_file)
        _log_warn("resume: dropped %d pre-backend-field row(s) from %s "
                  "(re-measured this run with the backend field)",
                  dropped, out_file)


def _load_rows(results_dir: pathlib.Path) -> List[Dict[str, Any]]:
    rows = []
    for f in sorted(results_dir.glob("*.jsonl")):
        for line in f.read_text().splitlines():
            if line.strip():
                rows.append(json.loads(line))
    return rows


def export_csv(results_dir, out_path=None) -> pathlib.Path:
    """JSON-lines → CSV — the ``data_export`` subcommand."""
    import csv

    results_dir = pathlib.Path(results_dir)
    out_path = pathlib.Path(out_path or results_dir / "results.csv")
    rows = _load_rows(results_dir)
    if not rows:
        raise FileNotFoundError(f"no results under {results_dir}")
    cols = ["dataset", "backend", "algo", "build_params", "search_params",
            "k", "batch_size", "search_iters", "build_seconds",
            "build_cached", "qps", "recall"]
    with open(out_path, "w", newline="") as fh:
        w = csv.DictWriter(fh, fieldnames=cols)
        w.writeheader()
        for r in rows:
            # .get: rows from pre-cache runs lack build_cached
            w.writerow({c: json.dumps(r.get(c)) if isinstance(r.get(c), dict)
                        else r.get(c) for c in cols})
    return out_path


def plot_results(results_dir, out_path=None) -> pathlib.Path:
    """Recall-vs-QPS pareto plot — the ``plot`` subcommand
    (``plot/__main__.py``; the reference's published artifact shape)."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    results_dir = pathlib.Path(results_dir)
    out_path = pathlib.Path(out_path or results_dir / "recall_vs_qps.png")
    rows = _load_rows(results_dir)
    # rows measured at different search_iters (smoke vs full depth) are
    # distinct series — mixing them would zigzag the pareto line
    depths = {r.get("search_iters") for r in rows}
    series = sorted({(r["algo"], r.get("search_iters")) for r in rows},
                    key=lambda t: (t[0], str(t[1])))
    fig, ax = plt.subplots(figsize=(7, 5))
    for algo, depth in series:
        label = algo if len(depths) == 1 else f"{algo} (iters={depth})"
        pts = sorted(
            [(r["recall"], r["qps"]) for r in rows
             if r["algo"] == algo and r.get("search_iters") == depth
             and r["recall"] is not None]
        )
        if pts:
            ax.plot([p[0] for p in pts], [p[1] for p in pts],
                    marker="o", label=label)
    ax.set_xlabel(f"recall@k")
    ax.set_ylabel("QPS")
    ax.set_yscale("log")
    ax.legend()
    ax.grid(True, alpha=0.3)
    fig.tight_layout()
    fig.savefig(out_path, dpi=120)
    plt.close(fig)
    return out_path
