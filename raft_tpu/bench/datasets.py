"""Dataset preparation — analog of ``raft-ann-bench/get_dataset``
(hdf5 → big-ann bin conversion) plus a synthetic generator for
air-gapped runs (this environment has no egress; the reference
downloads ann-benchmarks HDF5 files).

Layout convention (the reference's, ``run/__main__.py``):
``<dir>/<name>/base.fbin``, ``query.fbin``, ``groundtruth.neighbors.ibin``,
``groundtruth.distances.fbin``.
"""

from __future__ import annotations

import pathlib
from typing import Optional

import numpy as np

from raft_tpu.distance.types import DistanceType
from raft_tpu.io import write_bin

# metric.txt name → framework metric; shared by the runner and the
# groundtruth generator so the accepted sets can't drift apart
METRICS = {
    "euclidean": DistanceType.L2SqrtExpanded,
    "sqeuclidean": DistanceType.L2Expanded,
    "inner_product": DistanceType.InnerProduct,
    "angular": DistanceType.CosineExpanded,
}


def _groundtruth(base: np.ndarray, queries: np.ndarray, k: int,
                 metric: str = "euclidean"):
    """Exact groundtruth via the framework's own brute force (on the
    default backend)."""
    from raft_tpu.neighbors import brute_force

    d, i = brute_force.knn(None, base, queries, k, METRICS[metric])
    return np.asarray(d), np.asarray(i)


def make_dataset(
    out_dir,
    name: str,
    n: int = 100_000,
    dim: int = 128,
    n_queries: int = 1000,
    k: int = 100,
    metric: str = "euclidean",
    seed: int = 0,
    kind: str = "blobs",
) -> pathlib.Path:
    """Generate a synthetic dataset tree with exact groundtruth.

    ``kind``: "random" (iid gaussian — worst case for ANN) or "blobs"
    (clustered — the realistic regime)."""
    rng = np.random.default_rng(seed)
    if kind == "random":
        base = rng.standard_normal((n, dim)).astype(np.float32)
        queries = rng.standard_normal((n_queries, dim)).astype(np.float32)
    elif kind == "blobs":
        n_centers = max(10, int(np.sqrt(n) / 4))
        centers = rng.standard_normal((n_centers, dim)).astype(np.float32) * 4
        who = rng.integers(0, n_centers, n)
        base = centers[who] + rng.standard_normal((n, dim)).astype(np.float32)
        whoq = rng.integers(0, n_centers, n_queries)
        queries = centers[whoq] + rng.standard_normal(
            (n_queries, dim)).astype(np.float32)
    else:
        raise ValueError(f"unknown dataset kind {kind!r}")

    root = pathlib.Path(out_dir) / name
    root.mkdir(parents=True, exist_ok=True)
    write_bin(root / "base.fbin", base)
    write_bin(root / "query.fbin", queries)
    gd, gi = _groundtruth(base, queries, k, metric)
    write_bin(root / "groundtruth.neighbors.ibin", gi.astype(np.int32))
    write_bin(root / "groundtruth.distances.fbin", gd.astype(np.float32))
    (root / "metric.txt").write_text(metric + "\n")
    return root


def convert_hdf5(hdf5_path, out_dir, name: Optional[str] = None) -> pathlib.Path:
    """Convert an ann-benchmarks HDF5 file (train/test/neighbors/distances
    datasets) into the bin-file tree — ``get_dataset/__main__.py``'s
    ``hdf5_to_fbin`` role."""
    import h5py

    hdf5_path = pathlib.Path(hdf5_path)
    name = name or hdf5_path.stem
    root = pathlib.Path(out_dir) / name
    root.mkdir(parents=True, exist_ok=True)
    with h5py.File(hdf5_path, "r") as f:
        write_bin(root / "base.fbin", np.asarray(f["train"], np.float32))
        write_bin(root / "query.fbin", np.asarray(f["test"], np.float32))
        if "neighbors" in f:
            write_bin(root / "groundtruth.neighbors.ibin",
                      np.asarray(f["neighbors"], np.int32))
        if "distances" in f:
            write_bin(root / "groundtruth.distances.fbin",
                      np.asarray(f["distances"], np.float32))
        metric = f.attrs.get("distance", "euclidean")
        if isinstance(metric, bytes):
            metric = metric.decode()
    (root / "metric.txt").write_text(str(metric) + "\n")
    return root
