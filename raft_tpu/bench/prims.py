"""Per-primitive micro-benchmarks — the ``cpp/bench/prims`` analog.

Each bench reports wall-clock ms plus achieved GB/s (against the bytes
the primitive must move through HBM) and MFU (against the configured
matmul peak), so per-primitive regressions and anomalies (e.g. a bf16
path running slower than f32) are visible in isolation rather than
buried in an end-to-end number. Reference: the gbench suite under
``cpp/bench/prims/`` (e.g. ``matrix/select_k.cu``).

Run::

    python -m raft_tpu.bench.prims [--filter substr] [--size tiny|small|full]
        [--out results.jsonl] [--seconds 10]

Output: one JSON line per bench on stdout (and optionally appended to
``--out``). Peaks default to TPU v5e (197 TFLOP/s bf16 matmul,
819 GB/s HBM) and are overridable via RAFT_TPU_PEAK_FLOPS /
RAFT_TPU_PEAK_BW for other chips; on CPU the ratios are still printed
but are meaningful only relative to each other.

Timing is fetch-anchored and pipelined exactly like ``bench.py``:
``block_until_ready`` does not block on relayed backends, so each
measurement dispatches a run of iterations and fetches one element at
the end.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import time
from typing import Callable, Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

PEAK_FLOPS = float(os.environ.get("RAFT_TPU_PEAK_FLOPS", 197e12))
PEAK_BW = float(os.environ.get("RAFT_TPU_PEAK_BW", 819e9))


def _fetch(out) -> None:
    """Anchor completion on a host fetch of one element."""
    leaf = jax.tree_util.tree_leaves(out)[0]
    np.asarray(leaf.ravel()[:1])


def timeit_stats(fn: Callable[[], object], budget_s: float = 10.0) -> Dict:
    """Pipelined, fetch-anchored timing: dispatch a run of iterations
    and fetch once, so per-call relay round-trips amortize out. This is
    THE timing methodology for the repo — ``bench.py`` and the prims
    suite both call it, so a fix to the anchor or pipe sizing lands in
    both. Returns best/median seconds-per-iteration plus the schedule
    used."""
    _fetch(fn())  # compile + warm
    t0 = time.perf_counter()
    _fetch(fn())
    est = max(time.perf_counter() - t0, 1e-5)
    pipe = max(3, min(50, int(budget_s / 2 / est)))
    rates = []
    t_meas = time.perf_counter()
    while len(rates) < 6 and (
        not rates or time.perf_counter() - t_meas < budget_s
    ):
        t0 = time.perf_counter()
        out = None
        for _ in range(pipe):
            out = fn()
        _fetch(out)
        rates.append((time.perf_counter() - t0) / pipe)
    return {
        "best_s": min(rates),
        "median_s": sorted(rates)[len(rates) // 2],
        "single_iter_est_s": est,
        "pipe": pipe,
        "batches": len(rates),
    }


def timeit(fn: Callable[[], object], budget_s: float = 10.0) -> float:
    """Best steady-state seconds/iteration (see :func:`timeit_stats`)."""
    return timeit_stats(fn, budget_s)["best_s"]


def loop_queries(fn: Callable, queries, m: int) -> Callable[[], object]:
    """Wrap a ``(d, i) = fn(q)`` search in an m-iteration in-program
    loop whose carried query tile gets a data-dependent perturbation
    each step — XLA can neither hoist nor CSE the body, so one dispatch
    executes m real searches back-to-back."""
    import jax.numpy as jnp

    @jax.jit
    def run(q0):
        def body(_, carry):
            acc, q = carry
            d, _ = fn(q)
            pert = jnp.tanh(jnp.nanmin(d)).astype(jnp.float32) * 1e-6
            return (acc + pert, (q0 + pert).astype(q0.dtype))

        acc, _ = jax.lax.fori_loop(0, m, body, (jnp.float32(0.0), q0))
        return acc

    return lambda: run(queries)


# Slope pass spreads per dataset dtype, shared by bench.py and the
# profile scripts so a jitter recalibration can't drift between them.
# Calibration (r3): the relay's dispatch jitter is up to ~4 ms; a
# 2-vs-8 spread at f32 (~0.9 ms/pass) was inside it, and bf16 passes
# are ~2x faster, so bf16 gets twice the passes.
SLOPE_PASSES = {"float32": (2, 16), "bfloat16": (2, 32)}


def slope_passes(dtype) -> tuple:
    """(low, high) in-program pass counts for slope timing of a
    dataset-streaming kernel at ``dtype`` (jnp/np dtype, scalar type,
    or name)."""
    name = np.dtype(dtype).name
    return SLOPE_PASSES.get(name, SLOPE_PASSES["float32"])


def timeit_slope(make_fn: Callable[[int], Callable[[], object]],
                 m1: int, m2: int, reps: int = 4) -> Dict:
    """Per-iteration seconds from the slope between an m1- and an
    m2-iteration in-program loop: slope = (T(m2) - T(m1)) / (m2 - m1).
    Cancels per-dispatch overhead entirely — required on relayed
    backends, where a ~4 ms serialized dispatch gap (measured round 2)
    floors every single-dispatch number regardless of kernel cost.
    Uses best-of-``reps`` walls for each loop length."""
    f1, f2 = make_fn(m1), make_fn(m2)

    def best_wall(f):
        _fetch(f())  # compile + warm
        walls = []
        for _ in range(reps):
            t0 = time.perf_counter()
            _fetch(f())
            walls.append(time.perf_counter() - t0)
        return min(walls)

    t1, t2 = best_wall(f1), best_wall(f2)
    return {
        "slope_s": (t2 - t1) / (m2 - m1),
        "t1_s": t1,
        "t2_s": t2,
        "m1": m1,
        "m2": m2,
    }


@dataclasses.dataclass
class Prim:
    """One registered micro-bench: ``make(size)`` returns
    ``(run_fn, bytes_moved, flops, shape_desc)``."""

    name: str
    make: Callable[[str], tuple]


_REGISTRY: List[Prim] = []


def _register(name: str):
    def deco(fn):
        _REGISTRY.append(Prim(name, fn))
        return fn
    return deco


def _dims(size: str, tiny, small, full):
    return {"tiny": tiny, "small": small, "full": full}[size]


# ---------------------------------------------------------------------------
# the primitives
# ---------------------------------------------------------------------------


def _interp() -> bool:
    """Pallas kernels need interpret mode off-TPU; timings there are
    only smoke-level, but the suite stays runnable in CPU CI."""
    return jax.default_backend() != "tpu"


@_register("stream_read_f32")
def _stream_read(size: str):
    """Pure HBM stream ceiling: Pallas row-sum over a large array.
    This is the number every bandwidth-bound bench below is judged
    against (the 'prove the ceiling' probe)."""
    from raft_tpu.ops.fused_topk import stream_read_sum

    n, d = _dims(size, (1 << 14, 128), (1 << 18, 128), (1 << 22, 128))
    x = jax.random.normal(jax.random.key(0), (n, d), jnp.float32)
    jax.block_until_ready(x)
    return (lambda: stream_read_sum(x, interpret=_interp()),
            n * d * 4, n * d, f"{n}x{d} f32")


@_register("stream_read_f32_xl")
def _stream_read_xl(size: str):
    """The anomaly-resolver probe (VERDICT r2 weak #3): a working set
    ≥ 4 GB at --size full, so no cache level can flatter the slope —
    an above-roofline reading here would mean the methodology itself
    is broken, not reuse. tiny/small stay CI-sized."""
    from raft_tpu.ops.fused_topk import stream_read_sum

    n, d = _dims(size, (1 << 14, 128), (1 << 18, 128), (1 << 23, 128))
    x = jax.random.normal(jax.random.key(3), (n, d), jnp.float32)
    jax.block_until_ready(x)
    return (lambda: stream_read_sum(x, interpret=_interp()),
            n * d * 4, n * d, f"{n}x{d} f32 ({n * d * 4 / 1e9:.1f} GB)")


@_register("stream_read_bf16")
def _stream_read_bf16(size: str):
    from raft_tpu.ops.fused_topk import stream_read_sum

    n, d = _dims(size, (1 << 14, 128), (1 << 18, 128), (1 << 22, 128))
    x = jax.random.normal(jax.random.key(0), (n, d), jnp.bfloat16)
    jax.block_until_ready(x)
    return (lambda: stream_read_sum(x, interpret=_interp()),
            n * d * 2, n * d, f"{n}x{d} bf16")


@_register("pairwise_l2")
def _pairwise_l2(size: str):
    from raft_tpu.distance import pairwise_distance
    from raft_tpu.distance.types import DistanceType

    m, n, d = _dims(size, (256, 256, 64), (2048, 2048, 128),
                    (8192, 8192, 128))
    kx, ky = jax.random.split(jax.random.key(1))
    x = jax.random.normal(kx, (m, d), jnp.float32)
    y = jax.random.normal(ky, (n, d), jnp.float32)
    jax.block_until_ready((x, y))
    # NB every run fn below receives its arrays as jit ARGUMENTS (not
    # zero-arg closures): captured arrays become compile-time constants
    # and XLA constant-folds the whole benchmark away
    run = jax.jit(lambda a, b: pairwise_distance(
        None, a, b, DistanceType.L2Expanded))
    return (lambda: run(x, y), (m * d + n * d + m * n) * 4, 2 * m * n * d,
            f"{m}x{n}x{d} f32")


@_register("select_k_xla")
def _select_k_xla(size: str):
    from raft_tpu.matrix.select_k import select_k

    b, n, k = _dims(size, (16, 1 << 12, 32), (64, 1 << 16, 64),
                    (64, 1 << 20, 64))
    v = jax.random.normal(jax.random.key(2), (b, n), jnp.float32)
    jax.block_until_ready(v)
    return (lambda: select_k(None, v, k), b * n * 4, 0, f"{b}x{n} k={k}")


@_register("select_k_pallas")
def _select_k_pallas(size: str):
    from raft_tpu.ops.fused_topk import select_k_tiles

    b, n, k = _dims(size, (16, 1 << 12, 32), (64, 1 << 16, 64),
                    (64, 1 << 20, 64))
    v = jax.random.normal(jax.random.key(2), (b, n), jnp.float32)
    jax.block_until_ready(v)
    return (lambda: select_k_tiles(v, k, interpret=_interp()),
            b * n * 4, 0, f"{b}x{n} k={k}")


@_register("fused_knn_f32")
def _fused_knn_f32(size: str):
    return _fused_knn_case(size, jnp.float32)


@_register("fused_knn_bf16")
def _fused_knn_bf16(size: str):
    return _fused_knn_case(size, jnp.bfloat16)


def _fused_knn_case(size: str, dtype):
    from raft_tpu.distance.types import DistanceType
    from raft_tpu.ops.fused_topk import fused_knn

    n, d, q, k = _dims(size, (1 << 13, 128, 10, 10),
                       (1 << 17, 128, 10, 10), (1 << 20, 128, 10, 10))
    kd, kq = jax.random.split(jax.random.key(3))
    ds = jax.random.normal(kd, (n, d), jnp.float32)
    norms = jnp.sum(jnp.square(ds), axis=1)
    ds = ds.astype(dtype)
    qs = jax.random.normal(kq, (q, d), jnp.float32)
    jax.block_until_ready((ds, qs, norms))
    itemsize = 2 if dtype == jnp.bfloat16 else 4
    return (lambda: fused_knn(qs, ds, k, DistanceType.L2Expanded,
                              dataset_norms=norms, interpret=_interp()),
            n * d * itemsize, 2 * q * n * d,
            f"{n}x{d} {np.dtype(dtype).name} q={q} k={k}")


@_register("pq_score_onehot")
def _pq_score_onehot(size: str):
    return _pq_score_case(size, "onehot")


@_register("pq_score_gather")
def _pq_score_gather(size: str):
    return _pq_score_case(size, "gather")


@_register("pq_score_select4")
def _pq_score_select4(size: str):
    """The masked-sum path at its design point: 4-bit codes (J=16)."""
    return _pq_score_case(size, "select", J=16)


def _pq_score_case(size: str, mode: str, J: int = 256):
    from raft_tpu.neighbors.ivf_pq import score_fn

    q, m, s, _ = _dims(size, (4, 1 << 10, 16, 256), (10, 1 << 15, 64, 256),
                       (10, 1 << 17, 64, 256))
    kl, kr = jax.random.split(jax.random.key(4))
    lut = jax.random.normal(kl, (q, s, J), jnp.float32)
    rows = jax.random.randint(kr, (q, m, s), 0, J, jnp.int32).astype(jnp.uint8)
    jax.block_until_ready((lut, rows))
    jscore = jax.jit(score_fn(mode, J))
    run = lambda: jscore(lut, rows)  # noqa: E731
    # effective flops: the useful work is q·m·s adds; the one-hot and
    # select paths physically perform ~2·q·m·s·J ops — report the
    # physical number so MFU reflects what the units execute
    flops = 2 * q * m * s * J if mode in ("onehot", "select") else q * m * s
    nbytes = q * m * s + q * s * J * 4 + q * m * 4  # codes + LUT + out
    return (run, nbytes, flops, f"q={q} m={m} s={s} J={J}")


@_register("bq_score")
def _bq_score(size: str):
    """IVF-BQ sign-code scoring core (int32 word unpack + fused level
    GEMMs) — the lookup-free alternative to the pq_score family (the
    rank-major estimate path; the fused engines score the packed
    words directly by XOR+popcount)."""
    from raft_tpu.neighbors.ivf_bq import _unpack_pm1

    q, m, d, bits = _dims(size, (4, 1 << 10, 64, 2), (10, 1 << 15, 128, 2),
                          (10, 1 << 17, 128, 2))
    kq_, kb = jax.random.split(jax.random.key(12))
    qrot = jax.random.normal(kq_, (q, d), jnp.float32)
    words = jax.random.randint(kb, (q, m, bits * d // 32),
                               jnp.iinfo(jnp.int32).min,
                               jnp.iinfo(jnp.int32).max, jnp.int32)
    a = jnp.abs(jax.random.normal(kb, (q, m, bits), jnp.float32))
    jax.block_until_ready((qrot, words, a))

    @jax.jit
    def score(qr, wo, aa):
        pm1 = _unpack_pm1(wo).reshape(q, m, bits, d)
        crosses = jnp.einsum("qd,qmld->qml", qr.astype(jnp.bfloat16), pm1,
                             preferred_element_type=jnp.float32)
        return jnp.sum(aa * crosses, axis=-1)

    nbytes = q * m * bits * d // 8 + q * d * 4 + q * m * 4
    return (lambda: score(qrot, words, a), nbytes, 2 * q * m * bits * d,
            f"q={q} m={m} d={d} bits={bits}")


@_register("fused_l2_nn")
def _fused_l2_nn(size: str):
    from raft_tpu.distance.fused_l2_nn import fused_l2_nn_argmin

    n, c, d = _dims(size, (1 << 12, 256, 64), (1 << 17, 1024, 128),
                    (1 << 18, 1024, 128))
    kx, kc = jax.random.split(jax.random.key(5))
    x = jax.random.normal(kx, (n, d), jnp.float32)
    cent = jax.random.normal(kc, (c, d), jnp.float32)
    jax.block_until_ready((x, cent))
    return (lambda: fused_l2_nn_argmin(None, x, cent),
            n * d * 4, 2 * n * c * d, f"{n}x{c}x{d} f32")


@_register("norm_rows")
def _norm_rows(size: str):
    """Row L2 norms (``cpp/bench/prims/linalg`` norm family)."""
    from raft_tpu.linalg import L2Norm, norm

    n, d = _dims(size, (1 << 13, 128), (1 << 18, 128), (1 << 20, 128))
    x = jax.random.normal(jax.random.key(6), (n, d), jnp.float32)
    jax.block_until_ready(x)
    jn = jax.jit(lambda v: norm(None, v, L2Norm))
    return (lambda: jn(x), n * d * 4, 2 * n * d, f"{n}x{d} f32")


@_register("matrix_gather")
def _matrix_gather(size: str):
    """Row gather (``cpp/bench/prims/matrix/gather.cu``) — the op whose
    TPU scalar-core lowering motivated the gather-free redesigns."""
    n, m, d = _dims(size, (1 << 13, 1 << 10, 128), (1 << 18, 1 << 15, 128),
                    (1 << 20, 1 << 17, 128))
    from raft_tpu.matrix import gather

    kx, ki = jax.random.split(jax.random.key(7))
    x = jax.random.normal(kx, (n, d), jnp.float32)
    idx = jax.random.randint(ki, (m,), 0, n, jnp.int32)
    jax.block_until_ready((x, idx))
    jg = jax.jit(gather)
    return (lambda: jg(x, idx), m * d * 4, 0, f"{m} of {n}x{d}")


@_register("rng_normal")
def _rng_normal(size: str):
    """RNG throughput (``cpp/bench/prims/random``)."""
    from raft_tpu.random import RngState, normal

    n, d = _dims(size, (1 << 13, 128), (1 << 18, 128), (1 << 20, 128))
    jr = jax.jit(lambda: normal(RngState(0), (n, d)))
    return (lambda: jr(), n * d * 4, 0, f"{n}x{d} f32")


@_register("permute")
def _permute(size: str):
    from raft_tpu.random import RngState, permute

    n, _ = _dims(size, (1 << 16, 0), (1 << 20, 0), (1 << 22, 0))
    jp = jax.jit(lambda: permute(RngState(1), n))
    return (lambda: jp(), n * 4, 0, f"perm of {n}")


@_register("bitset_test")
def _bitset_test(size: str):
    """core bitset test throughput (``cpp/bench/prims/core/bitset``)."""
    from raft_tpu.core.bitset import Bitset, test_words

    n, m = _dims(size, (1 << 16, 1 << 13), (1 << 22, 1 << 18),
                 (1 << 24, 1 << 20))
    bs = Bitset.from_mask(jnp.ones((n,), bool))
    idx = jax.random.randint(jax.random.key(9), (m,), 0, n, jnp.int32)
    jax.block_until_ready((bs.words, idx))
    jt = jax.jit(test_words)
    # bytes: a 4-byte index read + a 4-byte gathered word per test
    return (lambda: jt(bs.words, idx), m * 8, 0, f"{m} tests of {n} bits")


@_register("sparse_spmm")
def _sparse_spmm(size: str):
    """CSR x dense (``cpp/bench/prims/sparse``)."""
    import scipy.sparse as sps

    from raft_tpu.sparse import CSR
    from raft_tpu.sparse.linalg import spmm

    n, d, nnz_per = _dims(size, (1 << 10, 64, 16), (1 << 14, 128, 32),
                          (1 << 16, 128, 32))
    rng = np.random.default_rng(10)
    rows = np.repeat(np.arange(n), nnz_per)
    cols = rng.integers(0, n, n * nnz_per)
    vals = rng.standard_normal(n * nnz_per).astype(np.float32)
    csr = CSR.from_scipy(sps.csr_matrix((vals, (rows, cols)), shape=(n, n)))
    dense = jnp.asarray(rng.standard_normal((n, d)), jnp.float32)
    jax.block_until_ready(dense)
    js = jax.jit(lambda mat: spmm(csr, mat))
    return (lambda: js(dense), n * nnz_per * 8 + n * d * 4,
            2 * n * nnz_per * d, f"{n}x{n} nnz/row={nnz_per} x {n}x{d}")


@_register("ivf_flat_search")
def _ivf_flat_search(size: str):
    """End-to-end IVF-Flat probe scan (``cpp/bench/prims/neighbors``)."""
    from raft_tpu.neighbors import ivf_flat

    n, d, q, p = _dims(size, (1 << 13, 64, 32, 8), (1 << 17, 128, 100, 32),
                       (1 << 20, 128, 100, 32))
    rng = np.random.default_rng(11)
    x = rng.standard_normal((n, d)).astype(np.float32)
    idx = ivf_flat.build(None, ivf_flat.IvfFlatIndexParams(
        n_lists=max(32, n // 256)), x)
    qs = jnp.asarray(rng.standard_normal((q, d)), jnp.float32)
    jax.block_until_ready((idx.data, qs))
    sp = ivf_flat.IvfFlatSearchParams(n_probes=p)
    avg_m = idx.max_list_size
    return (lambda: ivf_flat.search(None, sp, idx, qs, 10),
            q * p * avg_m * d * 4, 2 * q * p * avg_m * d,
            f"{n}x{d} p={p} q={q}")


@_register("kmeans_iter")
def _kmeans_iter(size: str):
    """One balanced-EM iteration: predict labels + recompute centers —
    the hot loop of every IVF build (``balancing_em_iters``)."""
    from raft_tpu.cluster.kmeans_balanced import (
        _calc_centers_and_sizes, _predict_impl)
    from raft_tpu.distance.types import DistanceType

    n, c, d = _dims(size, (1 << 12, 256, 64), (1 << 17, 1024, 128),
                    (1 << 18, 1024, 128))
    kx, kc = jax.random.split(jax.random.key(6))
    x = jax.random.normal(kx, (n, d), jnp.float32)
    cent = jax.random.normal(kc, (c, d), jnp.float32)
    jax.block_until_ready((x, cent))

    @jax.jit
    def step(xa, ca):
        labels = _predict_impl(xa, ca, DistanceType.L2Expanded)
        return _calc_centers_and_sizes(xa, labels, c)

    # predict reads x once + centers; update reads x again (scatter-add)
    return (lambda: step(x, cent), 2 * n * d * 4, 2 * n * c * d,
            f"{n}x{c}x{d} f32")


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------


def run_prims(
    size: str = "small",
    name_filter: str = "",
    budget_s: float = 10.0,
    out_path: Optional[str] = None,
) -> List[Dict]:
    results = []
    for prim in _REGISTRY:
        if name_filter and name_filter not in prim.name:
            continue
        try:
            fn, nbytes, flops, shape = prim.make(size)
            dt = timeit(fn, budget_s)
        except Exception as e:  # keep the suite going past one bad prim
            rec = {"prim": prim.name, "error": f"{type(e).__name__}: {e}"}
            print(json.dumps(rec), flush=True)
            results.append(rec)
            continue
        rec = {
            "prim": prim.name,
            "shape": shape,
            "ms": round(dt * 1e3, 3),
            "gbps": round(nbytes / dt / 1e9, 2),
            "bw_frac": round(nbytes / dt / PEAK_BW, 4),
            "mfu": round(flops / dt / PEAK_FLOPS, 4) if flops else 0.0,
            "backend": jax.default_backend(),
        }
        print(json.dumps(rec), flush=True)
        results.append(rec)
    if out_path:
        with open(out_path, "a") as fh:
            for rec in results:
                fh.write(json.dumps(rec) + "\n")
    return results


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--filter", default="", help="substring filter on names")
    p.add_argument("--size", default="small",
                   choices=("tiny", "small", "full"))
    p.add_argument("--seconds", type=float, default=10.0,
                   help="per-prim measurement budget")
    p.add_argument("--out", default=None, help="append JSONL here")
    args = p.parse_args(argv)
    run_prims(args.size, args.filter, args.seconds, args.out)


if __name__ == "__main__":
    main()
