"""CLI for the ANN benchmark harness — the ``raft-ann-bench`` command
surface (``run/__main__.py:70``: run / get-dataset / data-export / plot).
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="raft_tpu.bench",
        description="TPU ANN benchmark harness (raft-ann-bench analog)",
    )
    sub = parser.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("get-dataset", help="generate or convert a dataset")
    p.add_argument("--out-dir", default="datasets")
    p.add_argument("--name", default=None)
    p.add_argument("--kind", choices=["random", "blobs"], default="blobs")
    p.add_argument("--n", type=int, default=100_000)
    p.add_argument("--dim", type=int, default=128)
    p.add_argument("--n-queries", type=int, default=1000)
    p.add_argument("--k", type=int, default=100)
    p.add_argument("--metric", default="euclidean")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--hdf5", default=None,
                   help="convert this ann-benchmarks HDF5 instead")

    p = sub.add_parser("run", help="run benchmarks from a JSON config")
    p.add_argument("--dataset", required=True, help="dataset directory")
    p.add_argument("--config", required=True,
                   help="JSON config path, or the name of a bundled config "
                        "under raft_tpu/bench/conf (e.g. sift-128-euclidean)")
    p.add_argument("--out-dir", default="results")
    p.add_argument("-k", type=int, default=10)
    p.add_argument("--batch-size", type=int, default=0)
    p.add_argument("--search-iters", type=int, default=3)
    p.add_argument("--force-rebuild", action="store_true",
                   help="rebuild indexes even if a cached index file "
                        "exists under <out-dir>/indexes/")
    p.add_argument("--resume", action="store_true",
                   help="append to an existing results.jsonl, skipping "
                        "already-recorded combinations")
    p.add_argument("--algos", default=None,
                   help="comma-separated algo names to run (default all "
                        "in the config)")
    p.add_argument("--require-cached-index", action="store_true",
                   help="fail instead of building when a saveable "
                        "algo's index cache misses (for measurement "
                        "devices where builds are not acceptable)")

    p = sub.add_parser("data-export", help="results JSONL -> CSV")
    p.add_argument("--results", required=True)
    p.add_argument("--out", default=None)

    p = sub.add_parser("plot", help="recall-vs-QPS plot")
    p.add_argument("--results", required=True)
    p.add_argument("--out", default=None)

    args = parser.parse_args(argv)

    if args.cmd == "get-dataset":
        from raft_tpu.bench.datasets import convert_hdf5, make_dataset

        if args.hdf5:
            root = convert_hdf5(args.hdf5, args.out_dir, args.name)
        else:
            name = args.name or f"{args.kind}-{args.n}-{args.dim}"
            root = make_dataset(
                args.out_dir, name, n=args.n, dim=args.dim,
                n_queries=args.n_queries, k=args.k, metric=args.metric,
                seed=args.seed, kind=args.kind,
            )
        print(root)
    elif args.cmd == "run":
        from raft_tpu.bench.runner import run_benchmark

        cfg_path = pathlib.Path(args.config)
        if not cfg_path.exists():
            bundled = (pathlib.Path(__file__).parent / "conf"
                       / f"{args.config}.json")
            if bundled.exists():
                cfg_path = bundled
            else:
                parser.error(f"config {args.config!r} not found (no such "
                             f"file and no bundled conf/{args.config}.json)")
        config = json.loads(cfg_path.read_text())
        rows = run_benchmark(
            args.dataset, config, args.out_dir, k=args.k,
            batch_size=args.batch_size, search_iters=args.search_iters,
            force_rebuild=args.force_rebuild, resume=args.resume,
            only_algos=(args.algos.split(",") if args.algos else None),
            require_cached_index=args.require_cached_index,
        )
        for r in rows:
            print(json.dumps(r))
    elif args.cmd == "data-export":
        from raft_tpu.bench.runner import export_csv

        print(export_csv(args.results, args.out))
    elif args.cmd == "plot":
        from raft_tpu.bench.runner import plot_results

        print(plot_results(args.results, args.out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
