"""Checkpoint / resume for sharded indexes — the MNMG analog of the
per-index ``serialize``/``deserialize`` the reference only offers
single-GPU (``detail/ivf_flat_serialize.cuh:37``,
``detail/ivf_pq_serialize.cuh:39``; raft-dask has no distributed index
persistence — SURVEY.md §5 "Checkpoint / resume").

Format: the same versioned ``.npy``-record stream the single-chip
indexes use, with the arrays written in their global (dealt) list
order. ``load`` takes a ``Comms`` and RE-DEALS the lists round-robin
by population for the target mesh (the same balancing ``build`` does)
before block-sharding them, so the shard count may differ between save
and load — a checkpoint taken on an 8-chip mesh restores onto 4 or 16
with per-chip scan balance (and ``probe_mode='local'`` spread)
preserved.

Two storage schemes:

- ``save_*`` / ``load_*`` — single-controller: arrays are gathered to
  the host process (``jax.device_get``), which requires them to be
  fully addressable. One file; raises a clear error on multi-host
  meshes rather than writing a partial file.

- ``save_*_multihost`` / ``load_*_multihost`` — per-process: each
  process writes ONLY its addressable block of every list-sharded
  array to ``<dir>/part<rank>.bin`` (rank 0 adds ``meta.bin`` with the
  scalars + replicated arrays), so nothing is ever gathered across the
  DCN to one host. Load reads all parts from the shared filesystem,
  reassembles the global (dealt) order by block offset, and re-deals
  for the target comms — the shard count AND process count may both
  differ between save and load.
"""

from __future__ import annotations

import glob
import os

import jax
import numpy as np

from raft_tpu.comms.comms import Comms
from raft_tpu.core import tracing
from raft_tpu.core.serialize import (
    check_version,
    deserialize_array,
    deserialize_scalar,
    open_maybe_path,
    serialize_array,
    serialize_scalar,
)
from raft_tpu.core.validation import expect
from raft_tpu.distance.types import DistanceType
from raft_tpu.distributed.ivf import (
    DistributedIvfFlat,
    DistributedIvfPq,
    deal_order,
)
from raft_tpu.neighbors.ivf_pq import CodebookKind

# distinct magic+version per kind so loading the wrong file kind fails
# with a clear version mismatch instead of a shape error mid-parse
_FLAT_VERSION = 0x4601  # 'F' << 8 | 1
_PQ_VERSION = 0x5001    # 'P' << 8 | 1
# v3: RaBitQ corrections (rnorm/cfac/errw), int32 sign words, optional
# raw-vector rerank plane
_BQ_VERSION = 0x4203    # 'B' << 8 | 3


def _fetch(a) -> np.ndarray:
    expect(a.is_fully_addressable,
           "distributed checkpointing requires fully addressable arrays "
           "(single-controller); use a per-process scheme on multi-host "
           "meshes")
    return np.asarray(jax.device_get(a))


def save_flat(index: DistributedIvfFlat, fh_or_path) -> None:
    """Write a sharded IVF-Flat index; list order is the dealt order."""
    fh, own = open_maybe_path(fh_or_path, "wb")
    try:
        with tracing.range("raft_tpu.distributed.checkpoint.save_flat"):
            serialize_scalar(fh, _FLAT_VERSION, np.int32)
            serialize_scalar(fh, int(index.metric), np.int32)
            serialize_array(fh, _fetch(index.centers))
            serialize_array(fh, _fetch(index.data))
            serialize_array(fh, _fetch(index.data_norms))
            serialize_array(fh, _fetch(index.indices))
            serialize_array(fh, _fetch(index.list_sizes))
    finally:
        if own:
            fh.close()


def load_flat(res, comms: Comms, fh_or_path) -> DistributedIvfFlat:
    """Restore onto ``comms``'s mesh. The shard count may differ from
    save time; the mesh-axis size must divide ``n_lists``."""
    fh, own = open_maybe_path(fh_or_path, "rb")
    try:
        check_version(deserialize_scalar(fh), _FLAT_VERSION,
                      "distributed ivf_flat")
        metric = DistanceType(int(deserialize_scalar(fh)))
        arrays = [deserialize_array(fh) for _ in range(5)]
    finally:
        if own:
            fh.close()
    centers, data, norms, indices, sizes = arrays
    expect(centers.shape[0] % comms.size == 0,
           f"the mesh axis ({comms.size}) must divide n_lists "
           f"{centers.shape[0]}")
    shard = comms.sharding(comms.axis)
    deal = deal_order(np.asarray(sizes), comms.size)

    def place(a):
        # host-side permute + direct sharded device_put: each shard
        # transfers straight from host, never materializing the global
        # array on one device (the at-scale case this module serves)
        return jax.device_put(np.ascontiguousarray(a[deal]), shard)

    return DistributedIvfFlat(
        comms=comms,
        centers=place(centers),
        data=place(data),
        data_norms=place(norms),
        indices=place(indices),
        list_sizes=place(sizes),
        metric=metric,
    )


def save_pq(index: DistributedIvfPq, fh_or_path) -> None:
    """Write a sharded IVF-PQ index (codes always in unpacked layout —
    the distributed scan's working format)."""
    fh, own = open_maybe_path(fh_or_path, "wb")
    try:
        with tracing.range("raft_tpu.distributed.checkpoint.save_pq"):
            serialize_scalar(fh, _PQ_VERSION, np.int32)
            serialize_scalar(fh, int(index.metric), np.int32)
            serialize_scalar(fh, int(index.codebook_kind), np.int32)
            serialize_scalar(fh, index.pq_bits, np.int32)
            serialize_array(fh, _fetch(index.centers))
            serialize_array(fh, _fetch(index.rotation))
            serialize_array(fh, _fetch(index.codebooks))
            serialize_array(fh, _fetch(index.codes))
            serialize_array(fh, _fetch(index.indices))
            serialize_array(fh, _fetch(index.list_sizes))
    finally:
        if own:
            fh.close()


def load_pq(res, comms: Comms, fh_or_path) -> DistributedIvfPq:
    fh, own = open_maybe_path(fh_or_path, "rb")
    try:
        check_version(deserialize_scalar(fh), _PQ_VERSION,
                      "distributed ivf_pq")
        metric = DistanceType(int(deserialize_scalar(fh)))
        kind = CodebookKind(int(deserialize_scalar(fh)))
        pq_bits = int(deserialize_scalar(fh))
        arrays = [deserialize_array(fh) for _ in range(6)]
    finally:
        if own:
            fh.close()
    centers, rotation, codebooks, codes, indices, sizes = arrays
    expect(centers.shape[0] % comms.size == 0,
           f"the mesh axis ({comms.size}) must divide n_lists "
           f"{centers.shape[0]}")
    shard = comms.sharding(comms.axis)
    rep = comms.replicated()
    deal = deal_order(np.asarray(sizes), comms.size)

    def place(a):
        return jax.device_put(np.ascontiguousarray(a[deal]), shard)

    per_cluster = kind == CodebookKind.PER_CLUSTER
    return DistributedIvfPq(
        comms=comms,
        centers=place(centers),
        rotation=jax.device_put(np.asarray(rotation), rep),
        codebooks=(place(codebooks) if per_cluster
                   else jax.device_put(np.asarray(codebooks), rep)),
        codes=place(codes),
        indices=place(indices),
        list_sizes=place(sizes),
        metric=metric,
        pq_bits=pq_bits,
        codebook_kind=kind,
    )


def save_bq(index, fh_or_path) -> None:
    """Write a sharded IVF-BQ index (sign codes + RaBitQ correction
    scalars + the optional raw-vector rerank plane)."""
    fh, own = open_maybe_path(fh_or_path, "wb")
    try:
        with tracing.range("raft_tpu.distributed.checkpoint.save_bq"):
            serialize_scalar(fh, _BQ_VERSION, np.int32)
            serialize_scalar(fh, int(index.metric), np.int32)
            serialize_scalar(fh, index.bits, np.int32)
            serialize_scalar(fh, int(index.data is not None), np.int32)
            serialize_array(fh, _fetch(index.centers))
            serialize_array(fh, _fetch(index.rotation))
            serialize_array(fh, _fetch(index.codes))
            serialize_array(fh, _fetch(index.rnorm))
            serialize_array(fh, _fetch(index.cfac))
            serialize_array(fh, _fetch(index.errw))
            serialize_array(fh, _fetch(index.indices))
            serialize_array(fh, _fetch(index.list_sizes))
            if index.data is not None:
                serialize_array(fh, _fetch(index.data))
    finally:
        if own:
            fh.close()


def _bq_shard_rel_err(errw, rnorm, indices, dim_ext: int, deal,
                      r: int) -> tuple:
    """Re-derive the measured per-shard relative estimator error for
    the restored deal — the variance-corrected merge's input, via the
    ONE shared reduction (:func:`raft_tpu.distributed.bq
    .shard_rel_err_from_arrays` — the statistic the over-fetch
    calibration constant was measured against)."""
    from raft_tpu.distributed.bq import shard_rel_err_from_arrays

    return shard_rel_err_from_arrays(errw, rnorm, indices, dim_ext,
                                     deal, r)


def load_bq(res, comms: Comms, fh_or_path):
    """Restore onto ``comms``'s mesh with the shared re-deal (shard
    count may differ from save time); the per-shard estimator-error
    stats re-derive for the new deal."""
    from raft_tpu.distributed.bq import DistributedIvfBq

    fh, own = open_maybe_path(fh_or_path, "rb")
    try:
        check_version(deserialize_scalar(fh), _BQ_VERSION,
                      "distributed ivf_bq")
        metric = DistanceType(int(deserialize_scalar(fh)))
        int(deserialize_scalar(fh))  # bits — recorded; shape-derivable
        has_data = bool(deserialize_scalar(fh))
        arrays = [deserialize_array(fh) for _ in range(8)]
        data = deserialize_array(fh) if has_data else None
    finally:
        if own:
            fh.close()
    (centers, rotation, codes, rnorm, cfac, errw, indices,
     sizes) = arrays
    expect(centers.shape[0] % comms.size == 0,
           f"the mesh axis ({comms.size}) must divide n_lists "
           f"{centers.shape[0]}")
    shard = comms.sharding(comms.axis)
    deal = deal_order(np.asarray(sizes), comms.size)

    def place(a):
        return jax.device_put(np.ascontiguousarray(a[deal]), shard)

    data_norms = None
    if has_data:
        norms = np.sum(np.square(np.asarray(data, np.float32)), axis=2)
        data_norms = np.where(np.asarray(indices) >= 0, norms, np.inf)
    return DistributedIvfBq(
        comms=comms,
        centers=place(centers),
        rotation=jax.device_put(np.asarray(rotation),
                                comms.replicated()),
        codes=place(codes),
        rnorm=place(rnorm),
        cfac=place(cfac),
        errw=place(errw),
        indices=place(indices),
        list_sizes=place(sizes),
        metric=metric,
        shard_rel_err=_bq_shard_rel_err(
            errw, rnorm, indices, rotation.shape[0], deal, comms.size),
        data=place(data) if has_data else None,
        data_norms=(place(data_norms.astype(np.float32))
                    if has_data else None),
    )


# ---------------------------------------------------------------------------
# multi-host per-process scheme
# ---------------------------------------------------------------------------

def _mesh_participants(comms: Comms):
    """Process indices with devices in this comms mesh, sorted — the
    save/load unit of the multihost scheme (NOT jax.process_count():
    a sub-mesh may span fewer processes than the job)."""
    return sorted({d.process_index for d in comms.mesh.devices.flat})


def _local_block(a):
    """This process's contiguous dim-0 block of a list-sharded array,
    plus its global start offset (shards arrive device-ordered)."""
    shards = sorted(a.addressable_shards,
                    key=lambda s: int(s.index[0].start or 0))
    expect(len(shards) > 0,
           "this process holds no shard of the array — only mesh "
           "participants may call the multihost save")
    start = int(shards[0].index[0].start or 0)
    pos = start
    for s in shards:
        st = int(s.index[0].start or 0)
        expect(st == pos,
               "this process's shards are not one contiguous list block "
               f"(gap at row {pos}, next shard starts at {st}) — the "
               "multihost scheme requires a process-contiguous mesh "
               "(bootstrap.make_mesh default order)")
        pos = st + s.data.shape[0]
    block = np.concatenate(
        [np.asarray(jax.device_get(s.data)) for s in shards], axis=0)
    return start, block


def _save_parts(dirpath, version: int, comms: Comms, sharded,
                meta_scalars, meta_arrays) -> None:
    """Write this process's part file (+ meta from the first
    participant). ``sharded`` arrays must share one dim-0 sharding
    (the list axis). Non-participating processes are a no-op."""
    participants = _mesh_participants(comms)
    me = jax.process_index()
    if me not in participants:
        return
    ordinal = participants.index(me)
    n_parts = len(participants)
    os.makedirs(dirpath, exist_ok=True)
    with open(os.path.join(dirpath, f"part{ordinal:05d}.bin"), "wb") as fh:
        serialize_scalar(fh, version, np.int32)
        start = None
        for a in sharded:
            st, block = _local_block(a)
            start = st if start is None else start
            serialize_array(fh, block)
        serialize_scalar(fh, start, np.int64)
    if ordinal == 0:
        # a re-save into an existing dir must not leave stale
        # higher-ordinal parts behind — the loader would see a mixed
        # checkpoint. Peers only write ordinals < n_parts, so removing
        # the tail is race-free.
        for stale in glob.glob(os.path.join(dirpath, "part*.bin")):
            base = os.path.basename(stale)
            if int(base[4:9]) >= n_parts:
                os.remove(stale)
        with open(os.path.join(dirpath, "meta.bin"), "wb") as fh:
            serialize_scalar(fh, version, np.int32)
            serialize_scalar(fh, n_parts, np.int32)
            for s in meta_scalars:
                serialize_scalar(fh, int(s), np.int32)
            for a in meta_arrays:
                serialize_array(fh, np.asarray(jax.device_get(a)))


def _load_parts(dirpath, version: int, what: str, n_sharded: int,
                n_scalars: int, n_meta_arrays: int):
    """Read meta + every part; returns (scalars, meta_arrays, fields)
    with each field reassembled into the global dealt order."""
    with open(os.path.join(dirpath, "meta.bin"), "rb") as fh:
        check_version(deserialize_scalar(fh), version, what)
        n_parts = int(deserialize_scalar(fh))
        scalars = [int(deserialize_scalar(fh)) for _ in range(n_scalars)]
        metas = [deserialize_array(fh) for _ in range(n_meta_arrays)]
    paths = sorted(glob.glob(os.path.join(dirpath, "part*.bin")))
    expect(len(paths) == n_parts,
           f"checkpoint dir has {len(paths)} part files, meta says "
           f"{n_parts} — mixed checkpoints in one directory?")
    parts = []
    for p in paths:
        with open(p, "rb") as fh:
            check_version(deserialize_scalar(fh), version, what)
            arrays = [deserialize_array(fh) for _ in range(n_sharded)]
            start = int(deserialize_scalar(fh))
        parts.append((start, arrays))
    parts.sort(key=lambda t: t[0])
    fields = [np.concatenate([p[1][i] for p in parts], axis=0)
              for i in range(n_sharded)]
    return scalars, metas, fields


def _deal_place(comms: Comms, sizes: np.ndarray):
    """The shared restore placement: re-deal by population for the
    target mesh, then block-shard straight from host."""
    expect(len(sizes) % comms.size == 0,
           f"the mesh axis ({comms.size}) must divide n_lists "
           f"{len(sizes)}")
    shard = comms.sharding(comms.axis)
    deal = deal_order(np.asarray(sizes), comms.size)

    def place(a):
        return jax.device_put(np.ascontiguousarray(a[deal]), shard)

    return place


def save_flat_multihost(index: DistributedIvfFlat, dirpath) -> None:
    """Per-process IVF-Flat checkpoint (see module docstring)."""
    with tracing.range("raft_tpu.distributed.checkpoint.save_flat_mh"):
        _save_parts(dirpath, _FLAT_VERSION, index.comms,
                    [index.centers, index.data, index.data_norms,
                     index.indices, index.list_sizes],
                    meta_scalars=[int(index.metric)], meta_arrays=[])


def load_flat_multihost(res, comms: Comms, dirpath) -> DistributedIvfFlat:
    scalars, _, fields = _load_parts(
        dirpath, _FLAT_VERSION, "distributed ivf_flat", 5, 1, 0)
    centers, data, norms, indices, sizes = fields
    place = _deal_place(comms, sizes)
    return DistributedIvfFlat(
        comms=comms, centers=place(centers), data=place(data),
        data_norms=place(norms), indices=place(indices),
        list_sizes=place(sizes), metric=DistanceType(scalars[0]))


def save_pq_multihost(index: DistributedIvfPq, dirpath) -> None:
    """Per-process IVF-PQ checkpoint. PER_CLUSTER codebooks shard with
    the lists (into the parts); PER_SUBSPACE books ride meta.bin."""
    per_cluster = index.codebook_kind == CodebookKind.PER_CLUSTER
    sharded = [index.centers, index.codes, index.indices,
               index.list_sizes]
    metas = [index.rotation]
    (sharded if per_cluster else metas).append(index.codebooks)
    with tracing.range("raft_tpu.distributed.checkpoint.save_pq_mh"):
        _save_parts(dirpath, _PQ_VERSION, index.comms, sharded,
                    meta_scalars=[int(index.metric),
                                  int(index.codebook_kind),
                                  index.pq_bits],
                    meta_arrays=metas)


def load_pq_multihost(res, comms: Comms, dirpath) -> DistributedIvfPq:
    with open(os.path.join(dirpath, "meta.bin"), "rb") as fh:
        check_version(deserialize_scalar(fh), _PQ_VERSION,
                      "distributed ivf_pq")
        deserialize_scalar(fh)  # n_parts — re-read by _load_parts
        deserialize_scalar(fh)  # metric
        kind = CodebookKind(int(deserialize_scalar(fh)))
    per_cluster = kind == CodebookKind.PER_CLUSTER
    scalars, metas, fields = _load_parts(
        dirpath, _PQ_VERSION, "distributed ivf_pq",
        5 if per_cluster else 4, 3, 1 if per_cluster else 2)
    metric = DistanceType(scalars[0])
    pq_bits = scalars[2]
    if per_cluster:
        centers, codes, indices, sizes, codebooks = fields
        rotation = metas[0]
    else:
        centers, codes, indices, sizes = fields
        rotation, codebooks = metas
    place = _deal_place(comms, sizes)
    rep = comms.replicated()
    return DistributedIvfPq(
        comms=comms, centers=place(centers),
        rotation=jax.device_put(np.asarray(rotation), rep),
        codebooks=(place(codebooks) if per_cluster
                   else jax.device_put(np.asarray(codebooks), rep)),
        codes=place(codes), indices=place(indices),
        list_sizes=place(sizes), metric=metric, pq_bits=pq_bits,
        codebook_kind=kind)


def save_bq_multihost(index, dirpath) -> None:
    """Per-process IVF-BQ checkpoint (v3 fields; the optional rerank
    plane rides as an extra sharded field flagged in the meta)."""
    with tracing.range("raft_tpu.distributed.checkpoint.save_bq_mh"):
        fields = [index.centers, index.codes, index.rnorm, index.cfac,
                  index.errw, index.indices, index.list_sizes]
        if index.data is not None:
            fields.append(index.data)
        _save_parts(dirpath, _BQ_VERSION, index.comms, fields,
                    meta_scalars=[int(index.metric), index.bits,
                                  int(index.data is not None)],
                    meta_arrays=[index.rotation])


def load_bq_multihost(res, comms: Comms, dirpath):
    from raft_tpu.distributed.bq import DistributedIvfBq

    # peek the meta for the rerank-plane flag — it decides the
    # per-part field count before the parts are read
    with open(os.path.join(dirpath, "meta.bin"), "rb") as fh:
        check_version(deserialize_scalar(fh), _BQ_VERSION,
                      "distributed ivf_bq")
        int(deserialize_scalar(fh))                 # n_parts
        int(deserialize_scalar(fh))                 # metric
        int(deserialize_scalar(fh))                 # bits
        has_data = bool(deserialize_scalar(fh))
    scalars, metas, fields = _load_parts(
        dirpath, _BQ_VERSION, "distributed ivf_bq",
        8 if has_data else 7, 3, 1)
    (centers, codes, rnorm, cfac, errw, indices,
     sizes) = fields[:7]
    data = fields[7] if has_data else None
    place = _deal_place(comms, sizes)
    rotation = np.asarray(metas[0])
    deal = deal_order(np.asarray(sizes), comms.size)
    data_norms = None
    if has_data:
        norms = np.sum(np.square(np.asarray(data, np.float32)), axis=2)
        data_norms = np.where(np.asarray(indices) >= 0, norms,
                              np.inf).astype(np.float32)
    return DistributedIvfBq(
        comms=comms, centers=place(centers),
        rotation=jax.device_put(rotation, comms.replicated()),
        codes=place(codes), rnorm=place(rnorm), cfac=place(cfac),
        errw=place(errw), indices=place(indices),
        list_sizes=place(sizes), metric=DistanceType(scalars[0]),
        shard_rel_err=_bq_shard_rel_err(errw, rnorm, indices,
                                        rotation.shape[0], deal,
                                        comms.size),
        data=place(data) if has_data else None,
        data_norms=place(data_norms) if has_data else None)
