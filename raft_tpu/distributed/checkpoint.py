"""Checkpoint / resume for sharded indexes — the MNMG analog of the
per-index ``serialize``/``deserialize`` the reference only offers
single-GPU (``detail/ivf_flat_serialize.cuh:37``,
``detail/ivf_pq_serialize.cuh:39``; raft-dask has no distributed index
persistence — SURVEY.md §5 "Checkpoint / resume").

Format: the same versioned ``.npy``-record stream the single-chip
indexes use, with the arrays written in their global (dealt) list
order. ``load`` takes a ``Comms`` and RE-DEALS the lists round-robin
by population for the target mesh (the same balancing ``build`` does)
before block-sharding them, so the shard count may differ between save
and load — a checkpoint taken on an 8-chip mesh restores onto 4 or 16
with per-chip scan balance (and ``probe_mode='local'`` spread)
preserved.

Single-controller scope: arrays are gathered to the host process for
writing (``jax.device_get``), which requires them to be fully
addressable — true in single-process multi-device deployments. On
multi-host meshes, gather-to-host0 or a per-process scheme (e.g.
orbax) is needed; this module raises a clear error in that case
rather than writing a partial file.
"""

from __future__ import annotations

import jax
import numpy as np

from raft_tpu.comms.comms import Comms
from raft_tpu.core import tracing
from raft_tpu.core.serialize import (
    check_version,
    deserialize_array,
    deserialize_scalar,
    open_maybe_path,
    serialize_array,
    serialize_scalar,
)
from raft_tpu.core.validation import expect
from raft_tpu.distance.types import DistanceType
from raft_tpu.distributed.ivf import (
    DistributedIvfFlat,
    DistributedIvfPq,
    deal_order,
)
from raft_tpu.neighbors.ivf_pq import CodebookKind

# distinct magic+version per kind so loading the wrong file kind fails
# with a clear version mismatch instead of a shape error mid-parse
_FLAT_VERSION = 0x4601  # 'F' << 8 | 1
_PQ_VERSION = 0x5001    # 'P' << 8 | 1
_BQ_VERSION = 0x4202    # 'B' << 8 | 2 (v2: multi-level scales)


def _fetch(a) -> np.ndarray:
    expect(a.is_fully_addressable,
           "distributed checkpointing requires fully addressable arrays "
           "(single-controller); use a per-process scheme on multi-host "
           "meshes")
    return np.asarray(jax.device_get(a))


def save_flat(index: DistributedIvfFlat, fh_or_path) -> None:
    """Write a sharded IVF-Flat index; list order is the dealt order."""
    fh, own = open_maybe_path(fh_or_path, "wb")
    try:
        with tracing.range("raft_tpu.distributed.checkpoint.save_flat"):
            serialize_scalar(fh, _FLAT_VERSION, np.int32)
            serialize_scalar(fh, int(index.metric), np.int32)
            serialize_array(fh, _fetch(index.centers))
            serialize_array(fh, _fetch(index.data))
            serialize_array(fh, _fetch(index.data_norms))
            serialize_array(fh, _fetch(index.indices))
            serialize_array(fh, _fetch(index.list_sizes))
    finally:
        if own:
            fh.close()


def load_flat(res, comms: Comms, fh_or_path) -> DistributedIvfFlat:
    """Restore onto ``comms``'s mesh. The shard count may differ from
    save time; the mesh-axis size must divide ``n_lists``."""
    fh, own = open_maybe_path(fh_or_path, "rb")
    try:
        check_version(deserialize_scalar(fh), _FLAT_VERSION,
                      "distributed ivf_flat")
        metric = DistanceType(int(deserialize_scalar(fh)))
        arrays = [deserialize_array(fh) for _ in range(5)]
    finally:
        if own:
            fh.close()
    centers, data, norms, indices, sizes = arrays
    expect(centers.shape[0] % comms.size == 0,
           f"the mesh axis ({comms.size}) must divide n_lists "
           f"{centers.shape[0]}")
    shard = comms.sharding(comms.axis)
    deal = deal_order(np.asarray(sizes), comms.size)

    def place(a):
        # host-side permute + direct sharded device_put: each shard
        # transfers straight from host, never materializing the global
        # array on one device (the at-scale case this module serves)
        return jax.device_put(np.ascontiguousarray(a[deal]), shard)

    return DistributedIvfFlat(
        comms=comms,
        centers=place(centers),
        data=place(data),
        data_norms=place(norms),
        indices=place(indices),
        list_sizes=place(sizes),
        metric=metric,
    )


def save_pq(index: DistributedIvfPq, fh_or_path) -> None:
    """Write a sharded IVF-PQ index (codes always in unpacked layout —
    the distributed scan's working format)."""
    fh, own = open_maybe_path(fh_or_path, "wb")
    try:
        with tracing.range("raft_tpu.distributed.checkpoint.save_pq"):
            serialize_scalar(fh, _PQ_VERSION, np.int32)
            serialize_scalar(fh, int(index.metric), np.int32)
            serialize_scalar(fh, int(index.codebook_kind), np.int32)
            serialize_scalar(fh, index.pq_bits, np.int32)
            serialize_array(fh, _fetch(index.centers))
            serialize_array(fh, _fetch(index.rotation))
            serialize_array(fh, _fetch(index.codebooks))
            serialize_array(fh, _fetch(index.codes))
            serialize_array(fh, _fetch(index.indices))
            serialize_array(fh, _fetch(index.list_sizes))
    finally:
        if own:
            fh.close()


def load_pq(res, comms: Comms, fh_or_path) -> DistributedIvfPq:
    fh, own = open_maybe_path(fh_or_path, "rb")
    try:
        check_version(deserialize_scalar(fh), _PQ_VERSION,
                      "distributed ivf_pq")
        metric = DistanceType(int(deserialize_scalar(fh)))
        kind = CodebookKind(int(deserialize_scalar(fh)))
        pq_bits = int(deserialize_scalar(fh))
        arrays = [deserialize_array(fh) for _ in range(6)]
    finally:
        if own:
            fh.close()
    centers, rotation, codebooks, codes, indices, sizes = arrays
    expect(centers.shape[0] % comms.size == 0,
           f"the mesh axis ({comms.size}) must divide n_lists "
           f"{centers.shape[0]}")
    shard = comms.sharding(comms.axis)
    rep = comms.replicated()
    deal = deal_order(np.asarray(sizes), comms.size)

    def place(a):
        return jax.device_put(np.ascontiguousarray(a[deal]), shard)

    per_cluster = kind == CodebookKind.PER_CLUSTER
    return DistributedIvfPq(
        comms=comms,
        centers=place(centers),
        rotation=jax.device_put(np.asarray(rotation), rep),
        codebooks=(place(codebooks) if per_cluster
                   else jax.device_put(np.asarray(codebooks), rep)),
        codes=place(codes),
        indices=place(indices),
        list_sizes=place(sizes),
        metric=metric,
        pq_bits=pq_bits,
        codebook_kind=kind,
    )


def save_bq(index, fh_or_path) -> None:
    """Write a sharded IVF-BQ index (sign codes + per-vector scalars)."""
    fh, own = open_maybe_path(fh_or_path, "wb")
    try:
        with tracing.range("raft_tpu.distributed.checkpoint.save_bq"):
            serialize_scalar(fh, _BQ_VERSION, np.int32)
            serialize_scalar(fh, int(index.metric), np.int32)
            serialize_scalar(fh, index.bits, np.int32)
            serialize_array(fh, _fetch(index.centers))
            serialize_array(fh, _fetch(index.rotation))
            serialize_array(fh, _fetch(index.codes))
            serialize_array(fh, _fetch(index.scales))
            serialize_array(fh, _fetch(index.rnorm2))
            serialize_array(fh, _fetch(index.indices))
            serialize_array(fh, _fetch(index.list_sizes))
    finally:
        if own:
            fh.close()


def load_bq(res, comms: Comms, fh_or_path):
    """Restore onto ``comms``'s mesh with the shared re-deal (shard
    count may differ from save time)."""
    from raft_tpu.distributed.bq import DistributedIvfBq

    fh, own = open_maybe_path(fh_or_path, "rb")
    try:
        check_version(deserialize_scalar(fh), _BQ_VERSION,
                      "distributed ivf_bq")
        metric = DistanceType(int(deserialize_scalar(fh)))
        int(deserialize_scalar(fh))  # bits — recorded; shape-derivable
        arrays = [deserialize_array(fh) for _ in range(7)]
    finally:
        if own:
            fh.close()
    centers, rotation, codes, scales, rn2, indices, sizes = arrays
    expect(centers.shape[0] % comms.size == 0,
           f"the mesh axis ({comms.size}) must divide n_lists "
           f"{centers.shape[0]}")
    shard = comms.sharding(comms.axis)
    deal = deal_order(np.asarray(sizes), comms.size)

    def place(a):
        return jax.device_put(np.ascontiguousarray(a[deal]), shard)

    return DistributedIvfBq(
        comms=comms,
        centers=place(centers),
        rotation=jax.device_put(np.asarray(rotation),
                                comms.replicated()),
        codes=place(codes),
        scales=place(scales),
        rnorm2=place(rn2),
        indices=place(indices),
        list_sizes=place(sizes),
        metric=metric,
    )
