"""Index-per-shard ANN — raft-dask's MNMG pattern (one index per worker,
merge at query time; ``raft_dask`` + ``knn_merge_parts``,
SURVEY.md §5 "MNMG sharding via raft-dask").

The dataset is split into row shards; any single-device index family
(ivf_flat / ivf_pq / cagra / brute_force) is built per shard with its
arrays placed on that shard's device; search fans out per shard and
merges with the shared top-k merge. Host code orchestrates (exactly the
Dask worker role); per-shard compute stays jitted on its device.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core import tracing
from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.core.validation import expect
from raft_tpu.neighbors.brute_force import knn_merge_parts


@dataclasses.dataclass
class ShardedIndex:
    """Per-shard sub-indexes + their global row offsets."""

    shards: List[Any]
    offsets: List[int]
    search_fn: Callable  # (res, index, queries, k) -> (dists, ids)
    select_min: bool = True

    @property
    def n_shards(self) -> int:
        return len(self.shards)

    def search(
        self,
        res: Optional[Resources],
        queries,
        k: int,
    ) -> Tuple[jax.Array, jax.Array]:
        """Fan out to every shard, then ``knn_merge_parts``."""
        res = ensure_resources(res)
        queries = jnp.asarray(queries)
        with tracing.range("raft_tpu.distributed.sharded_search"):
            parts_d, parts_i = [], []
            for index, off in zip(self.shards, self.offsets):
                d, i = self.search_fn(res, index, queries, k)
                parts_d.append(d)
                parts_i.append(jnp.where(i >= 0, i + off, i))
            # per-shard parts live on their shard's device; the merge
            # needs them together (the raft-dask client-side
            # knn_merge_parts role) — gather to the resources' device
            # (default device when unset) before stacking
            merge_dev = res.device or jax.devices()[0]
            parts_d = [jax.device_put(p, merge_dev) for p in parts_d]
            parts_i = [jax.device_put(p, merge_dev) for p in parts_i]
            return knn_merge_parts(
                jnp.stack(parts_d), jnp.stack(parts_i), self.select_min
            )


def build_sharded(
    res: Optional[Resources],
    build_fn: Callable,
    search_fn: Callable,
    dataset,
    n_shards: Optional[int] = None,
    devices: Optional[Sequence[jax.Device]] = None,
    select_min: bool = True,
) -> ShardedIndex:
    """Split ``dataset`` into row shards and build one sub-index each.

    ``build_fn(res, shard)`` builds a sub-index; when ``devices`` is
    given, shard s's arrays are placed on ``devices[s % len]`` (one index
    per chip — the raft-dask worker layout).
    """
    res = ensure_resources(res)
    dataset = jnp.asarray(dataset)
    expect(dataset.ndim == 2, "dataset must be (n, d)")
    if devices is None and n_shards is None:
        devices = jax.devices()
    if n_shards is None:
        n_shards = len(devices)
    n = dataset.shape[0]
    expect(n_shards <= n, "more shards than rows")

    bounds = [round(s * n / n_shards) for s in range(n_shards + 1)]
    shards, offsets = [], []
    with tracing.range("raft_tpu.distributed.build_sharded"):
        for s in range(n_shards):
            part = dataset[bounds[s] : bounds[s + 1]]
            shard_res = dataclasses.replace(
                res, device=devices[s % len(devices)] if devices else None
            )
            shards.append(build_fn(shard_res, part))
            offsets.append(bounds[s])
    return ShardedIndex(shards, offsets, search_fn, select_min)
