"""Index-per-shard ANN — raft-dask's MNMG pattern (one index per worker,
merge at query time; ``raft_dask`` + ``knn_merge_parts``,
SURVEY.md §5 "MNMG sharding via raft-dask").

The dataset is split into row shards; any single-device index family
(ivf_flat / ivf_pq / cagra / brute_force) is built per shard with its
arrays placed on that shard's device; search fans out per shard and
merges with the shared top-k merge. Host code orchestrates (exactly the
Dask worker role); per-shard compute stays jitted on its device.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core import tracing
from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.core.validation import expect
from raft_tpu.neighbors.brute_force import knn_merge_parts


def _index_device(index) -> Optional[jax.Device]:
    """The device a sub-index's arrays live on (first array leaf), or
    None when the index is opaque to pytree flattening."""
    for leaf in jax.tree_util.tree_leaves(index):
        if isinstance(leaf, jax.Array):
            try:
                return list(leaf.devices())[0]
            except Exception:  # noqa: BLE001 — deleted/donated buffer
                return None
    return None


@dataclasses.dataclass
class ShardedIndex:
    """Per-shard sub-indexes + their global row offsets."""

    shards: List[Any]
    offsets: List[int]
    search_fn: Callable  # (res, index, queries, k) -> (dists, ids)
    select_min: bool = True

    @property
    def n_shards(self) -> int:
        return len(self.shards)

    def search(
        self,
        res: Optional[Resources],
        queries,
        k: int,
        trace_id: Optional[int] = None,
    ) -> Tuple[jax.Array, jax.Array]:
        """Fan out to every shard, merge with the shared top-k merge.

        Async-dispatch discipline (the Dask client's scatter/gather
        role, minus the round trips): queries are pre-placed once per
        shard device (one batched transfer), EVERY shard search is
        dispatched before anything blocks, the per-shard (q, k) parts
        come back to the merge device in ONE batched transfer, and the
        merge is one ``knn_merge_parts`` over the stacked parts —
        offsets applied on the merge device so shard devices run only
        their search.

        Straggler attribution (graftscope v2), opt-in via
        ``trace_id``: because this path dispatches per shard, each
        shard's host-side readiness is individually observable — after
        the fan-out a non-blocking poll measures every part's arrival
        offset and feeds the straggler detector
        (``serving.mesh.{shard_skew,slowest_shard}`` gauges +
        per-shard spans tagged with the id). The wait adds nothing to
        the critical path: the batched gather right after blocks on
        the same results."""
        res = ensure_resources(res)
        queries = jnp.asarray(queries)
        with tracing.range("raft_tpu.distributed.sharded_search"):
            t0 = time.perf_counter()
            # one batched host->device scatter of the query block
            devs = [_index_device(ix) for ix in self.shards]
            unique_devs = [d for d in dict.fromkeys(devs) if d is not None]
            placed = dict(zip(unique_devs, jax.device_put(
                [queries] * len(unique_devs), unique_devs))
            ) if unique_devs else {}
            # fan out: all shard searches dispatch before any fetch
            parts = [
                self.search_fn(res, index, placed.get(dev, queries), k)
                for index, dev in zip(self.shards, devs)
            ]
            # per-shard arrival times — the straggler detector's
            # input, opt-in via trace_id (same discipline as the
            # executor's default-off mesh_trace: unconditional
            # recording would fill the bounded span ring with shard
            # spans under steady traffic and evict the per-request
            # spans /trace.json?trace_id= exists to serve); the shared
            # non-blocking poll — see tracing.poll_shard_timings for
            # why sequential blocking would hide early stragglers
            if trace_id is not None:
                timings = tracing.poll_shard_timings(parts, t0)
                tracing.record_mesh_spans(
                    "sharded_ann", t0, t0 + max(timings),
                    trace_ids=(trace_id,), shard_timings=timings)
            # ONE batched gather of the (q, k) parts to the merge device
            merge_dev = res.device or jax.devices()[0]
            flat = [a for d, i in parts for a in (d, i)]
            flat = jax.device_put(flat, merge_dev)
            parts_i = [jnp.where(i >= 0, i + off, i)
                       for i, off in zip(flat[1::2], self.offsets)]
            return knn_merge_parts(jnp.stack(flat[0::2]),
                                   jnp.stack(parts_i), self.select_min)


def build_sharded(
    res: Optional[Resources],
    build_fn: Callable,
    search_fn: Callable,
    dataset,
    n_shards: Optional[int] = None,
    devices: Optional[Sequence[jax.Device]] = None,
    select_min: bool = True,
) -> ShardedIndex:
    """Split ``dataset`` into row shards and build one sub-index each.

    ``build_fn(res, shard)`` builds a sub-index; when ``devices`` is
    given, shard s's arrays are placed on ``devices[s % len]`` (one index
    per chip — the raft-dask worker layout).
    """
    res = ensure_resources(res)
    dataset = jnp.asarray(dataset)
    expect(dataset.ndim == 2, "dataset must be (n, d)")
    if devices is None and n_shards is None:
        devices = jax.devices()
    if n_shards is None:
        n_shards = len(devices)
    n = dataset.shape[0]
    expect(n_shards <= n, "more shards than rows")

    bounds = [round(s * n / n_shards) for s in range(n_shards + 1)]
    shards, offsets = [], []
    with tracing.range("raft_tpu.distributed.build_sharded"):
        for s in range(n_shards):
            part = dataset[bounds[s] : bounds[s + 1]]
            shard_res = dataclasses.replace(
                res, device=devices[s % len(devices)] if devices else None
            )
            shards.append(build_fn(shard_res, part))
            offsets.append(bounds[s])
    return ShardedIndex(shards, offsets, search_fn, select_min)
