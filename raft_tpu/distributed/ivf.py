"""SPMD distributed IVF-Flat — the index itself sharded over a mesh axis.

The reference scales IVF via raft-dask's index-per-worker pattern (host
orchestration + ``knn_merge_parts``). The TPU-native form keeps ONE
logical index whose inverted lists are block-sharded over the mesh
(``jax.sharding``): every chip owns ``n_lists / R`` lists, the coarse
quantizer is replicated, and a single jitted ``shard_map`` program does

    local coarse top-p  →  local probe scan  →  lean all_gather + merge

so the collectives ride ICI and no host round-trips happen per query
(SURVEY.md §5 "TPU equivalent" note; the merge is the
``knn_merge_parts`` pattern inside the program).

The shard-local probe scan is the SAME pluggable engine set as the
single-chip ``ivf_flat.search`` (``scan_engine: auto|pallas|xla|rank``,
:mod:`raft_tpu.ops.ivf_scan`): the list-major engines compute each
shard's probed-list union (not-owned probes masked to the sentinel id)
and stream every owned unique list once through one MXU GEMM. The
query hot path moves only lean payloads over ICI:

- probe selection (``"global"``): each shard contributes its top
  ``min(n_probes, n_local)`` (distance, id) candidates — an
  O(q · n_probes) collective, not the O(q · n_lists / R) coarse block;
- result merge: each shard's locally-reduced (q, k) top-k — O(q · k) —
  with an opt-in ``wire_dtype="bf16"`` low-precision wire format for
  the gathered distances (ids ride exact; ties re-rank by smallest id).

Probe semantics (``probe_mode``):

- ``"global"`` (default, exact): the global top-``n_probes`` lists are
  selected from the gathered per-shard candidates; each shard scans the
  probed lists it owns, masking the rest. Results match the
  single-device index exactly; per-chip wall-clock is ~the single-chip
  search, while HBM capacity scales with the mesh — the point of
  sharding at 1B rows.
- ``"local"`` (approximate, fast): each shard probes its own top
  ``ceil(n_probes / R)`` local lists. Lists are dealt round-robin by
  size at build time so relevant lists spread evenly; the union
  closely tracks the global top-``n_probes`` (the approximation
  sharded FAISS-IVF deployments make). Per-chip scan work drops by R.
"""

from __future__ import annotations

import dataclasses
import time
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from raft_tpu.comms.comms import (
    Comms,
    allgather,
    allgather_quantized,
    allgather_wire,
    alltoall,
    rank as comm_rank,
    reducescatter_quantized,
    resolve_probe_wire_dtype,
    resolve_wire_dtype,
    shard_map,
    size as comm_size,
)
from raft_tpu.core import interruptible, memwatch, tracing
from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.core.validation import expect
from raft_tpu.distance.types import DistanceType, is_min_close
from raft_tpu.matrix.select_k import merge_topk
from raft_tpu.neighbors import ivf_flat as ivf_flat_mod
from raft_tpu.neighbors import ivf_pq as ivf_pq_mod
from raft_tpu.neighbors._batching import coarse_select
from raft_tpu.neighbors._packing import padded_extent
from raft_tpu.neighbors.ivf_flat import IvfFlatIndexParams, IvfFlatSearchParams
from raft_tpu.neighbors.ivf_pq import (
    CodebookKind,
    IvfPqIndexParams,
    IvfPqSearchParams,
)
from raft_tpu.ops.ivf_scan import list_major_scan


@dataclasses.dataclass(frozen=True)
class DistributedIvfFlat:
    """List-sharded IVF-Flat index.

    Arrays with a leading ``n_lists`` axis are sharded over ``comms``'s
    mesh axis; ``centers`` is replicated (every shard needs the full
    codebook only for its local slice — centers are stored sharded too,
    matching the list assignment).
    """

    comms: Comms
    centers: jax.Array        # (n_lists, d) sharded on axis 0
    data: jax.Array           # (n_lists, max_list_size, d) sharded
    data_norms: jax.Array     # (n_lists, max_list_size) sharded
    indices: jax.Array        # (n_lists, max_list_size) int32 sharded
    list_sizes: jax.Array     # (n_lists,) sharded
    metric: DistanceType

    @property
    def n_lists(self) -> int:
        return self.centers.shape[0]

    @property
    def dim(self) -> int:
        return self.centers.shape[1]

    @property
    def max_list_size(self) -> int:
        return self.data.shape[1]

    @property
    def size(self) -> int:
        return int(jax.device_get(self.list_sizes).sum())


def deal_order(sizes: np.ndarray, r: int) -> np.ndarray:
    """Round-robin deal by descending population — THE list-to-shard
    layout policy, shared by build, build_pq and checkpoint restore:
    shard s gets every r-th list of the size-sorted order, so per-shard
    scan work and list relevance stay balanced at any shard count."""
    order = np.argsort(-np.asarray(sizes), kind="stable")
    return np.concatenate([order[s::r] for s in range(r)])


_gather_rows = jax.jit(lambda a, rows: jnp.take(a, rows, axis=0))


def admit_deal(arrays, r: int, what: str) -> None:
    """graftledger gate for the mesh deal (opt-in, no-op unless a
    gate is installed): the single-chip build admitted the BUILD
    device's packed layout, but the deal is a second allocation event
    — every SHARD device receives its ``1/r`` slice of each dealt
    tensor. Admit that per-shard slot model
    (:func:`raft_tpu.core.memwatch.dealt_shard_bytes` — headroom is
    per-device, so per-shard bytes is the unit) host-side BEFORE any
    block moves, so a mesh that cannot hold the sharded index fails
    as a typed ``CapacityExceeded`` instead of an OOM mid-deal.
    Accepts arrays or ``ShapeDtypeStruct``s (the streaming build
    admits its planned buffers before allocating them)."""
    memwatch.admit(memwatch.dealt_shard_bytes(arrays, r), what)


def place_dealt(a, perm: np.ndarray, comms: Comms):
    """Deal + place ONE build-device tensor onto the mesh, streaming
    per-shard blocks instead of materializing the fully-permuted tensor
    on the build device: each shard's list block (1/R of the tensor) is
    gathered on the build device, transferred to its device(s), and the
    global sharded array assembled from the per-device pieces. Peak
    extra build-device footprint drops from O(full tensor) to O(block);
    the high-water mark is recorded in the
    ``distributed.build.peak_deal_block_bytes`` tracing counter and the
    total moved in ``distributed.build.deal_bytes_total``."""
    perm = np.asarray(perm)
    shard = comms.sharding(comms.axis)
    shape = tuple(a.shape)
    imap = shard.devices_indices_map(shape)
    # group devices by their dim-0 block (a 2-D mesh replicates each
    # list block across the other axis — gather it once)
    groups: dict = {}
    order = []
    for dev, idx in imap.items():
        sl = idx[0]
        key = (sl.start or 0, sl.stop if sl.stop is not None else shape[0])
        groups.setdefault(key, []).append(dev)
        order.append((dev, key))
    pieces = {}
    for (start, stop), devs in groups.items():
        rows = jnp.asarray(perm[start:stop], jnp.int32)
        blk = _gather_rows(a, rows)          # ONE block on the build device
        blk_bytes = blk.size * blk.dtype.itemsize
        tracing.max_counter("distributed.build.peak_deal_block_bytes",
                            blk_bytes)
        tracing.inc_counter("distributed.build.deal_bytes_total",
                            blk_bytes * len(devs))
        # graftlint: disable=R5(streaming deal: per-block puts bound build staging to O(block))
        puts = [jax.device_put(blk, d) for d in devs]
        # block before gathering the next block so at most one block's
        # worth of staging lives on the build device at a time
        for p in puts:
            p.block_until_ready()
        for d, p in zip(devs, puts):
            pieces[d] = p
        del blk
    return jax.make_array_from_single_device_arrays(
        shape, shard, [pieces[dev] for dev, _ in order])


def select_probes_sharded(coarse, n_probes: int, axis: str,
                          probe_mode: str, coarse_algo: str = "exact",
                          probe_wire_dtype: str = "f32"):
    """Shared probe selection inside a shard_map body — THE
    probe-ownership arithmetic for every list-sharded index family.

    ``coarse`` is this shard's (q, n_local) min-close coarse distances.
    Returns ``(local, mine)``: per-(query, probe-rank) local list ids
    and a mask of the probes this shard owns.

    - ``"global"``: LEAN candidate exchange — each shard ranks only its
      own centers and contributes its top-``min(n_probes, n_local)``
      (distance, global id) pairs to the all_gather: an O(q · n_probes)
      payload instead of the O(q · n_local) coarse block (the global
      top-``n_probes`` provably lies inside the union of per-shard
      top-``n_probes``). The global probe set is the lexicographic
      (distance, id) top-``n_probes`` of the gathered candidates, so
      ties resolve deterministically at any shard count. When the
      candidate payload would NOT be leaner (probing most of the index:
      2 · min(n_probes, n_local) ≥ n_local), the dense coarse-block
      gather is used instead — same probe set, fewer bytes.
    - ``"local"``: each shard probes its own top-``n_probes`` lists.

    ``coarse_algo="approx"`` swaps the probe top-k for the TPU's
    native approximate top-k unit, via the same
    :func:`raft_tpu.neighbors._batching.coarse_select` dispatch the
    single-chip searches use (lean mode applies it to the local stage).

    ``probe_wire_dtype`` compresses the exchanged coarse *distances*
    on the wire (``f32|bf16|int8`` — int8 rides per-query affine
    scales, :func:`raft_tpu.comms.comms.allgather_quantized`);
    candidate ids stay exact int32, and the final probe select sorts
    (distance, id) so compression-induced ties resolve
    deterministically. The int8 scales derive from the FULL local
    coarse block (``scale_ref=coarse``), BEFORE candidate selection —
    each candidate's code is therefore independent of which (and how
    many) candidates were selected, which is what lets the int8 wire
    ride the ragged serving family's cap-vs-solo bit-identity
    contract. This trades probe-selection fidelity (hence a little
    recall) for 2-4x fewer coarse-exchange bytes — recall-checked in
    ``tests/test_distributed_serving.py``.
    """
    q, n_local = coarse.shape
    if probe_mode == "global":
        rank = comm_rank(axis)
        local_k = min(n_probes, n_local)
        if 2 * local_k < n_local:
            # lean candidate exchange: (distance, global id) pairs only
            loc = coarse_select(-coarse, local_k, coarse_algo)
            dloc = jnp.take_along_axis(coarse, loc, axis=1)
            gid = loc.astype(jnp.int32) + rank.astype(jnp.int32) * n_local
            # (R, q, local_k); distances optionally ride a quantized
            # wire format (scales from the full pre-selection block —
            # candidate-set-independent), ids always exact
            all_d = allgather_quantized(dloc, axis, probe_wire_dtype,
                                        scale_ref=coarse)
            all_g = allgather(gid, axis)
            r = all_d.shape[0]
            cand_d = jnp.moveaxis(all_d, 0, 1).reshape(q, r * local_k)
            cand_g = jnp.moveaxis(all_g, 0, 1).reshape(q, r * local_k)
            _, sg = jax.lax.sort((cand_d, cand_g), dimension=1,
                                 num_keys=2)
            probes = sg[:, :n_probes]
        else:
            coarse_all = allgather_quantized(
                coarse, axis, probe_wire_dtype)           # (R, q, L)
            r = coarse_all.shape[0]
            coarse_flat = jnp.moveaxis(coarse_all, 0, 1).reshape(
                q, r * n_local)
            probes = coarse_select(-coarse_flat, n_probes, coarse_algo)
        owner = probes // n_local
        local = probes - owner * n_local
        mine = owner == rank
        return local, mine
    probes = coarse_select(-coarse, n_probes, coarse_algo)
    return probes, jnp.ones(probes.shape, jnp.bool_)


def merge_results_sharded(best_d, best_i, axis: str, select_min: bool,
                          wire_dtype: str = "f32",
                          smallest_id_ties: bool = True,
                          scatter: bool = False):
    """All-gather each shard's locally-reduced (q, k) top-k and merge —
    the O(q · k) result collective of every list-sharded search (the
    ``knn_merge_parts`` pattern inside the program).

    ``wire_dtype="bf16"`` compresses the gathered *distances* on the
    wire (ids ride exact int32); ties — including the extra ties the
    compression creates — re-rank deterministically by smallest id, so
    the returned ids stay exact w.r.t. the wire-rounded ranking and
    shard-count invariant.

    ``smallest_id_ties=True`` merges by lexicographic (distance, id) —
    the list-major engines' order, bit-identical to the single-chip
    engines even on exact-duplicate ties. ``False`` keeps the legacy
    positional ``knn_merge_parts`` tie-break of the rank-major and BQ
    paths.

    ``scatter=True`` (the 2-D query×list grids) replaces the
    all-ranks gather — where every list shard redundantly merges the
    SAME (q, r·k) candidate table — with a scatter-merge: the
    distances ride
    :func:`raft_tpu.comms.comms.reducescatter_quantized`'s wire (fold
    = this sort-merge), so each list shard receives all ranks'
    candidates for a DISJOINT q/r query slice, merges only that
    slice, and one (q/r, k) allgather reassembles the rows in rank
    order — ~r/2× fewer merge bytes per shard. The received blocks
    stack in rank order, matching the gathered candidate order
    exactly, so the merged results are bit-identical to the
    allgather path (which stays the static fallback when r does not
    divide q)."""
    r = comm_size(axis)
    q, k = best_d.shape
    if scatter and q % r == 0 and q >= r:
        sub_i = alltoall(best_i, axis)                    # (R, q/r, k)
        merged = reducescatter_quantized(
            best_d, axis=axis, wire_dtype=wire_dtype,
            fold=lambda sub_d: _merge_candidates(
                sub_d, sub_i, k, select_min, smallest_id_ties))
        return (allgather(merged[0], axis, tiled=True),
                allgather(merged[1], axis, tiled=True))
    all_d = allgather_wire(best_d, axis, wire_dtype)      # (R, q, k)
    all_i = allgather(best_i, axis)
    return _merge_candidates(all_d, all_i, k, select_min,
                             smallest_id_ties)


def _merge_candidates(all_d, all_i, k: int, select_min: bool,
                      smallest_id_ties: bool):
    """Shared merge epilog of the gather and scatter wires: concat the
    (R, rows, k) rank stacks in rank order and reduce each row's r·k
    candidates to its top-k."""
    r, q, _ = all_d.shape
    cat_d = jnp.moveaxis(all_d, 0, 1).reshape(q, r * k)
    cat_i = jnp.moveaxis(all_i, 0, 1).reshape(q, r * k)
    if not smallest_id_ties:
        return merge_topk(cat_d[:, :k], cat_i[:, :k], cat_d[:, k:],
                          cat_i[:, k:], k, select_min)
    sd, si = jax.lax.sort((cat_d if select_min else -cat_d, cat_i),
                          dimension=1, num_keys=2)
    sd, si = sd[:, :k], si[:, :k]
    si = jnp.where(jnp.isfinite(sd), si, -1)
    return (sd if select_min else -sd), si


def collective_payload_model(q: int, k: int, n_probes: int, n_lists: int,
                             r: int, wire_dtype: str = "f32",
                             probe_mode: str = "global",
                             probe_wire_dtype: str = "f32") -> dict:
    """Modeled per-shard query-path collective payloads (bytes) — the
    accounting the bench rider emits next to measured throughput, and
    the contract the lean-collective tests assert on.

    ``coarse_bytes``/``merge_bytes`` are what the current implementation
    moves per shard; ``dense_coarse_bytes`` is the pre-lean coarse-block
    gather for comparison. ``probe_wire_dtype`` prices the quantized
    candidate exchange (int8 adds TWO f32 affine-scale planes — min and
    range — per (query, shard); the block-independent scheme the
    ragged family's bit-identity contract rides)."""
    n_local = max(n_lists // max(r, 1), 1)
    local_k = min(n_probes, n_local)
    wire_itemsize = 2 if wire_dtype == "bf16" else 4
    probe_itemsize = {"f32": 4, "bf16": 2, "int8": 1}[probe_wire_dtype]
    scale = 8 if probe_wire_dtype == "int8" else 0  # per-row (min, range)
    dense = q * (n_local * probe_itemsize + scale)
    lean = q * (local_k * (probe_itemsize + 4) + scale)  # + int32 ids
    coarse = 0
    if probe_mode == "global":
        coarse = lean if 2 * local_k < n_local else dense
    return {
        "coarse_bytes": coarse,
        "dense_coarse_bytes": q * n_local * 4
            if probe_mode == "global" else 0,
        "merge_bytes": q * k * (wire_itemsize + 4),
        "wire_dtype": wire_dtype,
        "probe_wire_dtype": probe_wire_dtype,
    }


def mesh_phases(model: dict) -> dict:
    """Map one :func:`collective_payload_model` result onto the three
    mesh query phases — the span attrs of the ``serving.mesh.*`` spans
    (PR 7 graftscope v2): ``coarse_select`` carries the probe-candidate
    exchange bytes, ``scan`` the shard-local probe scan (no wire
    bytes — it is the HBM-bound stage), ``merge`` the O(q · k) result
    collective. ``modeled: True`` marks the attribution as byte-model
    accounting over the shared dispatch window, not a device profile —
    the TPU-KNN methodology, machine-readable."""
    return {
        "coarse_select": {"modeled": True,
                          "wire_bytes": model["coarse_bytes"],
                          "dense_wire_bytes": model["dense_coarse_bytes"],
                          "probe_wire_dtype": model["probe_wire_dtype"]},
        "scan": {"modeled": True, "wire_bytes": 0},
        "merge": {"modeled": True, "wire_bytes": model["merge_bytes"],
                  "wire_dtype": model["wire_dtype"]},
    }


def record_dispatch(family: str, model, trace_id, thunk, *,
                    axis: str = "data",
                    phases: Optional[dict] = None,
                    modeled_bytes: Optional[float] = None,
                    attrs: Optional[dict] = None):
    """Shared traced-dispatch path of the direct distributed search
    entries: with ``trace_id=None`` (the default) the thunk dispatches
    untouched — fully async, zero instrumentation cost. With a
    ``trace_id`` the dispatch is timed through
    :func:`raft_tpu.comms.comms.timed_dispatch`, **blocks until the
    result is ready** (so the span duration covers the mesh execution,
    not just the enqueue — the one place tracing trades away async
    dispatch, opt-in per call), and the mesh phase spans are recorded
    with the modeled per-phase wire bytes attached.

    ``axis`` names the mesh axis the program's collectives ride (the
    caller's ``comms.axis`` — a span attr; hardcoding ``"data"`` would
    mislabel 2-D grids and renamed-axis meshes).
    ``phases``/``modeled_bytes`` default from ``model`` — a
    :func:`collective_payload_model` dict, or a zero-arg callable
    producing one so the untraced hot path (``trace_id=None``, every
    production call) never pays for building a model it immediately
    discards; callers with a different phase structure (the exact-kNN
    programs, which have no coarse phase) pass them explicitly and may
    leave ``model`` as None. ``attrs`` ride on the timed-dispatch
    span."""
    from raft_tpu.comms.comms import timed_dispatch

    if trace_id is None:
        return thunk()
    if callable(model):
        model = model()
    if phases is None:
        phases = mesh_phases(model)
    if modeled_bytes is None:
        modeled_bytes = float(model["coarse_bytes"] + model["merge_bytes"])
    ids = (trace_id,)
    t0 = time.perf_counter()
    out = timed_dispatch(
        family, lambda: jax.block_until_ready(thunk()), axis,
        modeled_bytes=modeled_bytes, trace_ids=ids, attrs=attrs)
    tracing.record_mesh_spans(family, t0, time.perf_counter(),
                              trace_ids=ids, phases=phases)
    return out


def publish_payload_gauges(family: str, model: dict) -> None:
    """Register one :func:`collective_payload_model` result as live
    ``serving.collective.*`` gauges — called once per compiled mesh
    executable by the executor (PR 6 graftscope), so a monitoring
    scrape sees the modeled per-shard wire bytes next to the achieved
    bandwidth counters instead of only in offline BENCH JSONs."""
    from raft_tpu.core import tracing

    base = (f"serving.collective.{family}."
            f"{model['wire_dtype']}.{model['probe_wire_dtype']}.")
    tracing.set_gauges({
        base + "coarse_bytes": float(model["coarse_bytes"]),
        base + "dense_coarse_bytes": float(model["dense_coarse_bytes"]),
        base + "merge_bytes": float(model["merge_bytes"]),
    })


def resolve_auto_wires(q: int, k: int, n_probes: int, n_lists: int,
                       r: int, wire_dtype: str, probe_mode: str,
                       probe_wire_dtype: str) -> Tuple[str, str]:
    """Resolve ``"auto"`` wire selections by argmin over the modeled
    per-shard payload (:func:`collective_payload_model`) — the byte
    accounting the comms ledger and bench riders publish, closing its
    own loop. The merge wire argmins ``merge_bytes`` over the
    result-wire formats, the probe wire ``coarse_bytes`` over the
    probe-wire formats (the candidate orderings differ: int8's affine
    scale planes can outweigh its code savings on tiny candidate
    sets). Ties prefer the wider (less lossy) wire; concrete dtypes
    pass through unchanged."""
    from raft_tpu.comms.comms import PROBE_WIRE_DTYPES, WIRE_DTYPES

    def bytes_for(wd: str, pwd: str) -> dict:
        return collective_payload_model(q, k, n_probes, n_lists, r,
                                        wd, probe_mode, pwd)

    if wire_dtype == "auto":
        wire_dtype = min(WIRE_DTYPES,
                         key=lambda wd: bytes_for(wd, "f32")["merge_bytes"])
    if probe_wire_dtype == "auto":
        probe_wire_dtype = min(
            PROBE_WIRE_DTYPES,
            key=lambda pwd: bytes_for("f32", pwd)["coarse_bytes"])
    return wire_dtype, probe_wire_dtype


def resolve_query_sharding(comms: Comms, queries, query_axis):
    """Shared ``query_axis`` validation + placement for the 2-D
    list×query grids: returns the sharding the replicated-or-sharded
    queries should be placed with."""
    if query_axis is not None:
        expect(query_axis in comms.mesh.axis_names
               and query_axis != comms.axis,
               f"query_axis {query_axis!r} must be another mesh axis")
        expect(queries.shape[0] % comms.mesh.shape[query_axis] == 0,
               "the query-axis size must divide the query count evenly")
        return comms.sharding(query_axis)
    return comms.replicated()


def resolve_probe_budget(n_probes: int, n_lists: int, mesh_size: int,
                         probe_mode: str) -> int:
    """Shared probe-budget clamp for the list-sharded search entries:
    validates ``probe_mode`` and converts the user's global probe count
    into this mode's per-program budget (local mode probes each shard's
    own ``ceil(n_probes / R)`` lists)."""
    expect(probe_mode in ("global", "local"),
           f"probe_mode must be 'global' or 'local', got {probe_mode!r}")
    local_lists = n_lists // mesh_size
    n_probes = min(n_probes, n_lists)
    if probe_mode == "local":
        n_probes = min(-(-n_probes // mesh_size), local_lists)
    return n_probes


def build(
    res: Optional[Resources],
    comms: Comms,
    params: IvfFlatIndexParams,
    dataset,
) -> DistributedIvfFlat:
    """Build a list-sharded index: global balanced-kmeans quantizer, then
    lists dealt round-robin by population and placed shard-local (the
    deal streams per shard block — :func:`place_dealt` — so the build
    device never holds a second fully-permuted copy of the index).

    ``params.n_lists`` is rounded up to a multiple of the mesh-axis size.
    """
    res = ensure_resources(res)
    r = comms.size
    n_lists = -(-params.n_lists // r) * r
    params = dataclasses.replace(params, n_lists=n_lists)

    with tracing.range("raft_tpu.distributed.ivf_flat.build"):
        # single-chip build (global quantizer + packed lists), then deal
        index = ivf_flat_mod.build(res, params, dataset)

        # blocked layout wants shard-contiguous rows: stream the deal
        # per shard block per the shared layout policy
        sizes = np.asarray(jax.device_get(index.list_sizes))
        perm = deal_order(sizes, r)
        admit_deal((index.centers, index.data, index.data_norms,
                    index.indices, index.list_sizes), r,
                   "distributed.ivf_flat.build.deal")

        def place(a):
            return place_dealt(a, perm, comms)

        return DistributedIvfFlat(
            comms=comms,
            centers=place(index.centers),
            data=place(index.data),
            data_norms=place(index.data_norms),
            indices=place(index.indices),
            list_sizes=place(index.list_sizes),
            metric=index.metric,
        )


def _dist_search_fn(queries, centers, data, data_norms, indices,
                    init_d=None, init_i=None, probe_counts=None,
                    n_valid=None, row_probes=None, *, axis: str, mesh,
                    n_probes: int, k: int, metric: DistanceType,
                    probe_mode: str, query_axis: Optional[str] = None,
                    coarse_algo: str = "exact", scan_engine: str = "rank",
                    wire_dtype: str = "f32",
                    probe_wire_dtype: str = "f32"):
    """One shard_map program: local coarse → (global|local) probe
    select → shard-local probe scan → lean O(q · k) result merge.

    ``scan_engine`` must arrive resolved (``rank``/``pallas``/``xla``,
    via :func:`raft_tpu.ops.ivf_scan.resolve_scan_engine`) — it is a
    jit static, and the mesh-aware serving path keys AOT executables on
    it. The list-major engines mask not-owned probes to the sentinel id
    ``n_local`` so each shard streams only the union of lists it owns.
    ``init_d``/``init_i`` optionally provide the (q, k) running top-k
    storage (values are reset here; the serving path donates them —
    the Pallas engine keeps its state in VMEM scratch instead).

    ``probe_counts`` (graftgauge) optionally provides the donated
    list-sharded (n_lists,) int32 probe-frequency plane: each shard
    scatter-adds only the probes it OWNS into its local slice (so a
    probe counts exactly once mesh-wide) and the updated plane returns
    as a third output. Replicated-query dispatches only (the mesh
    executor's mode; a ``query_axis`` grid would write divergent
    replicas).

    ``row_probes`` (the mesh ragged front, via
    :func:`_dist_search_ragged_fn`) optionally provides a packed
    ragged tile's per-row GLOBAL probe budgets (replicated ``(tile,)``
    int32, 0 on pad rows): the probe selection then runs at the class
    cap ``n_probes`` and each row's ownership columns past its own
    budget fold out of ``mine``
    (:func:`raft_tpu.ops.ivf_scan.ragged_owned`) — the scan's sentinel
    masking, the result merge, and the probe accounting all already
    consume that mask, so ONE replicated-tile executable serves every
    per-request ``n_probes`` in the class, bit-identical per request
    to the bucketed dispatch."""
    select_min = is_min_close(metric)
    pad_val = jnp.inf if select_min else -jnp.inf
    interpret = jax.default_backend() != "tpu"
    ragged = row_probes is not None

    if init_d is None:
        init_d = jnp.full((queries.shape[0], k), pad_val, jnp.float32)
    if init_i is None:
        init_i = jnp.full((queries.shape[0], k), -1, jnp.int32)

    def body(centers_l, data_l, norms_l, ids_l, qs, ind, ini, *rest):
        rest = list(rest)
        rp = rest.pop(0) if ragged else None
        cnt, nv = rest if rest else (None, None)
        q = qs.shape[0]
        n_local = centers_l.shape[0]
        qf = qs.astype(jnp.float32)

        # graftflight phase markers: each mesh phase runs under a
        # jax.named_scope so the HLO ops carry coarse_select/scan/
        # merge in their op paths — a profiler capture then attributes
        # MEASURED device time per phase (core/profiling.PHASE_MARKERS)
        # instead of only the modeled byte windows. Pure metadata:
        # zero ops added, bit-identity and zero-recompile untouched.
        with jax.named_scope("coarse_select"):
            # coarse distances to this shard's centers
            ip = jax.lax.dot_general(
                qf, centers_l, (((1,), (1,)), ((), ())),
                precision=jax.lax.Precision.HIGHEST,
                preferred_element_type=jnp.float32,
            )
            if metric == DistanceType.InnerProduct:
                coarse = -ip
            else:
                cn = jnp.sum(jnp.square(centers_l), axis=1)
                coarse = cn[None, :] - 2.0 * ip

            local, mine = select_probes_sharded(coarse, n_probes, axis,
                                                probe_mode, coarse_algo,
                                                probe_wire_dtype)
            if rp is not None:
                # ragged: a row owns only the probe columns below its
                # own budget (columns are rank-ordered — the prefix
                # property); local mode converts to per-shard budgets
                from raft_tpu.ops.ivf_scan import ragged_owned

                mine = ragged_owned(
                    mine, rp,
                    shards=(mesh.shape[axis]
                            if probe_mode == "local" else 1))
        if cnt is not None:
            from raft_tpu.ops.ivf_scan import probe_histogram

            cnt = probe_histogram(local, cnt, nv, owned=mine)

        if scan_engine != "rank":
            # list-major: not-owned probes mask to the sentinel id
            # n_local (ops/ivf_scan mask plumbing); each owned unique
            # list streams from HBM once and scores the whole query
            # tile in one MXU GEMM — the PR 2 single-chip engines,
            # unchanged, running inside the shard_map body
            with jax.named_scope("scan"):
                masked = jnp.where(mine, local, n_local).astype(jnp.int32)
                best_d, best_i = list_major_scan(
                    qf, data_l, norms_l, ids_l, masked, None, ind, ini,
                    k=k, metric=metric, engine=scan_engine,
                    interpret=interpret)
        else:
            def step(carry, rank_i):
                best_d, best_i = carry
                lists = local[:, rank_i]
                valid = mine[:, rank_i]
                rows = jnp.take(data_l, lists, axis=0).astype(jnp.float32)
                row_norms = jnp.take(norms_l, lists, axis=0)
                row_ids = jnp.take(ids_l, lists, axis=0)
                ipr = jax.lax.dot_general(
                    rows, qf, (((2,), (1,)), ((0,), (0,))),
                    precision=jax.lax.Precision.HIGHEST,
                    preferred_element_type=jnp.float32,
                )
                if metric == DistanceType.InnerProduct:
                    dist = ipr
                else:
                    dist = row_norms - 2.0 * ipr
                dist = jnp.where((row_ids >= 0) & valid[:, None], dist,
                                 pad_val)
                return merge_topk(best_d, best_i, dist, row_ids, k,
                                  select_min), None

            init = (jnp.full_like(ind, pad_val), jnp.full_like(ini, -1))
            with jax.named_scope("scan"):
                (best_d, best_i), _ = jax.lax.scan(
                    step, init, jnp.arange(local.shape[1]))

        with jax.named_scope("merge"):
            # 2-D grids scatter-merge: each list shard merges a
            # disjoint query slice instead of the whole replicated
            # candidate table (bit-identical — rank-order stacks)
            merged = merge_results_sharded(
                best_d, best_i, axis, select_min, wire_dtype,
                smallest_id_ties=scan_engine != "rank",
                scatter=query_axis is not None)
        if cnt is not None:
            return merged + (cnt,)
        return merged

    # 2-D grid: queries shard over a second mesh axis while lists shard
    # over the first — the reference's row/col process grid
    # (``sub_comms.hpp``). Each device handles its (list-block,
    # query-block) cell; merges stay within the list axis.
    qspec = P() if query_axis is None else P(query_axis, None)
    args = [centers, data, data_norms, indices, queries, init_d, init_i]
    in_specs = [P(axis, None), P(axis, None, None), P(axis, None),
                P(axis, None), qspec, qspec, qspec]
    out_specs = [qspec, qspec]
    if ragged:
        args += [row_probes]
        in_specs += [P()]           # replicated per-row budget plane
    if probe_counts is not None:
        args += [probe_counts, n_valid]
        in_specs += [P(axis), P()]
        out_specs += [P(axis)]
    outs = shard_map(
        body, mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=tuple(out_specs),
        check_vma=False,
    )(*args)
    out_d, out_i = outs[0], outs[1]

    if metric != DistanceType.InnerProduct:
        q_sq = jnp.sum(jnp.square(queries.astype(jnp.float32)), axis=1,
                       keepdims=True)
        out_d = jnp.where(jnp.isfinite(out_d),
                          jnp.maximum(out_d + q_sq, 0.0), out_d)
        if metric == DistanceType.L2SqrtExpanded:
            out_d = jnp.where(jnp.isfinite(out_d), jnp.sqrt(out_d), out_d)
    if probe_counts is not None:
        return out_d, out_i, outs[2]
    return out_d, out_i


_dist_search = partial(jax.jit, static_argnames=(
    "axis", "mesh", "n_probes", "k", "metric", "probe_mode", "query_axis",
    "coarse_algo", "scan_engine", "wire_dtype",
    "probe_wire_dtype"))(_dist_search_fn)


def _dist_search_ragged_fn(queries, row_probes, centers, data, data_norms,
                           indices, init_d=None, init_i=None,
                           probe_counts=None, n_valid=None, *, axis: str,
                           mesh, n_probes: int, k: int,
                           metric: DistanceType, probe_mode: str,
                           scan_engine: str = "xla",
                           wire_dtype: str = "f32",
                           probe_wire_dtype: str = "f32"):
    """Packed ragged-batch mesh search — the distributed IVF-flat
    member of the serving executor's ragged plan family: ONE
    replicated-tile executable per (mesh, params class) replaces the
    distributed bucket ladder. The packing contract is
    :func:`raft_tpu.neighbors.ivf_flat._search_ragged_fn`'s; the
    per-row budgets ride the replicated ``row_probes`` plane into
    :func:`_dist_search_fn`'s ownership mask
    (:func:`raft_tpu.ops.ivf_scan.ragged_owned`), so the sharded body
    — probe-ownership arithmetic, sentinel-masked shard-local scan,
    donated per-shard top-k state, list-sharded probe plane, lean
    result merge — is char-identical to the bucketed dispatch. Exact
    coarse select only, list-major engines only (the rank-major scan's
    positional-tie merge is not budget-prefix-stable)."""
    expect(scan_engine in ("pallas", "xla"),
           "mesh ragged serving needs a membership-masked list-major "
           f"engine (pallas|xla), got {scan_engine!r}")
    return _dist_search_fn(
        queries, centers, data, data_norms, indices, init_d, init_i,
        probe_counts, n_valid, row_probes=row_probes, axis=axis,
        mesh=mesh, n_probes=n_probes, k=k, metric=metric,
        probe_mode=probe_mode, coarse_algo="exact",
        scan_engine=scan_engine, wire_dtype=wire_dtype,
        probe_wire_dtype=probe_wire_dtype)


def search(
    res: Optional[Resources],
    params: IvfFlatSearchParams,
    index: DistributedIvfFlat,
    queries,
    k: int,
    probe_mode: str = "global",
    query_axis: Optional[str] = None,
    wire_dtype: str = "f32",
    probe_wire_dtype: str = "f32",
    trace_id: Optional[int] = None,
) -> Tuple[jax.Array, jax.Array]:
    """One-program distributed search; returns replicated (q, k) results
    with global row ids. See the module docstring for ``probe_mode``.
    ``query_axis`` names a second mesh axis to shard queries over (2-D
    list × query grid); results come back sharded over that axis.
    ``wire_dtype="bf16"`` halves the result-merge collective payload
    (distances compressed on the wire; ids exact, smallest-id ties);
    ``probe_wire_dtype`` (``f32|bf16|int8``) additionally compresses
    the probe-candidate exchange — int8 rides a per-query scale and
    trades a little probe-selection fidelity for ~4x fewer coarse
    bytes (see :func:`select_probes_sharded`).
    The probe scan engine follows ``params.scan_engine`` exactly like
    the single-chip entry (resolved per backend/shape by
    :func:`raft_tpu.ops.ivf_scan.resolve_scan_engine`).
    ``trace_id`` (graftscope v2) opts this call into mesh span
    recording — the dispatch blocks, times, and lands the three phase
    spans with modeled wire bytes (:func:`record_dispatch`)."""
    ensure_resources(res)
    queries = jnp.asarray(queries)
    expect(queries.ndim == 2 and queries.shape[1] == index.dim,
           "queries must be (q, dim)")
    comms = index.comms
    qsharding = resolve_query_sharding(comms, queries, query_axis)
    n_probes = resolve_probe_budget(params.n_probes, index.n_lists,
                                    comms.size, probe_mode)
    expect(params.coarse_algo in ("exact", "approx"),
           f"coarse_algo must be 'exact' or 'approx', got "
           f"{params.coarse_algo!r}")
    wire_dtype, probe_wire_dtype = resolve_auto_wires(
        queries.shape[0], k, n_probes, index.n_lists, comms.size,
        wire_dtype, probe_mode, probe_wire_dtype)
    resolve_wire_dtype(wire_dtype)
    resolve_probe_wire_dtype(probe_wire_dtype)
    from raft_tpu.ops.ivf_scan import resolve_scan_engine

    scan_engine = resolve_scan_engine(params.scan_engine, data=index.data,
                                      k=k)
    queries = jax.device_put(queries, qsharding)
    with tracing.range("raft_tpu.distributed.ivf_flat.search"):
        # lazy: only a traced dispatch (trace_id=) builds the model
        model = lambda: collective_payload_model(  # noqa: E731
            queries.shape[0], k, n_probes, index.n_lists, comms.size,
            wire_dtype, probe_mode, probe_wire_dtype)
        return record_dispatch(
            "dist_ivf_flat", model, trace_id, axis=comms.axis,
            thunk=lambda: _dist_search(
                queries, index.centers, index.data, index.data_norms,
                index.indices, axis=comms.axis, mesh=comms.mesh,
                n_probes=n_probes, k=k, metric=index.metric,
                probe_mode=probe_mode, query_axis=query_axis,
                coarse_algo=params.coarse_algo, scan_engine=scan_engine,
                wire_dtype=wire_dtype, probe_wire_dtype=probe_wire_dtype,
            ))


def build_streaming(
    res: Optional[Resources],
    comms: Comms,
    params: IvfFlatIndexParams,
    source,
    chunk_rows: int = 1 << 20,
    train_rows: int = 1 << 18,
) -> DistributedIvfFlat:
    """Stream a dataset larger than any single chip's HBM directly into
    the list-sharded index: the quantizer trains on a strided sample,
    then every prefetched chunk is scattered into the ALREADY-SHARDED
    device buffers (donated, so updates stay in place on their shards).
    This is the capacity story of the distributed index — the dataset
    never materializes on one device or in host memory.
    """
    res = ensure_resources(res)
    r = comms.size
    n_lists = -(-params.n_lists // r) * r
    params = dataclasses.replace(params, n_lists=n_lists,
                                 add_data_on_build=False)
    n, d = source.n_rows, source.dim

    with tracing.range("raft_tpu.distributed.ivf_flat.build_streaming"):
        # quantizer on a strided sample + per-chunk labels: the SAME
        # passes as the single-chip streaming builds — shared helpers,
        # not a re-implementation (each chunk a cancellation point)
        from raft_tpu.cluster.kmeans_balanced import KMeansBalancedParams
        from raft_tpu.neighbors._streaming import (
            label_pass,
            sample_trainset,
        )

        train_rows = max(n_lists, min(train_rows, n))
        trainset = sample_trainset(source, train_rows, chunk_rows)
        quant = ivf_flat_mod.build(res, params, trainset)

        km = KMeansBalancedParams(
            metric=(DistanceType.InnerProduct
                    if params.metric == DistanceType.InnerProduct
                    else DistanceType.L2Expanded))
        labels_np, sizes_np = label_pass(res, km, quant.centers, source,
                                         chunk_rows, n_lists)
        max_size = padded_extent(sizes_np)

        # deal lists round-robin by population; dealt[i] = original list
        deal = deal_order(sizes_np, r)
        dealt_pos = np.empty((n_lists,), np.int32)
        dealt_pos[deal] = np.arange(n_lists, dtype=np.int32)

        shard = comms.sharding(comms.axis)
        # gate the per-shard staging BEFORE the sharded buffers (and
        # the norms plane derived later) allocate — planned shapes,
        # nothing materialized yet
        admit_deal(
            (jax.ShapeDtypeStruct((n_lists, max_size, d), jnp.float32),
             jax.ShapeDtypeStruct((n_lists, max_size), jnp.int32),
             jax.ShapeDtypeStruct((n_lists, max_size), jnp.float32)),
            r, "distributed.ivf_flat.build_streaming.deal")
        data = jax.device_put(
            jnp.zeros((n_lists, max_size, d), jnp.float32), shard)
        indices = jax.device_put(
            jnp.full((n_lists, max_size), -1, jnp.int32), shard)

        @partial(jax.jit, donate_argnums=(0, 1))
        def scatter_chunk(data, idx, rows, ids, list_ids, ranks):
            return (data.at[list_ids, ranks].set(rows),
                    idx.at[list_ids, ranks].set(ids))

        fill = np.zeros((n_lists,), np.int64)
        for first, chunk in source.iter_chunks(chunk_rows):
            interruptible.yield_()  # cancellation point per chunk
            m = chunk.shape[0]
            lab = labels_np[first : first + m]
            corder = np.argsort(lab, kind="stable")
            sl = lab[corder]
            first_pos = np.searchsorted(sl, np.arange(n_lists))
            rank_sorted = np.arange(m) - first_pos[sl] + fill[sl]
            ranks = np.empty((m,), np.int32)
            ranks[corder] = rank_sorted.astype(np.int32)
            np.add.at(fill, lab, 1)
            data, indices = scatter_chunk(
                data, indices,
                jnp.asarray(chunk, jnp.float32),
                jnp.asarray(first + np.arange(m, dtype=np.int32)),
                jnp.asarray(dealt_pos[lab]),
                jnp.asarray(ranks),
            )

        @jax.jit
        def make_norms(data, indices):
            norms = jnp.sum(jnp.square(data), axis=2)
            return jnp.where(indices >= 0, norms, jnp.inf)

        return DistributedIvfFlat(
            comms=comms,
            centers=place_dealt(quant.centers, deal, comms),
            data=data,
            data_norms=make_norms(data, indices),
            indices=indices,
            list_sizes=jax.device_put(
                jnp.asarray(sizes_np[deal], jnp.int32), shard),
            metric=DistanceType(params.metric),
        )


# ---------------------------------------------------------------------------
# distributed IVF-PQ — the SIFT-1B-scale configuration: compressed codes
# sharded over the mesh, per-subspace codebooks replicated
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class DistributedIvfPq:
    """List-sharded IVF-PQ index (codes + ids sharded on the list axis,
    rotation replicated). PER_SUBSPACE codebooks are replicated;
    PER_CLUSTER codebooks are per-list data and shard with the lists."""

    comms: Comms
    centers: jax.Array        # (n_lists, dim) sharded on axis 0
    rotation: jax.Array       # (dim_ext, dim) replicated
    codebooks: jax.Array      # PER_SUBSPACE: (pq_dim, 2^bits, pq_len) repl.
                              # PER_CLUSTER:  (n_lists, 2^bits, pq_len) shard.
    codes: jax.Array          # (n_lists, max_list_size, pq_dim) u8 sharded
    indices: jax.Array        # (n_lists, max_list_size) int32 sharded
    list_sizes: jax.Array     # (n_lists,) sharded
    metric: DistanceType
    pq_bits: int
    codebook_kind: CodebookKind = CodebookKind.PER_SUBSPACE

    @property
    def n_lists(self) -> int:
        return self.centers.shape[0]

    @property
    def dim(self) -> int:
        return self.centers.shape[1]

    @property
    def max_list_size(self) -> int:
        return self.codes.shape[1]

    @property
    def pq_dim(self) -> int:
        return self.codes.shape[2]

    @property
    def pq_len(self) -> int:
        return self.codebooks.shape[2]

    @property
    def size(self) -> int:
        return int(jax.device_get(self.list_sizes).sum())


def build_pq(
    res: Optional[Resources],
    comms: Comms,
    params: IvfPqIndexParams,
    dataset,
) -> DistributedIvfPq:
    """Build + deal, like :func:`build`. PER_SUBSPACE codebooks are
    replicated; PER_CLUSTER codebooks are per-list data and are dealt +
    sharded together with the lists they describe."""
    res = ensure_resources(res)
    r = comms.size
    n_lists = -(-params.n_lists // r) * r
    params = dataclasses.replace(params, n_lists=n_lists)

    with tracing.range("raft_tpu.distributed.ivf_pq.build"):
        index = ivf_pq_mod.build(res, params, dataset)
        codes = index.codes
        if index.packed:
            # the distributed scan uses the unpacked layout
            from raft_tpu.neighbors.ivf_pq import _unpack_nibbles

            codes = _unpack_nibbles(codes)
            index = dataclasses.replace(index, codes=codes, packed=False)

        sizes = np.asarray(jax.device_get(index.list_sizes))
        perm = deal_order(sizes, r)
        per_cluster = params.codebook_kind == CodebookKind.PER_CLUSTER
        admit_deal(
            (index.centers, index.codes, index.indices,
             index.list_sizes)
            + ((index.codebooks,) if per_cluster else ()),
            r, "distributed.ivf_pq.build.deal")

        def place(a):
            return place_dealt(a, perm, comms)

        rep = comms.replicated()
        return DistributedIvfPq(
            comms=comms,
            centers=place(index.centers),
            rotation=jax.device_put(index.rotation, rep),
            codebooks=(place(index.codebooks) if per_cluster
                       else jax.device_put(index.codebooks, rep)),
            codes=place(index.codes),
            indices=place(index.indices),
            list_sizes=place(index.list_sizes),
            metric=index.metric,
            pq_bits=index.pq_bits,
            codebook_kind=params.codebook_kind,
        )


def _dist_search_pq_fn(queries, centers, rotation, codebooks, codes,
                       indices, init_d=None, init_i=None,
                       probe_counts=None, n_valid=None, row_probes=None,
                       *, axis: str,
                       mesh, n_probes: int, k: int, metric: DistanceType,
                       probe_mode: str, query_axis: Optional[str] = None,
                       codebook_kind: CodebookKind = (
                           CodebookKind.PER_SUBSPACE),
                       score_mode: str = "gather", lut_dtype=jnp.float32,
                       coarse_algo: str = "exact",
                       scan_engine: str = "rank",
                       wire_dtype: str = "f32",
                       probe_wire_dtype: str = "f32"):
    """Distributed ADC probe scan — same engine plumbing as
    :func:`_dist_search_fn` (``scan_engine: xla`` is the list-major
    union scan of :mod:`raft_tpu.neighbors.ivf_pq`, run per shard with
    not-owned probes masked to the sentinel id), including the optional
    donated list-sharded ``probe_counts`` plane (owned probes only)
    and the optional ragged ``row_probes`` budget plane (see
    :func:`_dist_search_fn`)."""
    select_min = is_min_close(metric)
    pad_val = jnp.inf if select_min else -jnp.inf
    pq_dim = codes.shape[2]
    pq_len = codebooks.shape[2]
    ip_metric = metric == DistanceType.InnerProduct
    per_cluster = codebook_kind == CodebookKind.PER_CLUSTER
    score = ivf_pq_mod.score_fn(score_mode, codebooks.shape[1])
    ragged = row_probes is not None

    if init_d is None:
        init_d = jnp.full((queries.shape[0], k), pad_val, jnp.float32)
    if init_i is None:
        init_i = jnp.full((queries.shape[0], k), -1, jnp.int32)

    def body(centers_l, books_l, codes_l, ids_l, qs, ind, ini, *rest):
        rest = list(rest)
        rp = rest.pop(0) if ragged else None
        cnt, nv = rest if rest else (None, None)
        q = qs.shape[0]
        n_local = centers_l.shape[0]
        qf = qs.astype(jnp.float32)

        # graftflight phase markers (see _dist_search_fn): pure HLO
        # op-path metadata for measured per-phase device attribution
        with jax.named_scope("coarse_select"):
            ip = jax.lax.dot_general(
                qf, centers_l, (((1,), (1,)), ((), ())),
                precision=jax.lax.Precision.HIGHEST,
                preferred_element_type=jnp.float32,
            )
            if ip_metric:
                coarse = -ip
            else:
                cn = jnp.sum(jnp.square(centers_l), axis=1)
                coarse = cn[None, :] - 2.0 * ip

            local, mine = select_probes_sharded(coarse, n_probes, axis,
                                                probe_mode, coarse_algo,
                                                probe_wire_dtype)
            if rp is not None:
                from raft_tpu.ops.ivf_scan import ragged_owned

                mine = ragged_owned(
                    mine, rp,
                    shards=(mesh.shape[axis]
                            if probe_mode == "local" else 1))
        if cnt is not None:
            from raft_tpu.ops.ivf_scan import probe_histogram

            cnt = probe_histogram(local, cnt, nv, owned=mine)

        qsub_fixed = (qf @ rotation.T).reshape(q, pq_dim, pq_len)
        lut_fixed = (jnp.einsum("qsl,sjl->qsj", qsub_fixed, books_l)
                     if ip_metric and not per_cluster else None)

        def probe_dist(lists, rows, row_ids):
            c = jnp.take(centers_l, lists, axis=0)        # (q, dim)
            lut, base = ivf_pq_mod._probe_lut(
                qf, c, qsub_fixed, lut_fixed, rotation, books_l, lists,
                ip_metric, per_cluster)
            lut, lut_scale = ivf_pq_mod.quantize_lut(lut, lut_dtype)
            dist = score(lut, rows)
            if lut_scale is not None:
                dist = dist * lut_scale
            dist = dist + base[:, None]
            return jnp.where(row_ids >= 0, dist, pad_val)

        if scan_engine != "rank":
            # list-major union scan (the single-chip ivf_pq "xla"
            # engine inside the shard body): min-space with the
            # smallest-id tie-break, not-owned probes masked out
            from raft_tpu.ops.ivf_scan import (
                _merge_smallest_id,
                unique_lists,
            )

            masked = jnp.where(mine, local, n_local).astype(jnp.int32)

            def step(carry, lid):
                best_d, best_i = carry
                lidc = jnp.minimum(lid, n_local - 1)       # sentinel-safe
                lists = jnp.full((q,), lidc, jnp.int32)
                rows1 = jax.lax.dynamic_index_in_dim(codes_l, lidc, 0,
                                                     False)
                ids1 = jax.lax.dynamic_index_in_dim(ids_l, lidc, 0, False)
                rows = jnp.broadcast_to(rows1[None], (q,) + rows1.shape)
                row_ids = jnp.broadcast_to(ids1[None], (q, ids1.shape[0]))
                dist = probe_dist(lists, rows, row_ids)
                if not select_min:
                    dist = -dist                           # to min-space
                probed = (jnp.any(masked == lid, axis=1)
                          & (lid < n_local))               # membership
                dist = jnp.where(probed[:, None], dist, jnp.inf)
                return _merge_smallest_id(best_d, best_i, dist, row_ids,
                                          k), None

            init = (jnp.full_like(ind, jnp.inf), jnp.full_like(ini, -1))
            with jax.named_scope("scan"):
                (best_d, best_i), _ = jax.lax.scan(
                    step, init, unique_lists(masked, n_local))
            if not select_min:
                best_d = -best_d
        else:
            def step(carry, rank_i):
                best_d, best_i = carry
                lists = local[:, rank_i]
                valid = mine[:, rank_i]
                rows = jnp.take(codes_l, lists, axis=0)    # (q, m, s) u8
                row_ids = jnp.take(ids_l, lists, axis=0)
                dist = probe_dist(lists, rows, row_ids)
                dist = jnp.where(valid[:, None], dist, pad_val)
                return merge_topk(best_d, best_i, dist, row_ids, k,
                                  select_min), None

            init = (jnp.full_like(ind, pad_val), jnp.full_like(ini, -1))
            with jax.named_scope("scan"):
                (best_d, best_i), _ = jax.lax.scan(
                    step, init, jnp.arange(local.shape[1]))

        with jax.named_scope("merge"):
            # 2-D grids scatter-merge: each list shard merges a
            # disjoint query slice instead of the whole replicated
            # candidate table (bit-identical — rank-order stacks)
            merged = merge_results_sharded(
                best_d, best_i, axis, select_min, wire_dtype,
                smallest_id_ties=scan_engine != "rank",
                scatter=query_axis is not None)
        if cnt is not None:
            return merged + (cnt,)
        return merged

    qspec = P() if query_axis is None else P(query_axis, None)
    bspec = P(axis, None, None) if per_cluster else P(None, None, None)
    args = [centers, codebooks, codes, indices, queries, init_d, init_i]
    in_specs = [P(axis, None), bspec, P(axis, None, None), P(axis, None),
                qspec, qspec, qspec]
    out_specs = [qspec, qspec]
    if ragged:
        args += [row_probes]
        in_specs += [P()]           # replicated per-row budget plane
    if probe_counts is not None:
        args += [probe_counts, n_valid]
        in_specs += [P(axis), P()]
        out_specs += [P(axis)]
    outs = shard_map(
        body, mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=tuple(out_specs),
        check_vma=False,
    )(*args)
    out_d, out_i = outs[0], outs[1]

    if metric == DistanceType.L2SqrtExpanded:
        out_d = jnp.where(jnp.isfinite(out_d),
                          jnp.sqrt(jnp.maximum(out_d, 0.0)), out_d)
    if probe_counts is not None:
        return out_d, out_i, outs[2]
    return out_d, out_i


_dist_search_pq = partial(jax.jit, static_argnames=(
    "axis", "mesh", "n_probes", "k", "metric", "probe_mode", "query_axis",
    "codebook_kind", "score_mode", "lut_dtype", "coarse_algo",
    "scan_engine", "wire_dtype", "probe_wire_dtype"))(_dist_search_pq_fn)


def _dist_search_ragged_pq_fn(queries, row_probes, centers, rotation,
                              codebooks, codes, indices, init_d=None,
                              init_i=None, probe_counts=None,
                              n_valid=None, *, axis: str, mesh,
                              n_probes: int, k: int,
                              metric: DistanceType, probe_mode: str,
                              codebook_kind: CodebookKind = (
                                  CodebookKind.PER_SUBSPACE),
                              score_mode: str = "gather",
                              lut_dtype=jnp.float32,
                              scan_engine: str = "xla",
                              wire_dtype: str = "f32",
                              probe_wire_dtype: str = "f32"):
    """Packed ragged-batch mesh PQ search — see
    :func:`_dist_search_ragged_fn` for the replicated-tile contract;
    per-row budgets fold into the shard body's ownership mask and the
    LUT union scan serves the packed tile unchanged."""
    expect(scan_engine == "xla",
           "mesh ragged PQ serving needs the membership-masked "
           f"list-major engine ('xla'), got {scan_engine!r}")
    return _dist_search_pq_fn(
        queries, centers, rotation, codebooks, codes, indices, init_d,
        init_i, probe_counts, n_valid, row_probes=row_probes, axis=axis,
        mesh=mesh, n_probes=n_probes, k=k, metric=metric,
        probe_mode=probe_mode, codebook_kind=codebook_kind,
        score_mode=score_mode, lut_dtype=lut_dtype,
        coarse_algo="exact", scan_engine=scan_engine,
        wire_dtype=wire_dtype, probe_wire_dtype=probe_wire_dtype)


def search_pq(
    res: Optional[Resources],
    params: IvfPqSearchParams,
    index: DistributedIvfPq,
    queries,
    k: int,
    probe_mode: str = "global",
    query_axis: Optional[str] = None,
    wire_dtype: str = "f32",
    probe_wire_dtype: str = "f32",
    trace_id: Optional[int] = None,
) -> Tuple[jax.Array, jax.Array]:
    """One-program distributed PQ search (LUT scoring per shard, lean
    global merge); semantics of :func:`search` incl. the 2-D
    ``query_axis``, the ``wire_dtype`` result compression, the
    ``probe_wire_dtype`` quantized probe-candidate exchange, and the
    opt-in ``trace_id`` mesh span recording. The probe
    scan follows ``params.scan_engine`` (``auto|xla|rank``, resolved by
    :func:`raft_tpu.neighbors.ivf_pq.resolve_scan_engine`)."""
    ensure_resources(res)
    queries = jnp.asarray(queries)
    expect(queries.ndim == 2 and queries.shape[1] == index.dim,
           "queries must be (q, dim)")
    comms = index.comms
    qsharding = resolve_query_sharding(comms, queries, query_axis)
    n_probes = resolve_probe_budget(params.n_probes, index.n_lists,
                                    comms.size, probe_mode)
    expect(params.coarse_algo in ("exact", "approx"),
           f"coarse_algo must be 'exact' or 'approx', got "
           f"{params.coarse_algo!r}")
    wire_dtype, probe_wire_dtype = resolve_auto_wires(
        queries.shape[0], k, n_probes, index.n_lists, comms.size,
        wire_dtype, probe_mode, probe_wire_dtype)
    resolve_wire_dtype(wire_dtype)
    resolve_probe_wire_dtype(probe_wire_dtype)
    scan_engine = ivf_pq_mod.resolve_scan_engine(params.scan_engine)
    queries = jax.device_put(queries, qsharding)
    with tracing.range("raft_tpu.distributed.ivf_pq.search"):
        # lazy: only a traced dispatch (trace_id=) builds the model
        model = lambda: collective_payload_model(  # noqa: E731
            queries.shape[0], k, n_probes, index.n_lists, comms.size,
            wire_dtype, probe_mode, probe_wire_dtype)
        return record_dispatch(
            "dist_ivf_pq", model, trace_id, axis=comms.axis,
            thunk=lambda: _dist_search_pq(
                queries, index.centers, index.rotation, index.codebooks,
                index.codes, index.indices, axis=comms.axis,
                mesh=comms.mesh, n_probes=n_probes, k=k,
                metric=index.metric, probe_mode=probe_mode,
                query_axis=query_axis, codebook_kind=index.codebook_kind,
                score_mode=params.score_mode, lut_dtype=params.lut_dtype,
                coarse_algo=params.coarse_algo, scan_engine=scan_engine,
                wire_dtype=wire_dtype, probe_wire_dtype=probe_wire_dtype,
            ))
