"""SPMD distributed IVF-Flat — the index itself sharded over a mesh axis.

The reference scales IVF via raft-dask's index-per-worker pattern (host
orchestration + ``knn_merge_parts``). The TPU-native form keeps ONE
logical index whose inverted lists are block-sharded over the mesh
(``jax.sharding``): every chip owns ``n_lists / R`` lists, the coarse
quantizer is replicated, and a single jitted ``shard_map`` program does

    local coarse top-p  →  local probe scan  →  all_gather + merge

so the collectives ride ICI and no host round-trips happen per query
(SURVEY.md §5 "TPU equivalent" note; the merge is the
``knn_merge_parts`` pattern inside the program).

Probe semantics (``probe_mode``):

- ``"global"`` (default, exact): every shard ranks ALL centers (they're
  cheap and replicated through an all_gather of the local slices),
  takes the global top-``n_probes``, and scans the probed lists it
  owns, masking the rest. Results match the single-device index
  exactly; per-chip wall-clock is ~the single-chip search, while HBM
  capacity scales with the mesh — the point of sharding at 1B rows.
- ``"local"`` (approximate, fast): each shard probes its own top
  ``ceil(n_probes / R)`` local lists. Lists are dealt round-robin by
  size at build time so relevant lists spread evenly; the union
  closely tracks the global top-``n_probes`` (the approximation
  sharded FAISS-IVF deployments make). Per-chip scan work drops by R.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from raft_tpu.comms.comms import Comms, allgather
from raft_tpu.core import interruptible, tracing
from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.core.validation import expect
from raft_tpu.distance.types import DistanceType, is_min_close
from raft_tpu.matrix.select_k import merge_topk
from raft_tpu.neighbors import ivf_flat as ivf_flat_mod
from raft_tpu.neighbors import ivf_pq as ivf_pq_mod
from raft_tpu.neighbors._batching import coarse_select
from raft_tpu.neighbors._packing import padded_extent
from raft_tpu.neighbors.brute_force import knn_merge_parts
from raft_tpu.neighbors.ivf_flat import IvfFlatIndexParams, IvfFlatSearchParams
from raft_tpu.neighbors.ivf_pq import (
    CodebookKind,
    IvfPqIndexParams,
    IvfPqSearchParams,
)


@dataclasses.dataclass(frozen=True)
class DistributedIvfFlat:
    """List-sharded IVF-Flat index.

    Arrays with a leading ``n_lists`` axis are sharded over ``comms``'s
    mesh axis; ``centers`` is replicated (every shard needs the full
    codebook only for its local slice — centers are stored sharded too,
    matching the list assignment).
    """

    comms: Comms
    centers: jax.Array        # (n_lists, d) sharded on axis 0
    data: jax.Array           # (n_lists, max_list_size, d) sharded
    data_norms: jax.Array     # (n_lists, max_list_size) sharded
    indices: jax.Array        # (n_lists, max_list_size) int32 sharded
    list_sizes: jax.Array     # (n_lists,) sharded
    metric: DistanceType

    @property
    def n_lists(self) -> int:
        return self.centers.shape[0]

    @property
    def dim(self) -> int:
        return self.centers.shape[1]

    @property
    def size(self) -> int:
        return int(jax.device_get(self.list_sizes).sum())


def deal_order(sizes: np.ndarray, r: int) -> np.ndarray:
    """Round-robin deal by descending population — THE list-to-shard
    layout policy, shared by build, build_pq and checkpoint restore:
    shard s gets every r-th list of the size-sorted order, so per-shard
    scan work and list relevance stay balanced at any shard count."""
    order = np.argsort(-np.asarray(sizes), kind="stable")
    return np.concatenate([order[s::r] for s in range(r)])


def select_probes_sharded(coarse, n_probes: int, axis: str,
                          probe_mode: str, coarse_algo: str = "exact"):
    """Shared probe selection inside a shard_map body — THE
    probe-ownership arithmetic for every list-sharded index family.

    ``coarse`` is this shard's (q, n_local) min-close coarse distances.
    Returns ``(local, mine)``: per-(query, probe-rank) local list ids
    and a mask of the probes this shard owns.

    - ``"global"``: all_gather every shard's coarse block, take the
      global top-``n_probes``, keep the locally-owned ones.
    - ``"local"``: each shard probes its own top-``n_probes`` lists.

    ``coarse_algo="approx"`` swaps the probe top-k for the TPU's
    native approximate top-k unit, via the same
    :func:`raft_tpu.neighbors._batching.coarse_select` dispatch the
    single-chip searches use.
    """
    q, n_local = coarse.shape
    if probe_mode == "global":
        coarse_all = allgather(coarse, axis)              # (R, q, L)
        r = coarse_all.shape[0]
        coarse_flat = jnp.moveaxis(coarse_all, 0, 1).reshape(
            q, r * n_local)
        probes = coarse_select(-coarse_flat, n_probes, coarse_algo)
        owner = probes // n_local
        local = probes - owner * n_local
        mine = owner == jax.lax.axis_index(axis)
        return local, mine
    probes = coarse_select(-coarse, n_probes, coarse_algo)
    return probes, jnp.ones(probes.shape, jnp.bool_)


def resolve_query_sharding(comms: Comms, queries, query_axis):
    """Shared ``query_axis`` validation + placement for the 2-D
    list×query grids: returns the sharding the replicated-or-sharded
    queries should be placed with."""
    if query_axis is not None:
        expect(query_axis in comms.mesh.axis_names
               and query_axis != comms.axis,
               f"query_axis {query_axis!r} must be another mesh axis")
        expect(queries.shape[0] % comms.mesh.shape[query_axis] == 0,
               "the query-axis size must divide the query count evenly")
        return comms.sharding(query_axis)
    return comms.replicated()


def resolve_probe_budget(n_probes: int, n_lists: int, mesh_size: int,
                         probe_mode: str) -> int:
    """Shared probe-budget clamp for the list-sharded search entries:
    validates ``probe_mode`` and converts the user's global probe count
    into this mode's per-program budget (local mode probes each shard's
    own ``ceil(n_probes / R)`` lists)."""
    expect(probe_mode in ("global", "local"),
           f"probe_mode must be 'global' or 'local', got {probe_mode!r}")
    local_lists = n_lists // mesh_size
    n_probes = min(n_probes, n_lists)
    if probe_mode == "local":
        n_probes = min(-(-n_probes // mesh_size), local_lists)
    return n_probes


def build(
    res: Optional[Resources],
    comms: Comms,
    params: IvfFlatIndexParams,
    dataset,
) -> DistributedIvfFlat:
    """Build a list-sharded index: global balanced-kmeans quantizer, then
    lists dealt round-robin by population and placed shard-local.

    ``params.n_lists`` is rounded up to a multiple of the mesh-axis size.
    """
    res = ensure_resources(res)
    r = comms.size
    n_lists = -(-params.n_lists // r) * r
    params = dataclasses.replace(params, n_lists=n_lists)

    with tracing.range("raft_tpu.distributed.ivf_flat.build"):
        # single-chip build (global quantizer + packed lists), then deal
        index = ivf_flat_mod.build(res, params, dataset)

        # blocked layout wants shard-contiguous rows: permute to
        # [shard0 lists..., shard1 lists...] per the shared deal policy
        sizes = np.asarray(jax.device_get(index.list_sizes))
        perm = jnp.asarray(deal_order(sizes, r), jnp.int32)

        shard = comms.sharding(comms.axis)              # P(axis) on dim 0
        def place(a):
            return jax.device_put(jnp.take(a, perm, axis=0), shard)

        return DistributedIvfFlat(
            comms=comms,
            centers=place(index.centers),
            data=place(index.data),
            data_norms=place(index.data_norms),
            indices=place(index.indices),
            list_sizes=place(index.list_sizes),
            metric=index.metric,
        )


@partial(jax.jit, static_argnames=("axis", "mesh", "n_probes", "k", "metric",
                                   "probe_mode", "query_axis", "coarse_algo"))
def _dist_search(centers, data, data_norms, indices, queries,
                 axis: str, mesh, n_probes: int, k: int,
                 metric: DistanceType, probe_mode: str,
                 query_axis: Optional[str] = None,
                 coarse_algo: str = "exact"):
    select_min = is_min_close(metric)
    pad_val = jnp.inf if select_min else -jnp.inf

    def body(centers_l, data_l, norms_l, ids_l, qs):
        q = qs.shape[0]
        n_local = centers_l.shape[0]
        qf = qs.astype(jnp.float32)

        # coarse distances to this shard's centers
        ip = jax.lax.dot_general(
            qf, centers_l, (((1,), (1,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32,
        )
        if metric == DistanceType.InnerProduct:
            coarse = -ip
        else:
            cn = jnp.sum(jnp.square(centers_l), axis=1)
            coarse = cn[None, :] - 2.0 * ip

        local, mine = select_probes_sharded(coarse, n_probes, axis,
                                            probe_mode, coarse_algo)

        def step(carry, rank_i):
            best_d, best_i = carry
            lists = local[:, rank_i]
            valid = mine[:, rank_i]
            rows = jnp.take(data_l, lists, axis=0).astype(jnp.float32)
            row_norms = jnp.take(norms_l, lists, axis=0)
            row_ids = jnp.take(ids_l, lists, axis=0)
            ipr = jax.lax.dot_general(
                rows, qf, (((2,), (1,)), ((0,), (0,))),
                precision=jax.lax.Precision.HIGHEST,
                preferred_element_type=jnp.float32,
            )
            if metric == DistanceType.InnerProduct:
                dist = ipr
            else:
                dist = row_norms - 2.0 * ipr
            dist = jnp.where((row_ids >= 0) & valid[:, None], dist, pad_val)
            return merge_topk(best_d, best_i, dist, row_ids, k,
                              select_min), None

        init = (jnp.full((q, k), pad_val, jnp.float32),
                jnp.full((q, k), -1, jnp.int32))
        (best_d, best_i), _ = jax.lax.scan(
            step, init, jnp.arange(local.shape[1]))

        all_d = allgather(best_d, axis)                  # (R, q, k)
        all_i = allgather(best_i, axis)
        return knn_merge_parts(all_d, all_i, select_min)

    # 2-D grid: queries shard over a second mesh axis while lists shard
    # over the first — the reference's row/col process grid
    # (``sub_comms.hpp``). Each device handles its (list-block,
    # query-block) cell; merges stay within the list axis.
    qspec = P() if query_axis is None else P(query_axis, None)
    out_d, out_i = jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(axis, None), P(axis, None, None), P(axis, None),
                  P(axis, None), qspec),
        out_specs=(qspec, qspec),
        check_vma=False,
    )(centers, data, data_norms, indices, queries)

    if metric != DistanceType.InnerProduct:
        q_sq = jnp.sum(jnp.square(queries.astype(jnp.float32)), axis=1,
                       keepdims=True)
        out_d = jnp.where(jnp.isfinite(out_d),
                          jnp.maximum(out_d + q_sq, 0.0), out_d)
        if metric == DistanceType.L2SqrtExpanded:
            out_d = jnp.where(jnp.isfinite(out_d), jnp.sqrt(out_d), out_d)
    return out_d, out_i


def search(
    res: Optional[Resources],
    params: IvfFlatSearchParams,
    index: DistributedIvfFlat,
    queries,
    k: int,
    probe_mode: str = "global",
    query_axis: Optional[str] = None,
) -> Tuple[jax.Array, jax.Array]:
    """One-program distributed search; returns replicated (q, k) results
    with global row ids. See the module docstring for ``probe_mode``.
    ``query_axis`` names a second mesh axis to shard queries over (2-D
    list × query grid); results come back sharded over that axis."""
    ensure_resources(res)
    queries = jnp.asarray(queries)
    expect(queries.ndim == 2 and queries.shape[1] == index.dim,
           "queries must be (q, dim)")
    comms = index.comms
    qsharding = resolve_query_sharding(comms, queries, query_axis)
    n_probes = resolve_probe_budget(params.n_probes, index.n_lists,
                                    comms.size, probe_mode)
    expect(params.coarse_algo in ("exact", "approx"),
           f"coarse_algo must be 'exact' or 'approx', got "
           f"{params.coarse_algo!r}")
    queries = jax.device_put(queries, qsharding)
    with tracing.range("raft_tpu.distributed.ivf_flat.search"):
        return _dist_search(
            index.centers, index.data, index.data_norms, index.indices,
            queries, comms.axis, comms.mesh, n_probes, k, index.metric,
            probe_mode, query_axis, params.coarse_algo,
        )


def build_streaming(
    res: Optional[Resources],
    comms: Comms,
    params: IvfFlatIndexParams,
    source,
    chunk_rows: int = 1 << 20,
    train_rows: int = 1 << 18,
) -> DistributedIvfFlat:
    """Stream a dataset larger than any single chip's HBM directly into
    the list-sharded index: the quantizer trains on a strided sample,
    then every prefetched chunk is scattered into the ALREADY-SHARDED
    device buffers (donated, so updates stay in place on their shards).
    This is the capacity story of the distributed index — the dataset
    never materializes on one device or in host memory.
    """
    res = ensure_resources(res)
    r = comms.size
    n_lists = -(-params.n_lists // r) * r
    params = dataclasses.replace(params, n_lists=n_lists,
                                 add_data_on_build=False)
    n, d = source.n_rows, source.dim

    with tracing.range("raft_tpu.distributed.ivf_flat.build_streaming"):
        # quantizer on a strided sample + per-chunk labels: the SAME
        # passes as the single-chip streaming builds — shared helpers,
        # not a re-implementation (each chunk a cancellation point)
        from raft_tpu.cluster.kmeans_balanced import KMeansBalancedParams
        from raft_tpu.neighbors._streaming import (
            label_pass,
            sample_trainset,
        )

        train_rows = max(n_lists, min(train_rows, n))
        trainset = sample_trainset(source, train_rows, chunk_rows)
        quant = ivf_flat_mod.build(res, params, trainset)

        km = KMeansBalancedParams(
            metric=(DistanceType.InnerProduct
                    if params.metric == DistanceType.InnerProduct
                    else DistanceType.L2Expanded))
        labels_np, sizes_np = label_pass(res, km, quant.centers, source,
                                         chunk_rows, n_lists)
        max_size = padded_extent(sizes_np)

        # deal lists round-robin by population; dealt[i] = original list
        order = np.argsort(-sizes_np, kind="stable")
        deal = np.concatenate([order[s::r] for s in range(r)])
        dealt_pos = np.empty((n_lists,), np.int32)
        dealt_pos[deal] = np.arange(n_lists, dtype=np.int32)

        shard = comms.sharding(comms.axis)
        data = jax.device_put(
            jnp.zeros((n_lists, max_size, d), jnp.float32), shard)
        indices = jax.device_put(
            jnp.full((n_lists, max_size), -1, jnp.int32), shard)

        @partial(jax.jit, donate_argnums=(0, 1))
        def scatter_chunk(data, idx, rows, ids, list_ids, ranks):
            return (data.at[list_ids, ranks].set(rows),
                    idx.at[list_ids, ranks].set(ids))

        fill = np.zeros((n_lists,), np.int64)
        for first, chunk in source.iter_chunks(chunk_rows):
            interruptible.yield_()  # cancellation point per chunk
            m = chunk.shape[0]
            lab = labels_np[first : first + m]
            corder = np.argsort(lab, kind="stable")
            sl = lab[corder]
            first_pos = np.searchsorted(sl, np.arange(n_lists))
            rank_sorted = np.arange(m) - first_pos[sl] + fill[sl]
            ranks = np.empty((m,), np.int32)
            ranks[corder] = rank_sorted.astype(np.int32)
            np.add.at(fill, lab, 1)
            data, indices = scatter_chunk(
                data, indices,
                jnp.asarray(chunk, jnp.float32),
                jnp.asarray(first + np.arange(m, dtype=np.int32)),
                jnp.asarray(dealt_pos[lab]),
                jnp.asarray(ranks),
            )

        @jax.jit
        def make_norms(data, indices):
            norms = jnp.sum(jnp.square(data), axis=2)
            return jnp.where(indices >= 0, norms, jnp.inf)

        perm = jnp.asarray(deal, jnp.int32)
        return DistributedIvfFlat(
            comms=comms,
            centers=jax.device_put(jnp.take(quant.centers, perm, axis=0),
                                   shard),
            data=data,
            data_norms=make_norms(data, indices),
            indices=indices,
            list_sizes=jax.device_put(
                jnp.asarray(sizes_np[deal], jnp.int32), shard),
            metric=DistanceType(params.metric),
        )


# ---------------------------------------------------------------------------
# distributed IVF-PQ — the SIFT-1B-scale configuration: compressed codes
# sharded over the mesh, per-subspace codebooks replicated
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class DistributedIvfPq:
    """List-sharded IVF-PQ index (codes + ids sharded on the list axis,
    rotation replicated). PER_SUBSPACE codebooks are replicated;
    PER_CLUSTER codebooks are per-list data and shard with the lists."""

    comms: Comms
    centers: jax.Array        # (n_lists, dim) sharded on axis 0
    rotation: jax.Array       # (dim_ext, dim) replicated
    codebooks: jax.Array      # PER_SUBSPACE: (pq_dim, 2^bits, pq_len) repl.
                              # PER_CLUSTER:  (n_lists, 2^bits, pq_len) shard.
    codes: jax.Array          # (n_lists, max_list_size, pq_dim) u8 sharded
    indices: jax.Array        # (n_lists, max_list_size) int32 sharded
    list_sizes: jax.Array     # (n_lists,) sharded
    metric: DistanceType
    pq_bits: int
    codebook_kind: CodebookKind = CodebookKind.PER_SUBSPACE

    @property
    def n_lists(self) -> int:
        return self.centers.shape[0]

    @property
    def dim(self) -> int:
        return self.centers.shape[1]

    @property
    def pq_dim(self) -> int:
        return self.codes.shape[2]

    @property
    def pq_len(self) -> int:
        return self.codebooks.shape[2]

    @property
    def size(self) -> int:
        return int(jax.device_get(self.list_sizes).sum())


def build_pq(
    res: Optional[Resources],
    comms: Comms,
    params: IvfPqIndexParams,
    dataset,
) -> DistributedIvfPq:
    """Build + deal, like :func:`build`. PER_SUBSPACE codebooks are
    replicated; PER_CLUSTER codebooks are per-list data and are dealt +
    sharded together with the lists they describe."""
    res = ensure_resources(res)
    r = comms.size
    n_lists = -(-params.n_lists // r) * r
    params = dataclasses.replace(params, n_lists=n_lists)

    with tracing.range("raft_tpu.distributed.ivf_pq.build"):
        index = ivf_pq_mod.build(res, params, dataset)
        codes = index.codes
        if index.packed:
            # the distributed scan uses the unpacked layout
            from raft_tpu.neighbors.ivf_pq import _unpack_nibbles

            codes = _unpack_nibbles(codes)
            index = dataclasses.replace(index, codes=codes, packed=False)

        sizes = np.asarray(jax.device_get(index.list_sizes))
        perm = jnp.asarray(deal_order(sizes, r), jnp.int32)

        shard = comms.sharding(comms.axis)
        def place(a):
            return jax.device_put(jnp.take(a, perm, axis=0), shard)

        rep = comms.replicated()
        per_cluster = params.codebook_kind == CodebookKind.PER_CLUSTER
        return DistributedIvfPq(
            comms=comms,
            centers=place(index.centers),
            rotation=jax.device_put(index.rotation, rep),
            codebooks=(place(index.codebooks) if per_cluster
                       else jax.device_put(index.codebooks, rep)),
            codes=place(index.codes),
            indices=place(index.indices),
            list_sizes=place(index.list_sizes),
            metric=index.metric,
            pq_bits=index.pq_bits,
            codebook_kind=params.codebook_kind,
        )


@partial(jax.jit, static_argnames=("axis", "mesh", "n_probes", "k", "metric",
                                   "probe_mode", "query_axis",
                                   "codebook_kind", "score_mode", "lut_dtype",
                                   "coarse_algo"))
def _dist_search_pq(centers, rotation, codebooks, codes, indices, queries,
                    axis: str, mesh, n_probes: int, k: int,
                    metric: DistanceType, probe_mode: str,
                    query_axis: Optional[str] = None,
                    codebook_kind: CodebookKind = CodebookKind.PER_SUBSPACE,
                    score_mode: str = "gather",
                    lut_dtype=jnp.float32,
                    coarse_algo: str = "exact"):
    select_min = is_min_close(metric)
    pad_val = jnp.inf if select_min else -jnp.inf
    pq_dim = codes.shape[2]
    pq_len = codebooks.shape[2]
    ip_metric = metric == DistanceType.InnerProduct
    per_cluster = codebook_kind == CodebookKind.PER_CLUSTER
    score = ivf_pq_mod.score_fn(score_mode, codebooks.shape[1])

    def body(centers_l, books_l, codes_l, ids_l, qs):
        q = qs.shape[0]
        n_local = centers_l.shape[0]
        qf = qs.astype(jnp.float32)

        ip = jax.lax.dot_general(
            qf, centers_l, (((1,), (1,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32,
        )
        if ip_metric:
            coarse = -ip
        else:
            cn = jnp.sum(jnp.square(centers_l), axis=1)
            coarse = cn[None, :] - 2.0 * ip

        local, mine = select_probes_sharded(coarse, n_probes, axis,
                                            probe_mode, coarse_algo)

        qsub_fixed = (qf @ rotation.T).reshape(q, pq_dim, pq_len)
        lut_fixed = (jnp.einsum("qsl,sjl->qsj", qsub_fixed, books_l)
                     if ip_metric and not per_cluster else None)

        def step(carry, rank_i):
            best_d, best_i = carry
            lists = local[:, rank_i]
            valid = mine[:, rank_i]
            c = jnp.take(centers_l, lists, axis=0)        # (q, dim)
            lut, base = ivf_pq_mod._probe_lut(
                qf, c, qsub_fixed, lut_fixed, rotation, books_l, lists,
                ip_metric, per_cluster)
            lut, lut_scale = ivf_pq_mod.quantize_lut(lut, lut_dtype)
            rows = jnp.take(codes_l, lists, axis=0)       # (q, m, s) u8
            row_ids = jnp.take(ids_l, lists, axis=0)
            dist = score(lut, rows)
            if lut_scale is not None:
                dist = dist * lut_scale
            dist = dist + base[:, None]
            dist = jnp.where((row_ids >= 0) & valid[:, None], dist, pad_val)
            return merge_topk(best_d, best_i, dist, row_ids, k,
                              select_min), None

        init = (jnp.full((q, k), pad_val, jnp.float32),
                jnp.full((q, k), -1, jnp.int32))
        (best_d, best_i), _ = jax.lax.scan(
            step, init, jnp.arange(local.shape[1]))

        all_d = allgather(best_d, axis)
        all_i = allgather(best_i, axis)
        return knn_merge_parts(all_d, all_i, select_min)

    qspec = P() if query_axis is None else P(query_axis, None)
    bspec = P(axis, None, None) if per_cluster else P(None, None, None)
    out_d, out_i = jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(axis, None), bspec, P(axis, None, None), P(axis, None),
                  qspec),
        out_specs=(qspec, qspec),
        check_vma=False,
    )(centers, codebooks, codes, indices, queries)

    if metric == DistanceType.L2SqrtExpanded:
        out_d = jnp.where(jnp.isfinite(out_d),
                          jnp.sqrt(jnp.maximum(out_d, 0.0)), out_d)
    return out_d, out_i


def search_pq(
    res: Optional[Resources],
    params: IvfPqSearchParams,
    index: DistributedIvfPq,
    queries,
    k: int,
    probe_mode: str = "global",
    query_axis: Optional[str] = None,
) -> Tuple[jax.Array, jax.Array]:
    """One-program distributed PQ search (LUT scoring per shard, global
    merge); semantics of :func:`search` incl. the 2-D ``query_axis``."""
    ensure_resources(res)
    queries = jnp.asarray(queries)
    expect(queries.ndim == 2 and queries.shape[1] == index.dim,
           "queries must be (q, dim)")
    comms = index.comms
    qsharding = resolve_query_sharding(comms, queries, query_axis)
    n_probes = resolve_probe_budget(params.n_probes, index.n_lists,
                                    comms.size, probe_mode)
    expect(params.coarse_algo in ("exact", "approx"),
           f"coarse_algo must be 'exact' or 'approx', got "
           f"{params.coarse_algo!r}")
    queries = jax.device_put(queries, qsharding)
    with tracing.range("raft_tpu.distributed.ivf_pq.search"):
        return _dist_search_pq(
            index.centers, index.rotation, index.codebooks, index.codes,
            index.indices, queries, comms.axis, comms.mesh, n_probes, k,
            index.metric, probe_mode, query_axis,
            index.codebook_kind, params.score_mode, params.lut_dtype,
            params.coarse_algo,
        )
