"""Distributed (multi-chip / multi-host) algorithms — the consumer side
of :mod:`raft_tpu.comms`, replacing the reference's raft-dask MNMG layer
(SURVEY.md §2.6, §3.5).

Two composition patterns, mirroring the reference:

- **SPMD over a mesh** (``shard_map`` + collectives): distributed k-means
  (psum'd center updates — the ``calc_centers_and_sizes`` + allreduce
  pattern) and distributed brute-force kNN (per-shard top-k + all-gather
  merge, replacing ``knn_merge_parts``).
- **index-per-shard** (host orchestration): ANN indexes built per shard
  and merged at query time — raft-dask's index-per-worker pattern.
"""

from raft_tpu.distributed import ivf as ivf_flat
from raft_tpu.distributed import bq as ivf_bq
from raft_tpu.distributed import checkpoint
from raft_tpu.distributed.bq import DistributedIvfBq
from raft_tpu.distributed.ivf import DistributedIvfFlat, DistributedIvfPq
from raft_tpu.distributed.kmeans import fit as kmeans_fit
from raft_tpu.distributed.knn import brute_force_knn, brute_force_knn_ring
from raft_tpu.distributed.sharded_ann import ShardedIndex, build_sharded

__all__ = [
    "DistributedIvfBq",
    "DistributedIvfFlat",
    "DistributedIvfPq",
    "checkpoint",
    "ivf_bq",
    "ivf_flat",
    "kmeans_fit",
    "brute_force_knn",
    "brute_force_knn_ring",
    "ShardedIndex",
    "build_sharded",
]
