"""Distributed exact kNN — per-shard top-k + all-gather merge, and a
ring-pass variant for sharded query sets.

This is the TPU-native form of the reference's MNMG search pattern:
raft-dask shards the dataset one part per worker, each worker runs local
brute force, and ``knn_merge_parts`` (``detail/knn_merge_parts.cuh``)
fuses the per-part results. Here the dataset is row-sharded over a mesh
axis, the local scan runs per shard under ``shard_map``, and the merge is
an ``all_gather`` + top-k — XLA rides the ICI ring for the gather.

:func:`brute_force_knn_ring` is the sequence-parallel-style form (the
ring-attention communication pattern applied to search): queries are
ALSO sharded, and each query block circulates the mesh ring via
``ppermute``, merging a running top-k against each dataset shard it
visits. Per-device memory is O(n/R + q/R) with no replication, and the
block transfer overlaps the local scan — the pattern that scales query
batches to multi-host meshes.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from raft_tpu.comms.comms import (
    Comms,
    allgather,
    device_sendrecv,
    mark_varying,
    rank,
    shard_map,
)
from raft_tpu.core import tracing
from raft_tpu.core.validation import expect
from raft_tpu.distance.pairwise import _pairwise_distance_impl
from raft_tpu.distance.types import DistanceType, is_min_close
from raft_tpu.matrix.select_k import merge_topk
from raft_tpu.neighbors.brute_force import knn_merge_parts


def _traced_knn_dispatch(family: str, trace_id, q: int, k: int,
                         r: int, axis: str, thunk):
    """Opt-in graftscope-v2 span recording for the exact-kNN mesh
    programs — a thin phase adapter over the shared
    :func:`raft_tpu.distributed.ivf.record_dispatch` protocol: kNN has
    no coarse phase (the scan + one merge collective IS the program),
    so the merge span carries the modeled per-shard gather payload
    (the (q, k) distance+id pairs each of the ``r`` shards
    contributes) and the coarse phase is simply absent. ``axis`` is
    the caller's mesh axis (span attr)."""
    from raft_tpu.distributed.ivf import record_dispatch

    merge_bytes = q * k * 8          # f32 distance + int32 id per slot
    return record_dispatch(
        family, None, trace_id, thunk, axis=axis,
        phases={"scan": {"modeled": True, "wire_bytes": 0},
                "merge": {"modeled": True, "wire_bytes": merge_bytes}},
        modeled_bytes=float(merge_bytes), attrs={"shards": r})


def brute_force_knn(
    comms: Comms,
    dataset,
    queries,
    k: int,
    metric: DistanceType = DistanceType.L2Expanded,
    metric_arg: float = 2.0,
    db_tile: int = 32768,
    trace_id: Optional[int] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Exact kNN over a row-sharded dataset.

    Args:
      comms: mesh/axis handle; ``dataset`` is (re)sharded over its axis.
      dataset: (n, d) — placed row-sharded if not already.
      queries: (q, d) — replicated to every shard.
      k: neighbors per query.
      trace_id: opt-in mesh span recording (blocks + times the
        dispatch — :func:`_traced_knn_dispatch`).

    Returns (distances (q, k), global indices (q, k) int32), identical to
    single-device ``brute_force.knn`` up to tie ordering.
    """
    dataset = jnp.asarray(dataset)
    queries = jnp.asarray(queries)
    expect(dataset.ndim == 2 and queries.ndim == 2, "2-D inputs required")
    expect(dataset.shape[0] % comms.size == 0,
           "dataset rows must divide the mesh axis (pad the dataset)")
    n_local = dataset.shape[0] // comms.size
    expect(0 < k <= n_local, "k must be <= rows per shard")
    select_min = is_min_close(metric)
    axis = comms.axis

    dataset = jax.device_put(dataset, comms.row_sharded())
    queries = jax.device_put(queries, comms.replicated())
    tile = min(db_tile, max(128, n_local))

    @partial(jax.jit, static_argnames=())
    def _run(ds, qs):
        def body(ds_local, qs_rep):
            d_loc, i_loc = _local_scan(qs_rep, ds_local, k, metric,
                                       metric_arg, tile, select_min, axis)
            i_glob = i_loc + rank(axis) * n_local
            all_d = allgather(d_loc, axis)            # (R, q, k)
            all_i = allgather(i_glob, axis)
            return knn_merge_parts(all_d, all_i, select_min)

        # the merged result is replicated (identical on every shard) but
        # post-all_gather values can't be statically proven so; skip the
        # vma check
        return shard_map(
            body, mesh=comms.mesh, in_specs=(P(axis, None), P()),
            out_specs=(P(), P()), check_vma=False,
        )(ds, qs)

    with tracing.range("raft_tpu.distributed.brute_force_knn"):
        return _traced_knn_dispatch(
            "dist_knn", trace_id, queries.shape[0], k, comms.size,
            comms.axis, lambda: _run(dataset, queries))


def brute_force_knn_ring(
    comms: Comms,
    dataset,
    queries,
    k: int,
    metric: DistanceType = DistanceType.L2Expanded,
    metric_arg: float = 2.0,
    db_tile: int = 32768,
    trace_id: Optional[int] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Exact kNN with BOTH dataset and queries row-sharded; query blocks
    circulate the ring (``ppermute``) so nothing is ever replicated.

    After R ring steps every query block has been scanned against every
    dataset shard and is back on its home device carrying its merged
    top-k. Returns (distances, global indices) sharded like the queries.
    """
    dataset = jnp.asarray(dataset)
    queries = jnp.asarray(queries)
    expect(dataset.ndim == 2 and queries.ndim == 2, "2-D inputs required")
    R = comms.size
    expect(dataset.shape[0] % R == 0,
           "dataset rows must divide the mesh axis (pad the dataset)")
    expect(queries.shape[0] % R == 0,
           "query rows must divide the mesh axis (pad the queries)")
    n_local = dataset.shape[0] // R
    expect(0 < k <= n_local, "k must be <= rows per shard")
    select_min = is_min_close(metric)
    axis = comms.axis
    tile = min(db_tile, max(128, n_local))
    perm = [(i, (i + 1) % R) for i in range(R)]

    dataset = jax.device_put(dataset, comms.row_sharded())
    queries = jax.device_put(queries, comms.row_sharded())

    @jax.jit
    def _run(ds, qs):
        def body(ds_local, qs_local):
            pad_val = jnp.inf if select_min else -jnp.inf
            qb = qs_local.shape[0]
            state = (
                qs_local,
                jnp.full((qb, k), pad_val, jnp.float32),
                jnp.full((qb, k), -1, jnp.int32),
            )
            my_base = rank(axis) * n_local
            # R scan+merge rounds, each followed by one ring hop; after
            # R hops the block is home with its full merge. A Python
            # loop (R is static) keeps each ppermute visible to XLA for
            # transfer/compute overlap.
            for _ in range(R):
                blk, best_d, best_i = state
                d_loc, i_loc = _local_scan(blk, ds_local, k, metric,
                                           metric_arg, tile, select_min,
                                           axis)
                best_d, best_i = merge_topk(
                    best_d, best_i, d_loc,
                    (i_loc + my_base).astype(jnp.int32), k, select_min)
                state = device_sendrecv((blk, best_d, best_i), perm,
                                        axis)
            _, best_d, best_i = state
            return best_d, best_i

        return shard_map(
            body, mesh=comms.mesh,
            in_specs=(P(axis, None), P(axis, None)),
            out_specs=(P(axis, None), P(axis, None)),
            check_vma=False,
        )(ds, qs)

    with tracing.range("raft_tpu.distributed.brute_force_knn_ring"):
        return _traced_knn_dispatch(
            "dist_knn_ring", trace_id, queries.shape[0], k, R,
            comms.axis, lambda: _run(dataset, queries))


def _local_scan(queries, dataset, k: int, metric, metric_arg, tile: int,
                select_min: bool, axis: Optional[str] = None):
    """Per-shard tiled scan (the single-device ``_knn_scan`` body inlined
    so it traces inside shard_map; ``axis`` marks the carry as
    device-varying for shard_map's vma check)."""
    n, d = dataset.shape
    q = queries.shape[0]
    pad_val = jnp.inf if select_min else -jnp.inf
    pad = (-n) % tile
    dsp = jnp.pad(dataset, ((0, pad), (0, 0)))
    tiles = dsp.reshape(-1, tile, d)

    def step(carry, inp):
        best_d, best_i = carry
        t_idx, yt = inp
        dist = _pairwise_distance_impl(queries, yt, metric, metric_arg,
                                       "highest")
        col_ids = t_idx * tile + jnp.arange(tile)
        dist = jnp.where((col_ids < n)[None, :], dist, pad_val)
        kk = min(k, tile)
        if select_min:
            tile_d, tile_i = jax.lax.top_k(-dist, kk)
            tile_d = -tile_d
        else:
            tile_d, tile_i = jax.lax.top_k(dist, kk)
        tile_gi = (t_idx * tile + tile_i).astype(jnp.int32)
        return merge_topk(best_d, best_i, tile_d, tile_gi, k, select_min), None

    init = (jnp.full((q, k), pad_val, jnp.float32),
            jnp.full((q, k), -1, jnp.int32))
    if axis is not None:
        # mark the carry device-varying for shard_map's vma check (the
        # pvary/pcast version shim lives in the comms veneer)
        init = mark_varying(init, axis)
    (best_d, best_i), _ = jax.lax.scan(
        step, init, (jnp.arange(tiles.shape[0]), tiles))
    return best_d, best_i
