"""SPMD distributed IVF-BQ — the RaBitQ residual sign-code index (1-4
bits/dim) list-sharded over a mesh axis (same layout policy as
:mod:`raft_tpu.distributed.ivf`: lists dealt round-robin by
population, coarse quantizer sharded with its lists, rotation
replicated, raw-vector rerank plane sharded with its lists). Search is
one jitted ``shard_map`` program: local coarse top-p → shard-local
scan → all_gather + merge.

The shard-local scan runs the single-chip engine family
(:mod:`raft_tpu.ops.bq_scan`): the fused estimate-then-rerank
list-major engines (``scan_engine: auto|pallas|xla`` — exact
distances, probes the shard does not own masked to the sentinel the
same way the flat/PQ paths do) or the legacy rank-major estimate scan
(``"rank"``, and every codes-only index).

**Variance-corrected merge** (the ROADMAP residual): the per-shard
estimator error bound is measured at build time (``shard_rel_err``,
from the dealt layout) and :func:`merge_overfetch` derives the fetch
depth the caller needs from it — instead of the flat 2× over-fetch
the estimate-only merge used to burn (recall 0.95 vs 0.99 at equal
budget). With the fused engines the exchanged distances are exact,
the merge is lossless, and the derived depth collapses to ``k``
outright. The wire discipline ((distance, id) candidates at the
requested depth, ``collective_payload_model`` accounting) is
unchanged — only how the depth is chosen moved, from a hand constant
to the measured bound.

Probe semantics (``probe_mode``) match the IVF-Flat/PQ paths:
``"global"`` ranks all centers for exact list selection; ``"local"``
probes each shard's own top lists.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from raft_tpu.comms.comms import (
    Comms,
    resolve_probe_wire_dtype,
    resolve_wire_dtype,
    shard_map,
)
from raft_tpu.core import tracing
from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.core.validation import expect
from raft_tpu.distance.types import DistanceType, is_min_close
from raft_tpu.matrix.select_k import merge_topk
from raft_tpu.neighbors import ivf_bq as ivf_bq_mod
from raft_tpu.neighbors._batching import tile_queries
from raft_tpu.neighbors.ivf_bq import (
    _OVERFETCH_KAPPA,
    IvfBqIndexParams,
    IvfBqSearchParams,
    score_probe,
)
from raft_tpu.distributed.ivf import (
    admit_deal,
    collective_payload_model,
    deal_order,
    merge_results_sharded,
    place_dealt,
    record_dispatch,
    resolve_probe_budget,
    resolve_query_sharding,
    select_probes_sharded,
)


@dataclasses.dataclass(frozen=True)
class DistributedIvfBq:
    """List-sharded IVF-BQ index (RaBitQ construction)."""

    comms: Comms
    centers: jax.Array        # (n_lists, dim) sharded on axis 0
    rotation: jax.Array       # (dim_ext, dim) replicated
    codes: jax.Array          # (n_lists, max, bits·D/32) i32 sharded
    rnorm: jax.Array          # (n_lists, max) f32 sharded — ‖r‖
    cfac: jax.Array           # (n_lists, max, bits) f32 sharded
    errw: jax.Array           # (n_lists, max) f32 sharded — ‖r−recon‖
    indices: jax.Array        # (n_lists, max) int32 sharded
    list_sizes: jax.Array     # (n_lists,) sharded
    metric: DistanceType
    # measured per-shard relative estimator error (host tuple, from
    # the dealt layout at build time) — the variance-corrected merge's
    # input; () means "unmeasured" and the merge falls back to the
    # most conservative shard-free bound
    shard_rel_err: tuple = ()
    # optional rerank plane (sharded with the lists)
    data: Optional[jax.Array] = None         # (n_lists, max, dim) f32
    data_norms: Optional[jax.Array] = None   # (n_lists, max) f32

    @property
    def n_lists(self) -> int:
        return self.centers.shape[0]

    @property
    def dim(self) -> int:
        return self.centers.shape[1]

    @property
    def dim_ext(self) -> int:
        return self.rotation.shape[0]

    @property
    def bits(self) -> int:
        return self.cfac.shape[2]

    @property
    def size(self) -> int:
        return int(jax.device_get(self.list_sizes).sum())


def shard_rel_err_from_arrays(errw, rnorm, indices, dim_ext: int,
                              perm, r: int) -> tuple:
    """Measured per-shard relative estimator error of a dealt layout:
    shard s owns lists ``perm[s·L:(s+1)·L]``, and its error statistic
    is the same ``rel_err`` knob :func:`raft_tpu.neighbors.ivf_bq
    .estimator_stats` measures index-wide — THE one implementation
    (``_OVERFETCH_KAPPA`` was calibrated against this exact
    statistic); build time and checkpoint restore both call it over
    host arrays in the pre-deal (global list id) order."""
    perm = np.asarray(perm)
    valid = np.asarray(indices) >= 0
    errw = np.asarray(errw)
    rn2 = np.square(np.asarray(rnorm))
    n_local = len(perm) // r
    out = []
    for s in range(r):
        lists = perm[s * n_local : (s + 1) * n_local]
        v = valid[lists]
        cnt = max(int(v.sum()), 1)
        mean_e = float(errw[lists][v].sum()) / cnt
        mean_rn2 = float(rn2[lists][v].sum()) / cnt
        rel = (2.0 * mean_e / (math.sqrt(dim_ext)
                               * math.sqrt(max(mean_rn2, 1e-20)))
               if mean_rn2 > 0 else 0.0)
        out.append(rel)
    return tuple(out)


def _shard_rel_err(index, perm: np.ndarray, r: int) -> tuple:
    """Build-time wrapper: ONE small device fetch of the single-chip
    planes, then the shared per-shard reduction."""
    return shard_rel_err_from_arrays(
        jax.device_get(index.errw), jax.device_get(index.rnorm),
        jax.device_get(index.indices), index.dim_ext, perm, r)


def merge_overfetch(index: DistributedIvfBq, k: int, *,
                    confidence: float = 1.0) -> int:
    """Variance-corrected merge budget: how deep to fetch through the
    sharded merge so the true top-k survives the exact re-rank at the
    stated confidence — the bound-derived replacement for the flat 2×
    caller-side over-fetch.

    An index carrying the rerank plane exchanges **exact** distances —
    the merge is lossless (the global top-k restricted to a shard lies
    inside that shard's top-k), so the budget is ``k`` outright.
    Estimate-only indexes over-fetch by the worst *measured* per-shard
    relative estimator error (the same bound-derived budget as the
    single-chip :func:`raft_tpu.neighbors.ivf_bq.overfetch_budget`,
    per shard — searched at this depth and refined host-side)."""
    expect(k >= 1, "k must be >= 1")
    if index.data is not None:
        return k
    worst = max(index.shard_rel_err) if index.shard_rel_err else 1.0
    return int(math.ceil(
        k * (1.0 + confidence * _OVERFETCH_KAPPA * worst)))


def build_bq(
    res: Optional[Resources],
    comms: Comms,
    params: IvfBqIndexParams,
    dataset,
) -> DistributedIvfBq:
    """Single-chip build, then deal + shard (the shared layout policy).
    ``params.n_lists`` is rounded up to a multiple of the mesh axis."""
    res = ensure_resources(res)
    r = comms.size
    n_lists = -(-params.n_lists // r) * r
    params = dataclasses.replace(params, n_lists=n_lists)

    with tracing.range("raft_tpu.distributed.ivf_bq.build"):
        index = ivf_bq_mod.build(res, params, dataset)
        sizes = np.asarray(jax.device_get(index.list_sizes))
        perm = deal_order(sizes, r)
        rel = _shard_rel_err(index, perm, r)
        # graftledger gate for the mesh deal (opt-in): per-shard slot
        # model of every dealt plane, incl. the optional rerank plane
        admit_deal(
            (index.centers, index.codes, index.rnorm, index.cfac,
             index.errw, index.indices, index.list_sizes, index.data,
             index.data_norms), r, "distributed.ivf_bq.build.deal")

        def place(a):
            # streamed per-shard deal — no fully-permuted build-device copy
            return place_dealt(a, perm, comms)

        return DistributedIvfBq(
            comms=comms,
            centers=place(index.centers),
            rotation=jax.device_put(index.rotation, comms.replicated()),
            codes=place(index.codes),
            rnorm=place(index.rnorm),
            cfac=place(index.cfac),
            errw=place(index.errw),
            indices=place(index.indices),
            list_sizes=place(index.list_sizes),
            metric=index.metric,
            shard_rel_err=rel,
            data=place(index.data) if index.data is not None else None,
            data_norms=(place(index.data_norms)
                        if index.data_norms is not None else None),
        )


def _dist_search_bq_fn(queries, centers, rotation, codes, rnorm, cfac,
                       errw, indices, data, data_norms, init_d=None,
                       init_i=None, probe_counts=None, n_valid=None,
                       row_probes=None, *,
                       axis: str, mesh, n_probes: int, k: int,
                       metric: DistanceType,
                       probe_mode: str, query_axis=None,
                       coarse_algo: str = "exact",
                       scan_engine: str = "rank",
                       epsilon: float = 3.0,
                       wire_dtype: str = "f32",
                       probe_wire_dtype: str = "f32"):
    """Distributed BQ probe scan: lean probe selection + shard-local
    scan (fused estimate-then-rerank engines or the legacy rank-major
    estimate scan) + O(q · merge_k) result merge. ``merge_k`` is the
    variance-corrected per-shard contribution (:func:`merge_overfetch`
    — ``wire_dtype`` compresses the gathered distances on the wire).
    ``init_d``/``init_i`` optionally provide the (q, merge_k) running
    top-k storage (values are reset here; the serving path donates
    them). ``probe_counts`` optionally provides the donated
    list-sharded (n_lists,) int32 probe-frequency plane (graftgauge —
    owned probes only, returned as a third output) and the optional
    ragged ``row_probes`` budget plane (see
    :func:`raft_tpu.distributed.ivf._dist_search_fn`). ``scan_engine``
    must arrive resolved (:func:`raft_tpu.ops.bq_scan
    .resolve_bq_engine`) — it is a jit static."""
    select_min = is_min_close(metric)
    pad_val = jnp.inf if select_min else -jnp.inf
    ip_metric = metric == DistanceType.InnerProduct
    ragged = row_probes is not None

    if init_d is None:
        init_d = jnp.full((queries.shape[0], k), pad_val, jnp.float32)
    if init_i is None:
        init_i = jnp.full((queries.shape[0], k), -1, jnp.int32)

    with_data = data is not None

    def body(centers_l, codes_l, rn_l, cf_l, ew_l, ids_l, *rest):
        rest = list(rest)
        if with_data:
            data_l, dn_l = rest[0], rest[1]
            rest = rest[2:]
        else:
            data_l, dn_l = None, None
        qs, ind, ini = rest[0], rest[1], rest[2]
        rest = rest[3:]
        rp = rest.pop(0) if ragged else None
        cnt, nv = rest if rest else (None, None)
        qf = qs.astype(jnp.float32)
        n_local = centers_l.shape[0]

        # graftflight phase markers (see ivf._dist_search_fn): pure
        # HLO op-path metadata for measured per-phase attribution
        with jax.named_scope("coarse_select"):
            ip = jax.lax.dot_general(
                qf, centers_l, (((1,), (1,)), ((), ())),
                precision=jax.lax.Precision.HIGHEST,
                preferred_element_type=jnp.float32,
            )
            if ip_metric:
                coarse = -ip
                cn = None
                qnorm = None
            else:
                cn = jnp.sum(jnp.square(centers_l), axis=1)
                coarse = cn[None, :] - 2.0 * ip
                qnorm = jnp.sum(jnp.square(qf), axis=1)

            local, mine = select_probes_sharded(coarse, n_probes, axis,
                                                probe_mode, coarse_algo,
                                                probe_wire_dtype)
            if rp is not None:
                from raft_tpu.ops.ivf_scan import ragged_owned

                mine = ragged_owned(
                    mine, rp,
                    shards=(mesh.shape[axis]
                            if probe_mode == "local" else 1))
        if cnt is not None:
            from raft_tpu.ops.ivf_scan import probe_histogram

            cnt = probe_histogram(local, cnt, nv, owned=mine)

        qrot = qf @ rotation.T
        centers_rot = centers_l @ rotation.T

        if scan_engine != "rank":
            # fused estimate-then-rerank on the shard's own lists:
            # not-owned probes mask to the sentinel id n_local — the
            # engines' shared membership predicate rejects them, the
            # exact machinery the flat/PQ shard bodies already use
            from raft_tpu.ops.bq_scan import bq_list_major_scan

            masked = jnp.where(mine, local, n_local)
            with jax.named_scope("scan"):
                best_d, best_i = bq_list_major_scan(
                    qf, qrot, centers_rot, codes_l, rn_l, cf_l, ew_l,
                    ids_l, data_l, dn_l, masked, None, ind, ini,
                    k=k, metric=metric, epsilon=epsilon,
                    engine=scan_engine,
                    interpret=jax.default_backend() != "tpu")
        else:
            def step(carry, rank_i):
                best_d, best_i = carry
                dist, row_ids = score_probe(
                    local[:, rank_i], qrot,
                    None if ip_metric else centers_rot, ip, cn, qnorm,
                    codes_l, rn_l, cf_l, ids_l, ip_metric, pad_val,
                    valid=mine[:, rank_i])
                return merge_topk(best_d, best_i, dist, row_ids, k,
                                  select_min), None

            init = (jnp.full_like(ind, pad_val), jnp.full_like(ini, -1))
            with jax.named_scope("scan"):
                (best_d, best_i), _ = jax.lax.scan(
                    step, init, jnp.arange(local.shape[1]))

        with jax.named_scope("merge"):
            # 2-D grids scatter-merge: each list shard merges a
            # disjoint query slice instead of the whole replicated
            # candidate table (bit-identical — rank-order stacks)
            merged = merge_results_sharded(
                best_d, best_i, axis, select_min, wire_dtype,
                smallest_id_ties=scan_engine != "rank",
                scatter=query_axis is not None)
        if cnt is not None:
            return merged + (cnt,)
        return merged

    qspec = P() if query_axis is None else P(query_axis, None)
    args = [centers, codes, rnorm, cfac, errw, indices]
    in_specs = [P(axis, None), P(axis, None, None), P(axis, None),
                P(axis, None, None), P(axis, None), P(axis, None)]
    if with_data:
        args += [data, data_norms]
        in_specs += [P(axis, None, None), P(axis, None)]
    args += [queries, init_d, init_i]
    in_specs += [qspec, qspec, qspec]
    out_specs = [qspec, qspec]
    if ragged:
        args += [row_probes]
        in_specs += [P()]           # replicated per-row budget plane
    if probe_counts is not None:
        args += [probe_counts, n_valid]
        in_specs += [P(axis), P()]
        out_specs += [P(axis)]
    outs = shard_map(
        body, mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=tuple(out_specs),
        check_vma=False,
    )(*args)
    out_d, out_i = outs[0], outs[1]

    if metric == DistanceType.L2SqrtExpanded:
        out_d = jnp.where(jnp.isfinite(out_d),
                          jnp.sqrt(jnp.maximum(out_d, 0.0)), out_d)
    if probe_counts is not None:
        return out_d, out_i, outs[2]
    return out_d, out_i


_dist_search_bq = partial(jax.jit, static_argnames=(
    "axis", "mesh", "n_probes", "k", "metric", "probe_mode",
    "query_axis", "coarse_algo", "scan_engine", "epsilon", "wire_dtype",
    "probe_wire_dtype"))(_dist_search_bq_fn)


def _dist_search_ragged_bq_fn(queries, row_probes, centers, rotation,
                              codes, rnorm, cfac, errw, indices, data,
                              data_norms, init_d=None, init_i=None,
                              probe_counts=None, n_valid=None, *,
                              axis: str, mesh, n_probes: int, k: int,
                              metric: DistanceType, probe_mode: str,
                              scan_engine: str = "xla",
                              epsilon: float = 3.0,
                              wire_dtype: str = "f32",
                              probe_wire_dtype: str = "f32"):
    """Packed ragged-batch mesh BQ search — see
    :func:`raft_tpu.distributed.ivf._dist_search_ragged_fn` for the
    replicated-tile contract. The fused estimate-then-rerank engines
    carry exact distances, so the lean merge stays lossless at the
    class-cap ``k`` and per-request ``k`` is the usual column slice.
    Fused engines only (a codes-only index resolves to the rank
    estimate scan and stays bucketed)."""
    expect(scan_engine in ("pallas", "xla"),
           "mesh ragged BQ serving needs a fused membership-masked "
           f"engine (pallas|xla), got {scan_engine!r}")
    return _dist_search_bq_fn(
        queries, centers, rotation, codes, rnorm, cfac, errw, indices,
        data, data_norms, init_d, init_i, probe_counts, n_valid,
        row_probes=row_probes, axis=axis, mesh=mesh, n_probes=n_probes,
        k=k, metric=metric, probe_mode=probe_mode, coarse_algo="exact",
        scan_engine=scan_engine, epsilon=epsilon, wire_dtype=wire_dtype,
        probe_wire_dtype=probe_wire_dtype)


def search_bq(
    res: Optional[Resources],
    params: IvfBqSearchParams,
    index: DistributedIvfBq,
    queries,
    k: int,
    probe_mode: str = "global",
    query_axis: Optional[str] = None,
    query_tile: int = 4096,
    wire_dtype: str = "f32",
    probe_wire_dtype: str = "f32",
    trace_id: Optional[int] = None,
) -> Tuple[jax.Array, jax.Array]:
    """One-program distributed BQ search at depth ``k``. With the
    fused engines (the default on an index carrying the rerank plane)
    the returned distances are **exact** and the merge is lossless —
    ask for the ``k`` you want. A codes-only index returns
    estimate-ranked candidates: pass ``k = merge_overfetch(index,
    want_k)`` (the variance-corrected merge budget derived from the
    measured per-shard estimator error) and re-rank host-side with
    :func:`raft_tpu.neighbors.refine`. Large query sets
    run in ``query_tile`` batches, bounding the per-shard
    intermediates like the single-chip path. ``query_axis`` names a
    second mesh axis to shard queries over; ``wire_dtype="bf16"``
    compresses the merge collective's distances; ``probe_wire_dtype``
    (``f32|bf16|int8``) compresses the probe-candidate exchange;
    ``trace_id`` opts into graftscope-v2 mesh span recording."""
    ensure_resources(res)
    queries = jnp.asarray(queries)
    expect(queries.ndim == 2 and queries.shape[1] == index.dim,
           "queries must be (q, dim)")
    comms = index.comms
    qsharding = resolve_query_sharding(comms, queries, query_axis)
    n_probes = resolve_probe_budget(params.n_probes, index.n_lists,
                                    comms.size, probe_mode)
    expect(params.coarse_algo in ("exact", "approx"),
           f"coarse_algo must be 'exact' or 'approx', got "
           f"{params.coarse_algo!r}")
    from raft_tpu.distributed.ivf import resolve_auto_wires

    wire_dtype, probe_wire_dtype = resolve_auto_wires(
        queries.shape[0], k, n_probes, index.n_lists, comms.size,
        wire_dtype, probe_mode, probe_wire_dtype)
    resolve_wire_dtype(wire_dtype)
    resolve_probe_wire_dtype(probe_wire_dtype)
    from raft_tpu.ops.bq_scan import resolve_bq_engine

    scan_engine = resolve_bq_engine(
        params.scan_engine, data=index.data, filter_words=None,
        k=k, dim_ext=index.dim_ext, bits=index.bits,
        n_probes=n_probes)
    queries = jax.device_put(queries, qsharding)
    with tracing.range("raft_tpu.distributed.ivf_bq.search"):
        def run(qt, _fw):
            return _dist_search_bq(
                qt, index.centers, index.rotation, index.codes,
                index.rnorm, index.cfac, index.errw, index.indices,
                index.data, index.data_norms,
                axis=comms.axis, mesh=comms.mesh, n_probes=n_probes,
                k=k, metric=index.metric,
                probe_mode=probe_mode, query_axis=query_axis,
                coarse_algo=params.coarse_algo, scan_engine=scan_engine,
                epsilon=params.epsilon, wire_dtype=wire_dtype,
                probe_wire_dtype=probe_wire_dtype,
            )

        # lazy: only a traced dispatch (trace_id=) builds the model
        model = lambda: collective_payload_model(  # noqa: E731
            queries.shape[0], k, n_probes, index.n_lists,
            comms.size, wire_dtype, probe_mode, probe_wire_dtype)
        if query_axis is not None:
            # already query-sharded: tiling would slice across the
            # shard layout and force a reshard per tile — run whole
            # (the 2-D grid is itself the large-batch mechanism)
            return record_dispatch("dist_ivf_bq", model, trace_id,
                                   lambda: run(queries, None),
                                   axis=comms.axis)
        return record_dispatch(
            "dist_ivf_bq", model, trace_id,
            lambda: tile_queries(run, queries, None, query_tile),
            axis=comms.axis)
