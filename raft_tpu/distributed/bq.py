"""SPMD distributed IVF-BQ — the residual sign-code index (1-4
bits/dim) list-sharded over a mesh
axis (same layout policy as :mod:`raft_tpu.distributed.ivf`: lists
dealt round-robin by population, coarse quantizer sharded with its
lists, rotation replicated). Search is one jitted ``shard_map``
program: local coarse top-p → local MXU sign-code scan →
all_gather + ``knn_merge_parts``.

Probe semantics (``probe_mode``) match the IVF-Flat/PQ paths:
``"global"`` ranks all centers for exact list selection; ``"local"``
probes each shard's own top lists (deeper over-fetch recommended —
sign-code estimates are noisy, see :mod:`raft_tpu.neighbors.ivf_bq`).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from raft_tpu.comms.comms import (
    Comms,
    resolve_probe_wire_dtype,
    resolve_wire_dtype,
    shard_map,
)
from raft_tpu.core import tracing
from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.core.validation import expect
from raft_tpu.distance.types import DistanceType, is_min_close
from raft_tpu.matrix.select_k import merge_topk
from raft_tpu.neighbors import ivf_bq as ivf_bq_mod
from raft_tpu.neighbors._batching import tile_queries
from raft_tpu.neighbors.ivf_bq import (
    IvfBqIndexParams,
    IvfBqSearchParams,
    score_probe,
)
from raft_tpu.distributed.ivf import (
    collective_payload_model,
    deal_order,
    merge_results_sharded,
    place_dealt,
    record_dispatch,
    resolve_probe_budget,
    resolve_query_sharding,
    select_probes_sharded,
)


@dataclasses.dataclass(frozen=True)
class DistributedIvfBq:
    """List-sharded IVF-BQ index."""

    comms: Comms
    centers: jax.Array        # (n_lists, dim) sharded on axis 0
    rotation: jax.Array       # (dim_ext, dim) replicated
    codes: jax.Array          # (n_lists, max_list_size, bits·D/8) u8 shard.
    scales: jax.Array         # (n_lists, max_list_size, bits) f32 sharded
    rnorm2: jax.Array         # (n_lists, max_list_size) f32 sharded
    indices: jax.Array        # (n_lists, max_list_size) int32 sharded
    list_sizes: jax.Array     # (n_lists,) sharded
    metric: DistanceType

    @property
    def n_lists(self) -> int:
        return self.centers.shape[0]

    @property
    def dim(self) -> int:
        return self.centers.shape[1]

    @property
    def bits(self) -> int:
        return self.scales.shape[2]

    @property
    def size(self) -> int:
        return int(jax.device_get(self.list_sizes).sum())


def build_bq(
    res: Optional[Resources],
    comms: Comms,
    params: IvfBqIndexParams,
    dataset,
) -> DistributedIvfBq:
    """Single-chip build, then deal + shard (the shared layout policy).
    ``params.n_lists`` is rounded up to a multiple of the mesh axis."""
    res = ensure_resources(res)
    r = comms.size
    n_lists = -(-params.n_lists // r) * r
    params = dataclasses.replace(params, n_lists=n_lists)

    with tracing.range("raft_tpu.distributed.ivf_bq.build"):
        index = ivf_bq_mod.build(res, params, dataset)
        sizes = np.asarray(jax.device_get(index.list_sizes))
        perm = deal_order(sizes, r)

        def place(a):
            # streamed per-shard deal — no fully-permuted build-device copy
            return place_dealt(a, perm, comms)

        return DistributedIvfBq(
            comms=comms,
            centers=place(index.centers),
            rotation=jax.device_put(index.rotation, comms.replicated()),
            codes=place(index.codes),
            scales=place(index.scales),
            rnorm2=place(index.rnorm2),
            indices=place(index.indices),
            list_sizes=place(index.list_sizes),
            metric=index.metric,
        )


def _dist_search_bq_fn(queries, centers, rotation, codes, scales, rn2,
                       indices, init_d=None, init_i=None,
                       probe_counts=None, n_valid=None, *, axis: str,
                       mesh, n_probes: int, k: int, metric: DistanceType,
                       probe_mode: str, query_axis=None,
                       coarse_algo: str = "exact",
                       wire_dtype: str = "f32",
                       probe_wire_dtype: str = "f32"):
    """Distributed sign-code probe scan: lean probe selection + local
    MXU scan + O(q · k) result merge (``wire_dtype`` compresses the
    gathered estimate distances; the positional ``knn_merge_parts``
    tie-break is kept so results match the single-chip BQ index).
    ``init_d``/``init_i`` optionally provide the (q, k) running top-k
    storage (values are reset here; the serving path donates them).
    ``probe_counts`` optionally provides the donated list-sharded
    (n_lists,) int32 probe-frequency plane (graftgauge — owned probes
    only, returned as a third output)."""
    select_min = is_min_close(metric)
    pad_val = jnp.inf if select_min else -jnp.inf
    ip_metric = metric == DistanceType.InnerProduct

    if init_d is None:
        init_d = jnp.full((queries.shape[0], k), pad_val, jnp.float32)
    if init_i is None:
        init_i = jnp.full((queries.shape[0], k), -1, jnp.int32)

    def body(centers_l, codes_l, scales_l, rn2_l, ids_l, qs, ind, ini,
             cnt=None, nv=None):
        qf = qs.astype(jnp.float32)

        ip = jax.lax.dot_general(
            qf, centers_l, (((1,), (1,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32,
        )
        if ip_metric:
            coarse = -ip
            cn = None
            qnorm = None
        else:
            cn = jnp.sum(jnp.square(centers_l), axis=1)
            coarse = cn[None, :] - 2.0 * ip
            qnorm = jnp.sum(jnp.square(qf), axis=1)

        local, mine = select_probes_sharded(coarse, n_probes, axis,
                                            probe_mode, coarse_algo,
                                            probe_wire_dtype)
        if cnt is not None:
            from raft_tpu.ops.ivf_scan import probe_histogram

            cnt = probe_histogram(local, cnt, nv, owned=mine)

        qrot = qf @ rotation.T
        centers_rot = None if ip_metric else centers_l @ rotation.T

        def step(carry, rank_i):
            best_d, best_i = carry
            dist, row_ids = score_probe(
                local[:, rank_i], qrot, centers_rot, ip, cn, qnorm,
                codes_l, scales_l, rn2_l, ids_l, ip_metric, pad_val,
                valid=mine[:, rank_i])
            return merge_topk(best_d, best_i, dist, row_ids, k,
                              select_min), None

        init = (jnp.full_like(ind, pad_val), jnp.full_like(ini, -1))
        (best_d, best_i), _ = jax.lax.scan(
            step, init, jnp.arange(local.shape[1]))

        merged = merge_results_sharded(best_d, best_i, axis, select_min,
                                       wire_dtype, smallest_id_ties=False)
        if cnt is not None:
            return merged + (cnt,)
        return merged

    qspec = P() if query_axis is None else P(query_axis, None)
    args = [centers, codes, scales, rn2, indices, queries, init_d, init_i]
    in_specs = [P(axis, None), P(axis, None, None),
                P(axis, None, None), P(axis, None), P(axis, None),
                qspec, qspec, qspec]
    out_specs = [qspec, qspec]
    if probe_counts is not None:
        args += [probe_counts, n_valid]
        in_specs += [P(axis), P()]
        out_specs += [P(axis)]
    outs = shard_map(
        body, mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=tuple(out_specs),
        check_vma=False,
    )(*args)
    out_d, out_i = outs[0], outs[1]

    if metric == DistanceType.L2SqrtExpanded:
        out_d = jnp.where(jnp.isfinite(out_d),
                          jnp.sqrt(jnp.maximum(out_d, 0.0)), out_d)
    if probe_counts is not None:
        return out_d, out_i, outs[2]
    return out_d, out_i


_dist_search_bq = partial(jax.jit, static_argnames=(
    "axis", "mesh", "n_probes", "k", "metric", "probe_mode", "query_axis",
    "coarse_algo", "wire_dtype", "probe_wire_dtype"))(_dist_search_bq_fn)


def search_bq(
    res: Optional[Resources],
    params: IvfBqSearchParams,
    index: DistributedIvfBq,
    queries,
    k: int,
    probe_mode: str = "global",
    query_axis: Optional[str] = None,
    query_tile: int = 4096,
    wire_dtype: str = "f32",
    probe_wire_dtype: str = "f32",
    trace_id: Optional[int] = None,
) -> Tuple[jax.Array, jax.Array]:
    """One-program distributed BQ search (estimated distances — refine
    host-side as with the single-chip index). Large query sets run in
    ``query_tile`` batches, bounding the per-shard unpacked-code
    intermediate like the single-chip path. ``query_axis`` names a
    second mesh axis to shard queries over (the 2-D list×query grid,
    matching :func:`raft_tpu.distributed.ivf.search_pq`);
    ``wire_dtype="bf16"`` compresses the merge collective's distances
    (sign-code estimates are already coarse — the cheap payload win);
    ``probe_wire_dtype`` (``f32|bf16|int8``) compresses the
    probe-candidate exchange (see
    :func:`raft_tpu.distributed.ivf.select_probes_sharded`);
    ``trace_id`` opts into graftscope-v2 mesh span recording (the
    dispatch then blocks and times —
    :func:`raft_tpu.distributed.ivf.record_dispatch`)."""
    ensure_resources(res)
    queries = jnp.asarray(queries)
    expect(queries.ndim == 2 and queries.shape[1] == index.dim,
           "queries must be (q, dim)")
    comms = index.comms
    qsharding = resolve_query_sharding(comms, queries, query_axis)
    n_probes = resolve_probe_budget(params.n_probes, index.n_lists,
                                    comms.size, probe_mode)
    expect(params.coarse_algo in ("exact", "approx"),
           f"coarse_algo must be 'exact' or 'approx', got "
           f"{params.coarse_algo!r}")
    resolve_wire_dtype(wire_dtype)
    resolve_probe_wire_dtype(probe_wire_dtype)
    queries = jax.device_put(queries, qsharding)
    with tracing.range("raft_tpu.distributed.ivf_bq.search"):
        def run(qt, _fw):
            return _dist_search_bq(
                qt, index.centers, index.rotation, index.codes,
                index.scales, index.rnorm2, index.indices,
                axis=comms.axis, mesh=comms.mesh, n_probes=n_probes,
                k=k, metric=index.metric, probe_mode=probe_mode,
                query_axis=query_axis, coarse_algo=params.coarse_algo,
                wire_dtype=wire_dtype,
                probe_wire_dtype=probe_wire_dtype,
            )

        # lazy: only a traced dispatch (trace_id=) builds the model
        model = lambda: collective_payload_model(  # noqa: E731
            queries.shape[0], k, n_probes, index.n_lists, comms.size,
            wire_dtype, probe_mode, probe_wire_dtype)
        if query_axis is not None:
            # already query-sharded: tiling would slice across the
            # shard layout and force a reshard per tile — run whole
            # (the 2-D grid is itself the large-batch mechanism)
            return record_dispatch("dist_ivf_bq", model, trace_id,
                                   lambda: run(queries, None),
                                   axis=comms.axis)
        return record_dispatch(
            "dist_ivf_bq", model, trace_id,
            lambda: tile_queries(run, queries, None, query_tile),
            axis=comms.axis)
