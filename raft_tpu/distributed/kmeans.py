"""Distributed k-means — the psum'd EM the reference runs MNMG via
allreduce (SURVEY.md §7 step 7: "kmeans EM with psum of per-shard
centers/sizes — exactly mirrors ``calc_centers_and_sizes`` + allreduce").

One ``shard_map``-ed program: each shard labels its rows against the
replicated centers (MXU GEMM), computes local center sums/counts, and a
``psum`` across the mesh axis produces the global M-step. Convergence is
checked on the psum'd inertia, like the reference's per-iteration
inertia reduction (``cluster/detail/kmeans.cuh``).
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from raft_tpu.cluster.kmeans import _kmeanspp_init
from raft_tpu.comms.comms import (
    QUANT_BLOCK,
    REDUCE_WIRE_DTYPES,
    Comms,
    Op,
    allreduce,
    allreduce_quantized,
    shard_map,
)
from raft_tpu.core import tracing
from raft_tpu.core.validation import expect


def collective_payload_model(n_clusters: int, dim: int,
                             wire_dtype: str = "f32",
                             block: int = QUANT_BLOCK) -> dict:
    """Modeled per-EM-iteration wire bytes per shard — the build-side
    twin of :func:`raft_tpu.distributed.ivf.collective_payload_model`
    (what the bench rider emits next to the measured A/B, and what
    ``wire_dtype="auto"`` argmins over).

    ``sums_bytes`` prices the centroid-sum allreduce on the chosen
    wire (int8 adds one f32 scale per :data:`QUANT_BLOCK` feature
    block per centroid); ``counts_bytes`` is the exact int32 count
    reduction, wire-dtype-independent by design."""
    itemsize = {"f32": 4, "bf16": 2, "int8": 1}[wire_dtype]
    nb = -(-dim // block)
    scale = n_clusters * nb * 4 if wire_dtype == "int8" else 0
    sums = n_clusters * dim * itemsize + scale
    counts = n_clusters * 4
    return {
        "sums_bytes": sums,
        "counts_bytes": counts,
        "iter_bytes": sums + counts,
        "wire_dtype": wire_dtype,
    }


def resolve_kmeans_wire(wire_dtype: str, n_clusters: int,
                        dim: int) -> str:
    """Resolve the EM ``wire_dtype``: ``"auto"`` argmins the modeled
    per-iteration bytes (:func:`collective_payload_model`) over the
    reduce-wire formats — the byte accounting closing its own loop;
    ties prefer the wider (less lossy) wire."""
    if wire_dtype == "auto":
        return min(REDUCE_WIRE_DTYPES,
                   key=lambda wd: collective_payload_model(
                       n_clusters, dim, wd)["iter_bytes"])
    if wire_dtype not in REDUCE_WIRE_DTYPES:
        raise ValueError(
            f"wire_dtype must be 'auto' or one of {REDUCE_WIRE_DTYPES}, "
            f"got {wire_dtype!r}")
    return wire_dtype


def fit(
    comms: Comms,
    x,
    n_clusters: int,
    n_iters: int = 20,
    seed: int = 0,
    wire_dtype: str = "f32",
    params=None,
) -> Tuple[jax.Array, jax.Array]:
    """Fit k-means over a row-sharded dataset.

    Returns (centers (k, d) replicated, inertia scalar). Matches the
    single-device :func:`raft_tpu.cluster.kmeans.fit` EM up to shard
    summation order.

    ``wire_dtype`` (``f32|bf16|int8|auto``, default exact f32 — also
    settable via :class:`raft_tpu.cluster.kmeans.KMeansParams`
    ``.wire_dtype``) compresses the per-iteration centroid-sum
    allreduce on the wire (EQuARX block-wise scales,
    :func:`raft_tpu.comms.comms.allreduce_quantized`); the count
    reduction always rides the exact int32 wire and the convergence
    inertia stays f32, so a narrow wire perturbs only the M-step's
    summed coordinates — convergence vs the f32 EM is pinned in
    ``tests/test_comms.py``. ``"auto"`` argmins the modeled
    per-iteration bytes (:func:`collective_payload_model`).

    ``params`` (a :class:`raft_tpu.cluster.kmeans.KMeansParams`)
    optionally carries the wire choice instead: its ``.wire_dtype``
    wins over the keyword when given — the opt-in surface callers who
    already thread KMeansParams use.
    """
    if params is not None:
        wire_dtype = params.wire_dtype
    wire_dtype = resolve_kmeans_wire(wire_dtype, n_clusters,
                                     jnp.asarray(x).shape[-1])
    x = jnp.asarray(x, jnp.float32)
    expect(x.ndim == 2, "x must be (n, d)")
    n, d = x.shape
    expect(n % comms.size == 0,
           "rows must divide the mesh axis (pad the dataset)")
    expect(n_clusters <= n, "n_clusters > n_rows")
    axis = comms.axis

    # kmeans++ init on a strided subsample (replicated), then the
    # sharded EM — the reference MNMG kmeans seeds on one worker and
    # broadcasts too. The subsample must cover n_clusters distinct picks.
    sub_size = min(n, max(2048, 4 * n_clusters))
    sub = x[:: max(1, n // sub_size)][:sub_size]
    expect(n_clusters <= sub.shape[0], "n_clusters exceeds init subsample")
    centers0 = _kmeanspp_init(jax.random.key(seed), sub, n_clusters)
    x = jax.device_put(x, comms.row_sharded())
    centers0 = jax.device_put(centers0, comms.replicated())

    @partial(jax.jit, static_argnames=())
    def _run(x_sh, c0):
        def body(x_loc, c0_rep):
            def em(_, centers):
                d2 = (
                    jnp.sum(jnp.square(x_loc), 1)[:, None]
                    - 2.0 * x_loc @ centers.T
                    + jnp.sum(jnp.square(centers), 1)[None, :]
                )
                labels = jnp.argmin(d2, axis=1)
                sums = jax.ops.segment_sum(x_loc, labels,
                                           num_segments=n_clusters)
                if wire_dtype == "f32":
                    sums = allreduce(sums, Op.SUM, axis)
                    counts = allreduce(jax.ops.segment_sum(
                        jnp.ones((x_loc.shape[0],), jnp.float32),
                        labels, num_segments=n_clusters), Op.SUM, axis)
                else:
                    # quantized centroid-sum wire; counts ride the
                    # exact int32 path inside the same veneer
                    sums = allreduce_quantized(sums, Op.SUM, axis,
                                               wire_dtype=wire_dtype)
                    counts = allreduce_quantized(jax.ops.segment_sum(
                        jnp.ones((x_loc.shape[0],), jnp.int32),
                        labels, num_segments=n_clusters),
                        Op.SUM, axis).astype(jnp.float32)
                new = sums / jnp.maximum(counts, 1.0)[:, None]
                return jnp.where((counts > 0)[:, None], new, centers)

            centers = jax.lax.fori_loop(0, n_iters, em, c0_rep)
            d2 = (
                jnp.sum(jnp.square(x_loc), 1)[:, None]
                - 2.0 * x_loc @ centers.T
                + jnp.sum(jnp.square(centers), 1)[None, :]
            )
            inertia = allreduce(jnp.sum(jnp.min(d2, axis=1)), Op.SUM, axis)
            return centers, inertia

        # check_vma=False: the quantized allreduce's gather+sum epilog
        # is replicated by construction but not statically inferrable
        # (same stance as the serving fns)
        return shard_map(
            body, mesh=comms.mesh, in_specs=(P(axis, None), P()),
            out_specs=(P(), P()), check_vma=False,
        )(x_sh, c0)

    with tracing.range("raft_tpu.distributed.kmeans_fit"):
        return _run(x, centers0)
