"""Distributed k-means — the psum'd EM the reference runs MNMG via
allreduce (SURVEY.md §7 step 7: "kmeans EM with psum of per-shard
centers/sizes — exactly mirrors ``calc_centers_and_sizes`` + allreduce").

One ``shard_map``-ed program: each shard labels its rows against the
replicated centers (MXU GEMM), computes local center sums/counts, and a
``psum`` across the mesh axis produces the global M-step. Convergence is
checked on the psum'd inertia, like the reference's per-iteration
inertia reduction (``cluster/detail/kmeans.cuh``).
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from raft_tpu.cluster.kmeans import _kmeanspp_init
from raft_tpu.comms.comms import Comms, Op, allreduce, shard_map
from raft_tpu.core import tracing
from raft_tpu.core.validation import expect


def fit(
    comms: Comms,
    x,
    n_clusters: int,
    n_iters: int = 20,
    seed: int = 0,
) -> Tuple[jax.Array, jax.Array]:
    """Fit k-means over a row-sharded dataset.

    Returns (centers (k, d) replicated, inertia scalar). Matches the
    single-device :func:`raft_tpu.cluster.kmeans.fit` EM up to shard
    summation order.
    """
    x = jnp.asarray(x, jnp.float32)
    expect(x.ndim == 2, "x must be (n, d)")
    n, d = x.shape
    expect(n % comms.size == 0,
           "rows must divide the mesh axis (pad the dataset)")
    expect(n_clusters <= n, "n_clusters > n_rows")
    axis = comms.axis

    # kmeans++ init on a strided subsample (replicated), then the
    # sharded EM — the reference MNMG kmeans seeds on one worker and
    # broadcasts too. The subsample must cover n_clusters distinct picks.
    sub_size = min(n, max(2048, 4 * n_clusters))
    sub = x[:: max(1, n // sub_size)][:sub_size]
    expect(n_clusters <= sub.shape[0], "n_clusters exceeds init subsample")
    centers0 = _kmeanspp_init(jax.random.key(seed), sub, n_clusters)
    x = jax.device_put(x, comms.row_sharded())
    centers0 = jax.device_put(centers0, comms.replicated())

    @partial(jax.jit, static_argnames=())
    def _run(x_sh, c0):
        def body(x_loc, c0_rep):
            def em(_, centers):
                d2 = (
                    jnp.sum(jnp.square(x_loc), 1)[:, None]
                    - 2.0 * x_loc @ centers.T
                    + jnp.sum(jnp.square(centers), 1)[None, :]
                )
                labels = jnp.argmin(d2, axis=1)
                sums = jax.ops.segment_sum(x_loc, labels,
                                           num_segments=n_clusters)
                counts = jax.ops.segment_sum(
                    jnp.ones((x_loc.shape[0],), jnp.float32), labels,
                    num_segments=n_clusters)
                sums = allreduce(sums, Op.SUM, axis)
                counts = allreduce(counts, Op.SUM, axis)
                new = sums / jnp.maximum(counts, 1.0)[:, None]
                return jnp.where((counts > 0)[:, None], new, centers)

            centers = jax.lax.fori_loop(0, n_iters, em, c0_rep)
            d2 = (
                jnp.sum(jnp.square(x_loc), 1)[:, None]
                - 2.0 * x_loc @ centers.T
                + jnp.sum(jnp.square(centers), 1)[None, :]
            )
            inertia = allreduce(jnp.sum(jnp.min(d2, axis=1)), Op.SUM, axis)
            return centers, inertia

        return shard_map(
            body, mesh=comms.mesh, in_specs=(P(axis, None), P()),
            out_specs=(P(), P()),
        )(x_sh, c0)

    with tracing.range("raft_tpu.distributed.kmeans_fit"):
        return _run(x, centers0)
