"""Combinatorial solvers — analog of ``raft/solver/`` / ``raft/lap/``
(``solver/linear_assignment.cuh``, the Date–Nagi GPU Hungarian variant).
"""

from raft_tpu.solver.lap import LinearAssignmentProblem, linear_assignment

__all__ = ["LinearAssignmentProblem", "linear_assignment"]
