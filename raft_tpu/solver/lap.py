"""Linear assignment (LAP) — analog of ``solver::LinearAssignmentProblem``
(``solver/linear_assignment.cuh``), the batched Date–Nagi Hungarian
solver.

TPU re-design: the Hungarian algorithm's zero-cover phases are
pointer-chasing-heavy; the **auction algorithm** (Bertsekas) reaches the
same optimum through dense, data-parallel bidding rounds — every round
is a (n, n) matrix of values, a top-2 reduction per row, and a
segment-max per column: pure VPU/MXU shapes inside one
``lax.while_loop``. ε-scaling gives the standard optimality guarantee
(exact for integer costs when ε < 1/n; within n·ε otherwise). Batched
over problem instances with ``vmap`` exactly like the reference's
batched API.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core import tracing
from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.core.validation import expect

_NEG = -1e30


@partial(jax.jit, static_argnames=("max_iter",))
def _auction_phase(benefit, prices, eps, max_iter: int):
    """One ε-phase of the auction: bid until all rows are assigned."""
    n = benefit.shape[0]

    def cond(state):
        assign_row, _, _, it = state
        return (it < max_iter) & jnp.any(assign_row < 0)

    def body(state):
        assign_row, owner_col, prices, it = state
        unassigned = assign_row < 0                       # (n,)
        vals = benefit - prices[None, :]                  # (n, n)
        top2, top2_idx = jax.lax.top_k(vals, 2)
        w1, w2 = top2[:, 0], top2[:, 1]
        jstar = top2_idx[:, 0]
        bid = prices[jstar] + (w1 - w2) + eps             # (n,)

        # column-wise max over bidders (one-hot scatter of bids)
        onehot = jax.nn.one_hot(jstar, n, dtype=jnp.float32)
        bids = jnp.where(unassigned[:, None], onehot * bid[:, None]
                         + (1.0 - onehot) * _NEG, _NEG)   # (n, n)
        col_best = jnp.max(bids, axis=0)                  # (n,)
        col_winner = jnp.argmax(bids, axis=0)             # (n,)
        has_bid = col_best > _NEG / 2

        prices = jnp.where(has_bid, col_best, prices)
        # unassign previous owners of re-auctioned columns (dummy index n
        # + mode="drop" so no-bid columns cannot clobber row 0)
        prev_owner = jnp.where(has_bid, owner_col, -1)
        lost = jnp.zeros((n,), bool).at[
            jnp.where(prev_owner >= 0, prev_owner, n)
        ].set(True, mode="drop")
        assign_row = jnp.where(lost, -1, assign_row)
        owner_col = jnp.where(has_bid, col_winner, owner_col)
        # winners take their columns
        assign_row = assign_row.at[
            jnp.where(has_bid, col_winner, n)
        ].set(jnp.arange(n, dtype=jnp.int32), mode="drop")
        return assign_row, owner_col, prices, it + 1

    assign0 = jnp.full((n,), -1, jnp.int32)
    owner0 = jnp.full((n,), -1, jnp.int32)
    assign, owner, prices, _ = jax.lax.while_loop(
        cond, body, (assign0, owner0, prices, jnp.int32(0))
    )
    return assign, prices


def linear_assignment(
    res: Optional[Resources],
    cost,
    *,
    maximize: bool = False,
    eps_scaling_factor: float = 4.0,
    max_iter_per_phase: int = 0,
) -> Tuple[jax.Array, jax.Array]:
    """Solve min-cost (or max-benefit) one-to-one assignment on a square
    cost matrix — the ``LinearAssignmentProblem::solve`` API.

    Returns (row_assignment, total_cost) where ``row_assignment[i]`` is
    the column assigned to row i.
    """
    ensure_resources(res)
    cost = jnp.asarray(cost, jnp.float32)
    expect(cost.ndim == 2 and cost.shape[0] == cost.shape[1],
           "linear_assignment expects a square cost matrix")
    n = cost.shape[0]
    benefit = cost if maximize else -cost
    max_iter = max_iter_per_phase or (50 * n + 1000)

    with tracing.range("raft_tpu.solver.lap"):
        # ε-scaling: from max|benefit|/2 down past 1/(n+1)
        spread = float(jnp.max(jnp.abs(benefit)))
        eps = max(spread / 2.0, 1.0 / (n + 1))
        prices = jnp.zeros((n,), jnp.float32)
        assign = jnp.full((n,), -1, jnp.int32)
        while True:
            assign, prices = _auction_phase(benefit, prices,
                                            jnp.float32(eps), max_iter)
            if eps <= 1.0 / (n + 1):
                break
            eps = max(eps / eps_scaling_factor, 1.0 / (n + 1))
        total = jnp.sum(jnp.take_along_axis(cost, assign[:, None], 1)[:, 0])
        return assign, total


class LinearAssignmentProblem:
    """Object API mirroring ``solver::LinearAssignmentProblem``
    (``solver/linear_assignment.cuh``): batched solve with accessors."""

    def __init__(self, res: Optional[Resources], size: int,
                 batch_size: int = 1):
        self._res = ensure_resources(res)
        self.size = size
        self.batch_size = batch_size
        self._assignments = None
        self._costs = None

    def solve(self, cost_batch):
        """cost_batch: (batch, n, n) or (n, n)."""
        cost_batch = jnp.asarray(cost_batch, jnp.float32)
        if cost_batch.ndim == 2:
            cost_batch = cost_batch[None]
        outs = [linear_assignment(self._res, c) for c in cost_batch]
        self._assignments = jnp.stack([a for a, _ in outs])
        self._costs = jnp.stack([c for _, c in outs])
        return self._assignments

    @property
    def row_assignments(self):
        return self._assignments

    @property
    def objective_values(self):
        return self._costs
