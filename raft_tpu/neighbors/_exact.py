"""Shared gather+GEMM exact-distance helper for the graph-based
neighbors (nn_descent, cagra) and refine — one implementation of the
numerically sensitive clip-gather / HIGHEST-precision inner-product /
expanded-L2 pattern (role of the reference's shared naive distance path,
``cpp/internal/raft_internal/neighbors/naive_knn.cuh``)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from raft_tpu.distance.types import DistanceType


def gathered_distances(x, dataset, cand_ids, metric: DistanceType):
    """Distance from each row of ``x`` to its candidate dataset rows.

    Args:
      x: (t, d) float32 query/node vectors.
      dataset: (n, d) vectors to gather from.
      cand_ids: (t, c) int ids into dataset; negatives are invalid.
      metric: L2Expanded / L2SqrtExpanded score as squared L2;
        InnerProduct scores as NEGATED similarity (minimization form).

    Returns (t, c) float32 with +inf at invalid ids.
    """
    rows = jnp.take(dataset, jnp.clip(cand_ids, 0), axis=0).astype(jnp.float32)
    ip = jnp.einsum("td,tcd->tc", x, rows,
                    precision=jax.lax.Precision.HIGHEST)
    if metric == DistanceType.InnerProduct:
        d = -ip
    else:
        d = (
            jnp.sum(jnp.square(rows), axis=2)
            - 2.0 * ip
            + jnp.sum(jnp.square(x), axis=1)[:, None]
        )
        d = jnp.maximum(d, 0.0)
    return jnp.where(cand_ids >= 0, d, jnp.inf)
