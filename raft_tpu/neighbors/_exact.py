"""Shared gather+GEMM exact-distance helper for the graph-based
neighbors (nn_descent, cagra) and refine — one implementation of the
numerically sensitive clip-gather / HIGHEST-precision inner-product /
expanded-L2 pattern (role of the reference's shared naive distance path,
``cpp/internal/raft_internal/neighbors/naive_knn.cuh``)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from raft_tpu.distance.types import DistanceType


def dedup_candidate_mask(cand_ids, buf_ids):
    """Beam-merge dedup shared by BOTH CAGRA search engines (the XLA
    ``_buffer_merge`` and the Pallas kernel — their visited semantics
    must not drift): True where a candidate duplicates a live buffer id
    (buffer copy wins) or an earlier candidate (first proposal wins).

    ``buf_ids`` must already encode dead slots as a value no candidate
    can take (e.g. -2). Pure jnp, Mosaic-compatible (iota, not tril)."""
    q, C = cand_ids.shape
    dup_b = jnp.any(cand_ids[:, :, None] == buf_ids[:, None, :], axis=2)
    eq = cand_ids[:, :, None] == cand_ids[:, None, :]
    r = jax.lax.broadcasted_iota(jnp.int32, (C, C), 0)
    c = jax.lax.broadcasted_iota(jnp.int32, (C, C), 1)
    dup_c = jnp.any(eq & ((c < r)[None]), axis=2)
    return dup_b | dup_c


def gathered_distances(x, dataset, cand_ids, metric: DistanceType):
    """Distance from each row of ``x`` to its candidate dataset rows.

    Args:
      x: (t, d) float32 query/node vectors.
      dataset: (n, d) vectors to gather from.
      cand_ids: (t, c) int ids into dataset; negatives are invalid.
      metric: L2Expanded / L2SqrtExpanded score as squared L2;
        InnerProduct scores as NEGATED similarity (minimization form).

    Returns (t, c) float32 with +inf at invalid ids.
    """
    rows = jnp.take(dataset, jnp.clip(cand_ids, 0), axis=0).astype(jnp.float32)
    ip = jnp.einsum("td,tcd->tc", x, rows,
                    precision=jax.lax.Precision.HIGHEST)
    if metric == DistanceType.InnerProduct:
        d = -ip
    else:
        d = (
            jnp.sum(jnp.square(rows), axis=2)
            - 2.0 * ip
            + jnp.sum(jnp.square(x), axis=1)[:, None]
        )
        d = jnp.maximum(d, 0.0)
    return jnp.where(cand_ids >= 0, d, jnp.inf)
