"""Refinement — exact re-ranking of ANN candidates, analog of
``raft::neighbors::refine`` (``neighbors/refine-inl.cuh``; device impl
``detail/refine_device.cuh:40-93``).

The reference reuses the IVF-Flat interleaved scan over a fake
1-query-per-list index; on TPU the natural form is a batched gather +
one MXU GEMM per query block: gather candidate rows, compute exact
distances, select_k. One fused jit program, no index gymnastics.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core import tracing
from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.core.validation import expect
from raft_tpu.distance.types import DistanceType, is_min_close


@partial(jax.jit, static_argnames=("k", "metric"))
def _refine_impl(dataset, queries, candidates, k: int, metric: DistanceType):
    q, n_cand = candidates.shape
    select_min = is_min_close(metric)
    pad_val = jnp.inf if select_min else -jnp.inf
    qf = queries.astype(jnp.float32)

    safe = jnp.clip(candidates, 0)
    rows = jnp.take(dataset, safe, axis=0).astype(jnp.float32)  # (q, c, d)
    ip = jax.lax.dot_general(
        rows, qf, (((2,), (1,)), ((0,), (0,))),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    )                                                           # (q, c)
    if metric == DistanceType.InnerProduct:
        dist = ip
    else:
        dist = (
            jnp.sum(jnp.square(rows), axis=2)
            - 2.0 * ip
            + jnp.sum(jnp.square(qf), axis=1)[:, None]
        )
        dist = jnp.maximum(dist, 0.0)
        if metric == DistanceType.L2SqrtExpanded:
            dist = jnp.sqrt(dist)
    dist = jnp.where(candidates >= 0, dist, pad_val)

    if select_min:
        vals, pos = jax.lax.top_k(-dist, k)
        vals = -vals
    else:
        vals, pos = jax.lax.top_k(dist, k)
    idx = jnp.take_along_axis(candidates, pos, axis=1)
    return vals, idx


def refine(
    res: Optional[Resources],
    dataset,
    queries,
    candidates,
    k: int,
    metric: DistanceType = DistanceType.L2Expanded,
) -> Tuple[jax.Array, jax.Array]:
    """Re-rank ``candidates`` (q, n_cand int32, -1 = missing) by exact
    distance against ``dataset``; return the top k of each row.

    Mirrors ``neighbors::refine(handle, dataset, queries, candidates, k)``.
    """
    ensure_resources(res)
    dataset = jnp.asarray(dataset)
    queries = jnp.asarray(queries)
    candidates = jnp.asarray(candidates, jnp.int32)
    expect(dataset.ndim == 2 and queries.ndim == 2, "dataset/queries must be 2-D")
    expect(queries.shape[1] == dataset.shape[1], "dim mismatch")
    expect(candidates.ndim == 2 and candidates.shape[0] == queries.shape[0],
           "candidates must be (n_queries, n_candidates)")
    expect(k <= candidates.shape[1], "k larger than candidate count")
    with tracing.range("raft_tpu.refine"):
        return _refine_impl(dataset, queries, candidates, k, DistanceType(metric))
