"""Exact brute-force kNN — analog of ``raft::neighbors::brute_force``
(``neighbors/brute_force-inl.cuh``; impl ``detail/knn_brute_force.cuh``).

Reference architecture: a tiled loop (row tiles × database tiles) running
``pairwise_distance`` then per-tile ``select_k``, with a global merge
(``tiled_brute_force_knn:57-260``), plus a fused L2 kernel for small k and
``knn_merge_parts`` for multi-shard merges.

TPU re-design: one jitted scan over database tiles that carries a running
(k-best values, indices) state and merges each tile's local top-k with a
single ``lax.top_k`` over the 2k concatenation. The pairwise tile rides
the MXU; the merge is the TPU-KNN-paper two-phase pattern. Queries are
tiled host-side only to bound the q×tile buffer; dataset tiling is inside
the scan so HBM traffic is streamed.

The index object precomputes database norms, mirroring
``brute_force_types.hpp``'s norm caching.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core import memwatch, tracing
from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.core.serialize import (
    check_version,
    deserialize_array,
    deserialize_scalar,
    open_maybe_path,
    serialize_array,
    serialize_scalar,
)
from raft_tpu.core.validation import expect
from raft_tpu.distance.pairwise import _pairwise_distance_impl
from raft_tpu.distance.types import DistanceType, is_min_close
from raft_tpu.matrix.select_k import merge_topk
from raft_tpu.neighbors._batching import tile_queries

_SERIALIZATION_VERSION = 1


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass(frozen=True)
class BruteForceIndex:
    """Exact-search index: the dataset plus cached norms
    (``brute_force_types.hpp`` ``brute_force::index``)."""

    dataset: jax.Array          # (n, d)
    norms: jax.Array            # (n,) cached ||y||^2 for expanded metrics
    metric: DistanceType
    metric_arg: float

    def tree_flatten(self):
        return (self.dataset, self.norms), (self.metric, self.metric_arg)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], aux[0], aux[1])

    @property
    def size(self) -> int:
        return self.dataset.shape[0]

    @property
    def dim(self) -> int:
        return self.dataset.shape[1]


def build(
    res: Optional[Resources],
    dataset,
    metric: DistanceType = DistanceType.L2Expanded,
    metric_arg: float = 2.0,
    storage_dtype=None,
) -> BruteForceIndex:
    """Construct the index (norm caching only — exact search has no train
    step). Analog of ``brute_force::build``.

    ``storage_dtype=jnp.bfloat16`` stores the dataset half-width — the
    TPU analog of the reference's fp16 dataset support: HBM traffic (the
    search bottleneck) halves, and bf16×bf16 MXU products are exact in
    f32, so distances are exact *for the quantized dataset*."""
    res = ensure_resources(res)
    dataset = jnp.asarray(dataset)
    expect(dataset.ndim == 2, "dataset must be (n, d)")
    if storage_dtype is not None:
        dataset = dataset.astype(storage_dtype)
    # graftledger capacity gate (opt-in): the dataset copy plus its
    # f32 norm plane is the whole resident footprint of this family
    memwatch.admit(
        int(dataset.shape[0]) * int(dataset.shape[1])
        * dataset.dtype.itemsize + int(dataset.shape[0]) * 4,
        "brute_force.build")
    dataset = res.put(dataset)
    norms = jnp.sum(jnp.square(dataset.astype(jnp.float32)), axis=1)
    return BruteForceIndex(dataset, norms, DistanceType(metric), metric_arg)


def _knn_scan_fn(queries, dataset, init_d=None, init_i=None, *, k: int,
                 metric: DistanceType, metric_arg: float, tile: int,
                 precision: str = "highest", approx: bool = False):
    """Scan database tiles, carrying running top-k (the global-merge loop of
    ``tiled_brute_force_knn``). ``approx`` swaps the per-tile exact top-k
    for the TPU's native approximate top-k unit (the TPU-KNN-paper
    peak-FLOP/s recipe); the cross-tile merge stays exact.

    ``init_d``/``init_i`` are optional (q, k) buffers whose *storage*
    seeds the running top-k state; their values are reset here. The
    serving path (``core/executor.py``) passes them with buffer
    donation so the scan state reuses one HBM allocation across calls.
    """
    n, d = dataset.shape
    q = queries.shape[0]
    select_min = is_min_close(metric)
    pad_val = jnp.inf if select_min else -jnp.inf

    tile = min(tile, n)
    n_tiles = -(-n // tile)

    def step(carry, t_idx):
        best_d, best_i = carry
        # slice the dataset in place — no padded copy of the whole
        # dataset per call; the ragged tail clamps to (n - tile, n) and
        # the rows already seen by the previous tile are masked out
        start = jnp.minimum(t_idx * tile, n - tile)
        yt = jax.lax.dynamic_slice_in_dim(dataset, start, tile)
        dist = _pairwise_distance_impl(queries, yt, metric, metric_arg,
                                       precision)
        col_ids = start + jnp.arange(tile)
        dist = jnp.where((col_ids >= t_idx * tile)[None, :], dist, pad_val)
        kk = min(k, tile)
        if approx:
            sel = (jax.lax.approx_min_k if select_min
                   else jax.lax.approx_max_k)
            tile_d, tile_i = sel(dist, kk, recall_target=0.95)
        elif select_min:
            tile_d, tile_i = jax.lax.top_k(-dist, kk)
            tile_d = -tile_d
        else:
            tile_d, tile_i = jax.lax.top_k(dist, kk)
        tile_gi = start + tile_i
        new_d, new_i = merge_topk(best_d, best_i, tile_d,
                                  tile_gi.astype(jnp.int32), k, select_min)
        return (new_d, new_i), None

    init = (
        jnp.full((q, k), pad_val, jnp.float32) if init_d is None
        else jnp.full_like(init_d, pad_val),
        jnp.full((q, k), -1, jnp.int32) if init_i is None
        else jnp.full_like(init_i, -1),
    )
    (best_d, best_i), _ = jax.lax.scan(step, init, jnp.arange(n_tiles))
    return best_d, best_i


_knn_scan = partial(jax.jit, static_argnames=(
    "k", "metric", "metric_arg", "tile", "precision", "approx"))(_knn_scan_fn)


def _use_fused_kernel(metric: DistanceType, k: int, q: int) -> bool:
    """Dispatch to the Pallas fused scan (role of the reference's
    fused-vs-tiled choice, ``detail/knn_brute_force.cuh:324``): TPU
    hardware, an expanded metric the kernel supports, small-k (the
    VPU merge is O(k·tile)), and a VMEM-resident query block.
    ``RAFT_TPU_DISABLE_FUSED=1`` forces the XLA tile-scan path
    (A/B profiling knob)."""
    import os

    from raft_tpu.ops.fused_topk import _SUPPORTED_METRICS

    return (
        jax.default_backend() == "tpu"
        and os.environ.get("RAFT_TPU_DISABLE_FUSED") != "1"
        and metric in _SUPPORTED_METRICS
        and k <= 64
        and q <= 512
    )


def search(
    res: Optional[Resources],
    index: BruteForceIndex,
    queries,
    k: int,
    query_tile: int = 8192,
    db_tile: int = 32768,
    approx: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Exact kNN: returns (distances (q, k), indices (q, k) int32) —
    ``brute_force::knn`` / ``brute_force::search``. ``approx=True``
    trades exactness for the TPU's approximate top-k unit in the
    per-tile selection (recall ≈ 0.95 per tile; merge stays exact).

    For ``InnerProduct`` the returned "distances" are similarities sorted
    descending (``is_min_close`` semantics, matching the reference).

    On TPU with small k and an expanded metric this dispatches to the
    Pallas fused scan (``raft_tpu.ops.fused_knn`` — the ``fusedL2kNN``
    analog); otherwise the XLA tile-scan path runs."""
    res = ensure_resources(res)
    queries = jnp.asarray(queries)
    expect(queries.ndim == 2, "queries must be (q, d)")
    expect(queries.shape[1] == index.dim, "query dim mismatch")
    expect(0 < k <= index.size, f"k must be in (0, {index.size}]")
    # bound the (q_tile, db_tile) distance buffer by the handle's
    # workspace budget (the reference sizes its tiles from the workspace
    # memory resource the same way, ``knn_brute_force.cuh:57-90``)
    q_rows = min(queries.shape[0], query_tile)
    budget_cols = max(128, res.workspace_limit_bytes // (4 * max(q_rows, 1)))
    db_tile = min(db_tile, budget_cols, max(128, index.size))
    precision = res.matmul_precision
    if index.dataset.dtype == jnp.bfloat16:
        # bf16 products are exact in the f32 accumulator — extra MXU
        # passes would only re-derive the same bits
        queries = queries.astype(jnp.bfloat16)
        precision = "default"
    with tracing.range("raft_tpu.brute_force.search"):
        q = queries.shape[0]
        if not approx and _use_fused_kernel(index.metric, k, q):
            from raft_tpu.ops.fused_topk import fused_knn

            return fused_knn(queries, index.dataset, k, index.metric,
                             dataset_norms=index.norms)
        def run(qt, _fw):
            return _knn_scan(qt, index.dataset, k=k, metric=index.metric,
                             metric_arg=index.metric_arg, tile=db_tile,
                             precision=precision, approx=approx)

        return tile_queries(run, queries, None, query_tile)


def knn(
    res: Optional[Resources],
    dataset,
    queries,
    k: int,
    metric: DistanceType = DistanceType.L2Expanded,
    metric_arg: float = 2.0,
) -> Tuple[jax.Array, jax.Array]:
    """One-shot convenience matching ``brute_force::knn``.

    Examples
    --------
    >>> import numpy as np
    >>> from raft_tpu.neighbors import brute_force
    >>> x = np.eye(4, dtype=np.float32)
    >>> d, i = brute_force.knn(None, x, x[:2], 1)
    >>> np.asarray(i).ravel().tolist()
    [0, 1]
    """
    index = build(res, dataset, metric, metric_arg)
    return search(res, index, queries, k)


def knn_merge_parts(distances, indices, select_min: bool = True):
    """Merge per-shard kNN results — analog of ``knn_merge_parts``
    (``detail/knn_merge_parts.cuh``), the building block of distributed
    search (SURVEY.md §5 long-context equivalent).

    Args:
      distances: (n_parts, q, k); indices: (n_parts, q, k) with *global* ids.
    Returns merged (q, k) pair.
    """
    distances = jnp.asarray(distances)
    indices = jnp.asarray(indices)
    n_parts, q, k = distances.shape
    cat_d = jnp.moveaxis(distances, 0, 1).reshape(q, n_parts * k)
    cat_i = jnp.moveaxis(indices, 0, 1).reshape(q, n_parts * k)
    return merge_topk(cat_d[:, :k], cat_i[:, :k], cat_d[:, k:], cat_i[:, k:],
                      k, select_min)


# -- serialization ----------------------------------------------------------


def save(index: BruteForceIndex, fh_or_path) -> None:
    """Versioned npy-stream serialization (pattern of
    ``brute_force_serialize``)."""
    fh, own = open_maybe_path(fh_or_path, "wb")
    try:
        serialize_scalar(fh, _SERIALIZATION_VERSION, np.int32)
        serialize_scalar(fh, int(index.metric), np.int32)
        serialize_scalar(fh, index.metric_arg, np.float32)
        serialize_array(fh, index.dataset)
        serialize_array(fh, index.norms)
    finally:
        if own:
            fh.close()


def load(res: Optional[Resources], fh_or_path) -> BruteForceIndex:
    res = ensure_resources(res)
    fh, own = open_maybe_path(fh_or_path, "rb")
    try:
        check_version(deserialize_scalar(fh), _SERIALIZATION_VERSION, "brute_force")
        metric = DistanceType(int(deserialize_scalar(fh)))
        metric_arg = float(deserialize_scalar(fh))
        dataset = res.put(deserialize_array(fh))
        norms = res.put(deserialize_array(fh))
        return BruteForceIndex(dataset, norms, metric, metric_arg)
    finally:
        if own:
            fh.close()
