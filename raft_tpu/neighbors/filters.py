"""Search-time sample filters — analog of ``neighbors/filtering``
(``sample_filter_types.hpp:27-95``). The reference exposes none- and
bitset-filters and documents a per-query bitmask pattern; all three are
first-class here:

- :class:`NoneSampleFilter` — allow everything (the default).
- :class:`BitsetFilter` — one shared bitset over sample ids; bit set =
  sample allowed (``filtering::bitset_filter``, used by
  ``cagra::search_with_filtering``).
- :class:`BitmapFilter` — an independent bitset **per query** (the
  ``bitmask_ivf_sample_filter`` pattern): words shaped
  ``(n_queries, ceil(n/32))``.

Search functions accept a raw :class:`~raft_tpu.core.bitset.Bitset`
(treated as a :class:`BitsetFilter`) or any of these wrappers.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core.bitset import WORD_BITS, Bitset, test_words


@dataclasses.dataclass(frozen=True)
class NoneSampleFilter:
    """Allow every sample (``none_ivf_sample_filter`` /
    ``none_cagra_sample_filter``)."""


@dataclasses.dataclass(frozen=True)
class BitsetFilter:
    """Shared greenlight bitset over sample ids."""

    bitset: Bitset


@dataclasses.dataclass(frozen=True)
class BitmapFilter:
    """Per-query greenlight bits: ``words[q, id // 32]`` bit ``id % 32``."""

    words: jax.Array  # (n_queries, n_words) uint32

    @classmethod
    def from_mask(cls, mask) -> "BitmapFilter":
        """Build from a (n_queries, n_samples) boolean mask."""
        mask = np.asarray(mask, bool)
        q, n = mask.shape
        n_words = -(-n // WORD_BITS)
        padded = np.zeros((q, n_words * WORD_BITS), bool)
        padded[:, :n] = mask
        bits = padded.reshape(q, n_words, WORD_BITS)
        words = (bits.astype(np.uint32)
                 << np.arange(WORD_BITS, dtype=np.uint32)).sum(
                     axis=2, dtype=np.uint32)
        return cls(jnp.asarray(words))


def resolve_filter_words(sample_filter):
    """Normalize any accepted filter form to a words array (1-D shared,
    2-D per-query) or None. Idempotent: an already-resolved words array
    passes through unchanged (the serving batcher resolves once at
    admission and re-submits the words)."""
    if sample_filter is None or isinstance(sample_filter, NoneSampleFilter):
        return None
    if isinstance(sample_filter, Bitset):
        return sample_filter.words
    if isinstance(sample_filter, BitsetFilter):
        return sample_filter.bitset.words
    if isinstance(sample_filter, BitmapFilter):
        return sample_filter.words
    if hasattr(sample_filter, "ndim") and hasattr(sample_filter, "dtype"):
        if sample_filter.ndim not in (1, 2):
            raise TypeError(
                f"filter words must be 1-D or 2-D, got "
                f"{sample_filter.ndim}-D")
        return sample_filter
    raise TypeError(
        f"unsupported sample_filter type {type(sample_filter).__name__}; "
        "pass a Bitset, BitsetFilter, BitmapFilter, or NoneSampleFilter"
    )


def test_filter(words, ids):
    """Greenlight bits for ``ids`` (q, m) under shared (1-D) or
    per-query (2-D) words."""
    if words.ndim == 1:
        return test_words(words, ids)
    ids = jnp.asarray(ids)
    safe = jnp.clip(ids, 0)
    word = jnp.take_along_axis(words, safe // WORD_BITS, axis=1)
    return ((word >> (safe % WORD_BITS).astype(jnp.uint32)) & 1).astype(
        jnp.bool_)
