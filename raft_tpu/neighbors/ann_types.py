"""Base ANN types — analog of ``neighbors/ann_types.hpp:29-48``.

Every index family follows the reference's contract: an ``index`` object
built by ``build(params, dataset)``, queried by ``search(params, index,
queries, k)``, extended by ``extend``, and (de)serialized. Indexes here are
registered pytrees of jax.Arrays + static metadata, so they pass through
jit, shard over meshes, and donate cleanly.
"""

from __future__ import annotations

import dataclasses

from raft_tpu.distance.types import DistanceType


@dataclasses.dataclass(frozen=True)
class IndexParams:
    """Base build parameters (``ann_types.hpp`` ``index_params``)."""

    metric: DistanceType = DistanceType.L2Expanded
    metric_arg: float = 2.0
    add_data_on_build: bool = True


@dataclasses.dataclass(frozen=True)
class SearchParams:
    """Base search parameters (``ann_types.hpp`` ``search_params``)."""
