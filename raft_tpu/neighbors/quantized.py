"""Scalar-quantized (8-bit) exact kNN — the role of the reference's
``ann_quantized`` wrapper (``spatial/knn/detail/ann_quantized.cuh``),
which trains an 8-bit quantizer over the dataset and searches in the
compressed domain.

TPU re-design: affine int8 quantization ``x ≈ scale · (q - zero)`` with a
single global (scale, zero) pair fitted to the data range. Search runs
the q·dataset inner products as an **int8 × int8 MXU matmul with int32
accumulation** — the TPU's highest-throughput matmul mode — and expands
the affine terms algebraically:

    <x, y>  ≈ s² (<qx, qy> - z·Σqx - z·Σqy + d·z²)

so L2/IP/cosine distances need only the int32 Gram tile plus cheap
per-row sums. 4x less HBM traffic than fp32 brute force and ~4x more
MACs per cycle; recall loss is the quantization error (tiny for k well
below the distance-gap scale).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core import tracing
from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.core.serialize import (
    check_version,
    deserialize_array,
    deserialize_scalar,
    open_maybe_path,
    serialize_array,
    serialize_scalar,
)
from raft_tpu.core.validation import expect
from raft_tpu.distance.types import DistanceType
from raft_tpu.matrix.select_k import merge_topk

_SERIALIZATION_VERSION = 1

_SUPPORTED = (
    DistanceType.L2Expanded,
    DistanceType.L2SqrtExpanded,
    DistanceType.InnerProduct,
)


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass(frozen=True)
class QuantizedIndex:
    """int8 codes + affine parameters + cached code row sums."""

    codes: jax.Array        # (n, d) int8
    row_sums: jax.Array     # (n,) int32  Σ codes per row
    scale: float
    zero: float
    metric: DistanceType

    def tree_flatten(self):
        return (self.codes, self.row_sums), (self.scale, self.zero,
                                             self.metric)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], aux[0], aux[1], aux[2])

    @property
    def size(self) -> int:
        return self.codes.shape[0]

    @property
    def dim(self) -> int:
        return self.codes.shape[1]


def build(
    res: Optional[Resources],
    dataset,
    metric: DistanceType = DistanceType.L2Expanded,
) -> QuantizedIndex:
    """Fit the affine quantizer and encode the dataset."""
    res = ensure_resources(res)
    dataset = jnp.asarray(dataset, jnp.float32)
    expect(dataset.ndim == 2, "dataset must be (n, d)")
    expect(DistanceType(metric) in _SUPPORTED,
           f"quantized knn supports L2/InnerProduct, got {metric!r}")
    with tracing.range("raft_tpu.quantized.build"):
        lo = jnp.min(dataset)
        hi = jnp.max(dataset)
        scale = float(jnp.maximum(hi - lo, 1e-12)) / 254.0
        zero = float(lo) / scale + 127.0  # maps lo → -127
        codes = jnp.clip(jnp.round(dataset / scale - zero), -127, 127)
        codes = codes.astype(jnp.int8)
        row_sums = jnp.sum(codes.astype(jnp.int32), axis=1)
        return QuantizedIndex(res.put(codes), res.put(row_sums),
                              scale, zero, DistanceType(metric))


@partial(jax.jit, static_argnames=("k", "metric", "tile"))
def _search_impl(q_codes, q_sums, codes, row_sums, scale: float, zero: float,
                 k: int, metric: DistanceType, tile: int):
    nq, d = q_codes.shape
    n = codes.shape[0]
    select_min = metric != DistanceType.InnerProduct
    pad_val = jnp.inf if select_min else -jnp.inf

    pad = (-n) % tile
    cp = jnp.pad(codes, ((0, pad), (0, 0)))
    sp = jnp.pad(row_sums, (0, pad))
    ctiles = cp.reshape(-1, tile, d)
    stiles = sp.reshape(-1, tile)

    s2 = scale * scale
    z = zero
    # decode is x = scale * (code + zero)  (encode was x/scale - zero),
    # so <x, y> = s²(qx·qy + z·Σqx + z·Σqy + d·z²)
    qn = s2 * jnp.sum((q_codes.astype(jnp.float32) + z) ** 2, axis=1)

    def step(carry, inp):
        best_d, best_i = carry
        t_idx, ct, st = inp
        # int8 × int8 → int32 Gram tile on the MXU
        gram = jax.lax.dot_general(
            q_codes, ct,
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.int32,
        ).astype(jnp.float32)
        ip = s2 * (gram + z * q_sums[:, None] + z * st[None, :] + d * z * z)
        if select_min:
            yn = s2 * (jnp.sum(
                (ct.astype(jnp.float32) + z) ** 2, axis=1))
            dist = qn[:, None] + yn[None, :] - 2.0 * ip
            dist = jnp.maximum(dist, 0.0)
        else:
            dist = ip
        col_ids = t_idx * tile + jnp.arange(tile)
        dist = jnp.where((col_ids < n)[None, :], dist, pad_val)
        kk = min(k, tile)
        td, tp = jax.lax.top_k(-dist if select_min else dist, kk)
        td = -td if select_min else td
        tgi = (t_idx * tile + tp).astype(jnp.int32)
        return merge_topk(best_d, best_i, td, tgi, k, select_min), None

    init = (
        jnp.full((nq, k), pad_val, jnp.float32),
        jnp.full((nq, k), -1, jnp.int32),
    )
    (best_d, best_i), _ = jax.lax.scan(
        step, init, (jnp.arange(ctiles.shape[0]), ctiles, stiles)
    )
    if metric == DistanceType.L2SqrtExpanded:
        best_d = jnp.where(jnp.isfinite(best_d), jnp.sqrt(best_d), best_d)
    return best_d, best_i


def search(
    res: Optional[Resources],
    index: QuantizedIndex,
    queries,
    k: int,
    db_tile: int = 32768,
) -> Tuple[jax.Array, jax.Array]:
    """Approximate kNN over the int8 codes (distances reported in the
    de-quantized scale)."""
    ensure_resources(res)
    queries = jnp.asarray(queries, jnp.float32)
    expect(queries.ndim == 2 and queries.shape[1] == index.dim,
           "queries must be (q, dim)")
    expect(0 < k <= index.size, f"k must be in (0, {index.size}]")
    with tracing.range("raft_tpu.quantized.search"):
        q_codes = jnp.clip(jnp.round(queries / index.scale - index.zero),
                           -127, 127).astype(jnp.int8)
        q_sums = jnp.sum(q_codes.astype(jnp.int32), axis=1)
        tile = min(db_tile, max(128, index.size))
        return _search_impl(q_codes, q_sums, index.codes, index.row_sums,
                            index.scale, index.zero, k, index.metric, tile)


def knn(
    res: Optional[Resources],
    dataset,
    queries,
    k: int,
    metric: DistanceType = DistanceType.L2Expanded,
) -> Tuple[jax.Array, jax.Array]:
    """One-shot build + search (the ``ann_quantized`` call shape)."""
    index = build(res, dataset, metric)
    return search(res, index, queries, k)


# -- serialization ----------------------------------------------------------


def save(index: QuantizedIndex, fh_or_path) -> None:
    fh, own = open_maybe_path(fh_or_path, "wb")
    try:
        serialize_scalar(fh, _SERIALIZATION_VERSION, np.int32)
        serialize_scalar(fh, int(index.metric), np.int32)
        serialize_scalar(fh, index.scale, np.float64)
        serialize_scalar(fh, index.zero, np.float64)
        serialize_array(fh, index.codes)
        serialize_array(fh, index.row_sums)
    finally:
        if own:
            fh.close()


def load(res: Optional[Resources], fh_or_path) -> QuantizedIndex:
    res = ensure_resources(res)
    fh, own = open_maybe_path(fh_or_path, "rb")
    try:
        check_version(deserialize_scalar(fh), _SERIALIZATION_VERSION,
                      "quantized")
        metric = DistanceType(int(deserialize_scalar(fh)))
        scale = float(deserialize_scalar(fh))
        zero = float(deserialize_scalar(fh))
        codes = res.put(deserialize_array(fh))
        row_sums = res.put(deserialize_array(fh))
        return QuantizedIndex(codes, row_sums, scale, zero, metric)
    finally:
        if own:
            fh.close()
