"""Epsilon neighborhood — analog of ``neighbors/epsilon_neighborhood.cuh``
(``epsNeighborhoodL2``): all pairs within radius eps, emitted as a dense
boolean adjacency plus per-row vertex degrees (the DBSCAN building block).

TPU design: one tiled L2 distance evaluation fused with the threshold
compare — XLA fuses the compare into the distance epilog, so the boolean
matrix never costs a second pass over HBM.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core import tracing
from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.distance.pairwise import _pairwise_distance_impl
from raft_tpu.distance.types import DistanceType


def eps_neighbors(
    res: Optional[Resources],
    x,
    y,
    eps: float,
    *,
    tile: int = 4096,
) -> Tuple[jax.Array, jax.Array]:
    """Boolean adjacency ``adj[i, j] = ||x_i - y_j||² <= eps²`` and row
    degrees — ``neighbors::epsilon_neighborhood::eps_neighbors_l2sq``.

    ``eps`` is the radius (the reference API takes eps² — here the
    squared compare happens internally against L2Expanded distances).
    """
    ensure_resources(res)
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    m = x.shape[0]
    eps_sq = jnp.float32(eps) ** 2

    with tracing.range("raft_tpu.neighbors.eps_neighbors"):
        adjs = []
        for start in range(0, m, tile):
            stop = min(start + tile, m)
            d = _pairwise_distance_impl(
                x[start:stop], y, DistanceType.L2Expanded, 2.0, "highest"
            )
            adjs.append(d <= eps_sq)
        adj = adjs[0] if len(adjs) == 1 else jnp.concatenate(adjs, axis=0)
        vd = jnp.sum(adj, axis=1, dtype=jnp.int32)
        return adj, vd
