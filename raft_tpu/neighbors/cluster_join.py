"""Cluster-join k-NN-graph construction — a TPU-first graph builder
(no reference analog; role of ``nn_descent``/IVF-PQ batches as the
CAGRA intermediate-graph source, ``detail/cagra/cagra_build.cuh:44``).

Motivation: the reference's two graph-build paths are gather-heavy —
NN-descent joins sampled neighbor lists (``detail/nn_descent.cuh:341``)
and the IVF-PQ path streams per-query probed lists. On TPU, row gathers
lower to the scalar core and dominate the build (measured: ~18 s per
descent round at n=50k). This builder restates graph construction as
dense MXU work:

1. Partition the points with balanced k-means (cluster size ~
   ``target_cluster_size``), pack each cluster's rows into a padded
   (C, m, d) tensor — the IVF-Flat list layout.
2. Within each cluster, run exact brute-force kNN: one (m, d) x (d, m)
   MXU GEMM + top-k per cluster, batched over clusters in a scan.
   No per-row gathers anywhere in the hot loop.
3. Repeat for ``passes`` independent clusterings (different k-means
   seeds) and merge per-node candidates — a true neighbor is recovered
   unless every pass separates the pair.
4. Optionally polish with a couple of standard NN-descent rounds seeded
   from the merged graph (``nn_descent.build(init_graph=...)``), which
   recovers the remaining cross-cluster-boundary edges at a fraction of
   a from-scratch descent.

FLOPs: passes · n · m · d MACs — e.g. n=1M, m=4k, d=128, 3 passes ≈
3.2 TFLOP ≈ tens of milliseconds of MXU time; the build becomes
k-means-bound instead of gather-bound.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from raft_tpu.cluster import kmeans_balanced
from raft_tpu.cluster.kmeans_balanced import KMeansBalancedParams
from raft_tpu.core import tracing
from raft_tpu.core.logger import info as _log_info
from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.core.validation import expect
from raft_tpu.distance.types import DistanceType
from raft_tpu.neighbors.nn_descent import NNDescentParams, _merge_dedup
from raft_tpu.neighbors import nn_descent as nn_descent_mod


@dataclasses.dataclass(frozen=True)
class ClusterJoinParams:
    """Knobs for the cluster-join graph builder."""

    graph_degree: int = 64
    passes: int = 3
    target_cluster_size: int = 2048
    kmeans_n_iters: int = 8
    kmeans_trainset_fraction: float = 0.25
    polish_rounds: int = 1
    metric: DistanceType = DistanceType.L2Expanded
    seed: int = 0


def _pack_cluster_indices(labels, n_clusters: int, max_size: int):
    """(C, m) int32 member ids per cluster, -1 padded (the IVF
    sort-and-rank packing, minus the data scatter)."""
    n = labels.shape[0]
    labels = labels.astype(jnp.int32)
    order = jnp.argsort(labels, stable=True)
    sorted_labels = labels[order]
    first_pos = jnp.searchsorted(sorted_labels, jnp.arange(n_clusters),
                                 side="left")
    rank = jnp.arange(n) - first_pos[sorted_labels]
    slot = sorted_labels * max_size + rank
    flat = jnp.full((n_clusters * max_size,), -1, jnp.int32)
    flat = flat.at[slot].set(order.astype(jnp.int32))
    return flat.reshape(n_clusters, max_size)


@partial(jax.jit, static_argnames=("k", "metric"))
def _one_pass(dataset, idx, k: int, metric: DistanceType):
    """Within-cluster exact kNN for every cluster.

    dataset (n, d) f32; idx (C, m) member ids (-1 pad).
    Returns (n, k) global neighbor ids + distances (min-close form).
    """
    n, d = dataset.shape
    C, m = idx.shape
    ip_metric = metric == DistanceType.InnerProduct

    out_ids = jnp.full((n + 1, k), -1, jnp.int32)
    out_d = jnp.full((n + 1, k), jnp.inf, jnp.float32)

    def step(carry, c):
        o_ids, o_d = carry
        members = idx[c]                                   # (m,)
        rows = jnp.take(dataset, jnp.clip(members, 0), axis=0)  # (m, d)
        valid = members >= 0
        ip = jax.lax.dot_general(
            rows, rows, (((1,), (1,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32,
        )                                                  # (m, m)
        if ip_metric:
            dist = -ip
        else:
            nr = jnp.sum(jnp.square(rows), axis=1)
            dist = jnp.maximum(nr[:, None] + nr[None, :] - 2.0 * ip, 0.0)
        eye = jnp.eye(m, dtype=bool)
        dist = jnp.where(eye | ~valid[None, :], jnp.inf, dist)
        kk = min(k, m)
        neg, pos = jax.lax.top_k(-dist, kk)                # (m, kk)
        nbr_ids = jnp.take(members, pos)                   # (m, kk) global
        nbr_d = -neg
        nbr_ids = jnp.where(jnp.isfinite(nbr_d), nbr_ids, -1)
        if kk < k:
            nbr_ids = jnp.pad(nbr_ids, ((0, 0), (0, k - kk)),
                              constant_values=-1)
            nbr_d = jnp.pad(nbr_d, ((0, 0), (0, k - kk)),
                            constant_values=jnp.inf)
        # scatter to the member rows; padded slots dump into row n
        dest = jnp.where(valid, members, n)
        return (o_ids.at[dest].set(nbr_ids), o_d.at[dest].set(nbr_d)), None

    (out_ids, out_d), _ = jax.lax.scan(step, (out_ids, out_d),
                                       jnp.arange(C))
    return out_ids[:n], out_d[:n]


def build(
    res: Optional[Resources],
    params: ClusterJoinParams,
    dataset,
    return_distances: bool = False,
):
    """Build an approximate k-NN graph by merged within-cluster
    brute-force passes. Returns (n, graph_degree) int32 (+ distances)."""
    res = ensure_resources(res)
    dataset = jnp.asarray(dataset)
    expect(dataset.ndim == 2, "dataset must be (n, d)")
    n, d = dataset.shape
    k = params.graph_degree
    expect(k < n, "graph_degree must be < n_rows")
    expect(params.metric in (DistanceType.L2Expanded,
                             DistanceType.L2SqrtExpanded,
                             DistanceType.InnerProduct),
           f"cluster_join supports L2/InnerProduct, got {params.metric!r}")
    metric = (DistanceType.InnerProduct
              if params.metric == DistanceType.InnerProduct
              else DistanceType.L2Expanded)
    ds32 = dataset.astype(jnp.float32)

    with tracing.range("raft_tpu.cluster_join.build"):
        C = max(1, -(-n // params.target_cluster_size))
        best_ids = jnp.full((n, k), -1, jnp.int32)
        best_d = jnp.full((n, k), jnp.inf, jnp.float32)
        for p in range(params.passes):
            if C == 1:
                idx = jnp.arange(n, dtype=jnp.int32)[None, :]
            else:
                km = KMeansBalancedParams(
                    n_iters=params.kmeans_n_iters, metric=metric,
                    seed=params.seed * 31 + p)
                frac = min(max(params.kmeans_trainset_fraction, 0.0), 1.0)
                n_train = min(n, max(C * 32, int(n * frac)))
                stride = max(1, n // n_train)
                offset = (p * 17) % stride if stride > 1 else 0
                _log_info("cluster_join pass %d/%d: kmeans fit "
                          "(C=%d, n_train=%d)", p + 1, params.passes,
                          C, n_train)
                centers = kmeans_balanced.fit(
                    res, km, ds32[offset::stride][:n_train], C)
                _log_info("cluster_join pass %d: predict", p + 1)
                labels = kmeans_balanced.predict(res, km, centers, ds32)
                sizes = jax.ops.segment_sum(
                    jnp.ones((n,), jnp.int32), labels, num_segments=C)
                max_size = int(jnp.max(sizes))
                # coarse bucket (multiple of half the target size) so
                # nearby data-dependent max cluster sizes land on the
                # same padded shape — passes recompile _one_pass only
                # when their max size crosses a bucket boundary, not on
                # every fluctuation (remote compiles cost minutes)
                bucket = max(8, params.target_cluster_size // 2)
                max_size = max(8, -(-max_size // bucket) * bucket)
                idx = _pack_cluster_indices(labels, C, max_size)
            _log_info("cluster_join pass %d: within-cluster kNN "
                      "(C=%d, m=%d)", p + 1, idx.shape[0], idx.shape[1])
            pass_ids, pass_d = _one_pass(ds32, idx, k, metric)
            if p == 0:
                best_ids, best_d = pass_ids, pass_d
            else:
                best_ids, best_d = _merge_dedup(
                    jnp.concatenate([best_ids, pass_ids], axis=1),
                    jnp.concatenate([best_d, pass_d], axis=1), k)
            if C == 1:
                break  # one pass IS exact brute force

        if params.polish_rounds > 0 and C > 1:
            _log_info("cluster_join: NN-descent polish (%d rounds)",
                        params.polish_rounds)
            nnd = NNDescentParams(
                graph_degree=k,
                intermediate_graph_degree=k,
                max_iterations=params.polish_rounds,
                termination_threshold=0.0,
                metric=params.metric,
                seed=params.seed,
            )
            return nn_descent_mod.build(res, nnd, dataset,
                                        return_distances=return_distances,
                                        init_graph=best_ids)

        if params.metric == DistanceType.L2SqrtExpanded:
            best_d = jnp.sqrt(jnp.maximum(best_d, 0.0))
        elif params.metric == DistanceType.InnerProduct:
            best_d = -best_d
        if return_distances:
            return best_ids, best_d
        return best_ids
