"""Random ball cover — analog of ``neighbors/ball_cover-inl.cuh``
(``ball_cover::build_index`` / ``knn_query`` / ``eps_nn_query``), the
landmark-based exact/approx kNN for low-dim (2D/3D) euclidean and
haversine data.

Reference architecture: sample √n landmarks, assign every point to its
nearest landmark, then prune landmark balls with the triangle inequality
(``registers*.cu`` kernels). TPU re-design: per-landmark member lists
become a **padded dense (L, M) table** (XLA needs static shapes); a query
probes its ``n_probes`` nearest landmarks, gathers their members in one
batched gather, and scores them with one batched MXU contraction.
Landmark radii give the same triangle-inequality certificate the
reference uses: if the kth-best distance is below the lower bound of
every unprobed ball, the answer is provably exact.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core import tracing
from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.core.validation import expect
from raft_tpu.distance.pairwise import _pairwise_distance_impl
from raft_tpu.distance.types import DistanceType


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class BallCoverIndex:
    """``BallCoverIndex`` analog (``ball_cover_types.hpp``)."""

    dataset: jax.Array        # (n, d)
    landmarks: jax.Array      # (L, d)
    members: jax.Array        # (L, M) int32 dataset row ids, -1 padding
    member_dists: jax.Array   # (L, M) distance of member to its landmark
    radii: jax.Array          # (L,) max member distance per ball
    metric: DistanceType

    def tree_flatten(self):
        return (
            (self.dataset, self.landmarks, self.members,
             self.member_dists, self.radii),
            (self.metric,),
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, metric=aux[0])

    @property
    def n_landmarks(self) -> int:
        return self.landmarks.shape[0]


def build_index(
    res: Optional[Resources],
    dataset,
    metric: DistanceType = DistanceType.L2SqrtExpanded,
    *,
    n_landmarks: Optional[int] = None,
) -> BallCoverIndex:
    """Sample √n landmarks and bucket every point into its nearest
    landmark's ball — ``ball_cover::build_index``."""
    res = ensure_resources(res)
    x = jnp.asarray(dataset)
    n = x.shape[0]
    L = n_landmarks or max(1, int(math.ceil(math.sqrt(n))))
    expect(L <= n, "ball_cover: more landmarks than points")

    with tracing.range("raft_tpu.neighbors.ball_cover.build"):
        perm = jax.random.permutation(res.next_key(), n)[:L]
        landmarks = x[perm]
        d = _pairwise_distance_impl(x, landmarks, metric, 2.0, "highest")
        owner = jnp.argmin(d, axis=1).astype(jnp.int32)          # (n,)
        dist_own = jnp.min(d, axis=1)
        # bucket into a padded (L, M) table, sorted by distance within
        # the ball (the reference sorts each ball for pruning quality)
        counts = np.bincount(np.asarray(owner), minlength=L)
        M = int(counts.max())
        order = np.lexsort((np.asarray(dist_own), np.asarray(owner)))
        rows_sorted = np.asarray(owner)[order]
        pos_in_row = np.arange(n) - np.concatenate(
            [[0], np.cumsum(counts)[:-1]])[rows_sorted]
        members = np.full((L, M), -1, np.int32)
        mdists = np.full((L, M), np.inf, np.float32)
        members[rows_sorted, pos_in_row] = order
        mdists[rows_sorted, pos_in_row] = np.asarray(dist_own)[order]
        radii = jax.ops.segment_max(dist_own, owner, num_segments=L)
        return BallCoverIndex(
            dataset=x,
            landmarks=landmarks,
            members=jnp.asarray(members),
            member_dists=jnp.asarray(mdists),
            radii=radii,
            metric=metric,
        )


@partial(jax.jit, static_argnames=("k", "n_probes", "metric"))
def _query_batch(queries, dataset, landmarks, members, radii,
                 k: int, n_probes: int, metric: DistanceType):
    q = queries.shape[0]
    L, M = members.shape
    d_ql = _pairwise_distance_impl(queries, landmarks, metric, 2.0,
                                   "highest")                    # (q, L)
    _, probe = jax.lax.top_k(-d_ql, n_probes)                    # (q, p)
    cand = members[probe].reshape(q, n_probes * M)               # (q, pM)
    valid = cand >= 0
    cand_safe = jnp.where(valid, cand, 0)
    cvecs = dataset[cand_safe]                                   # (q, pM, dim)
    dist = jax.vmap(
        lambda qv, cv: _pairwise_distance_impl(qv[None], cv, metric, 2.0,
                                               "highest")[0]
    )(queries, cvecs)                                            # (q, pM)
    dist = jnp.where(valid, dist, jnp.inf)
    topd, topi = jax.lax.top_k(-dist, k)
    topd = -topd
    idx = jnp.take_along_axis(cand_safe, topi, axis=1)
    idx = jnp.where(jnp.isfinite(topd), idx, -1)
    # exactness certificate: kth best vs lower bound of unprobed balls
    lb = d_ql - radii[None, :]                                   # (q, L)
    probed = jnp.zeros((q, L), bool).at[
        jnp.arange(q)[:, None], probe].set(True)
    min_unprobed_lb = jnp.min(jnp.where(probed, jnp.inf, lb), axis=1)
    exact = topd[:, k - 1] <= min_unprobed_lb
    return topd, idx, exact


def knn_query(
    res: Optional[Resources],
    index: BallCoverIndex,
    queries,
    k: int,
    *,
    n_probes: int = 0,
    tile: int = 1024,
) -> Tuple[jax.Array, jax.Array]:
    """k nearest neighbors via ball-cover pruning —
    ``ball_cover::knn_query``. ``n_probes=0`` → probe √L + k balls
    (typically exact on low-dim data; raise for a guarantee — probing
    all L balls is exhaustive)."""
    res = ensure_resources(res)
    queries = jnp.asarray(queries)
    L = index.n_landmarks
    p = n_probes or min(L, int(math.ceil(math.sqrt(L))) + k)
    p = min(p, L)
    expect(k >= 1, "knn_query: k must be >= 1")

    with tracing.range("raft_tpu.neighbors.ball_cover.knn"):
        outs = []
        for start in range(0, queries.shape[0], tile):
            stop = min(start + tile, queries.shape[0])
            outs.append(_query_batch(
                queries[start:stop], index.dataset, index.landmarks,
                index.members, index.radii, k, p, index.metric))
        dists = jnp.concatenate([o[0] for o in outs], axis=0) \
            if len(outs) > 1 else outs[0][0]
        idx = jnp.concatenate([o[1] for o in outs], axis=0) \
            if len(outs) > 1 else outs[0][1]
        return dists, idx


def eps_nn_query(
    res: Optional[Resources],
    index: BallCoverIndex,
    queries,
    eps: float,
) -> Tuple[jax.Array, jax.Array]:
    """All neighbors within radius eps — ``ball_cover::eps_nn_query``.
    Returns (adjacency (q, n) bool, vertex degrees)."""
    from raft_tpu.neighbors.epsilon_neighborhood import eps_neighbors

    # ball pruning would only skip compute XLA already fuses; the dense
    # epsilon pass reuses the tiled distance engine directly
    return eps_neighbors(res, queries, index.dataset, eps)
