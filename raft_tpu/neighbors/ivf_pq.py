"""IVF-PQ — inverted file with product quantization, TPU-native re-design
of ``raft::neighbors::ivf_pq`` (``neighbors/ivf_pq_types.hpp:219``, build
``detail/ivf_pq_build.cuh:1513``, search ``detail/ivf_pq_search.cuh:732``).

Reference architecture: balanced-kmeans coarse clusters; residuals rotated
by a (random orthogonal) matrix (``make_rotation_matrix``,
``detail/ivf_pq_build.cuh:122``); product codebooks trained per subspace or
per cluster (``:344``/``:421``); codes packed interleaved in 16-byte
chunks; search builds a per-(query, probe) lookup table and scores codes in
a fused kernel with fp8/fp16/fp32 LUTs
(``detail/ivf_pq_compute_similarity-inl.cuh:125-177``).

TPU re-design:

- codes live in ONE dense padded tensor ``codes[n_lists, max_list_size,
  pq_dim] uint8`` — no interleaving: the TPU reads codes in vectorized
  rows, and XLA lays out the trailing dims for the VPU. (The CUDA
  interleave exists to serve 32 threads striding a list; irrelevant here.)
- the LUT phase is a batched MXU GEMM (`q̃` rotation + pairwise-sq-dist
  against codebooks); scoring is a vectorized table gather per subspace,
  merged into a running top-k scan over probe ranks, identical in shape
  to the IVF-Flat scan.
- codebook training is a ``vmap``-ed fixed-iteration Lloyd EM over the
  pq_dim subspaces (one compiled kernel trains all codebooks at once,
  vs the reference's stream-parallel loop of kmeans launches).

Supported metrics: L2Expanded / L2SqrtExpanded / InnerProduct (reference
set, ``ivf_pq_types.hpp``).
"""

from __future__ import annotations

import dataclasses
import enum
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.cluster import kmeans_balanced
from raft_tpu.cluster.kmeans_balanced import KMeansBalancedParams
from raft_tpu.core import interruptible, memwatch, tracing
from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.core.serialize import (
    check_version,
    deserialize_array,
    deserialize_scalar,
    open_maybe_path,
    serialize_array,
    serialize_scalar,
)
from raft_tpu.core.validation import expect
from raft_tpu.distance.types import DistanceType, is_min_close
from raft_tpu.matrix.select_k import merge_topk
from raft_tpu.neighbors._batching import coarse_select, tile_queries
from raft_tpu.neighbors._streaming import label_pass, sample_trainset
from raft_tpu.neighbors._packing import (
    pack_padded_lists,
    padded_extent,
    streaming_ranks,
)
from raft_tpu.neighbors.ann_types import IndexParams, SearchParams
from raft_tpu.neighbors.filters import resolve_filter_words, test_filter

_SERIALIZATION_VERSION = 4  # v4: adds the 4-bit nibble-packed codes flag


class CodebookKind(enum.IntEnum):
    """Mirrors ``ivf_pq::codebook_gen`` (``ivf_pq_types.hpp:42-46``)."""

    PER_SUBSPACE = 0
    PER_CLUSTER = 1


@dataclasses.dataclass(frozen=True)
class IvfPqIndexParams(IndexParams):
    """Mirrors ``ivf_pq::index_params`` (``ivf_pq_types.hpp:48-111``)."""

    n_lists: int = 1024
    kmeans_n_iters: int = 20
    kmeans_trainset_fraction: float = 0.5
    pq_bits: int = 8              # 4..8
    pq_dim: int = 0               # 0 → auto: dim/4 rounded to multiple of 8
    codebook_kind: CodebookKind = CodebookKind.PER_SUBSPACE
    force_random_rotation: bool = False


@dataclasses.dataclass(frozen=True)
class IvfPqSearchParams(SearchParams):
    """Mirrors ``ivf_pq::search_params`` — ``lut_dtype``/
    ``internal_distance_dtype`` select the scoring precision like the
    reference's fp32/fp16/fp8 LUT variants."""

    n_probes: int = 20
    # "approx" routes cluster selection through the TPU's native
    # approximate top-k unit — worthwhile at 10k+ lists (same knob as
    # IvfFlatSearchParams.coarse_algo)
    coarse_algo: str = "exact"
    # probe-scan formulation (same knob as IvfFlatSearchParams):
    # "rank" gathers one probed list per query per probe rank; "xla"
    # scans the *union* of probed lists list-major (ops/ivf_scan) —
    # each list's codes stream from HBM once and score against the
    # whole query tile. "auto" = list-major on TPU (the gather is the
    # scalar-core bottleneck there), rank-major elsewhere.
    scan_engine: str = "auto"
    # f32 / bf16 / float8_e4m3fn — the reference's fp32/fp16/fp8 LUT
    # ladder (ivf_pq_compute_similarity-inl.cuh:125-177). fp8 quarters
    # the LUT's VMEM footprint (the probe-tile bound); scoring upcasts
    # to bf16 on the fly, so only LUT entries round
    lut_dtype: jnp.dtype = jnp.float32
    # "gather": per-element LUT lookup; "onehot": gather-free MXU
    # contraction (J-fold more FLOPs, no dynamic gathers). "auto"
    # resolves per backend: measured on TPU v5e the one-hot path is
    # ~18x faster (dynamic gathers lower to the scalar core), while on
    # CPU the gather wins.
    score_mode: str = "auto"


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass(frozen=True)
class IvfPqIndex:
    """PQ-compressed IVF index (role of ``ivf_pq::index``)."""

    centers: jax.Array        # (n_lists, dim) f32 cluster centers
    rotation: jax.Array       # (dim_ext, dim) f32 orthogonal-ish map
    codebooks: jax.Array      # PER_SUBSPACE: (pq_dim, 2^bits, pq_len)
                              # PER_CLUSTER:  (n_lists, 2^bits, pq_len)
    codes: jax.Array          # (n_lists, max_list_size, pq_dim) uint8 —
                              # or (…, pq_dim // 2) nibble-packed when
                              # ``packed`` (pq_bits == 4)
    indices: jax.Array        # (n_lists, max_list_size) int32, -1 pad
    list_sizes: jax.Array     # (n_lists,) int32
    metric: DistanceType
    codebook_kind: CodebookKind
    pq_bits: int
    packed: bool = False      # two 4-bit codes per byte (halves HBM)

    def tree_flatten(self):
        return (
            self.centers, self.rotation, self.codebooks, self.codes,
            self.indices, self.list_sizes,
        ), (self.metric, self.codebook_kind, self.pq_bits, self.packed)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, metric=aux[0], codebook_kind=aux[1],
                   pq_bits=aux[2], packed=aux[3])

    @property
    def n_lists(self) -> int:
        return self.centers.shape[0]

    @property
    def dim(self) -> int:
        return self.centers.shape[1]

    @property
    def dim_ext(self) -> int:
        return self.rotation.shape[0]

    @property
    def pq_dim(self) -> int:
        return self.codes.shape[2] * 2 if self.packed else self.codes.shape[2]

    @property
    def pq_len(self) -> int:
        return self.codebooks.shape[2]

    @property
    def pq_book_size(self) -> int:
        return 1 << self.pq_bits

    @property
    def max_list_size(self) -> int:
        return self.codes.shape[1]

    @property
    def size(self) -> int:
        return int(self.list_sizes.sum())


# ---------------------------------------------------------------------------
# build helpers
# ---------------------------------------------------------------------------


def _auto_pq_dim(dim: int) -> int:
    """Reference heuristic: dim/4 rounded up to a multiple of 8
    (``ivf_pq_types.hpp`` pq_dim docs)."""
    pq = max(1, dim // 4)
    return max(8, -(-pq // 8) * 8) if dim >= 32 else max(1, pq)


def make_rotation_matrix(key, dim_ext: int, dim: int, force_random: bool):
    """Orthogonal projection dim → dim_ext
    (``detail/ivf_pq_build.cuh:122``): identity when dims align and
    randomness is not forced; otherwise QR of a gaussian."""
    if not force_random and dim_ext == dim:
        return jnp.eye(dim, dtype=jnp.float32)
    g = jax.random.normal(key, (dim_ext, max(dim_ext, dim)), jnp.float32)
    qmat, _ = jnp.linalg.qr(g.T)            # (max, dim_ext) orthonormal cols
    return qmat[:dim, :].T                  # (dim_ext, dim), R R^T = I on range


@partial(jax.jit, static_argnames=("n_centers", "n_iters"))
def _vmapped_lloyd(trainsets, key, n_centers: int, n_iters: int):
    """Fixed-iteration Lloyd EM vmapped over leading axis — trains all
    pq_dim (or n_lists) codebooks in one compiled kernel
    (role of ``train_per_subset``/``train_per_cluster``,
    ``detail/ivf_pq_build.cuh:344,421``)."""

    def one(trainset, k):
        n = trainset.shape[0]
        idx = jax.random.choice(k, n, (n_centers,), replace=n < n_centers)
        centers = trainset[idx]

        def body(_, centers):
            d = (
                jnp.sum(jnp.square(trainset), 1)[:, None]
                - 2.0 * trainset @ centers.T
                + jnp.sum(jnp.square(centers), 1)[None, :]
            )
            labels = jnp.argmin(d, axis=1)
            sums = jax.ops.segment_sum(trainset, labels, num_segments=n_centers)
            counts = jax.ops.segment_sum(
                jnp.ones((n,), jnp.float32), labels, num_segments=n_centers
            )
            new = sums / jnp.maximum(counts, 1.0)[:, None]
            return jnp.where((counts > 0)[:, None], new, centers)

        return jax.lax.fori_loop(0, n_iters, body, centers)

    keys = jax.random.split(key, trainsets.shape[0])
    return jax.vmap(one)(trainsets, keys)


def _rotate_residuals(vectors, labels, centers, rotation):
    """R @ (x - c_label), reshaped to (n, pq_dim, pq_len)."""
    res = vectors.astype(jnp.float32) - centers[labels]
    rot = res @ rotation.T                     # (n, dim_ext)
    return rot


def _encode(rot_residuals, codebooks, labels, codebook_kind: CodebookKind,
            pq_dim: int, pq_len: int):
    """Nearest-codeword per subspace
    (role of ``process_and_fill_codes_kernel``, ``ivf_pq_build.cuh:946``).

    Scans over subspaces so the distance tensor is O(n · 2^bits) per
    step instead of the O(n · pq_dim · 2^bits) a one-shot form needs
    (13 GB at n=200k, pq_dim=64, 8 bits — over HBM). PER_CLUSTER
    additionally keeps the gathered per-row codebooks,
    O(n · 2^bits · pq_len), alive across the scan. The constant
    ``||sub||²`` term is dropped: it does not move the argmin."""
    n = rot_residuals.shape[0]
    sub = rot_residuals.reshape(n, pq_dim, pq_len)
    if codebook_kind == CodebookKind.PER_CLUSTER:
        cb_rows = codebooks[labels]            # (n, 2^bits, pq_len)
        cb_norms = jnp.sum(jnp.square(cb_rows), -1)

        def step(_, s):
            v = jax.lax.dynamic_index_in_dim(sub, s, 1, False)   # (n, L)
            scores = cb_norms - 2.0 * jnp.einsum("nl,njl->nj", v, cb_rows)
            return _, jnp.argmin(scores, axis=1).astype(jnp.uint8)
    else:

        def step(_, s):
            v = jax.lax.dynamic_index_in_dim(sub, s, 1, False)   # (n, L)
            cb = jax.lax.dynamic_index_in_dim(codebooks, s, 0, False)
            scores = jnp.sum(jnp.square(cb), -1)[None, :] - 2.0 * (v @ cb.T)
            return _, jnp.argmin(scores, axis=1).astype(jnp.uint8)

    _, codes = jax.lax.scan(step, None, jnp.arange(pq_dim))
    return codes.T                              # (n, pq_dim)


def _pack_nibbles(codes):
    """Two 4-bit codes per byte along the last axis: even subspaces in
    the low nibble (role of the reference's bit-packed 4-bit code
    planes, ``ivf_pq_types.hpp`` list_spec)."""
    return (codes[..., 0::2] | (codes[..., 1::2] << 4)).astype(jnp.uint8)


def _unpack_nibbles(packed):
    """Inverse of :func:`_pack_nibbles` → (..., 2 * packed.shape[-1])."""
    lo = packed & jnp.uint8(0x0F)
    hi = packed >> 4
    stacked = jnp.stack([lo, hi], axis=-1)          # (..., s/2, 2)
    return stacked.reshape(*packed.shape[:-1], packed.shape[-1] * 2)


def _pack_codes(codes, ids, labels, n_lists: int, max_list_size: int,
                sizes=None):
    """Scatter code rows into the padded [n_lists, max_list_size] layout
    (the shared sort-and-rank packing)."""
    (packed, indices), sizes = pack_padded_lists(
        labels, n_lists, max_list_size, [(codes, 0), (ids, -1)],
        sizes=sizes)
    return packed, indices, sizes


# ---------------------------------------------------------------------------
# build / extend
# ---------------------------------------------------------------------------


def build(
    res: Optional[Resources],
    params: IvfPqIndexParams,
    dataset,
) -> IvfPqIndex:
    """Train coarse centers, rotation, codebooks; encode the dataset —
    ``ivf_pq::build`` (``detail/ivf_pq_build.cuh:1513-1723``).

    Examples
    --------
    >>> import numpy as np
    >>> from raft_tpu.neighbors import ivf_pq
    >>> x = np.random.default_rng(1).standard_normal(
    ...     (256, 8)).astype(np.float32)
    >>> idx = ivf_pq.build(
    ...     None, ivf_pq.IvfPqIndexParams(n_lists=4, pq_dim=4), x)
    >>> (idx.n_lists, idx.pq_dim, idx.size)
    (4, 4, 256)
    """
    res = ensure_resources(res)
    dataset = jnp.asarray(dataset)
    expect(dataset.ndim == 2, "dataset must be (n, d)")
    n, dim = dataset.shape
    expect(4 <= params.pq_bits <= 8, "pq_bits must be in [4, 8]")
    expect(params.n_lists <= n, "n_lists > n_rows")
    expect(
        params.metric in (DistanceType.L2Expanded, DistanceType.L2SqrtExpanded,
                          DistanceType.InnerProduct),
        f"ivf_pq supports L2/L2Sqrt/InnerProduct, got {params.metric!r}",
    )
    pq_dim = params.pq_dim if params.pq_dim > 0 else _auto_pq_dim(dim)
    pq_len = -(-dim // pq_dim)                 # ceil
    dim_ext = pq_dim * pq_len

    with tracing.range("raft_tpu.ivf_pq.build"):
        frac = min(max(params.kmeans_trainset_fraction, 0.0), 1.0)
        # trainset must cover both the coarse clusters and the codebooks
        n_train = max(params.n_lists * 2, 1 << params.pq_bits, int(n * frac))
        n_train = min(n, n_train)
        stride = max(1, n // n_train)
        trainset = dataset[::stride][:n_train].astype(jnp.float32)

        km = KMeansBalancedParams(
            n_iters=params.kmeans_n_iters,
            metric=(DistanceType.InnerProduct
                    if params.metric == DistanceType.InnerProduct
                    else DistanceType.L2Expanded),
            seed=res.seed,
        )
        centers = kmeans_balanced.fit(res, km, trainset, params.n_lists)

        rotation = make_rotation_matrix(
            jax.random.fold_in(jax.random.key(res.seed), 7),
            dim_ext, dim,
            params.force_random_rotation or (dim != dim_ext),
        )

        # codebook training on rotated trainset residuals
        train_labels = kmeans_balanced.predict(res, km, centers, trainset)
        rot = _rotate_residuals(trainset, train_labels, centers, rotation)
        book_size = 1 << params.pq_bits
        key = jax.random.fold_in(jax.random.key(res.seed), 11)
        if params.codebook_kind == CodebookKind.PER_SUBSPACE:
            sub = jnp.moveaxis(rot.reshape(-1, pq_dim, pq_len), 1, 0)
            codebooks = _vmapped_lloyd(sub, key, book_size, 25)
        else:
            # per cluster: train on that cluster's OWN subvectors (all
            # subspaces pooled); rows are drawn modulo the cluster's segment
            # length so no foreign-cluster residuals leak in
            per = max(book_size * 4 // pq_dim + 1, 64)
            order = jnp.argsort(train_labels, stable=True)
            sorted_lab = train_labels[order]
            firsts = jnp.searchsorted(sorted_lab, jnp.arange(params.n_lists))
            ends = jnp.append(firsts[1:], trainset.shape[0])
            seg_len = jnp.maximum(ends - firsts, 1)
            take = firsts[:, None] + (jnp.arange(per)[None, :] % seg_len[:, None])
            rows = rot[order][take]            # (n_lists, per, dim_ext)
            pooled = rows.reshape(params.n_lists, per * pq_dim, pq_len)
            codebooks = _vmapped_lloyd(pooled, key, book_size, 25)

        empty = IvfPqIndex(
            centers=centers,
            rotation=rotation,
            codebooks=codebooks,
            codes=jnp.zeros((params.n_lists, 0, pq_dim), jnp.uint8),
            indices=jnp.full((params.n_lists, 0), -1, jnp.int32),
            list_sizes=jnp.zeros((params.n_lists,), jnp.int32),
            metric=DistanceType(params.metric),
            codebook_kind=params.codebook_kind,
            pq_bits=params.pq_bits,
        )
        if not params.add_data_on_build:
            return empty
        return extend(res, empty, dataset, jnp.arange(n, dtype=jnp.int32))


def build_streaming(
    res: Optional[Resources],
    params: IvfPqIndexParams,
    source,
    chunk_rows: int = 1 << 20,
    train_rows: int = 1 << 18,
) -> IvfPqIndex:
    """Streamed PQ build over a :class:`raft_tpu.io.BinDataset` — the
    dataset never fully materializes host-side (role of the reference's
    managed-memory trainset spill, ``ivf_pq_build.cuh:1542-1554``).

    Passes: (1) strided trainset sample → centers + rotation +
    codebooks via the in-memory trainer; (2) per-chunk label predict +
    size count; (3) per-chunk encode + scatter into donated code
    buffers. Only the compressed codes live on device, so datasets many
    times HBM fit."""
    res = ensure_resources(res)
    expect(params.codebook_kind == CodebookKind.PER_SUBSPACE,
           "build_streaming supports PER_SUBSPACE codebooks")
    n, dim = source.n_rows, source.dim
    expect(params.n_lists <= n, "n_lists > n_rows")
    pq_dim = params.pq_dim if params.pq_dim > 0 else _auto_pq_dim(dim)
    pq_len = -(-dim // pq_dim)

    with tracing.range("raft_tpu.ivf_pq.build_streaming"):
        # -- pass 1: trainset sample → full training via build()
        train_rows = max(params.n_lists * 2, 1 << params.pq_bits,
                         min(train_rows, n))
        trainset = sample_trainset(source, train_rows, chunk_rows)
        empty = build(res, dataclasses.replace(params,
                                               add_data_on_build=False),
                      trainset)

        km = KMeansBalancedParams(
            metric=(DistanceType.InnerProduct
                    if params.metric == DistanceType.InnerProduct
                    else DistanceType.L2Expanded))

        # -- pass 2: labels + sizes
        labels_np, sizes_np = label_pass(res, km, empty.centers, source,
                                         chunk_rows, params.n_lists)
        max_size = padded_extent(sizes_np)

        # -- pass 3: encode + scatter with donated buffers. 2-D
        # (list, rank) indexing: flat slots would overflow int32 past
        # 2^31 total slots (the billion-row regime this path targets).
        @partial(jax.jit, donate_argnums=(0, 1))
        def encode_scatter(codes_buf, idx_buf, rows, labels, ids, ranks):
            rot = _rotate_residuals(rows, labels, empty.centers,
                                    empty.rotation)
            codes = _encode(rot, empty.codebooks, labels,
                            CodebookKind.PER_SUBSPACE, pq_dim, pq_len)
            return (codes_buf.at[labels, ranks].set(codes),
                    idx_buf.at[labels, ranks].set(ids))

        # graftledger capacity gate (opt-in): admit the streaming
        # path's padded code planes before they allocate (no norms
        # plane in the PQ layout; this path never nibble-packs)
        memwatch.admit(
            memwatch.packed_layout_bytes(params.n_lists, int(max_size),
                                         pq_dim, norms=False),
            "ivf_pq.build_streaming")
        codes_buf = jnp.zeros((params.n_lists, max_size, pq_dim), jnp.uint8)
        idx_buf = jnp.full((params.n_lists, max_size), -1, jnp.int32)
        fill = np.zeros((params.n_lists,), np.int64)
        for first, chunk in source.iter_chunks(chunk_rows):
            interruptible.yield_()  # cancellation point per chunk
            m = chunk.shape[0]
            lab = labels_np[first : first + m]
            ranks = streaming_ranks(lab, fill, params.n_lists)
            codes_buf, idx_buf = encode_scatter(
                codes_buf, idx_buf,
                jnp.asarray(chunk, jnp.float32),
                jnp.asarray(lab),
                jnp.asarray(first + np.arange(m, dtype=np.int32)),
                jnp.asarray(ranks),
            )

        return IvfPqIndex(
            centers=empty.centers,
            rotation=empty.rotation,
            codebooks=empty.codebooks,
            codes=codes_buf,
            indices=idx_buf,
            list_sizes=jnp.asarray(sizes_np, jnp.int32),
            metric=DistanceType(params.metric),
            codebook_kind=params.codebook_kind,
            pq_bits=params.pq_bits,
        )


def _scatter_codes_fn(codes, indices, new_codes, ids, list_ids, ranks):
    """Incremental ``extend`` scatter (see ivf_flat._scatter_extend_fn):
    new code rows land at the running fill ranks of their lists."""
    return (codes.at[list_ids, ranks].set(new_codes),
            indices.at[list_ids, ranks].set(ids))


_scatter_codes = jax.jit(_scatter_codes_fn)
_scatter_codes_donated = jax.jit(_scatter_codes_fn, donate_argnums=(0, 1))


def extend(
    res: Optional[Resources],
    index: IvfPqIndex,
    new_vectors,
    new_indices=None,
    donate: bool = False,
) -> IvfPqIndex:
    """Encode + add vectors — ``ivf_pq::extend``. Functional rebuild of the
    padded code planes. When the new rows fit the existing padding they
    are scattered incrementally (O(new), not O(total)); ``donate=True``
    additionally donates the old code planes to that scatter so the
    rebuild reuses their HBM in place (the old index object must not be
    used afterwards)."""
    res = ensure_resources(res)
    new_vectors = jnp.asarray(new_vectors)
    expect(new_vectors.ndim == 2 and new_vectors.shape[1] == index.dim,
           "new_vectors must be (n, dim)")
    n_new = new_vectors.shape[0]
    if new_indices is None:
        start = index.size
        new_indices = jnp.arange(start, start + n_new, dtype=jnp.int32)
    else:
        new_indices = jnp.asarray(new_indices, jnp.int32)

    with tracing.range("raft_tpu.ivf_pq.extend"):
        km = KMeansBalancedParams(
            metric=(DistanceType.InnerProduct
                    if index.metric == DistanceType.InnerProduct
                    else DistanceType.L2Expanded))
        labels = kmeans_balanced.predict(res, km, index.centers,
                                         new_vectors.astype(jnp.float32))
        rot = _rotate_residuals(new_vectors, labels, index.centers, index.rotation)
        new_codes = _encode(rot, index.codebooks, labels, index.codebook_kind,
                            index.pq_dim, index.pq_len)

        # -- incremental fast path: new codes fit the existing padding.
        # Slot assignment matches the full repack bit-for-bit.
        if index.max_list_size > 0:
            sizes_new = index.list_sizes + jax.ops.segment_sum(
                jnp.ones((n_new,), jnp.int32), labels,
                num_segments=index.n_lists)
            if padded_extent(sizes_new) <= index.max_list_size:
                lab_np = np.asarray(labels)
                fill = np.asarray(index.list_sizes).astype(np.int64)
                ranks = streaming_ranks(lab_np, fill, index.n_lists)
                rows = (_pack_nibbles(new_codes) if index.packed
                        else new_codes)
                scatter = _scatter_codes_donated if donate else _scatter_codes
                codes, indices = scatter(
                    index.codes, index.indices, rows, new_indices,
                    jnp.asarray(lab_np), jnp.asarray(ranks))
                return dataclasses.replace(index, codes=codes,
                                           indices=indices,
                                           list_sizes=sizes_new)

        if index.max_list_size > 0:
            stored = (_unpack_nibbles(index.codes) if index.packed
                      else index.codes)
            old_codes = stored.reshape(-1, index.pq_dim)
            old_ids = index.indices.reshape(-1)
            old_labels = jnp.repeat(jnp.arange(index.n_lists, dtype=jnp.int32),
                                    index.max_list_size)
            keep = old_ids >= 0
            all_codes = jnp.concatenate([old_codes[keep], new_codes])
            all_ids = jnp.concatenate([old_ids[keep], new_indices])
            all_labels = jnp.concatenate([old_labels[keep], labels])
        else:
            all_codes, all_ids, all_labels = new_codes, new_indices, labels

        sizes = jax.ops.segment_sum(
            jnp.ones((all_codes.shape[0],), jnp.int32), all_labels,
            num_segments=index.n_lists,
        )
        max_size = padded_extent(sizes)
        # graftledger capacity gate (opt-in): admit the padded code
        # planes host-side before the repack allocates them. The
        # repack always materializes UNPACKED (pq_dim-wide) planes;
        # a nibble-packed index then allocates the half-width copy
        # BEFORE the unpacked one frees — the transient peak is what
        # must fit, not the stored width. No norms plane in the PQ
        # layout.
        slot_width = index.pq_dim
        if index.pq_bits == 4 and index.pq_dim % 2 == 0:
            slot_width += index.pq_dim // 2
        memwatch.admit(
            memwatch.packed_layout_bytes(
                index.n_lists, int(max_size), slot_width, norms=False),
            "ivf_pq.extend")
        codes, indices, sizes = _pack_codes(all_codes, all_ids, all_labels,
                                            index.n_lists, max_size,
                                            sizes=sizes)
        should_pack = index.pq_bits == 4 and index.pq_dim % 2 == 0
        if should_pack:
            codes = _pack_nibbles(codes)
        return dataclasses.replace(index, codes=codes, indices=indices,
                                   list_sizes=sizes, packed=should_pack)


# ---------------------------------------------------------------------------
# search
# ---------------------------------------------------------------------------


def resolve_scan_engine(engine: str) -> str:
    """Resolve the PQ probe-scan formulation. ``auto`` is the
    list-major union scan on TPU (per-query list gathers bottleneck on
    the scalar core there) and the rank-major gather scan elsewhere.
    There is no Pallas PQ engine (yet) — see ARCHITECTURE.md "IVF scan
    engines" for the measured reasoning."""
    expect(engine in ("auto", "xla", "rank"),
           f"scan_engine must be auto|xla|rank, got {engine!r}")
    if engine == "auto":
        return "xla" if jax.default_backend() == "tpu" else "rank"
    return engine


def resolve_score_mode(score_mode: str, book_size: int = 256) -> str:
    """Resolve "auto" per backend: dynamic per-element gathers lower to
    the TPU scalar core (measured ~18x slower than the one-hot MXU
    contraction on v5e), while on CPU/GPU the direct gather wins. For
    small codebooks (pq_bits <= 5) the masked-sum "select" path beats
    the one-hot contraction on TPU — J compare/select/add VPU ops per
    element with no J-fold matmul inflation."""
    expect(score_mode in ("auto", "gather", "onehot", "select"),
           f"score_mode must be auto|gather|onehot|select, got {score_mode!r}")
    if score_mode == "auto":
        if jax.default_backend() == "tpu":
            return "select" if book_size <= 32 else "onehot"
        return "gather"
    return score_mode


def score_fn(score_mode: str, book_size: int = 256):
    """Resolve a score_mode string (incl. "auto") to its scoring
    function — the single place mapping modes to implementations."""
    mode = resolve_score_mode(score_mode, book_size)
    return {"onehot": _score_onehot, "gather": _score_gather,
            "select": _score_select}[mode]


def _score_gather(lut, rows):
    """dist contributions via per-element LUT gather —
    O(q·m·s) dynamic gathers (the GPU's shared-mem LUT access pattern)."""
    gathered = jnp.take_along_axis(
        lut[:, None, :, :],                            # (q, 1, s, J)
        rows.astype(jnp.int32)[:, :, :, None],         # (q, m, s, 1)
        axis=3,
    )[..., 0]                                          # (q, m, s)
    return jnp.sum(gathered.astype(jnp.float32), axis=2)


def _score_onehot(lut, rows):
    """dist contributions via one-hot × LUT MXU contraction: trades a
    J-fold FLOP inflation for gather-free systolic throughput — the
    profitable trade on TPU when q is small (the VPU executes XLA
    gathers element-at-a-time; the MXU does 256 MACs/cycle/lane).
    dist[q, m] = Σ_{s} lut[q, s, rows[q, m, s]].

    The LUT keeps its dtype (``lut_dtype``): bf16 LUTs get the native
    one-pass MXU path; f32 LUTs stay f32, with internal matmul
    precision governed by the platform default (wrap in
    ``jax.default_matmul_precision('float32')`` for full-width f32 on
    TPU). The one-hot operand is always bf16 — 0/1 are exact there, so
    it carries no rounding and the dominant (q, m, s, J) intermediate
    stays half-width; the only rounding is of the LUT entries
    themselves, and accumulation is always f32 via
    ``preferred_element_type``."""
    q, s, J = lut.shape
    # bf16/fp8 LUTs contract in bf16 (fp8 -> bf16 is exact; rounding
    # already happened at the lut_dtype cast); f32 stays f32
    ctype = (jnp.float32 if lut.dtype == jnp.float32 else jnp.bfloat16)
    oh = jax.nn.one_hot(rows.astype(jnp.int32), J,
                        dtype=jnp.bfloat16)            # (q, m, s, J)
    return jnp.einsum("qmsj,qsj->qm", oh,
                      lut.astype(ctype),
                      preferred_element_type=jnp.float32)


def _score_select(lut, rows):
    """dist contributions via a masked sum over codewords:
    ``acc[q, m, s] = Σ_j lut[q, s, j] · (rows[q, m, s] == j)`` — J
    unrolled compare/select/add terms, entirely elementwise so XLA
    fuses the whole chain (no per-element gathers, no one-hot
    materialization, no J-fold MXU FLOP inflation). The profitable
    TPU path for small codebooks (pq_bits <= 5)."""
    q, s, J = lut.shape
    expect(J <= 32, "score_mode='select' unrolls J terms — use "
           f"onehot/gather for book_size {J} > 32")
    lutf = lut.astype(jnp.float32)
    acc = jnp.zeros(rows.shape, jnp.float32)           # (q, m, s)
    for j in range(J):
        plane = lutf[:, :, j][:, None, :]              # (q, 1, s)
        acc = acc + jnp.where(rows == jnp.uint8(j), plane, 0.0)
    return jnp.sum(acc, axis=2)


def _probe_lut(qf, c, qsub_fixed, lut_fixed, rotation, codebooks, lists,
               ip_metric: bool, per_cluster: bool):
    """Per-probe LUT + base score — the LUT-build half of the reference's
    fused similarity kernel (``detail/ivf_pq_compute_similarity-inl.cuh:
    125-177``), shared by the single-chip and distributed search paths.

    ``qsub_fixed``/``lut_fixed`` are the probe-invariant precomputations
    (rotated query; and, for replicated-codebook IP, the full LUT).
    Returns ``(lut (q, pq_dim, book), base (q,))`` with
    ``score = sum_s lut[q, s, code] + base``.
    """
    q = qf.shape[0]
    pq_len = codebooks.shape[2]
    cb = jnp.take(codebooks, lists, axis=0) if per_cluster else codebooks
    if ip_metric:
        base = jnp.sum(qf * c, axis=1)
        lut = (jnp.einsum("qsl,qjl->qsj", qsub_fixed, cb) if per_cluster
               else lut_fixed)
    else:
        qsub = ((qf - c) @ rotation.T).reshape(q, -1, pq_len)
        base = jnp.zeros((q,), jnp.float32)
        if per_cluster:
            lut = (
                jnp.sum(jnp.square(qsub), -1)[:, :, None]
                - 2.0 * jnp.einsum("qsl,qjl->qsj", qsub, cb)
                + jnp.sum(jnp.square(cb), -1)[:, None, :]
            )
        else:
            lut = (
                jnp.sum(jnp.square(qsub), -1)[:, :, None]
                - 2.0 * jnp.einsum("qsl,sjl->qsj", qsub, cb)
                + jnp.sum(jnp.square(cb), -1)[None, :, :]
            )
    return lut, base


_FP8_DTYPES = tuple(
    getattr(jnp, name) for name in ("float8_e4m3fn", "float8_e5m2")
    if hasattr(jnp, name))
_FP8_MAX = {"float8_e4m3fn": 448.0, "float8_e5m2": 57344.0}


def quantize_lut(lut, lut_dtype):
    """Cast the per-probe LUT to ``lut_dtype`` — the reference's
    fp32/fp16/fp8 LUT ladder (``ivf_pq_compute_similarity-inl.cuh:125-177``).
    fp8's ±448 range can't hold raw squared-distance contributions, so
    (like the reference's fp8 path) entries are scaled per query into
    range; returns ``(lut, scale)`` where ``scale`` is ``(q, 1)`` to
    multiply back into the summed scores, or ``None`` when no scaling
    happened. Scaling is per *query*, not per subspace, so the
    Σ_s lut[q, s, code_s] accumulation stays a plain sum."""
    expect(lut_dtype in (jnp.float32, jnp.bfloat16) + _FP8_DTYPES,
           f"lut_dtype must be float32/bfloat16/float8, got {lut_dtype}")
    if lut_dtype in _FP8_DTYPES:
        fmax = _FP8_MAX[jnp.dtype(lut_dtype).name]
        scale = jnp.max(jnp.abs(lut), axis=(1, 2), keepdims=True) / fmax
        scale = jnp.maximum(scale, 1e-30)
        return (lut / scale).astype(lut_dtype), scale[:, :, 0]
    return lut.astype(lut_dtype), None


def _search_impl_fn(queries, centers, rotation, codebooks, codes, indices,
                    filter_words, init_d=None, init_i=None,
                    probe_counts=None, n_valid=None, row_probes=None,
                    cold_codes=None, hot_slot_map=None,
                    cold_slot_map=None, *,
                    n_probes: int, k: int, metric: DistanceType,
                    codebook_kind: CodebookKind, lut_dtype,
                    score_mode: str = "gather", packed: bool = False,
                    coarse_algo: str = "exact", scan_engine: str = "rank"):
    """ADC probe scan. ``init_d``/``init_i`` optionally provide the
    (q, k) running-state storage (values are reset here); the serving
    path donates them so the scan state reuses one HBM allocation.
    ``probe_counts`` optionally provides the donated (n_lists,) int32
    probe-frequency plane (graftgauge): selected probe ids scatter-add
    into it (rows past ``n_valid`` masked) and the updated plane
    returns as a third output — the results never read it.
    ``row_probes`` (the ragged front — see :func:`_search_ragged_fn`)
    optionally provides a packed batch's per-row probe budgets: the
    coarse stage selects at the class cap and masks each row's slots
    past its own budget to the sentinel id, which the list-major
    engine's membership predicate already rejects.

    ``scan_engine`` must arrive resolved (``rank``/``xla`` via
    :func:`resolve_scan_engine` — it is a jit static). ``rank`` scans
    probe ranks with per-query gathered code rows; ``xla`` scans the
    union of probed lists list-major (``ops/ivf_scan`` formulation):
    each unique list's code plane streams once, scores against every
    query in the tile, and a per-query membership predicate masks
    queries that did not probe it.

    ``cold_codes``/``hot_slot_map``/``cold_slot_map`` (graftcast —
    the tiered PQ cold engine) optionally split the codes plane:
    ``codes`` is then the HOT plane ``(n_hot, m, pq_dim)`` and each
    list-major step selects its block from its tier
    (:func:`raft_tpu.ops.tier_scan.tier_block_select`). Everything
    downstream of the fetch is THIS same body, so the tiered LUT
    union scan is bit-identical to the all-HBM scan by construction.
    List-major only: the rank-major gather has no per-list fetch
    step to steer (``resolve_tier_pq_engine`` rejects it)."""
    q, dim = queries.shape
    tiered_codes = cold_codes is not None
    assert not (tiered_codes and scan_engine == "rank"), \
        "tiered PQ codes need the list-major engine"
    # with a tiered codes plane, codes.shape[0] is the HOT slot count,
    # not the list count — the resident centers plane is the authority
    n_lists = centers.shape[0]
    max_size, pq_dim = codes.shape[1], codes.shape[2]
    if packed:
        pq_dim = pq_dim * 2
    book_size = codebooks.shape[1]
    pq_len = codebooks.shape[2]
    select_min = is_min_close(metric)
    qf = queries.astype(jnp.float32)

    # ---- coarse cluster selection (``select_clusters``,
    #      detail/ivf_pq_search.cuh:70-156)
    ip = jax.lax.dot_general(
        qf, centers, (((1,), (1,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    )
    score = (ip if metric == DistanceType.InnerProduct
             else -(jnp.sum(jnp.square(centers), axis=1)[None, :] - 2.0 * ip))
    probes = coarse_select(score, n_probes, coarse_algo)
    if row_probes is not None:
        from raft_tpu.ops.ivf_scan import ragged_probes

        probes = ragged_probes(probes, row_probes, n_lists)
    if probe_counts is not None:
        from raft_tpu.ops.ivf_scan import probe_histogram

        probe_counts = probe_histogram(
            probes, probe_counts,
            None if row_probes is not None else n_valid)

    pad_val = jnp.inf if select_min else -jnp.inf

    # ---- probe-invariant precomputation (hoisted out of the scan)
    ip_query = metric == DistanceType.InnerProduct
    if ip_query:
        # score = q·y = q·c + (Rq)·ỹ — the rotated query never changes
        qsub_fixed = (qf @ rotation.T).reshape(q, pq_dim, pq_len)
        if codebook_kind == CodebookKind.PER_SUBSPACE:
            lut_fixed = jnp.einsum("qsl,sjl->qsj", qsub_fixed, codebooks)
        else:
            lut_fixed = None
    else:
        qsub_fixed = None
        lut_fixed = None

    # ---- shared per-probe scoring: LUT build + ADC code scan
    score = score_fn(score_mode, book_size)

    def probe_dist(lists, rows, row_ids):
        """(q,) list ids + unpacked (q, m, pq_dim) code rows + (q, m)
        ids -> masked (q, m) dist."""
        c = centers[lists]                             # (q, dim)
        lut, base = _probe_lut(
            qf, c, qsub_fixed, lut_fixed, rotation, codebooks, lists,
            ip_query, codebook_kind == CodebookKind.PER_CLUSTER)
        lut, lut_scale = quantize_lut(lut, lut_dtype)  # (q, pq_dim, J)
        # score codes: dist[q, m] = sum_s lut[q, s, rows[q, m, s]]
        dist = score(lut, rows)
        if lut_scale is not None:
            dist = dist * lut_scale
        dist = dist + base[:, None]
        dist = jnp.where(row_ids >= 0, dist, pad_val)
        if filter_words is not None:
            bits = test_filter(filter_words, row_ids)
            dist = jnp.where(bits & (row_ids >= 0), dist, pad_val)
        return dist

    if scan_engine != "rank":
        # list-major: scan the union of probed lists; one streamed
        # code plane per unique list scores the whole query tile. The
        # scan runs in min-space with the smallest-id tie-break merge
        # (shared with the ivf_flat engines), so exact ADC ties — easy
        # to hit after quantization — resolve deterministically and
        # independently of the list visitation order; IP negates back
        # after the scan (exact for floats).
        from raft_tpu.ops.ivf_scan import _merge_smallest_id, unique_lists

        def step(carry, lid):
            best_d, best_i = carry
            lidc = jnp.minimum(lid, n_lists - 1)       # sentinel-safe
            lists = jnp.full((q,), lidc, jnp.int32)
            if tiered_codes:
                from raft_tpu.ops.tier_scan import (
                    tier_block_select,
                    tier_slot_pair,
                )

                hs, cs = tier_slot_pair(hot_slot_map, cold_slot_map,
                                        lidc)
                rows1 = tier_block_select(codes, cold_codes, hs, cs)
            else:
                rows1 = jax.lax.dynamic_index_in_dim(codes, lidc, 0,
                                                     False)
            ids1 = jax.lax.dynamic_index_in_dim(indices, lidc, 0, False)
            if packed:
                rows1 = _unpack_nibbles(rows1)  # once, before broadcast
            rows = jnp.broadcast_to(rows1[None], (q,) + rows1.shape)
            row_ids = jnp.broadcast_to(ids1[None], (q, ids1.shape[0]))
            dist = probe_dist(lists, rows, row_ids)
            if not select_min:
                dist = -dist                           # to min-space
            # membership (sentinel steps — and sentinel-valued masked
            # probe slots — match nothing, as in ops/ivf_scan)
            probed = jnp.any(probes == lid, axis=1) & (lid < n_lists)
            dist = jnp.where(probed[:, None], dist, jnp.inf)
            return _merge_smallest_id(best_d, best_i, dist, row_ids,
                                      k), None

        init = (
            jnp.full((q, k), jnp.inf, jnp.float32) if init_d is None
            else jnp.full_like(init_d, jnp.inf),
            jnp.full((q, k), -1, jnp.int32) if init_i is None
            else jnp.full_like(init_i, -1),
        )
        (best_d, best_i), _ = jax.lax.scan(step, init,
                                           unique_lists(probes, n_lists))
        if not select_min:
            best_d = -best_d       # inf (unfilled) -> -inf, like rank
    else:

        def step(carry, rank):
            best_d, best_i = carry
            lists = probes[:, rank]                    # (q,)
            rows = jnp.take(codes, lists, axis=0)      # (q, m, pq_dim) u8
            if packed:
                # nibble-unpack right after the HBM gather — the
                # stream stays half-width end to end
                rows = _unpack_nibbles(rows)
            row_ids = jnp.take(indices, lists, axis=0)  # (q, m)
            dist = probe_dist(lists, rows, row_ids)
            new_d, new_i = merge_topk(best_d, best_i, dist, row_ids, k,
                                      select_min)
            return (new_d, new_i), None

        init = (
            jnp.full((q, k), pad_val, jnp.float32) if init_d is None
            else jnp.full_like(init_d, pad_val),
            jnp.full((q, k), -1, jnp.int32) if init_i is None
            else jnp.full_like(init_i, -1),
        )
        (best_d, best_i), _ = jax.lax.scan(step, init,
                                           jnp.arange(n_probes))

    if metric == DistanceType.L2SqrtExpanded:
        best_d = jnp.where(jnp.isfinite(best_d),
                           jnp.sqrt(jnp.maximum(best_d, 0.0)), best_d)
    if probe_counts is not None:
        return best_d, best_i, probe_counts
    return best_d, best_i


_search_impl = partial(jax.jit, static_argnames=(
    "n_probes", "k", "metric", "codebook_kind", "lut_dtype", "score_mode",
    "packed", "coarse_algo", "scan_engine"))(_search_impl_fn)


def _search_ragged_fn(queries, row_probes, centers, rotation, codebooks,
                      codes, indices, filter_words, init_d=None,
                      init_i=None, probe_counts=None, n_valid=None, *,
                      n_probes: int, k: int, metric: DistanceType,
                      codebook_kind: CodebookKind, lut_dtype,
                      score_mode: str = "gather", packed: bool = False,
                      scan_engine: str = "xla"):
    """Packed ragged-batch ADC search body — the PQ member of the
    serving executor's ragged plan family (see
    :func:`raft_tpu.neighbors.ivf_flat._search_ragged_fn` for the
    packing contract; this is the same wrapper over the same hook).
    ``n_probes``/``k`` are the packed batch's CLASS CAPS; per-row
    budgets ride ``row_probes`` into the list-major engine's
    membership mask, and each per-probe LUT depends only on its own
    (query row, list) pair, so a row's scores are independent of what
    else shares the tile — bit-identical per request to
    :func:`_search_impl_fn` on that request alone. Exact coarse
    select only (the prefix-property argument), list-major engine
    only (the rank-major scan has no membership mask)."""
    del n_valid
    expect(scan_engine == "xla",
           "ragged PQ serving needs the membership-masked list-major "
           f"engine ('xla'), got {scan_engine!r}")
    return _search_impl_fn(
        queries, centers, rotation, codebooks, codes, indices,
        filter_words, init_d, init_i, probe_counts, None,
        row_probes=row_probes, n_probes=n_probes, k=k, metric=metric,
        codebook_kind=codebook_kind, lut_dtype=lut_dtype,
        score_mode=score_mode, packed=packed, coarse_algo="exact",
        scan_engine=scan_engine)


def search(
    res: Optional[Resources],
    params: IvfPqSearchParams,
    index: IvfPqIndex,
    queries,
    k: int,
    sample_filter=None,
    query_tile: int = 4096,
) -> Tuple[jax.Array, jax.Array]:
    """ANN search — ``ivf_pq::search`` (``detail/ivf_pq_search.cuh:732``).
    Large query sets run in ``query_tile`` batches (the reference's
    max_queries=4096 loop, ``ivf_pq_search.cuh:790``).

    For L2 metrics the returned distances are approximate (residual-PQ)
    squared L2 (or sqrt thereof); use :func:`raft_tpu.neighbors.refine`
    to re-rank with exact distances, as the reference does."""
    ensure_resources(res)
    queries = jnp.asarray(queries)
    expect(queries.ndim == 2 and queries.shape[1] == index.dim,
           "queries must be (q, dim)")
    expect(index.max_list_size > 0, "index is empty — extend() it first")
    n_probes = min(params.n_probes, index.n_lists)
    expect(params.coarse_algo in ("exact", "approx"),
           f"coarse_algo must be 'exact' or 'approx', got "
           f"{params.coarse_algo!r}")
    expect(params.lut_dtype in (jnp.float32, jnp.bfloat16) + _FP8_DTYPES,
           f"lut_dtype must be float32/bfloat16/float8, got "
           f"{params.lut_dtype}")
    filter_words = resolve_filter_words(sample_filter)
    score_mode = resolve_score_mode(params.score_mode, index.pq_book_size)
    scan_engine = resolve_scan_engine(params.scan_engine)
    with tracing.range("raft_tpu.ivf_pq.search"):
        def run(qt, fw):
            return _search_impl(
                qt, index.centers, index.rotation, index.codebooks,
                index.codes, index.indices, fw,
                n_probes=n_probes, k=k, metric=index.metric,
                codebook_kind=index.codebook_kind,
                lut_dtype=params.lut_dtype, score_mode=score_mode,
                packed=index.packed, coarse_algo=params.coarse_algo,
                scan_engine=scan_engine,
            )

        return tile_queries(run, queries, filter_words, query_tile)


# ---------------------------------------------------------------------------
# serialization
# ---------------------------------------------------------------------------


def save(index: IvfPqIndex, fh_or_path) -> None:
    """``ivf_pq::serialize`` (``detail/ivf_pq_serialize.cuh:39``)."""
    fh, own = open_maybe_path(fh_or_path, "wb")
    try:
        serialize_scalar(fh, _SERIALIZATION_VERSION, np.int32)
        serialize_scalar(fh, int(index.packed), np.int32)
        serialize_scalar(fh, int(index.metric), np.int32)
        serialize_scalar(fh, int(index.codebook_kind), np.int32)
        serialize_scalar(fh, index.pq_bits, np.int32)
        serialize_array(fh, index.centers)
        serialize_array(fh, index.rotation)
        serialize_array(fh, index.codebooks)
        serialize_array(fh, index.codes)
        serialize_array(fh, index.indices)
        serialize_array(fh, index.list_sizes)
    finally:
        if own:
            fh.close()


def load(res: Optional[Resources], fh_or_path) -> IvfPqIndex:
    res = ensure_resources(res)
    fh, own = open_maybe_path(fh_or_path, "rb")
    try:
        check_version(deserialize_scalar(fh), _SERIALIZATION_VERSION, "ivf_pq")
        packed = bool(int(deserialize_scalar(fh)))
        metric = DistanceType(int(deserialize_scalar(fh)))
        kind = CodebookKind(int(deserialize_scalar(fh)))
        pq_bits = int(deserialize_scalar(fh))
        arrays = [res.put(deserialize_array(fh)) for _ in range(6)]
    finally:
        if own:
            fh.close()
    centers, rotation, codebooks, codes, indices, sizes = map(jnp.asarray, arrays)
    return IvfPqIndex(
        centers=centers, rotation=rotation, codebooks=codebooks,
        codes=codes, indices=indices, list_sizes=sizes,
        metric=metric, codebook_kind=kind, pq_bits=pq_bits, packed=packed,
    )
