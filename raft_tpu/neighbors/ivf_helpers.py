"""Index-introspection helpers — analogs of ``ivf_flat_helpers.cuh`` /
``ivf_pq_helpers.cuh`` (pack/unpack list codes, reconstruct vectors,
extract centers). The reference needs these because its lists are opaque
interleaved device buffers; here the layouts are dense, so the helpers
are thin views plus the PQ decoder.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core.validation import expect
from raft_tpu.neighbors.ivf_flat import IvfFlatIndex
from raft_tpu.neighbors.ivf_pq import CodebookKind, IvfPqIndex


# -- IVF-Flat (``ivf_flat_helpers.cuh`` / ``ivf_flat_codepacker.hpp``) ------


def flat_unpack_list_data(index: IvfFlatIndex, label: int) -> Tuple[jax.Array, jax.Array]:
    """Return (vectors (size, d), source ids (size,)) of one list —
    ``helpers::codepacker::unpack`` without the interleave undo."""
    expect(0 <= label < index.n_lists, "bad list id")
    size = int(index.list_sizes[label])
    return index.data[label, :size], index.indices[label, :size]


def flat_pack_list_data(index: IvfFlatIndex, label: int, vectors,
                        ids) -> IvfFlatIndex:
    """Overwrite one list's contents (``helpers::codepacker::pack``).
    Functional: returns a new index."""
    import dataclasses

    expect(0 <= label < index.n_lists, "bad list id")
    vectors = jnp.asarray(vectors, index.data.dtype)
    ids = jnp.asarray(ids, jnp.int32)
    m = index.max_list_size
    expect(vectors.shape[0] <= m, "list overflow — extend() instead")
    n_new = vectors.shape[0]
    pad = m - n_new
    row_data = jnp.pad(vectors, ((0, pad), (0, 0)))
    row_ids = jnp.pad(ids, (0, pad), constant_values=-1)
    data = index.data.at[label].set(row_data)
    indices = index.indices.at[label].set(row_ids)
    norms = jnp.sum(jnp.square(row_data.astype(jnp.float32)), axis=1)
    norms = jnp.where(row_ids >= 0, norms, jnp.inf)
    return dataclasses.replace(
        index,
        data=data,
        data_norms=index.data_norms.at[label].set(norms),
        indices=indices,
        list_sizes=index.list_sizes.at[label].set(n_new),
    )


# -- IVF-PQ (``ivf_pq_helpers.cuh``) ----------------------------------------


def pq_unpack_list_data(index: IvfPqIndex, label: int) -> Tuple[jax.Array, jax.Array]:
    """(codes (size, pq_dim) uint8, ids (size,)) of one list —
    ``helpers::codepacker::unpack_list_data``. Nibble-packed 4-bit
    storage is expanded back to one code per byte."""
    from raft_tpu.neighbors.ivf_pq import _unpack_nibbles

    expect(0 <= label < index.n_lists, "bad list id")
    size = int(index.list_sizes[label])
    codes = index.codes[label, :size]
    if index.packed:
        codes = _unpack_nibbles(codes)
    return codes, index.indices[label, :size]


def pq_reconstruct_list_data(index: IvfPqIndex, label: int) -> jax.Array:
    """Decode one list back to approximate input-space vectors —
    ``helpers::reconstruct_list_data``:

        ŷ = c + R⁺ · concat_s codebook_s[code_s]

    (R is orthogonal on its range so the pseudo-inverse is Rᵀ).
    """
    codes, _ = pq_unpack_list_data(index, label)
    size = codes.shape[0]
    if index.codebook_kind == CodebookKind.PER_SUBSPACE:
        # (size, pq_dim, pq_len): codebooks[s, code[i, s]]
        sub = jnp.take_along_axis(
            index.codebooks[None, :, :, :],            # (1, s, J, L)
            codes.astype(jnp.int32)[:, :, None, None],  # (size, s, 1, 1)
            axis=2,
        )[:, :, 0, :]
    else:
        cb = index.codebooks[label]                    # (J, L)
        sub = cb[codes.astype(jnp.int32)]              # (size, s, L)
    flat = sub.reshape(size, index.pq_dim * index.pq_len)
    resid = flat[:, : index.dim_ext] @ index.rotation  # (size, dim)
    return index.centers[label][None, :] + resid


def pq_extract_centers(index: IvfPqIndex) -> jax.Array:
    """Cluster centers (n_lists, dim) — ``helpers::extract_centers``."""
    return index.centers
