"""CAGRA ⇄ hnswlib interop — TPU-native analog of the reference's
``raft::neighbors::hnsw`` bridge (``cagra_serialize.cuh``'s
``serialize_to_hnswlib``, added to RAFT just after the v23.10 snapshot;
the role here is the same: the index-interop story).

``save_hnswlib`` writes a CAGRA index as a *flat* (single-level)
hnswlib-format file that stock ``hnswlib.Index.load_index`` accepts:
every element sits at level 0 with the full CAGRA ``graph_degree`` as
its level-0 link list, ``maxlevel = 0`` and entrypoint 0, so hnswlib's
search descends straight into the level-0 beam search over the CAGRA
graph. The layout below mirrors ``hnswalg.h``'s ``saveIndex`` field by
field (all scalars little-endian; ``size_t``/``labeltype`` = u64,
``tableint``/``linklistsizeint`` = u32):

    offsetLevel0  u64   = 0
    max_elements  u64   = n
    cur_count     u64   = n
    size_per_elem u64   = 4 + 4*maxM0 + data_bytes + 8
    label_offset  u64   = 4 + 4*maxM0 + data_bytes
    offset_data   u64   = 4 + 4*maxM0
    maxlevel      i32   = 0
    entrypoint    u32   = 0
    maxM          u64   = graph_degree / 2
    maxM0         u64   = graph_degree
    M             u64   = graph_degree / 2
    mult          f64   = 1 / ln(M)
    ef_constr     u64   (cosmetic; hnswlib only replays it)
    n × [ u32 n_links | u32 links[maxM0] | vector | u64 label ]
    n × [ u32 0 ]       (no upper levels)

``load_hnswlib`` is the reverse bridge: it parses any level-0-complete
hnswlib file (including ones produced by hnswlib itself) back into a
:class:`~raft_tpu.neighbors.cagra.CagraIndex`, so foreign HNSW indexes
can be searched with the TPU beam-search kernel.
"""

from __future__ import annotations

import struct

import jax.numpy as jnp
import numpy as np

from raft_tpu.core import tracing
from raft_tpu.core.resources import Resources
from raft_tpu.core.validation import expect
from raft_tpu.distance.types import DistanceType
from raft_tpu.neighbors.cagra import CagraIndex

_HDR = struct.Struct("<QQQQQQiIQQQdQ")  # fields in docstring order


def _data_dtype(dtype) -> np.dtype:
    dt = np.dtype(dtype)
    expect(dt in (np.dtype(np.float32), np.dtype(np.int8),
                  np.dtype(np.uint8)),
           f"hnswlib interop supports f32/int8/uint8 datasets, got {dt} "
           "(cast bf16 datasets to float32 first)")
    return dt


def save_hnswlib(res: Resources | None, index: CagraIndex, path: str,
                 ef_construction: int = 500) -> None:
    """Serialize ``index`` into hnswlib's native file format (see module
    docstring for the exact layout). Float32 exports load with stock
    ``hnswlib.Index(space, dim).load_index(path)`` — ``space='l2'`` for
    the L2 metrics, ``'ip'`` for InnerProduct — and search at the
    recall of the CAGRA graph. int8/uint8 exports use the same layout
    with 1-byte elements, which stock hnswlib's float spaces do NOT
    understand (its data_size is dim*4) — they round-trip through
    :func:`load_hnswlib` or custom-space builds only."""
    dataset = np.asarray(index.dataset)
    dt = _data_dtype(dataset.dtype)
    graph = np.asarray(index.graph, dtype=np.uint32)
    n, degree = graph.shape
    expect(dataset.shape[0] == n, "graph/dataset row mismatch")
    data_bytes = dataset.shape[1] * dt.itemsize
    m = max(degree // 2, 1)
    size_links0 = 4 + 4 * degree
    size_per_elem = size_links0 + data_bytes + 8

    with tracing.range("raft_tpu.hnsw.save_hnswlib"):
        # one structured-array write instead of n struct.pack loops
        elem = np.dtype([
            ("n_links", "<u4"),
            ("links", "<u4", (degree,)),
            ("data", np.dtype(dt).newbyteorder("<"), (dataset.shape[1],)),
            ("label", "<u8"),
        ])
        assert elem.itemsize == size_per_elem
        block = np.empty(n, dtype=elem)
        block["n_links"] = degree
        block["links"] = graph
        block["data"] = dataset
        block["label"] = np.arange(n, dtype=np.uint64)

        with open(path, "wb") as f:
            f.write(_HDR.pack(0, n, n, size_per_elem,
                              size_links0 + data_bytes, size_links0,
                              0, 0, m, degree, m,
                              1.0 / float(np.log(max(m, 2))),
                              ef_construction))
            f.write(block.tobytes())
            f.write(np.zeros(n, dtype="<u4").tobytes())


def load_hnswlib(res: Resources | None, path: str, dim: int,
                 metric: DistanceType = DistanceType.L2Expanded,
                 dtype=np.float32) -> CagraIndex:
    """Parse an hnswlib index file into a :class:`CagraIndex` (level-0
    graph + vectors). Rows with fewer than ``maxM0`` links are padded by
    repeating their first link (a no-op for the beam search's dedup).
    ``dim``/``dtype`` play the role of hnswlib's ``SpaceInterface`` —
    the file itself does not record them."""
    dt = _data_dtype(dtype)
    with tracing.range("raft_tpu.hnsw.load_hnswlib"), open(path, "rb") as f:
        raw = f.read()
    (off0, max_elems, n, size_per_elem, label_off, data_off,
     _maxlevel, _entry, _max_m, max_m0, _m, _mult, _efc) = \
        _HDR.unpack_from(raw, 0)
    expect(off0 == 0, "multi-section hnswlib files are not supported")
    expect(n <= max_elems, "corrupt hnswlib header (count > capacity)")
    data_bytes = dim * dt.itemsize
    expect(data_off == 4 + 4 * max_m0,
           f"level-0 link block mismatch: dim/space wrong? "
           f"(offset_data {data_off} != {4 + 4 * max_m0})")
    expect(label_off == data_off + data_bytes and
           size_per_elem == label_off + 8,
           f"element layout mismatch for dim={dim} itemsize={dt.itemsize}")
    body = _HDR.size + n * size_per_elem
    expect(len(raw) >= body, "truncated hnswlib file")

    elem = np.dtype([
        ("n_links", "<u4"),
        ("links", "<u4", (max_m0,)),
        ("data", np.dtype(dt).newbyteorder("<"), (dim,)),
        ("label", "<u8"),
    ])
    block = np.frombuffer(raw, dtype=elem, count=n, offset=_HDR.size)
    counts = block["n_links"].astype(np.int64)
    expect(bool((counts <= max_m0).all()), "corrupt link counts")
    links = block["links"].astype(np.int64)
    expect(bool((links[np.arange(max_m0) < counts[:, None]] < n).all()),
           "link id out of range")
    # pad short rows with their first link (self-loop if empty)
    first = np.where(counts > 0, links[:, 0], np.arange(n))
    pad = np.arange(max_m0)[None, :] >= counts[:, None]
    graph = np.where(pad, first[:, None], links)

    # hnswlib insertion order is not label order — undo the permutation
    labels = block["label"].astype(np.int64)
    expect(bool((labels < n).all()) and len(np.unique(labels)) == n,
           "labels are not a permutation of [0, n)")
    order = np.argsort(labels)
    data = block["data"][order]
    # rows into label order; link targets from internal id -> label
    graph = labels[graph[order]]

    return CagraIndex(dataset=jnp.asarray(np.ascontiguousarray(data)),
                      graph=jnp.asarray(graph, dtype=jnp.int32),
                      metric=metric)
