"""Shared host-side query batching for the search entry points — the
reference's max_queries loop (``ivf_pq_search.cuh:790``), with per-tile
slicing of 2-D (per-query) filter words.

Shape stability: the ragged final tile is PADDED up to ``query_tile``
instead of tracing a second program specialization for the tail shape
(the serving-path bucketing policy, ``core/executor.py``). Search
results are per-query-row independent in every index family, so pad
rows cannot perturb real rows; their outputs are sliced away. Per-query
(2-D) filter words are padded with zeros — an all-rejected filter row —
which only affects the discarded pad outputs.

Pipelining: every tile is dispatched before any result is fetched. All
device ops here (slices, the per-tile search programs, the final
concatenate) are asynchronous under XLA, so a caller that blocks on the
returned arrays pays ONE device synchronization per call, not one per
tile — the same async-dispatch discipline as the reference's stream
usage.
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp


def pad_rows(arr: jax.Array, rows: int) -> jax.Array:
    """Pad ``arr`` with zero rows up to ``rows`` along axis 0 (no-op if
    already that tall). Zeros are safe pad queries: search results are
    rowwise, so pad rows only produce discarded outputs."""
    q = arr.shape[0]
    if q >= rows:
        return arr
    pad = jnp.zeros((rows - q,) + arr.shape[1:], arr.dtype)
    return jnp.concatenate([arr, pad])


def tile_queries(
    run: Callable,
    queries: jax.Array,
    filter_words,
    query_tile: int,
) -> Tuple[jax.Array, jax.Array]:
    """Apply ``run(queries_tile, filter_words_tile)`` over uniform
    ``query_tile``-row tiles and concatenate. 1-D (shared) filter words
    pass through unchanged; 2-D (per-query) words are sliced with their
    queries. The ragged tail is padded into the tile so every tile runs
    the SAME compiled program (one specialization per tile shape, not
    two), and all tiles are dispatched before anything is fetched."""
    q = queries.shape[0]
    if q <= query_tile:
        return run(queries, filter_words)
    outs_d, outs_i = [], []
    for start in range(0, q, query_tile):
        qt = queries[start : start + query_tile]
        fw = filter_words
        if fw is not None and fw.ndim == 2:
            fw = fw[start : start + query_tile]
        if qt.shape[0] < query_tile:  # ragged tail → pad into the tile
            qt = pad_rows(qt, query_tile)
            if fw is not None and fw.ndim == 2:
                fw = pad_rows(fw, query_tile)
        d, i = run(qt, fw)
        outs_d.append(d)
        outs_i.append(i)
    return (jnp.concatenate(outs_d)[:q], jnp.concatenate(outs_i)[:q])


def coarse_select(score, n_probes: int, coarse_algo: str,
                  recall_target: float = 0.95):
    """Shared coarse cluster selection for the IVF search entries:
    larger-is-better ``score`` (q, n_lists) → (q, n_probes) int32 list
    ids, via exact ``top_k`` or the TPU's native approximate top-k
    unit (``coarse_algo="approx"`` — worthwhile at 10k+ lists)."""
    if coarse_algo == "approx":
        _, probes = jax.lax.approx_max_k(score, n_probes,
                                         recall_target=recall_target)
    else:
        _, probes = jax.lax.top_k(score, n_probes)
    return probes.astype(jnp.int32)
