"""Shared host-side query batching for the search entry points — the
reference's max_queries loop (``ivf_pq_search.cuh:790``), with per-tile
slicing of 2-D (per-query) filter words."""

from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp


def tile_queries(
    run: Callable,
    queries: jax.Array,
    filter_words,
    query_tile: int,
) -> Tuple[jax.Array, jax.Array]:
    """Apply ``run(queries_tile, filter_words_tile)`` over query tiles and
    concatenate. 1-D (shared) filter words pass through unchanged; 2-D
    (per-query) words are sliced with their queries."""
    if queries.shape[0] <= query_tile:
        return run(queries, filter_words)
    outs_d, outs_i = [], []
    for start in range(0, queries.shape[0], query_tile):
        fw = filter_words
        if fw is not None and fw.ndim == 2:
            fw = fw[start : start + query_tile]
        d, i = run(queries[start : start + query_tile], fw)
        outs_d.append(d)
        outs_i.append(i)
    return jnp.concatenate(outs_d), jnp.concatenate(outs_i)


def coarse_select(score, n_probes: int, coarse_algo: str,
                  recall_target: float = 0.95):
    """Shared coarse cluster selection for the IVF search entries:
    larger-is-better ``score`` (q, n_lists) → (q, n_probes) int32 list
    ids, via exact ``top_k`` or the TPU's native approximate top-k
    unit (``coarse_algo="approx"`` — worthwhile at 10k+ lists)."""
    if coarse_algo == "approx":
        _, probes = jax.lax.approx_max_k(score, n_probes,
                                         recall_target=recall_target)
    else:
        _, probes = jax.lax.top_k(score, n_probes)
    return probes.astype(jnp.int32)
