"""IVF-BQ — inverted file with RaBitQ-grade binary quantization, a
TPU-first compression family (quantizer follows RaBitQ, arXiv
2405.12497, and the IVF-RaBitQ build in PAPERS.md: sign codes of the
per-vector residual under a pinned random rotation, with per-vector
scalar correction factors that make the distance estimator *unbiased*
and give it a *known per-candidate error bound*).

Why this exists on TPU: PQ scoring needs per-code LUT lookups — gathers
(scalar-core serialized) or one-hot/masked-sum workarounds (J-fold FLOP
inflation). A sign code has no lookup at all. The geometry-aware
construction (all in the rotated space, ``R`` orthonormal):

    r = x − c            (residual against the list centroid)
    s_l = sign(resid_l)  (level l encodes what levels < l left over)
    a_l = per-level scale, globally rescaled so ⟨r, Σ a_l s_l⟩ = ‖r‖²

stored per vector as the packed sign words plus three scalars:

    rnorm = ‖r‖          (residual norm)
    cfac_l = a_l / ‖r‖   (dimensionless code/residual alignment — for
                          one level this is 1/(√D·⟨r̂, û⟩), the
                          reciprocal code/residual inner product of
                          the RaBitQ estimator)
    errw = ‖r − Σ a_l s_l‖   (unexplained residual norm — the whole
                              error budget of the estimator)

The estimator  ‖q − x‖² ≈ ‖q − c‖² − 2·Σ_l a_l·⟨q̃, s_l⟩ + ‖r‖²
(``q̃ = R(q−c)``) is unbiased with per-candidate error
``2·⟨q̃, r − recon⟩``; under the random rotation the error's standard
deviation is ``≈ 2·‖q̃‖·errw/√D`` — a *measurable* quantity
(:func:`estimator_stats`), which is what retires the hand-calibrated
over-fetch constants (:func:`overfetch_budget`) and powers the fused
estimate-then-rerank scan (:mod:`raft_tpu.ops.bq_scan`): candidates
whose estimate minus the bound cannot beat the running k-th exact
distance are pruned *before* their raw vector is ever read.

Two search modes:

- **fused** (``scan_engine: auto|pallas|xla``, index built with
  ``store_vectors=True`` — the default): list-major scan that scores
  packed codes by XOR+popcount and re-ranks surviving rows against the
  raw vectors of the *same resident block* — returns **exact**
  distances, no separate ``refine`` pass needed.
- **estimate-only** (``scan_engine: "rank"``, or any index without the
  vector plane — e.g. a codes-only streaming build): today's
  rank-major estimate scan; over-fetch by :func:`overfetch_budget` and
  re-rank with :func:`raft_tpu.neighbors.refine`.

Supported metrics: L2Expanded / L2SqrtExpanded / InnerProduct.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.cluster import kmeans_balanced
from raft_tpu.cluster.kmeans_balanced import KMeansBalancedParams
from raft_tpu.core import interruptible, memwatch, tracing
from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.core.serialize import (
    check_version,
    deserialize_array,
    deserialize_scalar,
    open_maybe_path,
    serialize_array,
    serialize_scalar,
)
from raft_tpu.core.validation import expect
from raft_tpu.distance.types import DistanceType, is_min_close
from raft_tpu.matrix.select_k import merge_topk
from raft_tpu.neighbors._batching import coarse_select, tile_queries
from raft_tpu.neighbors._streaming import label_pass, sample_trainset
from raft_tpu.neighbors._packing import (
    pack_padded_lists,
    padded_extent,
    streaming_ranks,
)
from raft_tpu.neighbors.ann_types import IndexParams, SearchParams
from raft_tpu.neighbors.filters import resolve_filter_words, test_filter

# v3: RaBitQ corrections (rnorm/cfac/errw), int32 sign words, optional
# raw-vector rerank plane
_SERIALIZATION_VERSION = 3

# entangled into the pinned rotation stream; bumping it redraws every
# rotation (and re-derives the estimator-quality expectations)
_ROTATION_STREAM = 0

# ONE calibration constant for the bound-derived over-fetch budgets —
# candidates displaced per unit of relative estimator error (measured
# once against the pinned rotation stream; replaces the three
# hand-calibrated constants 40/240/60 retired in this PR: derived
# budgets land at ~38 on the self-hit config, ~41 on the streamed
# 2-bit config, and k on every index carrying the rerank plane)
_OVERFETCH_KAPPA = 25.0


def _pinned_rotation(seed: int, dim_ext: int, dim: int) -> jax.Array:
    """Random orthogonal rotation dim → dim_ext from a **pinned**
    generator: numpy's PCG64 stream is stable across numpy versions,
    where ``jax.random`` draws shift across jax releases (threefry
    partitionable default, key layout). The estimator-quality contracts
    in ``tests/test_ivf_bq.py`` are calibrated against this exact
    stream — a jax upgrade must not silently redraw the rotation every
    saved BQ index and recall bound was derived under."""
    rng = np.random.default_rng(
        np.random.SeedSequence([seed, _ROTATION_STREAM]))
    g = rng.standard_normal((max(dim_ext, dim), dim_ext))
    q, r = np.linalg.qr(g)          # orthonormal columns
    # LAPACK backends disagree on QR column signs — normalize so the
    # rotation (not just the stream) is backend-invariant
    d = np.sign(np.diag(r))
    d[d == 0] = 1.0
    q = q * d
    return jnp.asarray(q[:dim, :].T, jnp.float32)  # (dim_ext, dim)


@dataclasses.dataclass(frozen=True)
class IvfBqIndexParams(IndexParams):
    n_lists: int = 1024
    kmeans_n_iters: int = 20
    kmeans_trainset_fraction: float = 0.5
    # residual sign-quantization levels (bits/dim, 1..4): level l
    # encodes the residual left by levels < l. Each level adds D bits
    # and one f32 scale per vector and one more popcount term to the
    # score; 2 bits roughly halves the estimator noise of 1 bit.
    bits: int = 1
    # keep the raw vectors in list layout next to the codes — the
    # rerank plane of the fused estimate-then-rerank scan. False =
    # codes-only (the many-times-HBM streaming regime): searches are
    # estimate-only and re-rank host-side via neighbors.refine.
    store_vectors: bool = True


@dataclasses.dataclass(frozen=True)
class IvfBqSearchParams(SearchParams):
    n_probes: int = 20
    # "approx" routes cluster selection through the TPU's native
    # approximate top-k unit (same knob as the flat/PQ params)
    coarse_algo: str = "exact"
    # probe-scan engine (ops/bq_scan): auto = fused Pallas kernel on
    # TPU / fused XLA scan elsewhere when the index carries the
    # rerank plane; "rank" = the legacy rank-major estimate-only scan
    scan_engine: str = "auto"    # "auto" | "pallas" | "xla" | "rank"
    # error-bound confidence multiplier for the fused prune (est −
    # epsilon·sigma must beat the running k-th exact distance to
    # trigger a re-rank): 3.0 covers ≥ 99% of estimator errors —
    # measured in tests/test_ivf_bq.py::TestEstimatorContract
    epsilon: float = 3.0
    # query-side quantization grid width for the popcount estimate
    # (RaBitQ's asymmetric query treatment). 0 resolves per code
    # ladder (raft_tpu.ops.bq_scan.auto_query_bits): 4 below 3 code
    # bits, 8 at bits >= 3 — where the code estimate is sharp enough
    # that the 4-bit query grid becomes the dominant noise source
    query_bits: int = 0


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass(frozen=True)
class IvfBqIndex:
    """Binary-quantized IVF index (RaBitQ construction)."""

    centers: jax.Array        # (n_lists, dim) f32
    rotation: jax.Array       # (dim_ext, dim) f32 random orthogonal
    codes: jax.Array          # (n_lists, max_list_size, bits·D/32) i32
    rnorm: jax.Array          # (n_lists, max_list_size) f32 — ‖r‖
    cfac: jax.Array           # (n_lists, max_list_size, bits) f32
    errw: jax.Array           # (n_lists, max_list_size) f32 — ‖r−recon‖
    indices: jax.Array        # (n_lists, max_list_size) int32, -1 pad
    list_sizes: jax.Array     # (n_lists,) int32
    metric: DistanceType
    # optional rerank plane (store_vectors=True): raw vectors in list
    # layout + per-slot squared norms (+inf at padding, like ivf_flat)
    data: Optional[jax.Array] = None         # (n_lists, max, dim) f32
    data_norms: Optional[jax.Array] = None   # (n_lists, max) f32

    def tree_flatten(self):
        return (self.centers, self.rotation, self.codes, self.rnorm,
                self.cfac, self.errw, self.indices, self.list_sizes,
                self.data, self.data_norms), (self.metric,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children[:8], metric=aux[0], data=children[8],
                   data_norms=children[9])

    @property
    def n_lists(self) -> int:
        return self.centers.shape[0]

    @property
    def dim(self) -> int:
        return self.centers.shape[1]

    @property
    def dim_ext(self) -> int:
        return self.rotation.shape[0]

    @property
    def bits(self) -> int:
        return self.cfac.shape[2]

    @property
    def max_list_size(self) -> int:
        return self.codes.shape[1]

    @property
    def size(self) -> int:
        return int(self.list_sizes.sum())


def _pack_words(signs):
    """(..., dim_ext) bool (sign >= 0) → (..., dim_ext // 32) int32
    sign words, bit b of word w = component 32w + b. int32 words (not
    the old uint8 bytes) so the fused kernel's XOR+popcount scoring
    runs on native VPU lanes."""
    d = signs.shape[-1]
    b = signs.reshape(*signs.shape[:-1], d // 32, 32).astype(jnp.int32)
    weights = jnp.left_shift(
        jnp.int32(1), jnp.arange(32, dtype=jnp.int32))
    return jnp.sum(b * weights, axis=-1, dtype=jnp.int32)


def _unpack_pm1(words, dtype=jnp.bfloat16):
    """(..., n_words) int32 → (..., 32·n_words) ±1 in ``dtype``."""
    bits = (words[..., None] >> jnp.arange(32, dtype=jnp.int32)) & 1
    pm1 = bits.astype(dtype) * 2 - 1
    return pm1.reshape(*words.shape[:-1], words.shape[-1] * 32)


def _encode(rot_residuals, bits: int = 1):
    """residual r → (packed sign words per level, ‖r‖, per-level
    dimensionless scales, unexplained-residual norm).

    Level 0 sign-encodes r with the least-squares scale ⟨r,s⟩/D; each
    further level encodes what the previous levels left over (residual
    sign quantization). A final global rescale γ = ‖r‖² / ⟨r, recon⟩
    is folded into every level's scale so that ⟨r, Σ a_l s_l⟩ = ‖r‖²
    EXACTLY — the collinearity correction of the RaBitQ estimator,
    which makes the distance estimate of a vector to itself 0 (with a
    single level a = ‖r‖²/⟨r, s⟩ = ‖r‖/(√D·⟨r̂, û⟩) — the
    reciprocal code/residual inner product). The stored scale is
    ``cfac_l = a_l/‖r‖``; ``errw = ‖r − γ·recon‖`` is the residual
    the code fails to explain — the estimator's entire error budget
    (per-candidate error std ≈ 2·‖q̃‖·errw/√D under the rotation).

    Returns codes (..., bits·D/32) i32, rnorm, cfac (..., bits),
    errw."""
    d = rot_residuals.shape[-1]
    rn2 = jnp.sum(jnp.square(rot_residuals), axis=-1)
    rnorm = jnp.sqrt(rn2)
    level_codes, level_scales = [], []
    resid = rot_residuals
    recon = jnp.zeros_like(rot_residuals)
    for _ in range(bits):
        signs = resid >= 0
        s = jnp.where(signs, 1.0, -1.0)
        a = jnp.sum(resid * s, axis=-1) / d           # LS scale per level
        level_codes.append(_pack_words(signs))
        level_scales.append(a)
        recon = recon + a[..., None] * s
        resid = resid - a[..., None] * s
    gamma = rn2 / jnp.maximum(
        jnp.sum(rot_residuals * recon, axis=-1), 1e-20)
    codes = jnp.concatenate(level_codes, axis=-1)
    scales = jnp.stack(level_scales, axis=-1) * gamma[..., None]
    errw = jnp.linalg.norm(
        rot_residuals - recon * gamma[..., None], axis=-1)
    cfac = scales / jnp.maximum(rnorm, 1e-20)[..., None]
    return (codes, rnorm.astype(jnp.float32), cfac.astype(jnp.float32),
            errw.astype(jnp.float32))


def _pack_lists(codes, rnorm, cfac, errw, ids, labels, n_lists,
                max_size, vectors=None, sizes=None):
    """Scatter rows into the padded [n_lists, max_list_size] layout
    (the shared sort-and-rank packing). ``vectors`` optionally rides
    along as the rerank plane."""
    payloads = [(codes, 0), (rnorm, 0.0), (cfac, 0.0), (errw, 0.0),
                (ids, -1)]
    if vectors is not None:
        payloads.append((vectors, 0.0))
    packed, sizes = pack_padded_lists(labels, n_lists, max_size,
                                      payloads, sizes=sizes)
    return packed, sizes


def _vector_norms(data, indices):
    """Per-slot squared norms, +inf at padding so padded slots never
    win the exact re-rank (the ivf_flat convention)."""
    norms = jnp.sum(jnp.square(data.astype(jnp.float32)), axis=2)
    return jnp.where(indices >= 0, norms, jnp.inf)


def build(
    res: Optional[Resources],
    params: IvfBqIndexParams,
    dataset,
) -> IvfBqIndex:
    """Train coarse centers + random rotation, RaBitQ-encode the
    dataset (and, by default, keep the raw vectors as the fused
    re-rank plane)."""
    res = ensure_resources(res)
    dataset = jnp.asarray(dataset)
    expect(dataset.ndim == 2, "dataset must be (n, d)")
    n, dim = dataset.shape
    expect(params.n_lists <= n, "n_lists > n_rows")
    expect(params.metric in (DistanceType.L2Expanded,
                             DistanceType.L2SqrtExpanded,
                             DistanceType.InnerProduct),
           f"ivf_bq supports L2/L2Sqrt/InnerProduct, got {params.metric!r}")
    expect(1 <= params.bits <= 4, "bits must be in [1, 4]")
    dim_ext = -(-dim // 32) * 32

    with tracing.range("raft_tpu.ivf_bq.build"):
        frac = min(max(params.kmeans_trainset_fraction, 0.0), 1.0)
        n_train = min(n, max(params.n_lists * 2, int(n * frac)))
        stride = max(1, n // n_train)
        trainset = dataset[::stride][:n_train].astype(jnp.float32)
        km = KMeansBalancedParams(
            n_iters=params.kmeans_n_iters,
            metric=(DistanceType.InnerProduct
                    if params.metric == DistanceType.InnerProduct
                    else DistanceType.L2Expanded),
            seed=res.seed,
        )
        centers = kmeans_balanced.fit(res, km, trainset, params.n_lists)
        # the random rotation is what makes sign codes informative —
        # always random, never identity; pinned so recall contracts
        # survive jax upgrades
        rotation = _pinned_rotation(res.seed, dim_ext, dim)

        empty = IvfBqIndex(
            centers=centers, rotation=rotation,
            codes=jnp.zeros((params.n_lists, 0,
                             params.bits * dim_ext // 32), jnp.int32),
            rnorm=jnp.zeros((params.n_lists, 0), jnp.float32),
            cfac=jnp.zeros((params.n_lists, 0, params.bits),
                           jnp.float32),
            errw=jnp.zeros((params.n_lists, 0), jnp.float32),
            indices=jnp.full((params.n_lists, 0), -1, jnp.int32),
            list_sizes=jnp.zeros((params.n_lists,), jnp.int32),
            metric=DistanceType(params.metric),
            data=(jnp.zeros((params.n_lists, 0, dim), jnp.float32)
                  if params.store_vectors else None),
            data_norms=(jnp.zeros((params.n_lists, 0), jnp.float32)
                        if params.store_vectors else None),
        )
        if not params.add_data_on_build:
            return empty
        return extend(res, empty, dataset, jnp.arange(n, dtype=jnp.int32))


def build_streaming(
    res: Optional[Resources],
    params: IvfBqIndexParams,
    source,
    chunk_rows: int = 1 << 20,
    train_rows: int = 1 << 18,
) -> IvfBqIndex:
    """Streamed BQ build over a :class:`raft_tpu.io.BinDataset` — the
    dataset never fully materializes host-side (same three passes as
    the flat/PQ streaming builds: trainset sample → label count →
    encode + scatter into donated buffers). With
    ``store_vectors=False`` only the sign codes and per-vector scalars
    live in HBM, so datasets many times HBM fit (searches are then
    estimate-only — over-fetch by :func:`overfetch_budget` and refine
    host-side); the default keeps the rerank plane and streams the raw
    rows into it chunk-by-chunk."""
    res = ensure_resources(res)
    n, dim = source.n_rows, source.dim
    expect(params.n_lists <= n, "n_lists > n_rows")

    with tracing.range("raft_tpu.ivf_bq.build_streaming"):
        # -- pass 1: trainset sample → centers + rotation via build()
        train_rows = max(params.n_lists * 2, min(train_rows, n))
        trainset = sample_trainset(source, train_rows, chunk_rows)
        empty = build(res, dataclasses.replace(params,
                                               add_data_on_build=False),
                      trainset)

        km = KMeansBalancedParams(
            metric=(DistanceType.InnerProduct
                    if params.metric == DistanceType.InnerProduct
                    else DistanceType.L2Expanded))

        # -- pass 2: labels + sizes
        labels_np, sizes_np = label_pass(res, km, empty.centers, source,
                                         chunk_rows, params.n_lists)
        max_size = padded_extent(sizes_np)

        # -- pass 3: encode + scatter with donated buffers (the code
        # planes and, when kept, the rerank plane each thread through
        # their own donated scatter — state = step(state) discipline)
        @partial(jax.jit, donate_argnums=(0, 1, 2, 3, 4))
        def encode_scatter(codes_buf, rn_buf, cf_buf, ew_buf, idx_buf,
                           rows, labels, ids, ranks):
            resid = rows - empty.centers[labels]
            rot = resid @ empty.rotation.T
            codes, rnorm, cfac, errw = _encode(rot, params.bits)
            return (codes_buf.at[labels, ranks].set(codes),
                    rn_buf.at[labels, ranks].set(rnorm),
                    cf_buf.at[labels, ranks].set(cfac),
                    ew_buf.at[labels, ranks].set(errw),
                    idx_buf.at[labels, ranks].set(ids))

        @partial(jax.jit, donate_argnums=(0,))
        def scatter_rows(data_buf, rows, labels, ranks):
            return data_buf.at[labels, ranks].set(rows)

        dim_ext = empty.dim_ext
        # graftledger capacity gate (opt-in): one slot = packed words
        # + the three correction scalars + the id plane (+ the raw
        # vector when the rerank plane streams too) — the same slot
        # model the extend gate admits against
        slot = (params.bits * dim_ext // 32) * 4 + 4 + params.bits * 4 \
            + 4 + 4
        if params.store_vectors:
            # raw vector plane + the f32 data_norms plane the
            # store_vectors epilog materializes (_vector_norms)
            slot += dim * 4 + 4
        memwatch.admit(params.n_lists * int(max_size) * slot,
                       "ivf_bq.build_streaming")
        codes_buf = jnp.zeros(
            (params.n_lists, max_size, params.bits * dim_ext // 32),
            jnp.int32)
        rn_buf = jnp.zeros((params.n_lists, max_size), jnp.float32)
        cf_buf = jnp.zeros((params.n_lists, max_size, params.bits),
                           jnp.float32)
        ew_buf = jnp.zeros((params.n_lists, max_size), jnp.float32)
        idx_buf = jnp.full((params.n_lists, max_size), -1, jnp.int32)
        data_buf = (jnp.zeros((params.n_lists, max_size, dim),
                              jnp.float32)
                    if params.store_vectors else None)
        fill = np.zeros((params.n_lists,), np.int64)
        for first, chunk in source.iter_chunks(chunk_rows):
            interruptible.yield_()  # cancellation point per chunk
            m = chunk.shape[0]
            lab = labels_np[first : first + m]
            ranks = streaming_ranks(lab, fill, params.n_lists)
            rows = jnp.asarray(chunk, jnp.float32)
            lab_d = jnp.asarray(lab)
            ranks_d = jnp.asarray(ranks)
            codes_buf, rn_buf, cf_buf, ew_buf, idx_buf = encode_scatter(
                codes_buf, rn_buf, cf_buf, ew_buf, idx_buf, rows, lab_d,
                jnp.asarray(first + np.arange(m, dtype=np.int32)),
                ranks_d,
            )
            if params.store_vectors:
                data_buf = scatter_rows(data_buf, rows, lab_d, ranks_d)

        return IvfBqIndex(
            centers=empty.centers,
            rotation=empty.rotation,
            codes=codes_buf,
            rnorm=rn_buf,
            cfac=cf_buf,
            errw=ew_buf,
            indices=idx_buf,
            list_sizes=jnp.asarray(sizes_np, jnp.int32),
            metric=DistanceType(params.metric),
            data=data_buf,
            data_norms=(_vector_norms(data_buf, idx_buf)
                        if params.store_vectors else None),
        )


def extend(
    res: Optional[Resources],
    index: IvfBqIndex,
    new_vectors,
    new_indices=None,
) -> IvfBqIndex:
    """Encode + add vectors (functional rebuild of the padded lists)."""
    res = ensure_resources(res)
    new_vectors = jnp.asarray(new_vectors)
    expect(new_vectors.ndim == 2 and new_vectors.shape[1] == index.dim,
           "new_vectors must be (n, dim)")
    n_new = new_vectors.shape[0]
    if new_indices is None:
        start = index.size
        new_indices = jnp.arange(start, start + n_new, dtype=jnp.int32)
    else:
        new_indices = jnp.asarray(new_indices, jnp.int32)

    with tracing.range("raft_tpu.ivf_bq.extend"):
        km = KMeansBalancedParams(
            metric=(DistanceType.InnerProduct
                    if index.metric == DistanceType.InnerProduct
                    else DistanceType.L2Expanded))
        labels = kmeans_balanced.predict(res, km, index.centers,
                                         new_vectors.astype(jnp.float32))
        newf = new_vectors.astype(jnp.float32)
        resid = newf - index.centers[labels]
        rot = resid @ index.rotation.T                   # (n, dim_ext)
        codes, rnorm, cfac, errw = _encode(rot, index.bits)
        with_vectors = index.data is not None

        if index.max_list_size > 0:
            keep = index.indices.reshape(-1) >= 0
            old_labels = jnp.repeat(
                jnp.arange(index.n_lists, dtype=jnp.int32),
                index.max_list_size)
            nw = index.codes.shape[2]
            all_codes = jnp.concatenate(
                [index.codes.reshape(-1, nw)[keep], codes])
            all_rn = jnp.concatenate(
                [index.rnorm.reshape(-1)[keep], rnorm])
            all_cf = jnp.concatenate(
                [index.cfac.reshape(-1, index.bits)[keep], cfac])
            all_ew = jnp.concatenate(
                [index.errw.reshape(-1)[keep], errw])
            all_ids = jnp.concatenate(
                [index.indices.reshape(-1)[keep], new_indices])
            all_labels = jnp.concatenate([old_labels[keep], labels])
            all_vecs = None
            if with_vectors:
                all_vecs = jnp.concatenate(
                    [index.data.reshape(-1, index.dim)[keep], newf])
        else:
            all_codes, all_rn, all_cf, all_ew = codes, rnorm, cfac, errw
            all_ids, all_labels = new_indices, labels
            all_vecs = newf if with_vectors else None

        sizes = jax.ops.segment_sum(
            jnp.ones((all_codes.shape[0],), jnp.int32), all_labels,
            num_segments=index.n_lists)
        max_size = padded_extent(sizes)
        # graftledger capacity gate (opt-in): one slot carries the
        # packed sign words (i32), the three correction scalars
        # (rnorm + per-level cfac + errw, f32), the id plane, and —
        # with the rerank plane — the raw f32 vector + its norm
        slot = (all_codes.shape[1] * 4 + 4 + index.bits * 4 + 4 + 4)
        if with_vectors:
            slot += index.dim * 4 + 4
        memwatch.admit(index.n_lists * int(max_size) * slot,
                       "ivf_bq.extend")
        packed, sizes = _pack_lists(all_codes, all_rn, all_cf, all_ew,
                                    all_ids, all_labels, index.n_lists,
                                    max_size, vectors=all_vecs,
                                    sizes=sizes)
        c, rn, cf, ew, ids = packed[:5]
        data = packed[5] if with_vectors else None
        return dataclasses.replace(
            index, codes=c, rnorm=rn, cfac=cf, errw=ew, indices=ids,
            list_sizes=sizes, data=data,
            data_norms=(_vector_norms(data, ids) if with_vectors
                        else None))


# ---------------------------------------------------------------------------
# estimator statistics and bound-derived over-fetch budgets
# ---------------------------------------------------------------------------


def estimator_stats(index) -> dict:
    """Measured estimator-error statistics of one (shard of an) index
    — the quantities the bound-derived budgets consume. ONE small
    device fetch; build/plan-time only, never on the dispatch path.

    - ``mean_errw``: mean unexplained-residual norm ‖r − recon‖
    - ``mean_rnorm2``: mean squared residual norm (the distance scale)
    - ``rel_err``: 2·mean_errw / (√D · √mean_rnorm2) — the
      per-candidate distance-error std over the distance scale, the
      dimensionless knob every budget below is monotone in
    """
    ids = index.indices
    valid = (ids >= 0).astype(jnp.float32)
    cnt = jnp.maximum(jnp.sum(valid), 1.0)
    mean_e = jnp.sum(index.errw * valid) / cnt
    mean_rn2 = jnp.sum(jnp.square(index.rnorm) * valid) / cnt
    mean_e, mean_rn2 = jax.device_get((mean_e, mean_rn2))
    mean_e = float(mean_e)
    mean_rn2 = float(mean_rn2)
    rel = (2.0 * mean_e / (math.sqrt(index.dim_ext)
                           * math.sqrt(max(mean_rn2, 1e-20)))
           if mean_rn2 > 0 else 0.0)
    return {"mean_errw": mean_e, "mean_rnorm2": mean_rn2,
            "rel_err": rel, "dim_ext": index.dim_ext}


def overfetch_budget(index, k: int, *, confidence: float = 1.0,
                     query_bits: int = 4) -> int:
    """Bound-derived candidate budget for the estimate-only path: how
    many estimate-ranked candidates to fetch so the true top-k survive
    the exact re-rank (:func:`raft_tpu.neighbors.refine`).

    ``budget = ceil(k · (1 + confidence·κ_eff·ρ))`` where ``ρ`` is the
    index's measured relative estimator error
    (:func:`estimator_stats`) and ``κ_eff`` scales the one calibration
    constant ``_OVERFETCH_KAPPA`` (displacement per unit relative
    error, measured against the pinned rotation stream at the 4-bit
    query grid) by the query grid actually searched with:
    ``κ_eff = κ·(2^(4−query_bits) + 1)/2`` — the quantization noise
    term halves per extra query bit while the rotation term stays, so
    the identity holds at ``query_bits=4`` and an 8-bit grid
    (``auto_query_bits`` at a bits≥3 ladder) buys ~47% less
    over-fetch. Replaces the three hand-calibrated constants
    (self-hit 40, sharded merge 240, streamed-bits2 60;
    ``tests/test_ivf_bq.py`` pins derived ≤ old at equal recall
    targets, and pins the ladder's monotone budget drop). An index
    carrying the rerank plane needs no over-fetch at all: the fused
    scan already returns exact distances, so the budget is ``k``."""
    expect(k >= 1, "k must be >= 1")
    expect(1 <= query_bits <= 8,
           f"query_bits must be 1..8, got {query_bits}")
    if index.data is not None:
        return k
    stats = estimator_stats(index)
    kappa_eff = _OVERFETCH_KAPPA * (2.0 ** (4 - query_bits) + 1.0) / 2.0
    budget = math.ceil(
        k * (1.0 + confidence * kappa_eff * stats["rel_err"]))
    return max(k, min(budget, index.size))


def estimator_margin(qc_norm, rnorm, errw, delta, dim_ext: int,
                     epsilon: float):
    """Per-candidate distance-error bound at confidence ``epsilon``
    (the fused prune's margin; shared with the engines in
    :mod:`raft_tpu.ops.bq_scan` and the estimator-contract tests).

    Two independent noise sources add in quadrature: the rotation
    part (the unexplained residual ``errw`` projected on the query
    direction — std ``‖q̃‖·errw/√D`` under the random rotation) and
    the query-quantization part (uniform rounding noise of width
    ``delta`` against the reconstruction, whose squared norm is
    ``rnorm² + errw²`` by the collinearity rescale). The factor 2 is
    the cross term's weight in the squared-distance estimator."""
    recon2 = jnp.square(rnorm) + jnp.square(errw)
    return 2.0 * epsilon * jnp.sqrt(
        jnp.square(qc_norm * errw) / dim_ext
        + jnp.square(delta) * recon2 / 12.0)


# ---------------------------------------------------------------------------
# search
# ---------------------------------------------------------------------------


def score_probe(lists, qrot, centers_rot, ip, cn, qnorm, codes, rnorm,
                cfac, indices, ip_metric: bool, pad_val, valid=None):
    """THE per-probe scoring step of the rank-major estimate-only
    engine, shared by the single-chip and distributed searches: gather
    one probed list per query, unpack the sign words, one MXU GEMM
    cross term, estimator assembly. Rows that are padding (or,
    distributed, probes this shard does not own via ``valid``) score
    ``pad_val``. Returns ``(dist (q, m), row_ids)``.

    Inputs are the probe-invariant precomputations: ``qrot = R q``,
    ``centers_rot = R c`` (L2 only), the coarse-stage ``ip = q·c``
    matrix and norms (L2 only)."""
    q = qrot.shape[0]
    qidx = jnp.arange(q)
    words = jnp.take(codes, lists, axis=0)       # (q, m, bits·D/32)
    cf = jnp.take(cfac, lists, axis=0)           # (q, m, bits)
    rn = jnp.take(rnorm, lists, axis=0)          # (q, m)
    bits = cf.shape[-1]
    a = rn[..., None] * cf                       # per-level scales
    pm1 = _unpack_pm1(words)                     # (q, m, bits·D) bf16
    m = pm1.shape[1]
    pm1 = pm1.reshape(q, m, bits, -1)            # (q, m, L, D)
    row_ids = jnp.take(indices, lists, axis=0)   # (q, m)
    if ip_metric:
        # similarity (select_min is False for IP — no negation)
        crosses = jnp.einsum("qd,qmld->qml", qrot.astype(jnp.bfloat16),
                             pm1, preferred_element_type=jnp.float32)
        base = ip[qidx, lists]                   # q·c from coarse
        dist = base[:, None] + jnp.sum(a * crosses, axis=-1)
    else:
        qsub = qrot - centers_rot[lists]         # (q, dim_ext)
        crosses = jnp.einsum("qd,qmld->qml", qsub.astype(jnp.bfloat16),
                             pm1, preferred_element_type=jnp.float32)
        r2 = jnp.square(rn)
        # ||q−c||² from the coarse-stage terms (R is an isometry, so
        # this equals Σ qsub² without re-reducing per probe)
        qc2 = qnorm + cn[lists] - 2.0 * ip[qidx, lists]
        dist = (jnp.maximum(qc2, 0.0)[:, None]
                - 2.0 * jnp.sum(a * crosses, axis=-1) + r2)
    ok = row_ids >= 0
    if valid is not None:
        ok = ok & valid[:, None]
    return jnp.where(ok, dist, pad_val), row_ids


def _search_impl_fn(queries, centers, rotation, codes, rnorm, cfac,
                    errw, indices, data, data_norms, filter_words,
                    init_d=None, init_i=None, probe_counts=None,
                    n_valid=None, row_probes=None, cold_planes=None,
                    hot_slot_map=None, cold_slot_map=None, *,
                    n_probes: int,
                    k: int, metric: DistanceType,
                    coarse_algo: str = "exact",
                    scan_engine: str = "rank", epsilon: float = 3.0,
                    query_bits: int = 0):
    """BQ probe scan: coarse select, then either the fused
    estimate-then-rerank list-major engines (``pallas``/``xla`` —
    :mod:`raft_tpu.ops.bq_scan`, exact output distances) or the legacy
    rank-major estimate-only scan (``rank``). ``init_d``/``init_i``
    optionally provide the (q, k) running-state storage (values are
    reset here); the serving path donates them (rank and xla engines —
    the Pallas kernel's state lives in VMEM scratch). ``probe_counts``
    optionally provides the donated (n_lists,) int32 probe-frequency
    plane (graftgauge): selected probe ids scatter-add into it (rows
    past ``n_valid`` masked) and the updated plane returns as a third
    output. ``row_probes`` (the ragged front — see
    :func:`_search_ragged_fn`) optionally provides a packed batch's
    per-row probe budgets: the coarse stage selects at the class cap
    and masks each row's slots past its own budget to the sentinel id,
    which the fused engines' membership predicate already rejects.
    ``scan_engine`` must arrive resolved (via
    :func:`raft_tpu.ops.bq_scan.resolve_bq_engine`): it is a jit
    static, so an unresolved ``"auto"`` would fork the compile cache.
    ``cold_planes`` (graftcast) optionally carries the cold halves of
    the five per-row record planes — ``codes``/``rnorm``/``cfac``/
    ``errw``/``data`` are then the HOT halves and the fused XLA
    engine selects each list's planes from one tier through
    ``(hot_slot_map, cold_slot_map)`` (same body, same estimates,
    same prune decisions ⇒ bit-identical to all-HBM)."""
    q, dim = queries.shape
    if cold_planes is not None:
        assert scan_engine == "xla", \
            "tiered BQ record planes need the fused XLA engine"
    select_min = is_min_close(metric)
    qf = queries.astype(jnp.float32)
    ip_metric = metric == DistanceType.InnerProduct

    # coarse cluster selection (shared shape with ivf_flat/pq)
    ip = jax.lax.dot_general(
        qf, centers, (((1,), (1,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    )
    if ip_metric:
        score = ip
        c_norms = None
        qnorm = None
    else:
        c_norms = jnp.sum(jnp.square(centers), axis=1)
        score = -(c_norms[None, :] - 2.0 * ip)
        qnorm = jnp.sum(jnp.square(qf), axis=1)
    probes = coarse_select(score, n_probes, coarse_algo)
    if row_probes is not None:
        from raft_tpu.ops.ivf_scan import ragged_probes

        probes = ragged_probes(probes, row_probes, centers.shape[0])
    if probe_counts is not None:
        from raft_tpu.ops.ivf_scan import probe_histogram

        probe_counts = probe_histogram(
            probes, probe_counts,
            None if row_probes is not None else n_valid)
    pad_val = jnp.inf if select_min else -jnp.inf

    # probe-invariant precomputation: the rotated query never changes,
    # and q̃ = R(q−c) = Rq − (Rc) needs only a rotated-centers table
    qrot = qf @ rotation.T                             # (q, dim_ext)
    centers_rot = centers @ rotation.T

    if scan_engine != "rank":
        # fused estimate-then-rerank (ops/bq_scan): stream each unique
        # probed list's codes once, XOR+popcount estimates, exact f32
        # re-rank of surviving rows from the same resident block
        from raft_tpu.ops.bq_scan import auto_query_bits, bq_list_major_scan

        qb = query_bits if query_bits else auto_query_bits(
            int(cfac.shape[2]))
        best_d, best_i = bq_list_major_scan(
            qf, qrot, centers_rot, codes, rnorm, cfac, errw, indices,
            data, data_norms, probes, filter_words, init_d, init_i,
            cold_planes, hot_slot_map, cold_slot_map,
            k=k, metric=metric, epsilon=epsilon, engine=scan_engine,
            query_bits=qb, interpret=jax.default_backend() != "tpu")
    else:
        def step(carry, rank):
            best_d, best_i = carry
            lists = probes[:, rank]                    # (q,)
            dist, row_ids = score_probe(
                lists, qrot, None if ip_metric else centers_rot, ip,
                c_norms, qnorm, codes, rnorm, cfac, indices, ip_metric,
                pad_val)
            if filter_words is not None:
                bits = test_filter(filter_words, row_ids)
                dist = jnp.where(bits & (row_ids >= 0), dist, pad_val)
            return merge_topk(best_d, best_i, dist, row_ids, k,
                              select_min), None

        init = (jnp.full((q, k), pad_val, jnp.float32) if init_d is None
                else jnp.full_like(init_d, pad_val),
                jnp.full((q, k), -1, jnp.int32) if init_i is None
                else jnp.full_like(init_i, -1))
        (best_d, best_i), _ = jax.lax.scan(step, init,
                                           jnp.arange(n_probes))

    if metric == DistanceType.L2SqrtExpanded:
        best_d = jnp.where(jnp.isfinite(best_d),
                           jnp.sqrt(jnp.maximum(best_d, 0.0)), best_d)
    if probe_counts is not None:
        return best_d, best_i, probe_counts
    return best_d, best_i


_search_impl = partial(jax.jit, static_argnames=(
    "n_probes", "k", "metric", "coarse_algo", "scan_engine",
    "epsilon", "query_bits"))(_search_impl_fn)


def _search_ragged_fn(queries, row_probes, centers, rotation, codes,
                      rnorm, cfac, errw, indices, data, data_norms,
                      filter_words, init_d=None, init_i=None,
                      probe_counts=None, n_valid=None, *, n_probes: int,
                      k: int, metric: DistanceType,
                      scan_engine: str = "xla", epsilon: float = 3.0,
                      query_bits: int = 0):
    """Packed ragged-batch BQ search body — the BQ member of the
    serving executor's ragged plan family (see
    :func:`raft_tpu.neighbors.ivf_flat._search_ragged_fn` for the
    packing contract). ``n_probes``/``k`` are the packed batch's
    CLASS CAPS; per-row budgets ride ``row_probes`` into the fused
    estimate-then-rerank engines' membership mask (the sentinel
    machinery the list-sharded BQ bodies already use for not-owned
    probes), and the running k-th-distance prune threshold is
    per-row, so a row's prune decisions — and its exact reranked
    output — are independent of what else shares the tile.
    Bit-identical per request to :func:`_search_impl_fn` on that
    request alone. Fused engines only: the rank-major estimate-only
    scan has no membership mask (and a codes-only index resolves to
    it, so codes-only BQ stays on the bucketed path)."""
    del n_valid
    expect(scan_engine in ("pallas", "xla"),
           "ragged BQ serving needs a fused membership-masked engine "
           f"(pallas|xla), got {scan_engine!r}")
    return _search_impl_fn(
        queries, centers, rotation, codes, rnorm, cfac, errw, indices,
        data, data_norms, filter_words, init_d, init_i, probe_counts,
        None, row_probes=row_probes, n_probes=n_probes, k=k,
        metric=metric, coarse_algo="exact", scan_engine=scan_engine,
        epsilon=epsilon, query_bits=query_bits)


def search(
    res: Optional[Resources],
    params: IvfBqSearchParams,
    index: IvfBqIndex,
    queries,
    k: int,
    sample_filter=None,
    query_tile: int = 4096,
) -> Tuple[jax.Array, jax.Array]:
    """ANN search over RaBitQ codes. With the fused engines (the
    default on an index carrying the rerank plane) the returned
    distances are **exact** — estimate-then-rerank happens inside one
    list-major pass, no separate :func:`raft_tpu.neighbors.refine`
    needed. On a codes-only index (or ``scan_engine="rank"``) the
    distances are unbiased estimates: over-fetch by
    :func:`overfetch_budget` and refine host-side."""
    ensure_resources(res)
    queries = jnp.asarray(queries)
    expect(queries.ndim == 2 and queries.shape[1] == index.dim,
           "queries must be (q, dim)")
    expect(index.max_list_size > 0, "index is empty — extend() it first")
    n_probes = min(params.n_probes, index.n_lists)
    expect(params.coarse_algo in ("exact", "approx"),
           f"coarse_algo must be 'exact' or 'approx', got "
           f"{params.coarse_algo!r}")
    expect(params.query_bits == 0 or 1 <= params.query_bits <= 8,
           "query_bits must be 0 (auto) or 1..8, got "
           f"{params.query_bits}")
    filter_words = resolve_filter_words(sample_filter)
    from raft_tpu.ops.bq_scan import resolve_bq_engine

    scan_engine = resolve_bq_engine(
        params.scan_engine, data=index.data, filter_words=filter_words,
        k=k, dim_ext=index.dim_ext, bits=index.bits,
        n_probes=n_probes)
    with tracing.range("raft_tpu.ivf_bq.search"):
        def run(qt, fw):
            return _search_impl(
                qt, index.centers, index.rotation, index.codes,
                index.rnorm, index.cfac, index.errw, index.indices,
                index.data, index.data_norms, fw,
                n_probes=n_probes, k=k, metric=index.metric,
                coarse_algo=params.coarse_algo, scan_engine=scan_engine,
                epsilon=params.epsilon, query_bits=params.query_bits)

        return tile_queries(run, queries, filter_words, query_tile)


def save(index: IvfBqIndex, fh_or_path) -> None:
    fh, own = open_maybe_path(fh_or_path, "wb")
    try:
        serialize_scalar(fh, _SERIALIZATION_VERSION, np.int32)
        serialize_scalar(fh, int(index.metric), np.int32)
        serialize_scalar(fh, index.bits, np.int32)
        serialize_scalar(fh, int(index.data is not None), np.int32)
        serialize_array(fh, index.centers)
        serialize_array(fh, index.rotation)
        serialize_array(fh, index.codes)
        serialize_array(fh, index.rnorm)
        serialize_array(fh, index.cfac)
        serialize_array(fh, index.errw)
        serialize_array(fh, index.indices)
        serialize_array(fh, index.list_sizes)
        if index.data is not None:
            serialize_array(fh, index.data)
    finally:
        if own:
            fh.close()


def load(res: Optional[Resources], fh_or_path) -> IvfBqIndex:
    res = ensure_resources(res)
    fh, own = open_maybe_path(fh_or_path, "rb")
    try:
        check_version(deserialize_scalar(fh), _SERIALIZATION_VERSION,
                      "ivf_bq")
        metric = DistanceType(int(deserialize_scalar(fh)))
        int(deserialize_scalar(fh))  # bits — recorded; shape-derivable
        has_data = bool(deserialize_scalar(fh))
        arrays = [res.put(deserialize_array(fh)) for _ in range(8)]
        data = res.put(deserialize_array(fh)) if has_data else None
    finally:
        if own:
            fh.close()
    (centers, rotation, codes, rnorm, cfac, errw, indices,
     sizes) = map(jnp.asarray, arrays)
    data = jnp.asarray(data) if has_data else None
    return IvfBqIndex(
        centers=centers, rotation=rotation, codes=codes, rnorm=rnorm,
        cfac=cfac, errw=errw, indices=indices, list_sizes=sizes,
        metric=metric, data=data,
        data_norms=_vector_norms(data, indices) if has_data else None,
    )
