"""IVF-BQ — inverted file with 1-bit (binary) quantization, a TPU-first
index with no reference analog (closest: ``ivf_pq`` with its smallest
codebooks; the quantizer follows the RaBitQ line of work — sign codes
under a random rotation with per-vector scalar correction, arXiv
2405.12497 / the IVF-RaBitQ build in PAPERS.md).

Why this exists on TPU: PQ scoring needs per-code LUT lookups — gathers
(scalar-core serialized) or one-hot/masked-sum workarounds (J-fold FLOP
inflation). A sign code has no lookup at all:

    x ≈ c + Rᵀ(a · s),   s = sign(R(x − c)) ∈ {−1, +1}^D

    ||q − x||² ≈ ||q − c||² − 2·a·(q̃ · s) + ||r||²,   q̃ = R(q − c)

so scoring a whole probed list is ONE MXU GEMM of the rotated query
against the ±1 code matrix (exact in bf16), plus precomputed per-vector
scalars (per-level scales and the true residual norm ``||r||²``).
``bits`` stacks residual sign-quantization levels — each level encodes
what the previous left over and adds D bits + one scale + one GEMM
term. Measured on 128-dim clustered data with 4x over-fetch + exact
refine: recall@10 0.81 at 1 bit (16 B codes), 0.96 at 2 bits (32 B),
0.99 at 3 bits. Codes unpack to ±1 in VMEM right after the HBM gather;
pair with :func:`raft_tpu.neighbors.refine` the way the reference
pairs IVF-PQ with refinement.

Supported metrics: L2Expanded / L2SqrtExpanded / InnerProduct.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.cluster import kmeans_balanced
from raft_tpu.cluster.kmeans_balanced import KMeansBalancedParams
from raft_tpu.core import interruptible, tracing
from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.core.serialize import (
    check_version,
    deserialize_array,
    deserialize_scalar,
    open_maybe_path,
    serialize_array,
    serialize_scalar,
)
from raft_tpu.core.validation import expect
from raft_tpu.distance.types import DistanceType, is_min_close
from raft_tpu.matrix.select_k import merge_topk
from raft_tpu.neighbors._batching import coarse_select, tile_queries
from raft_tpu.neighbors._streaming import label_pass, sample_trainset
from raft_tpu.neighbors._packing import (
    pack_padded_lists,
    padded_extent,
    streaming_ranks,
)
from raft_tpu.neighbors.ann_types import IndexParams, SearchParams
from raft_tpu.neighbors.filters import resolve_filter_words, test_filter

_SERIALIZATION_VERSION = 2  # v2: multi-level (bits > 1) residual codes

# entangled into the pinned rotation stream; bumping it redraws every
# rotation (and re-derives the estimator-quality expectations)
_ROTATION_STREAM = 0


def _pinned_rotation(seed: int, dim_ext: int, dim: int) -> jax.Array:
    """Random orthogonal rotation dim → dim_ext from a **pinned**
    generator: numpy's PCG64 stream is stable across numpy versions,
    where ``jax.random`` draws shift across jax releases (threefry
    partitionable default, key layout). The estimator-quality contracts
    in ``tests/test_ivf_bq.py`` are calibrated against this exact
    stream — a jax upgrade must not silently redraw the rotation every
    saved BQ index and recall bound was derived under (the ROADMAP's
    "BQ estimator quality on jax 0.4.x" item)."""
    rng = np.random.default_rng(
        np.random.SeedSequence([seed, _ROTATION_STREAM]))
    g = rng.standard_normal((max(dim_ext, dim), dim_ext))
    q, r = np.linalg.qr(g)          # orthonormal columns
    # LAPACK backends disagree on QR column signs — normalize so the
    # rotation (not just the stream) is backend-invariant
    d = np.sign(np.diag(r))
    d[d == 0] = 1.0
    q = q * d
    return jnp.asarray(q[:dim, :].T, jnp.float32)  # (dim_ext, dim)


@dataclasses.dataclass(frozen=True)
class IvfBqIndexParams(IndexParams):
    n_lists: int = 1024
    kmeans_n_iters: int = 20
    kmeans_trainset_fraction: float = 0.5
    # residual sign-quantization levels (bits/dim, 1..4): level l
    # encodes the residual left by levels < l. Each level adds D bits
    # and one f32 scale per vector and one more GEMM term to the score;
    # 2 bits roughly halves the estimator noise of 1 bit.
    bits: int = 1


@dataclasses.dataclass(frozen=True)
class IvfBqSearchParams(SearchParams):
    n_probes: int = 20
    # "approx" routes cluster selection through the TPU's native
    # approximate top-k unit (same knob as the flat/PQ params)
    coarse_algo: str = "exact"


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass(frozen=True)
class IvfBqIndex:
    """Binary-quantized IVF index."""

    centers: jax.Array        # (n_lists, dim) f32
    rotation: jax.Array       # (dim_ext, dim) f32 random orthogonal
    codes: jax.Array          # (n_lists, max_list_size, bits·dim_ext//8) u8
    scales: jax.Array         # (n_lists, max_list_size, bits) f32
    rnorm2: jax.Array         # (n_lists, max_list_size) f32 — ||r||²
    indices: jax.Array        # (n_lists, max_list_size) int32, -1 pad
    list_sizes: jax.Array     # (n_lists,) int32
    metric: DistanceType

    def tree_flatten(self):
        return (self.centers, self.rotation, self.codes, self.scales,
                self.rnorm2, self.indices, self.list_sizes), (self.metric,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, metric=aux[0])

    @property
    def n_lists(self) -> int:
        return self.centers.shape[0]

    @property
    def dim(self) -> int:
        return self.centers.shape[1]

    @property
    def dim_ext(self) -> int:
        return self.rotation.shape[0]

    @property
    def bits(self) -> int:
        return self.scales.shape[2]

    @property
    def max_list_size(self) -> int:
        return self.codes.shape[1]

    @property
    def size(self) -> int:
        return int(self.list_sizes.sum())


def _pack_bits(signs):
    """(..., dim_ext) bool (sign >= 0) → (..., dim_ext // 8) uint8,
    bit b of byte k = component 8k + b."""
    b = signs.reshape(*signs.shape[:-1], -1, 8).astype(jnp.uint8)
    weights = (1 << jnp.arange(8, dtype=jnp.uint8))
    return jnp.sum(b * weights, axis=-1, dtype=jnp.uint8)


def _unpack_pm1(bytes_, dtype=jnp.bfloat16):
    """(..., n_bytes) uint8 → (..., 8·n_bytes) ±1 in ``dtype``."""
    bits = (bytes_[..., None] >> jnp.arange(8, dtype=jnp.uint8)) & 1
    pm1 = bits.astype(dtype) * 2 - 1
    return pm1.reshape(*bytes_.shape[:-1], bytes_.shape[-1] * 8)


def _encode(rot_residuals, bits: int = 1):
    """residual r → (packed sign bits per level, scales, ||r||²).

    Level 0 sign-encodes r with the least-squares scale ⟨r,s⟩/D; each
    further level encodes what the previous levels left over (residual
    sign quantization). A final global rescale γ = ||r||² / ⟨r, r̂⟩ is
    folded into every level's scale so that ⟨r, Σ a_l s_l⟩ = ||r||²
    EXACTLY — the collinearity correction of the RaBitQ estimator,
    which makes the distance estimate of a vector to itself 0 (with a
    single level this reduces to a = ||r||²/⟨r, s⟩).

    Returns codes (..., bits·D/8) u8, scales (..., bits) f32, rn2."""
    d = rot_residuals.shape[-1]
    rn2 = jnp.sum(jnp.square(rot_residuals), axis=-1)
    level_codes, level_scales = [], []
    resid = rot_residuals
    recon = jnp.zeros_like(rot_residuals)
    for _ in range(bits):
        signs = resid >= 0
        s = jnp.where(signs, 1.0, -1.0)
        a = jnp.sum(resid * s, axis=-1) / d           # LS scale per level
        level_codes.append(_pack_bits(signs))
        level_scales.append(a)
        recon = recon + a[..., None] * s
        resid = resid - a[..., None] * s
    gamma = rn2 / jnp.maximum(
        jnp.sum(rot_residuals * recon, axis=-1), 1e-20)
    codes = jnp.concatenate(level_codes, axis=-1)
    scales = jnp.stack(level_scales, axis=-1) * gamma[..., None]
    return codes, scales.astype(jnp.float32), rn2.astype(jnp.float32)


def _pack_lists(codes, scales, rn2, ids, labels, n_lists, max_size,
                sizes=None):
    """Scatter rows into the padded [n_lists, max_list_size] layout
    (the shared sort-and-rank packing)."""
    (fc, fa, fr, fi), sizes = pack_padded_lists(
        labels, n_lists, max_size,
        [(codes, 0), (scales, 0.0), (rn2, 0.0), (ids, -1)], sizes=sizes)
    return fc, fa, fr, fi, sizes


def build(
    res: Optional[Resources],
    params: IvfBqIndexParams,
    dataset,
) -> IvfBqIndex:
    """Train coarse centers + random rotation, sign-encode the dataset."""
    res = ensure_resources(res)
    dataset = jnp.asarray(dataset)
    expect(dataset.ndim == 2, "dataset must be (n, d)")
    n, dim = dataset.shape
    expect(params.n_lists <= n, "n_lists > n_rows")
    expect(params.metric in (DistanceType.L2Expanded,
                             DistanceType.L2SqrtExpanded,
                             DistanceType.InnerProduct),
           f"ivf_bq supports L2/L2Sqrt/InnerProduct, got {params.metric!r}")
    expect(1 <= params.bits <= 4, "bits must be in [1, 4]")
    dim_ext = -(-dim // 8) * 8

    with tracing.range("raft_tpu.ivf_bq.build"):
        frac = min(max(params.kmeans_trainset_fraction, 0.0), 1.0)
        n_train = min(n, max(params.n_lists * 2, int(n * frac)))
        stride = max(1, n // n_train)
        trainset = dataset[::stride][:n_train].astype(jnp.float32)
        km = KMeansBalancedParams(
            n_iters=params.kmeans_n_iters,
            metric=(DistanceType.InnerProduct
                    if params.metric == DistanceType.InnerProduct
                    else DistanceType.L2Expanded),
            seed=res.seed,
        )
        centers = kmeans_balanced.fit(res, km, trainset, params.n_lists)
        # the random rotation is what makes sign codes informative —
        # always random, never identity; pinned so recall contracts
        # survive jax upgrades
        rotation = _pinned_rotation(res.seed, dim_ext, dim)

        empty = IvfBqIndex(
            centers=centers, rotation=rotation,
            codes=jnp.zeros((params.n_lists, 0,
                             params.bits * dim_ext // 8), jnp.uint8),
            scales=jnp.zeros((params.n_lists, 0, params.bits),
                             jnp.float32),
            rnorm2=jnp.zeros((params.n_lists, 0), jnp.float32),
            indices=jnp.full((params.n_lists, 0), -1, jnp.int32),
            list_sizes=jnp.zeros((params.n_lists,), jnp.int32),
            metric=DistanceType(params.metric),
        )
        if not params.add_data_on_build:
            return empty
        return extend(res, empty, dataset, jnp.arange(n, dtype=jnp.int32))


def build_streaming(
    res: Optional[Resources],
    params: IvfBqIndexParams,
    source,
    chunk_rows: int = 1 << 20,
    train_rows: int = 1 << 18,
) -> IvfBqIndex:
    """Streamed BQ build over a :class:`raft_tpu.io.BinDataset` — the
    dataset never fully materializes host-side (same three passes as
    the flat/PQ streaming builds: trainset sample → label count →
    encode + scatter into donated buffers). Only the sign codes and
    per-vector scalars live in HBM, so datasets many times HBM fit."""
    res = ensure_resources(res)
    n, dim = source.n_rows, source.dim
    expect(params.n_lists <= n, "n_lists > n_rows")

    with tracing.range("raft_tpu.ivf_bq.build_streaming"):
        # -- pass 1: trainset sample → centers + rotation via build()
        train_rows = max(params.n_lists * 2, min(train_rows, n))
        trainset = sample_trainset(source, train_rows, chunk_rows)
        empty = build(res, dataclasses.replace(params,
                                               add_data_on_build=False),
                      trainset)

        km = KMeansBalancedParams(
            metric=(DistanceType.InnerProduct
                    if params.metric == DistanceType.InnerProduct
                    else DistanceType.L2Expanded))

        # -- pass 2: labels + sizes
        labels_np, sizes_np = label_pass(res, km, empty.centers, source,
                                         chunk_rows, params.n_lists)
        max_size = padded_extent(sizes_np)

        # -- pass 3: encode + scatter with donated buffers
        @partial(jax.jit, donate_argnums=(0, 1, 2, 3))
        def encode_scatter(codes_buf, scales_buf, rn2_buf, idx_buf,
                           rows, labels, ids, ranks):
            resid = rows - empty.centers[labels]
            rot = resid @ empty.rotation.T
            codes, scales, rn2 = _encode(rot, params.bits)
            return (codes_buf.at[labels, ranks].set(codes),
                    scales_buf.at[labels, ranks].set(scales),
                    rn2_buf.at[labels, ranks].set(rn2),
                    idx_buf.at[labels, ranks].set(ids))

        dim_ext = empty.dim_ext
        codes_buf = jnp.zeros(
            (params.n_lists, max_size, params.bits * dim_ext // 8),
            jnp.uint8)
        scales_buf = jnp.zeros((params.n_lists, max_size, params.bits),
                               jnp.float32)
        rn2_buf = jnp.zeros((params.n_lists, max_size), jnp.float32)
        idx_buf = jnp.full((params.n_lists, max_size), -1, jnp.int32)
        fill = np.zeros((params.n_lists,), np.int64)
        for first, chunk in source.iter_chunks(chunk_rows):
            interruptible.yield_()  # cancellation point per chunk
            m = chunk.shape[0]
            lab = labels_np[first : first + m]
            ranks = streaming_ranks(lab, fill, params.n_lists)
            codes_buf, scales_buf, rn2_buf, idx_buf = encode_scatter(
                codes_buf, scales_buf, rn2_buf, idx_buf,
                jnp.asarray(chunk, jnp.float32),
                jnp.asarray(lab),
                jnp.asarray(first + np.arange(m, dtype=np.int32)),
                jnp.asarray(ranks),
            )

        return IvfBqIndex(
            centers=empty.centers,
            rotation=empty.rotation,
            codes=codes_buf,
            scales=scales_buf,
            rnorm2=rn2_buf,
            indices=idx_buf,
            list_sizes=jnp.asarray(sizes_np, jnp.int32),
            metric=DistanceType(params.metric),
        )


def extend(
    res: Optional[Resources],
    index: IvfBqIndex,
    new_vectors,
    new_indices=None,
) -> IvfBqIndex:
    """Encode + add vectors (functional rebuild of the padded lists)."""
    res = ensure_resources(res)
    new_vectors = jnp.asarray(new_vectors)
    expect(new_vectors.ndim == 2 and new_vectors.shape[1] == index.dim,
           "new_vectors must be (n, dim)")
    n_new = new_vectors.shape[0]
    if new_indices is None:
        start = index.size
        new_indices = jnp.arange(start, start + n_new, dtype=jnp.int32)
    else:
        new_indices = jnp.asarray(new_indices, jnp.int32)

    with tracing.range("raft_tpu.ivf_bq.extend"):
        km = KMeansBalancedParams(
            metric=(DistanceType.InnerProduct
                    if index.metric == DistanceType.InnerProduct
                    else DistanceType.L2Expanded))
        labels = kmeans_balanced.predict(res, km, index.centers,
                                         new_vectors.astype(jnp.float32))
        resid = new_vectors.astype(jnp.float32) - index.centers[labels]
        rot = resid @ index.rotation.T                   # (n, dim_ext)
        codes, scales, rn2 = _encode(rot, index.bits)

        if index.max_list_size > 0:
            keep = index.indices.reshape(-1) >= 0
            old_labels = jnp.repeat(
                jnp.arange(index.n_lists, dtype=jnp.int32),
                index.max_list_size)
            nb = index.codes.shape[2]
            all_codes = jnp.concatenate(
                [index.codes.reshape(-1, nb)[keep], codes])
            all_scales = jnp.concatenate(
                [index.scales.reshape(-1, index.bits)[keep], scales])
            all_rn2 = jnp.concatenate(
                [index.rnorm2.reshape(-1)[keep], rn2])
            all_ids = jnp.concatenate(
                [index.indices.reshape(-1)[keep], new_indices])
            all_labels = jnp.concatenate([old_labels[keep], labels])
        else:
            all_codes, all_scales, all_rn2 = codes, scales, rn2
            all_ids, all_labels = new_indices, labels

        sizes = jax.ops.segment_sum(
            jnp.ones((all_codes.shape[0],), jnp.int32), all_labels,
            num_segments=index.n_lists)
        max_size = padded_extent(sizes)
        c, a, r, i, s = _pack_lists(all_codes, all_scales, all_rn2,
                                    all_ids, all_labels, index.n_lists,
                                    max_size, sizes=sizes)
        return dataclasses.replace(index, codes=c, scales=a, rnorm2=r,
                                   indices=i, list_sizes=s)


def score_probe(lists, qrot, centers_rot, ip, cn, qnorm, codes, scales,
                rn2, indices, ip_metric: bool, pad_val, valid=None):
    """THE per-probe scoring step, shared by the single-chip and
    distributed searches: gather one probed list per query, unpack the
    sign codes, one MXU GEMM cross term, estimator assembly. Rows that
    are padding (or, distributed, probes this shard does not own via
    ``valid``) score ``pad_val``. Returns ``(dist (q, m), row_ids)``.

    Inputs are the probe-invariant precomputations: ``qrot = R q``,
    ``centers_rot = R c`` (L2 only), the coarse-stage ``ip = q·c``
    matrix and norms (L2 only).
    """
    q = qrot.shape[0]
    qidx = jnp.arange(q)
    byts = jnp.take(codes, lists, axis=0)          # (q, m, bits·D/8) u8
    a = jnp.take(scales, lists, axis=0)            # (q, m, bits)
    bits = a.shape[-1]
    pm1 = _unpack_pm1(byts)                        # (q, m, bits·D) bf16
    m = pm1.shape[1]
    pm1 = pm1.reshape(q, m, bits, -1)              # (q, m, L, D)
    row_ids = jnp.take(indices, lists, axis=0)     # (q, m)
    if ip_metric:
        # similarity (select_min is False for IP — no negation)
        crosses = jnp.einsum("qd,qmld->qml", qrot.astype(jnp.bfloat16),
                             pm1, preferred_element_type=jnp.float32)
        base = ip[qidx, lists]                     # q·c from coarse
        dist = base[:, None] + jnp.sum(a * crosses, axis=-1)
    else:
        qsub = qrot - centers_rot[lists]           # (q, dim_ext)
        crosses = jnp.einsum("qd,qmld->qml", qsub.astype(jnp.bfloat16),
                             pm1, preferred_element_type=jnp.float32)
        r2 = jnp.take(rn2, lists, axis=0)
        # ||q−c||² from the coarse-stage terms (R is an isometry, so
        # this equals Σ qsub² without re-reducing per probe)
        qc2 = qnorm + cn[lists] - 2.0 * ip[qidx, lists]
        dist = (jnp.maximum(qc2, 0.0)[:, None]
                - 2.0 * jnp.sum(a * crosses, axis=-1) + r2)
    ok = row_ids >= 0
    if valid is not None:
        ok = ok & valid[:, None]
    return jnp.where(ok, dist, pad_val), row_ids


def _search_impl_fn(queries, centers, rotation, codes, scales, rn2, indices,
                    filter_words, init_d=None, init_i=None,
                    probe_counts=None, n_valid=None, *, n_probes: int,
                    k: int, metric: DistanceType, coarse_algo: str = "exact"):
    """Sign-code probe scan. ``init_d``/``init_i`` optionally provide
    the (q, k) running-state storage (values are reset here); the
    serving path donates them so the scan state reuses one HBM
    allocation. ``probe_counts`` optionally provides the donated
    (n_lists,) int32 probe-frequency plane (graftgauge): selected
    probe ids scatter-add into it (rows past ``n_valid`` masked) and
    the updated plane returns as a third output."""
    q, dim = queries.shape
    select_min = is_min_close(metric)
    qf = queries.astype(jnp.float32)
    ip_metric = metric == DistanceType.InnerProduct

    # coarse cluster selection (shared shape with ivf_flat/pq)
    ip = jax.lax.dot_general(
        qf, centers, (((1,), (1,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    )
    if ip_metric:
        score = ip
        c_norms = None
        qnorm = None
    else:
        c_norms = jnp.sum(jnp.square(centers), axis=1)
        score = -(c_norms[None, :] - 2.0 * ip)
        qnorm = jnp.sum(jnp.square(qf), axis=1)
    probes = coarse_select(score, n_probes, coarse_algo)
    if probe_counts is not None:
        from raft_tpu.ops.ivf_scan import probe_histogram

        probe_counts = probe_histogram(probes, probe_counts, n_valid)
    pad_val = jnp.inf if select_min else -jnp.inf

    # probe-invariant precomputation: the rotated query never changes,
    # and q̃ = R(q−c) = Rq − (Rc) needs only a rotated-centers table
    qrot = qf @ rotation.T                             # (q, dim_ext)
    centers_rot = None if ip_metric else centers @ rotation.T

    def step(carry, rank):
        best_d, best_i = carry
        lists = probes[:, rank]                        # (q,)
        dist, row_ids = score_probe(
            lists, qrot, centers_rot, ip, c_norms, qnorm, codes, scales,
            rn2, indices, ip_metric, pad_val)
        if filter_words is not None:
            bits = test_filter(filter_words, row_ids)
            dist = jnp.where(bits & (row_ids >= 0), dist, pad_val)
        return merge_topk(best_d, best_i, dist, row_ids, k, select_min), None

    init = (jnp.full((q, k), pad_val, jnp.float32) if init_d is None
            else jnp.full_like(init_d, pad_val),
            jnp.full((q, k), -1, jnp.int32) if init_i is None
            else jnp.full_like(init_i, -1))
    (best_d, best_i), _ = jax.lax.scan(step, init, jnp.arange(n_probes))

    if metric == DistanceType.L2SqrtExpanded:
        best_d = jnp.where(jnp.isfinite(best_d),
                           jnp.sqrt(jnp.maximum(best_d, 0.0)), best_d)
    if probe_counts is not None:
        return best_d, best_i, probe_counts
    return best_d, best_i


_search_impl = partial(jax.jit, static_argnames=(
    "n_probes", "k", "metric", "coarse_algo"))(_search_impl_fn)


def search(
    res: Optional[Resources],
    params: IvfBqSearchParams,
    index: IvfBqIndex,
    queries,
    k: int,
    sample_filter=None,
    query_tile: int = 4096,
) -> Tuple[jax.Array, jax.Array]:
    """ANN search over sign codes — estimated distances; re-rank with
    :func:`raft_tpu.neighbors.refine` (fetch 3-5x k here) for high
    recall, as with IVF-PQ."""
    ensure_resources(res)
    queries = jnp.asarray(queries)
    expect(queries.ndim == 2 and queries.shape[1] == index.dim,
           "queries must be (q, dim)")
    expect(index.max_list_size > 0, "index is empty — extend() it first")
    n_probes = min(params.n_probes, index.n_lists)
    expect(params.coarse_algo in ("exact", "approx"),
           f"coarse_algo must be 'exact' or 'approx', got "
           f"{params.coarse_algo!r}")
    filter_words = resolve_filter_words(sample_filter)
    with tracing.range("raft_tpu.ivf_bq.search"):
        def run(qt, fw):
            return _search_impl(
                qt, index.centers, index.rotation, index.codes,
                index.scales, index.rnorm2, index.indices, fw,
                n_probes=n_probes, k=k, metric=index.metric,
                coarse_algo=params.coarse_algo)

        return tile_queries(run, queries, filter_words, query_tile)


def save(index: IvfBqIndex, fh_or_path) -> None:
    fh, own = open_maybe_path(fh_or_path, "wb")
    try:
        serialize_scalar(fh, _SERIALIZATION_VERSION, np.int32)
        serialize_scalar(fh, int(index.metric), np.int32)
        serialize_scalar(fh, index.bits, np.int32)
        serialize_array(fh, index.centers)
        serialize_array(fh, index.rotation)
        serialize_array(fh, index.codes)
        serialize_array(fh, index.scales)
        serialize_array(fh, index.rnorm2)
        serialize_array(fh, index.indices)
        serialize_array(fh, index.list_sizes)
    finally:
        if own:
            fh.close()


def load(res: Optional[Resources], fh_or_path) -> IvfBqIndex:
    res = ensure_resources(res)
    fh, own = open_maybe_path(fh_or_path, "rb")
    try:
        check_version(deserialize_scalar(fh), _SERIALIZATION_VERSION,
                      "ivf_bq")
        metric = DistanceType(int(deserialize_scalar(fh)))
        int(deserialize_scalar(fh))  # bits — recorded; shape-derivable
        arrays = [res.put(deserialize_array(fh)) for _ in range(7)]
    finally:
        if own:
            fh.close()
    centers, rotation, codes, scales, rn2, indices, sizes = map(
        jnp.asarray, arrays)
    return IvfBqIndex(
        centers=centers, rotation=rotation, codes=codes, scales=scales,
        rnorm2=rn2, indices=indices, list_sizes=sizes, metric=metric,
    )
