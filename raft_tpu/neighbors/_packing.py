"""Shared padded-list packing — THE sort-and-rank scatter used by every
IVF index type (role of the reference's per-list packing,
``detail/ivf_flat_build.cuh:161`` extend; dense re-design per
SURVEY.md §7.4: ragged ``ivf::list`` → one padded tensor).

Stable-sort rows by label, compute each row's rank within its list,
scatter into ``label * max_size + rank`` slots.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def streaming_ranks(labels_chunk, fill, n_lists: int):
    """Host-side within-list rank assignment for the streaming builds:
    given a chunk's list labels and the running per-list fill counts
    (np.int64, updated IN PLACE), return each row's destination rank
    within its padded list."""
    lab = np.asarray(labels_chunk)
    m = lab.shape[0]
    order = np.argsort(lab, kind="stable")
    sl = lab[order]
    first_pos = np.searchsorted(sl, np.arange(n_lists))
    rank_sorted = np.arange(m) - first_pos[sl] + fill[sl]
    ranks = np.empty((m,), np.int32)
    ranks[order] = rank_sorted.astype(np.int32)
    np.add.at(fill, lab, 1)
    return ranks


def padded_extent(sizes) -> int:
    """Shared max-list-size rounding: the largest list, rounded up to
    the sublane multiple (8). One host sync per build/extend."""
    return max(8, -(-int(jnp.max(jnp.asarray(sizes))) // 8) * 8)


def pack_padded_lists(
    labels,
    n_lists: int,
    max_size: int,
    payloads: Sequence[Tuple[object, object]],
    sizes=None,
):
    """Scatter per-row payloads into padded ``[n_lists, max_size]``
    layouts.

    Args:
      labels: (n,) int list assignment per row.
      payloads: sequence of ``(array, fill)`` — each array is (n, ...)
        and lands in a ``(n_lists, max_size, ...)`` output initialized
        to ``fill``.
      sizes: optional precomputed per-list populations (callers usually
        have them already — they sized ``max_size`` from them); when
        omitted they are recomputed here.

    Returns ``([packed...], sizes)`` with sizes (n_lists,) int32.
    """
    labels = jnp.asarray(labels, jnp.int32)
    n = labels.shape[0]
    order = jnp.argsort(labels, stable=True)
    sorted_labels = labels[order]
    first = jnp.searchsorted(sorted_labels, jnp.arange(n_lists),
                             side="left")
    rank = jnp.arange(n) - first[sorted_labels]
    slot = sorted_labels * max_size + rank

    outs = []
    for arr, fill in payloads:
        arr = jnp.asarray(arr)
        flat = jnp.full((n_lists * max_size,) + arr.shape[1:], fill,
                        arr.dtype)
        flat = flat.at[slot].set(arr[order])
        outs.append(flat.reshape((n_lists, max_size) + arr.shape[1:]))
    if sizes is None:
        sizes = jax.ops.segment_sum(jnp.ones((n,), jnp.int32), labels,
                                    num_segments=n_lists)
    return outs, jnp.asarray(sizes, jnp.int32)
