"""grafttier — billion-scale tiered IVF storage (PR 14; graftcast
extended it across the compressed families, PR 18).

Every index family so far is fully HBM-resident, which caps corpus
size at device memory — far below the SIFT-1B north star ("millions
of users, corpus ≫ HBM"). :class:`TieredIvf` splits an
:class:`~raft_tpu.neighbors.ivf_flat.IvfFlatIndex`'s lists into an
HBM-resident **hot tier** (fixed slot capacity, sized against
graftledger's live headroom via :func:`resolve_hot_slots`) and a
host-memory **cold tier** (committed via :func:`host_put` — honest
fallback to device placement on backends without memory kinds, i.e.
the CPU tier-1 environment), and serves the probed-list union in one
pass through :mod:`raft_tpu.ops.tier_scan`: hot blocks ride the
existing scalar-prefetched BlockSpec pipeline, cold blocks stream
through a double-buffered manual-DMA pipeline from the host operand.

graftcast generalizes the split to the compressed families — the
actual billion-vector story: :class:`TieredIvfPq` tiers the PQ codes
plane, :class:`TieredIvfBq` tiers the five-plane RaBitQ record
(codes/scales/error/rerank vectors move as ONE unit per list so an
estimate and its re-rank can never split across tiers). Every
container declares its hot/cold plane pairs in ``_PLANE_PAIRS`` and
shares one placement executor (:func:`apply_plan`), one snapshot
discipline and one layout truth through :class:`_TieredPlanes`.

The split moves ONLY the heavy per-row planes: centers, norms, ids,
slot maps and list sizes (~2% of the bytes at serving dims) stay
resident, so coarse selection, membership masking, filters and
graftgauge's probe accounting are untouched — and search results are
**bit-identical** to the all-HBM index per engine.

**Shape stability is the serving contract.** The hot tier has a FIXED
slot count decided once at construction; a placement epoch
(:mod:`raft_tpu.serving.placement`) only PERMUTES which lists occupy
those slots, via :func:`apply_plan`'s fixed-width donated block swaps
(pad entries carry out-of-range slots — gathers clamp, scatters
``mode="drop"`` — so every epoch runs the same compiled programs).
Shapes never change ⇒ the ``SearchExecutor``'s AOT cache keys never
change ⇒ steady-state serving stays at zero backend compiles across
re-placement epochs (pinned in ``tests/test_tiered.py``). The
container is deliberately MUTABLE (unlike the frozen index
dataclasses): the arrays are re-placed in place across epochs while
``id(index)`` — the coalesce key's and probe plane's identity — stays
stable; the container itself never flows through jit, only its
arrays do.
"""

from __future__ import annotations

import dataclasses
import functools
import threading
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core import tracing
from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.core.validation import expect
from raft_tpu.distance.types import DistanceType
from raft_tpu.neighbors._batching import coarse_select, tile_queries
from raft_tpu.neighbors.ann_types import SearchParams
from raft_tpu.neighbors.filters import resolve_filter_words
from raft_tpu.neighbors.ivf_flat import IvfFlatIndex


@dataclasses.dataclass(frozen=True)
class TieredSearchParams(SearchParams):
    """Search params of the tiered index. ``scan_engine`` selects the
    tiered engine pair (:mod:`raft_tpu.ops.tier_scan`): ``"auto"`` is
    the dual-source Pallas kernel on TPU and the tiered XLA scan
    elsewhere; ``"pallas"`` degrades per ``resolve_tier_engine``."""

    n_probes: int = 20
    coarse_algo: str = "exact"   # "exact" | "approx"
    scan_engine: str = "auto"    # "auto" | "pallas" | "xla"


class _TieredPlanes:
    """Shared tiered-container machinery (graftcast). Every tiered
    family declares its hot/cold plane name pairs in ``_PLANE_PAIRS``
    and inherits the geometry, byte accounting, atomic generation
    snapshot and layout truth from here — ONE implementation, so the
    flat/PQ/BQ containers cannot drift on the placement contract.

    ``generation`` is the placement-generation counter
    (:func:`apply_plan` bumps it under the swap lock): the
    prefetcher stamps staged blocks with it, so a block staged
    against an older placement is detectably stale, and the ragged
    packing contract is generation-STABLE — a packed tile's plan
    carries no placement arrays in its cache key, every dispatch
    re-snapshots the planes, so epochs permute placement without
    ever invalidating (or even touching) the one ragged
    executable."""

    _PLANE_PAIRS = ()          # ((hot_name, cold_name), ...)

    @property
    def n_lists(self) -> int:
        return self.centers.shape[0]

    @property
    def dim(self) -> int:
        return self.centers.shape[1]

    @property
    def max_list_size(self) -> int:
        return getattr(self, self._PLANE_PAIRS[0][0]).shape[1]

    @property
    def n_hot(self) -> int:
        return getattr(self, self._PLANE_PAIRS[0][0]).shape[0]

    @property
    def n_cold(self) -> int:
        return getattr(self, self._PLANE_PAIRS[0][1]).shape[0]

    @property
    def block_bytes(self) -> int:
        """Bytes of ONE list's tiered planes (summed across plane
        pairs) — the unit every placement swap moves twice (one
        promotion + one demotion) and every prefetch stages once."""
        total = 0
        for hot_name, _ in self._PLANE_PAIRS:
            a = getattr(self, hot_name)
            total += int(np.prod(a.shape[1:])) * a.dtype.itemsize
        return total

    @property
    def hot_bytes(self) -> int:
        return self.n_hot * self.block_bytes

    @property
    def cold_bytes(self) -> int:
        return self.n_cold * self.block_bytes

    def tier_planes(self) -> tuple:
        """Atomic snapshot of the placement generation across EVERY
        tiered plane pair: ``(hot_planes, cold_planes, hot_slot_map,
        cold_slot_map, generation)`` read under the swap lock — the
        generic sibling of :meth:`TieredIvf.tier_arrays`
        (:func:`apply_plan` replaces all of them, and bumps the
        generation, under the same lock)."""
        with self._swap_lock:
            return (
                tuple(getattr(self, h) for h, _ in self._PLANE_PAIRS),
                tuple(getattr(self, c) for _, c in self._PLANE_PAIRS),
                self.hot_slot_map, self.cold_slot_map,
                self.generation)

    def layout(self) -> dict:
        """The host-side placement truth (the ``/tier.json`` body's
        core): which lists are hot, which cold, and the byte split.
        Read under the swap lock — a concurrent epoch must never show
        a scrape new hot mirrors against old cold mirrors (a list in
        both tiers, or neither)."""
        with self._swap_lock:
            return {
                "n_lists": self.n_lists,
                "n_hot": self.n_hot,
                "n_cold": self.n_cold,
                "hot_lists": [int(x) for x in self.hot_lists],
                "cold_lists": [int(x) for x in self.cold_lists],
                "hot_bytes": self.hot_bytes,
                "cold_bytes": self.cold_bytes,
                "block_bytes": self.block_bytes,
                "host_resident": self.host_resident,
                "generation": self.generation,
            }


@dataclasses.dataclass
class TieredIvf(_TieredPlanes):
    """Hot/cold tiered IVF container (MUTABLE — see module docstring;
    placement epochs re-place the arrays in place, shapes fixed)."""

    centers: jax.Array         # (n_lists, d) f32 — HBM
    center_norms: jax.Array    # (n_lists,) f32
    data_norms: jax.Array      # (n_lists, max_list_size) f32, full plane
    indices: jax.Array         # (n_lists, max_list_size) int32, full plane
    list_sizes: jax.Array      # (n_lists,) int32
    hot_data: jax.Array        # (n_hot, max_list_size, d) f32 — HBM
    cold_data: jax.Array       # (n_cold, max_list_size, d) f32 — host
    hot_slot_map: jax.Array    # (n_lists,) int32, hot slot or -1  # guarded-by: _swap_lock
    cold_slot_map: jax.Array   # (n_lists,) int32, cold slot or -1  # guarded-by: _swap_lock
    hot_lists: np.ndarray      # (n_hot,) list id occupying each hot slot  # guarded-by: _swap_lock
    cold_lists: np.ndarray     # (n_cold,) list id occupying each cold slot  # guarded-by: _swap_lock
    metric: DistanceType
    host_resident: bool        # did the cold tier land in host memory?
    generation: int = 0        # placement generation (apply_plan bumps)  # guarded-by: _swap_lock
    # serializes placement writes against serving reads: a search
    # must capture the placement-affected arrays as ONE consistent
    # generation (all pre-swap or all post-swap, never mixed — a new
    # hot plane against an old slot map would serve a list from the
    # wrong slot). Not an array field, so the memwatch model walk
    # skips it.
    _swap_lock: threading.Lock = dataclasses.field(
        default_factory=threading.Lock, repr=False, compare=False)

    _PLANE_PAIRS = (("hot_data", "cold_data"),)

    def tier_arrays(self) -> tuple:
        """Atomic snapshot of the placement generation:
        ``(hot_data, cold_data, hot_slot_map, cold_slot_map)`` read
        under the swap lock — THE way the serving path must capture
        the tier arrays (:func:`apply_plan` replaces all four under
        the same lock). Flat-family convenience over the generic
        :meth:`_TieredPlanes.tier_planes`."""
        with self._swap_lock:
            return (self.hot_data, self.cold_data,
                    self.hot_slot_map, self.cold_slot_map)


@dataclasses.dataclass
class TieredIvfPq(_TieredPlanes):
    """Hot/cold tiered IVF-PQ container (graftcast): the codes plane
    — the only billion-scale plane of a PQ index — splits hot/cold
    under the same fixed-slot, fixed-shape contract as
    :class:`TieredIvf`; centers, rotation, codebooks and the id
    plane stay resident, so coarse selection, the LUT build,
    membership masking and probe accounting are untouched and the
    tiered search is bit-identical to the all-HBM index."""

    centers: jax.Array         # (n_lists, dim) f32 — HBM
    rotation: jax.Array        # (dim_ext, dim) f32
    codebooks: jax.Array       # PQ codebooks — resident
    indices: jax.Array         # (n_lists, max_list_size) int32, full
    list_sizes: jax.Array      # (n_lists,) int32
    hot_codes: jax.Array       # (n_hot, max, pq_bytes) u8 — HBM
    cold_codes: jax.Array      # (n_cold, max, pq_bytes) u8 — host
    hot_slot_map: jax.Array    # (n_lists,) int32, hot slot or -1  # guarded-by: _swap_lock
    cold_slot_map: jax.Array   # (n_lists,) int32, cold slot or -1  # guarded-by: _swap_lock
    hot_lists: np.ndarray  # guarded-by: _swap_lock
    cold_lists: np.ndarray  # guarded-by: _swap_lock
    metric: DistanceType
    codebook_kind: object      # ivf_pq.CodebookKind
    pq_bits: int
    packed: bool
    host_resident: bool
    generation: int = 0  # guarded-by: _swap_lock
    _swap_lock: threading.Lock = dataclasses.field(
        default_factory=threading.Lock, repr=False, compare=False)

    _PLANE_PAIRS = (("hot_codes", "cold_codes"),)

    @property
    def pq_book_size(self) -> int:
        return self.codebooks.shape[1]

    @property
    def pq_dim(self) -> int:
        d = self.hot_codes.shape[2]
        return d * 2 if self.packed else d


@dataclasses.dataclass
class TieredIvfBq(_TieredPlanes):
    """Hot/cold tiered IVF-RaBitQ container (graftcast): the five
    per-row record planes — sign codes, residual norm, per-level
    scales, error weight and the raw re-rank vectors — tier as ONE
    unit per list (a single slot assignment covers all five), so the
    fused estimate-then-rerank can never read a list's estimate
    planes from one tier and its re-rank rows from another. Centers,
    rotation, ids and the norm plane stay resident. Requires the
    re-rank plane (``store_vectors=True``): a codes-only index
    serves through the rank-major scan, which has no per-list fetch
    step to tier."""

    centers: jax.Array         # (n_lists, dim) f32 — HBM
    rotation: jax.Array        # (dim_ext, dim) f32
    indices: jax.Array         # (n_lists, max) int32, full plane
    list_sizes: jax.Array      # (n_lists,) int32
    data_norms: jax.Array      # (n_lists, max) f32 — resident
    hot_codes: jax.Array       # (n_hot, max, bits·D/32) i32 — HBM
    cold_codes: jax.Array
    hot_rnorm: jax.Array       # (n_hot, max) f32
    cold_rnorm: jax.Array
    hot_cfac: jax.Array        # (n_hot, max, bits) f32
    cold_cfac: jax.Array
    hot_errw: jax.Array        # (n_hot, max) f32
    cold_errw: jax.Array
    hot_data: jax.Array        # (n_hot, max, dim) f32 — rerank rows
    cold_data: jax.Array
    hot_slot_map: jax.Array  # guarded-by: _swap_lock
    cold_slot_map: jax.Array  # guarded-by: _swap_lock
    hot_lists: np.ndarray  # guarded-by: _swap_lock
    cold_lists: np.ndarray  # guarded-by: _swap_lock
    metric: DistanceType
    host_resident: bool
    generation: int = 0  # guarded-by: _swap_lock
    _swap_lock: threading.Lock = dataclasses.field(
        default_factory=threading.Lock, repr=False, compare=False)

    _PLANE_PAIRS = (
        ("hot_codes", "cold_codes"),
        ("hot_rnorm", "cold_rnorm"),
        ("hot_cfac", "cold_cfac"),
        ("hot_errw", "cold_errw"),
        ("hot_data", "cold_data"),
    )

    @property
    def dim_ext(self) -> int:
        return self.rotation.shape[0]

    @property
    def bits(self) -> int:
        return self.hot_cfac.shape[2]


def host_put(x) -> Tuple[jax.Array, bool]:
    """Commit ``x`` to host memory (``pinned_host``) when the backend
    supports memory kinds; returns ``(array, host_resident)``. The
    fallback is HONEST: on backends without a host memory space (the
    CPU tier-1 environment, where host and device memory are the same
    pool anyway) the array stays on the default device and the flag
    says so — nothing pretends bytes left HBM that didn't."""
    x = jnp.asarray(x)
    dev = x.devices().pop() if hasattr(x, "devices") \
        else jax.devices()[0]
    try:
        kinds = tuple(m.kind for m in dev.addressable_memories())
    except Exception:  # noqa: BLE001 — no memories API at all
        kinds = ()
    if "pinned_host" not in kinds:
        # honest fallback, taken ONLY when the backend exposes no
        # pinned-host memory space (the CPU tier-1 environment, whose
        # single memory is already host RAM). COMMITTED placement
        # (explicit sharding): the cold plane must present the same
        # committed-ness from its first epoch that the
        # out_shardings-pinned swap output carries ever after — an
        # uncommitted first generation would re-specialize the swap
        # program once, breaking the warm-one-epoch zero-recompile
        # discipline.
        return jax.device_put(
            x, jax.sharding.SingleDeviceSharding(dev)), False
    # the backend DOES support pinned host memory: a failure here is
    # a real allocation problem (host RAM pressure, allocator error)
    # and must stay loud — swallowing it would silently park the
    # whole cold tier in the HBM it exists to vacate
    sharding = jax.sharding.SingleDeviceSharding(
        dev, memory_kind="pinned_host")
    return jax.device_put(x, sharding), True


def resolve_hot_slots(index, *, hot_slots=None,
                      hot_fraction: float = 0.5, ledger=None,
                      safety_fraction: float = 0.1,
                      block_bytes: Optional[int] = None) -> int:
    """Decide the hot tier's FIXED slot capacity. Precedence:

    1. an explicit ``hot_slots``;
    2. a graftledger :class:`~raft_tpu.core.memwatch.MemoryLedger`
       with known headroom: the largest slot count whose hot-tier
       bytes fit ``headroom × (1 − safety_fraction)`` (the byte half
       of the placement signal — live truth beats any fraction);
    3. ``hot_fraction`` of the lists (the unknown-headroom default —
       CPU tier-1, or no ledger attached).

    Always clamped to [1, n_lists − 1]: an all-hot or all-cold split
    is not a tiered index. ``block_bytes`` overrides the per-list
    byte unit (the compressed-family builders pass their own — a PQ
    list block is codes bytes, a BQ block the five-plane sum);
    without it the flat raw-vector block is assumed."""
    n_lists = index.n_lists
    block = block_bytes if block_bytes is not None else (
        index.max_list_size * index.dim * index.data.dtype.itemsize)
    if hot_slots is None and ledger is not None:
        headroom = ledger.headroom_bytes()
        if headroom is not None:
            usable = max(float(headroom) * (1.0 - safety_fraction), 0.0)
            hot_slots = int(usable // max(block, 1))
    if hot_slots is None:
        hot_slots = int(n_lists * hot_fraction)
    return max(1, min(int(hot_slots), n_lists - 1))


def _slot_maps(hot_lists: np.ndarray, cold_lists: np.ndarray,
               n_lists: int):
    """The (hot_map, cold_map) numpy planes for one assignment: each
    list's slot in its tier, −1 in the other — ONE implementation
    shared by construction and the swap executor, so the two can
    never disagree about the map convention."""
    hot_map = np.full((n_lists,), -1, np.int32)
    cold_map = np.full((n_lists,), -1, np.int32)
    hot_map[hot_lists] = np.arange(len(hot_lists), dtype=np.int32)
    cold_map[cold_lists] = np.arange(len(cold_lists), dtype=np.int32)
    return hot_map, cold_map


def build_tiered(index: IvfFlatIndex, *, hot_slots=None,
                 hot_fraction: float = 0.5, ledger=None,
                 safety_fraction: float = 0.1,
                 probe_counts=None) -> TieredIvf:
    """Split a built :class:`IvfFlatIndex` into the tiered layout.

    ``probe_counts`` (optional ``(n_lists,)`` counts — graftgauge's
    claimed probe-frequency plane, or any traffic prior) decides the
    INITIAL placement: the hottest ``hot_slots`` lists by count (ties
    to the smaller list id — deterministic) go hot, the rest cold.
    Without counts, lists 0..H−1 go hot — the first placement epoch
    corrects it from live traffic. ``ledger`` sizes the hot tier from
    live headroom (see :func:`resolve_hot_slots`).

    The tiered path is f32-only (the cold DMA scratch and hot blocks
    must agree on layout); int8/bf16 tiering is a follow-on."""
    expect(index.max_list_size > 0, "index is empty — extend() it first")
    expect(index.data.dtype == jnp.float32,
           "tiered storage supports f32 list data only")
    n_lists = index.n_lists
    h = resolve_hot_slots(index, hot_slots=hot_slots,
                          hot_fraction=hot_fraction, ledger=ledger,
                          safety_fraction=safety_fraction)
    hot_lists, cold_lists = _split_lists(n_lists, h, probe_counts)

    hot_map, cold_map = _slot_maps(hot_lists, cold_lists, n_lists)

    # the placement-affected arrays are COMMITTED (explicit device)
    # from construction: the epoch swap's jit outputs are committed,
    # and a committed-ness flip between the first and second epoch
    # would re-specialize the swap programs once — committing here
    # makes epoch 0 already run the steady-state executables
    dev = jax.sharding.SingleDeviceSharding(jax.devices()[0])
    hot_data = jax.device_put(
        _gather_blocks(index.data, jnp.asarray(hot_lists)), dev)
    cold_dev = _gather_blocks(index.data, jnp.asarray(cold_lists))
    cold_data, host_resident = host_put(cold_dev)
    return TieredIvf(
        centers=index.centers,
        center_norms=index.center_norms,
        data_norms=index.data_norms,
        indices=index.indices,
        list_sizes=index.list_sizes,
        hot_data=hot_data,
        cold_data=cold_data,
        hot_slot_map=jax.device_put(jnp.asarray(hot_map), dev),
        cold_slot_map=jax.device_put(jnp.asarray(cold_map), dev),
        hot_lists=hot_lists,
        cold_lists=cold_lists,
        metric=index.metric,
        host_resident=host_resident,
    )


_gather_blocks = jax.jit(lambda a, rows: jnp.take(a, rows, axis=0))


def _split_lists(n_lists: int, h: int, probe_counts):
    """Initial hot/cold list split shared by every builder: the
    hottest ``h`` lists by count go hot (ties to the smaller list id
    — argsort is stable on the already-ordered lid axis), the rest
    cold; no counts → lists 0..h−1 (the first placement epoch
    corrects it from live traffic)."""
    if probe_counts is None:
        counts = np.zeros((n_lists,), np.int64)
    else:
        counts = np.asarray(probe_counts, np.int64)
        expect(counts.shape == (n_lists,),
               "probe_counts must be one count per list")
    order = np.argsort(-counts, kind="stable").astype(np.int32)
    return np.sort(order[:h]), np.sort(order[h:])


def _tier_place(full_planes, hot_lists, cold_lists):
    """Gather each full ``(n_lists, ...)`` plane into a COMMITTED
    device hot plane and a host-committed cold plane (see
    :func:`build_tiered` on why committed-ness must hold from epoch
    0); returns ``(hot_planes, cold_planes, host_resident)``."""
    dev = jax.sharding.SingleDeviceSharding(jax.devices()[0])
    hl = jnp.asarray(hot_lists)
    cl = jnp.asarray(cold_lists)
    # one batched placement covers the whole hot plane set (R5: no
    # per-iteration transfers, even at build time)
    hots = tuple(jax.device_put(
        [_gather_blocks(plane, hl) for plane in full_planes], dev))
    colds, resident = [], True
    for plane in full_planes:
        cold, hr = host_put(_gather_blocks(plane, cl))
        colds.append(cold)
        resident = resident and hr
    return hots, tuple(colds), resident


def build_tiered_pq(index, *, hot_slots=None, hot_fraction: float = 0.5,
                    ledger=None, safety_fraction: float = 0.1,
                    probe_counts=None) -> TieredIvfPq:
    """Split a built :class:`~raft_tpu.neighbors.ivf_pq.IvfPqIndex`
    into the tiered layout — same contract as :func:`build_tiered`,
    tiering the codes plane (the only billion-scale plane of a PQ
    index). The hot-slot budget prices a list block at its CODES
    bytes, so a ledger-sized hot tier holds ~32× the lists the flat
    tier would at the same headroom (the compression ratio is the
    point)."""
    expect(index.max_list_size > 0, "index is empty — extend() it first")
    n_lists = index.n_lists
    block = (int(np.prod(index.codes.shape[1:]))
             * index.codes.dtype.itemsize)
    h = resolve_hot_slots(index, hot_slots=hot_slots,
                          hot_fraction=hot_fraction, ledger=ledger,
                          safety_fraction=safety_fraction,
                          block_bytes=block)
    hot_lists, cold_lists = _split_lists(n_lists, h, probe_counts)
    hot_map, cold_map = _slot_maps(hot_lists, cold_lists, n_lists)
    (hot_codes,), (cold_codes,), host_resident = _tier_place(
        (index.codes,), hot_lists, cold_lists)
    dev = jax.sharding.SingleDeviceSharding(jax.devices()[0])
    return TieredIvfPq(
        centers=index.centers,
        rotation=index.rotation,
        codebooks=index.codebooks,
        indices=index.indices,
        list_sizes=index.list_sizes,
        hot_codes=hot_codes,
        cold_codes=cold_codes,
        hot_slot_map=jax.device_put(jnp.asarray(hot_map), dev),
        cold_slot_map=jax.device_put(jnp.asarray(cold_map), dev),
        hot_lists=hot_lists,
        cold_lists=cold_lists,
        metric=index.metric,
        codebook_kind=index.codebook_kind,
        pq_bits=index.pq_bits,
        packed=index.packed,
        host_resident=host_resident,
    )


def build_tiered_bq(index, *, hot_slots=None, hot_fraction: float = 0.5,
                    ledger=None, safety_fraction: float = 0.1,
                    probe_counts=None) -> TieredIvfBq:
    """Split a built :class:`~raft_tpu.neighbors.ivf_bq.IvfBqIndex`
    into the tiered layout — the five per-row record planes move as
    one unit per list (see :class:`TieredIvfBq`). Requires the
    re-rank plane and f32 vectors (same f32-only rule as
    :func:`build_tiered`)."""
    expect(index.max_list_size > 0, "index is empty — extend() it first")
    expect(index.data is not None and index.data_norms is not None,
           "tiered BQ needs the re-rank plane "
           "(build with store_vectors=True)")
    expect(index.data.dtype == jnp.float32,
           "tiered storage supports f32 list data only")
    n_lists = index.n_lists
    planes = (index.codes, index.rnorm, index.cfac, index.errw,
              index.data)
    block = sum(int(np.prod(p.shape[1:])) * p.dtype.itemsize
                for p in planes)
    h = resolve_hot_slots(index, hot_slots=hot_slots,
                          hot_fraction=hot_fraction, ledger=ledger,
                          safety_fraction=safety_fraction,
                          block_bytes=block)
    hot_lists, cold_lists = _split_lists(n_lists, h, probe_counts)
    hot_map, cold_map = _slot_maps(hot_lists, cold_lists, n_lists)
    hots, colds, host_resident = _tier_place(planes, hot_lists,
                                             cold_lists)
    dev = jax.sharding.SingleDeviceSharding(jax.devices()[0])
    return TieredIvfBq(
        centers=index.centers,
        rotation=index.rotation,
        indices=index.indices,
        list_sizes=index.list_sizes,
        data_norms=index.data_norms,
        hot_codes=hots[0], cold_codes=colds[0],
        hot_rnorm=hots[1], cold_rnorm=colds[1],
        hot_cfac=hots[2], cold_cfac=colds[2],
        hot_errw=hots[3], cold_errw=colds[3],
        hot_data=hots[4], cold_data=colds[4],
        hot_slot_map=jax.device_put(jnp.asarray(hot_map), dev),
        cold_slot_map=jax.device_put(jnp.asarray(cold_map), dev),
        hot_lists=hot_lists,
        cold_lists=cold_lists,
        metric=index.metric,
        host_resident=host_resident,
    )


# ---------------------------------------------------------------------------
# placement execution — fixed-width donated block swaps
# ---------------------------------------------------------------------------


@partial(jax.jit, donate_argnums=(0,))
def _swap_hot_fn(hot_data, hot_slots, promoted):
    """Hot half of one epoch's swap: scatter the promoted blocks
    into the freed hot slots, DONATED — the hot tier is the scarce
    HBM pool and must update in place (the ``place_dealt``
    discipline: stream blocks, never materialize a permuted copy).
    ``hot_slots`` is a FIXED-width int32 vector: live pairs carry
    real slots, pad entries carry out-of-range slots the scatter
    ``mode="drop"``s — every epoch runs this one compiled program
    regardless of how many swaps it planned (zero-recompile)."""
    return hot_data.at[hot_slots].set(promoted, mode="drop")


@functools.lru_cache(maxsize=8)
def _cold_scatter_for(sharding):
    """Cold half of the swap, specialized per cold-tier sharding:
    ``out_shardings`` pins the output to the cold plane's OWN
    placement, so a host-committed (``pinned_host``) tier STAYS
    host-committed across epochs — without it the first epoch's
    output would land in default device memory, both hauling the
    cold tier back into HBM and invalidating the executor's AOT
    executable that was lowered with the host-memory aval
    (``_Plan.keep_sharding``). Not donated: host RAM is the abundant
    pool, and pinned-host donation semantics are backend-dependent —
    a transient functional copy there is the safe trade. One cached
    jit per sharding; the sharding is stable across epochs, so this
    compiles once."""
    return jax.jit(
        lambda cold, slots, blocks: cold.at[slots].set(blocks,
                                                       mode="drop"),
        out_shardings=sharding)


@partial(jax.jit, donate_argnums=(0, 1))
def _swap_maps_fn(hot_map, cold_map, promo_lids, demo_lids, hot_slots,
                  cold_slots):
    """Slot-map half of the swap (same fixed width + drop-mode pad
    discipline): promoted lists take the freed hot slots, demoted
    lists the freed cold slots, each list's other-tier slot goes
    −1."""
    hot_map = hot_map.at[promo_lids].set(hot_slots, mode="drop")
    hot_map = hot_map.at[demo_lids].set(-1, mode="drop")
    cold_map = cold_map.at[demo_lids].set(cold_slots, mode="drop")
    cold_map = cold_map.at[promo_lids].set(-1, mode="drop")
    return hot_map, cold_map


@jax.jit
def _promote_mix_fn(staged_plane, cold_plane, st_rows, cg, hit):
    """Promotion-source mix (graftcast prefetch): rows the
    prefetcher already staged in HBM come from the staged plane, the
    rest gather from the cold plane. Fixed shapes (swap width ×
    staged capacity) — one compiled program per plane geometry, so a
    prefetch-assisted epoch runs the same executables as a reactive
    one plus exactly this mix. The per-row select is the accounting
    truth the bench gates on: a hit's bytes moved at STAGE time
    (background), off the serving-path epoch — a sparse cold gather
    that also skips the miss rows' neighbors on-chip is the ROADMAP
    follow-on."""
    a = jnp.take(staged_plane, jnp.maximum(st_rows, 0), axis=0)
    b = jnp.take(cold_plane, cg, axis=0)
    shape = (hit.shape[0],) + (1,) * (a.ndim - 1)
    return jnp.where(jnp.reshape(hit, shape), a, b)


def apply_plan(tiered, promotions, demotions,
               width: int, executor=None, staged=None) -> int:
    """Execute a placement plan IN PLACE: ``promotions[i]`` (a cold
    list id) takes the hot slot ``demotions[i]`` frees, which takes
    the cold slot ``promotions[i]`` frees. ``width`` is the fixed
    compiled swap width (the policy's ``max_swaps_per_epoch``) — the
    pair vectors pad to it with out-of-range slots (gathers clamp,
    scatters drop), so every epoch reuses one executable per
    (shapes, width). Works on ANY tiered container — the plane
    pairs come from ``_PLANE_PAIRS`` (flat: one raw-vector pair;
    PQ: codes; BQ: all five record planes under one slot decision).
    Returns the bytes moved (2 × block per pair: one promotion + one
    demotion).

    ``staged`` (graftcast prefetch) optionally provides promotion
    blocks the prefetcher already copied into HBM: an object with
    ``rows`` (one staged-plane row per promotion, −1 = miss) and
    ``planes`` (hot plane name → fixed ``(K, ...)`` staged storage).
    Hit rows skip the epoch-time cold stream (their bytes moved in
    the background at stage time); only misses count into the
    ``tier.promote_cold_bytes`` serving-path counter, which the
    reactive path charges in full — the A/B surface
    ``BENCH_TIERED`` gates.

    Concurrency discipline: the hot planes and the slot maps are
    DONATED to the swap (in-place HBM update), which is only safe
    against live traffic when swap enqueues serialize with dispatch
    enqueues — pass the serving ``executor`` (the TierManager does)
    and the swap runs under its dispatch lock. A dispatch that
    captured the pre-swap generation and enqueues after the swap
    hits jax's deleted-array error once and is retried by the
    executor against the new generation (see
    ``SearchExecutor._run``); readers always see a CONSISTENT
    generation because the container's placement arrays replace —
    and the generation counter bumps — atomically under the swap
    lock (:meth:`_TieredPlanes.tier_planes`)."""
    n = len(promotions)
    expect(n == len(demotions), "promotions/demotions must pair up")
    expect(n <= width, f"plan has {n} swaps, width is {width}")
    if n == 0:
        return 0
    promo = np.asarray(promotions, np.int32)
    demo = np.asarray(demotions, np.int32)
    hot_map_np, cold_map_np = _slot_maps(
        tiered.hot_lists, tiered.cold_lists, tiered.n_lists)
    hot_slots = hot_map_np[demo]
    cold_slots = cold_map_np[promo]
    expect(bool((hot_slots >= 0).all()),
           "every demotion must name a currently-hot list")
    expect(bool((cold_slots >= 0).all()),
           "every promotion must name a currently-cold list")

    # fixed-width pad: out-of-range slots/lids — gathers clamp,
    # scatters drop (see _swap_blocks_fn)
    def pad_to(v, fill):
        out = np.full((width,), fill, np.int32)
        out[:n] = v
        return jnp.asarray(out)

    hs = pad_to(hot_slots, tiered.n_hot)
    cs = pad_to(cold_slots, tiered.n_cold)
    pl_ = pad_to(promo, tiered.n_lists)
    dl = pad_to(demo, tiered.n_lists)

    st_rows = hit = None
    misses = n
    if staged is not None:
        rows_np = np.full((width,), -1, np.int32)
        rows_np[:n] = np.asarray(staged.rows, np.int32)[:n]
        st_rows = jnp.asarray(rows_np)
        hit = jnp.asarray(rows_np >= 0)
        misses = int(n - int((rows_np[:n] >= 0).sum()))

    # contextlib.nullcontext would be cleaner, but the executor lock
    # is the point: with a live executor attached, the donation
    # enqueues below must not interleave with dispatch enqueues
    ex_lock = getattr(executor, "_lock", None) if executor is not None \
        else None
    if ex_lock is not None:
        ex_lock.acquire()
    try:
        updates = {}
        for hot_name, cold_name in type(tiered)._PLANE_PAIRS:
            old_hot = getattr(tiered, hot_name)
            old_cold = getattr(tiered, cold_name)
            hg = jnp.minimum(hs, old_hot.shape[0] - 1)
            cg = jnp.minimum(cs, old_cold.shape[0] - 1)
            # gathers BEFORE the donation consumes the hot plane;
            # the promoted gather out of a host-committed cold plane
            # lands in device memory (that copy IS the promotion
            # transfer), and the demoted blocks ride into the
            # sharding-pinned cold scatter (the demotion transfer)
            demoted = _gather_blocks(old_hot, hg)
            if st_rows is not None:
                promoted = _promote_mix_fn(
                    staged.planes[hot_name], old_cold, st_rows, cg,
                    hit)
            else:
                promoted = _gather_blocks(old_cold, cg)
            updates[hot_name] = _swap_hot_fn(old_hot, hs, promoted)
            updates[cold_name] = _cold_scatter_for(old_cold.sharding)(
                old_cold, cs, demoted)
        hot_map, cold_map = _swap_maps_fn(
            tiered.hot_slot_map, tiered.cold_slot_map, pl_, dl, hs, cs)
        # host-side mirrors (the layout truth /tier.json serves)
        hot_lists = tiered.hot_lists.copy()
        cold_lists = tiered.cold_lists.copy()
        hot_lists[hot_slots] = promo
        cold_lists[cold_slots] = demo
        # the new generation replaces atomically: a concurrent
        # tier_planes()/tier_arrays() sees all-old or all-new, never
        # a mix — and the generation bump makes any still-in-flight
        # prefetch against the old placement detectably stale
        with tiered._swap_lock:
            for name, arr in updates.items():
                setattr(tiered, name, arr)
            tiered.hot_slot_map = hot_map
            tiered.cold_slot_map = cold_map
            tiered.hot_lists = hot_lists
            tiered.cold_lists = cold_lists
            tiered.generation += 1
    finally:
        if ex_lock is not None:
            ex_lock.release()
    moved = 2 * n * tiered.block_bytes
    tracing.inc_counters({
        "tier.swaps": float(n),
        "tier.swap_bytes": float(moved),
        "tier.promote_cold_bytes": float(misses * tiered.block_bytes),
    })
    return moved


# ---------------------------------------------------------------------------
# search
# ---------------------------------------------------------------------------


def _tiered_search_fn(queries, centers, center_norms, hot_data,
                      cold_data, hot_slot_map, cold_slot_map,
                      data_norms, indices, filter_words, init_d=None,
                      init_i=None, probe_counts=None, n_valid=None,
                      row_probes=None, *,
                      n_probes: int, k: int, metric: DistanceType,
                      coarse_algo: str = "exact",
                      scan_engine: str = "xla"):
    """Coarse select + tiered probe scan — the serving body (the
    executor's ``tiered_ivf`` plan compiles this). Mirrors ivf_flat's
    ``_search_impl_fn`` contract: the coarse stage and metric epilog
    are char-identical, only the scan swaps in the tiered engines, so
    results are bit-identical to the all-HBM index per engine.
    ``probe_counts``/``n_valid`` thread graftgauge's donated plane
    exactly like the un-tiered body. ``row_probes`` (the ragged
    front — see :func:`_tiered_search_ragged_fn`) masks each packed
    row's probe slots past its own budget to the sentinel id, which
    the tiered engines' membership predicate already rejects.
    ``scan_engine`` must arrive resolved (``pallas``/``xla``) — it
    is a jit static."""
    from raft_tpu.ops.tier_scan import tiered_list_major_scan

    qf = queries.astype(jnp.float32)

    ip = jax.lax.dot_general(
        qf, centers, (((1,), (1,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    )
    score = (ip if metric == DistanceType.InnerProduct
             else -(center_norms[None, :] - 2.0 * ip))
    probes = coarse_select(score, n_probes, coarse_algo)
    if row_probes is not None:
        from raft_tpu.ops.ivf_scan import ragged_probes

        probes = ragged_probes(probes, row_probes, centers.shape[0])
    if probe_counts is not None:
        from raft_tpu.ops.ivf_scan import probe_histogram

        probe_counts = probe_histogram(
            probes, probe_counts,
            None if row_probes is not None else n_valid)

    best_d, best_i = tiered_list_major_scan(
        qf, hot_data, cold_data, hot_slot_map, cold_slot_map,
        data_norms, indices, probes, filter_words, init_d, init_i,
        k=k, metric=metric, engine=scan_engine,
        interpret=jax.default_backend() != "tpu")

    if metric != DistanceType.InnerProduct:
        q_sq = jnp.sum(jnp.square(qf), axis=1, keepdims=True)
        best_d = jnp.where(jnp.isfinite(best_d),
                           jnp.maximum(best_d + q_sq, 0.0), best_d)
        if metric == DistanceType.L2SqrtExpanded:
            best_d = jnp.where(jnp.isfinite(best_d), jnp.sqrt(best_d),
                               best_d)
    if probe_counts is not None:
        return best_d, best_i, probe_counts
    return best_d, best_i


_tiered_search = partial(jax.jit, static_argnames=(
    "n_probes", "k", "metric", "coarse_algo",
    "scan_engine"))(_tiered_search_fn)


def _tiered_search_ragged_fn(queries, row_probes, centers,
                             center_norms, hot_data, cold_data,
                             hot_slot_map, cold_slot_map, data_norms,
                             indices, filter_words, init_d=None,
                             init_i=None, probe_counts=None,
                             n_valid=None, *, n_probes: int, k: int,
                             metric: DistanceType,
                             scan_engine: str = "xla"):
    """Packed ragged-batch tiered search body — the tiered member of
    the serving executor's ragged plan family (see
    :func:`raft_tpu.neighbors.ivf_flat._search_ragged_fn` for the
    packing contract). The plan is placement-GENERATION-stable: its
    cache key carries only shapes and statics, never the placement
    arrays, and every dispatch re-snapshots one consistent
    generation (:meth:`_TieredPlanes.tier_planes`) into the same
    fixed avals — an epoch permutes the hot/cold slot maps without
    touching the one ragged executable, which is what retired the
    ``"tiered"`` ragged-fallback pin. Bit-identical per request to
    :func:`_tiered_search_fn` on that request alone (same body, same
    membership-masked engines)."""
    del n_valid
    expect(scan_engine in ("pallas", "xla"),
           "ragged tiered serving needs a membership-masked tier "
           f"engine (pallas|xla), got {scan_engine!r}")
    return _tiered_search_fn(
        queries, centers, center_norms, hot_data, cold_data,
        hot_slot_map, cold_slot_map, data_norms, indices,
        filter_words, init_d, init_i, probe_counts, None,
        row_probes=row_probes, n_probes=n_probes, k=k, metric=metric,
        coarse_algo="exact", scan_engine=scan_engine)


def _tiered_pq_search_fn(queries, centers, rotation, codebooks,
                         hot_codes, cold_codes, hot_slot_map,
                         cold_slot_map, indices, filter_words,
                         init_d=None, init_i=None, probe_counts=None,
                         n_valid=None, row_probes=None, *,
                         n_probes: int, k: int, metric: DistanceType,
                         codebook_kind, lut_dtype,
                         score_mode: str = "gather",
                         packed: bool = False,
                         coarse_algo: str = "exact",
                         scan_engine: str = "xla"):
    """Tiered PQ serving body — a thin reorder over
    :func:`raft_tpu.neighbors.ivf_pq._search_impl_fn` with the cold
    codes plane live: the LUT union scan is the SAME body (coarse
    select, LUT build, accumulate, merge are char-identical), only
    the per-list codes fetch steers through the tier slot maps, so
    tiered PQ results are bit-identical to the all-HBM index."""
    from raft_tpu.neighbors.ivf_pq import _search_impl_fn

    return _search_impl_fn(
        queries, centers, rotation, codebooks, hot_codes, indices,
        filter_words, init_d, init_i, probe_counts, n_valid,
        row_probes=row_probes, cold_codes=cold_codes,
        hot_slot_map=hot_slot_map, cold_slot_map=cold_slot_map,
        n_probes=n_probes, k=k, metric=metric,
        codebook_kind=codebook_kind, lut_dtype=lut_dtype,
        score_mode=score_mode, packed=packed,
        coarse_algo=coarse_algo, scan_engine=scan_engine)


_tiered_pq_search = partial(jax.jit, static_argnames=(
    "n_probes", "k", "metric", "codebook_kind", "lut_dtype",
    "score_mode", "packed", "coarse_algo",
    "scan_engine"))(_tiered_pq_search_fn)


def _tiered_pq_search_ragged_fn(queries, row_probes, centers,
                                rotation, codebooks, hot_codes,
                                cold_codes, hot_slot_map,
                                cold_slot_map, indices, filter_words,
                                init_d=None, init_i=None,
                                probe_counts=None, n_valid=None, *,
                                n_probes: int, k: int,
                                metric: DistanceType, codebook_kind,
                                lut_dtype, score_mode: str = "gather",
                                packed: bool = False,
                                scan_engine: str = "xla"):
    """Packed ragged-batch tiered-PQ body (see
    :func:`_tiered_search_ragged_fn` for the generation-stable
    contract; XLA engine only, like the un-tiered PQ ragged twin)."""
    del n_valid
    expect(scan_engine == "xla",
           "ragged tiered PQ serving rides the list-major XLA scan, "
           f"got {scan_engine!r}")
    return _tiered_pq_search_fn(
        queries, centers, rotation, codebooks, hot_codes, cold_codes,
        hot_slot_map, cold_slot_map, indices, filter_words, init_d,
        init_i, probe_counts, None, row_probes=row_probes,
        n_probes=n_probes, k=k, metric=metric,
        codebook_kind=codebook_kind, lut_dtype=lut_dtype,
        score_mode=score_mode, packed=packed, coarse_algo="exact",
        scan_engine=scan_engine)


def _tiered_bq_search_fn(queries, centers, rotation, hot_codes,
                         hot_rnorm, hot_cfac, hot_errw, hot_data,
                         cold_codes, cold_rnorm, cold_cfac, cold_errw,
                         cold_data, hot_slot_map, cold_slot_map,
                         indices, data_norms, filter_words,
                         init_d=None, init_i=None, probe_counts=None,
                         n_valid=None, row_probes=None, *,
                         n_probes: int, k: int, metric: DistanceType,
                         coarse_algo: str = "exact",
                         scan_engine: str = "xla",
                         epsilon: float = 3.0, query_bits: int = 0):
    """Tiered BQ serving body — a thin reorder over
    :func:`raft_tpu.neighbors.ivf_bq._search_impl_fn` with the five
    cold record planes live (one slot decision per list covers the
    estimate planes AND the re-rank rows). Same fused
    estimate-then-rerank body ⇒ same prune decisions ⇒ bit-identical
    to the all-HBM index."""
    from raft_tpu.neighbors.ivf_bq import _search_impl_fn

    return _search_impl_fn(
        queries, centers, rotation, hot_codes, hot_rnorm, hot_cfac,
        hot_errw, indices, hot_data, data_norms, filter_words,
        init_d, init_i, probe_counts, n_valid,
        row_probes=row_probes,
        cold_planes=(cold_codes, cold_rnorm, cold_cfac, cold_errw,
                     cold_data),
        hot_slot_map=hot_slot_map, cold_slot_map=cold_slot_map,
        n_probes=n_probes, k=k, metric=metric,
        coarse_algo=coarse_algo, scan_engine=scan_engine,
        epsilon=epsilon, query_bits=query_bits)


_tiered_bq_search = partial(jax.jit, static_argnames=(
    "n_probes", "k", "metric", "coarse_algo", "scan_engine",
    "epsilon", "query_bits"))(_tiered_bq_search_fn)


def _tiered_bq_search_ragged_fn(queries, row_probes, centers,
                                rotation, hot_codes, hot_rnorm,
                                hot_cfac, hot_errw, hot_data,
                                cold_codes, cold_rnorm, cold_cfac,
                                cold_errw, cold_data, hot_slot_map,
                                cold_slot_map, indices, data_norms,
                                filter_words, init_d=None,
                                init_i=None, probe_counts=None,
                                n_valid=None, *, n_probes: int,
                                k: int, metric: DistanceType,
                                scan_engine: str = "xla",
                                epsilon: float = 3.0,
                                query_bits: int = 0):
    """Packed ragged-batch tiered-BQ body (see
    :func:`_tiered_search_ragged_fn` for the generation-stable
    contract; the fused XLA engine's per-row prune threshold keeps
    each request's re-rank decisions independent of its tile
    mates)."""
    del n_valid
    expect(scan_engine == "xla",
           "ragged tiered BQ serving rides the fused XLA scan, got "
           f"{scan_engine!r}")
    return _tiered_bq_search_fn(
        queries, centers, rotation, hot_codes, hot_rnorm, hot_cfac,
        hot_errw, hot_data, cold_codes, cold_rnorm, cold_cfac,
        cold_errw, cold_data, hot_slot_map, cold_slot_map, indices,
        data_norms, filter_words, init_d, init_i, probe_counts, None,
        row_probes=row_probes, n_probes=n_probes, k=k, metric=metric,
        coarse_algo="exact", scan_engine=scan_engine,
        epsilon=epsilon, query_bits=query_bits)


def search(
    res: Optional[Resources],
    params: TieredSearchParams,
    tiered: TieredIvf,
    queries,
    k: int,
    sample_filter=None,
    query_tile: int = 4096,
) -> Tuple[jax.Array, jax.Array]:
    """ANN search over the tiered index — same contract as
    ``ivf_flat.search`` (and bit-identical to it on the same lists):
    returns (distances, indices) of shape (q, k), missing slots id
    −1. The probe scan follows ``params.scan_engine`` (resolved per
    backend/shape by :func:`raft_tpu.ops.tier_scan
    .resolve_tier_engine`)."""
    ensure_resources(res)
    queries = jnp.asarray(queries)
    expect(queries.ndim == 2 and queries.shape[1] == tiered.dim,
           "queries must be (q, dim)")
    expect(params.coarse_algo in ("exact", "approx"),
           f"coarse_algo must be 'exact' or 'approx', got "
           f"{params.coarse_algo!r}")
    n_probes = min(params.n_probes, tiered.n_lists)
    filter_words = resolve_filter_words(sample_filter)
    from raft_tpu.ops.tier_scan import resolve_tier_engine

    # one consistent placement generation for the whole call — a
    # concurrent epoch swap must never hand this search a new hot
    # plane against an old slot map
    hot_data, cold_data, hot_map, cold_map = tiered.tier_arrays()
    scan_engine = resolve_tier_engine(
        params.scan_engine, hot_data=hot_data,
        filter_words=filter_words, k=k)
    with tracing.range("raft_tpu.tiered.search"):
        def run(qt, fw):
            return _tiered_search(
                qt, tiered.centers, tiered.center_norms,
                hot_data, cold_data, hot_map, cold_map,
                tiered.data_norms, tiered.indices, fw,
                n_probes=n_probes, k=k, metric=tiered.metric,
                coarse_algo=params.coarse_algo,
                scan_engine=scan_engine,
            )

        return tile_queries(run, queries, filter_words, query_tile)


def search_pq(
    res: Optional[Resources],
    params,
    tiered: TieredIvfPq,
    queries,
    k: int,
    sample_filter=None,
    query_tile: int = 4096,
) -> Tuple[jax.Array, jax.Array]:
    """ANN search over the tiered PQ index — same contract as (and
    bit-identical to) ``ivf_pq.search`` with
    :class:`~raft_tpu.neighbors.ivf_pq.IvfPqSearchParams`, forced
    onto the list-major XLA scan (the only engine with a per-list
    fetch step to steer through the tier — see
    :func:`raft_tpu.ops.tier_scan.resolve_tier_pq_engine`)."""
    from raft_tpu.neighbors import ivf_pq as m
    from raft_tpu.ops.tier_scan import resolve_tier_pq_engine

    ensure_resources(res)
    queries = jnp.asarray(queries)
    expect(queries.ndim == 2 and queries.shape[1] == tiered.dim,
           "queries must be (q, dim)")
    n_probes = min(params.n_probes, tiered.n_lists)
    filter_words = resolve_filter_words(sample_filter)
    engine = resolve_tier_pq_engine(params.scan_engine)
    score_mode = m.resolve_score_mode(params.score_mode,
                                      tiered.pq_book_size)
    (hot_codes,), (cold_codes,), hot_map, cold_map, _ = \
        tiered.tier_planes()
    with tracing.range("raft_tpu.tiered.search_pq"):
        def run(qt, fw):
            return _tiered_pq_search(
                qt, tiered.centers, tiered.rotation, tiered.codebooks,
                hot_codes, cold_codes, hot_map, cold_map,
                tiered.indices, fw, n_probes=n_probes, k=k,
                metric=tiered.metric,
                codebook_kind=tiered.codebook_kind,
                lut_dtype=params.lut_dtype, score_mode=score_mode,
                packed=tiered.packed, coarse_algo=params.coarse_algo,
                scan_engine=engine,
            )

        return tile_queries(run, queries, filter_words, query_tile)


def search_bq(
    res: Optional[Resources],
    params,
    tiered: TieredIvfBq,
    queries,
    k: int,
    sample_filter=None,
    query_tile: int = 4096,
) -> Tuple[jax.Array, jax.Array]:
    """ANN search over the tiered BQ index — same contract as (and
    bit-identical to) ``ivf_bq.search`` with
    :class:`~raft_tpu.neighbors.ivf_bq.IvfBqSearchParams` on a
    store-vectors index: exact distances out of the fused
    estimate-then-rerank XLA engine, with each probed list's five
    record planes fetched from its tier."""
    from raft_tpu.ops.bq_scan import auto_query_bits
    from raft_tpu.ops.tier_scan import resolve_tier_bq_engine

    ensure_resources(res)
    queries = jnp.asarray(queries)
    expect(queries.ndim == 2 and queries.shape[1] == tiered.dim,
           "queries must be (q, dim)")
    n_probes = min(params.n_probes, tiered.n_lists)
    filter_words = resolve_filter_words(sample_filter)
    engine = resolve_tier_bq_engine(params.scan_engine)
    qb = params.query_bits or auto_query_bits(tiered.bits)
    hots, colds, hot_map, cold_map, _ = tiered.tier_planes()
    with tracing.range("raft_tpu.tiered.search_bq"):
        def run(qt, fw):
            return _tiered_bq_search(
                qt, tiered.centers, tiered.rotation, *hots, *colds,
                hot_map, cold_map, tiered.indices, tiered.data_norms,
                fw, n_probes=n_probes, k=k, metric=tiered.metric,
                coarse_algo=params.coarse_algo, scan_engine=engine,
                epsilon=params.epsilon, query_bits=qb,
            )

        return tile_queries(run, queries, filter_words, query_tile)
