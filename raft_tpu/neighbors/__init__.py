"""Vector-search algorithms — the flagship layer (reference
``raft/neighbors/``, SURVEY.md §2.5)."""

from raft_tpu.neighbors import ball_cover
from raft_tpu.neighbors import brute_force
from raft_tpu.neighbors import cagra
from raft_tpu.neighbors import hnsw
from raft_tpu.neighbors import cluster_join
from raft_tpu.neighbors import epsilon_neighborhood
from raft_tpu.neighbors import ivf_bq
from raft_tpu.neighbors import ivf_flat
from raft_tpu.neighbors import ivf_pq
from raft_tpu.neighbors import nn_descent
from raft_tpu.neighbors import quantized
from raft_tpu.neighbors import tiered
from raft_tpu.neighbors.ann_types import IndexParams, SearchParams
from raft_tpu.neighbors.epsilon_neighborhood import eps_neighbors
# pylibraft parity: ``neighbors.refine`` is the function (the submodule
# stays importable as ``raft_tpu.neighbors.refine`` via sys.modules)
from raft_tpu.neighbors.refine import refine

__all__ = [
    "ball_cover",
    "brute_force",
    "cagra",
    "hnsw",
    "cluster_join",
    "epsilon_neighborhood",
    "eps_neighbors",
    "ivf_bq",
    "ivf_flat",
    "ivf_pq",
    "nn_descent",
    "quantized",
    "refine",
    "tiered",
    "IndexParams",
    "SearchParams",
]
