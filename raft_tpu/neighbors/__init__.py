"""Vector-search algorithms — the flagship layer (reference
``raft/neighbors/``, SURVEY.md §2.5)."""

from raft_tpu.neighbors import brute_force
from raft_tpu.neighbors.ann_types import IndexParams, SearchParams

__all__ = [
    "brute_force",
    "IndexParams",
    "SearchParams",
]
