"""IVF-Flat — inverted-file index with raw vectors, TPU-native re-design
of ``raft::neighbors::ivf_flat`` (``neighbors/ivf_flat_types.hpp:131``,
build ``detail/ivf_flat_build.cuh:301``, search
``detail/ivf_flat_search-inl.cuh:38-210``).

Reference architecture: balanced-kmeans cluster centers; ragged per-list
device arrays with vectors interleaved in groups of 32
(``ivf_flat_types.hpp:163-176``); search = coarse GEMM + select_k over
centers, then a fused ``interleaved_scan`` kernel over probed lists.

TPU re-design (SURVEY.md §7.4): raggedness is the enemy of XLA, so lists
live in ONE dense padded tensor ``data[n_lists, max_list_size, dim]``
(max_list_size = padded max cluster population; balanced k-means keeps the
overhead ≈2× worst case). The probe scan is pluggable
(``IvfFlatSearchParams.scan_engine``): the default **list-major**
engines (:mod:`raft_tpu.ops.ivf_scan` — fused Pallas kernel on TPU,
XLA scan elsewhere) stream each probed list once and score it against
the whole query tile in one dense MXU GEMM, with a per-query
membership mask; the legacy **rank-major** engine is a ``lax.scan``
over probe ranks gathering one probed list per query into a batched
GEMM. Per-slot squared norms are precomputed so every engine's scan is
a pure ``norms - 2 x·y`` epilog (the reference caches norms the same
way, ``ivf_flat_types.hpp``).

int8/uint8 datasets are stored packed and upcast inside the scan
(reference supports float/int8/uint8, ``ivf_flat_types.hpp:49-68``).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.cluster import kmeans_balanced
from raft_tpu.cluster.kmeans_balanced import KMeansBalancedParams
from raft_tpu.core import interruptible, memwatch, tracing
from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.core.serialize import (
    check_version,
    deserialize_array,
    deserialize_scalar,
    open_maybe_path,
    serialize_array,
    serialize_scalar,
)
from raft_tpu.core.validation import expect
from raft_tpu.distance.types import DistanceType, is_min_close
from raft_tpu.matrix.select_k import merge_topk
from raft_tpu.neighbors._batching import coarse_select, tile_queries
from raft_tpu.neighbors._streaming import label_pass, sample_trainset
from raft_tpu.neighbors._packing import (
    pack_padded_lists,
    padded_extent,
    streaming_ranks,
)
from raft_tpu.neighbors.ann_types import IndexParams, SearchParams
from raft_tpu.neighbors.filters import resolve_filter_words, test_filter

_SERIALIZATION_VERSION = 4  # kept in step with the reference's v4 format id


@dataclasses.dataclass(frozen=True)
class IvfFlatIndexParams(IndexParams):
    """Mirrors ``ivf_flat::index_params`` (``ivf_flat_types.hpp:49-68``)."""

    n_lists: int = 1024
    kmeans_n_iters: int = 20
    kmeans_trainset_fraction: float = 0.5
    adaptive_centers: bool = False


@dataclasses.dataclass(frozen=True)
class IvfFlatSearchParams(SearchParams):
    """Mirrors ``ivf_flat::search_params``. ``coarse_algo="approx"``
    routes cluster selection through the TPU's native approximate top-k
    unit (``lax.approx_min_k``) — worthwhile at 10k+ lists where the
    exact sort dominates the coarse stage.

    ``scan_engine`` selects the probe-scan formulation
    (:mod:`raft_tpu.ops.ivf_scan`): ``"auto"`` is the fused list-major
    Pallas kernel on TPU and the list-major XLA scan elsewhere;
    ``"pallas"``/``"xla"`` force an engine (pallas degrades to xla when
    its preconditions fail — see ``resolve_scan_engine``); ``"rank"``
    is the legacy rank-major gather scan."""

    n_probes: int = 20
    coarse_algo: str = "exact"   # "exact" | "approx"
    scan_engine: str = "auto"    # "auto" | "pallas" | "xla" | "rank"


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass(frozen=True)
class IvfFlatIndex:
    """Padded-dense IVF index (role of ``ivf_flat::index``,
    ``ivf_flat_types.hpp:131``)."""

    centers: jax.Array        # (n_lists, d) float32
    center_norms: jax.Array   # (n_lists,) float32 squared norms
    data: jax.Array           # (n_lists, max_list_size, d) storage dtype
    data_norms: jax.Array     # (n_lists, max_list_size) f32, +inf at padding
    indices: jax.Array        # (n_lists, max_list_size) int32, -1 at padding
    list_sizes: jax.Array     # (n_lists,) int32
    metric: DistanceType
    adaptive_centers: bool

    def tree_flatten(self):
        return (
            self.centers, self.center_norms, self.data, self.data_norms,
            self.indices, self.list_sizes,
        ), (self.metric, self.adaptive_centers)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, metric=aux[0], adaptive_centers=aux[1])

    @property
    def n_lists(self) -> int:
        return self.centers.shape[0]

    @property
    def dim(self) -> int:
        return self.centers.shape[1]

    @property
    def max_list_size(self) -> int:
        return self.data.shape[1]

    @property
    def size(self) -> int:
        return int(self.list_sizes.sum())


# ---------------------------------------------------------------------------
# build / extend
# ---------------------------------------------------------------------------


def _pack_lists(dataset, ids, labels, n_lists: int, max_list_size: int,
                sizes=None):
    """Scatter rows into the padded [n_lists, max_list_size] layout —
    the shared sort-and-rank packing (dense formulation of the
    reference's per-list packing, ``detail/ivf_flat_build.cuh:161``)."""
    (data, indices), sizes = pack_padded_lists(
        labels, n_lists, max_list_size,
        [(dataset, 0), (jnp.asarray(ids, jnp.int32), -1)], sizes=sizes)
    # per-slot norms; +inf on padding so padded slots never win the top-k
    norms = jnp.sum(jnp.square(data.astype(jnp.float32)), axis=2)
    norms = jnp.where(indices >= 0, norms, jnp.inf)
    return data, norms, indices, sizes


def build(
    res: Optional[Resources],
    params: IvfFlatIndexParams,
    dataset,
) -> IvfFlatIndex:
    """Train the coarse quantizer and (optionally) fill the lists —
    ``ivf_flat::build`` (``detail/ivf_flat_build.cuh:301``).

    Examples
    --------
    >>> import numpy as np
    >>> from raft_tpu.neighbors import ivf_flat
    >>> x = np.arange(32, dtype=np.float32).reshape(16, 2)
    >>> idx = ivf_flat.build(
    ...     None, ivf_flat.IvfFlatIndexParams(n_lists=2), x)
    >>> _, i = ivf_flat.search(
    ...     None, ivf_flat.IvfFlatSearchParams(n_probes=2), idx, x[:1], 1)
    >>> int(np.asarray(i)[0, 0])
    0
    """
    res = ensure_resources(res)
    dataset = jnp.asarray(dataset)
    expect(dataset.ndim == 2, "dataset must be (n, d)")
    n, d = dataset.shape
    expect(params.n_lists <= n, "n_lists > n_rows")
    expect(
        params.metric in (DistanceType.L2Expanded, DistanceType.L2SqrtExpanded,
                          DistanceType.InnerProduct),
        f"ivf_flat supports L2Expanded/L2SqrtExpanded/InnerProduct, got {params.metric!r}",
    )
    with tracing.range("raft_tpu.ivf_flat.build"):
        # subsample trainset (``ivf_pq_build.cuh:1537`` pattern shared by IVF)
        frac = min(max(params.kmeans_trainset_fraction, 0.0), 1.0)
        n_train = max(params.n_lists, int(n * frac))
        if n_train < n:
            stride = n // n_train
            trainset = dataset[:: stride][:n_train].astype(jnp.float32)
        else:
            trainset = dataset.astype(jnp.float32)
        km_params = KMeansBalancedParams(
            n_iters=params.kmeans_n_iters,
            metric=(DistanceType.InnerProduct
                    if params.metric == DistanceType.InnerProduct
                    else DistanceType.L2Expanded),
            seed=res.seed,
        )
        centers = kmeans_balanced.fit(res, km_params, trainset, params.n_lists)
        center_norms = jnp.sum(jnp.square(centers), axis=1)

        empty = IvfFlatIndex(
            centers=centers,
            center_norms=center_norms,
            data=jnp.zeros((params.n_lists, 0, d), dataset.dtype),
            data_norms=jnp.zeros((params.n_lists, 0), jnp.float32),
            indices=jnp.full((params.n_lists, 0), -1, jnp.int32),
            list_sizes=jnp.zeros((params.n_lists,), jnp.int32),
            metric=DistanceType(params.metric),
            adaptive_centers=params.adaptive_centers,
        )
        if not params.add_data_on_build:
            return empty
        return extend(res, empty, dataset, jnp.arange(n, dtype=jnp.int32))


def _scatter_extend_fn(data, norms, indices, rows, row_norms, ids, list_ids,
                       ranks):
    """Scatter new rows into the padded list tensors — the incremental
    half of ``extend``. With the donating wrapper the big (n_lists,
    max_list_size, dim) tensor is updated in place: no full repack, no
    second HBM allocation."""
    return (data.at[list_ids, ranks].set(rows),
            norms.at[list_ids, ranks].set(row_norms),
            indices.at[list_ids, ranks].set(ids))


_scatter_extend = jax.jit(_scatter_extend_fn)
_scatter_extend_donated = jax.jit(_scatter_extend_fn, donate_argnums=(0, 1, 2))


def extend(
    res: Optional[Resources],
    index: IvfFlatIndex,
    new_vectors,
    new_indices=None,
    donate: bool = False,
) -> IvfFlatIndex:
    """Add vectors to the index — ``ivf_flat::extend``
    (``detail/ivf_flat_build.cuh:161``). Functional: returns a new index
    (XLA model; the reference mutates device lists in place).

    When the new rows fit inside the existing padding, they are
    scattered incrementally — O(new) work instead of a full O(total)
    repack. With ``donate=True`` the old index's list tensors are
    donated to that scatter, so the rebuild reuses their HBM in place —
    the serving-ingestion mode; the *old* index object must not be used
    afterwards. Only the incremental path can donate; a growing padded
    extent always falls back to the full functional repack.

    With ``adaptive_centers`` the centers drift toward the running mean of
    their list (``ivf_flat_types.hpp:57-68``)."""
    res = ensure_resources(res)
    new_vectors = jnp.asarray(new_vectors)
    expect(new_vectors.ndim == 2 and new_vectors.shape[1] == index.dim,
           "new_vectors must be (n, dim)")
    n_new = new_vectors.shape[0]
    if new_indices is None:
        start = index.size
        new_indices = jnp.arange(start, start + n_new, dtype=jnp.int32)
    else:
        new_indices = jnp.asarray(new_indices, jnp.int32)

    with tracing.range("raft_tpu.ivf_flat.extend"):
        km_params = KMeansBalancedParams(
            metric=(DistanceType.InnerProduct
                    if index.metric == DistanceType.InnerProduct
                    else DistanceType.L2Expanded))
        new_labels = kmeans_balanced.predict(res, km_params, index.centers,
                                             new_vectors.astype(jnp.float32))

        # -- incremental fast path: new rows fit the existing padding.
        # Slot assignment matches the full repack bit-for-bit (old rows
        # keep their slots; new rows land at the running fill ranks),
        # so the two paths produce identical tensors.
        if index.max_list_size > 0 and not index.adaptive_centers:
            sizes_new = index.list_sizes + jax.ops.segment_sum(
                jnp.ones((n_new,), jnp.int32), new_labels,
                num_segments=index.n_lists)
            if padded_extent(sizes_new) <= index.max_list_size:
                lab_np = np.asarray(new_labels)
                fill = np.asarray(index.list_sizes).astype(np.int64)
                ranks = streaming_ranks(lab_np, fill, index.n_lists)
                rows = new_vectors.astype(index.data.dtype)
                row_norms = jnp.sum(
                    jnp.square(rows.astype(jnp.float32)), axis=1)
                scatter = _scatter_extend_donated if donate else _scatter_extend
                data, norms, indices = scatter(
                    index.data, index.data_norms, index.indices, rows,
                    row_norms, new_indices, jnp.asarray(lab_np),
                    jnp.asarray(ranks))
                return dataclasses.replace(
                    index, data=data, data_norms=norms, indices=indices,
                    list_sizes=sizes_new)

        # gather existing rows back to flat form and re-pack everything
        if index.max_list_size > 0:
            old_rows = index.data.reshape(-1, index.dim)
            old_ids = index.indices.reshape(-1)
            old_labels = jnp.repeat(jnp.arange(index.n_lists, dtype=jnp.int32),
                                    index.max_list_size)
            keep = old_ids >= 0
            # compaction happens on host-side sizes; keep as dense select
            all_vecs = jnp.concatenate([old_rows[keep], new_vectors])
            all_ids = jnp.concatenate([old_ids[keep], new_indices])
            all_labels = jnp.concatenate([old_labels[keep], new_labels])
        else:
            all_vecs, all_ids, all_labels = new_vectors, new_indices, new_labels

        sizes = jax.ops.segment_sum(
            jnp.ones((all_vecs.shape[0],), jnp.int32), all_labels,
            num_segments=index.n_lists,
        )
        # one host sync at build/extend time to fix the padded extent
        max_size = padded_extent(sizes)

        # graftledger capacity gate (opt-in, no-op unless installed):
        # the repack is the allocation event — admit its padded layout
        # host-side BEFORE any device tensor materializes, so an index
        # that cannot fit fails as a typed CapacityExceeded instead of
        # a backend OOM
        memwatch.admit(
            memwatch.packed_layout_bytes(
                index.n_lists, int(max_size),
                index.dim * all_vecs.dtype.itemsize),
            "ivf_flat.extend")

        data, norms, indices, sizes = _pack_lists(
            all_vecs, all_ids, all_labels, index.n_lists, max_size,
            sizes=sizes,
        )

        centers = index.centers
        if index.adaptive_centers:
            sums = jax.ops.segment_sum(
                all_vecs.astype(jnp.float32), all_labels,
                num_segments=index.n_lists,
            )
            nonempty = sizes > 0
            centers = jnp.where(
                nonempty[:, None],
                sums / jnp.maximum(sizes, 1)[:, None].astype(jnp.float32),
                centers,
            )
        center_norms = jnp.sum(jnp.square(centers), axis=1)

        return IvfFlatIndex(
            centers=centers,
            center_norms=center_norms,
            data=data,
            data_norms=norms,
            indices=indices,
            list_sizes=sizes,
            metric=index.metric,
            adaptive_centers=index.adaptive_centers,
        )


def build_streaming(
    res: Optional[Resources],
    params: IvfFlatIndexParams,
    source,
    chunk_rows: int = 1 << 20,
    train_rows: int = 1 << 18,
) -> IvfFlatIndex:
    """Build from a dataset that never fully materializes in host memory
    — the 100M+-row ingestion path (role of the reference's
    managed-memory trainset spill, ``ivf_pq_build.cuh:1542-1554``, plus
    its batched extend).

    ``source`` is a :class:`raft_tpu.io.BinDataset` (or any object with
    ``n_rows``/``dim``/``iter_chunks``). Three streamed passes over the
    native prefetch pipeline:

    1. strided trainset sample → balanced-kmeans centers;
    2. per-chunk label predict (device) + list-size count (host);
    3. per-chunk scatter into the padded list tensor with **donated**
       device buffers, so the big tensor is updated in place.
    """
    res = ensure_resources(res)
    n, d = source.n_rows, source.dim
    expect(params.n_lists <= n, "n_lists > n_rows")

    with tracing.range("raft_tpu.ivf_flat.build_streaming"):
        # -- pass 1: trainset sample + centers
        train_rows = max(params.n_lists, min(train_rows, n))
        trainset = sample_trainset(source, train_rows, chunk_rows)
        km_params = KMeansBalancedParams(
            n_iters=params.kmeans_n_iters,
            metric=(DistanceType.InnerProduct
                    if params.metric == DistanceType.InnerProduct
                    else DistanceType.L2Expanded),
            seed=res.seed,
        )
        centers = kmeans_balanced.fit(res, km_params, jnp.asarray(trainset),
                                      params.n_lists)

        # -- pass 2: labels + sizes
        labels_np, sizes_np = label_pass(res, km_params, centers, source,
                                         chunk_rows, params.n_lists)
        max_size = padded_extent(sizes_np)

        # -- pass 3: scatter chunks into donated padded buffers. Indexing
        # is 2-D (list id, rank within list): a flat slot index would
        # overflow int32 (jax default) past 2^31 total slots, well within
        # the billion-row regime this path targets.
        @partial(jax.jit, donate_argnums=(0, 1))
        def scatter_chunk(data, idx, rows, ids, list_ids, ranks):
            return (data.at[list_ids, ranks].set(rows),
                    idx.at[list_ids, ranks].set(ids))

        # graftledger capacity gate (opt-in): the donated padded
        # buffers below are THE allocation of the streaming path —
        # admit them host-side like the repack path does
        memwatch.admit(
            memwatch.packed_layout_bytes(params.n_lists, int(max_size),
                                         d * 4),
            "ivf_flat.build_streaming")
        data = jnp.zeros((params.n_lists, max_size, d), jnp.float32)
        indices = jnp.full((params.n_lists, max_size), -1, jnp.int32)
        fill = np.zeros((params.n_lists,), np.int64)
        for first, chunk in source.iter_chunks(chunk_rows):
            interruptible.yield_()  # cancellation point per chunk
            m = chunk.shape[0]
            lab = labels_np[first : first + m]
            ranks = streaming_ranks(lab, fill, params.n_lists)
            data, indices = scatter_chunk(
                data, indices,
                jnp.asarray(chunk, jnp.float32),
                jnp.asarray(first + np.arange(m, dtype=np.int32)),
                jnp.asarray(lab),
                jnp.asarray(ranks),
            )

        norms = jnp.sum(jnp.square(data), axis=2)
        norms = jnp.where(indices >= 0, norms, jnp.inf)
        return IvfFlatIndex(
            centers=centers,
            center_norms=jnp.sum(jnp.square(centers), axis=1),
            data=data,
            data_norms=norms,
            indices=indices,
            list_sizes=jnp.asarray(sizes_np, jnp.int32),
            metric=DistanceType(params.metric),
            adaptive_centers=params.adaptive_centers,
        )


# ---------------------------------------------------------------------------
# search
# ---------------------------------------------------------------------------


def _search_impl_fn(queries, centers, center_norms, data, data_norms, indices,
                    filter_words, init_d=None, init_i=None,
                    probe_counts=None, n_valid=None, row_probes=None, *,
                    n_probes: int, k: int, metric: DistanceType,
                    coarse_algo: str = "exact",
                    scan_engine: str = "rank"):
    """Coarse select + probe scan with running top-k merge.

    ``init_d``/``init_i`` optionally provide the (q, k) running-state
    storage (values are reset here); the serving path donates them so
    the scan state reuses one HBM allocation across calls (rank-major
    engine only — the list-major engines carry their state in VMEM).

    ``probe_counts`` (graftgauge) optionally provides the donated
    (n_lists,) int32 cumulative probe-frequency plane: the selected
    probe ids scatter-add into it (:func:`raft_tpu.ops.ivf_scan
    .probe_histogram`, pad rows past ``n_valid`` masked out) and the
    updated plane returns as a third output. The search results never
    read it, so enabling accounting cannot perturb them.

    ``row_probes`` (the ragged query-tile front, via
    :func:`_search_ragged_fn`) optionally provides the per-ROW probe
    budget plane of a packed ragged batch: the coarse stage then
    selects at the class cap ``n_probes`` and each row's slots past
    its own budget mask to the sentinel id
    (:func:`raft_tpu.ops.ivf_scan.ragged_probes`) — the scan below is
    char-identical between the bucketed and ragged paths, which IS the
    bit-identity argument. Pad rows carry budget 0, so ``n_valid``
    masking is redundant on this path (every pad slot is already the
    sentinel, which :func:`~raft_tpu.ops.ivf_scan.probe_histogram`
    drops).

    ``scan_engine`` must arrive resolved (``rank``/``pallas``/``xla``,
    via :func:`raft_tpu.ops.ivf_scan.resolve_scan_engine`): it is a jit
    static, so an unresolved ``"auto"`` would fork the compile cache."""
    q, d = queries.shape
    n_lists, max_size, _ = data.shape
    select_min = is_min_close(metric)
    qf = queries.astype(jnp.float32)

    # ---- coarse: ``select_clusters`` (GEMM + select_k over centers)
    ip = jax.lax.dot_general(
        qf, centers, (((1,), (1,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    )
    score = (ip if metric == DistanceType.InnerProduct
             else -(center_norms[None, :] - 2.0 * ip))          # larger=better
    probes = coarse_select(score, n_probes, coarse_algo)
    if row_probes is not None:
        from raft_tpu.ops.ivf_scan import ragged_probes

        probes = ragged_probes(probes, row_probes, n_lists)
    if probe_counts is not None:
        from raft_tpu.ops.ivf_scan import probe_histogram

        probe_counts = probe_histogram(
            probes, probe_counts,
            None if row_probes is not None else n_valid)

    pad_val = jnp.inf if select_min else -jnp.inf

    if scan_engine != "rank":
        # ---- list-major probe scan (ops/ivf_scan): stream each unique
        # probed list once, one dense GEMM per list for the whole tile.
        # The XLA engine reuses the donated running state; the Pallas
        # kernel's state lives in VMEM scratch and ignores it.
        from raft_tpu.ops.ivf_scan import list_major_scan

        best_d, best_i = list_major_scan(
            qf, data, data_norms, indices, probes, filter_words,
            init_d, init_i, k=k, metric=metric, engine=scan_engine,
            interpret=jax.default_backend() != "tpu")
    else:
        # ---- rank-major probe scan: one gathered list + one batched
        # GEMM per probe rank
        def step(carry, rank):
            best_d, best_i = carry
            lists = probes[:, rank]                              # (q,)
            rows = jnp.take(data, lists, axis=0).astype(
                jnp.float32)                                     # (q, m, d)
            row_norms = jnp.take(data_norms, lists, axis=0)      # (q, m)
            row_ids = jnp.take(indices, lists, axis=0)           # (q, m)
            ipr = jax.lax.dot_general(
                rows, qf, (((2,), (1,)), ((0,), (0,))),
                precision=jax.lax.Precision.HIGHEST,
                preferred_element_type=jnp.float32,
            )                                                    # (q, m)
            if metric == DistanceType.InnerProduct:
                dist = jnp.where(row_ids >= 0, ipr, pad_val)
            else:
                dist = row_norms - 2.0 * ipr                     # +||q||^2 later
                dist = jnp.where(row_ids >= 0, dist, pad_val)
            if filter_words is not None:
                bits = test_filter(filter_words, row_ids)
                dist = jnp.where(bits & (row_ids >= 0), dist, pad_val)

            new_d, new_i = merge_topk(best_d, best_i, dist, row_ids, k,
                                      select_min)
            return (new_d, new_i), None

        init = (
            jnp.full((q, k), pad_val, jnp.float32) if init_d is None
            else jnp.full_like(init_d, pad_val),
            jnp.full((q, k), -1, jnp.int32) if init_i is None
            else jnp.full_like(init_i, -1),
        )
        (best_d, best_i), _ = jax.lax.scan(step, init, jnp.arange(n_probes))

    if metric != DistanceType.InnerProduct:
        q_sq = jnp.sum(jnp.square(qf), axis=1, keepdims=True)
        best_d = jnp.where(jnp.isfinite(best_d),
                           jnp.maximum(best_d + q_sq, 0.0), best_d)
        if metric == DistanceType.L2SqrtExpanded:
            best_d = jnp.where(jnp.isfinite(best_d), jnp.sqrt(best_d), best_d)
    if probe_counts is not None:
        return best_d, best_i, probe_counts
    return best_d, best_i


_search_impl = partial(jax.jit, static_argnames=(
    "n_probes", "k", "metric", "coarse_algo", "scan_engine"))(_search_impl_fn)


def _search_ragged_fn(queries, row_probes, centers, center_norms, data,
                      data_norms, indices, filter_words, init_d=None,
                      init_i=None, probe_counts=None, n_valid=None, *,
                      n_probes: int, k: int, metric: DistanceType,
                      scan_engine: str = "xla"):
    """Packed ragged-batch search body — the serving executor's
    one-executable-per-params-class entry (Ragged Paged Attention
    style; see :mod:`raft_tpu.ops.ivf_scan`'s ragged front).

    ``queries`` is a fixed ``(tile, d)`` packed tensor holding several
    requests' rows adjacently (pad rows zero); ``row_probes`` is the
    per-row probe budget (:func:`raft_tpu.ops.ivf_scan
    .ragged_row_probes` — 0 on pad rows). ``n_probes`` and ``k`` are
    the packed batch's CLASS CAPS: the coarse stage selects the top
    ``n_probes`` lists exactly (``lax.top_k`` is a total order, so a
    row's first ``b`` slots equal a solo ``n_probes=b`` selection) and
    each row masks its slots past ``row_probes`` to the sentinel —
    per-request ``n_probes`` resolves through the engines' existing
    membership mask, and per-request ``k`` is a caller-side column
    slice of the total-order top-``k``. Bit-identical per request to
    :func:`_search_impl_fn` on that request alone — structurally: this
    IS :func:`_search_impl_fn` with the ``row_probes`` hook live, so
    the scan code cannot drift between the two paths.

    ``coarse_algo`` is deliberately NOT a knob: only the exact coarse
    top-k has the prefix property the class cap relies on
    (``approx_max_k`` at the cap is not a solo ``approx_max_k`` at the
    request's budget), so approx-coarse requests stay on the bucketed
    path; likewise the rank-major engine has no membership mask to
    resolve per-row budgets through. ``n_valid`` is accepted for
    signature parity but unused — ``row_probes`` already zeroes pad
    rows out of the scan and the histogram."""
    del n_valid
    expect(scan_engine in ("pallas", "xla"),
           "ragged serving needs a membership-masked list-major engine "
           f"(pallas|xla), got {scan_engine!r}")
    return _search_impl_fn(
        queries, centers, center_norms, data, data_norms, indices,
        filter_words, init_d, init_i, probe_counts, None,
        row_probes=row_probes, n_probes=n_probes, k=k, metric=metric,
        coarse_algo="exact", scan_engine=scan_engine)


def search(
    res: Optional[Resources],
    params: IvfFlatSearchParams,
    index: IvfFlatIndex,
    queries,
    k: int,
    sample_filter=None,
    query_tile: int = 4096,
) -> Tuple[jax.Array, jax.Array]:
    """ANN search — ``ivf_flat::search``
    (``detail/ivf_flat_search-inl.cuh:38-210``).

    ``sample_filter``: a Bitset or any :mod:`raft_tpu.neighbors.filters`
    type. Large query sets are processed in ``query_tile`` batches (the
    reference's max_queries=4096 batching loop). The probe-scan engine
    follows ``params.scan_engine`` (resolved per backend/shape by
    :func:`raft_tpu.ops.ivf_scan.resolve_scan_engine`). Returns
    (distances, indices) of shape (q, k); missing slots (when fewer
    than k valid candidates were probed) have index -1."""
    ensure_resources(res)
    queries = jnp.asarray(queries)
    expect(queries.ndim == 2 and queries.shape[1] == index.dim,
           "queries must be (q, dim)")
    expect(index.max_list_size > 0, "index is empty — extend() it first")
    expect(params.coarse_algo in ("exact", "approx"),
           f"coarse_algo must be 'exact' or 'approx', got {params.coarse_algo!r}")
    n_probes = min(params.n_probes, index.n_lists)
    filter_words = resolve_filter_words(sample_filter)
    from raft_tpu.ops.ivf_scan import resolve_scan_engine

    scan_engine = resolve_scan_engine(
        params.scan_engine, data=index.data, filter_words=filter_words, k=k)
    with tracing.range("raft_tpu.ivf_flat.search"):
        def run(qt, fw):
            return _search_impl(
                qt, index.centers, index.center_norms, index.data,
                index.data_norms, index.indices, fw,
                n_probes=n_probes, k=k, metric=index.metric,
                coarse_algo=params.coarse_algo, scan_engine=scan_engine,
            )

        return tile_queries(run, queries, filter_words, query_tile)


# ---------------------------------------------------------------------------
# serialization (versioned npy stream, reference v4 layout analog)
# ---------------------------------------------------------------------------


def save(index: IvfFlatIndex, fh_or_path) -> None:
    """``ivf_flat::serialize`` (``detail/ivf_flat_serialize.cuh:37``)."""
    fh, own = open_maybe_path(fh_or_path, "wb")
    try:
        serialize_scalar(fh, _SERIALIZATION_VERSION, np.int32)
        serialize_scalar(fh, int(index.metric), np.int32)
        serialize_scalar(fh, int(index.adaptive_centers), np.int32)
        serialize_array(fh, index.centers)
        serialize_array(fh, index.data)
        serialize_array(fh, index.indices)
        serialize_array(fh, index.list_sizes)
    finally:
        if own:
            fh.close()


def load(res: Optional[Resources], fh_or_path) -> IvfFlatIndex:
    """``ivf_flat::deserialize``."""
    res = ensure_resources(res)
    fh, own = open_maybe_path(fh_or_path, "rb")
    try:
        check_version(deserialize_scalar(fh), _SERIALIZATION_VERSION, "ivf_flat")
        metric = DistanceType(int(deserialize_scalar(fh)))
        adaptive = bool(deserialize_scalar(fh))
        centers = res.put(deserialize_array(fh))
        data = res.put(deserialize_array(fh))
        indices = res.put(deserialize_array(fh))
        sizes = res.put(deserialize_array(fh))
    finally:
        if own:
            fh.close()
    centers = jnp.asarray(centers)
    data_f = jnp.asarray(data).astype(jnp.float32)
    indices = jnp.asarray(indices)
    norms = jnp.sum(jnp.square(data_f), axis=2)
    norms = jnp.where(indices >= 0, norms, jnp.inf)
    return IvfFlatIndex(
        centers=centers,
        center_norms=jnp.sum(jnp.square(centers), axis=1),
        data=jnp.asarray(data),
        data_norms=norms,
        indices=indices,
        list_sizes=jnp.asarray(sizes),
        metric=metric,
        adaptive_centers=adaptive,
    )
