"""NN-descent k-NN-graph construction — TPU-native re-design of the
reference's GNND (``neighbors/detail/nn_descent.cuh:341`` ``GNND``,
``build:1369``; public API ``neighbors/nn_descent.cuh``; params
``nn_descent_types.hpp:49-55``).

Reference architecture: per-thread bitonic queues, sampled new/old
neighbor lists, and a shared-memory local join that updates both edge
endpoints with atomic queue insertions.

TPU re-design: the algorithm is reformulated as a *dense batched
expansion* — per iteration every node's candidate set is

  (its current neighbors) ∪ (sampled neighbors-of-neighbors)
                          ∪ (sampled reverse neighbors)

and one tiled MXU GEMM scores node-vs-candidates, followed by a
sorted-merge that deduplicates ids and keeps the k best. This replaces
the scatter-heavy local join with gather + GEMM + top-k (all XLA-native,
static shapes); reverse edges are recovered with the same
sort-and-rank packing used by the IVF list builder rather than atomic
counters. Convergence matches NN-descent's: each round propagates
"neighbor of a neighbor is likely a neighbor".
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from raft_tpu.core import tracing
from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.core.validation import expect
from raft_tpu.distance.types import DistanceType
from raft_tpu.neighbors._exact import gathered_distances


@dataclasses.dataclass(frozen=True)
class NNDescentParams:
    """Mirrors ``nn_descent::index_params`` (``nn_descent_types.hpp:49-55``).

    ``graph_degree`` is the output k; ``intermediate_graph_degree`` the
    internal working degree; ``max_iterations``/``termination_threshold``
    bound the EM loop exactly like the reference.

    Reproducibility note: the per-round reverse-edge sampling resolves
    scatter collisions by XLA's (unspecified) duplicate ordering, so
    builds are bit-reproducible only under the same compilation —
    across jax/XLA versions or backends the sampled reverse lists (and
    hence the exact round ``termination_threshold`` triggers on) may
    differ. Graph quality is statistically unaffected.
    """

    graph_degree: int = 64
    intermediate_graph_degree: int = 128
    max_iterations: int = 20
    termination_threshold: float = 0.0001
    metric: DistanceType = DistanceType.L2Expanded
    sample_size: int = 16         # neighbors-of-neighbors fan-out per node
    # 2-hop pairs kept per node per round; 0 = all sample_size². Measured:
    # subsampling trades quality-per-round for round speed at a net loss
    # on random data — keep full unless rounds are latency-bound.
    hop2_sample: int = 0
    seed: int = 0


def _merge_dedup(ids, dists, k: int):
    """Sort candidates by id, mask duplicates, then keep the k smallest
    distances (role of the reference's dedup-on-insert bitonic queue).

    ids/dists: (n, c). Returns (n, k) ids/dists sorted by distance.
    """
    order = jnp.argsort(ids, axis=1, stable=True)
    sids = jnp.take_along_axis(ids, order, axis=1)
    sdists = jnp.take_along_axis(dists, order, axis=1)
    dup = jnp.concatenate(
        [jnp.zeros((ids.shape[0], 1), bool), sids[:, 1:] == sids[:, :-1]], axis=1
    )
    sdists = jnp.where(dup | (sids < 0), jnp.inf, sdists)
    neg_top, pos = jax.lax.top_k(-sdists, k)
    out_ids = jnp.take_along_axis(sids, pos, axis=1)
    out_d = -neg_top
    out_ids = jnp.where(jnp.isfinite(out_d), out_ids, -1)
    return out_ids, out_d


def _distances_to(dataset, node_ids, cand_ids, metric: DistanceType):
    """Exact metric between each node and its candidate rows.

    dataset (n, d); node_ids (t,); cand_ids (t, c) → (t, c) f32.
    """
    x = jnp.take(dataset, node_ids, axis=0)                 # (t, d)
    return gathered_distances(x, dataset, cand_ids, metric)


def _reverse_sample(graph, n: int, r: int):
    """Sampled reverse graph: rev[j] = up to r nodes i with j ∈ graph[i]
    (sort-and-rank packing, no atomics). Deterministic first-r-by-source
    order — used by the one-shot CAGRA optimize, where the n·deg sort is
    amortized. The per-round NN-descent loop uses the cheaper
    :func:`_reverse_sample_random`."""
    deg = graph.shape[1]
    src = jnp.repeat(jnp.arange(n, dtype=jnp.int32), deg)
    dst = graph.reshape(-1)
    valid = dst >= 0
    dst_sort = jnp.where(valid, dst, n)
    order = jnp.argsort(dst_sort, stable=True)
    sdst = dst_sort[order]
    ssrc = src[order]
    first = jnp.searchsorted(sdst, jnp.arange(n), side="left")
    rank = jnp.arange(sdst.shape[0]) - first[jnp.clip(sdst, 0, n - 1)]
    slot = jnp.where((sdst < n) & (rank < r), sdst * r + rank, n * r)
    flat = jnp.full((n * r + 1,), -1, jnp.int32)
    flat = flat.at[slot].set(ssrc, mode="drop")
    return flat[: n * r].reshape(n, r)


@partial(jax.jit, static_argnames=("n", "r"))
def _reverse_sample_random(graph, n: int, r: int, key):
    """Sampled reverse graph without the n·deg sort: each edge scatters
    its source into a RANDOM slot of the destination's row; collisions
    drop edges — which is exactly the sampling this function exists to
    do (the sort dominated per-round build cost).

    To keep rows from running thin (with r slots and in-degree ~ r an
    expected ~1/e of each row stays empty), edges scatter into 2·r slots
    and the row is then compacted to its first r valid entries — a
    per-row width-2r sort, still far cheaper than the global edge sort.
    Which edge survives a colliding slot follows XLA's scatter duplicate
    ordering, so sampled rows are reproducible only per compilation (see
    the :class:`NNDescentParams` note)."""
    r2 = 2 * r
    src = jnp.broadcast_to(
        jnp.arange(n, dtype=jnp.int32)[:, None], graph.shape).reshape(-1)
    dst = graph.reshape(-1)
    slot_r = jax.random.randint(key, dst.shape, 0, r2)
    slot = jnp.where(dst >= 0, dst * r2 + slot_r, n * r2)
    flat = jnp.full((n * r2 + 1,), -1, jnp.int32)
    flat = flat.at[slot].set(src, mode="drop")
    rows = flat[: n * r2].reshape(n, r2)
    order = jnp.argsort(rows < 0, axis=1, stable=True)   # valid-first
    return jnp.take_along_axis(rows, order[:, :r], axis=1)


@partial(jax.jit, static_argnames=("k", "s", "s2", "metric", "tile"))
def _nn_descent_round(dataset, graph, dists, rev, key, k: int, s: int,
                      s2: int, metric: DistanceType, tile: int):
    """One expansion round over all nodes, tiled to bound the gather
    buffer (role of one GNND iteration, ``nn_descent.cuh:1369``)."""
    n = dataset.shape[0]

    # sample s of the current neighbors per node (random rank subset so
    # old/new mix over rounds, like the reference's new/old lists)
    k_rank, k_cols = jax.random.split(key)
    ranks = jax.random.randint(k_rank, (n, s), 0, graph.shape[1])
    sampled = jnp.take_along_axis(graph, ranks, axis=1)      # (n, s)
    # the s² 2-hop pairs may be subsampled to s2 columns per round (the
    # reference's local join also meets only a sampled pair subset);
    # candidate width — hence gather + dedup-sort cost — drops s²/s2-fold
    cols = (None if s2 >= s * s
            else jax.random.permutation(k_cols, s * s)[:s2])

    pad = (-n) % tile
    node_ids = jnp.arange(n + pad, dtype=jnp.int32) % n

    def step(carry, t):
        g, d, changed = carry
        nid = jax.lax.dynamic_slice_in_dim(node_ids, t * tile, tile)
        cur_ids = jnp.take(g, nid, axis=0)                   # (t, k)
        cur_d = jnp.take(d, nid, axis=0)
        # neighbors-of-(sampled)-neighbors
        hop1 = jnp.take(sampled, nid, axis=0)                # (t, s)
        if cols is None:
            hop2 = jnp.take(sampled, jnp.clip(hop1, 0), axis=0)  # (t, s, s)
            hop2 = jnp.where((hop1 >= 0)[:, :, None], hop2,
                             -1).reshape(tile, -1)
        else:
            # gather only the kept (i, j) pairs: hop2[t, m] =
            # sampled[hop1[t, cols[m] // s], cols[m] % s]
            h1c = jnp.take(hop1, cols // s, axis=1)          # (t, s2)
            flat = jnp.clip(h1c, 0) * s + (cols % s)[None, :]
            hop2 = jnp.take(sampled.reshape(-1), flat)       # (t, s2)
            hop2 = jnp.where(h1c >= 0, hop2, -1)
        rcand = jnp.take(rev, nid, axis=0)                   # (t, r)
        cand = jnp.concatenate([hop1, hop2, rcand], axis=1)
        cand = jnp.where(cand == nid[:, None], -1, cand)     # no self loops
        cd = _distances_to(dataset, nid, cand, metric)
        cd = jnp.where(cand >= 0, cd, jnp.inf)
        all_ids = jnp.concatenate([cur_ids, cand], axis=1)
        all_d = jnp.concatenate([cur_d, cd], axis=1)
        new_ids, new_d = _merge_dedup(all_ids, all_d, g.shape[1])
        changed = changed + jnp.sum(new_ids != cur_ids)
        g = g.at[nid].set(new_ids)
        d = d.at[nid].set(new_d)
        return (g, d, changed), None

    n_tiles = (n + pad) // tile
    (graph, dists, changed), _ = jax.lax.scan(
        step, (graph, dists, jnp.zeros((), jnp.int32)), jnp.arange(n_tiles)
    )
    return graph, dists, changed


def build(
    res: Optional[Resources],
    params: NNDescentParams,
    dataset,
    return_distances: bool = False,
    init_graph=None,
):
    """Build an approximate k-NN graph — ``nn_descent::build``.

    Returns graph (n, graph_degree) int32, optionally with distances.
    Self-edges are excluded (reference semantics: the graph used by CAGRA
    holds *other* nodes).

    ``init_graph`` — optional (n, w) int32 candidate ids (-1 = empty) to
    seed the working graph instead of pure random init; rows narrower
    than ``intermediate_graph_degree`` are topped up with random ids.
    With a good seed graph (e.g. the cluster-join builder) one or two
    descent rounds replace the usual ~20.
    """
    res = ensure_resources(res)
    dataset = jnp.asarray(dataset)
    expect(dataset.ndim == 2, "dataset must be (n, d)")
    n = dataset.shape[0]
    k = params.intermediate_graph_degree
    expect(params.graph_degree <= k,
           "graph_degree must be <= intermediate_graph_degree")
    expect(k < n, "intermediate_graph_degree must be < n_rows")
    expect(params.metric in (DistanceType.L2Expanded,
                             DistanceType.L2SqrtExpanded,
                             DistanceType.InnerProduct),
           f"nn_descent supports L2/InnerProduct, got {params.metric!r}")
    metric = (DistanceType.InnerProduct
              if params.metric == DistanceType.InnerProduct
              else DistanceType.L2Expanded)
    ds32 = dataset.astype(jnp.float32)

    with tracing.range("raft_tpu.nn_descent.build"):
        key = jax.random.key(params.seed)
        k_init, key = jax.random.split(key)
        # random init (reference: random sampling into per-node queues)
        init = jax.random.randint(k_init, (n, k), 0, n - 1, jnp.int32)
        init = jnp.where(init >= jnp.arange(n)[:, None], init + 1, init)
        if init_graph is not None:
            seed_ids = jnp.asarray(init_graph, jnp.int32)
            expect(seed_ids.ndim == 2 and seed_ids.shape[0] == n,
                   "init_graph must be (n, w)")
            w = min(seed_ids.shape[1], k)
            merged = jnp.concatenate([seed_ids[:, :w], init[:, w:]], axis=1)
            # top up -1 padding inside seed rows with the random ids so
            # sparse seeds never start from a thinner candidate pool
            # than plain random init
            merged = jnp.where(merged >= 0, merged, init)
            init = jnp.where(merged == jnp.arange(n)[:, None], -1, merged)
        tile = max(64, min(1024, (1 << 22) // max(k * 4, 1)))
        # init distances through the same tiled path the rounds use, so
        # the (tile, k, d) gather buffer — not an (n, k, d) cube — is the
        # peak allocation at any n
        d0_parts = [
            _distances_to(
                ds32,
                jnp.arange(s, min(s + tile, n), dtype=jnp.int32),
                init[s : s + tile],
                metric,
            )
            for s in range(0, n, tile)
        ]
        graph, dists = _merge_dedup(init, jnp.concatenate(d0_parts), k)

        s = min(params.sample_size, k)
        s2 = s * s if params.hop2_sample <= 0 else min(params.hop2_sample,
                                                       s * s)
        total = n * k
        for it in range(params.max_iterations):
            k_it = jax.random.fold_in(key, it)
            k_rev, k_round = jax.random.split(k_it)
            rev = _reverse_sample_random(graph, n, s, k_rev)
            graph, dists, changed = _nn_descent_round(
                ds32, graph, dists, rev, k_round, k, s, s2, metric, tile
            )
            if float(changed) / total < params.termination_threshold:
                break

        out = graph[:, : params.graph_degree]
        if not return_distances:
            return out
        out_d = dists[:, : params.graph_degree]
        if params.metric == DistanceType.InnerProduct:
            out_d = -out_d
        elif params.metric == DistanceType.L2SqrtExpanded:
            out_d = jnp.sqrt(jnp.maximum(out_d, 0.0))
        return out, out_d
