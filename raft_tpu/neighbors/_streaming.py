"""Shared passes for the streaming index builds (flat / PQ / BQ) —
the three-pass structure over a :class:`raft_tpu.io.BinDataset`:
strided trainset sample, per-chunk label predict + size count, then
each index's own encode+scatter pass (whose rank bookkeeping is
:func:`raft_tpu.neighbors._packing.streaming_ranks`)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from raft_tpu.cluster import kmeans_balanced
from raft_tpu.core import interruptible


def sample_trainset(source, train_rows: int, chunk_rows: int) -> np.ndarray:
    """Pass 1: a strided ``train_rows``-row sample spanning the whole
    dataset, assembled chunk by chunk (the stride keeps phase across
    chunk boundaries). Each chunk is a cancellation point
    (``interruptible.yield_``, ``core/interruptible.hpp:83`` role)."""
    n = source.n_rows
    stride = max(1, n // train_rows)
    parts = []
    for first, chunk in source.iter_chunks(chunk_rows):
        interruptible.yield_()
        offset = (-first) % stride
        parts.append(np.asarray(chunk[offset::stride], np.float32))
    return np.concatenate(parts)[:train_rows]


def label_pass(res, km_params, centers, source, chunk_rows: int,
               n_lists: int):
    """Pass 2: per-chunk nearest-center labels (device) + per-list
    population counts (host). Returns ``(labels_np, sizes_np)``.
    Each chunk is a cancellation point."""
    n = source.n_rows
    labels_np = np.empty((n,), np.int32)
    for first, chunk in source.iter_chunks(chunk_rows):
        interruptible.yield_()
        lab = kmeans_balanced.predict(
            res, km_params, centers, jnp.asarray(chunk, jnp.float32))
        labels_np[first : first + chunk.shape[0]] = np.asarray(lab)
    sizes_np = np.bincount(labels_np, minlength=n_lists)
    return labels_np, sizes_np
