"""CAGRA — graph-based ANN, TPU-native re-design of
``raft::neighbors::cagra`` (``cagra_types.hpp:131`` index, params
``:54-111``; build ``detail/cagra/cagra_build.cuh:44-123``; optimize
``detail/cagra/graph_core.cuh:320``; search ``detail/cagra/cagra_search.cuh:105``).

Reference architecture: k-NN graph from batched IVF-PQ searches (+refine)
or NN-descent; graph *optimize* = 2-hop detour counting (``kern_prune``,
``graph_core.cuh:128``) + reverse-edge augmentation (``kern_make_rev_graph
:191``); search = persistent CUDA kernels walking the graph with a
random-hash visited table, per-CTA bitonic top-M and three kernel
families (single-cta / multi-cta / multi-kernel).

TPU re-design:

- **build**: same two graph sources (IVF-PQ batches + refine, or the
  dense NN-descent in :mod:`raft_tpu.neighbors.nn_descent`).
- **optimize**: detour counting is a *dense batched tensor op* — for a
  node tile, gather the neighbor-of-neighbor id cube (t, K, K) and count
  rank-lower 2-hop matches with one broadcast compare; no atomics. The
  reverse graph uses sort-and-rank packing.
- **search**: one jitted ``lax.while_loop`` per query batch ("beam
  search" formulation): an itopk buffer (ids, dists, explored flags) is
  expanded ``search_width`` parents at a time; candidate scoring is a
  batched gather + MXU contraction over all queries at once. Instead of
  the GPU's visited hashmap, merging deduplicates ids with
  buffer-copy-priority, which both dedups and preserves explored flags —
  re-proposed candidates can never re-enter unexplored, so termination
  ("all buffer entries explored") is exact. Queries are tiled host-side;
  every shape is static.
- **seeding**: every beam starts from a build-time IVF-coarse *seed
  plane* (balanced k-means centers + a padded member table, serialized
  with the index): a query probes its nearest centroids and the beam
  opens from the best member rows — a pure function of query CONTENT,
  never of batch position, so blocks concatenate and CAGRA serves
  through the executor's batched + ragged plans like every other
  family. Indexes without the plane (``from_graph``, hnswlib loads)
  fall back to the query-aware strided pool, which is content-pure too.
- **BQ-coded traversal** (opt-in ``bq_bits`` at build): gathered graph
  neighbors are first scored by the RaBitQ XOR+popcount estimate
  against a packed per-row code plane and only estimate-survivors are
  exactly reranked — ``ops/bq_scan``'s estimate-then-rerank discipline
  on the beam's neighbor-gather path, in BOTH engines (the Pallas
  kernel skips the raw-row DMA for survivor-free batches).
"""

from __future__ import annotations

import dataclasses
import enum
from functools import partial
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core import tracing
from raft_tpu.core.logger import warn as _log_warn
from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.core.serialize import (
    check_version,
    deserialize_array,
    deserialize_scalar,
    open_maybe_path,
    serialize_array,
    serialize_scalar,
)
from raft_tpu.core.validation import expect
from raft_tpu.distance.types import DistanceType
from raft_tpu.neighbors import ivf_pq as ivf_pq_mod
from raft_tpu.neighbors import nn_descent as nn_descent_mod
from raft_tpu.neighbors._exact import dedup_candidate_mask, gathered_distances
from raft_tpu.neighbors.filters import resolve_filter_words, test_filter
from raft_tpu.neighbors.nn_descent import _reverse_sample
from raft_tpu.neighbors.refine import refine

_SERIALIZATION_VERSION = 5


class BuildAlgo(enum.Enum):
    """Mirrors ``cagra::graph_build_algo`` (``cagra_types.hpp``), plus
    the TPU-first CLUSTER_JOIN builder (merged within-cluster brute
    force — see :mod:`raft_tpu.neighbors.cluster_join`)."""

    IVF_PQ = "ivf_pq"
    NN_DESCENT = "nn_descent"
    CLUSTER_JOIN = "cluster_join"


@dataclasses.dataclass(frozen=True)
class CagraIndexParams:
    """Mirrors ``cagra::index_params`` (``cagra_types.hpp:54-111``)."""

    metric: DistanceType = DistanceType.L2Expanded
    intermediate_graph_degree: int = 128
    graph_degree: int = 64
    build_algo: BuildAlgo = BuildAlgo.IVF_PQ
    nn_descent_niter: int = 20
    # IVF-PQ graph-build knobs (reference auto-derives; exposed here)
    ivf_pq_n_lists: int = 0       # 0 → auto sqrt(n)
    ivf_pq_n_probes: int = 0      # 0 → auto
    refine_rate: float = 2.0      # gpu_top_k = degree * refine_rate
    # dataset storage dtype for the built index: bf16 halves both the
    # per-iteration gather bytes (XLA engine) and the VMEM residency
    # (Pallas engine: 500k×128 bf16 fits where f32 does not); build
    # math stays f32. Same contract as brute_force.build's
    # storage_dtype: None keeps the input dtype; accepts a dtype or
    # its name (JSON configs pass "bfloat16").
    storage_dtype: Optional[Any] = None
    # coarse seed plane: number of balanced-k-means lists trained at
    # build time for IVF-coarse beam seeding. 0 → auto (≈ sqrt(n),
    # capped at 1024). The plane is always built — it is the batching-
    # invariant seed source — and serializes with the index.
    seed_n_lists: int = 0
    # BQ-coded traversal plane: RaBitQ code bits per dimension level
    # (1..4) packed into the per-row record plane the beam's
    # estimate-then-rerank phase scores against. 0 (default) skips the
    # plane; traversal then always reranks exactly.
    bq_bits: int = 0


@dataclasses.dataclass(frozen=True)
class CagraSearchParams:
    """Mirrors ``cagra::search_params`` (``cagra_types.hpp``): ``itopk_size``
    is the retained candidate buffer, ``search_width`` the number of
    parents expanded per iteration, ``max_iterations`` 0 → auto."""

    itopk_size: int = 64
    search_width: int = 1
    max_iterations: int = 0
    num_random_samplings: int = 1
    query_tile: int = 256
    # Rows scored per query before the beam opens: in "coarse" mode the
    # member rows of ~ceil(seed_pool / list_cap) probed lists, in
    # "pool" mode a strided dataset sample of this width. 0 → auto
    # (max(256, 4·n_seeds)). The coarse plane reaches the pool-mode
    # entry quality at ~8× smaller pools — the probed lists are the
    # query's own neighborhoods, not a blind stride.
    seed_pool: int = 0
    # "coarse": IVF-coarse seeding from the build-time seed plane
    # (requires it); "pool": the query-aware strided pool; "auto":
    # coarse when the index carries the plane, else pool. Every mode is
    # a pure function of query content — batching-invariant.
    seed_mode: str = "auto"
    # "on": estimate-then-rerank neighbor scoring against the build-time
    # BQ record plane (requires bq_bits ≥ 1 at build); "off": always
    # rerank exactly; "auto": on when the plane exists (and, on the
    # kernel path, fits the VMEM budget).
    bq_traversal: str = "auto"
    # RaBitQ margin multiplier for the traversal prune — same role as
    # IvfBqSearchParams.epsilon (3σ of the estimator error model).
    bq_epsilon: float = 3.0
    # "pallas": the one-dispatch VMEM-resident beam-search kernel
    # (ops/beam_search, role of the reference's persistent single-CTA
    # kernel); "xla": the lax.while_loop path; "auto": pallas on TPU
    # when its constraints hold (supported metric, no filter,
    # dim % 128 == 0, dataset fits the VMEM budget), else xla.
    algo: str = "auto"


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass(frozen=True)
class CagraIndex:
    """Dataset + fixed-degree neighbor graph (``cagra::index``,
    ``cagra_types.hpp:131``; the dataset is stored padded/strided in the
    reference — on TPU a plain dense (n, d) array)."""

    dataset: jax.Array      # (n, d)
    graph: jax.Array        # (n, graph_degree) int32
    metric: DistanceType
    # IVF-coarse seed plane (built by :func:`build`, None on directly
    # assembled indexes): balanced-k-means centers + the -1-padded
    # member table mapping each list to its dataset rows
    seed_centers: Optional[jax.Array] = None    # (n_lists, d) f32
    seed_members: Optional[jax.Array] = None    # (n_lists, cap) int32
    # BQ traversal plane (built when CagraIndexParams.bq_bits ≥ 1):
    # the pinned rotation, the rotated global center row, and the
    # packed per-row record plane of ops/bq_scan.pack_bq_records
    bq_rotation: Optional[jax.Array] = None     # (dim_ext, d) f32
    bq_center_rot: Optional[jax.Array] = None   # (1, dim_ext) f32
    bq_records: Optional[jax.Array] = None      # (T, PW) int32
    bq_bits: int = 0

    def tree_flatten(self):
        return ((self.dataset, self.graph, self.seed_centers,
                 self.seed_members, self.bq_rotation, self.bq_center_rot,
                 self.bq_records),
                (self.metric, self.bq_bits))

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], aux[0], *children[2:],
                   bq_bits=aux[1])

    @property
    def size(self) -> int:
        return self.dataset.shape[0]

    @property
    def dim(self) -> int:
        return self.dataset.shape[1]

    @property
    def graph_degree(self) -> int:
        return self.graph.shape[1]

    @property
    def padded_graph(self) -> jax.Array:
        """Adjacency rows padded to the Pallas kernel's 128-lane DMA
        unit, computed lazily and cached on the index so repeated
        ``search()`` calls don't re-copy the graph."""
        cached = self.__dict__.get("_padded_graph")
        if cached is None:
            from raft_tpu.ops.beam_search import pad_graph

            cached = pad_graph(self.graph)
            object.__setattr__(self, "_padded_graph", cached)
        return cached


# ---------------------------------------------------------------------------
# build
# ---------------------------------------------------------------------------


def build_knn_graph(
    res: Optional[Resources],
    dataset,
    k: int,
    metric: DistanceType = DistanceType.L2Expanded,
    n_lists: int = 0,
    n_probes: int = 0,
    refine_rate: float = 2.0,
    batch: int = 1024,
) -> jax.Array:
    """Intermediate k-NN graph via batched IVF-PQ self-search + refine —
    ``detail/cagra/cagra_build.cuh:44-123`` (1024-query batches at
    ``:105``). Self-matches are dropped; returns (n, k) int32."""
    res = ensure_resources(res)
    dataset = jnp.asarray(dataset)
    n, dim = dataset.shape
    n_lists = n_lists or max(8, min(n // 39 + 1, int(np.sqrt(n) * 2)))
    n_probes = n_probes or max(8, n_lists // 10)
    gpu_k = max(k + 1, int((k + 1) * refine_rate))

    # 4-bit codes at doubled pq_dim: equal code bytes and measured-equal
    # graph recall vs the 8-bit default, but the scoring rides the
    # masked-sum select path (~6x faster on TPU) — and refine re-ranks
    # with exact distances anyway
    params = ivf_pq_mod.IvfPqIndexParams(
        metric=metric, n_lists=n_lists,
        pq_bits=4,
        pq_dim=min(dim, 2 * ivf_pq_mod._auto_pq_dim(dim)),
        kmeans_trainset_fraction=min(1.0, 10240 / max(n, 1) + 0.1),
    )
    index = ivf_pq_mod.build(res, params, dataset)
    sp = ivf_pq_mod.IvfPqSearchParams(n_probes=n_probes)

    out = []
    for start in range(0, n, batch):
        q = dataset[start : start + batch]
        _, cand = ivf_pq_mod.search(res, sp, index, q, gpu_k)
        _, idx = refine(res, dataset, q, cand, k + 1, metric)
        # drop self-hits: mask rows equal to the query's own id
        own = jnp.arange(start, start + q.shape[0], dtype=jnp.int32)[:, None]
        keep = idx != own
        # stable-compact each row to k entries (self-hit, if found, removed)
        pos = jnp.where(keep, jnp.cumsum(keep, axis=1) - 1, k + 1)
        row = jnp.full((q.shape[0], k + 2), -1, jnp.int32)
        row = row.at[jnp.arange(q.shape[0])[:, None], pos].set(idx, mode="drop")
        out.append(row[:, :k])
    return jnp.concatenate(out, axis=0)


@partial(jax.jit, static_argnames=("tile", "method"))
def _detour_counts(graph, tile: int, method: str = "auto"):
    """2-hop detour count per edge (role of ``kern_prune``,
    ``graph_core.cuh:128``): edge (i → g[i,r]) is detourable through the
    higher-ranked neighbor g[i,l] (l < r) when g[i,r] ∈ graph[g[i,l]].

    Two membership tests, picked per backend (the reference amortizes
    the same lookup with shared-memory hashing):

    - ``compare``: O(k³)-per-node broadcast equality — pure VPU
      compares, no gathers/sorts; the right trade on TPU where lane
      gathers serialize onto the scalar core.
    - ``search``: sort each neighbor row once + binary-search all edges
      into it — O(k² log k) per node; wins on CPU/GPU where gathers
      are cheap.
    """
    if method == "auto":
        method = "compare" if jax.default_backend() == "tpu" else "search"
    n, k = graph.shape
    pad = (-n) % tile
    node_ids = jnp.arange(n + pad, dtype=jnp.int32) % n
    sentinel = jnp.iinfo(jnp.int32).max
    rank = jnp.arange(k, dtype=jnp.int32)

    def step(_, t):
        nid = jax.lax.dynamic_slice_in_dim(node_ids, t * tile, tile)
        g = jnp.take(graph, nid, axis=0)                       # (t, k)
        nbrs = jnp.take(graph, jnp.clip(g, 0), axis=0)         # (t, k, k)
        # rows of invalid parents (or invalid entries) can match nothing
        nbrs = jnp.where((g >= 0)[:, :, None] & (nbrs >= 0), nbrs,
                         sentinel)
        if method == "search":
            snbrs = jnp.sort(nbrs, axis=2)
            pos = jax.vmap(jax.vmap(jnp.searchsorted, (0, None)))(snbrs, g)
            hit = jnp.take_along_axis(
                snbrs, jnp.clip(pos, 0, k - 1), axis=2
            ) == g[:, None, :]                                 # (t, l, r)
            ok = ((rank[None, :, None] < rank[None, None, :])
                  & (g >= 0)[:, None, :])
            return None, jnp.sum((hit & ok).astype(jnp.int32), axis=1)

        # "compare": accumulate over l so the intermediate stays
        # (t, k, k) instead of a (t, k, k, k) broadcast cube
        def count_l(l, counts):
            eq = nbrs[:, l, :, None] == g[:, None, :]          # (t, m, r)
            match = jnp.any(eq, axis=1) & (g >= 0)             # (t, r)
            return counts + (match & (rank > l)[None, :]).astype(jnp.int32)

        counts = jax.lax.fori_loop(
            0, k, count_l, jnp.zeros((tile, k), jnp.int32)
        )
        return None, counts

    n_tiles = (n + pad) // tile
    _, out = jax.lax.scan(step, None, jnp.arange(n_tiles))
    return out.reshape(-1, k)[:n]


@partial(jax.jit, static_argnames=("fwd_keep",))
def _select_forward(graph, detours, fwd_keep: int):
    """The fwd_keep lowest-detour edges per node, rank-order preserved
    (ties broken toward closer neighbors)."""
    k = graph.shape[1]
    rank = jnp.arange(k, dtype=jnp.int32)[None, :]
    score = jnp.where(graph >= 0, detours * k + rank, jnp.iinfo(jnp.int32).max)
    _, pos = jax.lax.top_k(-score, fwd_keep)
    return jnp.take_along_axis(graph, jnp.sort(pos, axis=1), axis=1)


@partial(jax.jit, static_argnames=("out_degree",))
def _merge_forward_reverse(graph, fwd, rev, out_degree: int):
    """Merge the kept forward edges with reverse edges and leftover
    forward edges, dedup'd by priority (role of ``graph_core.cuh``
    ``optimize:320`` + ``kern_make_rev_graph:191``)."""
    n, k = graph.shape

    # candidates in priority order: kept-forward, reverse, remaining-forward
    cand = jnp.concatenate([fwd, rev, graph], axis=1)
    c = cand.shape[1]
    prio = jnp.arange(c, dtype=jnp.int32)[None, :]
    prio = jnp.where(cand >= 0, prio, c)
    order = jnp.argsort(cand, axis=1, stable=True)      # groups equal ids
    sid = jnp.take_along_axis(cand, order, axis=1)
    sprio = jnp.take_along_axis(prio, order, axis=1)
    dup = jnp.concatenate(
        [jnp.zeros((n, 1), bool), sid[:, 1:] == sid[:, :-1]], axis=1
    )
    sprio = jnp.where(dup | (sid < 0), c, sprio)
    _, best = jax.lax.top_k(-sprio, out_degree)
    keep_ids = jnp.take_along_axis(sid, best, axis=1)
    keep_prio = jnp.take_along_axis(sprio, best, axis=1)
    # order final rows by priority so closest-first ordering survives
    reorder = jnp.argsort(keep_prio, axis=1, stable=True)
    out = jnp.take_along_axis(keep_ids, reorder, axis=1)
    return jnp.where(jnp.take_along_axis(keep_prio, reorder, axis=1) < c,
                     out, -1)


def optimize(
    res: Optional[Resources],
    knn_graph,
    out_degree: int,
    tile: int = 128,
) -> jax.Array:
    """Prune an intermediate k-NN graph to a fixed-degree search graph —
    ``cagra::optimize`` (``graph_core.cuh:320``)."""
    ensure_resources(res)
    knn_graph = jnp.asarray(knn_graph, jnp.int32)
    n, k = knn_graph.shape
    expect(out_degree <= k, "out_degree must be <= input graph degree")
    with tracing.range("raft_tpu.cagra.optimize"):
        detours = _detour_counts(knn_graph, tile)
        fwd = _select_forward(knn_graph, detours, out_degree // 2)
        rev = _reverse_sample(fwd, n, out_degree - out_degree // 2)
        return _merge_forward_reverse(knn_graph, fwd, rev, out_degree)


def _auto_seed_lists(n: int) -> int:
    """Default coarse-plane list count: ≈ sqrt(n) puts ~sqrt(n) rows in
    each list, so one probed list already carries a beam's worth of
    entry candidates; 1024 caps the center-scoring GEMM."""
    return max(1, min(1024, int(round(np.sqrt(max(n, 1))))))


def _build_seed_plane(res, dataset, metric: DistanceType, n_lists: int):
    """Train the IVF-coarse seed plane: balanced-k-means centers plus a
    dense -1-padded member table (list → dataset rows). Always built by
    :func:`build` — it is the batching-invariant seed source the
    serving path's block-concatenation rests on."""
    from raft_tpu.cluster import kmeans_balanced

    x = jnp.asarray(dataset).astype(jnp.float32)
    n = x.shape[0]
    n_lists = min(n_lists or _auto_seed_lists(n), n)
    km = kmeans_balanced.KMeansBalancedParams(
        metric=DistanceType(metric), seed=res.seed)
    centers, labels, sizes = kmeans_balanced.build_clusters(
        res, km, x, n_lists)
    labels_np = np.asarray(labels)
    cap = max(1, int(np.asarray(sizes).max()))
    members = np.full((n_lists, cap), -1, np.int32)
    order = np.argsort(labels_np, kind="stable")
    sl = labels_np[order]
    ranks = np.arange(n) - np.searchsorted(sl, sl)
    members[sl, ranks] = order
    # drop empty lists (degenerate data collapses k-means): a probed
    # empty list would contribute zero valid seeds, and a query whose
    # every probe lands empty would open the beam with no entries
    keep = np.flatnonzero(np.asarray(sizes) > 0)
    if keep.size < n_lists:
        centers = jnp.asarray(np.asarray(centers)[keep])
        members = members[keep]
    return centers.astype(jnp.float32), jnp.asarray(members)


def _build_bq_plane(dataset, bits: int, seed: int):
    """Encode the dataset into the packed BQ traversal plane: the
    ivf_bq pinned rotation + per-row RaBitQ codes about the GLOBAL
    dataset mean (one center, so the beam estimator needs no per-list
    bookkeeping), packed per-row by
    :func:`raft_tpu.ops.bq_scan.pack_bq_records`."""
    from raft_tpu.neighbors.ivf_bq import _encode, _pinned_rotation
    from raft_tpu.ops.bq_scan import pack_bq_records

    x = jnp.asarray(dataset).astype(jnp.float32)
    d = x.shape[1]
    dim_ext = -(-d // 32) * 32
    rotation = _pinned_rotation(seed, dim_ext, d)
    center = jnp.mean(x, axis=0, keepdims=True)
    center_rot = jnp.einsum("od,ed->oe", center, rotation,
                            precision=jax.lax.Precision.HIGHEST)
    rot = jnp.einsum("nd,ed->ne", x - center, rotation,
                     precision=jax.lax.Precision.HIGHEST)
    codes, rnorm, cfac, errw = _encode(rot, bits)
    return rotation, center_rot, pack_bq_records(codes, rnorm, cfac, errw)


def build(
    res: Optional[Resources],
    params: CagraIndexParams,
    dataset,
) -> CagraIndex:
    """knn-graph + optimize — ``cagra::build`` (``cagra.cuh:296-331``).

    Examples
    --------
    >>> import numpy as np
    >>> from raft_tpu.neighbors import cagra
    >>> x = np.random.default_rng(0).standard_normal(
    ...     (128, 16)).astype(np.float32)
    >>> idx = cagra.build(None, cagra.CagraIndexParams(
    ...     graph_degree=8, intermediate_graph_degree=16,
    ...     build_algo=cagra.BuildAlgo.NN_DESCENT), x)
    >>> _, i = cagra.search(None, cagra.CagraSearchParams(itopk_size=16),
    ...                     idx, x[:4], 1)
    >>> np.asarray(i).ravel().tolist()   # each point is its own NN
    [0, 1, 2, 3]
    """
    res = ensure_resources(res)
    dataset = jnp.asarray(dataset)
    expect(dataset.ndim == 2, "dataset must be (n, d)")
    expect(params.metric in (DistanceType.L2Expanded,
                             DistanceType.L2SqrtExpanded,
                             DistanceType.InnerProduct),
           f"cagra supports L2/InnerProduct, got {params.metric!r}")
    if params.storage_dtype is not None:   # fail fast, before the build
        expect(jnp.dtype(params.storage_dtype) in
               (jnp.dtype(jnp.float32), jnp.dtype(jnp.bfloat16)),
               f"storage_dtype must be float32/bfloat16, got "
               f"{params.storage_dtype!r}")
        params = dataclasses.replace(
            params, storage_dtype=jnp.dtype(params.storage_dtype))
    n = dataset.shape[0]
    ideg = min(params.intermediate_graph_degree, n - 1)
    if ideg < params.intermediate_graph_degree:
        _log_warn(
            "Intermediate graph degree cannot be larger than dataset "
            "size, reducing it to %d", ideg)
    odeg = min(params.graph_degree, ideg)
    if odeg < params.graph_degree:
        _log_warn(
            "Graph degree (%d) cannot be larger than intermediate graph "
            "degree (%d), reducing graph_degree", params.graph_degree, ideg)

    with tracing.range("raft_tpu.cagra.build"):
        if params.build_algo == BuildAlgo.CLUSTER_JOIN:
            from raft_tpu.neighbors import cluster_join

            cj = cluster_join.ClusterJoinParams(
                graph_degree=ideg,
                metric=params.metric,
                seed=res.seed,
            )
            knn_graph = cluster_join.build(res, cj, dataset)
        elif params.build_algo == BuildAlgo.NN_DESCENT:
            nnd = nn_descent_mod.NNDescentParams(
                graph_degree=ideg,
                intermediate_graph_degree=min(int(ideg * 1.5), n - 1),
                max_iterations=params.nn_descent_niter,
                metric=params.metric,
                seed=res.seed,
            )
            knn_graph = nn_descent_mod.build(res, nnd, dataset)
        else:
            knn_graph = build_knn_graph(
                res, dataset, ideg, params.metric,
                params.ivf_pq_n_lists, params.ivf_pq_n_probes,
                params.refine_rate,
            )
        graph = optimize(res, knn_graph, odeg)
        seed_centers, seed_members = _build_seed_plane(
            res, dataset, params.metric, params.seed_n_lists)
        bq_rotation = bq_center_rot = bq_records = None
        if params.bq_bits:
            expect(1 <= params.bq_bits <= 4,
                   f"bq_bits must be 0 (off) or 1..4, got {params.bq_bits}")
            bq_rotation, bq_center_rot, bq_records = _build_bq_plane(
                dataset, params.bq_bits, res.seed)
        stored = dataset
        if params.storage_dtype is not None:
            stored = jnp.asarray(dataset).astype(params.storage_dtype)
        return CagraIndex(
            dataset=res.put(stored), graph=graph,
            metric=DistanceType(params.metric),
            seed_centers=res.put(seed_centers),
            seed_members=res.put(seed_members),
            bq_rotation=None if bq_rotation is None else res.put(bq_rotation),
            bq_center_rot=(None if bq_center_rot is None
                           else res.put(bq_center_rot)),
            bq_records=None if bq_records is None else res.put(bq_records),
            bq_bits=params.bq_bits)


def from_graph(res, dataset, graph,
               metric: DistanceType = DistanceType.L2Expanded) -> CagraIndex:
    """Assemble an index from a prebuilt graph (reference's index
    constructor taking dataset + knn_graph views)."""
    res = ensure_resources(res)
    return CagraIndex(res.put(jnp.asarray(dataset)),
                      res.put(jnp.asarray(graph, jnp.int32)),
                      DistanceType(metric))


# ---------------------------------------------------------------------------
# search
# ---------------------------------------------------------------------------


def _buffer_merge(ids, dists, explored, cand_ids, cand_d, L: int):
    """Merge candidates into the itopk buffer with id-dedup where the
    buffer copy wins — preserving explored flags (the hash-free visited
    mechanism; see module docstring).

    Dedup is a broadcast equality mask (candidate-vs-buffer (C, L) +
    candidate-vs-earlier-candidate (C, C)) feeding one ``top_k`` — no
    argsort in the search hot loop (TPU sorts have poor constants; the
    masks are cheap VPU compares)."""
    # buffer copy wins over duplicates; first proposal wins among
    # candidates (shared helper — the Pallas engine uses the same one)
    buf_ids = jnp.where(ids >= 0, ids, -2)               # -2 ≠ any cand -1
    dup = dedup_candidate_mask(cand_ids, buf_ids)
    cd = jnp.where(dup | (cand_ids < 0), jnp.inf, cand_d)

    all_d = jnp.concatenate([dists, cd], axis=1)
    all_i = jnp.concatenate([ids, cand_ids], axis=1)
    all_e = jnp.concatenate(
        [explored, jnp.zeros(cand_ids.shape, bool)], axis=1
    )
    neg, pos = jax.lax.top_k(-all_d, L)
    return (
        jnp.take_along_axis(all_i, pos, axis=1),
        -neg,
        jnp.take_along_axis(all_e, pos, axis=1),
    )


@partial(jax.jit, static_argnames=("pool", "n_seeds", "metric"))
def _pooled_seeds(dataset, queries, pool: int, n_seeds: int,
                  metric: DistanceType):
    """Best ``n_seeds`` of a strided ``pool``-row sample per query — a
    one-GEMM routing stage replacing uniform-random seeding."""
    n = dataset.shape[0]
    stride = -(-n // pool)  # ceil: the pool must span the whole id range
    cand = (jnp.arange(pool, dtype=jnp.int32) * stride) % n
    qf = queries.astype(jnp.float32)
    d = gathered_distances(
        qf, dataset, jnp.broadcast_to(cand, (qf.shape[0], pool)), metric)
    _, pos = jax.lax.top_k(-d, min(n_seeds, pool))
    return cand[pos]


@partial(jax.jit, static_argnames=("n_probes", "n_seeds", "metric"))
def _coarse_seeds(dataset, centers, members, queries, *, n_probes: int,
                  n_seeds: int, metric: DistanceType):
    """IVF-coarse seeding: each query probes its ``n_probes`` nearest
    seed-plane centers, gathers their member rows, and the beam opens
    from the ``n_seeds`` best of them. Strictly row-wise (one GEMM on
    the center plane + one gathered-distance tile), hence a pure
    function of query content — the batching-invariance contract."""
    qf = queries.astype(jnp.float32)
    ip = jnp.einsum("qd,cd->qc", qf, centers,
                    precision=jax.lax.Precision.HIGHEST)
    if metric == DistanceType.InnerProduct:
        cdist = -ip
    else:
        cdist = jnp.sum(jnp.square(centers), axis=1)[None, :] - 2.0 * ip
    _, probes = jax.lax.top_k(-cdist, n_probes)          # (q, n_probes)
    cand = jnp.take(members, probes, axis=0).reshape(qf.shape[0], -1)
    d = gathered_distances(qf, dataset, cand, metric)    # -1 pads → inf
    _, pos = jax.lax.top_k(-d, n_seeds)
    seeds = jnp.take_along_axis(cand, pos, axis=1)
    return jnp.where(
        jnp.isfinite(jnp.take_along_axis(d, pos, axis=1)), seeds, -1)


def derive_search_config(params: "CagraSearchParams",
                         index: "CagraIndex", k: int) -> dict:
    """THE beam-search shape derivation (L, w, max_iters, n_seeds),
    shared by :func:`search` and the serving path
    (``core/executor.py``) — their bit-identity depends on these
    values agreeing, so they are derived in exactly one place.

    One seed-count formula for both engines (their parity depends on
    drawing identical seed sets): the XLA width, rounded up to a
    multiple of the kernel's chunk width C = w*graph_degree. Duplicate
    draws are harmless — the merge dedups them."""
    L = max(params.itopk_size, k)
    w = max(1, params.search_width)
    C = w * index.graph_degree
    n_seeds = max(L, C) * max(1, params.num_random_samplings)
    n_seeds = -(-n_seeds // C) * C
    return {
        "k": k,
        "L": L,
        "w": w,
        "max_iters": params.max_iterations or (L // w + 24),
        "n_seeds": n_seeds,
    }


def _resolve_seed_mode(params: CagraSearchParams,
                       index: CagraIndex) -> str:
    """Resolve ``params.seed_mode`` against what the index carries."""
    mode = params.seed_mode
    expect(mode in ("auto", "coarse", "pool"),
           f"seed_mode must be 'auto'/'coarse'/'pool', got {mode!r}")
    if mode == "coarse":
        expect(index.seed_centers is not None,
               "seed_mode='coarse' needs the build-time seed plane "
               "(cagra.build); this index was assembled without one")
        return "coarse"
    if mode == "auto" and index.seed_centers is not None:
        return "coarse"
    return "pool"


def _make_seeds(dataset, seed_centers, seed_members, qt, n_seeds: int,
                metric: DistanceType, seed_mode: str, seed_pool: int):
    """Shared seed policy for the direct and serving search paths:
    IVF-coarse seeds from the build-time plane, or the query-aware
    strided pool for plane-less indexes. Both are pure functions of
    query content (row-wise) — blocks concatenate, pad rows cannot
    perturb real rows, and the ragged family can pack any split."""
    n = dataset.shape[0]
    pool = seed_pool if seed_pool > 0 else max(256, 4 * n_seeds)
    if seed_mode == "coarse":
        cap = seed_members.shape[1]
        n_probes = max(1, min(-(-pool // cap), seed_centers.shape[0]))
        seeds = _coarse_seeds(
            dataset, seed_centers, seed_members, qt, n_probes=n_probes,
            n_seeds=min(n_seeds, n_probes * cap), metric=metric)
    else:
        pool = min(pool, n)
        seeds = _pooled_seeds(dataset, qt, pool, min(n_seeds, pool),
                              metric)
    if seeds.shape[1] < n_seeds:
        # pad to the shared width by repeating the best seeds
        # (dedup makes repeats free)
        reps = -(-n_seeds // seeds.shape[1])
        seeds = jnp.tile(seeds, (1, reps))[:, :n_seeds]
    return seeds


def _rotate_queries(queries, rotation):
    """Rotate queries into the BQ estimator basis — ONE implementation
    for both engines and both call paths, so the estimate inputs (and
    hence the prune decisions) are bit-identical everywhere."""
    return jnp.einsum("qd,ed->qe", queries.astype(jnp.float32), rotation,
                      precision=jax.lax.Precision.HIGHEST)


def _resolve_bq_traversal(params: CagraSearchParams, index: CagraIndex,
                          use_kernel: bool) -> bool:
    """Resolve ``params.bq_traversal`` against the index plane and (on
    the kernel path) the VMEM budget the record plane must co-reside
    in."""
    mode = params.bq_traversal
    expect(mode in ("auto", "on", "off"),
           f"bq_traversal must be 'auto'/'on'/'off', got {mode!r}")
    if mode == "off":
        return False
    if index.bq_records is None:
        expect(mode != "on",
               "bq_traversal='on' needs an index built with bq_bits >= 1")
        return False
    if use_kernel:
        from raft_tpu.ops.fused_topk import _default_vmem_mb

        # same rule the kernel wrapper enforces: the plane is
        # VMEM-resident in both dataset modes and must leave the ~8 MB
        # scratch headroom (the dataset then places around it)
        fits = (4 * index.bq_records.size
                <= (_default_vmem_mb() - 8) * 1024 * 1024)
        if mode == "on":
            expect(fits, "bq_traversal='on': the BQ record plane "
                   "exceeds the kernel VMEM budget")
        return fits
    return True


def _search_batch_fn(dataset, graph, queries, seed_ids, filter_words,
                     row_iters=None, bq_records=None, bq_qrot=None,
                     bq_center_rot=None, *,
                     k: int, L: int, w: int, max_iters: int,
                     metric: DistanceType, bq_bits: int = 0,
                     bq_query_bits: int = 4, bq_epsilon: float = 3.0):
    """The XLA beam engine. ``row_iters`` (q,) optionally caps each
    row's live iterations (the ragged-serving budget — iterations past
    it are bit-exact no-ops for that row). ``bq_records``/``bq_qrot``/
    ``bq_center_rot`` enable the estimate-then-prune candidate gate —
    the same shared :func:`raft_tpu.ops.bq_scan._block_estimate` math
    as the Pallas kernel, so prune decisions (and hence results) are
    engine-parity-exact. This engine still gathers every candidate row
    (it is the portable correctness engine); only the kernel converts
    the prune into skipped DMA traffic."""
    q, dim = queries.shape
    n, deg = graph.shape
    qf = queries.astype(jnp.float32)
    ip_metric = metric == DistanceType.InnerProduct
    use_bq = bq_records is not None

    def score(cand):                                     # (q, c) ids → dists
        d = gathered_distances(qf, dataset, cand, metric)
        if filter_words is not None:
            # filtered-out samples never enter the itopk buffer, so they
            # are neither returned nor expanded (the reference's
            # search_with_filtering greenlight semantics)
            d = jnp.where(test_filter(filter_words, cand), d, jnp.inf)
        return d

    if use_bq:
        from raft_tpu.ops.bq_scan import _block_estimate, bq_record_geometry

        words = bq_bits * ((dim + 31) // 32)
        dim_ext = ((dim + 31) // 32) * 32
        _, rec_pad, _, _ = bq_record_geometry(words, bq_bits)
        rows2d = bq_records.reshape(-1, rec_pad)

        def bq_survivors(cand, dists):
            """(q, C) candidate ids → bool survivor mask: estimate
            minus margin still beats the row's running L-th exact
            distance. Record extraction mirrors the kernel's lane
            split bit-for-bit."""
            r = jnp.take(rows2d, jnp.maximum(cand, 0), axis=0)
            codes_wb = r[..., :words]                    # (q, C, words)
            scal = jax.lax.bitcast_convert_type(
                r[..., words:words + bq_bits + 2], jnp.float32)

            def one(qr, codes_q, sc):
                rn = sc[:, 0][None, :]                   # (1, C)
                cf = jnp.transpose(sc[:, 1:1 + bq_bits])  # (bits, C)
                ew = sc[:, 1 + bq_bits][None, :]
                return _block_estimate(
                    qr[None, :], bq_center_rot, rn, ew, cf, codes_q,
                    dim_ext=dim_ext, bits=bq_bits,
                    query_bits=bq_query_bits, epsilon=bq_epsilon,
                    ip_metric=ip_metric)
            est, margin = jax.vmap(one)(bq_qrot, codes_wb, scal)
            kth = dists[:, L - 1:L]
            return ((est[:, 0, :] - margin[:, 0, :]) < kth) & (cand >= 0)

    ids = jnp.full((q, L), -1, jnp.int32)
    dists = jnp.full((q, L), jnp.inf)
    explored = jnp.zeros((q, L), bool)
    if use_bq:
        # seed rounds merge in C-wide chunks with the evolving buffer's
        # L-th distance as the prune bar — the kernel's exact order
        C = w * deg
        for chunk in range(seed_ids.shape[1] // C):
            cand = seed_ids[:, chunk * C:(chunk + 1) * C]
            cd = jnp.where(bq_survivors(cand, dists), score(cand),
                           jnp.inf)
            ids, dists, explored = _buffer_merge(ids, dists, explored,
                                                 cand, cd, L)
    else:
        # seeding (role of the reference's random_samplings)
        ids, dists, explored = _buffer_merge(
            ids, dists, explored, seed_ids, score(seed_ids), L)

    def cond(state):
        ids, dists, explored, it = state
        frontier = (~explored) & jnp.isfinite(dists)
        if row_iters is not None:
            frontier = frontier & (it < row_iters)[:, None]
        return (it < max_iters) & jnp.any(frontier)

    def body(state):
        ids, dists, explored, it = state
        masked = jnp.where(explored | (ids < 0), jnp.inf, dists)
        _, ppos = jax.lax.top_k(-masked, w)              # (q, w) parents
        valid = jnp.isfinite(jnp.take_along_axis(masked, ppos, axis=1))
        if row_iters is not None:
            # a row past its budget contributes no parents and marks
            # nothing explored — the whole iteration is a no-op for it
            valid = valid & (it < row_iters)[:, None]
        parents = jnp.where(valid,
                            jnp.take_along_axis(ids, ppos, axis=1), -1)
        explored = explored.at[
            jnp.arange(q)[:, None], ppos
        ].set(explored[jnp.arange(q)[:, None], ppos] | valid)
        cand = jnp.take(graph, jnp.clip(parents, 0), axis=0)  # (q, w, deg)
        cand = jnp.where((parents >= 0)[:, :, None], cand, -1)
        cand = cand.reshape(q, w * deg)
        cand_d = score(cand)
        if use_bq:
            cand_d = jnp.where(bq_survivors(cand, dists), cand_d,
                               jnp.inf)
        ids, dists, explored = _buffer_merge(ids, dists, explored, cand,
                                             cand_d, L)
        return ids, dists, explored, it + 1

    ids, dists, explored, _ = jax.lax.while_loop(
        cond, body, (ids, dists, explored, jnp.zeros((), jnp.int32))
    )

    # entries never scored finite (e.g. everything a filter rejected)
    # report index -1, like the ivf search paths
    out_d = dists[:, :k]
    out_i = jnp.where(jnp.isfinite(out_d), ids[:, :k], -1)
    if ip_metric:
        out_d = -out_d
    elif metric == DistanceType.L2SqrtExpanded:
        out_d = jnp.where(jnp.isfinite(out_d),
                          jnp.sqrt(jnp.maximum(out_d, 0.0)), out_d)
    return out_d, out_i


_search_batch = partial(jax.jit, static_argnames=(
    "k", "L", "w", "max_iters", "metric", "bq_bits", "bq_query_bits",
    "bq_epsilon"))(_search_batch_fn)


def _serve_impl(queries, row_iters, dataset, graph, seed_centers,
                seed_members, bq_rotation, bq_center_rot, bq_records,
                filter_words, *, engine: str, k: int, L: int, w: int,
                max_iters: int, n_seeds: int, metric: DistanceType,
                seed_mode: str, seed_pool: int, bq_bits: int,
                bq_query_bits: int, bq_epsilon: float, deg: int,
                interpret: bool):
    """Seeds + beam + metric epilog for BOTH engines — what
    ``core/executor.py`` AOT-compiles per bucket (``_serving_fn``) and
    per ragged params class (``_search_ragged_fn``). Seeds are a pure
    function of query content, so blocks concatenate and results for
    real rows are bit-identical to the direct :func:`search` path.
    ``graph`` arrives pre-padded (``pad_graph``) on the kernel
    engine."""
    seeds = _make_seeds(dataset, seed_centers, seed_members, queries,
                        n_seeds, metric, seed_mode, seed_pool)
    use_bq = bq_records is not None
    qrot = _rotate_queries(queries, bq_rotation) if use_bq else None
    if engine == "pallas":
        from raft_tpu.ops.beam_search import beam_search

        d, i = beam_search(
            queries, dataset, graph, seeds, k, L, w, max_iters, metric,
            row_iters=row_iters, bq_records=bq_records, bq_qrot=qrot,
            bq_crot=bq_center_rot, bq_bits=bq_bits if use_bq else 0,
            bq_query_bits=bq_query_bits, bq_epsilon=bq_epsilon,
            deg=deg, interpret=interpret)
        if metric == DistanceType.InnerProduct:
            d = -d
        elif metric == DistanceType.L2SqrtExpanded:
            d = jnp.where(jnp.isfinite(d),
                          jnp.sqrt(jnp.maximum(d, 0.0)), d)
        return d, i
    return _search_batch_fn(
        dataset, graph, queries, seeds, filter_words,
        row_iters=row_iters, bq_records=bq_records, bq_qrot=qrot,
        bq_center_rot=bq_center_rot, k=k, L=L, w=w,
        max_iters=max_iters, metric=metric,
        bq_bits=bq_bits if use_bq else 0,
        bq_query_bits=bq_query_bits, bq_epsilon=bq_epsilon)


def _serving_fn(queries, dataset, graph, seed_centers, seed_members,
                bq_rotation, bq_center_rot, bq_records,
                filter_words=None, *, engine: str, k: int, L: int,
                w: int, max_iters: int, n_seeds: int,
                metric: DistanceType, seed_mode: str, seed_pool: int,
                bq_bits: int, bq_query_bits: int, bq_epsilon: float,
                deg: int, interpret: bool):
    """Bucketed serving entry (see :func:`_serve_impl`)."""
    return _serve_impl(
        queries, None, dataset, graph, seed_centers, seed_members,
        bq_rotation, bq_center_rot, bq_records, filter_words,
        engine=engine, k=k, L=L, w=w, max_iters=max_iters,
        n_seeds=n_seeds, metric=metric, seed_mode=seed_mode,
        seed_pool=seed_pool, bq_bits=bq_bits,
        bq_query_bits=bq_query_bits, bq_epsilon=bq_epsilon, deg=deg,
        interpret=interpret)


def _search_ragged_fn(queries, row_iters, dataset, graph, seed_centers,
                      seed_members, bq_rotation, bq_center_rot,
                      bq_records, filter_words=None, *, engine: str,
                      k: int, L: int, w: int, max_iters: int,
                      n_seeds: int, metric: DistanceType,
                      seed_mode: str, seed_pool: int, bq_bits: int,
                      bq_query_bits: int, bq_epsilon: float, deg: int,
                      interpret: bool):
    """Ragged serving entry: one packed query tile, per-row iteration
    budgets (the per-request ``max_iterations``, resolved by the
    executor) folded into the beam as bit-exact no-op iterations —
    each row's columns equal a solo bucketed run at its own params."""
    return _serve_impl(
        queries, row_iters, dataset, graph, seed_centers, seed_members,
        bq_rotation, bq_center_rot, bq_records, filter_words,
        engine=engine, k=k, L=L, w=w, max_iters=max_iters,
        n_seeds=n_seeds, metric=metric, seed_mode=seed_mode,
        seed_pool=seed_pool, bq_bits=bq_bits,
        bq_query_bits=bq_query_bits, bq_epsilon=bq_epsilon, deg=deg,
        interpret=interpret)


def _resolve_search_algo(params: CagraSearchParams, index: CagraIndex,
                         filter_words) -> bool:
    """True → the one-dispatch Pallas beam kernel; False → XLA path."""
    from raft_tpu.ops import beam_search as bs

    if params.algo == "xla":
        return False
    expect(params.algo in ("auto", "pallas"),
           f"algo must be 'auto'/'pallas'/'xla', got {params.algo!r}")
    # any dataset size qualifies: the kernel streams candidate rows
    # from HBM when the dataset exceeds the VMEM budget (ds_mode auto)
    ok = (index.metric in bs._SUPPORTED
          and filter_words is None
          and index.dim % 128 == 0
          and index.dataset.dtype in (jnp.float32, jnp.bfloat16,
                                      jnp.int8))
    if params.algo == "pallas":
        expect(ok, "algo='pallas' needs: L2/IP metric, no sample_filter, "
               "dim % 128 == 0, f32/bf16/int8 dataset "
               f"(n={index.size}, dim={index.dim}, "
               f"dtype={index.dataset.dtype})")
        return True
    return ok and jax.default_backend() == "tpu"


def search(
    res: Optional[Resources],
    params: CagraSearchParams,
    index: CagraIndex,
    queries,
    k: int,
    sample_filter=None,
) -> Tuple[jax.Array, jax.Array]:
    """Graph beam search — ``cagra::search`` → ``search_main``
    (``detail/cagra/cagra_search.cuh:105``). With ``sample_filter``,
    only samples whose bit is set may be returned or expanded
    (``cagra::search_with_filtering``, ``cagra.cuh:430``).

    Two engines behind ``params.algo``: the ``lax.while_loop`` XLA path
    and the one-dispatch Pallas kernel with the dataset VMEM-resident
    (``ops/beam_search``, role of the reference's persistent
    single-CTA kernel)."""
    res = ensure_resources(res)
    queries = jnp.asarray(queries)
    expect(queries.ndim == 2 and queries.shape[1] == index.dim,
           "queries must be (q, dim)")
    if queries.shape[0] == 0:
        return (jnp.zeros((0, k), jnp.float32), jnp.zeros((0, k), jnp.int32))
    cfg = derive_search_config(params, index, k)
    L, w, max_iters, n_seeds = (cfg["L"], cfg["w"], cfg["max_iters"],
                                cfg["n_seeds"])
    filter_words = resolve_filter_words(sample_filter)
    use_kernel = _resolve_search_algo(params, index, filter_words)
    seed_mode = _resolve_seed_mode(params, index)
    use_bq = _resolve_bq_traversal(params, index, use_kernel)
    if use_bq:
        from raft_tpu.ops.bq_scan import auto_query_bits

        bq_query_bits = auto_query_bits(index.bq_bits)
    else:
        bq_query_bits = 4
    if filter_words is not None and filter_words.ndim == 2:
        expect(filter_words.shape[0] == queries.shape[0],
               "per-query BitmapFilter rows must match the query count")

    with tracing.range("raft_tpu.cagra.search"):
        outs_d, outs_i = [], []
        tile = max(1, params.query_tile)
        # padded once per index, not per search call or query tile
        # (the kernel DMAs whole 128-lane-aligned adjacency rows)
        padded_graph = index.padded_graph if use_kernel else None
        for start in range(0, queries.shape[0], tile):
            qt = queries[start : start + tile]
            fw = filter_words
            if fw is not None and fw.ndim == 2:
                fw = fw[start : start + tile]
            seeds = _make_seeds(index.dataset, index.seed_centers,
                                index.seed_members, qt, n_seeds,
                                index.metric, seed_mode, params.seed_pool)
            qrot = (_rotate_queries(qt, index.bq_rotation)
                    if use_bq else None)
            if use_kernel:
                from raft_tpu.ops.beam_search import beam_search

                d, i = beam_search(
                    qt, index.dataset, padded_graph, seeds, k, L, w,
                    max_iters, index.metric,
                    bq_records=index.bq_records if use_bq else None,
                    bq_qrot=qrot,
                    bq_crot=index.bq_center_rot if use_bq else None,
                    bq_bits=index.bq_bits if use_bq else 0,
                    bq_query_bits=bq_query_bits,
                    bq_epsilon=params.bq_epsilon,
                    deg=index.graph_degree,
                    interpret=jax.default_backend() != "tpu")
                if index.metric == DistanceType.InnerProduct:
                    d = -d
                elif index.metric == DistanceType.L2SqrtExpanded:
                    d = jnp.where(jnp.isfinite(d),
                                  jnp.sqrt(jnp.maximum(d, 0.0)), d)
            else:
                d, i = _search_batch(
                    index.dataset, index.graph, qt, seeds, fw, None,
                    index.bq_records if use_bq else None, qrot,
                    index.bq_center_rot if use_bq else None,
                    k=k, L=L, w=w, max_iters=max_iters,
                    metric=index.metric,
                    bq_bits=index.bq_bits if use_bq else 0,
                    bq_query_bits=bq_query_bits,
                    bq_epsilon=params.bq_epsilon)
            outs_d.append(d)
            outs_i.append(i)
        if len(outs_d) == 1:
            return outs_d[0], outs_i[0]
        return jnp.concatenate(outs_d), jnp.concatenate(outs_i)


# ---------------------------------------------------------------------------
# serialization
# ---------------------------------------------------------------------------


def save(index: CagraIndex, fh_or_path, include_dataset: bool = True) -> None:
    """``cagra::serialize`` (``detail/cagra/cagra_serialize.cuh``)."""
    fh, own = open_maybe_path(fh_or_path, "wb")
    try:
        serialize_scalar(fh, _SERIALIZATION_VERSION, np.int32)
        serialize_scalar(fh, int(index.metric), np.int32)
        serialize_scalar(fh, 1 if include_dataset else 0, np.int32)
        serialize_array(fh, index.graph)
        if include_dataset:
            serialize_array(fh, index.dataset)
        has_seed = index.seed_centers is not None
        serialize_scalar(fh, 1 if has_seed else 0, np.int32)
        if has_seed:
            serialize_array(fh, index.seed_centers)
            serialize_array(fh, index.seed_members)
        has_bq = index.bq_records is not None
        serialize_scalar(fh, 1 if has_bq else 0, np.int32)
        if has_bq:
            serialize_scalar(fh, index.bq_bits, np.int32)
            serialize_array(fh, index.bq_rotation)
            serialize_array(fh, index.bq_center_rot)
            serialize_array(fh, index.bq_records)
    finally:
        if own:
            fh.close()


def load(res: Optional[Resources], fh_or_path, dataset=None) -> CagraIndex:
    """Load an index; pass ``dataset`` when it was saved without one."""
    res = ensure_resources(res)
    fh, own = open_maybe_path(fh_or_path, "rb")
    try:
        check_version(deserialize_scalar(fh), _SERIALIZATION_VERSION, "cagra")
        metric = DistanceType(int(deserialize_scalar(fh)))
        has_ds = int(deserialize_scalar(fh)) != 0
        graph = res.put(deserialize_array(fh))
        if has_ds:
            dataset = res.put(deserialize_array(fh))
        seed_centers = seed_members = None
        if int(deserialize_scalar(fh)) != 0:
            seed_centers = res.put(deserialize_array(fh))
            seed_members = res.put(deserialize_array(fh))
        bq_rotation = bq_center_rot = bq_records = None
        bq_bits = 0
        if int(deserialize_scalar(fh)) != 0:
            bq_bits = int(deserialize_scalar(fh))
            bq_rotation = res.put(deserialize_array(fh))
            bq_center_rot = res.put(deserialize_array(fh))
            bq_records = res.put(deserialize_array(fh))
    finally:
        if own:
            fh.close()
    expect(dataset is not None, "index was saved without its dataset")
    return CagraIndex(jnp.asarray(dataset), jnp.asarray(graph), metric,
                      seed_centers=seed_centers, seed_members=seed_members,
                      bq_rotation=bq_rotation, bq_center_rot=bq_center_rot,
                      bq_records=bq_records, bq_bits=bq_bits)
