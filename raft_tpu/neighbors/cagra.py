"""CAGRA — graph-based ANN, TPU-native re-design of
``raft::neighbors::cagra`` (``cagra_types.hpp:131`` index, params
``:54-111``; build ``detail/cagra/cagra_build.cuh:44-123``; optimize
``detail/cagra/graph_core.cuh:320``; search ``detail/cagra/cagra_search.cuh:105``).

Reference architecture: k-NN graph from batched IVF-PQ searches (+refine)
or NN-descent; graph *optimize* = 2-hop detour counting (``kern_prune``,
``graph_core.cuh:128``) + reverse-edge augmentation (``kern_make_rev_graph
:191``); search = persistent CUDA kernels walking the graph with a
random-hash visited table, per-CTA bitonic top-M and three kernel
families (single-cta / multi-cta / multi-kernel).

TPU re-design:

- **build**: same two graph sources (IVF-PQ batches + refine, or the
  dense NN-descent in :mod:`raft_tpu.neighbors.nn_descent`).
- **optimize**: detour counting is a *dense batched tensor op* — for a
  node tile, gather the neighbor-of-neighbor id cube (t, K, K) and count
  rank-lower 2-hop matches with one broadcast compare; no atomics. The
  reverse graph uses sort-and-rank packing.
- **search**: one jitted ``lax.while_loop`` per query batch ("beam
  search" formulation): an itopk buffer (ids, dists, explored flags) is
  expanded ``search_width`` parents at a time; candidate scoring is a
  batched gather + MXU contraction over all queries at once. Instead of
  the GPU's visited hashmap, merging deduplicates ids with
  buffer-copy-priority, which both dedups and preserves explored flags —
  re-proposed candidates can never re-enter unexplored, so termination
  ("all buffer entries explored") is exact. Queries are tiled host-side;
  every shape is static.
"""

from __future__ import annotations

import dataclasses
import enum
from functools import partial
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core import tracing
from raft_tpu.core.logger import warn as _log_warn
from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.core.serialize import (
    check_version,
    deserialize_array,
    deserialize_scalar,
    open_maybe_path,
    serialize_array,
    serialize_scalar,
)
from raft_tpu.core.validation import expect
from raft_tpu.distance.types import DistanceType
from raft_tpu.neighbors import ivf_pq as ivf_pq_mod
from raft_tpu.neighbors import nn_descent as nn_descent_mod
from raft_tpu.neighbors._exact import dedup_candidate_mask, gathered_distances
from raft_tpu.neighbors.filters import resolve_filter_words, test_filter
from raft_tpu.neighbors.nn_descent import _reverse_sample
from raft_tpu.neighbors.refine import refine

_SERIALIZATION_VERSION = 4


class BuildAlgo(enum.Enum):
    """Mirrors ``cagra::graph_build_algo`` (``cagra_types.hpp``), plus
    the TPU-first CLUSTER_JOIN builder (merged within-cluster brute
    force — see :mod:`raft_tpu.neighbors.cluster_join`)."""

    IVF_PQ = "ivf_pq"
    NN_DESCENT = "nn_descent"
    CLUSTER_JOIN = "cluster_join"


@dataclasses.dataclass(frozen=True)
class CagraIndexParams:
    """Mirrors ``cagra::index_params`` (``cagra_types.hpp:54-111``)."""

    metric: DistanceType = DistanceType.L2Expanded
    intermediate_graph_degree: int = 128
    graph_degree: int = 64
    build_algo: BuildAlgo = BuildAlgo.IVF_PQ
    nn_descent_niter: int = 20
    # IVF-PQ graph-build knobs (reference auto-derives; exposed here)
    ivf_pq_n_lists: int = 0       # 0 → auto sqrt(n)
    ivf_pq_n_probes: int = 0      # 0 → auto
    refine_rate: float = 2.0      # gpu_top_k = degree * refine_rate
    # dataset storage dtype for the built index: bf16 halves both the
    # per-iteration gather bytes (XLA engine) and the VMEM residency
    # (Pallas engine: 500k×128 bf16 fits where f32 does not); build
    # math stays f32. Same contract as brute_force.build's
    # storage_dtype: None keeps the input dtype; accepts a dtype or
    # its name (JSON configs pass "bfloat16").
    storage_dtype: Optional[Any] = None


@dataclasses.dataclass(frozen=True)
class CagraSearchParams:
    """Mirrors ``cagra::search_params`` (``cagra_types.hpp``): ``itopk_size``
    is the retained candidate buffer, ``search_width`` the number of
    parents expanded per iteration, ``max_iterations`` 0 → auto."""

    itopk_size: int = 64
    search_width: int = 1
    max_iterations: int = 0
    num_random_samplings: int = 1
    rand_xor_mask: int = 0x128394  # seed salt, role of the reference field
    query_tile: int = 256
    # Query-aware seeding (beyond the reference): score this many
    # strided dataset rows per query and start the beam from the best
    # of them instead of uniform-random ids. One extra (q, pool) MXU
    # tile; on clustered data it removes the "did a random seed land in
    # the right cluster" recall ceiling. 0 = reference behavior.
    seed_pool: int = 0
    # "pallas": the one-dispatch VMEM-resident beam-search kernel
    # (ops/beam_search, role of the reference's persistent single-CTA
    # kernel); "xla": the lax.while_loop path; "auto": pallas on TPU
    # when its constraints hold (supported metric, no filter,
    # dim % 128 == 0, dataset fits the VMEM budget), else xla.
    algo: str = "auto"


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass(frozen=True)
class CagraIndex:
    """Dataset + fixed-degree neighbor graph (``cagra::index``,
    ``cagra_types.hpp:131``; the dataset is stored padded/strided in the
    reference — on TPU a plain dense (n, d) array)."""

    dataset: jax.Array      # (n, d)
    graph: jax.Array        # (n, graph_degree) int32
    metric: DistanceType

    def tree_flatten(self):
        return (self.dataset, self.graph), (self.metric,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], aux[0])

    @property
    def size(self) -> int:
        return self.dataset.shape[0]

    @property
    def dim(self) -> int:
        return self.dataset.shape[1]

    @property
    def graph_degree(self) -> int:
        return self.graph.shape[1]

    @property
    def padded_graph(self) -> jax.Array:
        """Adjacency rows padded to the Pallas kernel's 128-lane DMA
        unit, computed lazily and cached on the index so repeated
        ``search()`` calls don't re-copy the graph."""
        cached = self.__dict__.get("_padded_graph")
        if cached is None:
            from raft_tpu.ops.beam_search import pad_graph

            cached = pad_graph(self.graph)
            object.__setattr__(self, "_padded_graph", cached)
        return cached


# ---------------------------------------------------------------------------
# build
# ---------------------------------------------------------------------------


def build_knn_graph(
    res: Optional[Resources],
    dataset,
    k: int,
    metric: DistanceType = DistanceType.L2Expanded,
    n_lists: int = 0,
    n_probes: int = 0,
    refine_rate: float = 2.0,
    batch: int = 1024,
) -> jax.Array:
    """Intermediate k-NN graph via batched IVF-PQ self-search + refine —
    ``detail/cagra/cagra_build.cuh:44-123`` (1024-query batches at
    ``:105``). Self-matches are dropped; returns (n, k) int32."""
    res = ensure_resources(res)
    dataset = jnp.asarray(dataset)
    n, dim = dataset.shape
    n_lists = n_lists or max(8, min(n // 39 + 1, int(np.sqrt(n) * 2)))
    n_probes = n_probes or max(8, n_lists // 10)
    gpu_k = max(k + 1, int((k + 1) * refine_rate))

    # 4-bit codes at doubled pq_dim: equal code bytes and measured-equal
    # graph recall vs the 8-bit default, but the scoring rides the
    # masked-sum select path (~6x faster on TPU) — and refine re-ranks
    # with exact distances anyway
    params = ivf_pq_mod.IvfPqIndexParams(
        metric=metric, n_lists=n_lists,
        pq_bits=4,
        pq_dim=min(dim, 2 * ivf_pq_mod._auto_pq_dim(dim)),
        kmeans_trainset_fraction=min(1.0, 10240 / max(n, 1) + 0.1),
    )
    index = ivf_pq_mod.build(res, params, dataset)
    sp = ivf_pq_mod.IvfPqSearchParams(n_probes=n_probes)

    out = []
    for start in range(0, n, batch):
        q = dataset[start : start + batch]
        _, cand = ivf_pq_mod.search(res, sp, index, q, gpu_k)
        _, idx = refine(res, dataset, q, cand, k + 1, metric)
        # drop self-hits: mask rows equal to the query's own id
        own = jnp.arange(start, start + q.shape[0], dtype=jnp.int32)[:, None]
        keep = idx != own
        # stable-compact each row to k entries (self-hit, if found, removed)
        pos = jnp.where(keep, jnp.cumsum(keep, axis=1) - 1, k + 1)
        row = jnp.full((q.shape[0], k + 2), -1, jnp.int32)
        row = row.at[jnp.arange(q.shape[0])[:, None], pos].set(idx, mode="drop")
        out.append(row[:, :k])
    return jnp.concatenate(out, axis=0)


@partial(jax.jit, static_argnames=("tile", "method"))
def _detour_counts(graph, tile: int, method: str = "auto"):
    """2-hop detour count per edge (role of ``kern_prune``,
    ``graph_core.cuh:128``): edge (i → g[i,r]) is detourable through the
    higher-ranked neighbor g[i,l] (l < r) when g[i,r] ∈ graph[g[i,l]].

    Two membership tests, picked per backend (the reference amortizes
    the same lookup with shared-memory hashing):

    - ``compare``: O(k³)-per-node broadcast equality — pure VPU
      compares, no gathers/sorts; the right trade on TPU where lane
      gathers serialize onto the scalar core.
    - ``search``: sort each neighbor row once + binary-search all edges
      into it — O(k² log k) per node; wins on CPU/GPU where gathers
      are cheap.
    """
    if method == "auto":
        method = "compare" if jax.default_backend() == "tpu" else "search"
    n, k = graph.shape
    pad = (-n) % tile
    node_ids = jnp.arange(n + pad, dtype=jnp.int32) % n
    sentinel = jnp.iinfo(jnp.int32).max
    rank = jnp.arange(k, dtype=jnp.int32)

    def step(_, t):
        nid = jax.lax.dynamic_slice_in_dim(node_ids, t * tile, tile)
        g = jnp.take(graph, nid, axis=0)                       # (t, k)
        nbrs = jnp.take(graph, jnp.clip(g, 0), axis=0)         # (t, k, k)
        # rows of invalid parents (or invalid entries) can match nothing
        nbrs = jnp.where((g >= 0)[:, :, None] & (nbrs >= 0), nbrs,
                         sentinel)
        if method == "search":
            snbrs = jnp.sort(nbrs, axis=2)
            pos = jax.vmap(jax.vmap(jnp.searchsorted, (0, None)))(snbrs, g)
            hit = jnp.take_along_axis(
                snbrs, jnp.clip(pos, 0, k - 1), axis=2
            ) == g[:, None, :]                                 # (t, l, r)
            ok = ((rank[None, :, None] < rank[None, None, :])
                  & (g >= 0)[:, None, :])
            return None, jnp.sum((hit & ok).astype(jnp.int32), axis=1)

        # "compare": accumulate over l so the intermediate stays
        # (t, k, k) instead of a (t, k, k, k) broadcast cube
        def count_l(l, counts):
            eq = nbrs[:, l, :, None] == g[:, None, :]          # (t, m, r)
            match = jnp.any(eq, axis=1) & (g >= 0)             # (t, r)
            return counts + (match & (rank > l)[None, :]).astype(jnp.int32)

        counts = jax.lax.fori_loop(
            0, k, count_l, jnp.zeros((tile, k), jnp.int32)
        )
        return None, counts

    n_tiles = (n + pad) // tile
    _, out = jax.lax.scan(step, None, jnp.arange(n_tiles))
    return out.reshape(-1, k)[:n]


@partial(jax.jit, static_argnames=("fwd_keep",))
def _select_forward(graph, detours, fwd_keep: int):
    """The fwd_keep lowest-detour edges per node, rank-order preserved
    (ties broken toward closer neighbors)."""
    k = graph.shape[1]
    rank = jnp.arange(k, dtype=jnp.int32)[None, :]
    score = jnp.where(graph >= 0, detours * k + rank, jnp.iinfo(jnp.int32).max)
    _, pos = jax.lax.top_k(-score, fwd_keep)
    return jnp.take_along_axis(graph, jnp.sort(pos, axis=1), axis=1)


@partial(jax.jit, static_argnames=("out_degree",))
def _merge_forward_reverse(graph, fwd, rev, out_degree: int):
    """Merge the kept forward edges with reverse edges and leftover
    forward edges, dedup'd by priority (role of ``graph_core.cuh``
    ``optimize:320`` + ``kern_make_rev_graph:191``)."""
    n, k = graph.shape

    # candidates in priority order: kept-forward, reverse, remaining-forward
    cand = jnp.concatenate([fwd, rev, graph], axis=1)
    c = cand.shape[1]
    prio = jnp.arange(c, dtype=jnp.int32)[None, :]
    prio = jnp.where(cand >= 0, prio, c)
    order = jnp.argsort(cand, axis=1, stable=True)      # groups equal ids
    sid = jnp.take_along_axis(cand, order, axis=1)
    sprio = jnp.take_along_axis(prio, order, axis=1)
    dup = jnp.concatenate(
        [jnp.zeros((n, 1), bool), sid[:, 1:] == sid[:, :-1]], axis=1
    )
    sprio = jnp.where(dup | (sid < 0), c, sprio)
    _, best = jax.lax.top_k(-sprio, out_degree)
    keep_ids = jnp.take_along_axis(sid, best, axis=1)
    keep_prio = jnp.take_along_axis(sprio, best, axis=1)
    # order final rows by priority so closest-first ordering survives
    reorder = jnp.argsort(keep_prio, axis=1, stable=True)
    out = jnp.take_along_axis(keep_ids, reorder, axis=1)
    return jnp.where(jnp.take_along_axis(keep_prio, reorder, axis=1) < c,
                     out, -1)


def optimize(
    res: Optional[Resources],
    knn_graph,
    out_degree: int,
    tile: int = 128,
) -> jax.Array:
    """Prune an intermediate k-NN graph to a fixed-degree search graph —
    ``cagra::optimize`` (``graph_core.cuh:320``)."""
    ensure_resources(res)
    knn_graph = jnp.asarray(knn_graph, jnp.int32)
    n, k = knn_graph.shape
    expect(out_degree <= k, "out_degree must be <= input graph degree")
    with tracing.range("raft_tpu.cagra.optimize"):
        detours = _detour_counts(knn_graph, tile)
        fwd = _select_forward(knn_graph, detours, out_degree // 2)
        rev = _reverse_sample(fwd, n, out_degree - out_degree // 2)
        return _merge_forward_reverse(knn_graph, fwd, rev, out_degree)


def build(
    res: Optional[Resources],
    params: CagraIndexParams,
    dataset,
) -> CagraIndex:
    """knn-graph + optimize — ``cagra::build`` (``cagra.cuh:296-331``).

    Examples
    --------
    >>> import numpy as np
    >>> from raft_tpu.neighbors import cagra
    >>> x = np.random.default_rng(0).standard_normal(
    ...     (128, 16)).astype(np.float32)
    >>> idx = cagra.build(None, cagra.CagraIndexParams(
    ...     graph_degree=8, intermediate_graph_degree=16,
    ...     build_algo=cagra.BuildAlgo.NN_DESCENT), x)
    >>> _, i = cagra.search(None, cagra.CagraSearchParams(itopk_size=16),
    ...                     idx, x[:4], 1)
    >>> np.asarray(i).ravel().tolist()   # each point is its own NN
    [0, 1, 2, 3]
    """
    res = ensure_resources(res)
    dataset = jnp.asarray(dataset)
    expect(dataset.ndim == 2, "dataset must be (n, d)")
    expect(params.metric in (DistanceType.L2Expanded,
                             DistanceType.L2SqrtExpanded,
                             DistanceType.InnerProduct),
           f"cagra supports L2/InnerProduct, got {params.metric!r}")
    if params.storage_dtype is not None:   # fail fast, before the build
        expect(jnp.dtype(params.storage_dtype) in
               (jnp.dtype(jnp.float32), jnp.dtype(jnp.bfloat16)),
               f"storage_dtype must be float32/bfloat16, got "
               f"{params.storage_dtype!r}")
        params = dataclasses.replace(
            params, storage_dtype=jnp.dtype(params.storage_dtype))
    n = dataset.shape[0]
    ideg = min(params.intermediate_graph_degree, n - 1)
    if ideg < params.intermediate_graph_degree:
        _log_warn(
            "Intermediate graph degree cannot be larger than dataset "
            "size, reducing it to %d", ideg)
    odeg = min(params.graph_degree, ideg)
    if odeg < params.graph_degree:
        _log_warn(
            "Graph degree (%d) cannot be larger than intermediate graph "
            "degree (%d), reducing graph_degree", params.graph_degree, ideg)

    with tracing.range("raft_tpu.cagra.build"):
        if params.build_algo == BuildAlgo.CLUSTER_JOIN:
            from raft_tpu.neighbors import cluster_join

            cj = cluster_join.ClusterJoinParams(
                graph_degree=ideg,
                metric=params.metric,
                seed=res.seed,
            )
            knn_graph = cluster_join.build(res, cj, dataset)
        elif params.build_algo == BuildAlgo.NN_DESCENT:
            nnd = nn_descent_mod.NNDescentParams(
                graph_degree=ideg,
                intermediate_graph_degree=min(int(ideg * 1.5), n - 1),
                max_iterations=params.nn_descent_niter,
                metric=params.metric,
                seed=res.seed,
            )
            knn_graph = nn_descent_mod.build(res, nnd, dataset)
        else:
            knn_graph = build_knn_graph(
                res, dataset, ideg, params.metric,
                params.ivf_pq_n_lists, params.ivf_pq_n_probes,
                params.refine_rate,
            )
        graph = optimize(res, knn_graph, odeg)
        stored = dataset
        if params.storage_dtype is not None:
            stored = jnp.asarray(dataset).astype(params.storage_dtype)
        return CagraIndex(dataset=res.put(stored), graph=graph,
                          metric=DistanceType(params.metric))


def from_graph(res, dataset, graph,
               metric: DistanceType = DistanceType.L2Expanded) -> CagraIndex:
    """Assemble an index from a prebuilt graph (reference's index
    constructor taking dataset + knn_graph views)."""
    res = ensure_resources(res)
    return CagraIndex(res.put(jnp.asarray(dataset)),
                      res.put(jnp.asarray(graph, jnp.int32)),
                      DistanceType(metric))


# ---------------------------------------------------------------------------
# search
# ---------------------------------------------------------------------------


def _buffer_merge(ids, dists, explored, cand_ids, cand_d, L: int):
    """Merge candidates into the itopk buffer with id-dedup where the
    buffer copy wins — preserving explored flags (the hash-free visited
    mechanism; see module docstring).

    Dedup is a broadcast equality mask (candidate-vs-buffer (C, L) +
    candidate-vs-earlier-candidate (C, C)) feeding one ``top_k`` — no
    argsort in the search hot loop (TPU sorts have poor constants; the
    masks are cheap VPU compares)."""
    # buffer copy wins over duplicates; first proposal wins among
    # candidates (shared helper — the Pallas engine uses the same one)
    buf_ids = jnp.where(ids >= 0, ids, -2)               # -2 ≠ any cand -1
    dup = dedup_candidate_mask(cand_ids, buf_ids)
    cd = jnp.where(dup | (cand_ids < 0), jnp.inf, cand_d)

    all_d = jnp.concatenate([dists, cd], axis=1)
    all_i = jnp.concatenate([ids, cand_ids], axis=1)
    all_e = jnp.concatenate(
        [explored, jnp.zeros(cand_ids.shape, bool)], axis=1
    )
    neg, pos = jax.lax.top_k(-all_d, L)
    return (
        jnp.take_along_axis(all_i, pos, axis=1),
        -neg,
        jnp.take_along_axis(all_e, pos, axis=1),
    )


@partial(jax.jit, static_argnames=("pool", "n_seeds", "metric"))
def _pooled_seeds(dataset, queries, pool: int, n_seeds: int,
                  metric: DistanceType):
    """Best ``n_seeds`` of a strided ``pool``-row sample per query — a
    one-GEMM routing stage replacing uniform-random seeding."""
    n = dataset.shape[0]
    stride = -(-n // pool)  # ceil: the pool must span the whole id range
    cand = (jnp.arange(pool, dtype=jnp.int32) * stride) % n
    qf = queries.astype(jnp.float32)
    d = gathered_distances(
        qf, dataset, jnp.broadcast_to(cand, (qf.shape[0], pool)), metric)
    _, pos = jax.lax.top_k(-d, min(n_seeds, pool))
    return cand[pos]


@partial(jax.jit, static_argnames=("rows", "n_seeds", "n"))
def _draw_seeds(base_key, row0, rows: int, n_seeds: int, n: int):
    """Per-row seed draws, invariant to batching: row ``r`` of any call
    derives everything from ``fold_in(base_key, row0 + r)``, so a query
    at a given absolute position gets the same seeds no matter how the
    batch was tiled, padded or bucketed — the property the serving
    path's bit-identical-results guarantee rests on.

    Each row takes a random offset plus an even stride over the id
    space (iid uniform draws can leave whole clusters unsampled; the
    stride guarantees coverage, the per-row random offset and jitter
    keep rows decorrelated). Duplicate draws are harmless — the beam
    merge dedups them."""
    rids = row0 + jnp.arange(rows)
    keys = jax.vmap(lambda r: jax.random.fold_in(base_key, r))(rids)
    stride = max(1, n // n_seeds)

    def one(kk):
        off, jit_k = jax.random.split(kk)
        base = jax.random.randint(off, (), 0, n, jnp.int32)
        jitter = jax.random.randint(jit_k, (n_seeds,), 0, stride, jnp.int32)
        lattice = jnp.arange(n_seeds, dtype=jnp.int32) * stride
        return (base + lattice + jitter) % n

    return jax.vmap(one)(keys)


def derive_search_config(params: "CagraSearchParams", index: "CagraIndex",
                         k: int, seed: int) -> dict:
    """THE beam-search shape derivation (L, w, max_iters, n_seeds,
    seed_salt), shared by :func:`search` and the serving path
    (``core/executor.py``) — their bit-identity depends on these five
    values agreeing, so they are derived in exactly one place.

    One seed-count formula for both engines (their parity depends on
    drawing identical seed sets): the XLA width, rounded up to a
    multiple of the kernel's chunk width C = w*graph_degree. Duplicate
    draws are harmless — the merge dedups them."""
    L = max(params.itopk_size, k)
    w = max(1, params.search_width)
    C = w * index.graph_degree
    n_seeds = max(L, C) * max(1, params.num_random_samplings)
    n_seeds = -(-n_seeds // C) * C
    return {
        "k": k,
        "L": L,
        "w": w,
        "max_iters": params.max_iterations or (L // w + 24),
        "n_seeds": n_seeds,
        "seed_salt": seed ^ params.rand_xor_mask,
    }


def _make_seeds(dataset, qt, row0, n_seeds: int, metric: DistanceType,
                seed_pool: int, base_key):
    """Shared seed policy for the direct and serving search paths:
    query-aware pooled seeds when ``seed_pool > 0``, else per-row
    uniform draws (both rowwise — pad rows cannot perturb real rows)."""
    n = dataset.shape[0]
    if seed_pool > 0:
        seeds = _pooled_seeds(dataset, qt, min(seed_pool, n),
                              min(n_seeds, seed_pool, n), metric)
        if seeds.shape[1] < n_seeds:
            # pad to the shared width by repeating the best seeds
            # (dedup makes repeats free)
            reps = -(-n_seeds // seeds.shape[1])
            seeds = jnp.tile(seeds, (1, reps))[:, :n_seeds]
        return seeds
    return _draw_seeds(base_key, row0, qt.shape[0], n_seeds, n)


def _search_batch_fn(dataset, graph, queries, seed_ids, filter_words, *,
                     k: int, L: int, w: int, max_iters: int,
                     metric: DistanceType):
    q, dim = queries.shape
    n, deg = graph.shape
    qf = queries.astype(jnp.float32)
    ip_metric = metric == DistanceType.InnerProduct

    def score(cand):                                     # (q, c) ids → dists
        d = gathered_distances(qf, dataset, cand, metric)
        if filter_words is not None:
            # filtered-out samples never enter the itopk buffer, so they
            # are neither returned nor expanded (the reference's
            # search_with_filtering greenlight semantics)
            d = jnp.where(test_filter(filter_words, cand), d, jnp.inf)
        return d

    # random seeding (role of the reference's random_samplings)
    seed_d = score(seed_ids)
    ids, dists, explored = _buffer_merge(
        jnp.full((q, L), -1, jnp.int32), jnp.full((q, L), jnp.inf),
        jnp.zeros((q, L), bool), seed_ids, seed_d, L,
    )

    def cond(state):
        ids, dists, explored, it = state
        frontier = (~explored) & jnp.isfinite(dists)
        return (it < max_iters) & jnp.any(frontier)

    def body(state):
        ids, dists, explored, it = state
        masked = jnp.where(explored | (ids < 0), jnp.inf, dists)
        _, ppos = jax.lax.top_k(-masked, w)              # (q, w) parents
        valid = jnp.isfinite(jnp.take_along_axis(masked, ppos, axis=1))
        parents = jnp.where(valid,
                            jnp.take_along_axis(ids, ppos, axis=1), -1)
        explored = explored.at[
            jnp.arange(q)[:, None], ppos
        ].set(explored[jnp.arange(q)[:, None], ppos] | valid)
        cand = jnp.take(graph, jnp.clip(parents, 0), axis=0)  # (q, w, deg)
        cand = jnp.where((parents >= 0)[:, :, None], cand, -1)
        cand = cand.reshape(q, w * deg)
        cand_d = score(cand)
        ids, dists, explored = _buffer_merge(ids, dists, explored, cand,
                                             cand_d, L)
        return ids, dists, explored, it + 1

    ids, dists, explored, _ = jax.lax.while_loop(
        cond, body, (ids, dists, explored, jnp.zeros((), jnp.int32))
    )

    # entries never scored finite (e.g. everything a filter rejected)
    # report index -1, like the ivf search paths
    out_d = dists[:, :k]
    out_i = jnp.where(jnp.isfinite(out_d), ids[:, :k], -1)
    if ip_metric:
        out_d = -out_d
    elif metric == DistanceType.L2SqrtExpanded:
        out_d = jnp.where(jnp.isfinite(out_d),
                          jnp.sqrt(jnp.maximum(out_d, 0.0)), out_d)
    return out_d, out_i


_search_batch = partial(jax.jit, static_argnames=(
    "k", "L", "w", "max_iters", "metric"))(_search_batch_fn)


def _serving_xla_fn(dataset, graph, queries, row0, filter_words, *, k: int,
                    L: int, w: int, max_iters: int, metric: DistanceType,
                    n_seeds: int, seed_salt: int, seed_pool: int):
    """One-program serving entry (seeds + beam search) for the XLA
    engine — what ``core/executor.py`` AOT-compiles per bucket. Seeds
    are drawn per absolute row ``row0 + r`` (``_draw_seeds``; ``row0``
    is traced so oversized batches tile through ONE executable), so
    results for real rows are bit-identical to the direct
    :func:`search` path."""
    base_key = jax.random.key(seed_salt)
    seeds = _make_seeds(dataset, queries, row0, n_seeds, metric, seed_pool,
                        base_key)
    return _search_batch_fn(dataset, graph, queries, seeds, filter_words,
                            k=k, L=L, w=w, max_iters=max_iters, metric=metric)


def _serving_kernel_fn(dataset, padded_graph, queries, row0, *, k: int,
                       L: int, w: int, max_iters: int, metric: DistanceType,
                       deg: int, n_seeds: int, seed_salt: int,
                       seed_pool: int, interpret: bool = False):
    """Serving entry for the Pallas beam kernel (TPU), mirroring the
    kernel branch of :func:`search` including its distance postprocess."""
    from raft_tpu.ops.beam_search import beam_search

    base_key = jax.random.key(seed_salt)
    seeds = _make_seeds(dataset, queries, row0, n_seeds, metric, seed_pool,
                        base_key)
    d, i = beam_search(queries, dataset, padded_graph, seeds, k, L, w,
                       max_iters, metric, deg=deg, interpret=interpret)
    if metric == DistanceType.InnerProduct:
        d = -d
    elif metric == DistanceType.L2SqrtExpanded:
        d = jnp.where(jnp.isfinite(d), jnp.sqrt(jnp.maximum(d, 0.0)), d)
    return d, i


def _resolve_search_algo(params: CagraSearchParams, index: CagraIndex,
                         filter_words) -> bool:
    """True → the one-dispatch Pallas beam kernel; False → XLA path."""
    from raft_tpu.ops import beam_search as bs

    if params.algo == "xla":
        return False
    expect(params.algo in ("auto", "pallas"),
           f"algo must be 'auto'/'pallas'/'xla', got {params.algo!r}")
    # any dataset size qualifies: the kernel streams candidate rows
    # from HBM when the dataset exceeds the VMEM budget (ds_mode auto)
    ok = (index.metric in bs._SUPPORTED
          and filter_words is None
          and index.dim % 128 == 0
          and index.dataset.dtype in (jnp.float32, jnp.bfloat16,
                                      jnp.int8))
    if params.algo == "pallas":
        expect(ok, "algo='pallas' needs: L2/IP metric, no sample_filter, "
               "dim % 128 == 0, f32/bf16/int8 dataset "
               f"(n={index.size}, dim={index.dim}, "
               f"dtype={index.dataset.dtype})")
        return True
    return ok and jax.default_backend() == "tpu"


def search(
    res: Optional[Resources],
    params: CagraSearchParams,
    index: CagraIndex,
    queries,
    k: int,
    sample_filter=None,
) -> Tuple[jax.Array, jax.Array]:
    """Graph beam search — ``cagra::search`` → ``search_main``
    (``detail/cagra/cagra_search.cuh:105``). With ``sample_filter``,
    only samples whose bit is set may be returned or expanded
    (``cagra::search_with_filtering``, ``cagra.cuh:430``).

    Two engines behind ``params.algo``: the ``lax.while_loop`` XLA path
    and the one-dispatch Pallas kernel with the dataset VMEM-resident
    (``ops/beam_search``, role of the reference's persistent
    single-CTA kernel)."""
    res = ensure_resources(res)
    queries = jnp.asarray(queries)
    expect(queries.ndim == 2 and queries.shape[1] == index.dim,
           "queries must be (q, dim)")
    if queries.shape[0] == 0:
        return (jnp.zeros((0, k), jnp.float32), jnp.zeros((0, k), jnp.int32))
    cfg = derive_search_config(params, index, k, res.seed)
    L, w, max_iters, n_seeds = (cfg["L"], cfg["w"], cfg["max_iters"],
                                cfg["n_seeds"])
    filter_words = resolve_filter_words(sample_filter)
    use_kernel = _resolve_search_algo(params, index, filter_words)
    if filter_words is not None and filter_words.ndim == 2:
        expect(filter_words.shape[0] == queries.shape[0],
               "per-query BitmapFilter rows must match the query count")

    with tracing.range("raft_tpu.cagra.search"):
        outs_d, outs_i = [], []
        tile = max(1, params.query_tile)
        # padded once per index, not per search call or query tile
        # (the kernel DMAs whole 128-lane-aligned adjacency rows)
        padded_graph = index.padded_graph if use_kernel else None
        base_key = jax.random.key(cfg["seed_salt"])
        for start in range(0, queries.shape[0], tile):
            qt = queries[start : start + tile]
            fw = filter_words
            if fw is not None and fw.ndim == 2:
                fw = fw[start : start + tile]
            seeds = _make_seeds(index.dataset, qt, start, n_seeds,
                                index.metric, params.seed_pool, base_key)
            if use_kernel:
                from raft_tpu.ops.beam_search import beam_search

                d, i = beam_search(
                    qt, index.dataset, padded_graph, seeds, k, L, w,
                    max_iters, index.metric,
                    deg=index.graph_degree,
                    interpret=jax.default_backend() != "tpu")
                if index.metric == DistanceType.InnerProduct:
                    d = -d
                elif index.metric == DistanceType.L2SqrtExpanded:
                    d = jnp.where(jnp.isfinite(d),
                                  jnp.sqrt(jnp.maximum(d, 0.0)), d)
            else:
                d, i = _search_batch(index.dataset, index.graph, qt, seeds,
                                     fw, k=k, L=L, w=w, max_iters=max_iters,
                                     metric=index.metric)
            outs_d.append(d)
            outs_i.append(i)
        if len(outs_d) == 1:
            return outs_d[0], outs_i[0]
        return jnp.concatenate(outs_d), jnp.concatenate(outs_i)


# ---------------------------------------------------------------------------
# serialization
# ---------------------------------------------------------------------------


def save(index: CagraIndex, fh_or_path, include_dataset: bool = True) -> None:
    """``cagra::serialize`` (``detail/cagra/cagra_serialize.cuh``)."""
    fh, own = open_maybe_path(fh_or_path, "wb")
    try:
        serialize_scalar(fh, _SERIALIZATION_VERSION, np.int32)
        serialize_scalar(fh, int(index.metric), np.int32)
        serialize_scalar(fh, 1 if include_dataset else 0, np.int32)
        serialize_array(fh, index.graph)
        if include_dataset:
            serialize_array(fh, index.dataset)
    finally:
        if own:
            fh.close()


def load(res: Optional[Resources], fh_or_path, dataset=None) -> CagraIndex:
    """Load an index; pass ``dataset`` when it was saved without one."""
    res = ensure_resources(res)
    fh, own = open_maybe_path(fh_or_path, "rb")
    try:
        check_version(deserialize_scalar(fh), _SERIALIZATION_VERSION, "cagra")
        metric = DistanceType(int(deserialize_scalar(fh)))
        has_ds = int(deserialize_scalar(fh)) != 0
        graph = res.put(deserialize_array(fh))
        if has_ds:
            dataset = res.put(deserialize_array(fh))
    finally:
        if own:
            fh.close()
    expect(dataset is not None, "index was saved without its dataset")
    return CagraIndex(jnp.asarray(dataset), jnp.asarray(graph), metric)
