"""Spectral partitioning — ``spectral::partition`` (``spectral/
partition.cuh``): Laplacian smallest eigenvectors (Lanczos) → k-means on
the embedding; plus modularity maximization (``modularity_maximization.
cuh``: largest eigenvectors of the modularity matrix) and partition
quality analysis (edge cut / ratio cut / modularity).

The reference plugs ``lanczos_solver_t`` + ``kmeans_solver_t`` structs
into templated drivers; here the composition is plain function calls —
the eigensolver is ``raft_tpu.sparse.solver.lanczos_smallest`` and the
clusterer is ``raft_tpu.cluster.kmeans``.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core import tracing
from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.cluster import kmeans as _kmeans
from raft_tpu.sparse.types import CSR


def fit_embedding(
    res: Optional[Resources],
    adjacency: CSR,
    n_components: int,
    *,
    normalized: bool = True,
    seed: int = 0,
) -> Tuple[jax.Array, jax.Array]:
    """Spectral embedding: ``n_components`` smallest non-trivial
    Laplacian eigenpairs (drops the constant first eigenvector), the
    reference's ``sparse::spectral::fit_embedding`` path."""
    from raft_tpu.sparse.linalg import laplacian
    from raft_tpu.sparse.solver import lanczos_smallest

    ensure_resources(res)
    with tracing.range("raft_tpu.spectral.fit_embedding"):
        lap = laplacian(adjacency, normalized=normalized)
        evals, evecs = lanczos_smallest(res, lap, n_components + 1, seed=seed)
        return evals[1:], evecs[:, 1:]


def partition(
    res: Optional[Resources],
    adjacency: CSR,
    n_clusters: int,
    *,
    n_eigenvectors: Optional[int] = None,
    normalized: bool = True,
    seed: int = 0,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Graph partition via Laplacian spectral embedding + k-means —
    ``spectral::partition`` (``partition.cuh``).

    Returns (labels, eigenvalues, eigenvectors)."""
    res = ensure_resources(res)
    k = n_eigenvectors or n_clusters
    with tracing.range("raft_tpu.spectral.partition"):
        evals, emb = fit_embedding(
            res, adjacency, k, normalized=normalized, seed=seed
        )
        # row-normalize the embedding (standard normalized spectral
        # clustering; stabilizes k-means on the eigenvector rows)
        norms = jnp.linalg.norm(emb, axis=1, keepdims=True)
        emb_n = emb / jnp.maximum(norms, 1e-12)
        params = _kmeans.KMeansParams(n_clusters=n_clusters, seed=seed)
        _, labels, _, _ = _kmeans.fit_predict(res, params, emb_n)
        return labels, evals, emb


def modularity_maximization(
    res: Optional[Resources],
    adjacency: CSR,
    n_clusters: int,
    *,
    seed: int = 0,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Cluster by the top eigenvectors of the modularity matrix
    ``B = A - d d^T / 2m`` — ``spectral::modularity_maximization``.

    B's largest eigenpairs are the smallest of ``-B``; ``-B`` is applied
    via its sparse-plus-rank-one structure inside Lanczos by shifting:
    here B is formed densely only in the small embedded space via the
    Lanczos operator over CSR + rank-one correction. For the moderate n
    this API targets (graph partitioning), a dense eigh of B is both
    exact and MXU-friendly — the reference's Lanczos exists because
    cuSOLVER eigh on 10^5+ nodes was infeasible; XLA eigh handles the
    sizes tests use, and larger graphs should use ``partition``.
    """
    ensure_resources(res)
    with tracing.range("raft_tpu.spectral.modularity_maximization"):
        a = adjacency.to_dense().astype(jnp.float32)
        deg = jnp.sum(a, axis=1)
        two_m = jnp.maximum(jnp.sum(deg), 1e-12)
        b = a - jnp.outer(deg, deg) / two_m
        evals, evecs = jnp.linalg.eigh(b)
        emb = evecs[:, -n_clusters:]
        norms = jnp.linalg.norm(emb, axis=1, keepdims=True)
        emb_n = emb / jnp.maximum(norms, 1e-12)
        params = _kmeans.KMeansParams(n_clusters=n_clusters, seed=seed)
        _, labels, _, _ = _kmeans.fit_predict(res, params, emb_n)
        return labels, evals[-n_clusters:], emb


def modularity(res: Optional[Resources], adjacency: CSR, labels) -> jax.Array:
    """Modularity Q of a partition — the quantity
    ``spectral::analyzeModularity`` reports."""
    ensure_resources(res)
    a = adjacency.to_dense().astype(jnp.float32)
    deg = jnp.sum(a, axis=1)
    two_m = jnp.maximum(jnp.sum(deg), 1e-12)
    same = labels[:, None] == labels[None, :]
    b = a - jnp.outer(deg, deg) / two_m
    return jnp.sum(jnp.where(same, b, 0.0)) / two_m


def analyze_partition(
    res: Optional[Resources],
    adjacency: CSR,
    labels,
    n_clusters: Optional[int] = None,
) -> Tuple[jax.Array, jax.Array]:
    """(edge cut, ratio cut cost) of a partition —
    ``spectral::analyzePartition`` (``partition.cuh``)."""
    ensure_resources(res)
    labels = jnp.asarray(labels, jnp.int32)
    k = n_clusters or int(jnp.max(labels)) + 1
    a = adjacency.to_dense().astype(jnp.float32)
    cross = labels[:, None] != labels[None, :]
    edge_cut = jnp.sum(jnp.where(cross, a, 0.0)) / 2.0
    onehot = jax.nn.one_hot(labels, k, dtype=jnp.float32)
    sizes = jnp.sum(onehot, axis=0)
    # ratio cut: sum_c cut(c, rest) / |c|
    per_cluster_cut = jnp.sum(
        jnp.where(cross, a, 0.0) @ onehot, axis=0
    ) / 2.0  # symmetric halves
    cost = jnp.sum(
        jnp.where(sizes > 0, 2.0 * per_cluster_cut / jnp.maximum(sizes, 1.0), 0.0)
    )
    return edge_cut, cost
