"""Spectral clustering & graph partitioning — analog of ``raft/spectral/``
(``partition.cuh``, ``modularity_maximization.cuh``, ``eigen_solvers.cuh``,
``cluster_solvers.cuh``).
"""

from raft_tpu.spectral.partition import (
    analyze_partition,
    fit_embedding,
    modularity,
    modularity_maximization,
    partition,
)

__all__ = [
    "analyze_partition",
    "fit_embedding",
    "modularity",
    "modularity_maximization",
    "partition",
]
