"""Deprecated forwarding shims for the pre-``neighbors`` API surface
(``raft/spatial/knn/knn.cuh``). New code should import from
:mod:`raft_tpu.neighbors`."""

import warnings

from raft_tpu.neighbors.ball_cover import (  # noqa: F401
    BallCoverIndex,
    build_index as ball_cover_build_index,
    knn_query as ball_cover_knn_query,
)
from raft_tpu.neighbors.brute_force import knn as _bf_knn
from raft_tpu.neighbors.quantized import knn as ann_quantized_knn  # noqa: F401


def brute_force_knn(res, dataset, queries, k, metric=None, metric_arg=2.0):
    """``spatial::knn::brute_force_knn`` → ``neighbors::brute_force::knn``."""
    warnings.warn(
        "raft_tpu.spatial.knn is deprecated; use raft_tpu.neighbors",
        DeprecationWarning,
        stacklevel=2,
    )
    from raft_tpu.distance.types import DistanceType

    metric = DistanceType.L2Expanded if metric is None else metric
    return _bf_knn(res, dataset, queries, k, metric, metric_arg)


knn = brute_force_knn
