"""Legacy ``spatial.knn`` alias layer — the reference keeps a deprecated
forwarding API (``raft/spatial/knn/knn.cuh:89,125``) so existing callers
keep working after the ``neighbors`` rename. Same courtesy here."""

from raft_tpu.spatial import knn

__all__ = ["knn"]
