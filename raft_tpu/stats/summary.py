"""Summary statistics — analog of ``stats/mean.cuh``, ``stats/var.cuh``,
``stats/stddev.cuh``, ``stats/cov.cuh``, ``stats/histogram.cuh``,
``stats/minmax.cuh``, ``stats/weighted_mean.cuh``, ``stats/sum.cuh``,
``stats/mean_center.cuh``.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core.resources import Resources
from raft_tpu.core.validation import expect


def mean(res: Optional[Resources], data, *, along_rows: bool = False):
    """Column means by default (``stats::mean`` reduces over rows of a
    column-major sample matrix; samples are rows here)."""
    axis = 1 if along_rows else 0
    return jnp.mean(data.astype(jnp.float32), axis=axis)


def sum_stat(res: Optional[Resources], data, *, along_rows: bool = False):
    """``stats::sum``."""
    axis = 1 if along_rows else 0
    return jnp.sum(data.astype(jnp.float32), axis=axis)


def var(res: Optional[Resources], data, mu=None, *, sample: bool = True):
    """Column variances (``stats::vars``); ``sample=True`` → N-1 norm."""
    x = data.astype(jnp.float32)
    if mu is None:
        mu = jnp.mean(x, axis=0)
    n = x.shape[0]
    denom = max(n - 1, 1) if sample else n
    return jnp.sum(jnp.square(x - mu[None, :]), axis=0) / denom


def stddev(res: Optional[Resources], data, mu=None, *, sample: bool = True):
    """``stats::stddev``."""
    return jnp.sqrt(var(res, data, mu, sample=sample))


def meanvar(res: Optional[Resources], data, *, sample: bool = True):
    """Fused mean + variance in one pass (``stats/meanvar.cuh``)."""
    x = data.astype(jnp.float32)
    mu = jnp.mean(x, axis=0)
    return mu, var(res, data, mu, sample=sample)


def regression_metrics(res: Optional[Resources], predictions, ref):
    """Mean-absolute / mean-squared / median-absolute error
    (``stats/regression_metrics.cuh``). Returns (mae, mse, medae)."""
    p = jnp.asarray(predictions, jnp.float32).ravel()
    r = jnp.asarray(ref, jnp.float32).ravel()
    err = jnp.abs(p - r)
    return (jnp.mean(err), jnp.mean(jnp.square(p - r)), jnp.median(err))


def mean_center(res: Optional[Resources], data, mu=None):
    """``stats::meanCenter``: subtract column means."""
    x = data.astype(jnp.float32)
    if mu is None:
        mu = jnp.mean(x, axis=0)
    return x - mu[None, :]


def cov(
    res: Optional[Resources],
    data,
    mu=None,
    *,
    sample: bool = True,
):
    """Covariance matrix of row-sample data — ``stats::cov``
    (``stats/cov.cuh``): one centered MXU GEMM."""
    x = mean_center(res, data, mu)
    n = x.shape[0]
    denom = max(n - 1, 1) if sample else n
    return jax.lax.dot_general(
        x, x, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    ) / denom


def histogram(
    res: Optional[Resources],
    data,
    n_bins: int,
    *,
    lo: Optional[float] = None,
    hi: Optional[float] = None,
):
    """Per-column histograms — ``stats::histogram``
    (``stats/histogram.cuh``). Returns ``(n_bins, n_cols)`` int32 counts.

    The reference offers many binning strategies tuned for GPU shared
    memory; one bucketed one-hot reduction covers them on TPU.
    """
    x = data.astype(jnp.float32)
    if x.ndim == 1:
        x = x[:, None]
    lo_v = jnp.min(x) if lo is None else lo
    hi_v = jnp.max(x) if hi is None else hi
    width = jnp.maximum((hi_v - lo_v) / n_bins, 1e-30)
    idx = jnp.clip(((x - lo_v) / width).astype(jnp.int32), 0, n_bins - 1)
    onehot = jax.nn.one_hot(idx, n_bins, dtype=jnp.int32, axis=0)  # (bins, n, c)
    return jnp.sum(onehot, axis=1)


def minmax(
    res: Optional[Resources], data
) -> Tuple[jax.Array, jax.Array]:
    """Per-column (min, max) — ``stats::minmax`` (``stats/minmax.cuh``)."""
    return jnp.min(data, axis=0), jnp.max(data, axis=0)


def weighted_mean(
    res: Optional[Resources],
    data,
    weights,
    *,
    along_rows: bool = True,
):
    """Weighted mean — ``stats::rowWeightedMean`` / ``colWeightedMean``.

    ``along_rows=True`` averages within each row with one weight per
    column (the reference's row-weighted-mean), producing one value per
    row."""
    x = data.astype(jnp.float32)
    w = weights.astype(jnp.float32)
    wsum = jnp.maximum(jnp.sum(w), 1e-30)
    if along_rows:
        expect(w.shape[0] == x.shape[1], "weighted_mean: |weights| != n_cols")
        return x @ w / wsum
    expect(w.shape[0] == x.shape[0], "weighted_mean: |weights| != n_rows")
    return w @ x / wsum
