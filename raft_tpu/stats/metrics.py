"""ML evaluation metrics — analog of ``stats/accuracy.cuh``,
``stats/r2_score.cuh``, ``stats/entropy.cuh``, ``stats/kl_divergence.cuh``,
``stats/contingency_matrix.cuh``, ``stats/rand_index.cuh``,
``stats/adjusted_rand_index.cuh``, ``stats/mutual_info_score.cuh``,
``stats/homogeneity_score.cuh``, ``stats/completeness_score.cuh``,
``stats/v_measure.cuh``, ``stats/silhouette_score.cuh``,
``stats/trustworthiness_score.cuh``, ``stats/information_criterion.cuh``,
``stats/dispersion.cuh``.

Clustering-comparison metrics all flow through one contingency-matrix
builder (a one-hot MXU GEMM) the way the reference funnels them through
``contingencyMatrix``.
"""

from __future__ import annotations

import enum
from typing import Optional

import jax
import jax.numpy as jnp

from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.core.validation import expect
from raft_tpu.distance.types import DistanceType

_EPS = 1e-12


def accuracy(res: Optional[Resources], predictions, labels):
    """Fraction of exact matches — ``stats::accuracy``."""
    return jnp.mean((predictions == labels).astype(jnp.float32))


def r2_score(res: Optional[Resources], y, y_hat):
    """Coefficient of determination — ``stats::r2_score``."""
    y = y.astype(jnp.float32)
    y_hat = y_hat.astype(jnp.float32)
    ss_res = jnp.sum(jnp.square(y - y_hat))
    ss_tot = jnp.sum(jnp.square(y - jnp.mean(y)))
    return 1.0 - ss_res / jnp.maximum(ss_tot, _EPS)


def entropy(res: Optional[Resources], labels, n_classes: int):
    """Shannon entropy (nats) of a label set — ``stats::entropy``."""
    counts = jnp.bincount(labels.astype(jnp.int32), length=n_classes)
    p = counts / jnp.maximum(jnp.sum(counts), 1)
    return -jnp.sum(jnp.where(p > 0, p * jnp.log(jnp.maximum(p, _EPS)), 0.0))


def kl_divergence(res: Optional[Resources], p, q):
    """KL(p ‖ q) over two distributions — ``stats::kl_divergence``."""
    p = p.astype(jnp.float32)
    q = q.astype(jnp.float32)
    return jnp.sum(jnp.where(p > 0, p * jnp.log(jnp.maximum(p, _EPS) /
                                                jnp.maximum(q, _EPS)), 0.0))


def contingency_matrix(
    res: Optional[Resources],
    labels_a,
    labels_b,
    n_classes_a: Optional[int] = None,
    n_classes_b: Optional[int] = None,
):
    """(n_classes_a, n_classes_b) co-occurrence counts —
    ``stats::contingencyMatrix``; one-hot GEMM instead of the reference's
    shared-memory atomic kernels."""
    la = labels_a.astype(jnp.int32)
    lb = labels_b.astype(jnp.int32)
    na = n_classes_a if n_classes_a is not None else int(jnp.max(la)) + 1
    nb = n_classes_b if n_classes_b is not None else int(jnp.max(lb)) + 1
    oa = jax.nn.one_hot(la, na, dtype=jnp.float32)
    ob = jax.nn.one_hot(lb, nb, dtype=jnp.float32)
    return jax.lax.dot_general(
        oa, ob, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    ).astype(jnp.int32)


def _comb2(x):
    x = x.astype(jnp.float32)
    return x * (x - 1.0) / 2.0


def rand_index(res: Optional[Resources], labels_a, labels_b):
    """Rand index — ``stats::rand_index``."""
    cm = contingency_matrix(res, labels_a, labels_b).astype(jnp.float32)
    n = jnp.sum(cm)
    sum_ij = jnp.sum(_comb2(cm))
    sum_a = jnp.sum(_comb2(jnp.sum(cm, axis=1)))
    sum_b = jnp.sum(_comb2(jnp.sum(cm, axis=0)))
    total = _comb2(n)
    return (total + 2.0 * sum_ij - sum_a - sum_b) / jnp.maximum(total, _EPS)


def adjusted_rand_index(res: Optional[Resources], labels_a, labels_b):
    """Adjusted Rand index — ``stats::adjusted_rand_index``."""
    cm = contingency_matrix(res, labels_a, labels_b).astype(jnp.float32)
    n = jnp.sum(cm)
    sum_ij = jnp.sum(_comb2(cm))
    sum_a = jnp.sum(_comb2(jnp.sum(cm, axis=1)))
    sum_b = jnp.sum(_comb2(jnp.sum(cm, axis=0)))
    total = jnp.maximum(_comb2(n), _EPS)
    expected = sum_a * sum_b / total
    max_index = 0.5 * (sum_a + sum_b)
    return (sum_ij - expected) / jnp.maximum(max_index - expected, _EPS)


def mutual_info_score(res: Optional[Resources], labels_a, labels_b):
    """Mutual information (nats) — ``stats::mutual_info_score``."""
    cm = contingency_matrix(res, labels_a, labels_b).astype(jnp.float32)
    n = jnp.maximum(jnp.sum(cm), 1.0)
    p_ij = cm / n
    p_a = jnp.sum(p_ij, axis=1, keepdims=True)
    p_b = jnp.sum(p_ij, axis=0, keepdims=True)
    ratio = p_ij / jnp.maximum(p_a * p_b, _EPS)
    return jnp.sum(jnp.where(p_ij > 0,
                             p_ij * jnp.log(jnp.maximum(ratio, _EPS)), 0.0))


def homogeneity_score(res: Optional[Resources], labels_true, labels_pred,
                      n_classes: Optional[int] = None):
    """``stats::homogeneity_score``: 1 - H(C|K)/H(C)."""
    nc = n_classes or int(jnp.max(labels_true)) + 1
    mi = mutual_info_score(res, labels_true, labels_pred)
    h_c = entropy(res, labels_true, nc)
    return jnp.where(h_c > _EPS, mi / jnp.maximum(h_c, _EPS), 1.0)


def completeness_score(res: Optional[Resources], labels_true, labels_pred,
                       n_classes: Optional[int] = None):
    """``stats::completeness_score``: 1 - H(K|C)/H(K)."""
    nk = n_classes or int(jnp.max(labels_pred)) + 1
    mi = mutual_info_score(res, labels_true, labels_pred)
    h_k = entropy(res, labels_pred, nk)
    return jnp.where(h_k > _EPS, mi / jnp.maximum(h_k, _EPS), 1.0)


def v_measure(res: Optional[Resources], labels_true, labels_pred,
              beta: float = 1.0):
    """``stats::v_measure``: weighted harmonic mean of homogeneity and
    completeness."""
    h = homogeneity_score(res, labels_true, labels_pred)
    c = completeness_score(res, labels_true, labels_pred)
    return jnp.where(h + c > _EPS,
                     (1 + beta) * h * c / jnp.maximum(beta * h + c, _EPS),
                     0.0)


def silhouette_score(
    res: Optional[Resources],
    x,
    labels,
    n_clusters: Optional[int] = None,
    metric: DistanceType = DistanceType.L2SqrtExpanded,
    *,
    tile: int = 4096,
):
    """Mean silhouette coefficient — ``stats::silhouette_score`` (and its
    ``batched::`` variant: ``tile`` bounds the distance buffer at
    ``tile × n``, the reference's chunking knob).

    Per-sample mean distance to every cluster is one distance-tile ×
    one-hot GEMM; a(i)/b(i) then come from the (tile, n_clusters) matrix.
    """
    from raft_tpu.distance.pairwise import _pairwise_distance_impl

    res = ensure_resources(res)
    x = jnp.asarray(x)
    labels = jnp.asarray(labels, jnp.int32)
    n = x.shape[0]
    k = n_clusters or int(jnp.max(labels)) + 1
    expect(k >= 2, "silhouette_score requires >= 2 clusters")
    onehot = jax.nn.one_hot(labels, k, dtype=jnp.float32)   # (n, k)
    counts = jnp.sum(onehot, axis=0)                        # (k,)

    scores = []
    for start in range(0, n, tile):
        stop = min(start + tile, n)
        d = _pairwise_distance_impl(x[start:stop], x, metric, 2.0, "highest")
        # sum distance from each row to every cluster: (t, n) @ (n, k)
        sums = d @ onehot
        lt = labels[start:stop]
        own = counts[lt]                                     # cluster sizes
        own_sum = jnp.take_along_axis(sums, lt[:, None], axis=1)[:, 0]
        a = own_sum / jnp.maximum(own - 1.0, 1.0)            # excl. self (d=0)
        other_mean = sums / jnp.maximum(counts[None, :], 1.0)
        other_mean = jnp.where(
            jax.nn.one_hot(lt, k, dtype=bool), jnp.inf, other_mean)
        b = jnp.min(other_mean, axis=1)
        s = (b - a) / jnp.maximum(jnp.maximum(a, b), _EPS)
        s = jnp.where(own <= 1.0, 0.0, s)  # singleton convention
        scores.append(s)
    return jnp.mean(jnp.concatenate(scores))


def trustworthiness(
    res: Optional[Resources],
    x,
    x_embedded,
    k: int,
    metric: DistanceType = DistanceType.L2SqrtExpanded,
):
    """Trustworthiness of an embedding — ``stats::trustworthiness_score``:
    penalizes embedded-space neighbors that are far in the original space
    by their original-space rank."""
    from raft_tpu.distance.pairwise import _pairwise_distance_impl

    res = ensure_resources(res)
    x = jnp.asarray(x)
    xe = jnp.asarray(x_embedded)
    n = x.shape[0]
    expect(k < n / 2, "trustworthiness: k must be < n/2")

    d_orig = _pairwise_distance_impl(x, x, metric, 2.0, "highest")
    d_emb = _pairwise_distance_impl(xe, xe, metric, 2.0, "highest")
    eye = jnp.eye(n, dtype=bool)
    d_orig = jnp.where(eye, jnp.inf, d_orig)
    d_emb = jnp.where(eye, jnp.inf, d_emb)

    # original-space rank of every pair (0 = nearest)
    order_orig = jnp.argsort(d_orig, axis=1)
    ranks = jnp.zeros((n, n), jnp.int32)
    ranks = jax.vmap(
        lambda r, o: r.at[o].set(jnp.arange(n, dtype=jnp.int32))
    )(ranks, order_orig)

    # k nearest in embedded space
    _, nn_emb = jax.lax.top_k(-d_emb, k)
    r = jnp.take_along_axis(ranks, nn_emb, axis=1)          # (n, k)
    penalty = jnp.sum(jnp.maximum(r - k + 1, 0).astype(jnp.float32))
    norm = 2.0 / (n * k * (2.0 * n - 3.0 * k - 1.0))
    return 1.0 - norm * penalty


class ICType(enum.IntEnum):
    """``stats::IC_Type`` (``stats/information_criterion.cuh``)."""

    AIC = 0
    AICc = 1
    BIC = 2


def information_criterion(
    res: Optional[Resources],
    log_likelihood,
    ic_type: ICType,
    n_params: int,
    n_samples: int,
):
    """Batched AIC/AICc/BIC — ``stats::information_criterion_batched``."""
    ll = jnp.asarray(log_likelihood, jnp.float32)
    d = float(n_params)
    n = float(n_samples)
    if ic_type == ICType.AIC:
        pen = 2.0 * d
    elif ic_type == ICType.AICc:
        pen = 2.0 * d + 2.0 * d * (d + 1.0) / max(n - d - 1.0, 1e-6)
    elif ic_type == ICType.BIC:
        pen = jnp.log(n) * d
    else:
        raise ValueError(f"unknown IC type: {ic_type}")
    return -2.0 * ll + pen


def dispersion(
    res: Optional[Resources],
    centroids,
    cluster_sizes,
    global_centroid=None,
):
    """Cluster dispersion sqrt(Σ_c n_c ‖μ_c − μ‖²) — ``stats::dispersion``
    (used by kmeans ``find_k``)."""
    c = centroids.astype(jnp.float32)
    sz = cluster_sizes.astype(jnp.float32)
    if global_centroid is None:
        global_centroid = (sz @ c) / jnp.maximum(jnp.sum(sz), 1.0)
    d2 = jnp.sum(jnp.square(c - global_centroid[None, :]), axis=1)
    return jnp.sqrt(jnp.sum(sz * d2))
