"""Statistics — TPU-native re-design of ``raft/stats/`` (28 headers,
SURVEY.md §2.2): summary statistics plus ML evaluation metrics.

The reference hand-writes a CUDA kernel per statistic; here each is a
fused XLA expression (VPU reductions, one-hot MXU GEMMs for contingency
/ grouped statistics), keeping the reference's free-function API shape.
"""

from raft_tpu.stats.summary import (
    cov,
    histogram,
    mean,
    mean_center,
    meanvar,
    minmax,
    regression_metrics,
    stddev,
    sum_stat,
    var,
    weighted_mean,
)
from raft_tpu.stats.metrics import (
    accuracy,
    adjusted_rand_index,
    completeness_score,
    contingency_matrix,
    dispersion,
    entropy,
    homogeneity_score,
    information_criterion,
    kl_divergence,
    mutual_info_score,
    r2_score,
    rand_index,
    silhouette_score,
    trustworthiness,
    v_measure,
)

__all__ = [
    "cov",
    "histogram",
    "mean",
    "mean_center",
    "minmax",
    "stddev",
    "sum_stat",
    "var",
    "weighted_mean",
    "accuracy",
    "adjusted_rand_index",
    "completeness_score",
    "contingency_matrix",
    "dispersion",
    "entropy",
    "homogeneity_score",
    "information_criterion",
    "kl_divergence",
    "mutual_info_score",
    "r2_score",
    "rand_index",
    "silhouette_score",
    "trustworthiness",
    "trustworthiness_score",
    "v_measure",
    "meanvar",
    "regression_metrics",
]

# reference naming alias (``stats::trustworthiness_score``)
trustworthiness_score = trustworthiness
