"""Pairwise distance matrices — TPU-native re-design of ``raft/distance/``.

The reference implements one tiled register-blocked CUDA kernel
(``distance/detail/pairwise_matrix/kernel_sm60.cuh``) parameterized by
per-metric ``core()``/``epilog()`` structs (``distance/detail/distance_ops/``)
plus a CUTLASS path for L2/cosine on SM80. On TPU the same split maps to:

- **expanded metrics** → one ``jnp.dot`` on the MXU (f32 accumulation)
  followed by a vectorized epilog using precomputed row norms — exactly the
  ``core=x*y`` + ``epilog`` decomposition of the reference, but the GEMM is
  XLA's, which already tiles for MXU/VMEM;
- **unexpanded metrics** (elementwise accumulators like L1/Linf/Canberra)
  → broadcast-reduce expressions that XLA fuses into a single VPU kernel;
  row-tiled by the caller (brute-force kNN) to bound the m×n buffer.

Numerical behaviors intentionally matched to the reference:
zero-denominator guards in Canberra/KL/JensenShannon, the L2-expanded
negative clamp, Hellinger NaN rectification, Hamming/RusselRao 1/k scaling.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from raft_tpu.core import tracing
from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.core.validation import expect
from raft_tpu.distance.types import DistanceType

_EPS_L2_CLAMP = 1e-4  # mirrors the |val| >= 0.0001 rectifier in l2_exp epilog


def _dot(x, y, precision):
    """MXU GEMM with f32 accumulation: the `core` of all expanded metrics."""
    return jax.lax.dot_general(
        x,
        y,
        (((1,), (1,)), ((), ())),
        precision=precision,
        preferred_element_type=jnp.float32,
    )


def _row_sq_norms(x, precision):
    return jnp.sum(jnp.square(x.astype(jnp.float32)), axis=1)


# ---------------------------------------------------------------------------
# expanded family: GEMM + epilog  (reference distance_ops/*.cuh)
# ---------------------------------------------------------------------------


def _l2_expanded(x, y, sqrt: bool, precision):
    """``distance_ops/l2_exp.cuh``: xn + yn - 2 ip, clamped at ±1e-4."""
    ip = _dot(x, y, precision)
    xn = _row_sq_norms(x, precision)[:, None]
    yn = _row_sq_norms(y, precision)[None, :]
    val = xn + yn - 2.0 * ip
    # the reference zeroes |val| < 1e-4 to avoid sqrt(negative) from
    # cancellation (self-distances); reproduce for test parity
    val = val * (jnp.abs(val) >= _EPS_L2_CLAMP)
    val = jnp.maximum(val, 0.0)
    return jnp.sqrt(val) if sqrt else val


def _cosine(x, y, precision):
    """``distance_ops/cosine.cuh``: 1 - ip / (|x| |y|)."""
    ip = _dot(x, y, precision)
    xn = jnp.sqrt(_row_sq_norms(x, precision))[:, None]
    yn = jnp.sqrt(_row_sq_norms(y, precision))[None, :]
    return 1.0 - ip / (xn * yn)

def _inner_product(x, y, precision):
    return _dot(x, y, precision)


def _correlation(x, y, precision):
    """``distance_ops/correlation.cuh``: 1 - pearson r via expanded sums."""
    k = x.shape[1]
    ip = _dot(x, y, precision)
    sx = jnp.sum(x.astype(jnp.float32), axis=1)[:, None]
    sy = jnp.sum(y.astype(jnp.float32), axis=1)[None, :]
    sx2 = _row_sq_norms(x, precision)[:, None]
    sy2 = _row_sq_norms(y, precision)[None, :]
    numer = k * ip - sx * sy
    q_denom = k * sx2 - sx * sx
    r_denom = k * sy2 - sy * sy
    return 1.0 - numer / jnp.sqrt(q_denom * r_denom)


def _hellinger(x, y, precision):
    """``distance_ops/hellinger.cuh``: inputs pre-sqrt'ed, then
    sqrt(rectify(1 - ip))."""
    ip = _dot(
        jnp.sqrt(x.astype(jnp.float32)), jnp.sqrt(y.astype(jnp.float32)), precision
    )
    final = 1.0 - ip
    return jnp.sqrt(jnp.maximum(final, 0.0))


def _russel_rao(x, y, precision):
    """``distance_ops/russel_rao.cuh``: (k - ip) / k over binary data."""
    k = x.shape[1]
    ip = _dot(x, y, precision)
    return (k - ip) * (1.0 / k)


def _jaccard(x, y, precision):
    """Expanded Jaccard (sparse ref ``sparse/distance/detail/ip_distance.cuh``
    family): 1 - ip / (|x|^2 + |y|^2 - ip)."""
    ip = _dot(x, y, precision)
    xn = _row_sq_norms(x, precision)[:, None]
    yn = _row_sq_norms(y, precision)[None, :]
    denom = xn + yn - ip
    return 1.0 - jnp.where(denom != 0, ip / jnp.where(denom == 0, 1.0, denom), 0.0)


def _dice(x, y, precision):
    """Expanded Dice-Sørensen: 1 - 2 ip / (|x|^2 + |y|^2)."""
    ip = _dot(x, y, precision)
    xn = _row_sq_norms(x, precision)[:, None]
    yn = _row_sq_norms(y, precision)[None, :]
    denom = xn + yn
    return 1.0 - jnp.where(denom != 0, 2.0 * ip / jnp.where(denom == 0, 1.0, denom), 0.0)


def _kl_divergence(x, y, precision):
    """``distance_ops/kl_divergence.cuh`` (distinct-buffer path): the
    reference pre-transforms y -> log(y) (0 where y==0) and accumulates
    x * (log x - log y), i.e. a GEMM in disguise:
    sum_k x log x  -  x @ log(y)^T."""
    xf = x.astype(jnp.float32)
    yf = y.astype(jnp.float32)
    xlogx = jnp.sum(jnp.where(xf == 0, 0.0, xf * jnp.log(jnp.where(xf == 0, 1.0, xf))), axis=1)
    ylog = jnp.where(yf == 0, 0.0, jnp.log(jnp.where(yf == 0, 1.0, yf)))
    cross = jax.lax.dot_general(
        xf, ylog, (((1,), (1,)), ((), ())), precision=precision,
        preferred_element_type=jnp.float32,
    )
    return xlogx[:, None] - cross


# ---------------------------------------------------------------------------
# unexpanded family: broadcast-reduce on the VPU
# ---------------------------------------------------------------------------


def _pairwise_reduce(x, y, elem_fn, reduce_fn=jnp.sum):
    """Generic unexpanded pairwise: reduce(elem_fn(x_i, y_j)) over features.

    Expressed as a broadcast so XLA fuses elem+reduce into one kernel; the
    (m, n, d) intermediate only exists tiled in VMEM after fusion.
    """
    xf = x.astype(jnp.float32)[:, None, :]
    yf = y.astype(jnp.float32)[None, :, :]
    return reduce_fn(elem_fn(xf, yf), axis=2)


def _l1(x, y):
    return _pairwise_reduce(x, y, lambda a, b: jnp.abs(a - b))


def _l2_unexpanded(x, y, sqrt: bool):
    d = _pairwise_reduce(x, y, lambda a, b: jnp.square(a - b))
    return jnp.sqrt(d) if sqrt else d


def _linf(x, y):
    return _pairwise_reduce(x, y, lambda a, b: jnp.abs(a - b), reduce_fn=jnp.max)


def _canberra(x, y):
    def elem(a, b):
        diff = jnp.abs(a - b)
        add = jnp.abs(a) + jnp.abs(b)
        return jnp.where(add != 0, diff / jnp.where(add == 0, 1.0, add), 0.0)

    return _pairwise_reduce(x, y, elem)


def _lp_unexpanded(x, y, p: float):
    expect(p > 0, "LpUnexpanded requires metric_arg > 0")
    d = _pairwise_reduce(x, y, lambda a, b: jnp.power(jnp.abs(a - b), p))
    return jnp.power(d, 1.0 / p)


def _braycurtis(x, y):
    num = _pairwise_reduce(x, y, lambda a, b: jnp.abs(a - b))
    den = _pairwise_reduce(x, y, lambda a, b: jnp.abs(a + b))
    return jnp.where(den != 0, num / jnp.where(den == 0, 1.0, den), 0.0)


def _jensen_shannon(x, y):
    """``distance_ops/jensen_shannon.cuh``: sqrt(0.5 (KL(x|m)+KL(y|m)))."""

    def elem(a, b):
        m = 0.5 * (a + b)
        log_m = jnp.where(m == 0, 0.0, jnp.log(jnp.where(m == 0, 1.0, m)))
        ax = jnp.where(a == 0, 0.0, a * (jnp.log(jnp.where(a == 0, 1.0, a)) - log_m))
        bx = jnp.where(b == 0, 0.0, b * (jnp.log(jnp.where(b == 0, 1.0, b)) - log_m))
        return ax + bx

    return jnp.sqrt(0.5 * _pairwise_reduce(x, y, elem))


def _hamming(x, y):
    """``distance_ops/hamming.cuh``: mean of (x_i != y_i)."""
    k = x.shape[1]
    return _pairwise_reduce(x, y, lambda a, b: (a != b).astype(jnp.float32)) / k


def _haversine(x, y):
    """Great-circle distance over (lat, lon) radians
    (``spatial/knn/detail/haversine_distance.cuh:33``)."""
    expect(x.shape[1] == 2, "Haversine requires 2-D (lat, lon) inputs")
    x1, x2 = x[:, 0][:, None], x[:, 1][:, None]
    y1, y2 = y[:, 0][None, :], y[:, 1][None, :]
    sin_lat = jnp.sin(0.5 * (x1 - y1))
    sin_lon = jnp.sin(0.5 * (x2 - y2))
    a = sin_lat**2 + jnp.cos(x1) * jnp.cos(y1) * sin_lon**2
    return 2.0 * jnp.arcsin(jnp.sqrt(jnp.clip(a, 0.0, 1.0)))


# ---------------------------------------------------------------------------
# dispatch
# ---------------------------------------------------------------------------


def _pairwise_distance_impl(x, y, metric: DistanceType, metric_arg: float, precision):
    m = DistanceType(metric)
    if m == DistanceType.L2Expanded:
        return _l2_expanded(x, y, False, precision)
    if m == DistanceType.L2SqrtExpanded:
        return _l2_expanded(x, y, True, precision)
    if m == DistanceType.CosineExpanded:
        return _cosine(x, y, precision)
    if m == DistanceType.InnerProduct:
        return _inner_product(x, y, precision)
    if m == DistanceType.CorrelationExpanded:
        return _correlation(x, y, precision)
    if m == DistanceType.HellingerExpanded:
        return _hellinger(x, y, precision)
    if m == DistanceType.RusselRaoExpanded:
        return _russel_rao(x, y, precision)
    if m == DistanceType.JaccardExpanded:
        return _jaccard(x, y, precision)
    if m == DistanceType.DiceExpanded:
        return _dice(x, y, precision)
    if m == DistanceType.KLDivergence:
        return _kl_divergence(x, y, precision)
    if m == DistanceType.L1:
        return _l1(x, y)
    if m == DistanceType.L2Unexpanded:
        return _l2_unexpanded(x, y, False)
    if m == DistanceType.L2SqrtUnexpanded:
        return _l2_unexpanded(x, y, True)
    if m == DistanceType.Linf:
        return _linf(x, y)
    if m == DistanceType.Canberra:
        return _canberra(x, y)
    if m == DistanceType.LpUnexpanded:
        return _lp_unexpanded(x, y, metric_arg)
    if m == DistanceType.BrayCurtis:
        return _braycurtis(x, y)
    if m == DistanceType.JensenShannon:
        return _jensen_shannon(x, y)
    if m == DistanceType.HammingUnexpanded:
        return _hamming(x, y)
    if m == DistanceType.Haversine:
        return _haversine(x, y)
    raise NotImplementedError(f"metric {m!r} not supported by pairwise_distance")


def pairwise_distance(
    res: Optional[Resources],
    x,
    y,
    metric: DistanceType = DistanceType.L2Expanded,
    metric_arg: float = 2.0,
):
    """Full m×n distance matrix — analog of ``distance::pairwise_distance``
    (``distance/distance-inl.cuh:255``).

    Args:
      res: resources handle (or None for defaults).
      x: (m, d) queries.
      y: (n, d) database.
      metric: one of :class:`DistanceType` (20 metrics).
      metric_arg: p for ``LpUnexpanded``.

    Returns:
      float32 (m, n) distances. For ``InnerProduct`` larger means closer
      (``is_min_close``); everything else is a proper distance.

    Examples
    --------
    >>> import numpy as np
    >>> from raft_tpu.distance import pairwise_distance
    >>> x = np.zeros((2, 3), np.float32)
    >>> y = np.ones((1, 3), np.float32)
    >>> np.asarray(pairwise_distance(None, x, y)).ravel().tolist()
    [3.0, 3.0]
    """
    res = ensure_resources(res)
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    expect(x.ndim == 2 and y.ndim == 2, "x and y must be 2-D")
    expect(
        x.shape[1] == y.shape[1],
        f"feature dims differ: {x.shape[1]} vs {y.shape[1]}",
    )
    with tracing.range("raft_tpu.pairwise_distance"):
        return _pairwise_distance_impl(x, y, metric, metric_arg, res.matmul_precision)


def pairwise_distance_tiled(
    res: Optional[Resources],
    x,
    y,
    metric: DistanceType,
    metric_arg: float = 2.0,
    row_tile: int = 4096,
):
    """Row-tiled variant bounding peak memory to ``row_tile × n`` — the
    analog of the tiling loop in ``detail/knn_brute_force.cuh:57-90``,
    exposed for large m×n jobs that only need streaming access."""
    res = ensure_resources(res)
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    m = x.shape[0]
    if m <= row_tile:
        return pairwise_distance(res, x, y, metric, metric_arg)
    pad = (-m) % row_tile
    xp = jnp.pad(x, ((0, pad), (0, 0)))
    tiles = xp.reshape(-1, row_tile, x.shape[1])

    def one(tile):
        return _pairwise_distance_impl(tile, y, metric, metric_arg, res.matmul_precision)

    out = jax.lax.map(one, tiles)
    return out.reshape(-1, y.shape[0])[:m]
