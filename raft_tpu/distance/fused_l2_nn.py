"""Fused L2 nearest-neighbor (distance + argmin without materializing m×n).

Analog of ``fusedL2NN`` / ``fusedL2NNMinReduce``
(``distance/fused_l2_nn-inl.cuh:76,151``) — the hot kernel inside balanced
k-means EM (SURVEY.md §3.1). The reference fuses the GEMM epilog with a
warp argmin; on TPU we keep the GEMM on the MXU and fuse the argmin into
the same jit program, tiling over the *center* axis with ``lax.scan`` so
peak memory is ``m × tile`` instead of ``m × n``. XLA fuses the epilog
(norm add + min/argmin) into the GEMM consumer, which is the same
memory-traffic win the CUDA fusion buys.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core import tracing
from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.core.validation import expect


@partial(jax.jit, static_argnames=("sqrt", "tile"))
def _fused_l2_nn(x, y, y_sq_norms, sqrt: bool, tile: int):
    m, d = x.shape
    n = y.shape[0]
    xf = x.astype(jnp.float32)
    x_sq = jnp.sum(jnp.square(xf), axis=1)

    pad = (-n) % tile
    yp = jnp.pad(y.astype(jnp.float32), ((0, pad), (0, 0)))
    ynp = jnp.pad(y_sq_norms.astype(jnp.float32), (0, pad), constant_values=jnp.inf)
    y_tiles = yp.reshape(-1, tile, d)
    yn_tiles = ynp.reshape(-1, tile)

    def step(carry, inp):
        best_val, best_idx = carry
        tile_idx, (yt, ynt) = inp
        # (m, tile) partial distances: ||x||^2 dropped (constant per row)
        ip = jax.lax.dot_general(
            xf, yt, (((1,), (1,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32,
        )
        part = ynt[None, :] - 2.0 * ip
        idx = jnp.argmin(part, axis=1)
        val = jnp.take_along_axis(part, idx[:, None], axis=1)[:, 0]
        gidx = tile_idx * tile + idx
        better = val < best_val
        return (
            jnp.where(better, val, best_val),
            jnp.where(better, gidx, best_idx),
        ), None

    init = (jnp.full((m,), jnp.inf, jnp.float32), jnp.zeros((m,), jnp.int32))
    (best_val, best_idx), _ = jax.lax.scan(
        step, init, (jnp.arange(y_tiles.shape[0]), (y_tiles, yn_tiles))
    )
    dist = best_val + x_sq
    dist = jnp.maximum(dist, 0.0)
    if sqrt:
        dist = jnp.sqrt(dist)
    return dist, best_idx.astype(jnp.int32)


def fused_l2_nn_argmin(
    res: Optional[Resources],
    x,
    y,
    sqrt: bool = False,
    tile: int = 2048,
) -> Tuple[jax.Array, jax.Array]:
    """For each row of ``x``, the (distance, index) of its L2-nearest row
    of ``y`` — the ``fusedL2NNMinReduce`` entry point.

    Returns ``(min_dist[m] float32, argmin[m] int32)``; distances are
    squared L2 unless ``sqrt``.
    """
    ensure_resources(res)
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    expect(x.ndim == 2 and y.ndim == 2, "x and y must be 2-D")
    expect(x.shape[1] == y.shape[1], "feature dims differ")
    y_sq = jnp.sum(jnp.square(y.astype(jnp.float32)), axis=1)
    with tracing.range("raft_tpu.fused_l2_nn"):
        return _fused_l2_nn(x, y, y_sq, sqrt, min(tile, max(64, y.shape[0])))


def fused_l2_nn_argmin_precomputed(x, y, y_sq_norms, sqrt: bool = False, tile: int = 2048):
    """Variant taking precomputed ``||y||^2`` (the k-means hot loop reuses
    center norms across EM iterations, mirroring ``fusedL2NN``'s norm
    arguments)."""
    return _fused_l2_nn(
        jnp.asarray(x), jnp.asarray(y), jnp.asarray(y_sq_norms), sqrt,
        min(tile, max(64, jnp.asarray(y).shape[0])),
    )
