"""Kernel gram matrices — analog of ``raft/distance/kernels.cuh``.

Reference (``distance/detail/kernels/gram_matrix.cuh`` +
``distance_types.hpp`` ``kernels::KernelType``): LINEAR, POLYNOMIAL, RBF,
TANH gram matrices for SVM-style methods. All four ride one MXU GEMM.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional

import jax
import jax.numpy as jnp

from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.distance.pairwise import pairwise_distance
from raft_tpu.distance.types import DistanceType


class KernelType(enum.IntEnum):
    LINEAR = 0
    POLYNOMIAL = 1
    RBF = 2
    TANH = 3


@dataclasses.dataclass(frozen=True)
class KernelParams:
    """Mirrors ``raft::distance::kernels::KernelParams``."""

    kernel: KernelType = KernelType.LINEAR
    degree: int = 3
    gamma: float = 1.0
    coef0: float = 0.0


def gram_matrix(
    res: Optional[Resources],
    x,
    y,
    params: KernelParams = KernelParams(),
) -> jax.Array:
    """Compute K(x_i, y_j) for all pairs.

    LINEAR: <x,y>; POLYNOMIAL: (gamma <x,y> + coef0)^degree;
    RBF: exp(-gamma |x-y|^2); TANH: tanh(gamma <x,y> + coef0).
    """
    res = ensure_resources(res)
    if params.kernel == KernelType.RBF:
        sq = pairwise_distance(res, x, y, DistanceType.L2Expanded)
        return jnp.exp(-params.gamma * sq)
    ip = pairwise_distance(res, x, y, DistanceType.InnerProduct)
    if params.kernel == KernelType.LINEAR:
        return ip
    if params.kernel == KernelType.POLYNOMIAL:
        return jnp.power(params.gamma * ip + params.coef0, params.degree)
    if params.kernel == KernelType.TANH:
        return jnp.tanh(params.gamma * ip + params.coef0)
    raise NotImplementedError(f"kernel {params.kernel!r}")
