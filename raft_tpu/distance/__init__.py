"""Pairwise distances, fused NN reductions, gram kernels (reference L3,
``raft/distance/``)."""

from raft_tpu.distance.types import DistanceType, is_min_close, EXPANDED_METRICS
from raft_tpu.distance.pairwise import pairwise_distance, pairwise_distance_tiled
from raft_tpu.distance.fused_l2_nn import (
    fused_l2_nn_argmin,
    fused_l2_nn_argmin_precomputed,
)
from raft_tpu.distance.kernels import KernelType, KernelParams, gram_matrix
from raft_tpu.distance.masked_nn import compress_to_bits, masked_l2_nn

__all__ = [
    "DistanceType",
    "is_min_close",
    "EXPANDED_METRICS",
    "pairwise_distance",
    "pairwise_distance_tiled",
    "fused_l2_nn_argmin",
    "fused_l2_nn_argmin_precomputed",
    "compress_to_bits",
    "masked_l2_nn",
    "KernelType",
    "KernelParams",
    "gram_matrix",
]
