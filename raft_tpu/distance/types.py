"""Distance metric enumeration — mirrors ``distance/distance_types.hpp:23-67``.

Same names and integer values as the reference so serialized artifacts and
configs interop. ``is_min_close`` reproduces
``distance_types.hpp:72-86``: for similarity metrics (InnerProduct) nearest
neighbors are the *largest* values.
"""

from __future__ import annotations

import enum


class DistanceType(enum.IntEnum):
    """All 20 metric identifiers of the reference (+ Precomputed)."""

    L2Expanded = 0          # sum(x^2) + sum(y^2) - 2 sum(x*y)   (squared L2)
    L2SqrtExpanded = 1      # sqrt of the above
    CosineExpanded = 2      # 1 - <x,y> / (|x| |y|)
    L1 = 3                  # sum |x - y|
    L2Unexpanded = 4        # sum (x - y)^2
    L2SqrtUnexpanded = 5    # sqrt of the above
    InnerProduct = 6        # <x,y>  (similarity: larger = closer)
    Linf = 7                # max |x - y|  (Chebyshev)
    Canberra = 8            # sum |x-y| / (|x| + |y|)
    LpUnexpanded = 9        # (sum |x-y|^p)^(1/p), p = metric_arg
    CorrelationExpanded = 10
    JaccardExpanded = 11    # 1 - ip / (|x|^2 + |y|^2 - ip)
    HellingerExpanded = 12  # sqrt(1 - sum sqrt(x*y))
    Haversine = 13          # great-circle distance over (lat, lon) pairs
    BrayCurtis = 14         # sum |x-y| / sum |x+y|
    JensenShannon = 15      # sqrt(0.5 (KL(x|m) + KL(y|m))), m = (x+y)/2
    HammingUnexpanded = 16  # mean(x_i != y_i)
    KLDivergence = 17       # sum x log(x/y)
    RusselRaoExpanded = 18  # (k - ip) / k  (binary data)
    DiceExpanded = 19       # 1 - 2 ip / (|x|^2 + |y|^2)
    Precomputed = 100


def is_min_close(metric: DistanceType) -> bool:
    """True if smaller distance means more similar (``distance_types.hpp:72``)."""
    return metric != DistanceType.InnerProduct


#: Metrics whose pairwise form rides the MXU via a single GEMM + epilog
#: (the reference's "expanded" family, ``distance/detail/distance_ops/``).
EXPANDED_METRICS = frozenset(
    {
        DistanceType.L2Expanded,
        DistanceType.L2SqrtExpanded,
        DistanceType.CosineExpanded,
        DistanceType.InnerProduct,
        DistanceType.CorrelationExpanded,
        DistanceType.JaccardExpanded,
        DistanceType.HellingerExpanded,
        DistanceType.RusselRaoExpanded,
        DistanceType.DiceExpanded,
        DistanceType.KLDivergence,
    }
)
