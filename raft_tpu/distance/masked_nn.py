"""Masked L2 nearest neighbor — analog of ``distance/masked_nn.cuh``
(``masked_l2_nn``) and its bitfield helper ``compress_to_bits``.

The reference fuses a group-mask into its tiled fused-L2-argmin kernel so
masked-out tiles are skipped. On TPU, skipping tiles data-dependently
defeats XLA's static schedule; instead the mask becomes a ``+inf``
select fused into the distance epilog — the MXU computes the full
product either way, and the VPU applies the mask for free in the same
fusion. Memory stays bounded by row tiling.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core import tracing
from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.core.validation import expect


def compress_to_bits(res: Optional[Resources], mask) -> jax.Array:
    """Pack a boolean matrix into uint32 bitfields along rows —
    ``distance::compress_to_bits``. Layout: ``out[i, w]`` holds bits
    ``[32w, 32w+32)`` of row i, LSB-first."""
    ensure_resources(res)
    mask = jnp.asarray(mask, bool)
    m, n = mask.shape
    n_words = (n + 31) // 32
    pad = n_words * 32 - n
    bits = jnp.pad(mask, ((0, 0), (0, pad))).reshape(m, n_words, 32)
    weights = (jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32))[None, None, :]
    return jnp.sum(bits.astype(jnp.uint32) * weights, axis=2, dtype=jnp.uint32)


def masked_l2_nn(
    res: Optional[Resources],
    x,
    y,
    adj,
    group_idxs,
    *,
    sqrt: bool = False,
    tile: int = 4096,
) -> Tuple[jax.Array, jax.Array]:
    """For every row of ``x``, the L2-nearest row of ``y`` among groups
    enabled in ``adj`` — ``distance::masked_l2_nn``
    (``masked_nn.cuh``).

    Args:
      adj: (m, n_groups) boolean — which y-groups each x row may match.
      group_idxs: (n_groups,) int — *end offset* of each group in y's
        rows (the reference's prefix-scan layout: group g spans
        ``[group_idxs[g-1], group_idxs[g])``).

    Returns (min_dists (m,), min_indices (m,)) — the reference's KVP
    output split into two arrays; rows with no enabled group get
    ``inf`` / ``-1``.
    """
    ensure_resources(res)
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    adj = jnp.asarray(adj, bool)
    group_idxs = jnp.asarray(group_idxs, jnp.int32)
    m, d = x.shape
    n = y.shape[0]
    n_groups = adj.shape[1]
    expect(group_idxs.shape[0] == n_groups,
           "masked_l2_nn: adj and group_idxs disagree on group count")

    # group id of each y row from the end-offset table
    group_of_y = jnp.searchsorted(group_idxs, jnp.arange(n), side="right")
    group_of_y = jnp.clip(group_of_y, 0, n_groups - 1).astype(jnp.int32)

    with tracing.range("raft_tpu.distance.masked_l2_nn"):
        yn = jnp.sum(jnp.square(y.astype(jnp.float32)), axis=1)
        outs_d, outs_i = [], []
        for start in range(0, m, tile):
            stop = min(start + tile, m)
            xt = x[start:stop].astype(jnp.float32)
            ip = jax.lax.dot_general(
                xt, y.astype(jnp.float32), (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            dist = jnp.sum(jnp.square(xt), axis=1)[:, None] + yn[None, :] \
                - 2.0 * ip
            dist = jnp.maximum(dist, 0.0)
            allowed = adj[start:stop][:, group_of_y]       # (t, n)
            dist = jnp.where(allowed, dist, jnp.inf)
            best = jnp.min(dist, axis=1)
            best_i = jnp.argmin(dist, axis=1).astype(jnp.int32)
            best_i = jnp.where(jnp.isfinite(best), best_i, -1)
            if sqrt:
                best = jnp.sqrt(best)
            outs_d.append(best)
            outs_i.append(best_i)
        md = jnp.concatenate(outs_d) if len(outs_d) > 1 else outs_d[0]
        mi = jnp.concatenate(outs_i) if len(outs_i) > 1 else outs_i[0]
        return md, mi
