"""Fused distance + top-k Pallas kernels — the TPU re-design of the
reference's two hottest kernels:

- ``fusedL2kNN`` (``spatial/knn/detail/fused_l2_knn-inl.cuh:198``): exact
  kNN that never materializes the (q, n) distance matrix. The CUDA
  version keeps a warp-level register top-k; here a VMEM-resident
  (q, k) running state persists across a 1-D grid over database tiles —
  each step does one MXU contraction (the distance core) and a VPU
  extract-min merge, so the dataset streams through HBM exactly once.

- ``matrix::select_k`` (``matrix/detail/select_radix.cuh``,
  ``select_warpsort.cuh``): batched k-selection over a wide matrix,
  expressed as the same tiled merge without the distance core.

The merge primitive is k rounds of (min, first-argmin, mask) over the
lane axis — O(k·tile) VPU work per tile, negligible next to the O(d·tile)
MXU distance work, and free of gathers/sorts that Mosaic lowers poorly.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_tpu.core.validation import expect
from raft_tpu.distance.types import DistanceType

# jax renamed TPUCompilerParams -> CompilerParams (jax 0.5); accept both
# so the kernels load on either side of the rename
_COMPILER_PARAMS = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams")

_SUPPORTED_METRICS = (
    DistanceType.L2Expanded,
    DistanceType.L2SqrtExpanded,
    DistanceType.L2Unexpanded,
    DistanceType.L2SqrtUnexpanded,
    DistanceType.InnerProduct,
    DistanceType.CosineExpanded,
)


def _extract_topk(dist, ids, k: int):
    """k smallest of (q, m) with smallest-id tie-break, by k rounds of
    min / min-id / mask — the in-register merge network of the
    reference's warp-sort restated for the VPU (min reductions only:
    Mosaic has no cumsum/sort lowering)."""
    big = jnp.int32(jnp.iinfo(jnp.int32).max)
    outs_d, outs_i = [], []
    for _ in range(k):
        m = jnp.min(dist, axis=1, keepdims=True)                 # (q, 1)
        is_min = dist == m
        idx = jnp.min(jnp.where(is_min, ids, big), axis=1, keepdims=True)
        outs_d.append(m)
        outs_i.append(jnp.where(jnp.isfinite(m), idx, -1))
        dist = jnp.where(is_min & (ids == idx), jnp.inf, dist)
    return (jnp.concatenate(outs_d, axis=1),
            jnp.concatenate(outs_i, axis=1))


def _knn_kernel(q_ref, qn_ref, x_ref, xn_ref, outd_ref, outi_ref,
                bestd, besti, *, k: int, n: int, tile: int,
                steps: int, metric: DistanceType):
    # position within the current pass — the grid runs `passes` full
    # dataset streams back-to-back (pass > 1 only for slope timing:
    # per-pass cost = d wall / d passes, immune to dispatch overhead)
    step = pl.program_id(0) % steps

    @pl.when(step == 0)
    def _():
        bestd[:] = jnp.full_like(bestd, jnp.inf)
        besti[:] = jnp.full_like(besti, -1)

    xt = x_ref[:]                                                # (t, d)
    qt = q_ref[:]                                                # (q, d)
    # f32 inputs: HIGHEST — exact-kNN semantics need full f32 products
    # (default single-pass bf16 loses ~8 mantissa bits), and the stream
    # is HBM-bound so the extra passes hide behind the loads. bf16
    # inputs: their products are already exact in the f32 accumulator.
    prec = (jax.lax.Precision.DEFAULT if xt.dtype == jnp.bfloat16
            else jax.lax.Precision.HIGHEST)
    ip = jax.lax.dot_general(qt, xt, (((1,), (1,)), ((), ())),
                             precision=prec,
                             preferred_element_type=jnp.float32)  # (q, t)
    xn = xn_ref[:]                                               # (1, t)
    qn = qn_ref[:]                                               # (q, 1)
    if metric in (DistanceType.InnerProduct,):
        dist = -ip
    elif metric == DistanceType.CosineExpanded:
        inv = jax.lax.rsqrt(jnp.maximum(qn * xn, 1e-30))
        dist = 1.0 - ip * inv
    else:  # L2 expanded family
        dist = jnp.maximum(qn + xn - 2.0 * ip, 0.0)

    col = jax.lax.broadcasted_iota(jnp.int32, dist.shape, 1) + step * tile
    dist = jnp.where(col < n, dist, jnp.inf)

    # filtered merge (the reference's ``warp_sort_filtered`` idea,
    # ``matrix/detail/select_warpsort.cuh``): most tiles cannot improve
    # the running top-k — one VPU compare detects that and skips the
    # k-round extraction entirely
    kth = bestd[:, k - 1 : k]                                    # (q, 1)
    any_better = jnp.any(dist < kth)

    @pl.when(any_better)
    def _():
        cat_d = jnp.concatenate([bestd[:], dist], axis=1)
        cat_i = jnp.concatenate([besti[:], col], axis=1)
        new_d, new_i = _extract_topk(cat_d, cat_i, k)
        bestd[:] = new_d
        besti[:] = new_i

    @pl.when(step == steps - 1)
    def _():
        out = bestd[:]
        if metric in (DistanceType.L2SqrtExpanded,
                      DistanceType.L2SqrtUnexpanded):
            out = jnp.sqrt(out)
        elif metric == DistanceType.InnerProduct:
            out = -out
        outd_ref[:] = out
        outi_ref[:] = besti[:]


def _default_vmem_mb() -> int:
    """Per-kernel Mosaic VMEM budget (MB) — resolved OUTSIDE jit so the
    env var is honored per call, not frozen into the first trace.

    The default is derived from the attached device generation: v4+
    parts carry 128 MB of physical VMEM per core (64 MB budget leaves
    headroom, measured safe on v5e), while v2/v3 and unrecognized
    kinds fall back to a conservative 16 MB so Mosaic compiles where a
    64 MB request would be rejected. ``RAFT_TPU_VMEM_MB`` overrides."""
    import os

    env = os.environ.get("RAFT_TPU_VMEM_MB")
    if env:
        return int(env)
    try:
        kind = jax.local_devices()[0].device_kind.lower()
    except Exception:
        return 16
    if any(g in kind for g in ("v4", "v5", "v6", "v7")):
        return 64
    return 16


def fused_knn(
    queries,
    dataset,
    k: int,
    metric: DistanceType = DistanceType.L2Expanded,
    *,
    dataset_norms=None,
    tile: int = 0,
    vmem_mb: int = 0,
    passes: int = 1,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Exact kNN in one streamed Pallas pass: (q, k) distances + indices.

    Queries must be modest (they stay VMEM-resident: q·d + q·tile floats);
    the caller tiles large query sets. Any n — the ragged tail rides a
    partial final block, masked with +inf in the kernel.

    ``dataset_norms`` (f32 ``(n,)`` cached ||y||² as built by the
    brute-force index) skips the per-call norm pass; without it one extra
    full read of the dataset happens per call. The dataset itself is
    consumed in place when its dim is lane-aligned (d % 128 == 0) —
    per-call HBM traffic is then exactly one dataset stream.

    ``tile=0`` auto-sizes database blocks to the VMEM budget
    (``vmem_mb``, default from ``RAFT_TPU_VMEM_MB`` or 64). Measured on
    v5e the stream is per-grid-step bound (~16 us/step) far below the
    HBM roofline, so the right tile is the largest that fits — fewer,
    bigger DMAs — not a fixed 8k.

    ``passes > 1`` repeats the full dataset stream that many times in
    ONE dispatch (the grid wraps around) — a benchmarking aid: per-pass
    time from the slope between two pass counts cancels the dispatch
    overhead that floors single-dispatch timing on relayed backends.
    Results are identical to passes=1."""
    if vmem_mb <= 0:
        vmem_mb = _default_vmem_mb()
    return _fused_knn_impl(queries, dataset, k, metric,
                           dataset_norms=dataset_norms, tile=tile,
                           vmem_mb=vmem_mb, passes=passes,
                           interpret=interpret)


@functools.partial(jax.jit,
                   static_argnames=("k", "metric", "tile", "vmem_mb",
                                    "passes", "interpret"))
def _fused_knn_impl(
    queries,
    dataset,
    k: int,
    metric: DistanceType,
    *,
    dataset_norms,
    tile: int,
    vmem_mb: int,
    passes: int,
    interpret: bool,
) -> Tuple[jax.Array, jax.Array]:
    expect(metric in _SUPPORTED_METRICS,
           f"fused_knn: unsupported metric {metric}")
    q, d = queries.shape
    n = dataset.shape[0]
    expect(dataset.shape[1] == d, "fused_knn: dim mismatch")
    expect(0 < k <= n, "fused_knn: bad k")

    # sublane multiple: 8 for f32 blocks, 16 for bf16
    pad_q = (-q) % (16 if dataset.dtype == jnp.bfloat16 else 8)
    pad_d = (-d) % 128
    d_pad = d + pad_d
    q_pad = q + pad_q
    # VMEM budget per database row: double-buffered (tile, d) dataset
    # block + (1, tile) norms (f32, x2 buffers) + the kernel's live
    # (q_pad, tile) intermediates — ip/dist f32, col iota i32, and the
    # cat_d/cat_i concatenations in the merge — ~24 B per q_pad row.
    # 2 MB flat margin covers queries, out/scratch (q_pad, k) pairs and
    # compiler slack; cap at 65536 rows (past ~32 MB blocks the stream
    # is byte-bound and bigger tiles stop paying).
    itemsize = 2 if dataset.dtype == jnp.bfloat16 else 4
    budget = vmem_mb * 1024 * 1024 - q_pad * d_pad * itemsize - (2 << 20)
    per_row = 2 * (d_pad * itemsize + 4) + 24 * q_pad
    vmem_cap = max(512, (budget // per_row) // 128 * 128)
    if tile <= 0:
        tile = vmem_cap
    tile = min(tile, vmem_cap, 65536, max(128, ((n + 127) // 128) * 128))
    # bf16 datasets stay bf16 through HBM (the point of half storage);
    # everything else runs f32
    if dataset.dtype == jnp.bfloat16:
        qs = jnp.pad(queries.astype(jnp.bfloat16), ((0, pad_q), (0, pad_d)))
        xs = jnp.pad(dataset, ((0, 0), (0, pad_d)))
    else:
        qs = jnp.pad(queries.astype(jnp.float32), ((0, pad_q), (0, pad_d)))
        xs = jnp.pad(dataset.astype(jnp.float32), ((0, 0), (0, pad_d)))
    qn = jnp.sum(jnp.square(qs.astype(jnp.float32)), axis=1,
                 keepdims=True)                                   # (Q, 1)
    if dataset_norms is None:
        xn = jnp.sum(jnp.square(xs.astype(jnp.float32)), axis=1)[None, :]
    else:
        xn = jnp.asarray(dataset_norms, jnp.float32).reshape(1, n)
    qp = qs.shape[0]
    steps = -(-n // tile)

    kernel = functools.partial(_knn_kernel, k=k, n=n, tile=tile,
                               steps=steps, metric=metric)
    outd, outi = pl.pallas_call(
        kernel,
        grid=(steps * passes,),
        in_specs=[
            pl.BlockSpec((qp, qs.shape[1]), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((qp, 1), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile, xs.shape[1]), lambda i, s=steps: (i % s, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tile), lambda i, s=steps: (0, i % s),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((qp, k), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((qp, k), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((qp, k), jnp.float32),
            jax.ShapeDtypeStruct((qp, k), jnp.int32),
        ),
        scratch_shapes=[
            pltpu.VMEM((qp, k), jnp.float32),
            pltpu.VMEM((qp, k), jnp.int32),
        ],
        compiler_params=_COMPILER_PARAMS(
            vmem_limit_bytes=vmem_mb * 1024 * 1024),
        interpret=interpret,
    )(qs, qn, xs, xn)
    return outd[:q], outi[:q]


def _select_kernel(v_ref, outd_ref, outi_ref, bestd, besti,
                   *, k: int, n: int, tile: int, select_min: bool):
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _():
        bestd[:] = jnp.full_like(bestd, jnp.inf)
        besti[:] = jnp.full_like(besti, -1)

    vals = v_ref[:].astype(jnp.float32)
    if not select_min:
        vals = -vals
    col = jax.lax.broadcasted_iota(jnp.int32, vals.shape, 1) + step * tile
    vals = jnp.where(col < n, vals, jnp.inf)

    kth = bestd[:, k - 1 : k]
    any_better = jnp.any(vals < kth)

    @pl.when(any_better)
    def _():
        cat_d = jnp.concatenate([bestd[:], vals], axis=1)
        cat_i = jnp.concatenate([besti[:], col], axis=1)
        new_d, new_i = _extract_topk(cat_d, cat_i, k)
        bestd[:] = new_d
        besti[:] = new_i

    @pl.when(step == pl.num_programs(0) - 1)
    def _():
        outd_ref[:] = bestd[:] if select_min else -bestd[:]
        outi_ref[:] = besti[:]


def select_k_tiles(
    values,
    k: int,
    select_min: bool = True,
    *,
    tile: int = 4096,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Batched k-selection over a wide (batch, n) matrix as a streamed
    Pallas merge — the radix/warpsort-select analog. Exact, first-
    occurrence tie-break like the reference's stable warpsort.

    The VMEM budget is resolved OUTSIDE the jitted impl (like
    ``fused_knn``) so ``RAFT_TPU_VMEM_MB`` is honored per call instead
    of being frozen into the first trace."""
    return _select_k_tiles_impl(values, k, select_min, tile=tile,
                                interpret=interpret,
                                vmem_mb=_default_vmem_mb())


@functools.partial(jax.jit,
                   static_argnames=("k", "select_min", "tile",
                                    "interpret", "vmem_mb"))
def _select_k_tiles_impl(
    values,
    k: int,
    select_min: bool = True,
    *,
    tile: int = 4096,
    interpret: bool = False,
    vmem_mb: int = 64,
) -> Tuple[jax.Array, jax.Array]:
    b, n = values.shape
    expect(0 < k <= n, "select_k_tiles: bad k")
    tile = min(tile, max(128, ((n + 127) // 128) * 128))
    pad_n = (-n) % tile
    pad_b = (-b) % 8
    vs = jnp.pad(values.astype(jnp.float32), ((0, pad_b), (0, pad_n)))
    bp, npad = vs.shape
    grid = npad // tile

    kernel = functools.partial(_select_kernel, k=k, n=n, tile=tile,
                               select_min=select_min)
    outd, outi = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((bp, tile), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((bp, k), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bp, k), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((bp, k), jnp.float32),
            jax.ShapeDtypeStruct((bp, k), jnp.int32),
        ),
        scratch_shapes=[
            pltpu.VMEM((bp, k), jnp.float32),
            pltpu.VMEM((bp, k), jnp.int32),
        ],
        compiler_params=_COMPILER_PARAMS(
            vmem_limit_bytes=vmem_mb << 20),
        interpret=interpret,
    )(vs)
    return outd[:b], outi[:b]


# ---------------------------------------------------------------------------
# stream probe
# ---------------------------------------------------------------------------


def _stream_kernel(x_ref, o_ref, acc):
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _():
        acc[:] = jnp.zeros_like(acc)

    acc[:] += jnp.sum(x_ref[:].astype(jnp.float32), axis=0, keepdims=True)

    @pl.when(step == pl.num_programs(0) - 1)
    def _():
        o_ref[:] = acc[:]


def stream_read_sum(x, tile: int = 0, vmem_mb: int = 0,
                    interpret: bool = False):
    """Column-sum of ``x`` as a pure streamed read — the HBM-bandwidth
    ceiling probe every bandwidth-bound kernel is judged against (the
    prims micro-bench and roofline claims in BASELINE.md use it).
    Touches each element exactly once; compute is one VPU add per
    element, far under the bandwidth bound. Ragged shapes are handled
    by a zero-pad (padding adds 0 to the sum) — but the pad is a full
    materialized copy INSIDE this jitted call, so for bandwidth
    measurements use tile- and lane-aligned shapes (n % tile == 0,
    d % 128 == 0), where the input streams in place.

    ``tile=0`` auto-sizes blocks to the VMEM budget (``vmem_mb``,
    default ``RAFT_TPU_VMEM_MB`` or 64): the stream is per-grid-step
    bound (~16 us/step on v5e) well below the HBM roofline, so the
    probe uses the biggest block that fits — a small-block probe
    measures step overhead, not bandwidth."""
    if vmem_mb <= 0:
        vmem_mb = _default_vmem_mb()
    return _stream_read_impl(x, tile, vmem_mb, interpret)


@functools.partial(jax.jit, static_argnames=("tile", "vmem_mb", "interpret"))
def _stream_read_impl(x, tile: int, vmem_mb: int, interpret: bool):
    n, d = x.shape
    dpad_cols = d + ((-d) % 128)
    itemsize = x.dtype.itemsize
    budget = vmem_mb * 1024 * 1024 - (1 << 20)
    # per element: double-buffered input block + an f32-widened strip
    # for the astype inside the kernel (sub-f32 inputs upcast to sum)
    per_elem = 2 * itemsize + (4 if itemsize < 4 else 0)
    cap = max(8, budget // (dpad_cols * per_elem))
    # power-of-two tile: the probe shapes are powers of two, so the
    # auto tile divides n exactly and the pad-copy path (which would
    # corrupt the bandwidth measurement) never triggers
    cap = 1 << (cap.bit_length() - 1)
    if tile <= 0:
        tile = cap
    tile = min(tile, cap, max(8, ((n + 7) // 8) * 8))
    pad_n = (-n) % tile
    pad_d = (-d) % 128
    if pad_n or pad_d:
        x = jnp.pad(x, ((0, pad_n), (0, pad_d)))
    npad, dpad = x.shape
    return pl.pallas_call(
        _stream_kernel,
        grid=(npad // tile,),
        in_specs=[pl.BlockSpec((tile, dpad), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((1, dpad), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((1, dpad), jnp.float32),
        scratch_shapes=[pltpu.VMEM((1, dpad), jnp.float32)],
        compiler_params=_COMPILER_PARAMS(
            vmem_limit_bytes=vmem_mb * 1024 * 1024),
        interpret=interpret,
    )(x)[:, :d]
