"""List-major IVF probe scan — the TPU port of the reference's flagship
``ivf_flat_interleaved_scan`` (``detail/ivf_flat_interleaved_scan-inl.cuh``),
re-designed per the two papers the survey flags for this kernel:

- TPU-KNN (arxiv 2206.14286): peak FLOP/s on TPU means expressing kNN as
  large dense contractions with an in-register merge — never as gathers
  feeding batched matvecs.
- Ragged Paged Attention (arxiv 2604.15464): the TPU-native way to fetch
  data-dependent pages is a **scalar-prefetched block index map** — the
  page table (here: the probed-list union) rides ahead of the grid in
  SMEM and steers each step's HBM->VMEM block DMA.

The rank-major scan (``ivf_flat._search_impl_fn`` with
``scan_engine="rank"``) gathers one probed list *per query* per probe
rank: a `(q, m, d)` HBM materialization and a gather-bound batched
matvec, repeated ``n_probes`` times. This module turns the scan
**list-major**: compute the union of probed list ids for the whole
query tile (sort/unique on device, padded to a static cap with a
sentinel id ``n_lists``), then stream each unique list's
``(max_list_size, d)`` block from the packed ``data`` tensor exactly
once and contract it against the *entire* query tile in one MXU GEMM.
A per-query "did this query probe this list" predicate masks rows out,
so results match the rank-major scan (indices exactly; distances to
XLA's dot-reassociation tolerance — the same caveat as
``beam_search``'s two lowerings). Per-probe HBM traffic drops from
``q * n_probes`` gathered lists to at most ``min(n_lists,
q * n_probes)`` streamed lists, and the matvecs become dense GEMMs.

Two engines share the formulation:

- ``pallas``: the fused kernel. Grid ``(query_tiles, n_unique)``; the
  unique-list array is the scalar-prefetch operand steering the
  ``data``/``data_norms`` BlockSpec index maps; the running ``(q, k)``
  top-k lives in VMEM scratch and merges via the
  ``ops.fused_topk._extract_topk`` network with the ``any_better``
  skip. Shared (1-D) bitset filters fold into the gathered id planes
  before the kernel (a filtered slot becomes id -1 — padding).
- ``xla``: the same union/mask/merge as a ``lax.scan`` over unique
  lists, merging via one lexicographic two-key ``lax.sort`` (the same
  smallest-id tie-break as the kernel, any k without unrolling) — the
  portable fallback (CPU/GPU, 2-D per-query filters, int8 storage,
  large k, misaligned layouts on TPU).

**Ragged query-tile front** (the continuous-batching serving path):
several requests with *different* per-request ``n_probes`` pack
adjacently into one fixed query tile, and each row's probe slots past
its own budget mask to the sentinel id ``n_lists``
(:func:`ragged_row_probes` / :func:`ragged_probes`). Sentinel-valued
probe slots are exactly how the list-sharded indexes already mark
not-owned probes, so BOTH engines serve the packed tile unchanged —
the membership predicate is the raggedness mechanism, and the
scalar-prefetched index map streams only the union the packed batch
actually probed. One executable therefore serves every load shape;
the per-request results are bit-identical to solo calls. The same
front covers the whole index zoo (graftragged): the PQ LUT scan and
the fused BQ engines consume the identical sentinel-masked probes,
and on the mesh :func:`ragged_owned` folds each row's budget into
the sharded probe-ownership mask — so a replicated packed tile
serves the list-sharded families through their unchanged shard
bodies.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_tpu.core.validation import expect
from raft_tpu.distance.types import DistanceType
from raft_tpu.ops.fused_topk import (
    _COMPILER_PARAMS,
    _default_vmem_mb,
    _extract_topk,
)

SCAN_ENGINES = ("auto", "pallas", "xla", "rank")

# the merge network unrolls k rounds; past this the XLA merge wins
_PALLAS_MAX_K = 128


def resolve_scan_engine(engine: str, *, data=None, filter_words=None,
                        k=None, vmem_mb: int = 0) -> str:
    """Resolve a ``scan_engine`` search param to a concrete engine.

    ``auto`` is the Pallas kernel on TPU and the list-major XLA scan
    elsewhere. ``pallas`` degrades to ``xla`` when the kernel's
    preconditions fail: per-query (2-D) filter words (the id-fold
    trick needs one shared id plane), non-f32/bf16 storage (Mosaic
    block tiling), ``k`` past the unrolled-merge budget, or a single
    list block that cannot fit the VMEM budget double-buffered.
    ``rank`` is the legacy rank-major gather scan, kept for parity
    testing and as the small-``n_lists`` escape hatch."""
    expect(engine in SCAN_ENGINES,
           f"scan_engine must be one of {SCAN_ENGINES}, got {engine!r}")
    if engine == "auto":
        engine = "pallas" if jax.default_backend() == "tpu" else "xla"
    if engine != "pallas":
        return engine
    if filter_words is not None and getattr(filter_words, "ndim", 1) == 2:
        return "xla"
    if k is not None and k > _PALLAS_MAX_K:
        return "xla"
    if data is not None:
        if data.dtype not in (jnp.float32, jnp.bfloat16):
            return "xla"
        itemsize = 2 if data.dtype == jnp.bfloat16 else 4
        sub = 16 if itemsize == 2 else 8
        m_pad = -(-data.shape[1] // sub) * sub
        d_pad = -(-data.shape[2] // 128) * 128
        # on real hardware a misaligned layout would force _scan_pallas
        # to jnp.pad the WHOLE packed tensor per call — a full HBM
        # read+write dwarfing the probe scan — so compiled runs demand
        # build-time alignment (padded_extent gives m % 8; lane-aligned
        # dims like 128/256 give d). Interpret mode (off-TPU) keeps the
        # pad path: it exists so CPU CI can cover the kernel at any
        # test shape.
        if jax.default_backend() == "tpu" and (
                m_pad != data.shape[1] or d_pad != data.shape[2]):
            return "xla"
        if vmem_mb <= 0:
            vmem_mb = _default_vmem_mb()
        # mirror _scan_pallas's budget: the list block + margin fixed
        # cost must leave room for at least one minimal (8-row) query
        # tile — otherwise the kernel's q_tile floor would overshoot
        # vmem_limit_bytes and fail Mosaic compilation instead of
        # degrading here. p_pad is unknown at resolve time; 256 covers
        # n_probes up to 256 conservatively.
        fixed = 3 * m_pad * (d_pad * itemsize + 8) + (2 << 20)
        per_q = 4 * (d_pad + 256) + 24 * m_pad + 16 * (k or _PALLAS_MAX_K)
        if fixed + 8 * per_q > vmem_mb << 20:
            return "xla"
    return engine


def probe_histogram(probes: jax.Array, counts: jax.Array,
                    n_valid=None, owned=None) -> jax.Array:
    """Scatter-add a ``bincount`` of the selected probe ids into the
    running ``counts`` plane — the device half of graftgauge's
    probe-frequency accounting, shared by every IVF family's search
    body (single-chip and the shard-local half of the sharded ones).

    ``probes`` is the (q, n_probes) int32 probe selection; ``counts``
    is the donated (n_lists,) int32 cumulative plane (the serving
    executor threads it like the top-k state, so steady state stays
    zero-recompile). ``n_valid`` (traced scalar) masks the executor's
    inert bucket-pad rows — a pad query's phantom probes must not
    pollute the traffic histogram; ``owned`` is the sharded families'
    per-slot ownership mask (count a probe exactly once mesh-wide, on
    the shard that owns the list). Masked slots redirect to the
    out-of-range index ``n_lists`` and ``mode="drop"`` discards them —
    including sentinel-valued masked probes, which already carry
    ``n_lists``. Pure accumulation: the search results never read the
    plane, so bit-identity is untouched by construction."""
    n_lists = counts.shape[0]
    ids = probes.astype(jnp.int32)
    if owned is not None:
        ids = jnp.where(owned, ids, n_lists)
    if n_valid is not None:
        valid = jnp.arange(ids.shape[0], dtype=jnp.int32) < n_valid
        ids = jnp.where(valid[:, None], ids, n_lists)
    return counts.at[ids.reshape(-1)].add(1, mode="drop")


def ragged_row_probes(sizes, n_probes_list, tile: int):
    """Host-side half of the ragged query-tile front (Ragged Paged
    Attention's packing descriptor, arxiv 2604.15464): expand one
    packed tile's per-request row ranges into the per-ROW probe-budget
    plane the device front consumes.

    ``sizes[j]`` rows of request ``j`` occupy the next ``sizes[j]``
    packed rows (requests pack adjacently, in order), and every row of
    request ``j`` carries that request's probe budget
    ``n_probes_list[j]``. Rows past ``sum(sizes)`` are tile padding and
    get budget 0 — a pad row probes nothing, so it contributes nothing
    to any result, the probed-list union, or the probe-frequency
    histogram. Returns a ``(tile,)`` int32 numpy array (the serving
    path packs host-side; the executor ships it with the queries)."""
    out = np.zeros((tile,), np.int32)
    row = 0
    for m, p in zip(sizes, n_probes_list):
        out[row:row + m] = p
        row += m
    expect(row <= tile, f"packed rows {row} overflow the tile {tile}")
    return out


def ragged_probes(probes: jax.Array, row_probes: jax.Array,
                  n_lists: int) -> jax.Array:
    """Device half of the ragged front: mask each row's probe slots
    past its own budget to the sentinel id ``n_lists``.

    ``probes`` is the coarse selection at the packed tile's CLASS cap
    (``(tile, n_probes_class)``, exact top-k — so slots ``[0, b)`` of a
    row with budget ``b <= n_probes_class`` are exactly what a solo
    search with ``n_probes=b`` would have selected); ``row_probes`` is
    :func:`ragged_row_probes`'s per-row budget plane. Sentinel-masked
    slots ride the exact machinery the list-sharded indexes already
    use for not-owned probes: :func:`unique_lists` collapses them into
    sentinel steps, both engines' membership predicates reject them
    (``lid < n_lists``), and :func:`probe_histogram` drops them — so
    one packed executable serves every per-request ``n_probes`` in the
    class, bit-identical per request to the solo call."""
    slot = jnp.arange(probes.shape[1], dtype=jnp.int32)
    return jnp.where(slot[None, :] < row_probes[:, None], probes,
                     n_lists)


def ragged_owned(mine: jax.Array, row_probes: jax.Array,
                 shards: int = 1) -> jax.Array:
    """Fold a packed ragged tile's per-row probe budgets into a
    sharded probe-ownership mask — the mesh half of the ragged front.

    ``mine`` is :func:`raft_tpu.distributed.ivf.select_probes_sharded`'s
    per-(row, probe-rank) ownership mask, whose columns are
    rank-ordered by the exact coarse top-k (a total order, so the
    first ``b`` columns ARE the solo ``n_probes=b`` selection — the
    same prefix property the single-chip front rides). A row keeps
    only the slots below its own budget; everything downstream
    (sentinel masking for the scan, ``owned=`` for
    :func:`probe_histogram`) already consumes the mask, so the sharded
    bodies serve packed tiles with one ``jnp.logical_and``.

    ``shards`` converts the global per-row budget to the per-shard one
    for ``probe_mode="local"`` (each shard probes its own
    ``ceil(b / R)`` lists, exactly as
    :func:`~raft_tpu.distributed.ivf.resolve_probe_budget` resolves
    the scalar budget). Pad rows carry budget 0 and own nothing."""
    slot = jnp.arange(mine.shape[1], dtype=jnp.int32)
    budget = row_probes
    if shards > 1:
        budget = -(-row_probes // shards)       # ceil(b / R), 0 -> 0
    return jnp.logical_and(mine, slot[None, :] < budget[:, None])


def unique_lists(probes: jax.Array, n_lists: int) -> jax.Array:
    """Sorted union of probed list ids, padded to the static cap
    ``min(n_lists, q * n_probes)`` with the sentinel id ``n_lists``.

    The engines' membership predicates reject sentinel steps outright
    (``lid < n_lists``), so the ragged union rides a fixed shape — the
    same tail-masking discipline as ``fused_topk``'s partial final
    block. Probe slots may themselves carry the sentinel value
    ``n_lists`` ("masked probe" — e.g. a probe owned by another shard
    of a list-sharded index): they collapse into the sentinel steps and
    contribute nothing to any query's results."""
    q, p = probes.shape
    cap = min(n_lists, q * p)
    flat = jnp.sort(probes.reshape(-1).astype(jnp.int32))
    first = jnp.concatenate(
        [jnp.ones((1,), bool), flat[1:] != flat[:-1]])
    rank = jnp.cumsum(first) - 1          # unique slot of each element
    slot = jnp.where(first, rank, cap)    # non-first -> out of range
    uniq = jnp.full((cap,), n_lists, jnp.int32)
    return uniq.at[slot].set(flat, mode="drop")


def list_major_scan(qf, data, data_norms, indices, probes,
                    filter_words=None, init_d=None, init_i=None, *,
                    k: int, metric: DistanceType, engine: str = "xla",
                    interpret: bool = False):
    """Run the probe scan list-major; returns the pre-epilog running
    top-k ``(best_d, best_i)`` in the rank-major scan's convention
    (min-space ``norms - 2 x·y`` for L2 with +inf pads; raw inner
    products for IP with -inf pads), so the caller's metric epilog is
    shared across engines.

    Both engines break distance ties by smallest dataset id (the
    ``_extract_topk`` order), so their outputs are bit-identical to
    each other even on exact duplicates. ``init_d``/``init_i``
    optionally provide the (q, k) running-state storage for the XLA
    engine (values are reset; the serving path donates them); the
    Pallas engine keeps its state in VMEM scratch and ignores them.

    Probe slots carrying the sentinel value ``n_lists`` are masked
    probes (the list-sharded indexes mark not-owned probes this way);
    they are ignored by both engines."""
    expect(engine in ("pallas", "xla"),
           f"list_major_scan engine must be pallas|xla, got {engine!r}")
    if engine == "pallas":
        return _scan_pallas(qf, data, data_norms, indices, probes,
                            filter_words, k=k, metric=metric,
                            interpret=interpret)
    return _scan_xla(qf, data, data_norms, indices, probes, filter_words,
                     init_d, init_i, k=k, metric=metric)


# ---------------------------------------------------------------------------
# XLA list-major engine
# ---------------------------------------------------------------------------


def _merge_smallest_id(best_d, best_i, dist, ids, k: int):
    """Min-space running top-k merge with the smallest-id tie-break —
    the ``_extract_topk`` order as one lexicographic two-key sort, so
    the XLA engine matches the Pallas kernel bit-for-bit on exact
    ties (``merge_topk``'s positional tie-break would not), and any k
    works without unrolling k rounds."""
    cat_d = jnp.concatenate([best_d, dist], axis=1)
    cat_i = jnp.concatenate([best_i, ids], axis=1)
    sd, si = jax.lax.sort((cat_d, cat_i), dimension=1, num_keys=2)
    sd, si = sd[:, :k], si[:, :k]
    return sd, jnp.where(jnp.isfinite(sd), si, -1)


def _scan_xla(qf, data, data_norms, indices, probes, filter_words,
              init_d=None, init_i=None, *, k: int, metric: DistanceType):
    from raft_tpu.neighbors.filters import test_filter

    q = qf.shape[0]
    n_lists = data.shape[0]
    ip_metric = metric == DistanceType.InnerProduct
    uniq = unique_lists(probes, n_lists)

    # min-space scan like the Pallas kernel (IP negates back at the
    # end — exact for floats), so the tie-break order is identical
    def step(carry, lid):
        best_d, best_i = carry
        lidc = jnp.minimum(lid, n_lists - 1)      # sentinel-safe index
        rows = jax.lax.dynamic_index_in_dim(
            data, lidc, 0, False).astype(jnp.float32)         # (m, d)
        row_ids = jax.lax.dynamic_index_in_dim(indices, lidc, 0, False)
        ip = jax.lax.dot_general(
            qf, rows, (((1,), (1,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32,
        )                                                      # (q, m)
        if ip_metric:
            dist = -ip
        else:
            row_norms = jax.lax.dynamic_index_in_dim(
                data_norms, lidc, 0, False)
            dist = row_norms[None, :] - 2.0 * ip
        ids_b = jnp.broadcast_to(row_ids[None, :], dist.shape)
        # membership: which queries probed this list. A sentinel step
        # (lid == n_lists) matches nothing — including masked probe
        # slots, which carry the sentinel value themselves.
        probed = jnp.any(probes == lid, axis=1) & (lid < n_lists)  # (q,)
        ok = (ids_b >= 0) & probed[:, None]
        if filter_words is not None:
            ok = ok & test_filter(filter_words, ids_b)
        dist = jnp.where(ok, dist, jnp.inf)
        return _merge_smallest_id(best_d, best_i, dist, ids_b, k), None

    init = (
        jnp.full((q, k), jnp.inf, jnp.float32) if init_d is None
        else jnp.full_like(init_d, jnp.inf),
        jnp.full((q, k), -1, jnp.int32) if init_i is None
        else jnp.full_like(init_i, -1),
    )
    (best_d, best_i), _ = jax.lax.scan(step, init, uniq)
    if ip_metric:
        best_d = -best_d          # inf (unfilled) -> -inf, ip exact
    return best_d, best_i


# ---------------------------------------------------------------------------
# Pallas list-major engine
# ---------------------------------------------------------------------------


def _ivf_scan_kernel(u_ref, probes_ref, q_ref, x_ref, xn_ref, ids_ref,
                     outd_ref, outi_ref, bestd, besti, *, k: int,
                     n_steps: int, n_lists: int, ip_metric: bool):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        bestd[:] = jnp.full_like(bestd, jnp.inf)
        besti[:] = jnp.full_like(besti, -1)

    lid = u_ref[j]                        # scalar-prefetched list id
    # ONE dense (q_tile, d) x (d, m) MXU contraction for the whole
    # query tile against the whole list — the TPU-KNN shape. Storage
    # upcasts to f32 so bf16 lists match the rank-major scan's math.
    xt = x_ref[0].astype(jnp.float32)     # (m, d)
    ip = jax.lax.dot_general(
        q_ref[:], xt, (((1,), (1,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    )                                     # (q_tile, m)
    # min-space distances; IP negates back at the final step
    dist = -ip if ip_metric else xn_ref[:] - 2.0 * ip
    ids = ids_ref[:]                      # (1, m) — -1 marks pad/filtered
    # membership predicate: which tile rows actually probed this list.
    # The lid < n_lists guard kills sentinel steps outright, including
    # the case where probe slots carry the sentinel value themselves
    # (shard-masked probes of the list-sharded indexes).
    probed = jnp.any(probes_ref[:] == lid, axis=1, keepdims=True)
    probed = jnp.logical_and(probed, lid < n_lists)
    dist = jnp.where((ids >= 0) & probed, dist, jnp.inf)

    # filtered merge: skip the k-round extraction when no row improves
    kth = bestd[:, k - 1 : k]
    any_better = jnp.any(dist < kth)

    @pl.when(any_better)
    def _():
        cat_d = jnp.concatenate([bestd[:], dist], axis=1)
        cat_i = jnp.concatenate(
            [besti[:], jnp.broadcast_to(ids, dist.shape)], axis=1)
        new_d, new_i = _extract_topk(cat_d, cat_i, k)
        bestd[:] = new_d
        besti[:] = new_i

    @pl.when(j == n_steps - 1)
    def _():
        outd_ref[:] = -bestd[:] if ip_metric else bestd[:]
        outi_ref[:] = besti[:]


def _scan_pallas(qf, data, data_norms, indices, probes, filter_words, *,
                 k: int, metric: DistanceType, interpret: bool,
                 vmem_mb: int = 0):
    from raft_tpu.neighbors.filters import test_filter

    q, d = qf.shape
    n_lists, m, _ = data.shape
    ip_metric = metric == DistanceType.InnerProduct
    if vmem_mb <= 0:
        vmem_mb = _default_vmem_mb()
    itemsize = 2 if data.dtype == jnp.bfloat16 else 4
    sub = 16 if itemsize == 2 else 8

    uniq = unique_lists(probes, n_lists)
    n_steps = uniq.shape[0]

    # gathered id planes, one per unique list (4 B/slot — 1/32 of the
    # d=128 data stream); a shared bitset filter folds in here: a
    # filtered slot becomes id -1, i.e. padding, so the kernel needs no
    # per-element word gathers (Mosaic lowers those to the scalar core)
    ids_g = jnp.take(indices, jnp.minimum(uniq, n_lists - 1), axis=0)
    if filter_words is not None:
        bits = test_filter(filter_words, ids_g)
        ids_g = jnp.where(bits & (ids_g >= 0), ids_g, -1)

    # lane/sublane alignment; all no-ops on aligned serving layouts
    # (padded_extent rounds max_list_size to 8, d=128-multiples common)
    m_pad = -(-m // sub) * sub
    d_pad = -(-d // 128) * 128
    if m_pad != m or d_pad != d:
        data = jnp.pad(data, ((0, 0), (0, m_pad - m), (0, d_pad - d)))
        data_norms = jnp.pad(data_norms, ((0, 0), (0, m_pad - m)))
        ids_g = jnp.pad(ids_g, ((0, 0), (0, m_pad - m)),
                        constant_values=-1)
    p = probes.shape[1]
    p_pad = -(-p // 128) * 128

    # query-tile sizing from the VMEM budget: double-buffered list
    # block + f32 upcast strip are the fixed cost; per query row the
    # kernel keeps the query vector, the probe row, the (m) dist/cat
    # intermediates (~24 B) and the (k) running state
    budget = (vmem_mb << 20) - 3 * m_pad * (d_pad * itemsize + 8) - (2 << 20)
    per_q = 4 * (d_pad + p_pad) + 24 * m_pad + 16 * k
    q_tile = min(max(8, (budget // per_q) // 8 * 8), -(-q // 8) * 8)
    q_pad = -(-q // q_tile) * q_tile

    qs = jnp.pad(qf.astype(jnp.float32),
                 ((0, q_pad - q), (0, d_pad - d)))
    # pad probe rows/cols with -1: a pad query probes nothing, so its
    # running state stays empty and its rows are sliced away
    probes_p = jnp.pad(probes.astype(jnp.int32),
                       ((0, q_pad - q), (0, p_pad - p)),
                       constant_values=-1)

    kernel = functools.partial(_ivf_scan_kernel, k=k, n_steps=n_steps,
                               n_lists=n_lists, ip_metric=ip_metric)
    clamp = n_lists - 1
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(q_pad // q_tile, n_steps),
        in_specs=[
            pl.BlockSpec((q_tile, p_pad), lambda i, j, u: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((q_tile, d_pad), lambda i, j, u: (i, 0),
                         memory_space=pltpu.VMEM),
            # the scalar-prefetched dynamic index map: step j streams
            # list u[j]'s block; the sentinel clamps to a real list and
            # is masked by the membership predicate
            pl.BlockSpec((1, m_pad, d_pad),
                         lambda i, j, u: (jnp.minimum(u[j], clamp), 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, m_pad),
                         lambda i, j, u: (jnp.minimum(u[j], clamp), 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, m_pad), lambda i, j, u: (j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((q_tile, k), lambda i, j, u: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((q_tile, k), lambda i, j, u: (i, 0),
                         memory_space=pltpu.VMEM),
        ),
        scratch_shapes=[
            pltpu.VMEM((q_tile, k), jnp.float32),
            pltpu.VMEM((q_tile, k), jnp.int32),
        ],
    )
    outd, outi = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=(
            jax.ShapeDtypeStruct((q_pad, k), jnp.float32),
            jax.ShapeDtypeStruct((q_pad, k), jnp.int32),
        ),
        compiler_params=_COMPILER_PARAMS(
            vmem_limit_bytes=vmem_mb << 20),
        interpret=interpret,
    )(uniq, probes_p, qs, data, data_norms, ids_g)
    return outd[:q], outi[:q]
